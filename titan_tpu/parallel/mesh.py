"""Device-mesh helpers + the explicit-sharding compile layer.

The OLAP engine shards per-vertex state over a 1D mesh axis ``"v"``
(vertex blocks); frontier/state exchange rides ICI via ``all_gather``
inside ``shard_map`` (SURVEY §2.8: the TPU-native replacement for the
reference's storage-mediated data movement).

Since the sharded-exchange rebuild (ISSUE 13) this module is also the
compile seam for explicit shardings:

* :func:`mesh_jit` — the compile-once helper (SNIPPETS [1] pattern):
  build a mesh-bound kernel exactly once per (name, mesh), jit it with
  its OUTPUT shardings pinned as ``NamedSharding``s so XLA never
  re-infers placement across levels, and register it through
  ``utils/jitcache`` so the device-cost profiler shims it like every
  other kernel;
* :func:`vertex_mesh` — caches the mesh per device count, so every
  call site holding "the 8-device mesh" holds the SAME hashable object
  and static-argument jit caches never fork on mesh identity;
* :func:`bound_axes` / :func:`axis_bound` — explicit axis-environment
  introspection. ``global_sum`` used to swallow ``NameError`` to
  detect "axis not bound", which also swallowed genuinely misspelled
  axis names into a silent per-shard sum; now a bound-but-different
  axis environment raises loudly.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

VERTEX_AXIS = "v"


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """Version-spanning shard_map: ``jax.shard_map`` (new spelling) when
    present, ``jax.experimental.shard_map`` otherwise. Replication
    checking is disabled either way (check_vma/check_rep) — the engine
    kernels return deliberately-replicated pmax'd stats next to sharded
    state, which the checker rejects."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


#: mesh cache: one Mesh object per device count (device order is
#: process-stable), so jit caches keyed on the mesh — static arguments
#: and mesh_jit's registry alike — never fork on object identity
_MESHES: dict = {}


def vertex_mesh(num_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    if num_devices is None or num_devices <= 0:
        num_devices = len(devs)
    if num_devices > len(devs):
        raise ValueError(f"requested {num_devices} devices, have {len(devs)}")
    got = _MESHES.get(num_devices)
    if got is None or got.devices.size != num_devices:
        got = Mesh(np.array(devs[:num_devices]), (VERTEX_AXIS,))
        _MESHES[num_devices] = got
    return got


def bound_axes() -> tuple:
    """Names of the mapped axes bound in the CURRENT trace (inside a
    shard_map/pmap body: that map's axis names; top level: empty).

    Raises (does NOT return empty) when the axis-environment API is
    missing — a jax upgrade that renames it must surface as a loud
    error at the call site, never as a silent "no axis bound" that
    degrades ``global_sum`` into a per-shard sum (the failure mode the
    old NameError swallow had, which this module exists to close)."""
    try:
        from jax._src import core
        env = core.get_axis_env()
    except Exception as e:
        raise RuntimeError(
            "parallel.mesh.bound_axes: this jax version does not "
            "expose jax._src.core.get_axis_env() — update the axis-"
            "environment probe here (silently assuming 'no axis "
            "bound' would turn sharded global reductions into "
            f"per-shard sums): {type(e).__name__}: {e}") from e
    return tuple(env.axis_sizes)


def axis_bound(name: str = VERTEX_AXIS) -> bool:
    """True iff mapped axis ``name`` is bound in the current trace."""
    return name in bound_axes()


def global_sum(x, axis: str = VERTEX_AXIS):
    """Sum across the FULL vertex axis from inside a DenseProgram
    callback: shard-local sum + psum over the mesh when executing under
    shard_map, plain sum on a single device (no axis bound there).
    Programs with global reductions (e.g. HITS normalization) must use
    this instead of jnp.sum, or sharded runs silently normalize per
    shard.

    The "am I sharded?" test is an EXPLICIT axis-environment check
    (:func:`axis_bound`), not a swallowed NameError: executing under a
    mesh whose axis names don't include ``axis`` raises — a misspelled
    axis name must never degrade into a silent per-shard sum."""
    import jax.numpy as jnp
    total = jnp.sum(x)
    bound = bound_axes()
    if axis in bound:
        return jax.lax.psum(total, axis)
    if bound:
        raise ValueError(
            f"global_sum over axis {axis!r}, but the bound mapped axes "
            f"are {bound} — a per-shard sum here would be silently "
            "wrong; pass the mesh axis this program is sharded over")
    return total


def state_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(VERTEX_AXIS))


def edge_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(VERTEX_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# compile-once helper over explicit shardings (SNIPPETS [1] pattern)
# ---------------------------------------------------------------------------

def mesh_key(mesh: Mesh) -> str:
    """A stable fingerprint for jit-cache keys: axis layout + device
    ids (NOT id(mesh) — equal meshes must share compiled kernels)."""
    ids = ",".join(str(d.id) for d in mesh.devices.flat)
    ax = ",".join(f"{n}{s}" for n, s in zip(mesh.axis_names, mesh.shape.values()))
    return f"{ax}[{ids}]"


def mesh_jit(name: str, mesh: Mesh, builder, *, out_specs,
             static_argnames=(), donate_argnums=()):
    """Compile-once, donor-aware jit with pinned OUTPUT shardings.

    ``builder(mesh)`` returns the python callable (typically a
    shard_map-wrapped per-shard body closed over the mesh). It is
    called once per (name, mesh); the result is jitted with
    ``out_shardings`` materialized from ``out_specs`` (a PartitionSpec
    pytree) as ``NamedSharding``s on ``mesh``, so every level dispatch
    lands its outputs exactly where the next level's inputs are pinned
    — XLA never re-infers or reshuffles placement between dispatches.
    Inputs are pinned at the data instead (see
    ``partition.place_shards``): committed arrays carry their sharding
    through jit, and pinning uploads once beats re-specifying per call.

    The compiled function registers through ``utils/jitcache.jit_once``
    (key ``<name>@<mesh fingerprint>``), so the device-cost profiler
    shims it exactly like the single-chip kernels — ``device.exec.calls
    {kernel=<name>@...}`` is the per-level dispatch-budget evidence."""
    from titan_tpu.utils.jitcache import jit_once

    key = f"{name}@{mesh_key(mesh)}"

    def build():
        fn = builder(mesh)
        out_shardings = jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec), out_specs,
            is_leaf=lambda s: isinstance(s, P))
        return jax.jit(fn, out_shardings=out_shardings,
                       static_argnames=tuple(static_argnames),
                       donate_argnums=tuple(donate_argnums))

    return jit_once(key, build)
