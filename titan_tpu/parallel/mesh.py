"""Device-mesh helpers.

The OLAP engine shards per-vertex state over a 1D mesh axis ``"v"`` (vertex
blocks); frontier/state exchange rides ICI via ``all_gather`` inside
``shard_map`` (SURVEY §2.8: the TPU-native replacement for the reference's
storage-mediated data movement).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

VERTEX_AXIS = "v"


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """Version-spanning shard_map: ``jax.shard_map`` (new spelling) when
    present, ``jax.experimental.shard_map`` otherwise. Replication
    checking is disabled either way (check_vma/check_rep) — the engine
    kernels return deliberately-replicated pmax'd stats next to sharded
    state, which the checker rejects."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def vertex_mesh(num_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    if num_devices is None or num_devices <= 0:
        num_devices = len(devs)
    if num_devices > len(devs):
        raise ValueError(f"requested {num_devices} devices, have {len(devs)}")
    return Mesh(np.array(devs[:num_devices]), (VERTEX_AXIS,))


def global_sum(x):
    """Sum across the FULL vertex axis from inside a DenseProgram callback:
    shard-local sum + psum over the mesh when executing under shard_map,
    plain sum on a single device (the axis isn't bound there). Programs
    with global reductions (e.g. HITS normalization) must use this instead
    of jnp.sum, or sharded runs silently normalize per shard."""
    import jax.numpy as jnp
    total = jnp.sum(x)
    try:
        return jax.lax.psum(total, VERTEX_AXIS)
    except NameError:
        return total


def state_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(VERTEX_AXIS))


def edge_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(VERTEX_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
