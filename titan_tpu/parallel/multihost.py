"""Multi-host (DCN) execution: a process-spanning device mesh with
host-sharded graph loading.

SURVEY §2.8 names the JAX distributed runtime across hosts as the
rebuild's cross-host data plane (the reference distributes OLAP across
machines through Hadoop InputFormats —
titan-hadoop-core/.../scan/HadoopScanMapper.java:33); this module is the
TPU-native seam: every host calls :func:`init` (jax.distributed), all
hosts run the SAME program over a :func:`global_mesh` spanning every
process's devices, and graph arrays are materialized with
:func:`host_sharded` / :func:`host_replicated` so each host only ever
touches the shards its own devices hold (host-sharded snapshot loading —
a scale-26 graph never exists whole on any single host).

Single-controller semantics still hold per JAX's multi-controller model:
jit/shard_map calls must be issued by every process in lockstep, and
scalar readbacks of REPLICATED outputs are process-local. The sharded
BFS host loop (models/bfs_hybrid_sharded) is deterministic given the
stats vector, so every host takes identical branches.

Driven by ``__graft_entry__.dryrun_multihost`` (2 processes x 4 virtual
CPU devices) and tests/test_multihost.py.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np


def init(coordinator: str, num_processes: int, process_id: int) -> None:
    """Join the cross-host runtime (call ONCE per process, before any
    jax computation). ``coordinator`` is host:port of process 0; local
    device count comes from the platform (on CPU, set
    ``--xla_force_host_platform_device_count``)."""
    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh(axis: str = "v"):
    """A 1D mesh over EVERY device of EVERY process (DCN-spanning)."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis,))


def host_sharded(mesh, shape, dtype, fill: Callable[[int], np.ndarray],
                 axis: str = "v"):
    """A global array sharded along dim 0 of ``shape``, materialized
    host-locally: ``fill(block_index)`` is called ONLY for blocks whose
    owning device is addressable from this process — the host-sharded
    loading seam (no host holds the whole array)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(axis, *([None] * (len(shape) - 1)))
    sharding = NamedSharding(mesh, spec)
    ndev = mesh.devices.size
    if shape[0] % ndev:
        raise ValueError(f"dim0 {shape[0]} must divide over {ndev} devices")
    block = shape[0] // ndev

    def cb(index):
        # index is a tuple of slices into the global shape
        lo = index[0].start or 0
        return np.ascontiguousarray(fill(lo // block))

    return jax.make_array_from_callback(tuple(shape), sharding, cb)


def host_replicated(mesh, value: np.ndarray):
    """A fully-replicated global array (every host provides the same
    data for its local devices)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())
    return jax.make_array_from_callback(value.shape, sharding,
                                        lambda idx: value[idx])


def run_multihost_bfs(host_graph: dict, source_dense: int, mesh,
                      max_levels: int = 1000):
    """The sharded hybrid BFS over a process-spanning mesh with
    HOST-SHARDED loading: each process builds and uploads only the
    padded shard blocks its own devices hold (a production loader feeds
    the same ``fill`` callbacks from its key-range of the distributed
    scan tier). Every process must call this with identical arguments;
    returns (dist np [n], levels) on every process.

    ``host_graph``: the graph500-style host dict
    (n / q_total / deg / colstart / dstT numpy arrays)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from titan_tpu.models import bfs_hybrid_sharded as S
    from titan_tpu.utils.jitcache import set_scalar_sharding

    num = int(mesh.devices.size)
    n = host_graph["n"]
    deg = np.asarray(host_graph["deg"])
    dstT = np.asarray(host_graph["dstT"])
    degc_all = (-(-deg // 8)).astype(np.int32)
    colstart = np.zeros(n + 1, np.int64)
    np.cumsum(degc_all, out=colstart[1:])
    bounds, b_max, q_max = S.plan_shard_cuts(colstart, n, num)
    d_eff = len(bounds) - 1
    bounds_full = np.zeros(num + 1, np.int64)
    bounds_full[:len(bounds)] = bounds
    bounds_full[len(bounds):] = n

    # one shared block-packing definition with the single-host path
    # (S.pack_shard_block), so the layouts cannot drift
    def fill(part):
        def f(d):
            return S.pack_shard_block(d, colstart, dstT, degc_all,
                                      bounds_full, b_max, q_max,
                                      n)[part][None]
        return f

    dstT_sh = host_sharded(mesh, (num, 8, q_max), np.int32, fill(0))
    colstart_sh = host_sharded(mesh, (num, b_max + 1), np.int32, fill(1))
    degc_sh = host_sharded(mesh, (num, b_max), np.int32, fill(2))
    lo_sh = host_sharded(mesh, (num,), np.int32,
                         lambda d: bounds_full[d:d + 1].astype(np.int32))
    hi_sh = host_sharded(
        mesh, (num,), np.int32,
        lambda d: bounds_full[d + 1:d + 2].astype(np.int32))
    degc_rep = host_replicated(
        mesh, np.concatenate([degc_all, [0]]).astype(np.int32))
    total = int(colstart[n])
    sh = {
        "bounds": bounds_full, "n": n, "b_max": b_max, "q_max": q_max,
        "q_total": host_graph["q_total"], "total_chunks": total,
        "degc": np.concatenate([degc_all, [0]]).astype(np.int32),
        "shard_chunks": [int(colstart[bounds_full[d + 1]]
                             - colstart[bounds_full[d]])
                         for d in range(d_eff)],
        "nunv_chip_max": S.shard_unvisited_cap(degc_all,
                                               bounds_full[:d_eff + 1]),
        "_dev": (dstT_sh, colstart_sh, degc_sh, degc_rep, lo_sh, hi_sh),
    }
    host_graph["_shards"] = (num, sh)
    set_scalar_sharding(NamedSharding(mesh, P()))
    try:
        dist, levels = S.frontier_bfs_hybrid_sharded(
            host_graph, source_dense, mesh, max_levels=max_levels)
        return np.asarray(dist), levels
    finally:
        set_scalar_sharding(None)


def _worker(coordinator: str, num_processes: int, process_id: int,
            scale: int) -> None:
    """One process of the multihost dryrun (spawned by
    ``__graft_entry__.dryrun_multihost``): joins the distributed
    runtime, builds the SAME symmetric R-MAT graph as every peer, runs
    the host-sharded BFS over the process-spanning mesh, and process 0
    validates bit-equality against the single-chip hybrid."""
    import json

    init(coordinator, num_processes, process_id)
    import jax

    from titan_tpu.models.bfs_hybrid import (build_chunked_csr,
                                             frontier_bfs_hybrid)
    from titan_tpu.olap.tpu import snapshot as snap_mod
    from titan_tpu.olap.tpu.rmat import rmat_edges

    src_e, dst_e = rmat_edges(scale, 16, seed=2)
    snap = snap_mod.from_arrays(1 << scale,
                                np.concatenate([src_e, dst_e]),
                                np.concatenate([dst_e, src_e]))
    g = build_chunked_csr(snap)
    hg = {"n": snap.n, "q_total": g["q_total"],
          "deg": np.asarray(snap.out_degree),
          "colstart": g["_host"]["colstart"],
          "dstT": g["_host"]["dstT"]}
    source = int(np.argmax(snap.out_degree))
    mesh = global_mesh()
    dist, levels = run_multihost_bfs(hg, source, mesh)
    if process_id == 0:
        from titan_tpu.models import bfs_hybrid_sharded as S
        ref, _ = frontier_bfs_hybrid(snap, source)
        ok = bool((dist == np.asarray(ref)).all())
        # bottom-up levels must run through the FUSED shx_bu path on
        # the process-spanning mesh too (ISSUE 13: the r4 host-driven
        # bu0/bu_more/exhaust chain is deleted, as was r4's fused
        # full-width DCN fallback before it — this records the proof
        # that DCN meshes run the same one-dispatch-per-level kernels)
        bu_levels = [p for p in S.LAST_PROFILE if p["mode"] == "bu"]
        print("MULTIHOST_OK " + json.dumps({
            "processes": num_processes,
            "devices": jax.device_count(),
            "local_devices": jax.local_device_count(),
            "scale": scale, "levels": levels,
            "reached": int((dist < (1 << 30)).sum()),
            "bit_equal_vs_single_chip": ok,
            "bu_levels_fused": len(bu_levels),
            "dispatches_per_level_max":
                max((p["dispatches"] for p in S.LAST_PROFILE),
                    default=0),
            "bu_trails": [p["bu_trail"] for p in bu_levels]}),
            flush=True)
        # exit status gates on bit-correctness ONLY: whether any level
        # ran bottom-up is the direction heuristic's call (a scale or
        # degree distribution that stays top-down throughout is still
        # a correct run) — bu_levels_fused above is the evidence
        # the driver inspects instead (ADVICE r5 #1)
        if not ok:
            raise SystemExit(2)


if __name__ == "__main__":
    import sys

    _worker(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
            int(sys.argv[4]) if len(sys.argv) > 4 else 13)
