"""Edge/vertex partitioning for multi-chip execution.

The TPU-native replacement for the reference's data-placement machinery
(reference: titan-core SURVEY §2.7 — partition bits in ids shard rows across
the cluster; vertex cuts spread hot rows): vertices are block-partitioned
into D contiguous dense ranges (dense order is partition-major, so storage
partitions and device shards coincide); edges go to the shard that OWNS THE
DESTINATION vertex (pull layout), each shard keeping global source indices.
A superstep then needs exactly one all-gather of vertex state over ICI plus
a local gather + segment-combine — no shuffle.

All shards are padded to identical static shapes (XLA requirement): padded
edges point at a per-shard sink row (local index == block) and are masked
with the combine identity.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from titan_tpu.olap.tpu.snapshot import GraphSnapshot

_ALIGN = 1024  # pad edge blocks to multiples of this (8×128 tiles)


@dataclass
class ShardedCSR:
    n: int                      # true vertex count
    n_pad: int                  # D * block
    block: int                  # vertices per shard
    num_shards: int
    e_block: int                # edges per shard (padded, static)
    src_global: np.ndarray      # [D, e_block] int32
    dst_local: np.ndarray       # [D, e_block] int32 in [0, block]; block = sink
    valid: np.ndarray           # [D, e_block] bool
    last_idx: np.ndarray        # [D, block+1] int32 scan metadata (ops/segment)
    seg_has: np.ndarray         # [D, block+1] bool
    edge_values: dict = field(default_factory=dict)  # name -> [D, e_block]


def shard_csr(snap: GraphSnapshot, num_shards: int,
              align: int = _ALIGN) -> ShardedCSR:
    n = snap.n
    block = -(-max(n, 1) // num_shards)          # ceil
    block = -(-block // 8) * 8                   # sublane-align vertex blocks
    n_pad = block * num_shards

    # snapshot edges are dst-sorted: shard boundaries via searchsorted
    bounds = np.searchsorted(snap.dst, np.arange(0, n_pad + 1, block))
    counts = np.diff(bounds)
    e_block = int(max(counts.max() if len(counts) else 0, 1))
    e_block = -(-e_block // align) * align

    src_g = np.zeros((num_shards, e_block), dtype=np.int32)
    dst_l = np.full((num_shards, e_block), block, dtype=np.int32)  # sink
    valid = np.zeros((num_shards, e_block), dtype=bool)
    last_idx = np.zeros((num_shards, block + 1), dtype=np.int32)
    seg_has = np.zeros((num_shards, block + 1), dtype=bool)
    evs = {name: np.zeros((num_shards, e_block), dtype=np.asarray(v).dtype)
           for name, v in snap.edge_values.items()}
    from titan_tpu.ops.segment import segment_metadata
    for d in range(num_shards):
        lo, hi = bounds[d], bounds[d + 1]
        m = hi - lo
        src_g[d, :m] = snap.src[lo:hi]
        dst_l[d, :m] = snap.dst[lo:hi] - d * block
        valid[d, :m] = True
        for name, v in snap.edge_values.items():
            evs[name][d, :m] = v[lo:hi]
        # scan metadata over the local (block+1)-segment layout (sink last)
        indptr_l = np.zeros(block + 2, dtype=np.int64)
        np.add.at(indptr_l, dst_l[d] + 1, 1)
        np.cumsum(indptr_l, out=indptr_l)
        li, sh = segment_metadata(indptr_l)
        last_idx[d] = li[:block + 1]
        seg_has[d] = sh[:block + 1]
    return ShardedCSR(n, n_pad, block, num_shards, e_block, src_g, dst_l,
                      valid, last_idx, seg_has, evs)
