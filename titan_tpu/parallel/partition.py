"""Edge/vertex partitioning + explicit data placement for multi-chip
execution.

The TPU-native replacement for the reference's data-placement machinery
(reference: titan-core SURVEY §2.7 — partition bits in ids shard rows across
the cluster; vertex cuts spread hot rows): vertices are block-partitioned
into D contiguous dense ranges (dense order is partition-major, so storage
partitions and device shards coincide); edges go to the shard that OWNS THE
DESTINATION vertex (pull layout), each shard keeping global source indices.
A superstep then needs exactly one all-gather of vertex state over ICI plus
a local gather + segment-combine — no shuffle.

All shards are padded to identical static shapes (XLA requirement): padded
edges point at a per-shard sink row (local index == block) and are masked
with the combine identity.

Sharded-exchange rebuild (ISSUE 13) additions:

* :class:`BlockLayout` — the vertex-block layout descriptor: one object
  carrying the edge-balanced block bounds, per-shard padded widths and
  the int32 safety facts, shared by the sharded BFS, the multihost
  loader and the comm-profile reporting so the layout has exactly one
  definition;
* :func:`place_shards` / :func:`place_replicated` — explicit
  ``NamedSharding`` placement of the per-shard device arrays (uploaded
  ONCE, committed, so no per-dispatch resharding);
* :func:`exchange_found` — the shard_map-level sparse exchange
  primitive: compact each shard's newly-found vertex ids to a static
  cap and all-gather ONLY those lists — O(frontier) communication, the
  replicated-dist merge without an n-scale all-reduce;
* :func:`place_batched_csr` — mesh placement for the serving plane's
  batched ``[K, n]`` cohorts: the chunked CSR's columns shard over
  ``"v"`` and the dist state rides a ``P(None, "v")`` sharding (K
  replicated), so K-way plan amortization and sharding compose through
  the UNCHANGED batched kernels (GSPMD partitions them from the input
  placements).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from titan_tpu.olap.tpu.snapshot import GraphSnapshot

# kept in sync with parallel/mesh.VERTEX_AXIS (a string constant; the
# mesh module imports jax at module scope, which this module defers)
VERTEX_AXIS = "v"

_ALIGN = 1024  # pad edge blocks to multiples of this (8×128 tiles)


@dataclass
class ShardedCSR:
    n: int                      # true vertex count
    n_pad: int                  # D * block
    block: int                  # vertices per shard
    num_shards: int
    e_block: int                # edges per shard (padded, static)
    src_global: np.ndarray      # [D, e_block] int32
    dst_local: np.ndarray       # [D, e_block] int32 in [0, block]; block = sink
    valid: np.ndarray           # [D, e_block] bool
    last_idx: np.ndarray        # [D, block+1] int32 scan metadata (ops/segment)
    seg_has: np.ndarray         # [D, block+1] bool
    edge_values: dict = field(default_factory=dict)  # name -> [D, e_block]


def shard_csr(snap: GraphSnapshot, num_shards: int,
              align: int = _ALIGN) -> ShardedCSR:
    n = snap.n
    block = -(-max(n, 1) // num_shards)          # ceil
    block = -(-block // 8) * 8                   # sublane-align vertex blocks
    n_pad = block * num_shards

    # snapshot edges are dst-sorted: shard boundaries via searchsorted
    bounds = np.searchsorted(snap.dst, np.arange(0, n_pad + 1, block))
    counts = np.diff(bounds)
    e_block = int(max(counts.max() if len(counts) else 0, 1))
    e_block = -(-e_block // align) * align

    src_g = np.zeros((num_shards, e_block), dtype=np.int32)
    dst_l = np.full((num_shards, e_block), block, dtype=np.int32)  # sink
    valid = np.zeros((num_shards, e_block), dtype=bool)
    last_idx = np.zeros((num_shards, block + 1), dtype=np.int32)
    seg_has = np.zeros((num_shards, block + 1), dtype=bool)
    evs = {name: np.zeros((num_shards, e_block), dtype=np.asarray(v).dtype)
           for name, v in snap.edge_values.items()}
    from titan_tpu.ops.segment import segment_metadata
    for d in range(num_shards):
        lo, hi = bounds[d], bounds[d + 1]
        m = hi - lo
        src_g[d, :m] = snap.src[lo:hi]
        dst_l[d, :m] = snap.dst[lo:hi] - d * block
        valid[d, :m] = True
        for name, v in snap.edge_values.items():
            evs[name][d, :m] = v[lo:hi]
        # scan metadata over the local (block+1)-segment layout (sink last)
        indptr_l = np.zeros(block + 2, dtype=np.int64)
        np.add.at(indptr_l, dst_l[d] + 1, 1)
        np.cumsum(indptr_l, out=indptr_l)
        li, sh = segment_metadata(indptr_l)
        last_idx[d] = li[:block + 1]
        seg_has[d] = sh[:block + 1]
    return ShardedCSR(n, n_pad, block, num_shards, e_block, src_g, dst_l,
                      valid, last_idx, seg_has, evs)


# ---------------------------------------------------------------------------
# vertex-block layout descriptors (ISSUE 13)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class BlockLayout:
    """The vertex-block layout of a D-way mesh: edge-balanced
    contiguous vertex ranges over the chunk prefix, with the padded
    per-shard widths every kernel cap derives from.

    ``bounds`` is always ``num_shards + 1`` long (degenerate trailing
    shards own empty ranges, exactly like the packed arrays they
    describe). ``b_max``/``q_max`` are the padded per-shard vertex and
    chunk-column widths; ``q_max`` includes the +1 local sink column
    and is int32-guarded at construction (per-shard LOCAL column
    indices are int32). ``shard_chunks`` is the per-shard edge-chunk
    mass — the edge-balance evidence the comm profile reports.
    ``nunv_cap`` bounds the per-shard count of expandable vertices —
    the first bottom-up level's candidate cap, before any exchange
    stats exist."""

    n: int
    num_shards: int
    bounds: tuple                # [num_shards + 1] dense vertex cuts
    b_max: int                   # padded vertices per shard
    q_max: int                   # padded chunk columns per shard (+sink)
    shard_chunks: tuple          # per-shard chunk mass (live shards)
    nunv_cap: int

    @property
    def live_shards(self) -> int:
        return len(self.shard_chunks)

    def balance(self) -> float:
        """max/min chunk mass over live shards (1.0 = perfect)."""
        if not self.shard_chunks:
            return 1.0
        return max(self.shard_chunks) / max(min(self.shard_chunks), 1)

    def block_window(self, d: int) -> tuple:
        """(lo, hi) dense vertex range owned by shard ``d``."""
        return int(self.bounds[d]), int(self.bounds[d + 1])

    def describe(self) -> dict:
        return {"n": self.n, "num_shards": self.num_shards,
                "b_max": self.b_max, "q_max": self.q_max,
                "shard_chunks": list(self.shard_chunks),
                "balance_max_over_min": round(self.balance(), 3),
                "nunv_cap": self.nunv_cap}


def block_layout(colstart: np.ndarray, degc_all: np.ndarray, n: int,
                 num_shards: int) -> BlockLayout:
    """Plan the edge-balanced vertex-block layout (the ONE descriptor
    construction — single-host sharding and the multihost host-sharded
    loader both come through here via
    ``bfs_hybrid_sharded.plan_shard_cuts``)."""
    from titan_tpu.models.bfs_hybrid_sharded import (plan_shard_cuts,
                                                     shard_unvisited_cap)

    bounds, b_max, q_max = plan_shard_cuts(colstart, n, num_shards)
    d_eff = len(bounds) - 1
    bounds_full = np.zeros(num_shards + 1, np.int64)
    bounds_full[:len(bounds)] = bounds
    bounds_full[len(bounds):] = n
    chunks = tuple(int(colstart[bounds[d + 1]] - colstart[bounds[d]])
                   for d in range(d_eff))
    return BlockLayout(int(n), int(num_shards),
                       tuple(int(b) for b in bounds_full),
                       int(b_max), int(q_max), chunks,
                       shard_unvisited_cap(degc_all, bounds))


# ---------------------------------------------------------------------------
# explicit NamedSharding placement (ISSUE 13)
# ---------------------------------------------------------------------------

def place_shards(mesh, *arrays):
    """Commit per-shard arrays (leading dim = num_shards) onto the
    mesh with explicit ``NamedSharding(mesh, P("v", None, ...))`` —
    uploaded ONCE to their final placement, so no kernel dispatch ever
    pays a host round trip or a device reshuffle to put shard d's rows
    on device d. Returns the placed arrays in order."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = []
    for a in arrays:
        a = jnp.asarray(a)
        spec = P(VERTEX_AXIS, *([None] * (a.ndim - 1)))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return out


def place_replicated(mesh, *arrays):
    """Commit arrays fully replicated (``P()``) across the mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P())
    return [jax.device_put(jnp.asarray(a), sh) for a in arrays]


# ---------------------------------------------------------------------------
# the sparse exchange primitive (ISSUE 13)
# ---------------------------------------------------------------------------

def exchange_found(newly_mask, found_cap: int, n: int,
                   axis: str = VERTEX_AXIS):
    """The shard_map-level frontier exchange: compact this shard's
    newly-found vertex mask into a ``found_cap``-sized id list
    (ops.compaction — no n-wide nonzero) and all-gather ONLY those
    lists over the mesh axis. Communication is O(frontier), not O(n):
    D × found_cap int32 ids per level versus the n-element dist
    all-reduce the round-1 design paid (256 MB × levels at scale 26).

    The all-gather is issued HERE, before the caller's merge/stat
    reductions consume it, so XLA can overlap the collective with the
    n-scale stat compute that follows (the overlap model,
    docs/performance.md).

    Must be called INSIDE a shard_map body with ``axis`` bound. Returns
    ``(all_ids [D, found_cap] int32 with fill n+1, found_max)`` where
    ``found_max`` is the pmax'd true per-shard discovery count — the
    caller's overflow check (``found_max > found_cap`` ⇒ retry with the
    exact cap; the merged result is discarded)."""
    import jax
    import jax.numpy as jnp

    from titan_tpu.ops.compaction import compact_ids

    cnt = newly_mask.sum().astype(jnp.int32)
    found_max = jax.lax.pmax(cnt, axis)
    _, ids = compact_ids(newly_mask, found_cap, n + 1)
    all_ids = jax.lax.all_gather(ids, axis)          # [D, found_cap]
    return all_ids, found_max


# ---------------------------------------------------------------------------
# mesh placement for batched [K, n] cohorts (ISSUE 13, serving plane)
# ---------------------------------------------------------------------------

def batched_state_sharding(mesh):
    """The ``[K, n+1]`` dist placement for mesh-placed batched runs:
    vertex axis sharded over ``"v"``, K replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(None, VERTEX_AXIS))


def place_batched_csr(snap_or_graph, mesh) -> dict:
    """Chunked-CSR graph dict placed for a multi-device mesh: ``dstT``'s
    chunk columns shard over ``"v"`` (each device holds ~1/D of the
    edge image — the arrays that dominate HBM), the small per-vertex
    arrays replicate, and ``_state_sharding`` tells
    ``frontier_bfs_batched`` to pin its ``[K, n+1]`` dist to
    ``P(None, "v")`` (K replicated). The batched kernels themselves are
    UNCHANGED — committed input placements carry through jit and GSPMD
    partitions the sweep, which is what lets K-way plan amortization
    and sharding compose without a second kernel library.

    ``dstT`` is column-padded to a multiple of D (extra all-pad sink
    columns — this jax requires divisible shard extents); the padded
    columns behave exactly like the existing sink column (pad gathers
    clamp to the never-written ``dist[n]``). The state sharding is
    attached only when ``n + 1`` divides over the mesh; otherwise the
    state replicates (correct either way — GSPMD still shards the edge
    sweep) and the dict records ``_state_replicated_why``.

    Cached on the graph dict per mesh. Single-process meshes only (the
    serving plane is one process; multihost cohorts would need
    host-sharded loading, which is the sharded-BFS path's job)."""
    import jax

    from titan_tpu.models.bfs_hybrid import build_chunked_csr

    if jax.process_count() > 1:
        raise NotImplementedError(
            "place_batched_csr is single-process (the serving plane); "
            "multihost placement goes through parallel/multihost")
    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    cache = g.get("_meshed")
    if cache is not None and cache[0] == mesh:
        return cache[1]
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = g["n"]
    D = int(mesh.devices.size)
    host = g.get("_host", {})
    dstT_h = host.get("dstT")
    if dstT_h is None:
        dstT_h = np.asarray(g["dstT"])
    q = dstT_h.shape[1]
    q_pad = -(-q // D) * D
    if q_pad != q:
        dstT_h = np.concatenate(
            [dstT_h, np.full((8, q_pad - q), n + 1, np.int32)], axis=1)
    from titan_tpu.obs import devprof
    devprof.count_h2d("parallel.batched_csr", dstT_h.nbytes)
    placed = dict(g)
    placed["dstT"] = jax.device_put(
        jnp.asarray(dstT_h), NamedSharding(mesh, P(None, VERTEX_AXIS)))
    placed["colstart"], placed["degc"], placed["deg"] = place_replicated(
        mesh, g["colstart"], g["degc"], g["deg"])
    if (n + 1) % D == 0:
        placed["_state_sharding"] = batched_state_sharding(mesh)
    else:
        placed["_state_replicated_why"] = (
            f"n+1 = {n + 1} does not divide over {D} devices; dist "
            "replicates (edge sweep still sharded)")
    placed["_mesh"] = mesh
    placed.pop("_meshed", None)
    g["_meshed"] = (mesh, placed)
    return placed


