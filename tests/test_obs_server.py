"""Observability over the wire + kernel-purity guards (ISSUE r10).

Acceptance coverage: a retried batched BFS job served over HTTP yields
a ``GET /trace`` span tree (submit→queue→fuse→per-round→checkpoint→
retrying→resume→done) with monotonic timestamps; ``GET /metrics``
renders valid Prometheus text; kernel results stay bit-equal with
tracing enabled; and the tracer is fully removable via one flag within
a generous overhead bound.

Graph shapes are shared with existing suites on purpose (CPU XLA
compiles dominate tier-1): the gods example graph for HTTP flows
(test_serving_server.py's bucket) and the n=192/m=900/seed-42
from_arrays snapshot for kernel runs (test_serving.py's bucket).
"""

import json
import re
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import titan_tpu
from titan_tpu import example
from titan_tpu.olap.api import JobSpec
from titan_tpu.olap.recovery import FaultPlan
from titan_tpu.olap.serving.scheduler import JobScheduler
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.server import GraphServer
from titan_tpu.utils.metrics import MetricManager

_N = 192          # ONE pow-2 compile bucket across kernel tests here


def _sym_snapshot(seed: int = 42, n: int = _N, m: int = 900):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


def _req(srv, path, payload=None, method="GET"):
    req = urllib.request.Request(
        f"http://{srv.host}:{srv.port}{path}",
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"Content-Type": "application/json"}, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.headers.get("Content-Type"), r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type"), e.read()


def _poll(srv, job_id, timeout=90.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        code, _, body = _req(srv, f"/jobs/{job_id}")
        assert code == 200
        b = json.loads(body)
        if b["status"] not in ("queued", "running", "retrying"):
            return b
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} did not finish")


@pytest.fixture
def served():
    g = titan_tpu.open("inmemory")
    example.load(g)
    srv = GraphServer(g, port=0).start()
    yield g, srv
    srv.stop()
    g.close()


def _names(tree_node, acc):
    acc.append(tree_node["name"])
    for c in tree_node["children"]:
        _names(c, acc)
    return acc


def _walk(tree_node, acc):
    acc.append(tree_node)
    for c in tree_node["children"]:
        _walk(c, acc)
    return acc


# ---------------------------------------------------------------------------
# endpoints
# ---------------------------------------------------------------------------


_LABEL_PAIR = r"[a-zA-Z0-9_]+=\"([^\"\\]|\\.)*\""
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{" + _LABEL_PAIR + r"(," + _LABEL_PAIR + r")*\})? "
    r"[+-]?(\d+\.?\d*([eE][+-]?\d+)?)$")


def test_metrics_endpoint_prometheus_text(served):
    g, srv = served
    code, _, body = _req(srv, "/jobs", {"kind": "bfs", "source_dense": 0},
                         method="POST")
    assert code == 202
    _poll(srv, json.loads(body)["job"])
    code, ctype, body = _req(srv, "/metrics")
    assert code == 200
    assert ctype == "text/plain; version=0.0.4; charset=utf-8"
    text = body.decode()
    samples = []
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        assert _SAMPLE.match(ln), f"invalid Prometheus sample: {ln!r}"
        samples.append(ln)
    # every registered serving metric family renders
    assert any(ln.startswith("serving_jobs_submitted ") for ln in samples)
    assert any(ln.startswith("serving_batch_occupancy_count ")
               for ln in samples)
    assert any('quantile="0.95"' in ln for ln in samples)


def test_trace_endpoint_404_and_400(served):
    _, srv = served
    # an idle server must answer trace probes WITHOUT lazily spinning
    # up a scheduler (worker thread + ledger) just to 404
    code, ctype, body = _req(srv, "/trace?job=job-does-not-exist")
    assert code == 404 and ctype == "application/json"
    assert json.loads(body)["type"] == "NotFound"
    assert srv._scheduler is None
    code, _, body = _req(srv, "/trace")
    assert code == 400
    code, _, _ = _req(srv, "/trace?other=x")
    assert code == 400


def test_rejected_submit_leaves_no_orphan_trace(served):
    """A submit refused by a closed scheduler must not leave a
    forever-open root span occupying the tracer's LRU."""
    g, srv = served
    sched = JobScheduler(graph=g, metrics=MetricManager(),
                         autostart=False)
    sched.close()
    with pytest.raises(RuntimeError):
        sched.submit(JobSpec(kind="bfs", params={"source_dense": 0}))
    # the only trace ids left are admitted jobs' (none here)
    assert not sched.tracer._traces


def test_trace_disabled_scheduler_404_and_no_digest(served):
    """One flag removes the whole plane: no trace endpoint hits, no
    digest in /jobs, no TraceHandle on the job."""
    g, srv = served
    srv._scheduler = JobScheduler(graph=g, metrics=MetricManager(),
                                  tracing=False)
    code, _, body = _req(srv, "/jobs", {"kind": "bfs", "source_dense": 0},
                         method="POST")
    assert code == 202
    jid = json.loads(body)["job"]
    final = _poll(srv, jid)
    assert final["status"] == "done"
    assert "trace" not in final
    assert srv._scheduler.get(jid).trace is None
    code, _, _ = _req(srv, f"/trace?job={jid}")
    assert code == 404


# ---------------------------------------------------------------------------
# the acceptance flow: retried batched BFS over HTTP → full span tree
# ---------------------------------------------------------------------------


def test_retried_batched_bfs_trace_tree_over_http(served, tmp_path):
    g, srv = served
    metrics = MetricManager()
    sched = JobScheduler(graph=g, metrics=metrics, autostart=False,
                         checkpoint_dir=str(tmp_path / "ckpt"))
    srv._scheduler = sched
    # a fresh batchmate + one faulted job with checkpoints: the
    # injected crash at level 2 kills the fused batch AFTER the level-1
    # checkpoint committed; the faulted job retries and RESUMES from
    # it, the batchmate retries clean (max_retries=1 each)
    code, _, body = _req(srv, "/jobs",
                         {"kind": "bfs", "source_dense": 0,
                          "max_retries": 1}, method="POST")
    assert code == 202
    mate = json.loads(body)["job"]
    faulted = sched.submit(JobSpec(
        kind="bfs",
        params={"source_dense": 1,
                "faults": FaultPlan(crash_at_round=2)},
        max_retries=1, checkpoint_every=1))
    sched.start()
    final = _poll(srv, faulted.id)
    assert final["status"] == "done", final
    assert final["attempt"] == 2
    assert final["trace"]["rounds"] >= 1
    assert _poll(srv, mate)["status"] == "done"

    code, ctype, body = _req(srv, f"/trace?job={faulted.id}")
    assert code == 200 and ctype == "application/json"
    tree = json.loads(body)
    assert tree["trace"] == faulted.id
    assert len(tree["spans"]) == 1
    root = tree["spans"][0]
    assert root["name"] == "job"
    assert root["attrs"]["status"] == "done"
    names = _names(root, [])
    for want in ("submit", "queue", "fuse", "run", "round",
                 "checkpoint", "retrying", "resume", "done"):
        assert want in names, (want, names)
    # two attempts; the first's fuse saw the K=2 batch, the resumed
    # attempt ran solo from its checkpoint
    attempts = [c for c in root["children"] if c["name"] == "attempt"]
    assert [a["attrs"]["attempt"] for a in attempts] == [1, 2]
    fuse1 = next(c for c in attempts[0]["children"]
                 if c["name"] == "fuse")
    assert fuse1["attrs"]["k"] == 2 and fuse1["attrs"]["shared_plan"]
    fuse2 = next(c for c in attempts[1]["children"]
                 if c["name"] == "fuse")
    assert "resumed from checkpoint" in fuse2["attrs"]["solo"]
    resume = next(c for c in attempts[1]["children"]
                  if c["name"] == "resume")
    assert resume["attrs"]["from_round"] >= 0

    # monotonic timestamps: every span closes at/after it opens, every
    # child opens at/after its parent, and sibling rounds are ordered
    def check(node):
        assert node["end"] is not None and node["end"] >= node["start"]
        prev_round = None
        for c in node["children"]:
            assert c["start"] >= node["start"] - 1e-6
            if c["name"] == "round":
                if prev_round is not None:
                    assert c["start"] >= prev_round - 1e-6
                prev_round = c["start"]
            check(c)
    check(root)

    # the wire digest agrees with the tree
    assert final["trace"]["queue_ms"] >= 0
    assert final["trace"]["device_ms"] > 0


# ---------------------------------------------------------------------------
# kernel purity + overhead: tracing must not change results, and must
# be removable via one flag within a generous bound
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def snap_main():
    return _sym_snapshot(42)


def _run_bfs_jobs(snap, tracing: bool, sources, kind="bfs"):
    sched = JobScheduler(snapshot=snap, metrics=MetricManager(),
                         tracing=tracing)
    try:
        dists = []
        for s in sources:
            j = sched.submit(JobSpec(kind=kind,
                                     params={"source_dense": int(s)}))
            assert j.wait(120) and j.state.value == "done", j.error
            dists.append(np.asarray(j.result["dist"]))
        return dists
    finally:
        sched.close()


def test_kernel_results_bit_equal_with_tracing_enabled(snap_main):
    """Tracing is host-side bookkeeping only: the distance arrays of a
    traced run must be BIT-EQUAL to an untraced run (no extra device
    work, no perturbed iteration order). SSSP covers the
    ``_trace_rounds`` bridge (the plan trace hooked onto the cached
    CSR), and after a traced run the hook must be detached again."""
    on = _run_bfs_jobs(snap_main, True, [0, 7])
    off = _run_bfs_jobs(snap_main, False, [0, 7])
    for a, b in zip(on, off):
        assert (a == b).all()
    s_on = _run_bfs_jobs(snap_main, True, [0], kind="sssp")
    assert "_trace_rounds" not in snap_main._hybrid_csr
    s_off = _run_bfs_jobs(snap_main, False, [0], kind="sssp")
    assert (s_on[0] == s_off[0]).all()


def test_tracing_overhead_within_generous_bound(snap_main):
    """ISSUE r10 CI guard on the shared n=192/m=900 shape: tracer
    enabled vs disabled stays within a GENEROUS wall-clock bound (the
    hooks are host timestamps at existing boundaries; the bound only
    catches a rewrite that adds device syncs or per-round O(n) host
    work — box noise is ±15%, so the margin is wide)."""
    src = [3] * 4
    _run_bfs_jobs(snap_main, True, src[:1])     # warm the compile
    t0 = time.time()
    _run_bfs_jobs(snap_main, False, src)
    off_s = time.time() - t0
    t0 = time.time()
    _run_bfs_jobs(snap_main, True, src)
    on_s = time.time() - t0
    assert on_s <= off_s * 8 + 2.0, (
        f"tracing overhead blew the generous bound: "
        f"on={on_s:.3f}s off={off_s:.3f}s")
