"""TTL: per-type cell expiry.

Modeled on the reference's TTL tests in TitanGraphTest (titan-test;
mgmt.setTTL on edge labels / property keys, vertex TTL on static labels)
and the HBase storeTTL/cellTTL feature contract.
"""

import time

import pytest

import titan_tpu
from titan_tpu.errors import TitanError
from titan_tpu.storage.api import (Entry, KeySliceQuery, SliceQuery, TTLEntry,
                                   entry_ttl)
from titan_tpu.storage.inmemory import InMemoryStoreManager


@pytest.fixture(params=["inmemory", "sqlite"])
def graph(request, tmp_path):
    if request.param == "inmemory":
        g = titan_tpu.open("inmemory")
    else:
        g = titan_tpu.open({"storage.backend": "sqlite",
                            "storage.directory": str(tmp_path / "db")})
    yield g
    g.close()


def test_entry_ttl_helper():
    assert entry_ttl(Entry(b"c", b"v")) == 0.0
    assert entry_ttl(TTLEntry(b"c", b"v", 5.0)) == 5.0


def test_store_level_cell_ttl():
    mgr = InMemoryStoreManager()
    assert mgr.features.cell_ttl
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    store.mutate(b"k", [TTLEntry(b"a", b"1", 0.05), Entry(b"b", b"2")], [], txh)
    res = store.get_slice(KeySliceQuery(b"k", SliceQuery()), txh)
    assert [e.column for e in res] == [b"a", b"b"]
    time.sleep(0.07)
    res = store.get_slice(KeySliceQuery(b"k", SliceQuery()), txh)
    assert [e.column for e in res] == [b"b"]


def test_edge_label_ttl(graph):
    mgmt = graph.management()
    label = mgmt.make_edge_label("session")
    mgmt.set_ttl(label, 0.2)
    assert mgmt.get_ttl("session") == pytest.approx(0.2)
    mgmt.commit()

    tx = graph.new_transaction()
    a = tx.add_vertex("person", name="a")
    b = tx.add_vertex("person", name="b")
    a.add_edge("session", b)
    a.add_edge("knows", b)   # no TTL
    aid = a.id
    tx.commit()

    tx2 = graph.new_transaction()
    assert len(list(tx2.vertex(aid).out_edges("session"))) == 1
    tx2.rollback()

    time.sleep(0.25)
    tx3 = graph.new_transaction()
    assert len(list(tx3.vertex(aid).out_edges("session"))) == 0
    assert len(list(tx3.vertex(aid).out_edges("knows"))) == 1
    tx3.rollback()


def test_property_key_ttl(graph):
    mgmt = graph.management()
    key = mgmt.make_property_key("otp", str)
    mgmt.set_ttl(key, 0.2)
    mgmt.commit()

    tx = graph.new_transaction()
    v = tx.add_vertex("person", name="carol", otp="123456")
    vid = v.id
    tx.commit()

    tx2 = graph.new_transaction()
    assert tx2.vertex(vid).value("otp") == "123456"
    tx2.rollback()
    time.sleep(0.25)
    tx3 = graph.new_transaction()
    assert tx3.vertex(vid).value("otp") is None
    assert tx3.vertex(vid).value("name") == "carol"   # untouched
    tx3.rollback()


def test_vertex_ttl_requires_static_label(graph):
    mgmt = graph.management()
    lbl = mgmt.make_vertex_label("ephemeral")   # NOT static
    with pytest.raises(TitanError):
        mgmt.set_ttl(lbl, 1.0)


def test_static_vertex_label_ttl(graph):
    mgmt = graph.management()
    lbl = mgmt.make_vertex_label("flash", static=True)
    mgmt.set_ttl(lbl, 0.2)
    mgmt.commit()

    tx = graph.new_transaction()
    v = tx.add_vertex("flash", note="gone soon")
    vid = v.id
    tx.commit()

    tx2 = graph.new_transaction()
    assert tx2.vertex(vid) is not None
    tx2.rollback()
    time.sleep(0.25)
    tx3 = graph.new_transaction()
    assert tx3.vertex(vid) is None   # whole vertex expired
    tx3.rollback()


def test_static_label_blocks_later_modification(graph):
    """Static vertices cannot be modified after the creating tx (reference:
    VertexLabel static semantics) — the invariant vertex TTL relies on."""
    from titan_tpu.errors import SchemaViolationError
    mgmt = graph.management()
    mgmt.make_vertex_label("frozen", static=True)
    mgmt.commit()
    tx = graph.new_transaction()
    v = tx.add_vertex("frozen", note="initial")   # creating tx: allowed
    vid = v.id
    tx.commit()
    tx2 = graph.new_transaction()
    v2 = tx2.vertex(vid)
    with pytest.raises(SchemaViolationError):
        v2.property("note", "changed")
    with pytest.raises(SchemaViolationError):
        v2.remove()
    with pytest.raises(SchemaViolationError):
        tx2.add_vertex("person", name="x").add_edge("sees", v2)
    tx2.rollback()


def test_expired_vertex_frees_unique_index(graph):
    """Composite index entries expire WITH their element: a unique name can
    be reused after the TTL'd vertex is gone (no permanent ghost row)."""
    mgmt = graph.management()
    lbl = mgmt.make_vertex_label("token", static=True)
    mgmt.set_ttl(lbl, 0.2)
    key = mgmt.make_property_key("code", str)
    mgmt.build_index("byCode", "vertex").add_key(key).unique() \
        .build_composite_index()
    mgmt.commit()

    tx = graph.new_transaction()
    tx.add_vertex("token", code="X1")
    tx.commit()
    time.sleep(0.25)
    tx2 = graph.new_transaction()
    v2 = tx2.add_vertex("token", code="X1")   # reuse after expiry
    tx2.commit()
    tx3 = graph.new_transaction()
    hits = tx3.query().has("code", "X1").vertices()
    assert [v.id for v in hits] == [v2.id]
    tx3.rollback()


def test_ttl_survives_wal_payload_roundtrip(graph):
    """TTLEntry rows in a WAL payload replay as plain entries."""
    from titan_tpu.storage.api import TTLEntry
    adds = [tuple(TTLEntry(b"c", b"v", 3.0)), tuple(Entry(b"d", b"w"))]
    assert [Entry(a[0], a[1]) for a in adds] == [Entry(b"c", b"v"),
                                                Entry(b"d", b"w")]
