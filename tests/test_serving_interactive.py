"""Interactive traversal lane (ISSUE 11, olap/serving/interactive).

Property tests pinning compiled micro-traversals BIT-EQUAL to the
``traversal/dsl.py`` interpreter (directions × depths × labels,
including under a live overlay with adds AND base-edge tombstones),
batched personalized PageRank bit-equal per source to the
``pagerank_dense(reset=...)`` oracle, the HTTP-level fusion contract
(N concurrent ``POST /traverse`` calls → ONE fused device batch), the
loud interpreter fallback, the tenant-quota 429, and the lane's p95
SLO wiring (``obs.slo.SLO(metric="serving.interactive.latency_ms")``).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import titan_tpu
from titan_tpu.olap.serving.interactive import (FallbackToInterpreter,
                                                PPRPlan, TraversalPlan,
                                                compile_traversal,
                                                plan_from_wire,
                                                traversal_from_plan)
from titan_tpu.olap.serving.scheduler import JobScheduler


@pytest.fixture(scope="module")
def social():
    """Random labeled multigraph (parallel edges possible) shared by
    the module — built once, traversed many ways."""
    g = titan_tpu.open("inmemory")
    rng = np.random.default_rng(42)
    n = 48
    tx = g.new_transaction()
    vs = [tx.add_vertex("person", name=f"p{i}", age=int(rng.integers(1, 90)))
          for i in range(n)]
    for lab, m in (("knows", 90), ("likes", 60)):
        for a, b in zip(rng.integers(0, n, m), rng.integers(0, n, m)):
            if a != b:
                vs[int(a)].add_edge(lab, vs[int(b)])
    tx.commit()
    yield g
    g.close()


@pytest.fixture(scope="module")
def lane_sched(social):
    sched = JobScheduler(graph=social, autostart=False,
                         interactive_window_s=0.005)
    yield sched, sched.interactive()
    sched.close()


def _ids(g):
    out = sorted(v.id for v in g.traversal().V().to_list())
    g.rollback()
    return out


def _interpret(g, plan):
    t = traversal_from_plan(plan, g.traversal())
    out = t.to_list()
    g.rollback()          # fresh read view for the next check
    return out


def _check(g, lane, plan):
    res = lane.submit(plan)
    want = _interpret(g, plan)
    if plan.terminal == "count":
        assert res["result"] == (want[0] if want else 0), plan
    else:
        assert sorted(map(str, res["result"])) \
            == sorted(map(str, want)), plan
    return res


# ---------------------------------------------------------------- compiler

def test_compile_subset_gating(social):
    g = social.traversal()
    ok = compile_traversal(g.V(1).out().out().dedup().id_())
    assert ok is not None and ok.depth == 2 and ok.terminal == "id"
    rep = compile_traversal(
        g.V(1).out("knows").out("knows").dedup().count())
    assert rep is not None and rep.labels == ("knows",)
    # outside the subset: each miss interprets instead
    T = social.traversal
    assert compile_traversal(T().V(1).out().id_()) is None  # no dedup
    assert compile_traversal(T().V(1).out().in_().dedup().id_()) \
        is None                                             # mixed dir
    # per-hop label changes COMPILE since ISSUE 13 (union lease +
    # per-level slot masks); the fuse key carries the hop chain
    mixed = compile_traversal(
        T().V(1).out("knows").out("likes").dedup().id_())
    assert mixed is not None \
        and mixed.hop_labels == (("knows",), ("likes",)) \
        and mixed.labels == ("knows", "likes") \
        and mixed.hop_labels in mixed.fuse_key()
    # ...but an ALL-labels hop inside a labeled chain still interprets
    # (no union lease carries the unfiltered edge set)
    assert compile_traversal(
        T().V(1).out("knows").out().dedup().id_()) is None
    assert compile_traversal(T().V().out().dedup().id_()) is None  # no ids
    assert compile_traversal(T().V(1).dedup().id_()) is None  # no hops
    assert compile_traversal(
        T().V(1).out().dedup().values("a", "b")) is None  # multi-key
    deep = T().V(1)
    for _ in range(5):
        deep = deep.out()
    assert compile_traversal(deep.dedup().id_()) is None  # > max depth


def test_repeat_times_expands(social):
    from titan_tpu.traversal.dsl import anon
    t = social.traversal().V(1).repeat(anon().out("knows")).times(3) \
        .dedup().count()
    plan = compile_traversal(t)
    assert plan is not None and plan.depth == 3 \
        and plan.labels == ("knows",)


def test_plan_from_wire_validation():
    with pytest.raises(ValueError):
        plan_from_wire({"dir": "out"})              # no start
    with pytest.raises(ValueError):
        plan_from_wire({"start": [1], "dir": "up"})
    with pytest.raises(ValueError):
        plan_from_wire({"start": [1], "hops": 0})
    with pytest.raises(ValueError):
        plan_from_wire({"start": [1], "terminal": "paths"})
    with pytest.raises(ValueError):
        plan_from_wire({"kind": "ppr"})             # no source
    with pytest.raises(ValueError):                 # unbounded reply
        plan_from_wire({"kind": "ppr", "source": 1, "top_k": -1})
    with pytest.raises(ValueError):
        plan_from_wire({"kind": "ppr", "source": 1, "damping": 1.5})
    with pytest.raises(ValueError):
        plan_from_wire({"kind": "ppr", "source": 1, "iterations": 0})
    # scalar start form — vertex id 0 is a valid id, not "missing"
    assert plan_from_wire({"start": 0}).start_ids == (0,)
    with pytest.raises(ValueError):     # bare string would explode
        plan_from_wire({"start": [1], "labels": "knows"})
    with pytest.raises(ValueError):
        plan_from_wire({"kind": "ppr", "source": 1, "labels": "x"})
    with pytest.raises(ValueError):
        plan_from_wire({"start": [1], "hops": 1 << 30})
    p = plan_from_wire({"start": [7], "dir": "in", "hops": 2,
                        "labels": ["knows"],
                        "terminal": {"values": "name"}})
    assert isinstance(p, TraversalPlan) \
        and p.terminal == ("values", "name")


# ------------------------------------------------- interpreter equivalence

@pytest.mark.parametrize("dirname", ["out", "in", "both"])
@pytest.mark.parametrize("hops", [1, 2, 3])
def test_compiled_bit_equal_to_interpreter(social, lane_sched, dirname,
                                           hops):
    _sched, lane = lane_sched
    ids = _ids(social)
    for vid in ids[::11]:
        for terminal in ("id", "count"):
            _check(social, lane, plan_from_wire(
                {"start": [vid], "dir": dirname, "hops": hops,
                 "terminal": terminal}))


def test_mixed_label_chains_bit_equal_to_interpreter(social, lane_sched):
    """ISSUE 13 satellite: per-hop label changes run COMPILED (union
    lease + per-level slot masks through frontier_bfs_batched's
    level_masks seam) and stay bit-equal to the interpreter across
    directions, depths and the dsl path."""
    sched, lane = lane_sched
    ids = _ids(social)
    f0 = sched._metrics.counter_value("serving.interactive.fallbacks")
    _check(social, lane, plan_from_wire(
        {"start": [ids[1]], "dir": "out", "hops": 2,
         "labels": [["knows"], ["likes"]], "terminal": "id"}))
    _check(social, lane, plan_from_wire(
        {"start": [ids[2]], "dir": "both", "hops": 2,
         "labels": [["likes"], ["knows"]], "terminal": "count"}))
    _check(social, lane, plan_from_wire(
        {"start": [ids[5]], "dir": "in", "hops": 3,
         "labels": [["likes"], ["knows"], ["likes"]],
         "terminal": "id"}))
    _check(social, lane, plan_from_wire(
        {"start": ids[:3], "dir": "out", "hops": 3,
         "labels": [["knows"], ["knows"], ["likes"]],
         "terminal": "id"}))
    # the dsl compile path produces the same plan shape
    plan = compile_traversal(
        social.traversal().V(ids[1]).out("knows").out("likes")
        .dedup().id_())
    social.rollback()
    assert plan is not None and plan.hop_labels is not None
    _check(social, lane, plan)
    # none of those fell back to the interpreter
    assert sched._metrics.counter_value(
        "serving.interactive.fallbacks") == f0
    # wire validation: per-hop list length must match hops; empty or
    # non-string sets are 400s
    with pytest.raises(ValueError):
        plan_from_wire({"start": [ids[0]], "hops": 3,
                        "labels": [["a"], ["b"]]})
    with pytest.raises(ValueError):
        plan_from_wire({"start": [ids[0]], "hops": 2,
                        "labels": [["a"], []]})
    # uniform per-hop form folds back to a plain labeled plan
    p = plan_from_wire({"start": [ids[0]], "hops": 2,
                        "labels": [["knows"], ["knows"]]})
    assert p.hop_labels is None and p.labels == ("knows",)


def test_compiled_labels_values_and_multistart(social, lane_sched):
    _sched, lane = lane_sched
    ids = _ids(social)
    _check(social, lane, plan_from_wire(
        {"start": ids[:3], "dir": "out", "hops": 2,
         "labels": ["knows"], "terminal": "id"}))
    _check(social, lane, plan_from_wire(
        {"start": [ids[5]], "dir": "both", "hops": 2,
         "labels": ["likes"], "terminal": "count"}))
    _check(social, lane, plan_from_wire(
        {"start": [ids[2]], "dir": "out", "hops": 1,
         "terminal": {"values": "name"}}))
    # unknown start ids answer empty, like the interpreter's V() skip
    res = lane.submit(plan_from_wire(
        {"start": [999999], "dir": "out", "hops": 2,
         "terminal": "count"}))
    assert res["result"] == 0


def test_concurrent_point_queries_fuse_and_stay_bit_equal(
        social, lane_sched):
    sched, lane = lane_sched
    ids = _ids(social)
    m = sched._metrics
    b0 = m.counter_value("serving.interactive.batches")
    results = {}
    barrier = threading.Barrier(6)

    def go(vid, hops):
        barrier.wait()
        results[vid] = lane.submit(plan_from_wire(
            {"start": [vid], "dir": "both", "hops": hops,
             "terminal": "id"}))

    # MIXED depths fuse too (shallower members deactivate through the
    # keep mask)
    threads = [threading.Thread(target=go, args=(v, 2 + (i % 2)))
               for i, v in enumerate(ids[:6])]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert {r["fused_k"] for r in results.values()} == {6}
    assert len({r["batch"] for r in results.values()}) == 1
    # requests are answered inside the sweep; the batch counter lands
    # moments later in the worker's finally — wait for it
    deadline = time.time() + 5
    while m.counter_value("serving.interactive.batches") != b0 + 1 \
            and time.time() < deadline:
        time.sleep(0.01)
    assert m.counter_value("serving.interactive.batches") == b0 + 1
    for i, (vid, r) in enumerate(sorted(results.items())):
        hops = r["hops"]
        want = _interpret(social, plan_from_wire(
            {"start": [vid], "dir": "both", "hops": hops,
             "terminal": "id"}))
        assert sorted(r["result"]) == sorted(want), vid
    # the batch left a readable trace
    tree = sched.tracer.tree(results[ids[0]]["batch"])
    assert tree is not None \
        and tree["spans"][0]["name"] == "interactive"


# ------------------------------------------------------- under live writes

def test_compiled_bit_equal_under_live_overlay():
    from titan_tpu.olap.live.compactor import EpochCompactor
    from titan_tpu.olap.live.plane import LiveGraphPlane

    g = titan_tpu.open("inmemory")
    try:
        rng = np.random.default_rng(42)
        n = 40
        tx = g.new_transaction()
        vs = [tx.add_vertex("node", name=f"v{i}") for i in range(n)]
        edges = []
        for a, b in zip(rng.integers(0, n, 110),
                        rng.integers(0, n, 110)):
            if a != b:
                edges.append(vs[int(a)].add_edge("link", vs[int(b)]))
        tx.commit()
        plane = LiveGraphPlane(
            g, compactor=EpochCompactor(max_fill=0.99,
                                        max_tomb_fraction=0.99))
        sched = JobScheduler(live=plane, autostart=False,
                             interactive_window_s=0.003)
        lane = sched.interactive()
        ids = _ids(g)
        try:
            snap0, _v0, _i0 = plane.lease_state()
            # live adds land in the overlay, not a rebuild
            tx = g.new_transaction()
            a, b = tx.vertex(ids[0]), tx.vertex(ids[20])
            a.add_edge("link", b)
            b.add_edge("link", tx.vertex(ids[30]))
            tx.commit()
            # a BASE edge removal lands as a tombstone
            tx = g.new_transaction()
            for e in tx.vertex(ids[3]).out_edges():
                e.remove()
                break
            tx.commit()
            for vid in ids[:8]:
                for hops in (1, 2, 3):
                    _check(g, lane, plan_from_wire(
                        {"start": [vid], "dir": "both", "hops": hops,
                         "terminal": "id"}))
            # the checks above really ran against the OVERLAY on the
            # unrepublished base — not a rebuilt snapshot
            snap1, view, _info = plane.lease_state()
            assert snap1 is snap0
            st = plane.stats()["overlay"]
            assert st["adds"] >= 4 and st["tombstones"] >= 1, st
            assert view.count >= 4 and view.tomb_count >= 1
        finally:
            sched.close()
    finally:
        g.close()


# --------------------------------------------------- personalized PageRank

def test_batched_ppr_bit_equal_per_source():
    from titan_tpu.models.frontier import pagerank_dense
    from titan_tpu.models.pagerank import pagerank_personalized_batched
    from titan_tpu.olap.tpu import snapshot as snap_mod

    rng = np.random.default_rng(42)
    n, m = 192, 900
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    sources = [0, 7, 63, 100, 191]
    ranks, iters = pagerank_personalized_batched(snap, sources,
                                                 iterations=12)
    assert iters == 12 and ranks.shape == (5, n)
    for s, sd in enumerate(sources):
        reset = np.zeros(n, np.float32)
        reset[sd] = 1.0
        ref, _ = pagerank_dense(snap, iterations=12, reset=reset)
        assert np.array_equal(np.asarray(ref), ranks[s]), sd


def test_ppr_served_through_lane(social, lane_sched):
    from titan_tpu.models.frontier import pagerank_dense
    from titan_tpu.olap.tpu import snapshot as snap_mod

    _sched, lane = lane_sched
    ids = _ids(social)
    res = lane.submit(PPRPlan(source=ids[1], iterations=8, top_k=4))
    assert res["iterations"] == 8 and len(res["result"]) <= 4
    # oracle: sequential personalized run over the same (symmetrized)
    # snapshot, self excluded
    snap = snap_mod.build(social, directed=False)
    reset = np.zeros(snap.n, np.float32)
    sd = snap.dense_of(ids[1])
    reset[sd] = 1.0
    ref, _ = pagerank_dense(snap, iterations=8, reset=reset)
    ref = np.asarray(ref)
    order = np.argsort(-ref, kind="stable")
    want = [int(snap.vertex_ids[i]) for i in order
            if i != sd and ref[i] > 0][:4]
    assert [vid for vid, _r in res["result"]] == want


def test_ppr_fuses_users_into_one_batch(social, lane_sched):
    sched, lane = lane_sched
    ids = _ids(social)
    m = sched._metrics
    u0 = m.counter_value("serving.interactive.ppr_users")
    results = {}
    barrier = threading.Barrier(4)

    def go(vid):
        barrier.wait()
        results[vid] = lane.submit(PPRPlan(source=vid, iterations=6,
                                           top_k=3))

    threads = [threading.Thread(target=go, args=(v,))
               for v in ids[:4]]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert {r["fused_k"] for r in results.values()} == {4}
    assert m.counter_value("serving.interactive.ppr_users") == u0 + 4


def test_pagerank_dense_reset_validation():
    from titan_tpu.models.frontier import pagerank_dense
    from titan_tpu.olap.tpu import snapshot as snap_mod

    snap = snap_mod.from_arrays(8, [0, 1], [1, 2])
    with pytest.raises(ValueError):
        pagerank_dense(snap, iterations=2,
                       reset=np.ones(5, np.float32))


# ------------------------------------------------------------ HTTP surface

def _req(srv, path, payload=None, method="GET"):
    req = urllib.request.Request(
        f"http://{srv.host}:{srv.port}{path}",
        data=json.dumps(payload).encode() if payload is not None
        else None,
        headers={"Content-Type": "application/json"}, method=method)
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def served():
    from titan_tpu import example
    from titan_tpu.server import GraphServer

    from titan_tpu.olap.serving.tenants import TenantQuota

    g = titan_tpu.open("inmemory")
    example.load(g)
    sched = JobScheduler(graph=g, autostart=False,
                         interactive_window_s=0.25,
                         quotas={"flooder": TenantQuota(
                             max_in_flight=0)},
                         enforce_quotas=True)
    srv = GraphServer(g, port=0, scheduler=sched).start()
    yield g, srv, sched
    srv.stop()
    g.close()


def test_http_concurrent_traverse_fuse_into_one_batch(served):
    g, srv, sched = served
    _code, body = _req(srv, "/traversal",
                       {"gremlin": "sorted(v.id for v in "
                                   "g.V().to_list())"}, "POST")
    vids = body["result"][:6]
    out = {}

    def go(vid):
        out[vid] = _req(srv, "/traverse",
                        {"start": [vid], "dir": "both", "hops": 2,
                         "terminal": "id"}, "POST")

    threads = [threading.Thread(target=go, args=(v,)) for v in vids]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert all(c == 200 for c, _b in out.values())
    assert {b["fused_k"] for _c, b in out.values()} == {6}
    assert len({b["batch"] for _c, b in out.values()}) == 1
    for vid, (_c, b) in out.items():
        _c2, ref = _req(srv, "/traversal",
                        {"gremlin": f"g.V({vid}).both().both()"
                                    f".dedup().id_()"}, "POST")
        assert sorted(b["result"]) == sorted(ref["result"]), vid
        assert b["fallback"] is False and "epoch" in b
    # the fused batch is visible on the metric plane
    code, text = _prom(srv)
    assert "serving_interactive_fuse_k" in text


def _prom(srv):
    req = urllib.request.Request(
        f"http://{srv.host}:{srv.port}/metrics")
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, r.read().decode()


def test_http_gremlin_fallback_is_loud(served):
    g, srv, sched = served
    m = sched._metrics
    f0 = m.counter_value("serving.interactive.fallbacks")
    _code, body = _req(srv, "/traversal",
                       {"gremlin": "g.V().has('name','jupiter')"
                                   ".next().id"}, "POST")
    vid = body["result"]
    # no dedup → path-multiplicity count → interpreter, flagged
    code, b = _req(srv, "/traverse",
                   {"gremlin": f"g.V({vid}).out().out().count()"},
                   "POST")
    assert code == 200 and b["fallback"] is True
    _c, ref = _req(srv, "/traversal",
                   {"gremlin": f"g.V({vid}).out().out().count()"
                               ".next()"}, "POST")
    assert b["result"] == [ref["result"]]
    assert m.counter_value("serving.interactive.fallbacks") == f0 + 1
    # compiled gremlin answers on the device lane
    code, b = _req(srv, "/traverse",
                   {"gremlin": f"g.V({vid}).out().dedup().count()"},
                   "POST")
    assert code == 200 and b["fallback"] is False
    _c, ref = _req(srv, "/traversal",
                   {"gremlin": f"g.V({vid}).out().dedup().count()"
                               ".next()"}, "POST")
    assert b["result"] == ref["result"]


def test_http_traverse_quota_429_and_bad_request_400(served):
    g, srv, sched = served
    _code, body = _req(srv, "/traversal",
                       {"gremlin": "g.V().next().id"}, "POST")
    vid = body["result"]
    code, b = _req(srv, "/traverse",
                   {"start": [vid], "dir": "out", "hops": 1,
                    "terminal": "id", "tenant": "flooder"}, "POST")
    assert code == 429 and b["retryable"] is True
    # uncompilable chains are NOT a free interpreter ride around the
    # quota — the fallback path flows through the same gate
    code, b = _req(srv, "/traverse",
                   {"gremlin": f"g.V({vid}).out().count()",
                    "tenant": "flooder"}, "POST")
    assert code == 429 and b["retryable"] is True
    # depth past the lane ceiling falls back too — same gate
    code, b = _req(srv, "/traverse",
                   {"start": [vid], "hops": 9, "terminal": "count",
                    "tenant": "flooder"}, "POST")
    assert code == 429
    code, _b = _req(srv, "/traverse", {"start": [vid], "dir": "up"},
                    "POST")
    assert code == 400
    code, _b = _req(srv, "/traverse",
                    {"start": [vid], "hops": 1 << 30}, "POST")
    assert code == 400            # unbounded chain-build guard
    code, _b = _req(srv, "/traverse",
                    {"gremlin": "not a chain ("}, "POST")
    assert code == 400


def test_slo_metric_field_reads_interactive_latency():
    from titan_tpu.obs.slo import SLO, SLOEngine
    from titan_tpu.utils.metrics import MetricManager

    m = MetricManager()
    h = m.histogram("serving.interactive.latency_ms",
                    labels={"tenant": "default"})
    for v in (1.0, 2.0, 3.0, 50.0):       # one of four over 10ms
        h.update(v)
    clock = [1000.0]
    eng = SLOEngine(m, [SLO("inter-p95", p95_ms=10.0,
                            metric="serving.interactive.latency_ms",
                            windows=(60.0,))],
                    clock=lambda: clock[0])
    rep = eng.evaluate()
    slo = rep["slos"][0]
    assert slo["objective"]["metric"] \
        == "serving.interactive.latency_ms"
    assert slo["sli"]["events"] == 4 and slo["sli"]["bad"] == 1.0
    clock[0] += 30.0
    rep = eng.evaluate()
    w = rep["slos"][0]["windows"]["60s"]
    # 1 bad / 4 events / 0.05 budget = burn 5.0
    assert w["burn_rate"] == pytest.approx(5.0)
    with pytest.raises(ValueError):
        SLO("bad", success_rate=0.9,
            metric="serving.interactive.latency_ms")


def test_tenant_attribution_flows_through_lane(social):
    sched = JobScheduler(graph=social, autostart=False,
                         interactive_window_s=0.003)
    lane = sched.interactive()
    try:
        ids = _ids(social)
        lane.submit(plan_from_wire(
            {"start": [ids[0]], "dir": "both", "hops": 2,
             "terminal": "count"}), tenant="team-a")
        rows = sched.tenant_stats()["tenants"]
        assert rows["team-a"]["by_state"].get("completed") == 1
        # the batch-wall share lands in the worker's finally, moments
        # after the request is answered — wait for it
        deadline = time.time() + 5
        while sched.tenant_stats()["tenants"]["team-a"][
                "device_seconds"] <= 0 and time.time() < deadline:
            time.sleep(0.01)
        rows = sched.tenant_stats()["tenants"]
        assert rows["team-a"]["device_seconds"] > 0
        assert sched._metrics.counter_value(
            "serving.interactive.requests",
            labels={"tenant": "team-a"}) == 1
    finally:
        sched.close()
