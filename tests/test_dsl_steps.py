"""Traverser bulking + TP3 step-library coverage for the traversal DSL.

Mirrors TinkerPop semantics the reference inherits from its embedded TP3
runtime (reference: titan-all TitanGremlinPlugin.java:18 imports the whole
step library; LazyBarrierStrategy provides bulking, which Titan's
TitanVertexStep batching seam relies on — TitanVertexStep.java:69-96).
"""

import operator
import os

import numpy as np
import pytest

import titan_tpu
from titan_tpu import example
from titan_tpu.traversal.dsl import anon


@pytest.fixture(scope="module")
def gods():
    g = titan_tpu.open("inmemory")
    example.load(g)
    yield g
    g.close()


@pytest.fixture(scope="module")
def social():
    """Random dense-ish social graph where path counts explode: the
    bulked-vs-unbulked equivalence fixture."""
    g = titan_tpu.open("inmemory")
    rng = np.random.default_rng(11)
    n, deg = 400, 8
    tx = g.new_transaction()
    people = [tx.add_vertex("person", name=f"p{i}") for i in range(n)]
    for a, b in zip(rng.integers(0, n, n * deg // 2),
                    rng.integers(0, n, n * deg // 2)):
        if a != b:
            people[int(a)].add_edge("knows", people[int(b)])
    tx.commit()
    yield g
    g.close()


# ---------------------------------------------------------------- bulking

def _unbulked(monkeypatch_env, fn):
    os.environ["TITAN_TPU_NO_BULK"] = "1"
    try:
        return fn()
    finally:
        del os.environ["TITAN_TPU_NO_BULK"]


@pytest.mark.parametrize("hops", [2, 3, 4])
def test_bulked_khop_count_matches_unbulked(social, hops):
    g = social
    tx = g.new_transaction()
    vid = next(iter(tx.vertices())).id
    tx.rollback()

    def khop():
        t = g.traversal().V(vid)
        for _ in range(hops):
            t = t.out("knows")
        return t.count().next()

    bulked = khop()
    unbulked = _unbulked(None, khop)
    assert bulked == unbulked
    assert bulked > 0


def test_bulked_path_count_is_paths_not_vertices(gods):
    # count() counts PATHS (sum of bulks), not distinct end vertices
    g = gods.traversal()
    assert g.V().out().out().count().next() == 28


def test_bulk_groupcount_matches_unbulked(social):
    g = social

    def gc():
        return g.traversal().V().out("knows").out("knows") \
            .group_count().by("name").next()

    assert gc() == _unbulked(None, gc)


def test_bulk_sum_mean_fold(social):
    g = social
    tx = g.new_transaction()
    vid = next(iter(tx.vertices())).id
    tx.rollback()

    def agg(kind):
        t = g.traversal().V(vid).out("knows").out("knows").constant(2)
        return getattr(t, kind)().next()

    assert agg("sum_") == _unbulked(None, lambda: agg("sum_"))
    assert agg("mean") == pytest.approx(2.0)
    # fold expands bulks back into repeated objects
    def folded():
        return len(g.traversal().V(vid).out("knows").out("knows")
                   .fold().next())
    assert folded() == _unbulked(None, folded)


def test_bulk_limit_splits(social):
    g = social
    out = g.traversal().V().out("knows").out("knows").limit(7).to_list()
    assert len(out) == 7


def test_path_disables_bulking(social):
    g = social
    tx = g.new_transaction()
    vid = next(iter(tx.vertices())).id
    tx.rollback()
    paths = g.traversal().V(vid).out("knows").out("knows").path().to_list()
    n = g.traversal().V(vid).out("knows").out("knows").count().next()
    assert len(paths) == n
    assert all(len(p) == 3 for p in paths)


def test_dedup_resets_bulk(social):
    g = social
    distinct = g.traversal().V().out("knows").dedup().count().next()
    total = g.traversal().V().out("knows").count().next()
    assert 0 < distinct <= total


# ---------------------------------------------------------------- steps

def test_union(gods):
    g = gods.traversal()
    names = set(g.V().has("name", "hercules")
                .union(anon().out("father"), anon().out("mother"))
                .values("name").to_list())
    assert names == {"jupiter", "alcmene"}


def test_union_multiplicity(gods):
    g = gods.traversal()
    # union duplicates the stream per child: 2 children over all vertices
    n = g.V().count().next()
    assert g.V().union(anon().id_(), anon().id_()).count().next() == 2 * n


def test_coalesce_first_nonempty(gods):
    g = gods.traversal()
    # hercules has no "pet" edges -> falls through to father
    names = g.V().has("name", "hercules") \
        .coalesce(anon().out("pet"), anon().out("father")) \
        .values("name").to_list()
    assert names == ["jupiter"]
    # pluto HAS a pet -> first child wins
    names = gods.traversal().V().has("name", "pluto") \
        .coalesce(anon().out("pet"), anon().out("father")) \
        .values("name").to_list()
    assert names == ["cerberus"]


def test_choose_predicate_form(gods):
    g = gods.traversal()
    out = g.V().has_label("god") \
        .choose(lambda v: v.value("age") > 4200,
                anon().values("name"), anon().constant("young")) \
        .to_list()
    assert sorted(out) == ["jupiter", "neptune", "young"]


def test_choose_switch_form_with_options(gods):
    g = gods.traversal()
    out = g.V().has("name", "hercules") \
        .choose(lambda v: v.label()) \
        .option("demigod", anon().out("battled").values("name")) \
        .option("none", anon().constant("other")) \
        .to_list()
    assert sorted(out) == ["cerberus", "hydra", "nemean"]


def test_branch_routes_to_all_matching(gods):
    g = gods.traversal()
    out = g.V().has("name", "jupiter") \
        .branch(lambda v: v.label()) \
        .option("god", anon().values("name")) \
        .option("any", anon().label()) \
        .to_list()
    assert sorted(out) == ["god", "jupiter"]


def test_project_with_by(gods):
    g = gods.traversal()
    rows = g.V().has_label("god").order(by="name") \
        .project("n", "degree") \
        .by("name") \
        .by(anon().out().count()) \
        .to_list()
    assert [r["n"] for r in rows] == ["jupiter", "neptune", "pluto"]
    assert all(r["degree"] > 0 for r in rows)


def test_group_default_and_by_count(gods):
    g = gods.traversal()
    grouped = g.V().group().by("label").by("name").next()
    assert sorted(grouped["god"]) == ["jupiter", "neptune", "pluto"]
    counts = gods.traversal().V().group().by("label") \
        .by(anon().count()).next()
    assert counts["god"] == 3
    assert counts["monster"] == 3


def test_groupcount_by_modulator(gods):
    g = gods.traversal()
    counts = g.V().group_count().by("label").next()
    assert counts["location"] == 3
    assert counts["titan"] == 1


def test_local_isolates_limit(gods):
    g = gods.traversal()
    # one battled edge per monster-fighter, not one overall
    out = g.V().has_label("demigod") \
        .local(anon().out("battled").order(by="name").limit(1)) \
        .values("name").to_list()
    assert out == ["cerberus"]


def test_sack_accumulates(gods):
    src = gods.traversal().with_sack(1)
    total = src.V().has("name", "hercules").out_e("battled") \
        .sack(operator.add).by("time").sack().sum_().next()
    # times are 1, 2, 12 -> sacks 2, 3, 13
    assert total == 18


def test_unfold_and_fold_roundtrip(gods):
    g = gods.traversal()
    names = g.V().has_label("god").values("name").fold().unfold().to_list()
    assert sorted(names) == ["jupiter", "neptune", "pluto"]


def test_where_sub_and_not(gods):
    g = gods.traversal()
    with_pets = g.V().where(anon().out("pet")).values("name").to_list()
    assert with_pets == ["pluto"]
    no_pets = gods.traversal().V().has_label("god") \
        .not_(anon().out("pet")).values("name").to_list()
    assert sorted(no_pets) == ["jupiter", "neptune"]


def test_and_or(gods):
    g = gods.traversal()
    both = g.V().and_(anon().out("brother"), anon().out("pet")) \
        .values("name").to_list()
    assert both == ["pluto"]
    either = gods.traversal().V().has_label("god") \
        .or_(anon().out("pet"), anon().out("father")) \
        .values("name").to_list()
    assert sorted(either) == ["jupiter", "pluto"]


def test_repeat_until(gods):
    g = gods.traversal()
    # walk father edges up from hercules until a titan is reached
    out = g.V().has("name", "hercules") \
        .repeat(anon().out("father")) \
        .until(lambda v: v.label() == "titan") \
        .values("name").to_list()
    assert out == ["saturn"]


def test_repeat_emit(gods):
    g = gods.traversal()
    out = g.V().has("name", "hercules") \
        .repeat(anon().out("father")).emit().times(2) \
        .values("name").to_list()
    assert sorted(out) == ["jupiter", "saturn"]


def test_store_cap_and_aggregate(gods):
    g = gods.traversal()
    stored = g.V().has_label("god").values("name").store("x").cap("x") \
        .next()
    assert sorted(stored) == ["jupiter", "neptune", "pluto"]
    agg = gods.traversal().V().has_label("god").aggregate("g") \
        .out("lives").cap("g").next()
    assert len(agg) == 3


def test_select_with_by(gods):
    g = gods.traversal()
    rows = g.V().has("name", "hercules").as_("h").out("father").as_("f") \
        .select("h", "f").by("name").by("name").to_list()
    assert rows == [{"h": "hercules", "f": "jupiter"}]


def test_order_by_modulator_desc(gods):
    g = gods.traversal()
    names = g.V().has_label("god").order().by("age", desc=True) \
        .values("name").to_list()
    assert names == ["jupiter", "neptune", "pluto"]


def test_constant(gods):
    g = gods.traversal()
    assert g.V().has_label("god").constant(7).sum_().next() == 21


# ------------------------------------------------- review regressions

def test_limit_zero_yields_nothing(gods):
    g = gods.traversal()
    assert g.V().limit(0).to_list() == []
    assert gods.traversal().V().values("age").limit(0).max_().to_list() == []
    with pytest.raises(StopIteration):
        gods.traversal().V().limit(0).next()


def test_simple_path_inside_where(gods):
    # where(anon().simple_path()) must see real paths (path mode propagates
    # through filter sub-traversals)
    g = gods.traversal()
    direct = gods.traversal().V().has("name", "jupiter") \
        .out("brother").out("brother").simple_path() \
        .values("name").to_list()
    filtered = g.V().has("name", "jupiter") \
        .out("brother").out("brother").where(anon().simple_path()) \
        .values("name").to_list()
    assert sorted(filtered) == sorted(direct)


def test_local_path_sees_full_path(gods):
    out = gods.traversal().V().has("name", "hercules").out("father") \
        .local(anon().path()).to_list()
    assert len(out) == 1 and len(out[0]) == 2


def test_order_multiple_by_primary_then_tiebreak(gods):
    g = gods.traversal()
    # primary: label desc; tie-break: name asc
    names = g.V().has_label("god", "monster").order() \
        .by("label", desc=True).by("name").values("name").to_list()
    assert names == ["cerberus", "hydra", "nemean",
                     "jupiter", "neptune", "pluto"]


def test_until_before_repeat_is_while_do(gods):
    # TP3 while-do: seeds satisfying the predicate exit immediately
    out = gods.traversal().V().has("name", "saturn") \
        .until(lambda v: v.label() == "titan") \
        .repeat(anon().out("father")).values("name").to_list()
    assert out == ["saturn"]


def test_misplaced_modulator_raises(gods):
    with pytest.raises(ValueError):
        gods.traversal().V().by("name").to_list()
    with pytest.raises(ValueError):
        gods.traversal().V().option("x", anon().out()).to_list()
    with pytest.raises(ValueError):
        gods.traversal().V().times(3).to_list()


# ---------------------------------------------------------------- match

def test_match_chain(gods):
    out = gods.traversal().V().has("name", "hercules").match(
        anon().as_("h").out("father").as_("f"),
        anon().as_("f").out("father").as_("gf"),
    ).select("gf").by("name").to_list()
    assert out == ["saturn"]


def test_match_join_constraint(gods):
    # b must satisfy BOTH patterns: jupiter's brother AND a pet owner
    rows = gods.traversal().V().has("name", "jupiter").match(
        anon().as_("a").out("brother").as_("b"),
        anon().as_("b").out("pet").as_("p"),
    ).select("b", "p").by("name").by("name").to_list()
    assert rows == [{"b": "pluto", "p": "cerberus"}]


def test_match_shared_end_var_joins(gods):
    # both hercules and cerberus relate to the same target: father=jupiter
    # vs lives=tartarus never join; father=jupiter vs battled works via
    # two patterns from the same start
    rows = gods.traversal().V().has("name", "hercules").match(
        anon().as_("h").out("battled").as_("m"),
        anon().as_("m").out("lives").as_("place"),
    ).select("m", "place").by("name").by("name").to_list()
    assert {"m": "cerberus", "place": "tartarus"} in rows


def test_match_disconnected_raises(gods):
    with pytest.raises(ValueError, match="bound variable"):
        gods.traversal().V().has("name", "jupiter").match(
            anon().as_("x").out("brother").as_("y"),
            anon().as_("unrelated").out("pet").as_("p"),
        ).to_list()


def test_match_without_start_as_raises(gods):
    with pytest.raises(ValueError, match="as_"):
        gods.traversal().V().match(anon().out("brother")).to_list()


def test_match_mid_pattern_rebinding_enforces_join(gods):
    """Review regression: an as_() MID-pattern that rebinds a shared
    variable must enforce the join (zero rows), not silently overwrite."""
    rows = gods.traversal().V().has("name", "hercules").match(
        anon().as_("a").out("mother").as_("b"),
        anon().as_("a").out("father").as_("b"),
    ).to_list()
    assert rows == []      # mother (alcmene) != father (jupiter)
    # consistent double-binding DOES join
    rows = gods.traversal().V().has("name", "hercules").match(
        anon().as_("a").out("father").as_("b"),
        anon().as_("b").out("father").as_("gf"),
        anon().as_("a").out("father").as_("b"),   # duplicate, consistent
    ).select("gf").by("name").to_list()
    assert rows == ["saturn"]


def test_limit_keeps_vertex_step_lazy(social):
    """ADVICE r3: the bulking barrier is chunked (TP3 NoOpBarrier(2500)
    semantics) — g.V().out().limit(1) must not expand the entire
    frontier's adjacency before limit() can short-circuit."""
    g = social
    calls = []
    tx_cls = type(g.new_transaction())
    real = tx_cls.multi_vertex_edges

    def counting(self, vids, *a, **kw):
        calls.append(len(vids))
        return real(self, vids, *a, **kw)

    tx_cls.multi_vertex_edges = counting
    try:
        got = g.traversal().V().out("knows").out("knows").limit(1).to_list()
    finally:
        tx_cls.multi_vertex_edges = real
    assert len(got) == 1
    # lazy: far fewer sources expanded than the full two-hop frontier
    assert sum(calls) <= 2 * 512 + 2
