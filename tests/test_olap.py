"""OLAP engine tests: snapshot correctness, TPU programs vs numpy references,
single- vs multi-device equivalence, host computer, scan framework.

Modeled on the reference's OLAPTest + SimpleScanJob fixtures (titan-test)."""

import numpy as np
import pytest

import titan_tpu
from titan_tpu import example
from titan_tpu.core.defs import Direction
from titan_tpu.models import bfs, pagerank, sssp, wcc
from titan_tpu.olap.api import Memory, ScanJob, ScanMetrics, VertexProgram
from titan_tpu.olap.computer import HostGraphComputer
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.olap.tpu.engine import TPUGraphComputer
from titan_tpu.storage.scan import StandardScanner


# ---------------------------------------------------------------------------
# numpy reference implementations
# ---------------------------------------------------------------------------

def np_bfs(n, src, dst, source):
    INF = 1 << 30
    dist = np.full(n, INF, dtype=np.int64)
    dist[source] = 0
    frontier = {source}
    d = 0
    adj = {}
    for s, t in zip(src, dst):
        adj.setdefault(s, []).append(t)
    while frontier:
        nxt = set()
        for u in frontier:
            for v in adj.get(u, ()):
                if dist[v] > d + 1:
                    dist[v] = d + 1
                    nxt.add(v)
        frontier = nxt
        d += 1
    return dist


def np_pagerank(n, src, dst, alpha, iters):
    outdeg = np.zeros(n)
    np.add.at(outdeg, src, 1)
    rank = np.full(n, 1.0 / n)
    for _ in range(iters):
        contrib = np.where(outdeg[src] > 0, rank[src] / np.maximum(outdeg[src], 1), 0)
        agg = np.zeros(n)
        np.add.at(agg, dst, contrib)
        rank = (1 - alpha) / n + alpha * agg
    return rank


def np_sssp(n, src, dst, w, source):
    INF = float("inf")
    dist = np.full(n, INF)
    dist[source] = 0
    for _ in range(n):
        nd = dist.copy()
        relax = dist[src] + w
        np.minimum.at(nd, dst, relax)
        if np.array_equal(nd, dist):
            break
        dist = nd
    return dist


def np_wcc(n, src, dst):
    parent = list(range(n))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for s, t in zip(src, dst):
        a, b = find(s), find(t)
        if a != b:
            parent[max(a, b)] = min(a, b)
    return np.array([find(i) for i in range(n)])


def random_graph(n=200, e=1000, seed=7, weights=False):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    ev = {}
    if weights:
        ev["weight"] = rng.uniform(0.1, 5.0, e).astype(np.float32)
    return snap_mod.from_arrays(n, src, dst, edge_values=ev), src, dst, ev


@pytest.fixture(params=[1, 8])
def computer(request):
    def make(snap):
        return TPUGraphComputer(snapshot=snap, num_devices=request.param)
    return make


def test_bfs_matches_numpy(computer):
    snap, src, dst, _ = random_graph()
    res = bfs.run(computer(snap), 0, snapshot=snap)
    ref = np_bfs(snap.n, src, dst, 0)
    got = np.where(res["dist"] >= (1 << 30), 1 << 30, res["dist"])
    assert np.array_equal(got, ref)
    assert res.iterations <= ref[ref < (1 << 30)].max() + 2


def test_pagerank_matches_numpy(computer):
    snap, src, dst, _ = random_graph()
    res = pagerank.run(computer(snap), alpha=0.85, iterations=25, snapshot=snap)
    ref = np_pagerank(snap.n, src, dst, 0.85, 25)
    np.testing.assert_allclose(res["rank"], ref, rtol=1e-4, atol=1e-7)


def test_pagerank_convergence_tol(computer):
    snap, *_ = random_graph()
    res = pagerank.run(computer(snap), iterations=200, tol=1e-7, snapshot=snap)
    assert res.iterations < 200  # tol fired before the budget


def test_sssp_matches_numpy(computer):
    snap, src, dst, ev = random_graph(weights=True)
    res = sssp.run(computer(snap), 0, snapshot=snap)
    ref = np_sssp(snap.n, src, dst, ev["weight"].astype(np.float64), 0)
    finite = ref < float("inf")
    assert np.array_equal(res["dist"] < 3.0e38, finite)
    np.testing.assert_allclose(res["dist"][finite], ref[finite], rtol=1e-4)


def test_wcc_matches_union_find(computer):
    rng = np.random.default_rng(3)
    n = 300
    src = rng.integers(0, n, 400).astype(np.int32)
    dst = rng.integers(0, n, 400).astype(np.int32)
    both_src = np.concatenate([src, dst])
    both_dst = np.concatenate([dst, src])
    snap = snap_mod.from_arrays(n, both_src, both_dst)
    res = wcc.run(computer(snap), snapshot=snap)
    ref = np_wcc(n, src, dst)
    # same partition structure (labels may differ, grouping must match)
    _, got_grp = np.unique(res["label"], return_inverse=True)
    _, ref_grp = np.unique(ref, return_inverse=True)
    assert np.array_equal(got_grp, ref_grp)


def test_single_vs_multi_device_identical():
    snap, *_ = random_graph(n=500, e=4000, seed=11)
    r1 = pagerank.run(TPUGraphComputer(snapshot=snap), iterations=15,
                      snapshot=snap)
    r8 = pagerank.run(TPUGraphComputer(snapshot=snap, num_devices=8),
                      iterations=15, snapshot=snap)
    np.testing.assert_allclose(r1["rank"], r8["rank"], rtol=1e-6)
    b1 = bfs.run(TPUGraphComputer(snapshot=snap), 3, snapshot=snap)
    b8 = bfs.run(TPUGraphComputer(snapshot=snap, num_devices=8), 3,
                 snapshot=snap)
    assert np.array_equal(b1["dist"], b8["dist"])


# ---------------------------------------------------------------------------
# snapshot from a real graph
# ---------------------------------------------------------------------------

class TestSnapshotFromGraph:
    @pytest.fixture
    def gods(self):
        g = titan_tpu.open("inmemory")
        example.load(g)
        yield g
        g.close()

    def test_snapshot_edges_match_oltp(self, gods):
        snap = snap_mod.build(gods)
        assert snap.n == 12
        assert snap.num_edges == 17
        # cross-check adjacency against OLTP reads
        tx = gods.new_transaction()
        for i, vid in enumerate(snap.vertex_ids):
            v = tx.vertex(int(vid))
            out_ids = sorted(n.id for n in v.out())
            lo, hi = None, None
            mask = snap.src == i
            # snapshot is dst-sorted; out-neighbors of i = dst where src==i
            got = sorted(int(snap.vertex_ids[d]) for d in snap.dst[mask])
            assert got == out_ids
        tx.rollback()

    def test_snapshot_label_filter(self, gods):
        snap = snap_mod.build(gods, labels=["battled"])
        assert snap.num_edges == 3

    def test_snapshot_edge_values(self, gods):
        snap = snap_mod.build(gods, labels=["battled"], edge_keys=["time"])
        assert sorted(snap.edge_values["time"].tolist()) == [1, 2, 12]

    def test_graph_compute_entry(self, gods):
        comp = gods.compute()
        assert isinstance(comp, TPUGraphComputer)
        res = pagerank.run(comp, iterations=10)
        assert res.n == 12


# ---------------------------------------------------------------------------
# host computer (VertexProgram path)
# ---------------------------------------------------------------------------

class DegreeProgram(VertexProgram):
    """Counts in-degree via messages (exercise messaging + combiner)."""

    def __init__(self):
        self.rounds = 0

    def execute(self, vertex, messenger, memory):
        if memory.iteration == 0:
            messenger.send(1, [n.id for n in vertex.out()])
        else:
            total = sum(messenger.receive())
            vertex.set_state("indeg", total)

    def terminate(self, memory):
        return memory.iteration >= 1

    def combiner(self):
        return lambda a, b: a + b


def test_host_computer_degree_program():
    g = titan_tpu.open("inmemory")
    example.load(g)
    comp = HostGraphComputer(g, num_threads=4)
    result = comp.run(DegreeProgram(), max_iterations=5)
    assert result.iterations == 2
    tx = g.new_transaction()
    indeg = {v.value("name"): result.state_of(v.id).get("indeg", 0)
             for v in tx.vertices()}
    tx.rollback()
    assert indeg["jupiter"] == 3   # father(hercules), brother x2
    assert indeg["cerberus"] == 2  # battled, pet
    assert indeg["saturn"] == 1
    g.close()


def test_host_computer_dispatch():
    g = titan_tpu.open("inmemory")
    comp = g.compute("host")
    assert isinstance(comp, HostGraphComputer)
    g.close()


# ---------------------------------------------------------------------------
# scan framework
# ---------------------------------------------------------------------------

class CountingJob(ScanJob):
    def __init__(self, queries):
        self._queries = queries
        self.rows = 0
        self.entries = 0
        import threading
        self._lock = threading.Lock()

    def get_queries(self):
        return self._queries

    def process(self, key, entries_by_query, metrics):
        with self._lock:
            self.rows += 1
            self.entries += sum(len(v) for v in entries_by_query.values())


def test_scanner_executes_job_over_store():
    from titan_tpu.storage.api import Entry, SliceQuery
    from titan_tpu.storage.inmemory import InMemoryStoreManager

    m = InMemoryStoreManager()
    store = m.open_database("edgestore")
    t = m.begin_transaction()
    for i in range(100):
        cols = [Entry(bytes([c]), b"v") for c in range(i % 5 + 1)]
        store.mutate(i.to_bytes(8, "big"), cols, [], t)
    t.commit()
    job = CountingJob([SliceQuery(b"\x00", b"\x05")])
    metrics = StandardScanner(store, m).execute(job, num_threads=4)
    assert job.rows == 100
    assert metrics.get(ScanMetrics.SUCCESS) == 100
    # secondary query slicing: primary narrow, secondary wide
    job2 = CountingJob([SliceQuery(b"\x03", b"\x05"), SliceQuery(b"\x00", None)])
    StandardScanner(store, m).execute(job2, num_threads=2)
    assert job2.rows == 40  # only rows with >= 4 columns have column 0x03
