"""Metrics subsystem + operation-counting regression guard.

Modeled on the reference's util/stats/MetricManager +
MetricInstrumentedStore tests and — most importantly —
TitanOperationCountingTest (titan-test), which asserts EXACT backend call
counts per graph operation so backend-chattiness regressions fail loudly.
"""

import pytest

import titan_tpu
from titan_tpu.storage.api import Entry, KeySliceQuery, SliceQuery
from titan_tpu.storage.inmemory import InMemoryStoreManager
from titan_tpu.utils.metrics import (MERGED_STORE, MetricInstrumentedStoreManager,
                                     MetricManager)


@pytest.fixture
def metrics():
    m = MetricManager.instance()
    m.reset()
    yield m
    m.reset()


def test_counter_and_timer_basics(metrics):
    metrics.counter("a.b").inc()
    metrics.counter("a.b").inc(4)
    assert metrics.counter_value("a.b") == 5
    assert metrics.counter_value("missing") == 0
    t = metrics.timer("a.t")
    t.update(1_000_000)
    t.update(3_000_000)
    assert t.count == 2
    assert t.min_ns == 1_000_000 and t.max_ns == 3_000_000
    assert t.mean_ns == 2_000_000
    snap = metrics.snapshot()
    assert snap["a.b"] == 5
    assert snap["a.t"]["count"] == 2
    text = metrics.report_console()
    assert "a.b: 5" in text


def test_csv_report(metrics, tmp_path):
    metrics.counter("x").inc(2)
    metrics.timer("y").update(5_000_000)
    path = tmp_path / "metrics.csv"
    metrics.report_csv(str(path))
    lines = path.read_text().strip().splitlines()
    assert lines[0].startswith("metric,")
    assert any(line.startswith("x,2") for line in lines)
    assert any(line.startswith("y,1") for line in lines)


def test_instrumented_store_counts_ops(metrics):
    mgr = MetricInstrumentedStoreManager(InMemoryStoreManager(), "p",
                                         metrics=metrics)
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    store.mutate(b"k", [Entry(b"c", b"v"), Entry(b"d", b"w")], [], txh)
    res = store.get_slice(KeySliceQuery(b"k", SliceQuery()), txh)
    assert len(res) == 2
    store.get_slice(KeySliceQuery(b"nope", SliceQuery()), txh)
    base = f"p.{MERGED_STORE}"
    assert metrics.counter_value(f"{base}.mutate.calls") == 1
    assert metrics.counter_value(f"{base}.getSlice.calls") == 2
    assert metrics.counter_value(f"{base}.getSlice.entries-returned") == 2
    assert metrics.timer_count(f"{base}.getSlice.time") == 2
    assert metrics.counter_value(f"{base}.getSlice.exceptions") == 0


def test_instrumented_store_counts_exceptions(metrics):
    mgr = MetricInstrumentedStoreManager(InMemoryStoreManager(), "p",
                                         metrics=metrics)
    store = mgr.open_database("s")
    with pytest.raises(NotImplementedError):
        store.acquire_lock(b"k", b"c", None, mgr.begin_transaction())
    assert metrics.counter_value(f"p.{MERGED_STORE}.acquireLock.exceptions") == 1


@pytest.fixture
def metered_graph(metrics):
    g = titan_tpu.open({"storage.backend": "inmemory",
                        "metrics.enabled": True,
                        "metrics.prefix": "t"})
    yield g
    g.close()


def test_tx_lifecycle_counters(metered_graph, metrics):
    g = metered_graph
    base_begin = metrics.counter_value("t.tx.begin")
    tx = g.new_transaction()
    tx.add_vertex("person", name="a")
    tx.commit()
    tx2 = g.new_transaction()
    tx2.rollback()
    assert metrics.counter_value("t.tx.begin") == base_begin + 2
    assert metrics.counter_value("t.tx.commit") == 1
    assert metrics.counter_value("t.tx.rollback") == 1


def test_operation_counting_regression(metered_graph, metrics):
    """The TitanOperationCountingTest contract: a warm single-vertex read by
    id costs exactly ONE edgestore getSlice; a vertex-property read on the
    same loaded vertex costs zero additional backend calls."""
    g = metered_graph
    tx = g.new_transaction()
    v = tx.add_vertex("person", name="a", age=1)
    vid = v.id
    tx.commit()

    base = f"t.{MERGED_STORE}.getSlice.calls"
    multi = f"t.{MERGED_STORE}.getSliceMulti.calls"

    tx2 = g.new_transaction()
    before = metrics.counter_value(base) + metrics.counter_value(multi)
    v2 = tx2.vertex(vid)
    assert v2 is not None
    mid = metrics.counter_value(base) + metrics.counter_value(multi)
    # existence check is exactly one backend slice
    assert mid - before == 1
    _ = v2.value("name")
    prefetched = metrics.counter_value(base) + metrics.counter_value(multi)
    # first property access prefetches the whole property slice (ONE call,
    # reference: query.fast-property)...
    assert prefetched - mid == 1
    _ = v2.value("age")
    _ = v2.value("name")
    _ = list(v2.properties())
    after = metrics.counter_value(base) + metrics.counter_value(multi)
    # ...and every later property read answers from the tx slice cache
    assert after == prefetched
    tx2.commit()


def test_mutate_many_single_batch(metered_graph, metrics):
    """Commit flushes through ONE batched mutate_many (reference:
    StandardTitanGraph.commit → mutator.commitStorage, one batched RPC)."""
    g = metered_graph
    tx = g.new_transaction()
    for i in range(20):
        tx.add_vertex("person", name=f"p{i}")
    before = metrics.counter_value(f"t.{MERGED_STORE}.mutateMany.calls")
    tx.commit()
    after = metrics.counter_value(f"t.{MERGED_STORE}.mutateMany.calls")
    assert after - before == 1


# ---------------------------------------------------------------------------
# periodic background reporters (reference: the per-namespace scheduled
# reporter config, GraphDatabaseConfiguration.java:1010-1226)
# ---------------------------------------------------------------------------


def test_scheduled_console_reporter(tmp_path):
    import io as _io
    import time as _time

    from titan_tpu.utils.metrics import (MetricManager, ScheduledReporter,
                                         _console_emit)

    m = MetricManager()
    m.counter("ops").inc(5)
    buf = _io.StringIO()
    r = ScheduledReporter(m, 0.05, _console_emit(buf), "console")
    try:
        deadline = _time.time() + 5.0
        while r.reports < 2 and _time.time() < deadline:
            _time.sleep(0.02)
    finally:
        r.stop()
    assert r.reports >= 2 and r.errors == 0
    assert "ops: 5" in buf.getvalue()


def test_scheduled_csv_reporter_appends_rows(tmp_path):
    import csv as _csv
    import time as _time

    from titan_tpu.utils.metrics import (MetricManager, ScheduledReporter,
                                         _csv_emit)

    m = MetricManager()
    m.counter("reads").inc(3)
    m.timer("lat").update(2_000_000)
    d = str(tmp_path / "mdir")
    r = ScheduledReporter(m, 0.05, _csv_emit(d), "csv")
    try:
        deadline = _time.time() + 5.0
        while r.reports < 2 and _time.time() < deadline:
            _time.sleep(0.02)
    finally:
        r.stop()
    rows = list(_csv.reader(open(d + "/metrics.csv")))
    assert rows[0][0] == "timestamp"
    data = [row for row in rows[1:] if row]
    assert sum(1 for row in data if row[1] == "reads") >= 2
    lat = next(row for row in data if row[1] == "lat")
    assert float(lat[3]) == 2.0         # mean_ms


def test_graphite_reporter_speaks_plaintext_protocol():
    import socket
    import threading as _threading
    import time as _time

    from titan_tpu.utils.metrics import (MetricManager, ScheduledReporter,
                                         _graphite_emit)

    got: list[bytes] = []
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]
    stop = _threading.Event()

    def accept_loop():
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                c, _ = srv.accept()
            except socket.timeout:
                continue
            with c:
                while chunk := c.recv(65536):
                    got.append(chunk)

    t = _threading.Thread(target=accept_loop, daemon=True)
    t.start()
    m = MetricManager()
    m.counter("hits").inc(7)
    r = ScheduledReporter(m, 0.05,
                          _graphite_emit("127.0.0.1", port, "tt"),
                          "graphite")
    try:
        deadline = _time.time() + 5.0
        while r.reports < 1 and _time.time() < deadline:
            _time.sleep(0.02)
    finally:
        r.stop()
        stop.set()
        t.join()
        srv.close()
    text = b"".join(got).decode()
    line = next(ln for ln in text.splitlines() if ln)
    name, value, ts = line.split()
    assert name == "tt.hits" and value == "7" and ts.isdigit()


def test_graph_wires_reporters_from_config(tmp_path):
    import time as _time

    import titan_tpu

    d = str(tmp_path / "csvdir")
    g = titan_tpu.open({"storage.backend": "inmemory",
                        "metrics.enabled": True,
                        "metrics.csv.interval-s": 0.05,
                        "metrics.csv.directory": d})
    try:
        tx = g.new_transaction()
        tx.add_vertex()
        tx.commit()
        deadline = _time.time() + 5.0
        while not g._reporters[0].reports and _time.time() < deadline:
            _time.sleep(0.02)
        assert len(g._reporters) == 1
        assert g._reporters[0].reports >= 1
    finally:
        g.close()
    import os as _os
    assert _os.path.exists(d + "/metrics.csv")
    # close() stopped the thread
    assert not g._reporters[0]._thread.is_alive()


def test_start_reporters_dedups_per_manager_and_sink():
    """Two graphs with the same reporter config must SHARE one reporter
    thread (no duplicate console/CSV/Graphite streams — ADVICE r5 #5),
    and the shared reporter is refcounted: closing one graph must not
    silence the other."""
    from titan_tpu.config import defaults as d
    from titan_tpu.utils.metrics import MetricManager, start_reporters

    class _Cfg:
        def get(self, opt, *a):
            if opt is d.METRICS_CONSOLE_INTERVAL:
                return 300.0      # never fires during the test
            if opt is d.METRICS_PREFIX:
                return "tt"
            return 0

    m = MetricManager()
    cfg = _Cfg()
    r1 = start_reporters(cfg, m)
    r2 = start_reporters(cfg, m)
    m2 = MetricManager()
    r3 = start_reporters(cfg, m2)
    try:
        assert len(r1) == len(r2) == 1
        assert r1[0] is r2[0], "same (manager, sink) must share"
        assert r3[0] is not r1[0], "a different manager gets its own"
        r1[0].stop()
        assert not r1[0].stopped, "first close must not kill the shared one"
        r2[0].stop()
        assert r2[0].stopped, "last close ends the thread"
        # final stop evicts the registry entry (no dead-reporter pinning)
        from titan_tpu.utils.metrics import _ACTIVE_REPORTERS
        assert r1[0] not in _ACTIVE_REPORTERS.values()
        # a fresh start after full shutdown spawns a NEW reporter
        r4 = start_reporters(cfg, m)
        assert r4[0] is not r1[0] and not r4[0].stopped
        r4[0].stop()
        assert r4[0] not in _ACTIVE_REPORTERS.values()
    finally:
        r3[0].stop()
