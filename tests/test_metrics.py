"""Metrics subsystem + operation-counting regression guard.

Modeled on the reference's util/stats/MetricManager +
MetricInstrumentedStore tests and — most importantly —
TitanOperationCountingTest (titan-test), which asserts EXACT backend call
counts per graph operation so backend-chattiness regressions fail loudly.
"""

import pytest

import titan_tpu
from titan_tpu.storage.api import Entry, KeySliceQuery, SliceQuery
from titan_tpu.storage.inmemory import InMemoryStoreManager
from titan_tpu.utils.metrics import (MERGED_STORE, MetricInstrumentedStoreManager,
                                     MetricManager)


@pytest.fixture
def metrics():
    m = MetricManager.instance()
    m.reset()
    yield m
    m.reset()


def test_counter_and_timer_basics(metrics):
    metrics.counter("a.b").inc()
    metrics.counter("a.b").inc(4)
    assert metrics.counter_value("a.b") == 5
    assert metrics.counter_value("missing") == 0
    t = metrics.timer("a.t")
    t.update(1_000_000)
    t.update(3_000_000)
    assert t.count == 2
    assert t.min_ns == 1_000_000 and t.max_ns == 3_000_000
    assert t.mean_ns == 2_000_000
    snap = metrics.snapshot()
    assert snap["a.b"] == {"type": "counter", "count": 5}
    assert snap["a.t"]["type"] == "timer"
    assert snap["a.t"]["count"] == 2
    text = metrics.report_console()
    assert "a.b: 5" in text


def test_snapshot_schema_unified_across_kinds(metrics):
    """ISSUE r10 satellite: every metric kind reports through ONE
    snapshot shape — a dict with type + count + the kind's stats (the
    old schema was a bare int for counters, which made every consumer
    type-sniff and silently dropped timer stats from uniform paths)."""
    metrics.counter("c").inc(3)
    metrics.timer("t").update(2_000_000)
    metrics.histogram("h").update(1.5)
    snap = metrics.snapshot()
    assert {v["type"] for v in snap.values()} == {"counter", "timer",
                                                  "histogram"}
    for v in snap.values():
        assert "count" in v
    assert snap["t"]["mean_ms"] == 2.0 and snap["t"]["max_ms"] == 2.0
    assert snap["h"]["p50"] == 1.5 and snap["h"]["samples"] == 1
    assert snap["h"]["total"] == 1.5


def test_csv_report(metrics, tmp_path):
    """One STABLE header across all three metric kinds (ISSUE r10: the
    old writer reused timer column names for histogram raw stats)."""
    metrics.counter("x").inc(2)
    metrics.timer("y").update(5_000_000)
    metrics.histogram("z").update(4.0)
    path = tmp_path / "metrics.csv"
    metrics.report_csv(str(path))
    import csv as _csv
    rows = list(_csv.reader(open(path)))
    assert rows[0] == list(MetricManager.CSV_HEADER)
    by_name = {r[0]: r for r in rows[1:]}
    assert by_name["x"][1] == "counter" and by_name["x"][2] == "2"
    assert by_name["y"][1] == "timer" and by_name["y"][2] == "1"
    assert float(by_name["y"][3]) == 5.0            # mean (ms)
    assert by_name["z"][1] == "histogram"
    assert float(by_name["z"][6]) == 4.0            # p50
    # every row has exactly the header's width — no ragged columns
    assert all(len(r) == len(rows[0]) for r in rows[1:])


def test_histogram_reservoir_deterministic_under_seed():
    """ISSUE r10 satellite: reservoir sampling must be reproducible —
    same seed + same update sequence = identical percentiles even past
    the reservoir capacity (no process-global RNG), and ``to_dict``
    reports how many samples back the estimate."""
    from titan_tpu.utils.metrics import Histogram

    def fill(h):
        for i in range(300):
            h.update(float(i % 97))
        return h

    a = fill(Histogram(max_samples=64, seed=7))
    b = fill(Histogram(max_samples=64, seed=7))
    assert a.to_dict() == b.to_dict()
    assert a.to_dict()["samples"] == 64
    assert a.count == 300 and a.to_dict()["count"] == 300
    # the default seed is itself fixed: two default instances agree
    c, d = fill(Histogram(max_samples=64)), fill(Histogram(max_samples=64))
    assert c.to_dict() == d.to_dict()
    # a different seed keeps a different (still uniform) reservoir
    e = fill(Histogram(max_samples=64, seed=8))
    assert e._samples != a._samples


def test_instrumented_store_counts_ops(metrics):
    mgr = MetricInstrumentedStoreManager(InMemoryStoreManager(), "p",
                                         metrics=metrics)
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    store.mutate(b"k", [Entry(b"c", b"v"), Entry(b"d", b"w")], [], txh)
    res = store.get_slice(KeySliceQuery(b"k", SliceQuery()), txh)
    assert len(res) == 2
    store.get_slice(KeySliceQuery(b"nope", SliceQuery()), txh)
    base = f"p.{MERGED_STORE}"
    assert metrics.counter_value(f"{base}.mutate.calls") == 1
    assert metrics.counter_value(f"{base}.getSlice.calls") == 2
    assert metrics.counter_value(f"{base}.getSlice.entries-returned") == 2
    assert metrics.timer_count(f"{base}.getSlice.time") == 2
    assert metrics.counter_value(f"{base}.getSlice.exceptions") == 0


def test_instrumented_store_counts_exceptions(metrics):
    mgr = MetricInstrumentedStoreManager(InMemoryStoreManager(), "p",
                                         metrics=metrics)
    store = mgr.open_database("s")
    with pytest.raises(NotImplementedError):
        store.acquire_lock(b"k", b"c", None, mgr.begin_transaction())
    assert metrics.counter_value(f"p.{MERGED_STORE}.acquireLock.exceptions") == 1


@pytest.fixture
def metered_graph(metrics):
    g = titan_tpu.open({"storage.backend": "inmemory",
                        "metrics.enabled": True,
                        "metrics.prefix": "t"})
    yield g
    g.close()


def test_tx_lifecycle_counters(metered_graph, metrics):
    g = metered_graph
    base_begin = metrics.counter_value("t.tx.begin")
    tx = g.new_transaction()
    tx.add_vertex("person", name="a")
    tx.commit()
    tx2 = g.new_transaction()
    tx2.rollback()
    assert metrics.counter_value("t.tx.begin") == base_begin + 2
    assert metrics.counter_value("t.tx.commit") == 1
    assert metrics.counter_value("t.tx.rollback") == 1


def test_operation_counting_regression(metered_graph, metrics):
    """The TitanOperationCountingTest contract: a warm single-vertex read by
    id costs exactly ONE edgestore getSlice; a vertex-property read on the
    same loaded vertex costs zero additional backend calls."""
    g = metered_graph
    tx = g.new_transaction()
    v = tx.add_vertex("person", name="a", age=1)
    vid = v.id
    tx.commit()

    base = f"t.{MERGED_STORE}.getSlice.calls"
    multi = f"t.{MERGED_STORE}.getSliceMulti.calls"

    tx2 = g.new_transaction()
    before = metrics.counter_value(base) + metrics.counter_value(multi)
    v2 = tx2.vertex(vid)
    assert v2 is not None
    mid = metrics.counter_value(base) + metrics.counter_value(multi)
    # existence check is exactly one backend slice
    assert mid - before == 1
    _ = v2.value("name")
    prefetched = metrics.counter_value(base) + metrics.counter_value(multi)
    # first property access prefetches the whole property slice (ONE call,
    # reference: query.fast-property)...
    assert prefetched - mid == 1
    _ = v2.value("age")
    _ = v2.value("name")
    _ = list(v2.properties())
    after = metrics.counter_value(base) + metrics.counter_value(multi)
    # ...and every later property read answers from the tx slice cache
    assert after == prefetched
    tx2.commit()


def test_mutate_many_single_batch(metered_graph, metrics):
    """Commit flushes through ONE batched mutate_many (reference:
    StandardTitanGraph.commit → mutator.commitStorage, one batched RPC)."""
    g = metered_graph
    tx = g.new_transaction()
    for i in range(20):
        tx.add_vertex("person", name=f"p{i}")
    before = metrics.counter_value(f"t.{MERGED_STORE}.mutateMany.calls")
    tx.commit()
    after = metrics.counter_value(f"t.{MERGED_STORE}.mutateMany.calls")
    assert after - before == 1


# ---------------------------------------------------------------------------
# periodic background reporters (reference: the per-namespace scheduled
# reporter config, GraphDatabaseConfiguration.java:1010-1226)
# ---------------------------------------------------------------------------


def test_scheduled_console_reporter(tmp_path):
    import io as _io
    import time as _time

    from titan_tpu.utils.metrics import (MetricManager, ScheduledReporter,
                                         _console_emit)

    m = MetricManager()
    m.counter("ops").inc(5)
    buf = _io.StringIO()
    r = ScheduledReporter(m, 0.05, _console_emit(buf), "console")
    try:
        deadline = _time.time() + 5.0
        while r.reports < 2 and _time.time() < deadline:
            _time.sleep(0.02)
    finally:
        r.stop()
    assert r.reports >= 2 and r.errors == 0
    assert "ops: 5" in buf.getvalue()


def test_scheduled_csv_reporter_appends_rows(tmp_path):
    import csv as _csv
    import time as _time

    from titan_tpu.utils.metrics import (MetricManager, ScheduledReporter,
                                         _csv_emit)

    m = MetricManager()
    m.counter("reads").inc(3)
    m.timer("lat").update(2_000_000)
    d = str(tmp_path / "mdir")
    r = ScheduledReporter(m, 0.05, _csv_emit(d), "csv")
    try:
        deadline = _time.time() + 5.0
        while r.reports < 2 and _time.time() < deadline:
            _time.sleep(0.02)
    finally:
        r.stop()
    rows = list(_csv.reader(open(d + "/metrics.csv")))
    assert rows[0][0] == "timestamp"
    data = [row for row in rows[1:] if row]
    assert sum(1 for row in data if row[1] == "reads") >= 2
    lat = next(row for row in data if row[1] == "lat")
    assert float(lat[3]) == 2.0         # mean_ms


def test_graphite_reporter_speaks_plaintext_protocol():
    import socket
    import threading as _threading
    import time as _time

    from titan_tpu.utils.metrics import (MetricManager, ScheduledReporter,
                                         _graphite_emit)

    got: list[bytes] = []
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]
    stop = _threading.Event()

    def accept_loop():
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                c, _ = srv.accept()
            except socket.timeout:
                continue
            with c:
                while chunk := c.recv(65536):
                    got.append(chunk)

    t = _threading.Thread(target=accept_loop, daemon=True)
    t.start()
    m = MetricManager()
    m.counter("hits").inc(7)
    r = ScheduledReporter(m, 0.05,
                          _graphite_emit("127.0.0.1", port, "tt"),
                          "graphite")
    try:
        deadline = _time.time() + 5.0
        while r.reports < 1 and _time.time() < deadline:
            _time.sleep(0.02)
    finally:
        r.stop()
        stop.set()
        t.join()
        srv.close()
    text = b"".join(got).decode()
    line = next(ln for ln in text.splitlines() if ln)
    name, value, ts = line.split()
    assert name == "tt.hits" and value == "7" and ts.isdigit()


def test_graph_wires_reporters_from_config(tmp_path):
    import time as _time

    import titan_tpu

    d = str(tmp_path / "csvdir")
    g = titan_tpu.open({"storage.backend": "inmemory",
                        "metrics.enabled": True,
                        "metrics.csv.interval-s": 0.05,
                        "metrics.csv.directory": d})
    try:
        tx = g.new_transaction()
        tx.add_vertex()
        tx.commit()
        deadline = _time.time() + 5.0
        while not g._reporters[0].reports and _time.time() < deadline:
            _time.sleep(0.02)
        assert len(g._reporters) == 1
        assert g._reporters[0].reports >= 1
    finally:
        g.close()
    import os as _os
    assert _os.path.exists(d + "/metrics.csv")
    # close() stopped the thread
    assert not g._reporters[0]._thread.is_alive()


def test_reporter_stop_during_inflight_report_no_deadlock_or_double():
    """ISSUE r10 satellite: stop() racing an in-flight report_now must
    neither deadlock (stop joins the thread while emit is blocked) nor
    double-report (the in-flight emit completes and counts ONCE; any
    report_now after stop is a no-op)."""
    import threading as _threading
    import time as _time

    from titan_tpu.utils.metrics import MetricManager, ScheduledReporter

    entered = _threading.Event()
    release = _threading.Event()

    def emit(manager, ts):
        entered.set()
        assert release.wait(10), "test gate never released"

    m = MetricManager()
    r = ScheduledReporter(m, 0.01, emit, "race")
    assert entered.wait(5), "reporter never fired"

    stopper = _threading.Thread(target=r.stop)
    stopper.start()
    _time.sleep(0.05)            # stop() is now joining the blocked emit
    assert stopper.is_alive()    # ...not deadlocked, just waiting
    release.set()
    stopper.join(10)
    assert not stopper.is_alive(), "stop() deadlocked on in-flight emit"
    assert r.stopped and not r._thread.is_alive()
    assert r.reports == 1, "in-flight report must count exactly once"
    # post-stop flush attempts are no-ops, not duplicate reports
    r.report_now()
    assert r.reports == 1 and r.errors == 0


def test_start_reporters_dedups_per_manager_and_sink():
    """Two graphs with the same reporter config must SHARE one reporter
    thread (no duplicate console/CSV/Graphite streams — ADVICE r5 #5),
    and the shared reporter is refcounted: closing one graph must not
    silence the other."""
    from titan_tpu.config import defaults as d
    from titan_tpu.utils.metrics import MetricManager, start_reporters

    class _Cfg:
        def get(self, opt, *a):
            if opt is d.METRICS_CONSOLE_INTERVAL:
                return 300.0      # never fires during the test
            if opt is d.METRICS_PREFIX:
                return "tt"
            return 0

    m = MetricManager()
    cfg = _Cfg()
    r1 = start_reporters(cfg, m)
    r2 = start_reporters(cfg, m)
    m2 = MetricManager()
    r3 = start_reporters(cfg, m2)
    try:
        assert len(r1) == len(r2) == 1
        assert r1[0] is r2[0], "same (manager, sink) must share"
        assert r3[0] is not r1[0], "a different manager gets its own"
        r1[0].stop()
        assert not r1[0].stopped, "first close must not kill the shared one"
        r2[0].stop()
        assert r2[0].stopped, "last close ends the thread"
        # final stop evicts the registry entry (no dead-reporter pinning)
        from titan_tpu.utils.metrics import _ACTIVE_REPORTERS
        assert r1[0] not in _ACTIVE_REPORTERS.values()
        # a fresh start after full shutdown spawns a NEW reporter
        r4 = start_reporters(cfg, m)
        assert r4[0] is not r1[0] and not r4[0].stopped
        r4[0].stop()
        assert r4[0] not in _ACTIVE_REPORTERS.values()
    finally:
        r3[0].stop()


# -- dimensional children + gauges (ISSUE 8) ------------------------------


def test_labeled_children_roll_up_into_parent():
    """Every update through a labeled child lands on the unlabeled
    parent too — the roll-up contract all pre-label consumers rely on."""
    m = MetricManager()
    m.counter("serving.jobs.completed",
              labels={"tenant": "a", "kind": "bfs"}).inc(3)
    m.counter("serving.jobs.completed",
              labels={"tenant": "b", "kind": "bfs"}).inc(2)
    m.counter("serving.jobs.completed").inc()      # direct parent move
    assert m.counter_value("serving.jobs.completed") == 6
    # children() filters by label subset; counter_value(labels=) sums
    assert m.counter_value("serving.jobs.completed",
                           labels={"tenant": "a"}) == 3
    assert m.counter_value("serving.jobs.completed",
                           labels={"kind": "bfs"}) == 5
    t = m.timer("op.time", labels={"tenant": "a"})
    t.update(2_000_000)
    assert m.timer_count("op.time") == 1
    assert t.count == 1
    h = m.histogram("serving.job.latency_ms", labels={"tenant": "a"})
    for v in (1.0, 5.0, 9.0):
        h.update(v)
    parent = m.histogram("serving.job.latency_ms")
    assert parent.count == 3 and h.count == 3
    assert sorted(parent.values()) == [1.0, 5.0, 9.0]
    assert sorted(h.values()) == [1.0, 5.0, 9.0]


def test_label_set_canonical_regardless_of_order():
    m = MetricManager()
    a = m.counter("c.x.y", labels={"k1": "v", "k2": "w"})
    b = m.counter("c.x.y", labels={"k2": "w", "k1": "v"})
    a.inc()
    b.inc()
    assert a.child is b.child        # one child, not two
    assert m.counter_value("c.x.y") == 2
    assert len(m.children("c.x.y")) == 1


def test_labeled_sum_exact_under_concurrent_multitenant_updates():
    """The per-tenant children of a name sum EXACTLY to the unlabeled
    aggregate under concurrent updates from many threads (the ISSUE 8
    property the whole attribution plane hangs off)."""
    import threading

    m = MetricManager()
    tenants = ["a", "b", "c", "d"]
    per_thread = 200

    def worker(seed):
        for i in range(per_thread):
            m.counter("serving.jobs.submitted",
                      labels={"tenant": tenants[(seed + i) % 4]}).inc()

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = 8 * per_thread
    assert m.counter_value("serving.jobs.submitted") == total
    by_child = sum(c.count for _l, c in
                   m.children("serving.jobs.submitted"))
    assert by_child == total
    assert len(m.children("serving.jobs.submitted")) == 4


def test_max_children_cardinality_guard_degrades_to_parent():
    """Past MAX_CHILDREN a NEW label set degrades to the unlabeled
    parent (abusive wire-supplied tenant ids must not grow the registry
    without bound); existing children keep working."""
    m = MetricManager()
    m.MAX_CHILDREN = 2
    a = m.counter("c.a.b", labels={"t": "1"})
    b = m.counter("c.a.b", labels={"t": "2"})
    over = m.counter("c.a.b", labels={"t": "3"})
    parent = m.counter("c.a.b")
    assert over is parent            # degraded, not a third child
    a.inc()
    b.inc()
    over.inc()
    assert m.counter_value("c.a.b") == 3
    assert len(m.children("c.a.b")) == 2
    # the existing children still write through
    assert m.counter("c.a.b", labels={"t": "1"}) is a
    # the degrade is NEVER silent: every degraded lookup counts (the
    # family's children no longer sum to the parent and per-label
    # readers are blind to the dropped set — alertable signal)
    assert m.counter_value(MetricManager.LABELS_DROPPED) == 1
    m.counter("c.a.b", labels={"t": "4"}).inc()
    assert m.counter_value(MetricManager.LABELS_DROPPED) == 2
    assert "metrics.labels.dropped" in m.snapshot()
    # ...but a run that never overflows carries no trace of it
    assert MetricManager.LABELS_DROPPED not in MetricManager().snapshot()


def test_gauge_callback_set_value_and_parent_sum():
    m = MetricManager()
    # callback-backed: read at scrape time
    state = {"v": 7}
    m.gauge("pool.size.current", fn=lambda: state["v"])
    assert m.gauge_value("pool.size.current") == 7.0
    state["v"] = 9
    assert m.gauge_value("pool.size.current") == 9.0
    # set()-backed without callback
    g = m.gauge("plain.gauge.value")
    g.set(3.5)
    assert m.gauge_value("plain.gauge.value") == 3.5
    # a broken callback reads 0.0, never raises into the scrape
    m.gauge("dead.gauge.value", fn=lambda: 1 / 0)
    assert m.gauge_value("dead.gauge.value") == 0.0
    # a parent with no callback of its own sums its labeled children
    m.gauge("slo.burn.rate", fn=lambda: 1.25,
            labels={"slo": "x", "window": "300s"})
    m.gauge("slo.burn.rate", fn=lambda: 0.25,
            labels={"slo": "x", "window": "3600s"})
    assert m.gauge_value("slo.burn.rate") == 1.5
    assert m.gauge_value("slo.burn.rate",
                         labels={"slo": "x", "window": "300s"}) == 1.25
    snap = m.gauge_snapshot()
    assert snap["slo.burn.rate"]["value"] == 1.5
    assert len(snap["slo.burn.rate"]["children"]) == 2
    # latest registration re-binds the callback (owner turnover)
    m.gauge("pool.size.current", fn=lambda: 42)
    assert m.gauge_value("pool.size.current") == 42.0


def test_snapshot_csv_and_counter_value_unchanged_by_labels(tmp_path):
    """Regression (ISSUE 8 acceptance): labels are invisible to every
    pre-label consumer — ``snapshot()`` schema, the CSV header/rows and
    plain ``counter_value`` are byte-identical whether the updates came
    through labeled children or straight parents."""
    via_labels = MetricManager()
    via_labels.counter("serving.jobs.completed",
                       labels={"tenant": "a"}).inc(2)
    via_labels.counter("serving.jobs.completed",
                       labels={"tenant": "b"}).inc(1)
    via_labels.histogram("serving.job.latency_ms",
                         labels={"tenant": "a"}).update(5.0)
    via_labels.timer("op.x.time", labels={"tenant": "a"}).update(10**6)
    via_labels.gauge("hbm.resident.bytes", fn=lambda: 1)  # not in snapshot
    plain = MetricManager()
    plain.counter("serving.jobs.completed").inc(3)
    plain.histogram("serving.job.latency_ms").update(5.0)
    plain.timer("op.x.time").update(10**6)
    assert via_labels.snapshot() == plain.snapshot()
    pa, pb = tmp_path / "a.csv", tmp_path / "b.csv"
    via_labels.report_csv(str(pa))
    plain.report_csv(str(pb))
    assert pa.read_text() == pb.read_text()
    assert MetricManager.CSV_HEADER == ("metric", "type", "count",
                                        "mean", "min", "max",
                                        "p50", "p95")
