"""Graph-level feature suite, backend-parameterized.

Modeled on the reference's TitanGraphTest / TitanGraphBaseTest (titan-test):
open/clopen (close+reopen to flush caches), schema, CRUD, constraint
enforcement, tx isolation.
"""

import pytest

import titan_tpu
from titan_tpu import example
from titan_tpu.core.defs import Cardinality, Direction, Multiplicity
from titan_tpu.errors import SchemaViolationError


@pytest.fixture(params=["inmemory", "sqlite"])
def fresh_graph(request, tmp_path):
    if request.param == "inmemory":
        g = titan_tpu.open("inmemory")
    else:
        g = titan_tpu.open({"storage.backend": "sqlite",
                            "storage.directory": str(tmp_path / "db")})
    yield g
    g.close()


def test_add_and_read_vertex(fresh_graph):
    g = fresh_graph
    tx = g.new_transaction()
    v = tx.add_vertex("person", name="alice", age=30)
    vid = v.id
    assert v.value("name") == "alice"  # read-your-writes
    tx.commit()
    tx2 = g.new_transaction()
    v2 = tx2.vertex(vid)
    assert v2 is not None
    assert v2.value("name") == "alice" and v2.value("age") == 30
    assert v2.label() == "person"
    assert tx2.vertex(vid + 1234) is None
    tx2.commit()


def test_edges_directions_and_labels(fresh_graph):
    g = fresh_graph
    tx = g.new_transaction()
    a = tx.add_vertex(name="a")
    b = tx.add_vertex(name="b")
    c = tx.add_vertex(name="c")
    a.add_edge("knows", b, weight=0.5)
    a.add_edge("knows", c)
    b.add_edge("likes", c)
    tx.commit()
    tx = g.new_transaction()
    a2 = tx.vertex(a.id)
    assert sorted(v.value("name") for v in a2.out("knows")) == ["b", "c"]
    assert [v.value("name") for v in tx.vertex(c.id).in_("knows")] == ["a"]
    assert {v.value("name") for v in tx.vertex(c.id).both()} == {"a", "b"}
    e = next(iter(a2.out_edges("knows")))
    assert e.label() == "knows"
    tx.commit()


def test_single_cardinality_overwrites(fresh_graph):
    g = fresh_graph
    tx = g.new_transaction()
    v = tx.add_vertex(name="x")
    tx.commit()
    tx = g.new_transaction()
    v = tx.vertex(v.id)
    v.property("name", "y")
    assert v.value("name") == "y"
    tx.commit()
    tx = g.new_transaction()
    vals = [p.value for p in tx.vertex(v.id).properties("name")]
    assert vals == ["y"]
    tx.commit()


def test_set_and_list_cardinality(fresh_graph):
    g = fresh_graph
    mgmt = g.management()
    mgmt.make_property_key("nick", str, Cardinality.SET)
    mgmt.make_property_key("score", int, Cardinality.LIST)
    tx = g.new_transaction()
    v = tx.add_vertex()
    v.property("nick", "bob")
    v.property("nick", "bobby")
    v.property("nick", "bob")       # set: duplicate ignored
    v.property("score", 7)
    v.property("score", 7)          # list: duplicate kept
    tx.commit()
    tx = g.new_transaction()
    v = tx.vertex(v.id)
    assert sorted(v.values("nick")) == ["bob", "bobby"]
    assert v.values("score") == [7, 7]
    tx.commit()


def test_multiplicity_many2one_enforced(fresh_graph):
    g = fresh_graph
    g.management().make_edge_label("father", Multiplicity.MANY2ONE)
    tx = g.new_transaction()
    child = tx.add_vertex(name="child")
    f1 = tx.add_vertex(name="f1")
    f2 = tx.add_vertex(name="f2")
    child.add_edge("father", f1)
    with pytest.raises(SchemaViolationError):
        child.add_edge("father", f2)
    tx.commit()
    # cross-tx enforcement (reads stored edges)
    tx = g.new_transaction()
    with pytest.raises(SchemaViolationError):
        tx.vertex(child.id).add_edge("father", tx.vertex(f2.id))
    tx.rollback()


def test_multiplicity_simple_rejects_parallel(fresh_graph):
    g = fresh_graph
    g.management().make_edge_label("married", Multiplicity.SIMPLE)
    tx = g.new_transaction()
    a = tx.add_vertex()
    b = tx.add_vertex()
    a.add_edge("married", b)
    with pytest.raises(SchemaViolationError):
        a.add_edge("married", b)
    tx.commit()


def test_remove_edge_and_vertex(fresh_graph):
    g = fresh_graph
    tx = g.new_transaction()
    a = tx.add_vertex(name="a")
    b = tx.add_vertex(name="b")
    e = a.add_edge("knows", b)
    tx.commit()
    tx = g.new_transaction()
    a2 = tx.vertex(a.id)
    edges = list(a2.out_edges("knows"))
    assert len(edges) == 1
    edges[0].remove()
    assert list(a2.out_edges("knows")) == []  # delta visible pre-commit
    tx.commit()
    tx = g.new_transaction()
    assert list(tx.vertex(a.id).out_edges("knows")) == []
    # remove vertex b entirely
    tx.vertex(b.id).remove()
    tx.commit()
    tx = g.new_transaction()
    assert tx.vertex(b.id) is None
    assert tx.vertex(a.id) is not None
    tx.commit()


def test_tx_isolation_and_rollback(fresh_graph):
    g = fresh_graph
    tx1 = g.new_transaction()
    v = tx1.add_vertex(name="iso")
    vid = v.id
    tx2 = g.new_transaction()
    assert tx2.vertex(vid) is None      # uncommitted invisible
    tx1.rollback()
    tx3 = g.new_transaction()
    assert tx3.vertex(vid) is None      # rolled back, never persisted
    tx2.rollback()
    tx3.rollback()


def test_vertex_iteration(fresh_graph):
    g = fresh_graph
    tx = g.new_transaction()
    for i in range(20):
        tx.add_vertex(idx=i)
    tx.commit()
    tx = g.new_transaction()
    assert sum(1 for _ in tx.vertices()) == 20
    tx.commit()


def test_schema_persists_across_reopen(tmp_path):
    path = str(tmp_path / "db")
    g = titan_tpu.open({"storage.backend": "sqlite", "storage.directory": path})
    g.management().make_property_key("age", int)
    g.management().make_edge_label("father", Multiplicity.MANY2ONE)
    tx = g.new_transaction()
    v = tx.add_vertex(age=5)
    vid = v.id
    tx.commit()
    g.close()

    g2 = titan_tpu.open({"storage.backend": "sqlite", "storage.directory": path})
    pk = g2.management().get_property_key("age")
    assert pk is not None and pk.dtype is int
    el = g2.management().get_edge_label("father")
    assert el is not None and el.multiplicity is Multiplicity.MANY2ONE
    tx = g2.new_transaction()
    assert tx.vertex(vid).value("age") == 5
    tx.commit()
    g2.close()


class TestGraphOfTheGods:
    @pytest.fixture
    def gods(self, fresh_graph):
        return example.load(fresh_graph)

    def test_load_counts(self, gods):
        tx = gods.new_transaction()
        vs = list(tx.vertices())
        assert len(vs) == 12
        n_edges = sum(1 for v in vs for _ in v.out_edges())
        assert n_edges == 17
        tx.commit()

    def test_traversals(self, gods):
        g = gods.traversal()
        assert g.V().count().next() == 12
        assert g.V().has("name", "hercules").out("father").values("name") \
            .to_list() == ["jupiter"]
        # grandfather
        assert g.V().has("name", "hercules").out("father").out("father") \
            .values("name").to_list() == ["saturn"]
        battled = g.V().has("name", "hercules").out_e("battled") \
            .has("time", __import__("titan_tpu.query", fromlist=["P"]).P.gt(1)) \
            .in_v().values("name").to_list()
        assert sorted(battled) == ["cerberus", "hydra"]
        gods.rollback()

    def test_two_hop_count(self, gods):
        g = gods.traversal()
        # BASELINE config #1: g.V().out().out().count()
        assert g.V().out().out().count().next() == 28
        gods.rollback()

    def test_vertex_centric_interval(self, gods):
        tx = gods.new_transaction()
        herc = next(v for v in tx.vertices() if v.value("name") == "hercules")
        q = herc.query().labels("battled").direction(Direction.OUT) \
            .interval("time", 2, 13)
        assert sorted(e.value("time") for e in q.edges()) == [2, 12]
        assert q.count() == 2
        tx.commit()

    def test_label_groups(self, gods):
        g = gods.traversal()
        counts = g.V().group_count("label").next()
        assert counts == {"titan": 1, "location": 3, "god": 3, "demigod": 1,
                          "human": 1, "monster": 3}
        gods.rollback()
