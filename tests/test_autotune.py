"""Closed-loop autotune controller (ISSUE 14, ROADMAP #4).

Three contracts pinned here:

* **pinned decision sequences** — synthetic signal trajectories driven
  through an injectable clock produce exactly the decisions the rules
  promise: multiplicative bounded steps, cooldown hysteresis, burn
  gating, shed-victim selection, Young's cadence;
* **the explainable guarantee** — every journaled decision is
  reconstructible from its own entry alone: ``autotune.replay(entry)``
  re-runs the SAME pure rule functions over the journaled signal
  snapshot and must reproduce the decision;
* **shadow is provably inert** — a scheduler with autotune shadowed
  produces byte-identical job results and identical
  pre-``controller.*`` metric snapshots to one with autotune off,
  while enforce mode moves exactly the knobs it journals (batch K,
  tenant quota scale, compaction trigger, checkpoint cadence).
"""

import json
import os
import time

import numpy as np
import pytest

from titan_tpu.olap.api import JobSpec
from titan_tpu.olap.serving import autotune
from titan_tpu.olap.serving.autotune import Controller, replay
from titan_tpu.olap.serving.scheduler import JobScheduler
from titan_tpu.olap.serving.tenants import QuotaExceeded, TenantQuota
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.utils.metrics import MetricManager


class Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _sig(occ=None, batches=1, burn=0.0, burn_slo=None, protected=(),
         tenants=None, deltas=None, live=None, recovery=None,
         jobs_delta=0):
    """A synthetic signal snapshot in the collector's shape (minus the
    knob snapshot, which tick() stamps in itself)."""
    return {
        "t": 0.0,
        "occupancy": {"recent_mean": occ, "batches": batches},
        "queue_depth": 0,
        "burn": ({burn_slo or "slo": {"300s": burn}} if burn else {}),
        "burn_max": burn, "burn_max_slo": burn_slo,
        "protected_tenants": sorted(protected),
        "tenants": tenants or {},
        "tenant_device_s_delta": deltas or {},
        "jobs_delta": jobs_delta,
        "recovery": recovery or {},
        **({"live": live} if live is not None else {}),
    }


def _controller(clock, feed, **kw):
    kw.setdefault("metrics", MetricManager())
    return Controller(mode=kw.pop("mode", "shadow"), clock=clock,
                      signals=feed, **kw)


def _snap(n=192, m=900, seed=42):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


# -- mode resolution ----------------------------------------------------------

def test_mode_resolution():
    assert autotune.resolve_mode(None) == "shadow"
    assert autotune.resolve_mode("") == "shadow"
    assert autotune.resolve_mode("shadow") == "shadow"
    assert autotune.resolve_mode("OFF") == "off"
    assert autotune.resolve_mode("0") == "off"
    assert autotune.resolve_mode("false") == "off"
    assert autotune.resolve_mode("enforce") == "enforce"
    assert autotune.resolve_mode("1") == "enforce"
    with pytest.raises(ValueError):
        autotune.resolve_mode("sideways")


def test_off_mode_means_no_controller():
    snap = _snap()
    s = JobScheduler(snapshot=snap, metrics=MetricManager(),
                     autostart=False, profiling=False, autotune="off")
    try:
        assert s.controller is None
    finally:
        s.close()


def test_unknown_param_rejected():
    with pytest.raises(ValueError, match="unknown autotune params"):
        Controller(metrics=MetricManager(), signals=lambda: _sig(),
                   typo_knob=3)


# -- pinned decision sequences (simulation) -----------------------------------

def test_batch_k_trajectory_pinned():
    clk = Clock()
    feed = {"sig": _sig(occ=8.0)}
    ctl = _controller(clk, lambda: dict(feed["sig"]), k_init=8)

    # t0: occupancy at target, burn 0 → grow 8→16
    e = ctl.tick(force=True)
    assert [(x["rule"], x["old"], x["new"]) for x in e] == \
        [("batch_k.grow", 8, 16)]
    # inside the cooldown the SAME signal decides nothing
    clk.advance(0.5)
    feed["sig"] = _sig(occ=16.0)
    assert ctl.tick(force=True) == []
    # past the cooldown it doubles again, clamping at k_cap
    clk.advance(11.0)
    e = ctl.tick(force=True)
    assert [(x["rule"], x["old"], x["new"]) for x in e] == \
        [("batch_k.grow", 16, 32)]
    clk.advance(11.0)
    feed["sig"] = _sig(occ=32.0)
    assert ctl.tick(force=True) == []          # at the cap: bounded
    # occupancy collapse → halve back
    clk.advance(11.0)
    feed["sig"] = _sig(occ=2.0)
    e = ctl.tick(force=True)
    assert [(x["rule"], x["old"], x["new"]) for x in e] == \
        [("batch_k.shrink", 32, 16)]
    # high burn blocks growth even at full occupancy
    clk.advance(11.0)
    feed["sig"] = _sig(occ=16.0, burn=2.5, burn_slo="p95")
    assert ctl.tick(force=True) == []
    # an idle tick (no executed batch since last) decides nothing
    clk.advance(11.0)
    feed["sig"] = _sig(occ=None, batches=0)
    assert ctl.tick(force=True) == []
    assert ctl.target_k == 16


def test_tenant_shed_and_restore_pinned():
    clk = Clock()
    tenants = {"flood": {"in_flight": 5, "device_seconds": 1.0},
               "quiet": {"in_flight": 1, "device_seconds": 0.1}}
    spike = _sig(occ=None, batches=0, burn=3.0, burn_slo="quiet-p95",
                 protected=("quiet",), tenants=tenants,
                 deltas={"flood": 0.5, "quiet": 0.1})
    calm = _sig(occ=None, batches=0, burn=0.1, protected=("quiet",),
                tenants=tenants)
    feed = {"sig": spike}
    ctl = _controller(clk, lambda: dict(feed["sig"]))

    seq = []
    for _ in range(4):                 # shed halves to the floor, once
        seq += ctl.tick(force=True)    # per cooldown, then stops
        clk.advance(11.0)
    feed["sig"] = calm
    for _ in range(4):                 # restores double back to 1.0
        seq += ctl.tick(force=True)
        clk.advance(11.0)
    got = [(x["rule"], x["knob"], x["old"], x["new"]) for x in seq]
    assert got == [
        ("tenant.shed", "tenant.quota_scale.flood", 1.0, 0.5),
        ("tenant.shed", "tenant.quota_scale.flood", 0.5, 0.25),
        # floor reached: no further shed even under sustained burn
        ("tenant.restore", "tenant.quota_scale.flood", 0.25, 0.5),
        ("tenant.restore", "tenant.quota_scale.flood", 0.5, 1.0),
    ]
    assert ctl.scales == {}            # fully restored
    # the journal carries the triggering burn reading (smoke contract)
    sheds = [x for x in seq if x["rule"] == "tenant.shed"]
    assert all(x["signals"]["burn_max"] >= 2.0 for x in sheds)
    assert all("quiet-p95" in x["why"] for x in sheds)


def test_protected_tenant_is_never_shed():
    clk = Clock()
    sig = _sig(occ=None, batches=0, burn=5.0, burn_slo="quiet-p95",
               protected=("quiet",),
               tenants={"quiet": {"in_flight": 9,
                                  "device_seconds": 3.0}},
               deltas={"quiet": 3.0})
    ctl = _controller(clk, lambda: dict(sig))
    assert ctl.tick(force=True) == []  # the only consumer is protected


def test_compact_trigger_pinned():
    clk = Clock()
    live = {"overlay_rows": 1000, "tombs": 0, "fill": 0.1,
            "tomb_fraction": 0.0, "base_edges": 10_000,
            "merge_us_per_row": 0.05, "fallbacks": 0}
    feed = {"sig": _sig(occ=None, batches=0, live=live, jobs_delta=10)}
    ctl = _controller(clk, lambda: dict(feed["sig"]))
    e = ctl.tick(force=True)
    # defer = 1000 rows * 0.5us * 10 jobs = 5ms >= merge
    # 0.05us * 11000 rows = 0.55ms → compact
    assert [(x["rule"], x["old"], x["new"]) for x in e] == \
        [("live.compact", "deferred", "compact")]
    # idle plane (no job flow) defers forever
    clk.advance(11.0)
    feed["sig"] = _sig(occ=None, batches=0, live=live, jobs_delta=0)
    assert ctl.tick(force=True) == []
    # a tiny overlay never engages the rule
    clk.advance(11.0)
    feed["sig"] = _sig(occ=None, batches=0, jobs_delta=100,
                       live={**live, "overlay_rows": 8, "tombs": 0})
    assert ctl.tick(force=True) == []


def test_checkpoint_cadence_pinned():
    clk = Clock()
    rec = {"retries_delta": 1, "replayed_delta": 50,
           "checkpoint_ms_mean": 20.0, "round_ms_mean": 10.0,
           "retries": 1, "rounds_replayed": 50}
    ctl = _controller(clk, lambda: _sig(occ=None, batches=0,
                                        recovery=rec))
    e = ctl.tick(force=True)
    # Young: sqrt(2 * (20/10) * 50) = sqrt(200) ≈ 14 rounds
    assert [(x["rule"], x["old"], x["new"]) for x in e] == \
        [("recovery.cadence", 0, 14)]
    assert ctl.checkpoint_every == 14
    # no failure news → no cadence churn
    clk.advance(31.0)
    e = ctl.tick(force=True)
    assert e == []


# -- the explainable guarantee ------------------------------------------------

def test_replay_reconstructs_every_decision():
    """Every journal entry re-derives from its OWN signal snapshot:
    the rules are pure, the snapshot carries the knob state, and
    replay() must land on the same old→new."""
    clk = Clock()
    feeds = [
        _sig(occ=8.0),
        _sig(occ=16.0),
        _sig(occ=2.0),
        _sig(occ=None, batches=0, burn=3.0, burn_slo="q",
             protected=("q",),
             tenants={"flood": {"in_flight": 3, "device_seconds": 1.0}},
             deltas={"flood": 0.4}),
        _sig(occ=None, batches=0, burn=0.0, protected=("q",)),
        _sig(occ=None, batches=0, jobs_delta=10,
             live={"overlay_rows": 1000, "tombs": 50, "fill": 0.2,
                   "tomb_fraction": 0.01, "base_edges": 10_000,
                   "merge_us_per_row": None, "fallbacks": 0}),
        _sig(occ=None, batches=0,
             recovery={"retries_delta": 2, "replayed_delta": 36,
                       "checkpoint_ms_mean": 8.0, "round_ms_mean": 4.0}),
    ]
    it = iter(feeds)
    ctl = _controller(clk, lambda: dict(next(it)), k_init=8)
    entries = []
    for _ in feeds:
        entries += ctl.tick(force=True)
        clk.advance(31.0)              # past every cooldown
    assert len(entries) >= 5           # every rule family fired
    rules = {e["rule"] for e in entries}
    assert {"batch_k.grow", "batch_k.shrink", "tenant.shed",
            "tenant.restore", "live.compact",
            "recovery.cadence"} <= rules
    for e in entries:
        got = replay(e)
        assert got is not None, (e["rule"], e["knob"])
        assert got["new"] == e["new"], (e["rule"], got, e)
        assert got["old"] == e["old"], (e["rule"], got, e)
    # and a journal entry survives a JSON round trip intact (the wire /
    # postmortem form replays too)
    wire = json.loads(json.dumps(entries[0]))
    assert replay(wire)["new"] == wire["new"]


def test_journal_bounded_and_drop_counted():
    clk = Clock()
    m = MetricManager()
    flip = {"burn": 3.0}
    tenants = {"a": {"in_flight": 1, "device_seconds": 0.5},
               "b": {"in_flight": 1, "device_seconds": 0.4}}

    def feed():
        flip["burn"] = 3.0 if flip["burn"] < 1 else 0.0
        return _sig(occ=None, batches=0, burn=flip["burn"],
                    burn_slo="s", tenants=tenants,
                    deltas={"a": 0.5, "b": 0.4})

    ctl = _controller(clk, feed, metrics=m, journal_cap=4,
                      shed_cooldown_s=0.0)
    for _ in range(12):                # shed/restore ping-pong
        ctl.tick(force=True)
        clk.advance(1.0)
    j = ctl.journal()
    assert len(j) == 4                 # bounded
    assert m.counter_value("controller.journal.dropped") > 0
    assert ctl.state()["journal_dropped"] > 0
    # seq stays monotone across the drop window
    assert [e["seq"] for e in j] == sorted(e["seq"] for e in j)


# -- shadow mode: provably inert ----------------------------------------------

def _run_jobs(sched, snap, k=8):
    jobs = [sched.submit(JobSpec(kind="bfs",
                                 params={"source_dense": int(s)}))
            for s in range(k)]
    sched.start()
    for j in jobs:
        assert j.wait(120), j.state
    deadline = time.time() + 10
    while time.time() < deadline and sched._metrics.counter_value(
            "serving.jobs.completed") < k:
        time.sleep(0.01)
    return jobs


def _metric_shape(m):
    """{name: count} for every non-controller metric — the inertness
    comparison (values carry wall time and can never be identical
    across two real runs; counts and the name SET must be)."""
    return {name: v["count"] for name, v in m.snapshot().items()
            if not name.startswith("controller.")}


def test_shadow_mode_is_byte_identical_to_off():
    snap = _snap()
    m_off, m_sh = MetricManager(), MetricManager()
    s_off = JobScheduler(snapshot=snap, metrics=m_off, autostart=False,
                         profiling=False, max_batch=8, autotune="off")
    s_sh = JobScheduler(snapshot=snap, metrics=m_sh, autostart=False,
                        profiling=False, max_batch=8,
                        autotune="shadow", autotune_tick_s=3600.0)
    try:
        jobs_off = _run_jobs(s_off, snap)
        jobs_sh = _run_jobs(s_sh, snap)
        # a full-occupancy batch ran: the shadow controller DECIDES...
        entries = s_sh.controller.tick(force=True)
        assert [(e["rule"], e["old"], e["new"]) for e in entries] == \
            [("batch_k.grow", 8, 16)]
        assert entries[0]["mode"] == "shadow"
        assert entries[0]["applied"] is False
        # ...but nothing moves: the knob is untouched,
        assert s_sh.max_batch == 8 and s_sh.batcher.max_batch == 8
        # results are byte-identical,
        for jo, js in zip(jobs_off, jobs_sh):
            assert np.array_equal(jo.result["dist"], js.result["dist"])
            assert jo.result["levels"] == js.result["levels"]
        # and the pre-controller metric registries match exactly —
        # same name set, same counts (shadow observation created
        # NOTHING: every signal read is non-creating)
        assert _metric_shape(m_off) == _metric_shape(m_sh)
        # the controller family exists only on the shadow side
        assert not any(n.startswith("controller.")
                       for n in m_off.snapshot())
        assert m_sh.counter_value("controller.tick.count") >= 1
        # shadow never scales admission either
        s_sh.controller.scales["t"] = 0.25
        q = TenantQuota(max_in_flight=4)
        assert s_sh.controller.scaled_quota("t", q) is q
    finally:
        s_off.close()
        s_sh.close()


# -- enforce mode: the knobs actually move ------------------------------------

def test_scaled_quota_floors_at_one_in_flight():
    """A shed throttles, it never zeroes: int() truncation on a small
    max_in_flight must not turn 'halve the quota' into a total outage
    no restore could be observed through."""
    ctl = Controller(metrics=MetricManager(), mode="enforce",
                     signals=lambda: _sig())
    ctl.scales["t"] = 0.25
    q = ctl.scaled_quota("t", TenantQuota(max_in_flight=2,
                                          max_hbm_bytes=1000.0))
    assert q.max_in_flight == 1        # not int(0.5) == 0
    assert q.max_hbm_bytes == 250.0    # continuous limits scale freely
    assert ctl.scaled_quota("t", TenantQuota(
        max_in_flight=64)).max_in_flight == 16


class _FakeLive:
    """Just enough live-plane surface for the compact-apply seam."""

    def __init__(self):
        self.compacted = []

    def compact_now(self, why="controller"):
        self.compacted.append(why)
        return True

    def stats(self):
        return None

    def close(self):
        pass


def test_enforce_applies_batch_k_and_compact():
    snap = _snap()
    m = MetricManager()
    sched = JobScheduler(snapshot=snap, metrics=m, autostart=False,
                         profiling=False, max_batch=8,
                         autotune="enforce", autotune_tick_s=3600.0)
    fake = _FakeLive()
    sched.live = fake                  # the compact seam under test
    ctl = sched.controller
    clk = Clock()
    ctl.clock = clk
    feed = {"sig": _sig(occ=8.0)}
    ctl._signals_fn = lambda: dict(feed["sig"])
    try:
        e = ctl.tick(force=True)
        assert [(x["rule"], x["new"], x["applied"], x["mode"])
                for x in e] == [("batch_k.grow", 16, True, "enforced")]
        # the knob MOVED — scheduler and batcher both
        assert sched.max_batch == 16 and sched.batcher.max_batch == 16
        assert m.counter_value("controller.decisions.applied",
                               labels={"rule": "batch_k.grow"}) == 1
        # compaction trigger pokes the live plane
        clk.advance(11.0)
        feed["sig"] = _sig(occ=None, batches=0, jobs_delta=10,
                           live={"overlay_rows": 1000, "tombs": 0,
                                 "fill": 0.2, "tomb_fraction": 0.0,
                                 "base_edges": 10_000,
                                 "merge_us_per_row": 0.05,
                                 "fallbacks": 0})
        e = ctl.tick(force=True)
        assert [x["rule"] for x in e] == ["live.compact"]
        assert fake.compacted == ["controller"]
        # the decision timeline lives under the reserved trace id
        spans = sched.tracer.spans("controller")
        assert spans and all(s.name == "decision" for s in spans)
        assert {s.attrs["rule"] for s in spans} == \
            {"batch_k.grow", "live.compact"}
    finally:
        sched.close()


def test_enforce_shed_scales_admission_to_429():
    snap = _snap()
    sched = JobScheduler(snapshot=snap, metrics=MetricManager(),
                         autostart=False, profiling=False,
                         enforce_quotas=True,
                         quotas={"noisy": TenantQuota(max_in_flight=4)},
                         autotune="enforce", autotune_tick_s=3600.0)
    ctl = sched.controller
    try:
        # quota alone admits 4 in flight
        sched.submit(JobSpec(kind="bfs", params={"source_dense": 0},
                             tenant="noisy"))
        sched.submit(JobSpec(kind="bfs", params={"source_dense": 1},
                             tenant="noisy"))
        # a shed decision scales the CONFIGURED quota: 4 * 0.5 = 2
        ctl._signals_fn = lambda: _sig(
            occ=None, batches=0, burn=3.0, burn_slo="quiet-p95",
            protected=("quiet",),
            tenants={"noisy": {"in_flight": 2, "device_seconds": 1.0}},
            deltas={"noisy": 0.9})
        e = ctl.tick(force=True)
        assert [(x["rule"], x["new"]) for x in e] == \
            [("tenant.shed", 0.5)]
        with pytest.raises(QuotaExceeded):
            sched.submit(JobSpec(kind="bfs",
                                 params={"source_dense": 2},
                                 tenant="noisy"))
        # the interactive lane checks the SAME scaled quota — a shed
        # tenant cannot dodge the throttle via point queries
        lane = sched.interactive()
        with pytest.raises(QuotaExceeded):
            lane._admit("noisy")
        # unscaled tenants are untouched
        sched.submit(JobSpec(kind="bfs", params={"source_dense": 3},
                             tenant="quiet"))
        # a tenant with NO configured quota is never refused by a scale
        # (the controller scales limits, it does not invent them)
        ctl.scales["default"] = 0.25
        sched.submit(JobSpec(kind="bfs", params={"source_dense": 4}))
    finally:
        sched.close()


def test_enforce_cadence_hint_adopted_by_retryable_jobs(tmp_path):
    snap = _snap()
    sched = JobScheduler(snapshot=snap, metrics=MetricManager(),
                         autostart=False, profiling=False,
                         checkpoint_dir=str(tmp_path),
                         autotune="enforce", autotune_tick_s=3600.0)
    ctl = sched.controller
    try:
        ctl._signals_fn = lambda: _sig(
            occ=None, batches=0,
            recovery={"retries_delta": 1, "replayed_delta": 50,
                      "checkpoint_ms_mean": 20.0,
                      "round_ms_mean": 10.0})
        e = ctl.tick(force=True)
        assert [(x["rule"], x["new"]) for x in e] == \
            [("recovery.cadence", 14)]
        assert ctl.checkpoint_every_hint() == 14
        # a retryable job with NO cadence of its own adopts the hint
        j = sched.submit(JobSpec(kind="bfs",
                                 params={"source_dense": 0},
                                 max_retries=2))
        assert j.recovery is not None and j.recovery.every == 14
        # an explicit per-spec cadence always wins
        j2 = sched.submit(JobSpec(kind="bfs",
                                  params={"source_dense": 1},
                                  max_retries=2, checkpoint_every=3))
        assert j2.recovery.every == 3
        # a non-retryable job is never checkpointed by the hint
        j3 = sched.submit(JobSpec(kind="bfs",
                                  params={"source_dense": 2}))
        assert j3.recovery is None
    finally:
        sched.close()


def test_applied_decisions_stitched_into_job_traces():
    snap = _snap()
    sched = JobScheduler(snapshot=snap, metrics=MetricManager(),
                         autostart=False, profiling=False, max_batch=8,
                         autotune="enforce", autotune_tick_s=3600.0)
    ctl = sched.controller
    try:
        ctl._signals_fn = lambda: _sig(occ=8.0)
        # seed occupancy so the grow rule has a reading, then decide
        sched._metrics.histogram("serving.batch.occupancy").update(8.0)
        e = ctl.tick(force=True)
        assert e and e[0]["applied"]
        jobs = _run_jobs(sched, snap, k=4)
        tree = sched.tracer.tree(jobs[0].id)

        def names(node, acc):
            acc.append(node["name"])
            for c in node["children"]:
                names(c, acc)
            return acc

        got = []
        for root in tree["spans"]:
            names(root, got)
        assert "controller" in got
        spans = sched.tracer.spans(jobs[0].id)
        ctl_spans = [s for s in spans if s.name == "controller"]
        assert ctl_spans[0].attrs["decisions"][0]["rule"] == \
            "batch_k.grow"
    finally:
        sched.close()


# -- HTTP + postmortem surfaces ----------------------------------------------

def test_get_controller_endpoint():
    import urllib.request

    import titan_tpu
    from titan_tpu import example
    from titan_tpu.server import GraphServer

    g = titan_tpu.open("inmemory")
    example.load(g)
    sched = JobScheduler(graph=g, metrics=MetricManager(),
                         autostart=False, profiling=False,
                         autotune_tick_s=3600.0)
    srv = GraphServer(g, port=0, scheduler=sched).start()
    try:
        ctl = sched.controller
        ctl._signals_fn = lambda: _sig(occ=16.0)
        ctl.tick(force=True)
        with urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/controller",
                timeout=30) as resp:
            body = json.loads(resp.read())
        assert body["enabled"] is True
        assert body["mode"] == "shadow"
        # "knobs" is the EFFECTIVE state — shadow moved nothing; the
        # would-be trajectory is reported apart as shadow_knobs
        assert body["knobs"]["batcher.target_k"] == 16
        assert body["shadow_knobs"]["batcher.target_k"] == 32
        decs = body["decisions"]
        assert decs and decs[0]["rule"] == "batch_k.grow"
        # the wire entry replays — GET /controller is enough to audit
        assert replay(decs[0])["new"] == decs[0]["new"]
    finally:
        srv.stop()
        g.close()


def test_postmortem_bundle_carries_controller_state(tmp_path):
    snap = _snap()
    sched = JobScheduler(snapshot=snap, metrics=MetricManager(),
                         autostart=False, profiling=False,
                         flight_dir=str(tmp_path),
                         autotune_tick_s=3600.0)
    try:
        sched.controller._signals_fn = lambda: _sig(occ=16.0)
        sched.controller.tick(force=True)
        j = sched.submit(JobSpec(kind="bfs",
                                 params={"source": "junk"}))
        sched.start()
        assert j.wait(60)
        deadline = time.time() + 10
        while time.time() < deadline and j.dump_path is None:
            time.sleep(0.01)
        assert j.dump_path is not None
        with open(j.dump_path) as f:
            bundle = json.load(f)
        ctl = bundle["state"]["controller"]
        assert ctl["mode"] == "shadow"
        assert ctl["decisions"] and \
            ctl["decisions"][0]["rule"] == "batch_k.grow"
        assert bundle["config"]["autotune"] == "shadow"
    finally:
        sched.close()
