"""Replica fleet serving tier (ISSUE 19): router, failover, counters.

Three layers of coverage:

* **routing policy** — FleetMembership/_pick over an injected fetch
  (synthetic expositions, no sockets): weighted pick steers to the
  roomier replica, signal weights respond to the controller's fleet
  knob, eviction/un-evict rides the Federator contract;
* **live fleet** — a real FleetRouter over in-process GraphServer
  replicas sharing one KCVS store: admission counts
  ``serving.jobs.submitted`` exactly ONCE per logical job (the
  double-count regression), failover re-dispatches under the unchanged
  idempotency key, counts ``serving.fleet.redispatches``, and the
  stitched trace shows the dead replica's partial spans beside the
  redispatch span;
* **adoption** — a survivor scheduler over the shared checkpoint store
  RESUMES an idempotency-keyed job from the dead scheduler's newest
  checkpoint (``serving.recovery.resumes``), bit-equal to an
  uninterrupted run.

The full multi-PROCESS drill (SIGKILL and all) lives in
scripts/fleet_smoke.sh behind RUN_SMOKES=1; these tests keep the same
contracts pinned inside tier-1.
"""

import json
import tempfile
import time

import numpy as np
import pytest

import titan_tpu
from titan_tpu.errors import TemporaryBackendError
from titan_tpu.olap.api import JobSpec
from titan_tpu.olap.fleet import FleetMembership, FleetRouter
from titan_tpu.olap.serving.scheduler import JobScheduler
from titan_tpu.storage.inmemory import InMemoryStoreManager
from titan_tpu.storage.remote import KCVSServer
from titan_tpu.utils.httpnode import json_call, text_get
from titan_tpu.utils.metrics import MetricManager


def _expo(depth: float, hbm: float) -> str:
    """A minimal replica exposition carrying the two scraped routing
    samples (sanitized names, like promexport renders them)."""
    return (f"# TYPE serving_queue_depth counter\n"
            f"serving_queue_depth {depth}\n"
            f"# TYPE serving_hbm_resident_bytes gauge\n"
            f"serving_hbm_resident_bytes {hbm}\n")


class _FakeFleet:
    """Injectable fetch over synthetic replicas: ``rows`` maps url ->
    {"depth", "hbm", "lag"}; urls in ``dead`` raise."""

    def __init__(self, rows):
        self.rows = rows
        self.dead = set()

    def __call__(self, url, path):
        if url in self.dead:
            raise OSError("connection refused")
        row = self.rows[url]
        path = path.split("?", 1)[0]
        if path == "/metrics":
            return _expo(row.get("depth", 0), row.get("hbm", 0))
        if path == "/healthz":
            return json.dumps({"live": True, "ready": True})
        if path == "/live":
            return json.dumps(
                {"enabled": True,
                 "freshness": {"lag_epochs": row.get("lag", 0)}})
        raise ValueError(path)


# --------------------------------------------------------------------------
# membership + routing policy (no sockets)
# --------------------------------------------------------------------------

def test_membership_signals_parse_scraped_exposition():
    fake = _FakeFleet({"http://r1": {"depth": 3, "hbm": 1e6, "lag": 2},
                       "http://r2": {"depth": 0, "hbm": 0}})
    mem = FleetMembership(metrics=MetricManager(), fetch=fake)
    mem.add_replica("http://r1", instance="r1")
    mem.add_replica("http://r2", instance="r2")
    mem.scrape()
    sig = mem.signals()
    assert sig["r1"]["up"] and sig["r2"]["up"]
    assert sig["r1"]["queue_depth"] == 3.0
    assert sig["r1"]["hbm_resident_bytes"] == 1e6
    assert sig["r1"]["lag_epochs"] == 2.0
    assert sig["r2"]["queue_depth"] == 0.0
    assert sig["r2"]["lag_epochs"] == 0.0


def test_membership_eviction_and_unevict():
    """The Federator's consecutive-failure contract drives routability:
    a dead replica leaves the live set on its FIRST failed scrape
    round, is evicted at max_failures, and rejoins on recovery."""
    fake = _FakeFleet({"http://r1": {"depth": 0},
                       "http://r2": {"depth": 0}})
    mem = FleetMembership(metrics=MetricManager(), fetch=fake,
                          max_failures=3)
    mem.add_replica("http://r1", instance="r1")
    mem.add_replica("http://r2", instance="r2")
    mem.scrape()
    fake.dead.add("http://r1")
    mem.scrape()
    assert not mem.signals()["r1"]["up"]        # down at first failure
    assert not mem.fleet()["peers"][0]["evicted"]
    mem.scrape(); mem.scrape()
    assert mem.fleet()["peers"][0]["evicted"]   # 3rd consecutive
    fake.dead.clear()
    mem.scrape()
    sig = mem.signals()
    assert sig["r1"]["up"] and not mem.fleet()["peers"][0]["evicted"]


def _router(fake, **kw):
    kw.setdefault("metrics", MetricManager())
    kw.setdefault("autotune", "off")
    r = FleetRouter(fetch=fake, autopump=False, **kw)
    return r


def test_pick_prefers_roomier_replica_and_weights_move_it():
    """The weighted pick: default-neutral weights send traffic to the
    emptier replica; an enforce-mode controller's fleet weight changes
    the decision (the autotune-adjustable routing knob)."""
    fake = _FakeFleet({"http://r1": {"depth": 8, "hbm": 4e8},
                       "http://r2": {"depth": 2, "hbm": 5e8}})
    router = _router(fake, autotune="enforce")
    router.add_replica("http://r1", instance="r1")
    router.add_replica("http://r2", instance="r2")
    router.membership.scrape()
    # depth dominates under neutral weights: r2 (emptier queue) wins
    assert router._pick()[0] == "r2"
    # excluding the winner falls through to the survivor
    assert router._pick(exclude={"r2"})[0] == "r1"
    # bias HBM headroom hard enough and the loaded-HBM replica loses
    router.controller.fleet_weights["hbm"] = 100.0
    assert router._pick()[0] == "r1"
    assert router._weights()["hbm"] == 100.0
    # outside enforce mode the knob must NOT steer (shadow journals,
    # routing stays neutral)
    shadow = _router(fake, autotune="shadow")
    shadow.add_replica("http://r1", instance="r1")
    shadow.add_replica("http://r2", instance="r2")
    shadow.membership.scrape()
    shadow.controller.fleet_weights["hbm"] = 100.0
    assert shadow._weights()["hbm"] == 1.0
    assert shadow._pick()[0] == "r2"


def test_pick_breaks_ties_deterministically():
    """Equal scores resolve by instance name — same signals, same
    pick, every time (debuggability over spray)."""
    fake = _FakeFleet({"http://r1": {"depth": 1},
                       "http://r2": {"depth": 1}})
    router = _router(fake)
    router.add_replica("http://r2", instance="b")
    router.add_replica("http://r1", instance="a")
    router.membership.scrape()
    assert router._pick()[0] == "a"


def test_pick_skips_down_replicas_and_empty_fleet():
    fake = _FakeFleet({"http://r1": {"depth": 0},
                       "http://r2": {"depth": 9}})
    router = _router(fake)
    router.add_replica("http://r1", instance="r1")
    router.add_replica("http://r2", instance="r2")
    router.membership.scrape()
    fake.dead.add("http://r1")
    router.membership.scrape()
    assert router._pick()[0] == "r2"
    fake.dead.add("http://r2")
    router.membership.scrape()
    assert router._pick() is None


def test_fleet_signals_depth_spread_feeds_the_controller():
    """The router-side controller sees ONLY the fleet block — its
    depth_spread signal is what _rule_fleet keys on, and scheduler
    rules stay inert for lack of their blocks."""
    from titan_tpu.olap.serving.autotune import evaluate

    fake = _FakeFleet({"http://r1": {"depth": 0},
                       "http://r2": {"depth": 0}})
    router = _router(fake, autotune="enforce")
    router.add_replica("http://r1", instance="r1")
    router.add_replica("http://r2", instance="r2")
    router.membership.scrape()
    router._inflight = {"r1": 8, "r2": 0}
    sig = router._fleet_signals()
    assert sig["fleet"]["depth_spread"] == 2.0    # (8-0)/4
    sig["knobs"] = {"fleet_weights": {}}
    props = evaluate(sig, sig["knobs"], router.controller.params)
    assert [p["rule"] for p in props] == ["fleet.rebalance"]
    assert props[0]["knob"] == "fleet.routing_weight.depth"


# --------------------------------------------------------------------------
# live fleet: real replicas over one shared store
# --------------------------------------------------------------------------

_TERMINAL = ("done", "failed", "timeout", "cancelled", "expired")


@pytest.fixture(scope="module")
def shared_store():
    storage = KCVSServer(InMemoryStoreManager()).start()
    gcfg = {"storage.backend": "remote-cluster",
            "storage.hostname": [f"127.0.0.1:{storage.port}"]}
    loader = titan_tpu.open(dict(gcfg))
    tx = loader.new_transaction()
    vs = [tx.add_vertex() for _ in range(40)]
    for a in range(39):
        tx.add_edge(vs[a], "knows", vs[a + 1])
    tx.commit()
    yield gcfg, [v.id for v in vs]
    loader.close()
    storage.stop()


def _start_replicas(gcfg, count, ck):
    from titan_tpu.olap.fleet.replica import build

    reps = []
    for _ in range(count):
        g, sched, srv = build({"graph": gcfg, "checkpoint_dir": ck})
        srv.start()
        reps.append((g, sched, srv))
    return reps


def _stop_replicas(reps):
    for g, sched, srv in reps:
        try:
            sched.close(timeout=30)
        except Exception:   # noqa: BLE001 — teardown
            pass
        srv.stop()


def _drive(router, jid, rounds=400):
    w = None
    for _ in range(rounds):
        router.pump()
        w = json.loads(text_get(router.url, f"/jobs/{jid}"))
        if w["state"] in _TERMINAL:
            return w
        time.sleep(0.02)
    return w


def test_router_submit_complete_and_count_once(shared_store):
    """Happy path over real replicas: the public surface works end to
    end and admission counts submitted exactly once per logical job."""
    gcfg, ids = shared_store
    reps = _start_replicas(gcfg, 2, tempfile.mkdtemp())
    m = MetricManager()
    router = FleetRouter(
        [f"http://{s.host}:{s.port}" for _, _, s in reps],
        metrics=m, autotune="off", autopump=False).start()
    try:
        out = json_call(router.url, "/jobs",
                        {"kind": "bfs", "source": ids[0],
                         "targets": [ids[-1]]})
        w = _drive(router, out["job"])
        assert w["state"] == "done", w
        assert w["remote"]["result"]["targets"] == {str(ids[-1]): 39}
        assert m.counter_value("serving.jobs.submitted") == 1
        assert m.counter_value("serving.jobs.submitted",
                               labels={"kind": "bfs"}) == 1
        assert m.counter_value("serving.fleet.routed") == 1
        assert m.counter_value("serving.fleet.redispatches") == 0
        # surfaces: /fleet, /healthz, federated /metrics, /traverse
        fl = json.loads(text_get(router.url, "/fleet"))
        assert fl["up"] == 2 and fl["down"] == 0
        assert fl["routing"]["weights"]["depth"] == 1.0
        hz = json.loads(text_get(router.url, "/healthz"))
        assert hz["ready"] and hz["replicas_up"] == 2
        body = text_get(router.url, "/metrics?federate=1")
        assert 'instance="' in body
        assert "serving_fleet_replicas_up 2" in body
        tv = json_call(router.url, "/traverse",
                       {"start": [ids[0]], "steps": [["out", "knows"]]})
        assert tv["replica"] in fl["routing"]["inflight"] or True
        assert m.counter_value("serving.fleet.routed") == 2
    finally:
        router.stop()
        _stop_replicas(reps)


def test_failover_redispatches_once_never_recounts_submit(shared_store):
    """THE failover contract: the dispatched replica dies with the job
    in flight; the router re-dispatches to the survivor under the SAME
    idempotency key; the job completes bit-equal;
    ``serving.jobs.submitted`` stays at 1 (the double-count
    regression) while ``serving.fleet.redispatches`` counts the
    failover; the stitched trace carries the dead replica's partial
    spans AND the redispatched-marked dispatch span beside the
    survivor's.

    Determinism: the victim's scheduler never starts (autostart=False),
    so the job is ALWAYS still in flight at the kill — no race against
    a warm-JIT BFS finishing early. Its instance name ("a-victim")
    wins the equal-signal tie-break, pinning the initial pick. The
    mid-RUN kill with checkpoint resume is scripts/fleet_smoke.sh's
    job (real SIGKILL); the resume substrate is pinned below in
    test_idempotency_key_adopts_checkpoints_across_schedulers."""
    from titan_tpu.olap.fleet.replica import build

    gcfg, ids = shared_store
    ck = tempfile.mkdtemp()
    gv, sv, srvv = build({"graph": gcfg, "checkpoint_dir": ck,
                          "scheduler": {"autostart": False}})
    gs, ss, srvs = build({"graph": gcfg, "checkpoint_dir": ck})
    reps = [(gv, sv, srvv), (gs, ss, srvs)]
    srvv.start(); srvs.start()
    m = MetricManager()
    router = FleetRouter(metrics=m, autotune="off",
                         autopump=False)
    router.add_replica(f"http://{srvv.host}:{srvv.port}",
                       instance="a-victim")
    router.add_replica(f"http://{srvs.host}:{srvs.port}",
                       instance="b-survivor")
    router.start()
    try:
        out = json_call(router.url, "/jobs",
                        {"kind": "bfs", "source": ids[0],
                         "checkpoint_every": 1, "targets": [ids[-1]]})
        jid = out["job"]
        assert out["replica"] == "a-victim"
        # partial spans (submit, at least) ride back before the death
        for _ in range(2):
            router.pump()
        assert json.loads(
            text_get(router.url, f"/jobs/{jid}"))["state"] == "queued"
        srvv.stop()
        w = _drive(router, jid)
        assert w["state"] == "done", w
        assert w["replica"] == "b-survivor"
        assert w["attempts"] == 2
        # bit-equal completion (the 39-hop chain distance) on the
        # survivor, under the unchanged idempotency key
        assert w["remote"]["result"]["targets"] == {str(ids[-1]): 39}
        assert w["remote"].get("rounds_replayed", 0) <= 39
        assert m.counter_value("serving.jobs.submitted") == 1
        assert m.counter_value("serving.fleet.redispatches") == 1
        assert m.histogram_stats(
            "serving.fleet.redispatch_latency_ms")["count"] == 1
        # fleet view: the corpse is down, the survivor carried the job
        fl = json.loads(text_get(router.url, "/fleet"))
        rows = {p["instance"]: p for p in fl["peers"]}
        assert not rows["a-victim"]["up"]
        assert rows["b-survivor"]["up"]
        # stitched trace: two dispatch attempts under one root, the
        # first marked redispatched with the dead replica's remote
        # spans still parented under it
        tr = json.loads(text_get(router.url, f"/trace?job={jid}"))

        def walk(node):
            yield node
            for c in node.get("children", []):
                yield from walk(c)

        spans = [s for root in tr["spans"] for s in walk(root)]
        disp = [s for s in spans if s["name"] == "dispatch"]
        assert len(disp) == 2
        attrs = [s.get("attrs") or {} for s in disp]
        assert sum(1 for a in attrs if a.get("redispatched")) == 1
        dead_remote = [s for s in spans
                       if (s.get("attrs") or {}).get("instance")
                       == "a-victim"
                       and (s.get("attrs") or {}).get("remote")]
        assert dead_remote, "dead replica's partial spans must survive"
    finally:
        router.stop()
        _stop_replicas(reps)


def test_router_rejects_submit_with_no_replica_up():
    fake = _FakeFleet({"http://r1": {"depth": 0}})
    fake.dead.add("http://r1")
    router = _router(fake)
    router.add_replica("http://r1", instance="r1")
    router.membership.scrape()
    with pytest.raises(TemporaryBackendError):
        router._submit({"kind": "bfs", "source": 0})
    assert router._metrics.counter_value("serving.jobs.submitted") == 0


# --------------------------------------------------------------------------
# checkpoint adoption across schedulers (the failover substrate)
# --------------------------------------------------------------------------

def test_idempotency_key_adopts_checkpoints_across_schedulers(
        shared_store):
    """A second scheduler over the SHARED checkpoint store resumes an
    idempotency-keyed job from the first scheduler's newest checkpoint
    on its FIRST local attempt — the cross-process resume the router's
    failover relies on — and the result is bit-equal to a clean run."""
    gcfg, ids = shared_store
    ck = tempfile.mkdtemp()
    spec = dict(kind="bfs",
                params={"source": ids[0], "targets": [ids[-1]]},
                checkpoint_every=1, idempotency_key="logical-1")

    ma = MetricManager()
    ga = titan_tpu.open(dict(gcfg))
    A = JobScheduler(graph=ga, checkpoint_dir=ck, metrics=ma)
    ja = A.submit(JobSpec(**spec))
    deadline = time.time() + 30
    while (ja.checkpoint_round or 0) < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert (ja.checkpoint_round or 0) >= 3
    A.close(timeout=60)

    mb = MetricManager()
    gb = titan_tpu.open(dict(gcfg))
    B = JobScheduler(graph=gb, checkpoint_dir=ck, metrics=mb)
    try:
        jb = B.submit(JobSpec(**spec))
        assert jb.wait(120) and jb.state.value == "done", jb.error
        # resumed, not restarted: the adoption counter moved on B and
        # the replay charge is bounded by the chain's round count
        assert mb.counter_value("serving.recovery.resumes") == 1
        assert jb.rounds_replayed <= 39
        assert jb.result["targets"] == {str(ids[-1]): 39}
    finally:
        B.close()

    # reference: an uninterrupted run elsewhere agrees bit-for-bit
    mc = MetricManager()
    gc = titan_tpu.open(dict(gcfg))
    C = JobScheduler(graph=gc, checkpoint_dir=tempfile.mkdtemp(),
                     metrics=mc)
    try:
        jc = C.submit(JobSpec(kind="bfs",
                              params={"source": ids[0],
                                      "targets": [ids[-1]]}))
        assert jc.wait(120) and jc.state.value == "done", jc.error
        assert jc.result["targets"] == jb.result["targets"]
        assert mc.counter_value("serving.recovery.resumes") == 0
    finally:
        C.close()
