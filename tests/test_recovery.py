"""Checkpoint & recovery plane (olap/recovery + serving integration).

The acceptance contract (ISSUE r8): for each of BFS / SSSP / WCC /
PageRank, a run crashed at an injected round k and resumed from its
newest checkpoint produces final arrays BIT-EQUAL to an uninterrupted
run; a corrupted checkpoint is rejected by digest and recovery falls
back to the previous valid one (or a clean restart), never a wrong
answer. Faults are injected deterministically (recovery/faults.py) so
every path runs without flakiness.

Graph shapes: ONE vertex count (the same n=192 / m=900 seed-42 arrays
as tests/test_serving.py) across every kernel test in this file — the
round kernels compile per power-of-two capacity bucket and tier-1 is
serial and budgeted, so sharing shapes shares every XLA compile with
the serving suite.
"""

import os
import time

import numpy as np
import pytest

from titan_tpu.olap.api import JobSpec
from titan_tpu.olap.recovery import (CheckpointInvalid, CheckpointStore,
                                     FaultPlan, InjectedFault,
                                     SnapshotEvicted)
from titan_tpu.olap.serving.scheduler import JobScheduler
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.utils.metrics import MetricManager

_N = 192


def _sym_snapshot(seed: int, n: int = _N, m: int = 900):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


@pytest.fixture(scope="module")
def snap_main():
    return _sym_snapshot(42)


@pytest.fixture
def metrics():
    return MetricManager()


def _source(snap) -> int:
    return int(np.flatnonzero(snap.out_degree > 0)[0])


def _run_recovered(snap, spec: JobSpec, metrics, tmp_path, timeout=120.0):
    """Submit one job on a checkpointing scheduler; return the DONE job
    (asserting it finished)."""
    sched = JobScheduler(snapshot=snap, metrics=metrics,
                         checkpoint_dir=str(tmp_path / "ckpt"))
    try:
        job = sched.submit(spec)
        assert job.wait(timeout), "job did not reach a terminal state"
        return job
    finally:
        sched.close()


# --------------------------------------------------------------------------
# store: manifest + digests + atomic commit
# --------------------------------------------------------------------------

def test_store_roundtrip_and_ordering(tmp_path):
    st = CheckpointStore(str(tmp_path))
    a1 = {"dist": np.arange(16, dtype=np.int32)}
    st.save("j1", attempt=1, round_=10, kind="bfs", arrays=a1,
            meta={"epoch": 3})
    st.save("j1", attempt=2, round_=5, kind="bfs",
            arrays={"dist": np.arange(16, dtype=np.int32) * 2})
    # newest ATTEMPT wins even at a lower round (attempt 2 restarted
    # because attempt 1's trajectory was abandoned)
    ck = st.latest("j1")
    assert (ck.attempt, ck.round) == (2, 5)
    assert (ck.arrays["dist"] == np.arange(16, dtype=np.int32) * 2).all()
    # per-job isolation
    assert st.latest("j2") is None
    # meta + kind survive the roundtrip
    ck1 = st.load(st.checkpoints("j1")[0])
    assert ck1.meta == {"epoch": 3} and ck1.kind == "bfs"


def test_store_objects_payload_roundtrip(tmp_path):
    """Host-object payloads (host BSP computer state) are digest-checked
    pickles."""
    st = CheckpointStore(str(tmp_path))
    payload = {"states": {1: {"n": 2}}, "memory": {"x": 1.5}}
    st.save("j1", attempt=1, round_=2, kind="host", objects=payload)
    ck = st.latest("j1")
    assert ck.objects == payload


def test_store_detects_torn_and_corrupt_writes(tmp_path):
    st = CheckpointStore(str(tmp_path))
    p1 = st.save("j1", attempt=1, round_=1, kind="bfs",
                 arrays={"dist": np.arange(64, dtype=np.int32)})
    p2 = st.save("j1", attempt=1, round_=2, kind="bfs",
                 arrays={"dist": np.arange(64, dtype=np.int32) + 1})
    # a torn write is a tmp dir that never got renamed: invisible
    os.makedirs(os.path.join(str(tmp_path), "j1",
                             ".tmp-ckpt-a0001-r00000003-999"))
    assert st.latest("j1").round == 2
    # corrupt the newest payload: digest rejects it, latest() falls
    # back to the previous valid checkpoint
    FaultPlan.corrupt(p2)
    assert not st.validate(p2)
    with pytest.raises(CheckpointInvalid):
        st.load(p2)
    assert st.latest("j1").round == 1
    # corrupt the fallback too: no usable checkpoint -> clean restart
    FaultPlan.corrupt(p1)
    assert st.latest("j1") is None


def test_store_detects_manifest_garble(tmp_path):
    st = CheckpointStore(str(tmp_path))
    p = st.save("j1", attempt=1, round_=1, kind="bfs",
                arrays={"dist": np.zeros(8, np.int32)})
    with open(os.path.join(p, "manifest.json"), "w") as f:
        f.write("{not json")
    assert st.latest("j1") is None


# --------------------------------------------------------------------------
# fault injector: deterministic by construction
# --------------------------------------------------------------------------

def test_fault_plan_is_deterministic():
    assert FaultPlan.seeded(7, 10) == FaultPlan.seeded(7, 10)
    plan = FaultPlan(crash_at_round=3)
    plan.check(2, attempt=1)                       # not yet
    with pytest.raises(InjectedFault):
        plan.check(3, attempt=1)
    plan.check(3, attempt=2)                       # retry runs clean
    ev = FaultPlan(evict_at_round=1)
    with pytest.raises(SnapshotEvicted):
        ev.check(1, attempt=1)


# --------------------------------------------------------------------------
# kernel-level resume: bit-equal continuation (no scheduler)
# --------------------------------------------------------------------------

def test_bfs_batched_resume_bit_equal(snap_main):
    from titan_tpu.models.bfs_hybrid import frontier_bfs_batched

    s = _source(snap_main)
    caps = {}

    def ck(level, dist, act):
        caps[level] = np.asarray(dist[:, :snap_main.n]).copy()

    ref, levels, comp = frontier_bfs_batched(snap_main, [s], checkpoint=ck)
    assert comp.all() and len(caps) >= 2
    ks = sorted(caps)
    for k in (ks[1], ks[-1]):       # an early and the last boundary
        d2, lv2, c2 = frontier_bfs_batched(snap_main, [s],
                                           init_dist=caps[k],
                                           start_level=k)
        assert c2.all() and (d2 == ref).all(), f"level {k}"
        assert (lv2 == levels).all()


def test_sssp_resume_bit_equal(snap_main):
    from titan_tpu.models.frontier import frontier_sssp

    s = _source(snap_main)
    caps = {}

    def ck(rounds, state):
        caps[rounds] = {"val": np.asarray(state["val"]).copy(),
                        "val_exp": np.asarray(state["val_exp"]).copy(),
                        "bucket_end": state["bucket_end"],
                        "quantile_mass": state["quantile_mass"]}

    ref, ref_rounds = frontier_sssp(snap_main, s, checkpoint=ck)
    mids = [r for r in sorted(caps) if r > 0]
    assert mids, "sssp finished in one round — no boundary to resume"
    resume = dict(caps[mids[len(mids) // 2]])
    resume["rounds"] = mids[len(mids) // 2]
    got, rounds = frontier_sssp(snap_main, s, resume=resume)
    assert (np.asarray(got) == np.asarray(ref)).all()
    assert rounds == ref_rounds


def test_wcc_resume_bit_equal(snap_main):
    from titan_tpu.models.frontier import frontier_wcc

    caps = {}

    def ck(rounds, state):
        caps[rounds] = {"val": np.asarray(state["val"]).copy(),
                        "val_exp": np.asarray(state["val_exp"]).copy(),
                        "levels": state["levels"]}

    ref, ref_rounds = frontier_wcc(snap_main, checkpoint=ck)
    assert caps, "wcc ran no propagation rounds"
    r0 = sorted(caps)[-1]
    resume = dict(caps[r0])
    resume["rounds"] = r0
    got, rounds = frontier_wcc(snap_main, resume=resume)
    assert (np.asarray(got) == np.asarray(ref)).all()
    assert rounds == ref_rounds       # levels restored from the capture


def test_pagerank_resume_bit_equal(snap_main):
    from titan_tpu.models.frontier import pagerank_dense

    caps = {}

    def ck(it, state):
        caps[it] = np.asarray(state["rank"]).copy()

    ref, ref_iters = pagerank_dense(snap_main, iterations=10,
                                    checkpoint=ck)
    assert sorted(caps) == list(range(1, 11))
    got, iters = pagerank_dense(snap_main, iterations=10,
                                resume={"rank": caps[5], "it": 5})
    assert (np.asarray(got) == np.asarray(ref)).all()
    assert iters == ref_iters


# --------------------------------------------------------------------------
# engine: chunked DenseProgram execution + TPUGraphComputer resume_from
# --------------------------------------------------------------------------

def test_engine_chunked_run_bit_equal_and_resumes(snap_main):
    from titan_tpu.models.bfs import BFS
    from titan_tpu.olap.tpu.engine import run_single

    prog = BFS(max_iterations=100)
    s = _source(snap_main)
    ref = run_single(prog, snap_main, {"source_dense": s})
    caps = {}
    got = run_single(prog, snap_main, {"source_dense": s},
                     checkpoint=lambda it, st: caps.__setitem__(
                         it, {k: np.asarray(v) for k, v in st.items()}),
                     checkpoint_every=2)
    assert (got["dist"] == ref["dist"]).all()
    assert got.iterations == ref.iterations
    # resume from a mid-run boundary
    mid = sorted(caps)[0]
    res = run_single(prog, snap_main, {"source_dense": s},
                     resume={"state": caps[mid], "iteration": mid})
    assert (res["dist"] == ref["dist"]).all()
    assert res.iterations == ref.iterations


def test_computer_resume_from_checkpoint_dir(snap_main, tmp_path):
    """TPUGraphComputer.run(resume_from=...) reloads the newest VALID
    checkpoint under the path (a corrupted newest one is skipped by
    digest) and continues to the same final arrays."""
    from titan_tpu.models.bfs import BFS
    from titan_tpu.olap.tpu.engine import TPUGraphComputer, run_single

    s = _source(snap_main)
    comp = TPUGraphComputer(snapshot=snap_main, num_devices=1)
    ref = run_single(BFS(max_iterations=100), snap_main,
                     {"source_dense": s})
    ckdir = str(tmp_path / "run-ckpt")
    # a run truncated by its iteration cap leaves checkpoints behind...
    comp.run(BFS(max_iterations=2), {"source_dense": s},
             checkpoint_to=ckdir, checkpoint_every=1)
    # ...corrupt the newest so resume must fall back a round...
    store = CheckpointStore(ckdir)
    FaultPlan.corrupt(store.checkpoints("run")[-1])
    # ...and the resumed full run still converges bit-equal
    got = comp.run(BFS(max_iterations=100), {"source_dense": s},
                   resume_from=ckdir)
    assert (got["dist"] == ref["dist"]).all()
    with pytest.raises(ValueError):
        TPUGraphComputer(snapshot=snap_main, num_devices=2).run(
            BFS(), {"source_dense": s}, resume_from=ckdir)


def test_host_computer_checkpoint_resume():
    """Host BSP computer: superstep state (vertex states + memory)
    checkpoints as an object payload and a resumed run reaches the same
    final states and iteration count."""
    import titan_tpu
    from titan_tpu.olap.api import VertexProgram
    from titan_tpu.olap.computer import HostGraphComputer

    class CountProgram(VertexProgram):
        def execute(self, vertex, messenger, memory):
            vertex.set_state("n", vertex.get_state("n", 0) + 1)

        def terminate(self, memory):
            return memory.iteration >= 4

    g = titan_tpu.open("inmemory")
    try:
        tx = g.new_transaction()
        for i in range(4):
            tx.add_vertex("node", name=f"v{i}")
        tx.commit()
        comp = HostGraphComputer(g, num_threads=1)
        caps = {}
        ref = comp.run(CountProgram(), checkpoint_every=2,
                       checkpoint=lambda it, p: caps.__setitem__(it, p))
        assert ref.iterations == 5 and 2 in caps
        got = comp.run(CountProgram(), resume=caps[2])
        assert got.iterations == ref.iterations
        assert got.states == ref.states
    finally:
        g.close()


# --------------------------------------------------------------------------
# end-to-end: injected crash -> RETRYING -> resume -> bit-equal result
# --------------------------------------------------------------------------

def test_recovered_bfs_job_bit_equal(snap_main, metrics, tmp_path):
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid

    s = _source(snap_main)
    job = _run_recovered(
        snap_main,
        JobSpec(kind="bfs",
                params={"source_dense": s,
                        "faults": FaultPlan(crash_at_round=2)},
                max_retries=1, checkpoint_every=1, retry_backoff_s=0.01),
        metrics, tmp_path)
    assert job.state.value == "done", job.error
    assert job.attempt == 2 and job.checkpoint_round is not None
    ref, _ = frontier_bfs_hybrid(snap_main, s)
    assert (job.result["dist"] == np.asarray(ref)).all()
    assert metrics.counter_value("serving.recovery.resumes") == 1
    assert metrics.counter_value("serving.recovery.retries") == 1
    assert metrics.counter_value("serving.recovery.checkpoints") >= 1
    wire = job.to_wire()
    assert wire["attempt"] == 2 and "checkpoint_round" in wire


def test_recovered_sssp_job_bit_equal(snap_main, metrics, tmp_path):
    from titan_tpu.models.frontier import frontier_sssp

    s = _source(snap_main)
    job = _run_recovered(
        snap_main,
        JobSpec(kind="sssp",
                params={"source_dense": s,
                        "faults": FaultPlan(crash_at_round=4)},
                max_retries=1, checkpoint_every=1, retry_backoff_s=0.01),
        metrics, tmp_path)
    assert job.state.value == "done", job.error
    assert job.attempt == 2
    ref, _ = frontier_sssp(snap_main, s)
    assert (job.result["dist"] == np.asarray(ref)).all()
    assert metrics.counter_value("serving.recovery.resumes") == 1


def test_recovered_pagerank_job_bit_equal(snap_main, metrics, tmp_path):
    from titan_tpu.models.frontier import pagerank_dense

    job = _run_recovered(
        snap_main,
        JobSpec(kind="pagerank",
                params={"iterations": 8,
                        "faults": FaultPlan(crash_at_round=4)},
                max_retries=1, checkpoint_every=2, retry_backoff_s=0.01),
        metrics, tmp_path)
    assert job.state.value == "done", job.error
    assert job.attempt == 2
    ref, _ = pagerank_dense(snap_main, iterations=8)
    assert (job.result["rank"] == np.asarray(ref)).all()


def test_recovered_wcc_job_bit_equal(snap_main, metrics, tmp_path):
    """The BFS peel settles this graph's labels before any propagation
    round, so the crash at round 0 lands before the first cadence
    checkpoint — recovery takes the clean-restart path (resumes == 0)
    and must still be bit-equal."""
    from titan_tpu.models.frontier import frontier_wcc

    job = _run_recovered(
        snap_main,
        JobSpec(kind="wcc",
                params={"faults": FaultPlan(crash_at_round=0)},
                max_retries=1, checkpoint_every=1, retry_backoff_s=0.01),
        metrics, tmp_path)
    assert job.state.value == "done", job.error
    assert job.attempt == 2
    ref, ref_rounds = frontier_wcc(snap_main)
    assert (job.result["labels"] == np.asarray(ref)).all()
    assert job.result["rounds"] == ref_rounds


def test_corrupted_checkpoint_falls_back_then_bit_equal(
        snap_main, metrics, tmp_path):
    """The newest checkpoint is corrupted on disk after commit: resume
    must reject it by digest (serving.recovery.invalid_checkpoints),
    adopt the previous valid one, and still produce the exact result."""
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid

    s = _source(snap_main)
    job = _run_recovered(
        snap_main,
        JobSpec(kind="bfs",
                params={"source_dense": s,
                        "faults": FaultPlan(crash_at_round=4,
                                            corrupt_at_round=3)},
                max_retries=1, checkpoint_every=1, retry_backoff_s=0.01),
        metrics, tmp_path)
    assert job.state.value == "done", job.error
    ref, _ = frontier_bfs_hybrid(snap_main, s)
    assert (job.result["dist"] == np.asarray(ref)).all()
    assert metrics.counter_value(
        "serving.recovery.invalid_checkpoints") >= 1
    assert metrics.counter_value("serving.recovery.resumes") == 1


@pytest.mark.slow
def test_snapshot_eviction_mid_job_recovers(snap_main, metrics, tmp_path):
    """Injected mid-job loss of device residency: the retry re-uploads
    from host arrays and resumes from checkpoint, bit-equal."""
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid

    s = _source(snap_main)
    job = _run_recovered(
        snap_main,
        JobSpec(kind="bfs",
                params={"source_dense": s,
                        "faults": FaultPlan(evict_at_round=2)},
                max_retries=1, checkpoint_every=1, retry_backoff_s=0.01),
        metrics, tmp_path)
    assert job.state.value == "done", job.error
    assert "SnapshotEvicted" in (job.error or "") or job.attempt == 2
    ref, _ = frontier_bfs_hybrid(snap_main, s)
    assert (job.result["dist"] == np.asarray(ref)).all()


@pytest.mark.slow
def test_no_checkpoint_dir_retries_restart_clean(snap_main, metrics):
    """Fault plans work without a checkpoint store: the retry restarts
    from scratch (resumes == 0) and still completes correctly."""
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid

    s = _source(snap_main)
    sched = JobScheduler(snapshot=snap_main, metrics=metrics)
    try:
        job = sched.submit(JobSpec(
            kind="bfs",
            params={"source_dense": s,
                    "faults": FaultPlan(crash_at_round=2)},
            max_retries=1, checkpoint_every=1, retry_backoff_s=0.01))
        assert job.wait(60)
    finally:
        sched.close()
    assert job.state.value == "done", job.error
    assert job.attempt == 2
    assert metrics.counter_value("serving.recovery.resumes") == 0
    assert metrics.counter_value("serving.recovery.rounds_replayed") >= 1
    ref, _ = frontier_bfs_hybrid(snap_main, s)
    assert (job.result["dist"] == np.asarray(ref)).all()


@pytest.mark.slow
def test_dense_fault_without_store_still_fires(snap_main, metrics):
    """Fault injection on a 'dense' job must work WITHOUT a checkpoint
    store (the chunked loop is forced so the boundary hook exists):
    crash -> clean-restart retry -> correct result."""
    from titan_tpu.models.bfs import BFS
    from titan_tpu.olap.tpu.engine import run_single

    s = _source(snap_main)
    sched = JobScheduler(snapshot=snap_main, metrics=metrics)
    try:
        job = sched.submit(JobSpec(
            kind="dense",
            params={"program": BFS(max_iterations=100), "source_dense": s,
                    "faults": FaultPlan(crash_at_round=2)},
            max_retries=1, retry_backoff_s=0.01))
        assert job.wait(120)
    finally:
        sched.close()
    assert job.state.value == "done", job.error
    assert job.attempt == 2
    ref = run_single(BFS(max_iterations=100), snap_main,
                     {"source_dense": s})
    assert (job.result["dist"] == ref["dist"]).all()


@pytest.mark.slow
@pytest.mark.parametrize("kind,crash_at", [
    ("bfs", 1), ("bfs", 3), ("sssp", 2), ("sssp", 6),
    ("pagerank", 2), ("pagerank", 6), ("wcc", 0),
])
def test_fault_matrix_crash_positions(snap_main, metrics, tmp_path,
                                      kind, crash_at):
    """Slow sweep: crash position must not matter — every (kind, k)
    recovers bit-equal (CI tier; tier-1 covers one k per kind)."""
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid
    from titan_tpu.models.frontier import (frontier_sssp, frontier_wcc,
                                           pagerank_dense)

    s = _source(snap_main)
    params = {"faults": FaultPlan(crash_at_round=crash_at)}
    if kind in ("bfs", "sssp"):
        params["source_dense"] = s
    if kind == "pagerank":
        params["iterations"] = 8
    job = _run_recovered(
        snap_main,
        JobSpec(kind=kind, params=params, max_retries=2,
                checkpoint_every=1, retry_backoff_s=0.01),
        metrics, tmp_path)
    assert job.state.value == "done", job.error
    if kind == "bfs":
        ref = frontier_bfs_hybrid(snap_main, s)[0]
        assert (job.result["dist"] == np.asarray(ref)).all()
    elif kind == "sssp":
        ref = frontier_sssp(snap_main, s)[0]
        assert (job.result["dist"] == np.asarray(ref)).all()
    elif kind == "pagerank":
        ref = pagerank_dense(snap_main, iterations=8)[0]
        assert (job.result["rank"] == np.asarray(ref)).all()
    else:
        ref = frontier_wcc(snap_main)[0]
        assert (job.result["labels"] == np.asarray(ref)).all()


@pytest.mark.slow
def test_slow_write_fault_still_recovers(snap_main, metrics, tmp_path):
    job = _run_recovered(
        snap_main,
        JobSpec(kind="bfs",
                params={"source_dense": _source(snap_main),
                        "faults": FaultPlan(crash_at_round=3,
                                            slow_write_s=0.05)},
                max_retries=1, checkpoint_every=1, retry_backoff_s=0.01),
        metrics, tmp_path)
    assert job.state.value == "done", job.error
