"""Serializer-registry parity checklist against the reference.

Names every file in the reference's registry (titan-core
graphdb/database/serialize/attribute/, 29 serializers registered by
StandardSerializer.java) and its analog here, and exercises each covered
analog: self-describing round-trip, and — where the reference provides a
byte-order-preserving codec — that our ordered encoding sorts identically
to the values (reference: titan-test graphdb/serializer/SerializerTest
round-trip + order semantics).
"""

import datetime
import enum
import uuid

import numpy as np
import pytest

from titan_tpu.codec.attributes import DEFAULT, Serializer


class Color(enum.Enum):
    RED = 1
    GREEN = 2
    BLUE = 3


# reference serializer -> (our carrier value(s), orderable?) or a
# justification string for n/a rows
PARITY = {
    "BooleanSerializer": ([True, False], True),
    "ByteSerializer": ([-128, 0, 127], True),           # int codec
    "ShortSerializer": ([-32768, 0, 32767], True),      # int codec
    "IntegerSerializer": ([-2**31, 0, 2**31 - 1], True),
    "LongSerializer": ([-2**62, -1, 0, 1, 2**62], True),
    "CharacterSerializer": (["a", "é"], True),     # 1-char str
    "FloatSerializer": ([-1.5, 0.0, 2.25], True),
    "DoubleSerializer": ([-1e300, -0.0, 1e-300, 3.14], True),
    "StringSerializer": (["", "abc", "zürich"], True),
    "DateSerializer": ([
        datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc),
        datetime.datetime(2026, 7, 30, 12, 34, 56,
                          tzinfo=datetime.timezone.utc)], True),
    "InstantSerializer": ([
        datetime.datetime(2001, 2, 3, 4, 5, 6,
                          tzinfo=datetime.timezone.utc)], True),
    "DurationSerializer": ([datetime.timedelta(days=-2),
                            datetime.timedelta(microseconds=1)], True),
    "EnumSerializer": ([Color.RED, Color.BLUE], False),
    "UUIDSerializer": ([uuid.UUID(int=0), uuid.uuid5(
        uuid.NAMESPACE_DNS, "titan")], True),
    "ByteArraySerializer": ([b"", b"\x00\xff"], True),  # bytes codec
    "BooleanArraySerializer": ([np.array([True, False])], False),
    "CharArraySerializer": (["chars-as-str"], True),
    "ShortArraySerializer": ([np.array([-3, 7], np.int16)], False),
    "IntArraySerializer": ([np.array([1, 2, 3], np.int32)], False),
    "LongArraySerializer": ([np.array([2**40], np.int64)], False),
    "FloatArraySerializer": ([np.array([1.5], np.float32)], False),
    "DoubleArraySerializer": ([np.array([2.5], np.float64)], False),
    "StringArraySerializer": ([["a", "b"]], False),     # list codec
    "ArraySerializer": ([[1, "mixed", 2.5]], False),    # list codec
    "ObjectSerializer":
        "deliberate divergence: arbitrary-object pickling is a "
        "deserialization RCE vector; custom types register explicit "
        "handlers via Serializer.register (the reference's "
        "attributes.custom.* mechanism)",
    "ParameterSerializer":
        "index parameters are plain (str, value) pairs here, stored "
        "through the dict/list codecs by the schema layer "
        "(core/schema.py TypeDefinition) rather than a dedicated type",
    "ParameterArraySerializer":
        "see ParameterSerializer (list codec)",
    "StandardTransactionIdSerializer":
        "WAL records carry (instance_id, tx_ts) through the log codec "
        "(core/wal.py), not the attribute registry",
    "TypeDefinitionDescriptionSerializer":
        "schema definitions are vertices whose properties use the "
        "ordinary value codecs (core/schema.py schema-as-vertices)",
}


def test_checklist_is_exhaustive_against_reference_listing():
    # the 29 serializer files in the reference package
    assert len(PARITY) == 29


@pytest.mark.parametrize("name", sorted(PARITY))
def test_round_trip_or_justification(name):
    row = PARITY[name]
    if isinstance(row, str):
        assert len(row) > 20       # a real justification, not a stub
        return
    values, _ = row
    for v in values:
        b = DEFAULT.value_bytes(v)
        got = DEFAULT.value_from_bytes(b)
        if isinstance(v, np.ndarray):
            assert np.array_equal(got, v) and got.dtype == v.dtype
        else:
            assert got == v and type(got) is type(v)


@pytest.mark.parametrize("name", sorted(
    n for n, row in PARITY.items()
    if not isinstance(row, str) and row[1]))
def test_order_preserving_variants(name):
    values, _ = PARITY[name]
    t = type(values[0])
    assert DEFAULT.orderable(t), f"{name}: {t} must be orderable"
    enc = [DEFAULT.ordered_bytes(v, t) for v in values]
    order_vals = sorted(range(len(values)), key=lambda i: values[i])
    order_enc = sorted(range(len(values)), key=lambda i: enc[i])
    assert order_vals == order_enc
    # and the ordered form round-trips
    from titan_tpu.codec.attributes import ReadBuffer
    for v, e in zip(values, enc):
        assert DEFAULT.read_ordered(ReadBuffer(e), t) == v


def test_ordered_int_random_sort_parity():
    rng = np.random.default_rng(0)
    vals = [int(x) for x in rng.integers(-2**62, 2**62, 200)]
    enc = [DEFAULT.ordered_bytes(v, int) for v in vals]
    assert sorted(range(200), key=lambda i: vals[i]) == \
        sorted(range(200), key=lambda i: enc[i])


def test_ordered_float_random_sort_parity():
    rng = np.random.default_rng(1)
    vals = [float(x) for x in rng.normal(0, 1e10, 200)] + \
        [0.0, -0.0, 1e-320, -1e-320]
    enc = [DEFAULT.ordered_bytes(v, float) for v in vals]
    key_v = sorted(range(len(vals)), key=lambda i: (vals[i], enc[i]))
    key_e = sorted(range(len(vals)), key=lambda i: (enc[i],))
    # -0.0 == 0.0 compare equal; tie-break by encoding for determinism
    assert [vals[i] for i in key_v] == [vals[i] for i in key_e]


def test_enum_rejects_unknown_and_custom_registration():
    # a fresh registry without Enum still allows explicit registration
    s = Serializer()

    class Weird:
        def __init__(self, x):
            self.x = x

        def __eq__(self, other):
            return isinstance(other, Weird) and other.x == self.x

    from titan_tpu.codec.attributes import AttributeHandler
    s.register(AttributeHandler(
        200, Weird,
        lambda o, v: o.put_uvar(v.x),
        lambda b: Weird(b.get_uvar())))
    assert s.value_from_bytes(s.value_bytes(Weird(7))) == Weird(7)


def test_time_ordered_variant():
    vals = [datetime.time(0, 0), datetime.time(12, 30, 15, 250),
            datetime.time(23, 59, 59, 999999)]
    enc = [DEFAULT.ordered_bytes(v, datetime.time) for v in vals]
    assert enc == sorted(enc)
    with pytest.raises(TypeError):
        DEFAULT.ordered_bytes(
            datetime.time(1, 2, tzinfo=datetime.timezone.utc),
            datetime.time)


def test_int_enum_and_str_enum_keep_their_type():
    b = DEFAULT.value_bytes(Priority.HIGH)
    assert DEFAULT.value_from_bytes(b) is Priority.HIGH
    b2 = DEFAULT.value_bytes(Tag.X)
    assert DEFAULT.value_from_bytes(b2) is Tag.X


class Priority(enum.IntEnum):
    LOW = 1
    HIGH = 2


class Tag(str, enum.Enum):
    X = "x"


def test_local_enum_rejected_at_write_time():
    class Local(enum.Enum):
        A = 1
    with pytest.raises(TypeError, match="importable"):
        DEFAULT.value_bytes(Local.A)


def test_enum_read_guard_rejects_non_enum_paths():
    from titan_tpu.codec.dataio import DataOutput
    out = DataOutput()
    out.put_u8(20)                     # enum type code
    for s in ("os:path", "getcwd"):    # module attr that is NOT an Enum
        b = s.encode()
        out.put_uvar(len(b))
        out.put_bytes(b) if hasattr(out, "put_bytes") else [
            out.put_u8(x) for x in b]
    with pytest.raises(TypeError, match="Enum class"):
        DEFAULT.value_from_bytes(out.getvalue())


def test_int_enum_schema_key_gets_enum_dtype():
    import titan_tpu
    g = titan_tpu.open("inmemory")
    tx = g.new_transaction()
    tx.add_vertex("job", prio=Priority.HIGH)
    tx.commit()
    key = g.schema.get_by_name("prio")
    assert key.dtype is enum.Enum
    tx = g.new_transaction()
    [v] = [x for x in tx.vertices()]
    assert v.value("prio") is Priority.HIGH
    g.close()
