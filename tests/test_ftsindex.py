"""Index-provider SPI contract, parameterized over both local providers.

The reference's pattern: one shared suite (titan-test IndexProviderTest)
instantiated per backend (Lucene/ES/Solr). Here: the in-memory provider and
the sqlite-FTS5 provider (the Lucene-role embedded engine).
"""

import pytest

from titan_tpu.indexing.ftsindex import FTSIndex
from titan_tpu.indexing.memindex import MemoryIndex
from titan_tpu.indexing.provider import (And, FieldCondition, IndexQuery,
                                         KeyInformation, RawQuery)
from titan_tpu.query.predicates import P


@pytest.fixture(params=["mem", "fts", "fts-disk"])
def provider(request, tmp_path):
    if request.param == "mem":
        p = MemoryIndex("t")
    elif request.param == "fts":
        p = FTSIndex("t")
    else:
        p = FTSIndex("t", str(tmp_path / "idx"))
    yield p
    p.close()


def _doc(provider, store, docid, **fields):
    tx = provider.begin_transaction()
    for k, v in fields.items():
        tx.add(store, docid, k, v)
    tx.commit()


def _fill(provider):
    provider.register("s", "title", KeyInformation(str))
    provider.register("s", "sku", KeyInformation(str, parameters=("STRING",)))
    provider.register("s", "price", KeyInformation(float))
    _doc(provider, "s", "d1", title="red fish blue fish", sku="A-1", price=3.5)
    _doc(provider, "s", "d2", title="one fish two fish", sku="A-2", price=9.0)
    _doc(provider, "s", "d3", title="green eggs and ham", sku="B-1", price=5.0)


def test_text_contains(provider):
    _fill(provider)
    hits = provider.query("s", IndexQuery(
        FieldCondition("title", P.text_contains("fish"))))
    assert hits == ["d1", "d2"]
    # multi-token AND semantics
    hits = provider.query("s", IndexQuery(
        FieldCondition("title", P.text_contains("blue fish"))))
    assert hits == ["d1"]


def test_conjunction_with_numeric_range(provider):
    _fill(provider)
    q = IndexQuery(And((FieldCondition("title", P.text_contains("fish")),
                        FieldCondition("price", P.gt(4.0)))))
    assert provider.query("s", q) == ["d2"]


def test_string_mapped_exact(provider):
    _fill(provider)
    hits = provider.query("s", IndexQuery(
        FieldCondition("sku", P.eq("B-1"))))
    assert hits == ["d3"]


def test_order_and_limit(provider):
    _fill(provider)
    q = IndexQuery(FieldCondition("price", P.gt(0.0)),
                   orders=(("price", "desc"),), limit=2)
    assert provider.query("s", q) == ["d2", "d3"]


def test_field_deletion_and_doc_deletion(provider):
    _fill(provider)
    tx = provider.begin_transaction()
    tx.delete("s", "d1", "title")
    tx.commit()
    hits = provider.query("s", IndexQuery(
        FieldCondition("title", P.text_contains("fish"))))
    assert hits == ["d2"]
    tx2 = provider.begin_transaction()
    tx2.delete_document("s", "d2")
    tx2.commit()
    hits = provider.query("s", IndexQuery(
        FieldCondition("title", P.text_contains("fish"))))
    assert hits == []


def test_raw_query(provider):
    _fill(provider)
    hits = provider.raw_query("s", RawQuery("title:fish"))
    assert {d for d, _ in hits} == {"d1", "d2"}
    assert all(score > 0 for _, score in hits)
    hits = provider.raw_query("s", RawQuery("fish eggs"))
    assert hits == []                # AND across terms
    hits = provider.raw_query("s", RawQuery("title:fish", limit=1))
    assert len(hits) == 1


def test_drop_store(provider):
    _fill(provider)
    provider.drop_store("s")
    assert provider.query("s", IndexQuery(
        FieldCondition("title", P.text_contains("fish")))) == []


def test_fts_persistence_across_reopen(tmp_path):
    d = str(tmp_path / "idx")
    p = FTSIndex("t", d)
    _fill(p)
    p.close()
    p2 = FTSIndex("t", d)
    try:
        hits = p2.query("s", IndexQuery(
            FieldCondition("title", P.text_contains("fish"))))
        assert hits == ["d1", "d2"]
        # keyinfo (STRING mapping) survived too
        assert p2.query("s", IndexQuery(
            FieldCondition("sku", P.eq("A-2")))) == ["d2"]
        assert p2.raw_query("s", RawQuery("eggs"))[0][0] == "d3"
    finally:
        p2.close()
