"""Frontier-sparse traversal kernels: hybrid BFS, SSSP, WCC.

(reference parity: titan-test olap/OLAPTest + ShortestDistanceVertexProgram
semantics, validated here against plain-python BFS/Bellman-Ford/union-find
on random symmetrized graphs.)
"""

import numpy as np
import pytest

from titan_tpu.models import bfs_hybrid as H
from titan_tpu.models import frontier as F
from titan_tpu.models.bfs import INF, frontier_bfs
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.olap.tpu.rmat import rmat_edges


def sym_snap(rng, n, m):
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


def adjacency_with_slots(snap):
    """[(v, w, slot)] edges exactly as the chunked kernels see them."""
    g = H.build_chunked_csr(snap)
    colstart = np.asarray(g["colstart"])
    dstT = np.asarray(g["dstT"])
    deg = np.asarray(g["deg"])[:-1]
    edges = []
    for v in range(snap.n):
        for k in range(int(deg[v])):
            col = int(colstart[v]) + k // 8
            lane = k % 8
            edges.append((v, int(dstT[lane, col]), col * 8 + lane))
    return edges


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_hybrid_bfs_matches_reference(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(50, 400))
    snap = sym_snap(rng, n, int(rng.integers(n, 5 * n)))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, _ = frontier_bfs(snap, source)
    d_hyb, _ = H.frontier_bfs_hybrid(snap, source)
    assert (d_ref == np.asarray(d_hyb)).all()


def test_hybrid_bfs_rmat_both_modes():
    src, dst = rmat_edges(11, 8, seed=4)
    n = 1 << 11
    snap = snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, _ = frontier_bfs(snap, source)
    d_hyb, lv = H.frontier_bfs_hybrid(snap, source)
    assert (d_ref == np.asarray(d_hyb)).all() and lv > 2


@pytest.mark.parametrize("seed", [5, 6])
def test_frontier_sssp_matches_bellman_ford(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(30, 150))
    snap = sym_snap(rng, n, int(rng.integers(n, 4 * n)))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    edges = adjacency_with_slots(snap)
    w = F.slot_weights_np(np.asarray([s for _, _, s in edges]))
    # host Bellman-Ford over the same directed weighted edges
    dist = np.full(n, np.inf)
    dist[source] = 0.0
    for _ in range(n):
        changed = False
        for (v, u, _), wi in zip(edges, w):
            if dist[v] + wi < dist[u]:
                dist[u] = dist[v] + wi
                changed = True
        if not changed:
            break
    got, rounds = F.frontier_sssp(snap, source)
    finite = dist < np.inf
    assert (np.asarray(got)[finite] == pytest.approx(dist[finite],
                                                     rel=1e-5))
    assert (np.asarray(got)[~finite] >= float(F.FINF) - 1).all()


@pytest.mark.parametrize("seed", [7, 8])
def test_frontier_wcc_matches_union_find(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(30, 300))
    snap = sym_snap(rng, n, int(rng.integers(max(2, n // 3), 2 * n)))
    parent = list(range(n))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for v, u, _ in adjacency_with_slots(snap):
        parent[find(v)] = find(u)
    comp_min = {}
    for v in range(n):
        r = find(v)
        comp_min[r] = min(comp_min.get(r, v), v)
    expect = np.asarray([comp_min[find(v)] for v in range(n)])
    got, rounds = F.frontier_wcc(snap)
    assert (np.asarray(got) == expect).all()
