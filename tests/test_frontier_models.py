"""Frontier-sparse traversal kernels: hybrid BFS, SSSP, WCC.

(reference parity: titan-test olap/OLAPTest + ShortestDistanceVertexProgram
semantics, validated here against plain-python BFS/Bellman-Ford/union-find
on random symmetrized graphs.)
"""

import numpy as np
import pytest

from titan_tpu.models import bfs_hybrid as H
from titan_tpu.models import frontier as F
from titan_tpu.models.bfs import INF, frontier_bfs
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.olap.tpu.rmat import rmat_edges


def sym_snap(rng, n, m):
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


def adjacency_with_slots(snap):
    """[(v, w, slot)] edges exactly as the chunked kernels see them."""
    g = H.build_chunked_csr(snap)
    colstart = np.asarray(g["colstart"])
    dstT = np.asarray(g["dstT"])
    deg = np.asarray(g["deg"])[:-1]
    edges = []
    for v in range(snap.n):
        for k in range(int(deg[v])):
            col = int(colstart[v]) + k // 8
            lane = k % 8
            edges.append((v, int(dstT[lane, col]), col * 8 + lane))
    return edges


@pytest.mark.parametrize("seed", [1, 2, 3])
@pytest.mark.slow
def test_hybrid_bfs_matches_reference(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(50, 400))
    snap = sym_snap(rng, n, int(rng.integers(n, 5 * n)))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, _ = frontier_bfs(snap, source)
    d_hyb, _ = H.frontier_bfs_hybrid(snap, source)
    assert (d_ref == np.asarray(d_hyb)).all()


@pytest.mark.slow
def test_hybrid_bfs_rmat_both_modes():
    src, dst = rmat_edges(11, 8, seed=4)
    n = 1 << 11
    snap = snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, _ = frontier_bfs(snap, source)
    d_hyb, lv = H.frontier_bfs_hybrid(snap, source)
    assert (d_ref == np.asarray(d_hyb)).all() and lv > 2


@pytest.mark.parametrize("seed", [5, 6])
def test_frontier_sssp_matches_bellman_ford(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(30, 150))
    snap = sym_snap(rng, n, int(rng.integers(n, 4 * n)))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    edges = adjacency_with_slots(snap)
    w = F.slot_weights_np(np.asarray([s for _, _, s in edges]))
    # host Bellman-Ford over the same directed weighted edges
    dist = np.full(n, np.inf)
    dist[source] = 0.0
    for _ in range(n):
        changed = False
        for (v, u, _), wi in zip(edges, w):
            if dist[v] + wi < dist[u]:
                dist[u] = dist[v] + wi
                changed = True
        if not changed:
            break
    got, rounds = F.frontier_sssp(snap, source)
    finite = dist < np.inf
    assert (np.asarray(got)[finite] == pytest.approx(dist[finite],
                                                     rel=1e-5))
    assert (np.asarray(got)[~finite] >= float(F.FINF) - 1).all()


@pytest.mark.parametrize("seed", [7, 8])
def test_frontier_wcc_matches_union_find(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(30, 300))
    snap = sym_snap(rng, n, int(rng.integers(max(2, n // 3), 2 * n)))
    parent = list(range(n))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for v, u, _ in adjacency_with_slots(snap):
        parent[find(v)] = find(u)
    comp_min = {}
    for v in range(n):
        r = find(v)
        comp_min[r] = min(comp_min.get(r, v), v)
    expect = np.asarray([comp_min[find(v)] for v in range(n)])
    got, rounds = F.frontier_wcc(snap)
    assert (np.asarray(got) == expect).all()


@pytest.mark.parametrize("lanes", [2, 4])
@pytest.mark.parametrize("seed", [4, 9])
def test_hybrid_split_lane_opener_matches(lanes, seed, monkeypatch):
    """Force the split-lane bottom-up opener (bu0a/bu0b, normally gated
    behind SPLIT_LANE_MIN=2^21 candidates) at toy scale, for both lane
    widths, against the plain-python reference."""
    monkeypatch.setattr(H, "SPLIT_LANE_MIN", 1)
    monkeypatch.setattr(H, "SPLIT_LANES", lanes)
    rng = np.random.default_rng(seed)
    n = int(rng.integers(100, 400))
    snap = sym_snap(rng, n, int(rng.integers(2 * n, 6 * n)))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, _ = frontier_bfs(snap, source)
    d_hyb, _ = H.frontier_bfs_hybrid(snap, source)
    assert (d_ref == np.asarray(d_hyb)).all()


@pytest.mark.parametrize("kind", ["sssp", "wcc"])
def test_budget_sliced_rounds_match_single_slice(kind, monkeypatch):
    """Force tiny slice budgets (the scale-26 memory-bound regime: many
    slices per round, incl. forced single-hub slices) and check the
    fixpoint matches the single-slice run."""
    rng = np.random.default_rng(11)
    n = 200
    snap = sym_snap(rng, n, 700)
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    if kind == "wcc":
        ref, _ = F.frontier_wcc(snap)
    else:
        ref, _ = F.frontier_sssp(snap, source)
    monkeypatch.setattr(F, "SLICE_BUDGET_CHUNKS", 2)
    if kind == "wcc":
        got, _ = F.frontier_wcc(snap)
        assert (np.asarray(got) == np.asarray(ref)).all()
    else:
        got, _ = F.frontier_sssp(snap, source)
        assert np.asarray(got) == pytest.approx(np.asarray(ref),
                                                rel=1e-6)


def test_pagerank_dense_matches_numpy_reference():
    rng = np.random.default_rng(13)
    n = 120
    snap = sym_snap(rng, n, 500)
    edges = adjacency_with_slots(snap)
    deg = np.zeros(n)
    for v, _, _ in edges:
        deg[v] += 1
    rank = np.full(n, 1.0 / n)
    for _ in range(15):
        acc = np.zeros(n)
        for v, u, _ in edges:
            acc[u] += rank[v] / deg[v]
        rank = 0.15 / n + 0.85 * acc
    got, iters = F.pagerank_dense(snap, iterations=15)
    assert iters == 15
    assert np.asarray(got) == pytest.approx(rank, rel=2e-4)


def test_pagerank_dense_tolerance_early_exit():
    rng = np.random.default_rng(14)
    snap = sym_snap(rng, 80, 300)
    _, iters = F.pagerank_dense(snap, iterations=500, tol=1e-7)
    assert iters < 500


def test_pagerank_windowed_no_double_count(monkeypatch):
    """Non-divisor window sizes clamp the last window's slice start;
    scatter-ADD must not re-count the overlap (review finding)."""
    rng = np.random.default_rng(15)
    snap = sym_snap(rng, 150, 600)
    ref, _ = F.pagerank_dense(snap, iterations=8)
    for W in (3, 7, 13):
        monkeypatch.setattr(F, "DENSE_WINDOW", W)
        got, _ = F.pagerank_dense(snap, iterations=8)
        assert np.asarray(got) == pytest.approx(np.asarray(ref), rel=1e-5)


def test_graph500_numpy_fallback(tmp_path, monkeypatch):
    """Without the native module the pipeline builds via numpy and
    matches the native-built cache."""
    from titan_tpu.olap.tpu import graph500 as g5
    from titan_tpu import native
    ha = g5.load_or_build(9, 4, seed=6, cache_dir=str(tmp_path / "a"),
                          verbose=False)
    monkeypatch.setattr(native, "available", False)
    hb = g5.load_or_build(9, 4, seed=6, cache_dir=str(tmp_path / "b"),
                          verbose=False)
    # same generator only when native was used for both; the numpy
    # fallback generates with a different RNG stream, so compare
    # structure, not content
    assert hb["n"] == ha["n"]
    assert hb["q_total"] > 0 and hb["e_dedup"] <= hb["e_sym"]
    deg = np.asarray(hb["deg"])
    colstart = np.asarray(hb["colstart"])
    assert int(colstart[-1]) == int((-(-deg.astype(np.int64) // 8)).sum())


def test_pipelined_upload_matches_direct():
    from titan_tpu.olap.tpu.graph500 import pipelined_upload
    rng = np.random.default_rng(17)
    for cols in (10, 64, 100, 129):
        a = rng.integers(0, 1000, (8, cols)).astype(np.int32)
        got = np.asarray(pipelined_upload(a, chunk_cols=32))
        assert (got == a).all(), cols


@pytest.mark.parametrize("kind", ["sssp", "wcc"])
def test_sliced_rounds_cap_boundary_regime(kind, monkeypatch):
    """Power-of-2 n (cap_n == n, the scale-26 shape) with uneven degrees
    and a tiny separate component at the TAIL of the vertex space: the
    last slice lands in the dynamic_slice clamp zone, where an unshifted
    validity mask silently skipped tail vertices (review repro)."""
    n = 256
    rng = np.random.default_rng(21)
    # dense block over [0, 200), plus an isolated 2-vertex component at
    # the very end whose minimum must still propagate
    src = rng.integers(0, 200, 800).astype(np.int32)
    dst = rng.integers(0, 200, 800).astype(np.int32)
    src = np.concatenate([src, [254]])
    dst = np.concatenate([dst, [255]])
    snap = sym_snap_from_arrays(src, dst, n)
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    if kind == "wcc":
        ref, _ = F.frontier_wcc(snap)
    else:
        ref, _ = F.frontier_sssp(snap, source)
    monkeypatch.setattr(F, "SLICE_BUDGET_CHUNKS", 32)
    if kind == "wcc":
        got, _ = F.frontier_wcc(snap)
        assert np.asarray(got)[255] == 254
        assert (np.asarray(got) == np.asarray(ref)).all()
    else:
        got, _ = F.frontier_sssp(snap, source)
        assert np.asarray(got) == pytest.approx(np.asarray(ref),
                                                rel=1e-6)


def sym_snap_from_arrays(src, dst, n):
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


def test_hybrid_max_levels_truncates():
    """Review regression: the fused endgame must honor max_levels."""
    import numpy as np

    from titan_tpu.models.bfs import INF
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid
    from titan_tpu.olap.tpu import snapshot as snap_mod

    k = 8
    src = np.arange(k - 1, dtype=np.int64)
    dst = src + 1
    snap = snap_mod.from_arrays(
        k, np.concatenate([src, dst]).astype(np.int32),
        np.concatenate([dst, src]).astype(np.int32))
    dist, levels = frontier_bfs_hybrid(snap, 0, max_levels=2)
    assert levels <= 2
    assert dist[1] == 1 and dist[2] == 2
    assert (dist[3:] >= INF).all()


@pytest.mark.parametrize("seed", [7, 8])
def test_hybrid_bfs_split_lane_opener_matches(seed, monkeypatch):
    """Force the split-lane bottom-up opener (4-lane test + lanes-4-7
    refetch) on small graphs and check bit-equality with the plain BFS
    (in production it only engages above 2^21 candidates)."""
    monkeypatch.setattr(H, "SPLIT_LANE_MIN", 2)
    # also disable the fused endgame + head fast paths so the bu0a/bu0b
    # opener actually runs on these tiny graphs
    monkeypatch.setattr(H, "END_C_CAP", 0)
    monkeypatch.setattr(H, "END_P_CAP", 0)
    monkeypatch.setattr(H, "HEAD_F_CAP", 1)
    rng = np.random.default_rng(seed)
    n = int(rng.integers(100, 500))
    snap = sym_snap(rng, n, int(rng.integers(2 * n, 8 * n)))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, _ = frontier_bfs(snap, source)
    d_hyb, _ = H.frontier_bfs_hybrid(snap, source)
    assert (d_ref == np.asarray(d_hyb)).all()


def test_hybrid_bfs_split_lane_rmat(monkeypatch):
    monkeypatch.setattr(H, "SPLIT_LANE_MIN", 2)
    monkeypatch.setattr(H, "END_C_CAP", 0)
    monkeypatch.setattr(H, "END_P_CAP", 0)
    monkeypatch.setattr(H, "HEAD_F_CAP", 1)
    src, dst = rmat_edges(11, 8, seed=9)
    n = 1 << 11
    snap = snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, _ = frontier_bfs(snap, source)
    d_hyb, _ = H.frontier_bfs_hybrid(snap, source)
    assert (d_ref == np.asarray(d_hyb)).all()


# ---------------------------------------------------------------- fused BFS

import titan_tpu.models.bfs_hybrid_fused as FU


@pytest.mark.parametrize("seed", [4, 5])
def test_fused_bfs_matches_reference(seed, monkeypatch):
    """Single-dispatch BFS (device-side mode + bucket switching) is
    bit-equal to the plain BFS; endgame disabled so the td/bu ladder
    branches actually execute on CPU-sized graphs."""
    monkeypatch.setattr(FU, "END_C_CAP", 1)
    monkeypatch.setattr(FU, "END_P_CAP", 1)
    rng = np.random.default_rng(seed)
    n = int(rng.integers(80, 400))
    snap = sym_snap(rng, n, int(rng.integers(2 * n, 8 * n)))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, _ = frontier_bfs(snap, source)
    d_f, _ = FU.frontier_bfs_hybrid_fused(snap, source)
    assert (d_ref == np.asarray(d_f)).all()


def test_fused_bfs_rmat_and_endgame():
    src, dst = rmat_edges(11, 8, seed=4)
    n = 1 << 11
    snap = snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, _ = frontier_bfs(snap, source)
    d_f, _ = FU.frontier_bfs_hybrid_fused(snap, source)
    assert (d_ref == np.asarray(d_f)).all()


def test_fused_bfs_path_graph(monkeypatch):
    monkeypatch.setattr(FU, "END_C_CAP", 1)
    monkeypatch.setattr(FU, "END_P_CAP", 1)
    n = 300
    src = np.arange(n - 1, dtype=np.int32)
    snap = snap_mod.from_arrays(n, np.concatenate([src, src + 1]),
                                np.concatenate([src + 1, src]))
    d_ref, _ = frontier_bfs(snap, 0)
    d_f, lv = FU.frontier_bfs_hybrid_fused(snap, 0)
    assert (d_ref == np.asarray(d_f)).all() and lv >= n - 1


def test_sssp_quantile_matches_plain():
    """Quantile-batched SSSP (priority bands) is exact: same distances
    as the plain expand-all-improved frontier and the Bellman-Ford
    ground truth."""
    from titan_tpu.models.frontier import frontier_sssp
    rng = np.random.default_rng(17)
    n = 220
    m = 1400
    s = rng.integers(0, n, m)
    d = rng.integers(0, n, m)
    snap = snap_mod.from_arrays(n, np.concatenate([s, d]),
                                np.concatenate([d, s]))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_q, r_q = frontier_sssp(snap, source, quantile_mass=64)
    d_p, r_p = frontier_sssp(snap, source, quantile_mass=0)
    assert np.allclose(d_q, d_p, rtol=1e-6)


def test_fused_bfs_overflow_falls_back(monkeypatch):
    """A bu level whose candidate set exceeds the trimmed bucket ladder
    must set the overflow stat and transparently re-run host-driven —
    never truncate candidates (wrong distances)."""
    monkeypatch.setattr(FU, "END_C_CAP", 1)
    monkeypatch.setattr(FU, "END_P_CAP", 1)
    # shrink the whole bu ladder (FUSED_BU_MAX alone is floored by the
    # 2^23 bucket, which covers any CPU-test graph) and rebuild the
    # cached jit so the tiny ladder actually traces
    orig_ladders = FU._ladders

    def tiny_ladders(n, total_chunks):
        td, bu, cap_n, cap_q = orig_ladders(n, total_chunks)
        return td, [8], cap_n, cap_q

    monkeypatch.setattr(FU, "_ladders", tiny_ladders)
    from titan_tpu.utils import jitcache
    monkeypatch.delitem(jitcache._JITS, "hybrid_fused", raising=False)
    # record that the host-driven fallback actually ran
    called = []
    real = H.frontier_bfs_hybrid

    def spy(*a, **kw):
        called.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(H, "frontier_bfs_hybrid", spy)
    src, dst = rmat_edges(11, 8, seed=6)
    n = 1 << 11
    snap = snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, _ = frontier_bfs(snap, source)
    d_f, _ = FU.frontier_bfs_hybrid_fused(snap, source)
    assert called, "overflow did not route through the host fallback"
    assert (d_ref == np.asarray(d_f)).all()


def test_sssp_quantile_list_truncation_is_sound(monkeypatch):
    """A fixed in-band list cap smaller than the band must only defer
    vertices (they stay improved and get re-planned), never drop or
    corrupt distances — the soundness contract of _band_plan's
    truncating compaction (ops.compaction.banded_frontier)."""
    monkeypatch.setattr(F, "QUANT_LIST_CAP", 8)
    rng = np.random.default_rng(21)
    n = 150
    snap = sym_snap(rng, n, 600)
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    # plain mode ignores QUANT_LIST_CAP (it lists at full w_max width so
    # dense rounds keep the r5 one-round coverage) but still truncates
    # at w_max=128 < n=150 here — both truncation regimes must only
    # defer, never corrupt
    ref, _ = F.frontier_sssp(snap, source, quantile_mass=0)
    got, rounds = F.frontier_sssp(snap, source, quantile_mass=64)
    assert np.asarray(got) == pytest.approx(np.asarray(ref), rel=1e-6)
