"""Observability plane units: span tracer + Prometheus exposition.

The tracer contract (ISSUE r10): explicit spans with parent links and
an injectable clock (deterministic assertions, no sleeps), a bounded
ring-buffer journal per trace, bounded trace count, thread-safe writes,
and a disabled mode that records nothing. The exporter contract: every
registered metric renders as grammar-valid Prometheus text.
"""

import re
import threading

from titan_tpu.obs.promexport import (CONTENT_TYPE, render_prometheus,
                                      sanitize)
from titan_tpu.obs.tracing import (NULL_SPAN, TraceHandle, Tracer,
                                   trace_summary)
from titan_tpu.utils.metrics import MetricManager


class FakeClock:
    def __init__(self, t0: float = 100.0):
        self.t = t0

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float = 1.0) -> float:
        self.t += dt
        return self.t


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


def test_span_tree_structure_and_durations():
    clk = FakeClock()
    tr = Tracer(clock=clk)
    root = tr.start("t1", "job", kind="bfs")
    clk.tick()
    child = tr.start("t1", "queue", parent=root)
    clk.tick(2.0)
    tr.end(child)
    tr.event("t1", "submit", parent=root)       # instant event
    clk.tick()
    tr.end(root, status="done")

    spans = tr.spans("t1")
    assert [s.name for s in spans] == ["job", "queue", "submit"]
    assert spans[1].parent_id == root.span_id
    assert spans[1].duration_ms == 2000.0
    assert spans[2].t_start == spans[2].t_end      # instant
    assert root.duration_ms == 4000.0
    assert root.attrs == {"kind": "bfs", "status": "done"}

    tree = tr.tree("t1")
    assert tree["dropped_spans"] == 0
    assert len(tree["spans"]) == 1                 # one root
    node = tree["spans"][0]
    assert node["name"] == "job"
    assert [c["name"] for c in node["children"]] == ["queue", "submit"]
    assert tr.tree("nope") is None


def test_event_with_explicit_host_timestamps():
    """The retroactive form the round seams use: wall time measured by
    the kernel's own boundary callbacks, stamped after the fact."""
    clk = FakeClock()
    tr = Tracer(clock=clk)
    s = tr.event("t", "round", t0=50.0, t1=53.5, level=3, frontier=17)
    assert s.t_start == 50.0 and s.t_end == 53.5
    assert s.duration_ms == 3500.0
    assert s.attrs == {"level": 3, "frontier": 17}
    # t0 only → window closes at the (injected) clock's now
    s2 = tr.event("t", "apply", t0=90.0)
    assert s2.t_start == 90.0 and s2.t_end == clk.t


def test_ring_buffer_drops_oldest_but_keeps_root():
    clk = FakeClock()
    tr = Tracer(clock=clk, max_spans=8)
    root = tr.start("t", "job")
    for i in range(20):
        tr.event("t", "round", parent=root, round=i)
    spans = tr.spans("t")
    assert len(spans) == 8
    assert spans[0] is root, "the root anchor must survive the ring"
    assert tr.dropped("t") == 13
    assert tr.tree("t")["dropped_spans"] == 13
    # orphaned children (parent dropped) still render as roots
    kept_rounds = [s.attrs["round"] for s in spans[1:]]
    assert kept_rounds == list(range(13, 20))


def test_trace_count_bounded_oldest_evicted():
    tr = Tracer(clock=FakeClock(), max_traces=3)
    for i in range(5):
        tr.start(f"t{i}", "job")
    assert tr.spans("t0") is None and tr.spans("t1") is None
    assert all(tr.spans(f"t{i}") is not None for i in (2, 3, 4))
    tr.discard("t3")
    assert tr.spans("t3") is None


def test_disabled_tracer_records_nothing():
    tr = Tracer(enabled=False)
    s = tr.start("t", "job")
    assert s is NULL_SPAN
    assert s.set(x=1) is s
    tr.end(s)
    assert tr.event("t", "round") is NULL_SPAN
    with tr.span("t", "x") as sp:
        assert sp is NULL_SPAN
    assert tr.spans("t") is None and tr.tree("t") is None
    assert trace_summary(tr, "t") is None


def test_trace_handle_parent_switching_and_summary():
    clk = FakeClock()
    tr = Tracer(clock=clk)
    root = tr.start("j", "job")
    h = TraceHandle(tr, "j", root)
    h.queue = h.start("queue")
    clk.tick(0.004)
    h.end(h.queue)
    h.attempt = h.start("attempt", attempt=1)
    assert h.parent is h.attempt
    fuse = h.start("fuse")
    clk.tick(0.001)
    h.end(fuse)
    run = h.start("run")
    clk.tick(0.25)
    for i in range(3):
        h.event("round", parent=run, round=i)
    h.end(run)
    h.end(h.attempt)
    tr.end(root)
    assert fuse.parent_id == h.attempt.span_id
    s = trace_summary(tr, "j")
    assert s["queue_ms"] == 4.0
    assert s["fuse_ms"] == 1.0
    assert s["device_ms"] == 250.0
    assert s["rounds"] == 3


def test_tracer_thread_safe_under_concurrent_writes():
    tr = Tracer()
    errs: list = []

    def writer(k):
        try:
            for i in range(200):
                tr.event(f"trace-{k % 4}", "round", round=i)
        except Exception as e:          # pragma: no cover - fail loud
            errs.append(repr(e))

    threads = [threading.Thread(target=writer, args=(k,))
               for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs
    total = sum(len(tr.spans(f"trace-{i}")) + tr.dropped(f"trace-{i}")
                for i in range(4))
    assert total == 8 * 200


# ---------------------------------------------------------------------------
# prometheus exposition
# ---------------------------------------------------------------------------

# sample line grammar: name{labels} value  (exposition format 0.0.4)
_LABEL_PAIR = r"[a-zA-Z0-9_]+=\"([^\"\\]|\\.)*\""
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{" + _LABEL_PAIR + r"(," + _LABEL_PAIR + r")*\})? "
    r"[+-]?(\d+\.?\d*([eE][+-]?\d+)?|inf|nan)$")


def _assert_valid_exposition(text: str) -> list:
    lines = [ln for ln in text.splitlines() if ln]
    samples = []
    for ln in lines:
        if ln.startswith("#"):
            assert re.match(r"^# (TYPE|HELP) [a-zA-Z_:][a-zA-Z0-9_:]* ",
                            ln), ln
        else:
            assert _SAMPLE.match(ln), f"bad sample line: {ln!r}"
            samples.append(ln)
    return samples


def test_render_prometheus_all_three_kinds_valid():
    m = MetricManager()
    m.counter("serving.jobs.submitted").inc(42)
    m.timer("edgestore.getSlice.time").update(2_000_000)
    h = m.histogram("serving.job.latency_ms")
    for v in (1.0, 2.0, 3.0, 4.0):
        h.update(v)
    text = render_prometheus(m)
    samples = _assert_valid_exposition(text)
    assert "serving_jobs_submitted 42" in samples
    assert "edgestore_getSlice_time_seconds_count 1" in samples
    assert "edgestore_getSlice_time_seconds_sum 0.002" in samples
    # nearest-rank over 4 samples: round(0.5 * 3) = 2 → s[2] = 3
    assert 'serving_job_latency_ms{quantile="0.5"} 3' in samples
    assert 'serving_job_latency_ms{quantile="0.95"} 4' in samples
    assert "serving_job_latency_ms_count 4" in samples
    assert "serving_job_latency_ms_sum 10" in samples
    assert text.endswith("\n")
    assert CONTENT_TYPE.startswith("text/plain; version=0.0.4")


def test_render_prometheus_empty_registry():
    assert render_prometheus(MetricManager()) == "\n"


def test_queue_depth_renders_as_gauge_not_counter():
    """serving.queue.depth is inc/dec bookkeeping — exporting it as a
    Prometheus counter would make rate()/increase() read every dequeue
    as a counter reset. The flag lives on the metric itself
    (``counter(name, gauge=True)``, set by the scheduler at startup —
    ISSUE 8 replaced promexport's name allowlist), and it is sticky:
    later unflagged get-or-create calls keep the gauge typing."""
    m = MetricManager()
    m.counter("serving.queue.depth", gauge=True).inc(3)
    m.counter("serving.queue.depth").inc(-1)     # sticky after this
    m.counter("serving.jobs.submitted").inc(3)
    text = render_prometheus(m)
    assert "# TYPE serving_queue_depth gauge" in text
    assert "serving_queue_depth 2" in text
    assert "# TYPE serving_jobs_submitted counter" in text


def test_sanitize_names():
    assert sanitize("serving.job.latency_ms") == "serving_job_latency_ms"
    assert sanitize("a b-c/d") == "a_b_c_d"
    assert sanitize("0zero") == "_0zero"
    assert re.match(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$", sanitize("9!@#"))


def test_help_lines_from_description_registry():
    """ISSUE 8 satellite: ``# HELP`` text comes from promexport's
    per-name HELP registry and precedes the matching ``# TYPE``;
    undescribed names get TYPE but no HELP; the whole body still
    parses under the exposition grammar."""
    from titan_tpu.obs.promexport import HELP
    m = MetricManager()
    m.counter("serving.jobs.submitted").inc(1)
    m.counter("made.up.name").inc(1)
    m.histogram("serving.job.latency_ms").update(2.0)
    text = render_prometheus(m)
    _assert_valid_exposition(text)
    lines = text.splitlines()
    i_help = lines.index("# HELP serving_jobs_submitted "
                         + HELP["serving.jobs.submitted"])
    assert lines[i_help + 1] == "# TYPE serving_jobs_submitted counter"
    assert "# HELP serving_job_latency_ms " + \
        HELP["serving.job.latency_ms"] in lines
    assert "# TYPE made_up_name counter" in lines
    assert not any(ln.startswith("# HELP made_up_name") for ln in lines)
    # every HELP entry names a real metric family the registry can
    # create — entries must not rot as names churn (the doc-drift
    # guard covers the docs side; this pins the exposition side)
    for name, text_ in HELP.items():
        assert text_ and "\n" not in text_, name


def test_labeled_children_render_and_sum_to_parent():
    """Labeled children render as extra samples of the SAME family; the
    unlabeled parent sample equals their sum, and the parent lines are
    byte-identical to a registry that never used labels (ISSUE 8
    regression criterion for the no-tenant path)."""
    m = MetricManager()
    m.counter("serving.jobs.completed",
              labels={"tenant": "a", "kind": "bfs"}).inc(3)
    m.counter("serving.jobs.completed",
              labels={"tenant": "b", "kind": "bfs"}).inc(2)
    h = m.histogram("serving.job.latency_ms", labels={"tenant": "a"})
    for v in (1.0, 2.0, 3.0, 4.0):
        h.update(v)
    text = render_prometheus(m)
    samples = _assert_valid_exposition(text)
    assert "serving_jobs_completed 5" in samples
    assert ('serving_jobs_completed{kind="bfs",tenant="a"} 3'
            in samples)
    assert ('serving_jobs_completed{kind="bfs",tenant="b"} 2'
            in samples)
    assert 'serving_job_latency_ms{quantile="0.5"} 3' in samples
    # the summary's quantile pair lands LAST, after the child's own
    # sorted labels (promexport._labels extra convention)
    assert ('serving_job_latency_ms{tenant="a",quantile="0.95"} 4'
            in samples)
    assert 'serving_job_latency_ms_count{tenant="a"} 4' in samples
    # parent sample lines byte-identical to a never-labeled registry
    plain = MetricManager()
    plain.counter("serving.jobs.completed").inc(5)
    ph = plain.histogram("serving.job.latency_ms")
    for v in (1.0, 2.0, 3.0, 4.0):
        ph.update(v)
    plain_samples = _assert_valid_exposition(render_prometheus(plain))
    assert set(plain_samples) <= set(samples)


def test_gauges_render_with_children_and_escaping():
    m = MetricManager()
    m.gauge("serving.hbm.resident_bytes", fn=lambda: 1024)
    m.gauge("serving.slo.burn_rate", fn=lambda: 2.5,
            labels={"slo": 'we"ird\\na', "window": "300s"})
    text = render_prometheus(m)
    _assert_valid_exposition(text)
    assert "# TYPE serving_hbm_resident_bytes gauge" in text
    assert "serving_hbm_resident_bytes 1024" in text
    assert "# TYPE serving_slo_burn_rate gauge" in text
    # a children-only family (parent has no callback of its own) emits
    # NO unlabeled sample: the sum roll-up is meaningless for ratio
    # gauges like burn rates, so only the labeled children render
    assert "\nserving_slo_burn_rate 2.5" not in text
    assert ('serving_slo_burn_rate{slo="we\\"ird\\\\na",'
            'window="300s"} 2.5' in text)
    # programmatic roll-up read still available (additive families)
    assert m.gauge_value("serving.slo.burn_rate") == 2.5


# ---------------------------------------------------------------------------
# histogram quantile memo: scrape-vs-record (ISSUE 10 satellite)
# ---------------------------------------------------------------------------


def test_histogram_quantile_memo_invalidates_on_update():
    """The sorted reservoir is cached on the sample-count watermark:
    two reads without an update share ONE sort, any update (including
    reservoir replacement past max_samples) invalidates it."""
    from titan_tpu.utils.metrics import Histogram

    h = Histogram(max_samples=64)
    for v in range(10):
        h.update(float(v))
    first = h._sorted_samples()
    assert h._sorted_samples() is first          # memo hit: same list
    assert h.percentile(50) == 4.0 or h.percentile(50) == 5.0
    h.update(100.0)
    second = h._sorted_samples()
    assert second is not first                   # watermark moved
    assert h.to_dict()["max"] == 100.0
    # past max_samples every update still bumps count -> still fresh
    for v in range(200):
        h.update(float(v))
    assert len(h._sorted_samples()) == 64
    assert h._sorted_samples() == sorted(h.values())


def test_histogram_concurrent_scrape_vs_record_stress():
    """Prometheus scrapes (p50+p95 via to_dict / render) racing a
    recording thread must never throw, and every scrape must see a
    coherent sorted view (p50 <= p95, count monotone)."""
    m = MetricManager()
    h = m.histogram("serving.job.latency_ms")
    stop = threading.Event()
    errors = []

    def recorder():
        v = 0
        while not stop.is_set():
            h.update(float(v % 997))
            v += 1

    def scraper():
        last_count = 0
        while not stop.is_set():
            try:
                d = h.to_dict()
                assert d["p50"] <= d["p95"] <= d["max"] + 1e-9
                assert d["count"] >= last_count
                last_count = d["count"]
                text = render_prometheus(m)
                assert "serving_job_latency_ms" in text
            except Exception as e:               # pragma: no cover
                errors.append(e)
                return

    threads = [threading.Thread(target=recorder) for _ in range(2)] + \
              [threading.Thread(target=scraper) for _ in range(2)]
    for t in threads:
        t.start()
    import time as _time
    _time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(5)
    assert not errors, errors
    assert h.to_dict()["count"] > 0
