"""Device-side epoch compaction (ISSUE 9, ops/epoch_merge +
olap/live/compactor device path).

The contract under test: the device-merged next-epoch chunked CSR is
BIT-EQUAL to the host oracle (``EpochCompactor.merge`` + ``from_arrays``
+ ``build_chunked_csr`` — one global stable sort) across adds-only /
tombstones-only / mixed / labeled shapes; the host-durable snapshot
synced from delta pages (``snapshot.merge_delta``) is bit-equal to the
oracle's arrays; epochs double-buffer through the HBM ledger; and every
way the device path cannot run degrades LOUDLY to the host oracle
(fallback reason recorded, ``serving.live.device_merge_fallbacks``
bumped).

No kernel dispatches here beyond the eager merge ops — the suite pins
arrays, not BFS results (array equality is strictly stronger), so it
adds no XLA compile buckets to tier-1.
"""

import numpy as np
import pytest

import titan_tpu
from titan_tpu.models.bfs_hybrid import build_chunked_csr
from titan_tpu.olap.live.compactor import EpochCompactor
from titan_tpu.olap.live.overlay import DeltaOverlay
from titan_tpu.olap.serving.hbm import HBMLedger, snapshot_csr_bytes
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.ops import epoch_merge
from titan_tpu.utils.metrics import MetricManager

#: the repo-shared test shape (see tests/test_serving.py)
N, M, SEED = 192, 900, 42


def _base(seed=SEED, labeled=False, n=N, m=M):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    labs = rng.integers(0, 3, m).astype(np.int32) if labeled else None
    return snap_mod.from_arrays(n, src, dst, labels=labs), src, dst, \
        labs, rng


def _mutate(snap, src, dst, labs, rng, adds, removes, kill_add=False):
    ov = DeltaOverlay(snap, min_cap=64)
    a = None
    if adds:
        a = (rng.integers(0, snap.n, adds).astype(np.int32),
             rng.integers(0, snap.n, adds).astype(np.int32),
             rng.integers(0, 3, adds).astype(np.int32))
        ov.append_edges(*a)
    for i in rng.choice(len(src), removes, replace=False):
        ov.remove_edge(int(src[i]), int(dst[i]),
                       int(labs[i]) if labs is not None else None)
    if kill_add and adds > 4:
        # dead-add path: an appended row later tombstoned in place
        assert ov.remove_edge(int(a[0][2]), int(a[1][2]),
                              int(a[2][2]))
    return ov


def _assert_csr_equal(got, want):
    assert got["q_total"] == want["q_total"]
    for k in ("dstT", "colstart", "degc", "deg"):
        a, b = np.asarray(got[k]), np.asarray(want[k])
        assert a.shape == b.shape, k
        assert (a == b).all(), k


@pytest.mark.parametrize("adds,removes,labeled,kill", [
    (120, 40, False, True),    # mixed + dead add
    (120, 40, True, True),     # labeled mixed
    (120, 0, False, False),    # adds only
    (0, 60, True, False),      # tombstones only
    (300, 10, False, False),   # adds dominate (cap growth)
])
@pytest.mark.parametrize("seed", [1, SEED])
def test_device_merge_bit_equal_to_host_oracle(seed, adds, removes,
                                               labeled, kill):
    snap, src, dst, labs, rng = _base(seed, labeled)
    ov = _mutate(snap, src, dst, labs, rng, adds, removes, kill)
    build_chunked_csr(snap)            # base CSR device-resident
    comp = EpochCompactor()
    merged, mode = comp.compact(snap, ov)
    assert mode == "device" and comp.last_mode == "device"
    assert comp.device_merges == 1 and not comp.fallbacks
    oracle = comp.merge(snap, ov)
    # 1) the published device CSR vs a fresh build of the oracle
    _assert_csr_equal(merged._hybrid_csr, build_chunked_csr(oracle))
    # 2) the delta-page host sync vs the oracle's full-sort arrays
    for attr in ("src", "dst", "indptr_in", "out_degree"):
        assert (getattr(merged, attr) == getattr(oracle, attr)).all(), \
            attr
    if labeled:
        assert (merged.labels == oracle.labels).all()
    else:
        assert merged.labels is None
    # 3) the lazy _host mirror (shard-slicing surface) vs the oracle's
    hm = merged._hybrid_csr["_host"]
    for k in ("dstT", "colstart", "degc"):
        assert (np.asarray(hm[k])
                == build_chunked_csr(oracle)["_host"][k]).all(), k


def test_merged_degrees_host_matches_device_layout():
    snap, src, dst, labs, rng = _base()
    ov = _mutate(snap, src, dst, labs, rng, 80, 30)
    deg, degc, colstart, q_new = epoch_merge.merged_degrees_host(
        snap, ov)
    oracle = build_chunked_csr(EpochCompactor().merge(snap, ov))
    assert q_new == oracle["q_total"]
    assert (deg == np.asarray(oracle["deg"])).all()
    assert (degc == np.asarray(oracle["degc"])).all()
    assert (colstart == np.asarray(oracle["colstart"])).all()


def test_carry_over_vertex_values_and_epoch():
    snap, src, dst, labs, rng = _base()
    snap.vertex_values["rank"] = ("vals", "present")
    snap.epoch = 7
    ov = _mutate(snap, src, dst, labs, rng, 20, 0)
    build_chunked_csr(snap)
    merged, mode = EpochCompactor().compact(snap, ov)
    assert mode == "device"
    assert merged.vertex_values == {"rank": ("vals", "present")}
    assert merged.epoch == 7


# -- loud degrades -----------------------------------------------------------

def test_ledger_too_small_degrades_loudly_to_host():
    snap, src, dst, labs, rng = _base()
    ov = _mutate(snap, src, dst, labs, rng, 50, 10)
    build_chunked_csr(snap)
    mm = MetricManager()
    # budget below ONE epoch image: the double-buffer reservation for
    # the next epoch must fail and the merge must still succeed (host)
    ledger = HBMLedger(budget_bytes=16)
    comp = EpochCompactor()
    merged, mode = comp.compact(snap, ov, ledger=ledger, metrics=mm)
    assert mode == "host" and comp.last_mode == "host"
    assert comp.fallbacks == {"ledger-full": 1}
    assert mm.counter_value("serving.live.device_merge_fallbacks") == 1
    # host path charges the full re-upload the next run must pay
    assert mm.counter_value("serving.live.upload_bytes") \
        == snapshot_csr_bytes(merged)
    oracle = comp.merge(snap, ov)
    assert (merged.dst == oracle.dst).all()
    assert not hasattr(merged, "_hybrid_csr")


def test_double_buffer_reserves_next_epoch_beside_current():
    snap, src, dst, labs, rng = _base()
    ov = _mutate(snap, src, dst, labs, rng, 50, 10)
    build_chunked_csr(snap)
    ledger = HBMLedger(budget_bytes=10e6)
    # the current epoch is ledger-resident the way a served image is
    ledger.reserve(id(snap), snapshot_csr_bytes(snap))
    ledger.unpin(id(snap))
    before = ledger.resident_bytes()
    merged, mode = EpochCompactor().compact(snap, ov, ledger=ledger)
    assert mode == "device"
    # both epochs resident (double-buffered) until the old one retires
    assert ledger.resident_bytes() > before
    ledger.release(id(snap))           # pool retire path
    assert ledger.resident_bytes() == snapshot_csr_bytes(merged)
    # the new entry is resident-but-evictable: a job's reserve pins it
    ledger.reserve(id(merged), snapshot_csr_bytes(merged))
    assert ledger.pinned_bytes() == snapshot_csr_bytes(merged)


def test_base_not_resident_falls_back():
    snap, src, dst, labs, rng = _base()
    ov = _mutate(snap, src, dst, labs, rng, 30, 0)
    assert getattr(snap, "_hybrid_csr", None) is None
    comp = EpochCompactor()
    merged, mode = comp.compact(snap, ov)
    assert mode == "host"
    assert comp.fallbacks == {"base-not-resident": 1}


def test_empty_base_falls_back():
    empty = snap_mod.from_arrays(
        8, np.zeros(0, np.int32), np.zeros(0, np.int32))
    build_chunked_csr(empty)
    ov = DeltaOverlay(empty, min_cap=64)
    ov.append_edges(np.array([0, 1], np.int32),
                    np.array([1, 2], np.int32),
                    np.zeros(2, np.int32))
    comp = EpochCompactor()
    merged, mode = comp.compact(empty, ov)
    assert mode == "host"
    assert comp.fallbacks == {"empty-base": 1}
    assert merged.num_edges == 2


def test_device_merge_disabled_is_not_a_fallback():
    snap, src, dst, labs, rng = _base()
    ov = _mutate(snap, src, dst, labs, rng, 30, 0)
    build_chunked_csr(snap)
    mm = MetricManager()
    comp = EpochCompactor(device_merge=False)
    _, mode = comp.compact(snap, ov, metrics=mm)
    assert mode == "host" and not comp.fallbacks
    assert mm.counter_value(
        "serving.live.device_merge_fallbacks") == 0


def test_verify_device_mode_charges_download_bytes():
    snap, src, dst, labs, rng = _base()
    ov = _mutate(snap, src, dst, labs, rng, 40, 10)
    build_chunked_csr(snap)
    mm = MetricManager()
    comp = EpochCompactor(verify_device=True)
    merged, mode = comp.compact(snap, ov, metrics=mm)
    assert mode == "device"
    got = mm.counter_value("serving.live.download_bytes")
    assert got == np.asarray(merged._hybrid_csr["dstT"]).nbytes


# -- overlay delta pages -----------------------------------------------------

def test_overlay_uploads_only_delta_pages():
    snap, src, dst, labs, rng = _base()
    mm = MetricManager()
    ov = DeltaOverlay(snap, min_cap=64, metrics=mm)
    k = "serving.live.upload_bytes"
    ov.view()
    # buffer establishment is a device-side fill: ZERO bytes H2D
    assert mm.counter_value(k) == 0
    ov.append_edges(np.array([1, 2, 3], np.int32),
                    np.array([4, 5, 6], np.int32),
                    np.zeros(3, np.int32))
    ov.view()
    # 2 int32 payloads + 1 int32 scatter index per shipped row
    assert mm.counter_value(k) == 12 * 3          # the 3-row tail
    # capacity growth pad-extends on device: only the new rows ship
    ov.append_edges(rng.integers(0, N, 100).astype(np.int32),
                    rng.integers(0, N, 100).astype(np.int32),
                    np.zeros(100, np.int32))
    v = ov.view()
    assert v.cap == 128
    assert mm.counter_value(k) == 12 * 103
    # a tombstone dirties single bitmap bytes (1 payload + 4 index
    # bytes each)
    assert ov.remove_edge(int(src[0]), int(dst[0]), None)
    ov.view()
    assert mm.counter_value(k) <= 12 * 103 + 2 * 5
    # an in-place kill below the watermark re-ships just that row
    before = mm.counter_value(k)
    assert ov.remove_edge(1, 4, None)
    v2 = ov.view()
    assert mm.counter_value(k) == before + 12
    # device mirrors stay exact after the scatter-only path
    assert (np.asarray(v2.src_dev) == ov._h_src).all()
    assert (np.asarray(v2.dst_dev) == ov._h_dst).all()
    assert (np.asarray(v2.tomb_dev) == ov._h_tomb).all()
    # frozen views are immutable: the pre-growth view kept its arrays
    assert v.src_dev.shape[0] == 128


# -- plane integration -------------------------------------------------------

@pytest.fixture
def graph():
    g = titan_tpu.open("inmemory")
    tx = g.new_transaction()
    vs = [tx.add_vertex("node", name=f"v{i:02d}") for i in range(10)]
    for a, b in [(0, 1), (1, 2), (2, 3), (3, 4), (4, 5), (5, 6)]:
        vs[a].add_edge("link", vs[b])
    tx.commit()
    yield g
    g.close()


def _commit_edge(g, i, j):
    tx = g.new_transaction()
    vv = sorted(tx.vertices(), key=lambda v: v.id)
    vv[i].add_edge("link", vv[j])
    tx.commit()


def test_plane_publishes_device_merged_epoch(graph):
    from titan_tpu.olap.live import LiveGraphPlane

    mm = MetricManager()
    plane = LiveGraphPlane(graph, metrics=mm, min_cap=4, max_fill=0.5)
    try:
        snap0, v0, _ = plane.lease_state()
        build_chunked_csr(snap0)       # base image device-resident
        _commit_edge(graph, 6, 7)
        _commit_edge(graph, 7, 8)
        snap1, v1, info = plane.lease_state()
        st = plane.stats()
        assert st["epoch"] == 1 and snap1 is not snap0
        assert st["compactor"]["merge_mode"] == "device"
        assert st["compactor"]["device_merges"] == 1
        assert st["counters"]["device_merge_fallbacks"] == 0
        # the new epoch arrives with its CSR pre-attached — the next
        # run re-uploads NOTHING
        assert getattr(snap1, "_hybrid_csr", None) is not None
        # and it is bit-equal to a from-scratch rebuild of the store
        rebuilt = snap_mod.build(graph, directed=False)
        _assert_csr_equal(snap1._hybrid_csr, build_chunked_csr(rebuilt))
        for attr in ("src", "dst", "indptr_in", "out_degree"):
            assert (getattr(snap1, attr)
                    == getattr(rebuilt, attr)).all(), attr
        # byte accounting: only delta pages crossed the tunnel
        up = st["counters"]["upload_bytes"]
        assert 0 < up < snapshot_csr_bytes(rebuilt)
        assert st["compact_device_ms"]["count"] == 1
    finally:
        plane.close()


def test_plane_policy_is_configuration_not_module_constants(graph):
    from titan_tpu.olap.live import LiveGraphPlane

    plane = LiveGraphPlane(graph, metrics=MetricManager(),
                           max_fill=0.25, max_tomb_fraction=0.125,
                           device_merge=False)
    try:
        pol = plane.stats()["compactor"]
        assert pol["max_fill"] == 0.25
        assert pol["max_tomb_fraction"] == 0.125
        assert pol["device_merge"] is False
        assert plane.compactor.max_fill == 0.25
    finally:
        plane.close()


def test_plane_host_mode_when_device_disabled(graph):
    from titan_tpu.olap.live import LiveGraphPlane

    mm = MetricManager()
    plane = LiveGraphPlane(graph, metrics=mm, min_cap=4, max_fill=0.5,
                           device_merge=False)
    try:
        snap0, _, _ = plane.lease_state()
        build_chunked_csr(snap0)
        _commit_edge(graph, 6, 7)
        _commit_edge(graph, 7, 8)
        snap1, _, _ = plane.lease_state()
        st = plane.stats()
        assert st["epoch"] == 1
        assert st["compactor"]["merge_mode"] == "host"
        # the host path leaves no device CSR and charges the full
        # re-upload to the byte counter
        assert getattr(snap1, "_hybrid_csr", None) is None
        assert st["counters"]["upload_bytes"] \
            >= snapshot_csr_bytes(snap1)
    finally:
        plane.close()


# -- incremental out-CSR across merge_delta (ISSUE 11 satellite, the
# ROADMAP #5 residual: the merged epoch's src-order argsort must not be
# re-paid by the next overlay's slot-lookup index) -----------------------

def _fresh_out_csr(merged):
    """From-scratch recompute on an identical uncached snapshot."""
    fresh = snap_mod.GraphSnapshot(
        merged.n, merged.vertex_ids, merged.src, merged.dst,
        merged.indptr_in, merged.out_degree, {}, merged.labels,
        dict(merged.label_names))
    dbs, ip = fresh.out_csr()
    return dbs, ip, fresh._out_csr_order


@pytest.mark.parametrize("seed", [3, SEED])
@pytest.mark.parametrize("adds,removes", [(0, 0), (40, 0), (0, 60),
                                          (50, 80)])
def test_merge_delta_out_csr_incremental_bit_equal(seed, adds,
                                                   removes):
    snap, src, dst, labs, rng = _base(seed=seed, labeled=True)
    snap.out_csr()                      # the overlay init's build
    ov = _mutate(snap, src, dst, labs, rng, adds, removes)
    a_src, a_dst, a_lab = ov.live_adds()
    merged = snap_mod.merge_delta(snap, ~ov.tomb_row_mask, a_src,
                                  a_dst, a_lab)
    assert getattr(merged, "_out_csr", None) is not None, \
        "merge_delta must carry the out-CSR cache incrementally"
    got_dbs, got_ip = merged._out_csr
    ref_dbs, ref_ip, ref_order = _fresh_out_csr(merged)
    assert np.array_equal(got_dbs, ref_dbs)
    assert np.array_equal(got_ip, ref_ip)
    assert np.array_equal(np.asarray(merged._out_csr_order, np.int64),
                          np.asarray(ref_order, np.int64))


def test_overlay_slot_index_reuses_snapshot_order():
    """The next epoch's DeltaOverlay reads the cached permutation (no
    argsort): identity, and removals through it still kill the right
    rows."""
    snap, src, dst, labs, rng = _base(labeled=True)
    ov0 = _mutate(snap, src, dst, labs, rng, 16, 8)
    a_src, a_dst, a_lab = ov0.live_adds()
    merged = snap_mod.merge_delta(snap, ~ov0.tomb_row_mask, a_src,
                                  a_dst, a_lab)
    ov1 = DeltaOverlay(merged, min_cap=64)
    assert ov1._base_order() is merged._out_csr_order
    # a removal resolved through the carried index tombstones a live
    # base row (merge_delta output really is dst-sorted + consistent)
    e = 5
    assert ov1.remove_edge(int(merged.src[e]), int(merged.dst[e]),
                           int(merged.labels[e]))
    assert ov1.tomb_row_mask[e] or ov1.tomb_row_mask.sum() == 1


def test_device_compaction_chain_keeps_out_csr_incremental():
    """EpochCompactor's device path publishes a merged snapshot whose
    out-CSR cache is pre-attached (and correct) — epoch N+1's overlay
    never re-sorts."""
    snap, src, dst, labs, rng = _base()
    build_chunked_csr(snap)
    ov = _mutate(snap, src, dst, labs, rng, 24, 12)
    comp = EpochCompactor()
    merged, mode = comp.compact(snap, ov)
    assert mode == "device"
    assert getattr(merged, "_out_csr", None) is not None
    got_dbs, got_ip = merged._out_csr
    ref_dbs, ref_ip, ref_order = _fresh_out_csr(merged)
    assert np.array_equal(got_dbs, ref_dbs)
    assert np.array_equal(got_ip, ref_ip)
    assert np.array_equal(np.asarray(merged._out_csr_order, np.int64),
                          np.asarray(ref_order, np.int64))
