"""Gremlin→TPU compilation suite: compiled traversals must agree with the
OLTP interpreter (the reference's semantics oracle is TinkerPop's
interpreter; here our own interpreter plays that role).
"""

import random

import pytest

import titan_tpu
from titan_tpu.traversal.olap_compile import try_compile


@pytest.fixture
def g():
    graph = titan_tpu.open("inmemory")
    random.seed(7)
    tx = graph.new_transaction()
    people = [tx.add_vertex("person", name=f"p{i}") for i in range(30)]
    for _ in range(120):
        a, b = random.sample(people, 2)
        tx.add_edge(a, random.choice(["knows", "likes"]), b)
    tx.commit()
    yield graph
    graph.close()


def _both(g, build):
    """Run the same traversal on the interpreter and the TPU computer."""
    oltp = build(g.traversal()).to_list()
    tpu = build(g.traversal().with_computer("tpu")).to_list()
    return oltp, tpu


def test_two_hop_count(g):
    oltp, tpu = _both(g, lambda t: t.V().out().out().count())
    assert oltp == tpu and len(tpu) == 1


def test_labeled_step_count(g):
    oltp, tpu = _both(g, lambda t: t.V().out("knows").count())
    assert oltp == tpu
    oltp, tpu = _both(g, lambda t: t.V().in_("likes").out("knows").count())
    assert oltp == tpu


def test_both_direction(g):
    oltp, tpu = _both(g, lambda t: t.V().both().count())
    assert oltp == tpu


def test_dedup_count(g):
    oltp, tpu = _both(g, lambda t: t.V().out().out().dedup().count())
    assert sorted(oltp) == sorted(tpu)


def test_start_ids_and_id_terminal(g):
    tx = g.new_transaction()
    v0 = next(iter(tx.vertices()))
    tx.commit()
    oltp, tpu = _both(g, lambda t: t.V(v0.id).out().id_())
    assert sorted(oltp) == sorted(tpu)


def test_repeat_times(g):
    from titan_tpu.traversal.dsl import anon
    oltp, tpu = _both(
        g, lambda t: t.V().repeat(anon().out()).times(3).count())
    assert oltp == tpu


def test_has_start_compiles(g):
    oltp, tpu = _both(
        g, lambda t: t.V().has("name", "p0").out().count())
    assert oltp == tpu


def test_vertex_terminal(g):
    oltp, tpu = _both(g, lambda t: t.V().out("knows").dedup())
    assert {v.id for v in oltp} == {v.id for v in tpu}


def test_unsupported_falls_back(g):
    """Steps outside the subset (limit, multi-key values) must still
    answer via the interpreter."""
    tpu = g.traversal().with_computer("tpu").V().has("name", "p3") \
        .values("name").to_list()
    assert tpu == ["p3"]
    # and the matcher itself returns None for unsupported shapes
    src = g.traversal().with_computer("tpu")
    from titan_tpu.traversal.dsl import Traversal
    for t in (src.V().out().limit(3),
              src.V().values("name", "age"),
              src.V().out().order()):
        steps = Traversal._fold_has_into_start(list(t._steps))
        assert try_compile(steps, src) is None


def test_pseudo_key_has_still_works(g):
    """has('label', ...) / has('id', ...) are pseudo-keys answered by the
    streaming filters, not the property-index path."""
    tx = g.new_transaction()
    some = next(iter(tx.vertices()))
    tx.commit()
    assert len(g.traversal().V().has("label", "person").to_list()) == 30
    assert [v.id for v in g.traversal().V().has("id", some.id).to_list()] == \
        [some.id]


def test_multiple_has_id_intersect(g):
    tx = g.new_transaction()
    vs = list(tx.vertices())[:2]
    tx.commit()
    a, b = vs[0].id, vs[1].id
    assert g.traversal().V().has_id(a).has_id(b).to_list() == []
    assert [v.id for v in
            g.traversal().V().has_id(a, b).has_id(a).to_list()] == [a]


def test_anon_direct_execution_raises(g):
    from titan_tpu.traversal.dsl import anon
    with pytest.raises(ValueError):
        anon().out().to_list()


def test_compiled_sees_committed_only(g):
    """The snapshot is a committed-state image; uncommitted writes don't
    appear (documented divergence from the OLTP path)."""
    before = g.traversal().with_computer("tpu").V().out().count().to_list()[0]
    tx = g.new_transaction()
    a = tx.add_vertex("person", name="uncommitted")
    tx.commit()
    # new source → fresh snapshot sees the commit
    after = g.traversal().with_computer("tpu").V().both().count().to_list()[0]
    assert after >= before


def test_start_dedup_collapses_duplicates(g):
    # dedup() before any vertex step must dedup the start multiset
    tx = g.new_transaction()
    vid = next(iter(tx.query().vertices())).id
    tx.rollback()
    oltp, tpu = _both(g, lambda t: t.V(vid, vid).dedup().count())
    assert oltp == tpu == [1]
    oltp, tpu = _both(g, lambda t: t.V(vid, vid).dedup().out().count())
    assert oltp == tpu


def test_label_filter_without_codes_raises(g):
    # an explicitly supplied snapshot IS the dataset: if it lacks label
    # codes, a label-filtered step must raise — silently traversing every
    # edge (or silently answering from the live graph) would be wrong data
    from titan_tpu.olap.tpu import snapshot as snap_mod
    full = snap_mod.build(g)
    stripped = snap_mod.from_arrays(full.n, full.src, full.dst,
                                    full.vertex_ids)
    with pytest.raises(ValueError, match="label"):
        (g.traversal().with_computer("tpu", snapshot=stripped)
         .V().out("knows").count().to_list())
    # unfiltered steps on the same snapshot still run on the device
    got = (g.traversal().with_computer("tpu", snapshot=stripped)
           .V().out().count().to_list())
    assert got == g.traversal().V().out().count().to_list()


@pytest.fixture
def gp():
    """Graph with numeric vertex properties for the widened subset."""
    graph = titan_tpu.open("inmemory")
    random.seed(11)
    tx = graph.new_transaction()
    people = [tx.add_vertex("person", name=f"p{i}", age=20 + (i * 7) % 50)
              for i in range(40)]
    for _ in range(200):
        a, b = random.sample(people, 2)
        tx.add_edge(a, random.choice(["knows", "likes"]), b)
    tx.commit()
    yield graph
    graph.close()


def _assert_both(gp, build):
    oltp = build(gp.traversal()).to_list()
    tpu = build(gp.traversal().with_computer("tpu")).to_list()
    return oltp, tpu


def test_midchain_has_matches_interpreter(gp):
    from titan_tpu.query.predicates import P
    for build in (
        lambda t: t.V().out("knows").has("age", P.gt(40)).count(),
        lambda t: t.V().out().has("age", P.lte(30)).out("likes").count(),
        lambda t: t.V().out().has("age", 27).dedup().count(),
    ):
        oltp, tpu = _assert_both(gp, build)
        assert oltp == tpu, build
    # the matcher actually compiles these (no silent interpreter run)
    src = gp.traversal().with_computer("tpu")
    from titan_tpu.query.predicates import P as P2
    from titan_tpu.traversal.dsl import Traversal
    t = src.V().out("knows").has("age", P2.gt(40)).count()
    steps = Traversal._fold_has_into_start(list(t._steps))
    assert try_compile(steps, src) is not None


def test_values_sum_mean_match_interpreter(gp):
    oltp_s, tpu_s = _assert_both(
        gp, lambda t: t.V().out("knows").values("age").sum_())
    assert oltp_s == pytest.approx(tpu_s)
    oltp_m, tpu_m = _assert_both(
        gp, lambda t: t.V().out().out().values("age").mean())
    assert oltp_m == pytest.approx(tpu_m)
    oltp_v, tpu_v = _assert_both(
        gp, lambda t: t.V().out("likes").values("age"))
    assert sorted(oltp_v) == sorted(tpu_v)


def test_group_count_matches_interpreter(gp):
    oltp, tpu = _assert_both(
        gp, lambda t: t.V().out("knows").group_count("age"))
    assert oltp == tpu
    oltp, tpu = _assert_both(
        gp, lambda t: t.V().out().group_count().by("name"))
    assert oltp == tpu
    # un-keyed: vertices group by element id
    oltp, tpu = _assert_both(gp, lambda t: t.V().out().group_count())
    assert oltp == tpu


def test_ldbc_is3_shape_on_device(gp):
    """The LDBC IS3 4-hop friends shape end-to-end on the device path
    (VERDICT r3 #5 done-criterion)."""
    tx = gp.new_transaction()
    vid = next(iter(tx.vertices())).id
    tx.rollback()
    build = lambda t: t.V(vid).out("knows").out("knows") \
        .out("knows").out("knows").count()            # noqa: E731
    oltp, tpu = _assert_both(gp, build)
    assert oltp == tpu
    src = gp.traversal().with_computer("tpu")
    from titan_tpu.traversal.dsl import Traversal
    t = build(src)
    steps = Traversal._fold_has_into_start(list(t._steps))
    assert try_compile(steps, src) is not None


def test_refresh_invalidates_property_columns(gp):
    """Advisor r4 finding: the dense vertex-property columns must not
    survive a refresh() that applied a property mutation — a stale
    column silently mis-answers compiled has()/values()."""
    from titan_tpu.olap.tpu import snapshot as snap_mod
    from titan_tpu.query.predicates import P

    snap = snap_mod.build(gp)
    build = lambda t: t.V().out("knows") \
        .has("age", P.gt(25)).count()                 # noqa: E731
    src = gp.traversal().with_computer("tpu", snapshot=snap)
    assert build(src).to_list() == build(gp.traversal()).to_list()

    # flip every matching vertex across the predicate boundary
    tx = gp.new_transaction()
    for v in list(tx.vertices()):
        if (v.value("age") or 0) > 25:
            v.property("age", 0)
    tx.commit()
    snap.refresh()
    # drop the thread-bound tx: its slice caches legitimately hold the
    # pre-commit ages (repeatable read) — we want a fresh-read baseline
    gp.tx().rollback()
    after_oltp = build(gp.traversal()).to_list()
    after_tpu = build(
        gp.traversal().with_computer("tpu", snapshot=snap)).to_list()
    assert after_tpu == after_oltp == [0]


def test_refresh_vertex_add_keeps_columns_consistent(gp):
    """Vertex-set changes must drop the property columns (a stale
    column of the old length crashes the jitted filter plan)."""
    from titan_tpu.olap.tpu import snapshot as snap_mod
    from titan_tpu.query.predicates import P

    snap = snap_mod.build(gp)
    build = lambda t: t.V().out("knows") \
        .has("age", P.gt(25)).count()                 # noqa: E731
    src = gp.traversal().with_computer("tpu", snapshot=snap)
    build(src).to_list()                    # attaches the age column

    tx = gp.new_transaction()
    nv = tx.add_vertex("person", name="new", age=48)
    old = next(iter(tx.vertices()))
    nv.add_edge("knows", old)
    old.add_edge("knows", nv)
    tx.commit()
    snap.refresh()
    gp.tx().rollback()
    after_oltp = build(gp.traversal()).to_list()
    after_tpu = build(
        gp.traversal().with_computer("tpu", snapshot=snap)).to_list()
    assert after_tpu == after_oltp


def test_compiled_empty_sum_matches_interpreter(gp):
    """TP3 empty reducing barrier: sum of an empty stream emits NOTHING
    on the compiled path too (tests/test_tp3_differential pins the
    interpreter side)."""
    from titan_tpu.query.predicates import P
    build = lambda t: t.V().out("knows") \
        .has("age", P.gt(10 ** 6)).values("age").sum_()   # noqa: E731
    oltp, tpu = _assert_both(gp, build)
    assert oltp == tpu == []


def test_stale_explicit_snapshot_refuses_live_column_build(gp):
    """A property column must NOT be lazily built from a live graph
    that moved past an explicit snapshot's epoch (dataset mixing —
    mirrors the label-code guard in run())."""
    from titan_tpu.olap.tpu import snapshot as snap_mod
    from titan_tpu.query.predicates import P

    snap = snap_mod.build(gp)
    tx = gp.new_transaction()
    next(iter(tx.vertices())).property("age", 1)
    tx.commit()                      # snapshot now stale, NOT refreshed
    assert snap.stale
    with pytest.raises(ValueError, match="stale"):
        (gp.traversal().with_computer("tpu", snapshot=snap)
         .V().out("knows").has("age", P.gt(25)).count().to_list())
    # refresh heals it
    snap.refresh()
    got = (gp.traversal().with_computer("tpu", snapshot=snap)
           .V().out("knows").has("age", P.gt(25)).count().to_list())
    gp.tx().rollback()
    assert got == gp.traversal().V().out("knows") \
        .has("age", P.gt(25)).count().to_list()


def test_group_count_pseudo_and_missing_keys(gp):
    """Advisor r4: by('id') must match the interpreter (element-id
    buckets), by('label') must fall back (not silently answer {}), and
    vertices missing the key group under None, not dropped."""
    oltp, tpu = _assert_both(
        gp, lambda t: t.V().out("knows").group_count("id"))
    assert oltp == tpu and tpu != [{}]
    oltp, tpu = _assert_both(
        gp, lambda t: t.V().out("knows").group_count("label"))
    assert oltp == tpu                       # interpreter fallback
    # partially-populated key: gp has no 'nickname' anywhere
    oltp, tpu = _assert_both(
        gp, lambda t: t.V().out("knows").group_count("nickname"))
    assert oltp == tpu
    assert list(tpu[0].keys()) == [None]


def test_stale_auto_snapshot_falls_back(gp):
    """A STALE auto-built snapshot must fall back to the interpreter
    for property columns — only a user-supplied snapshot raises."""
    from titan_tpu.query.predicates import P
    src = gp.traversal().with_computer("tpu")
    src.V().out().count().to_list()          # builds + caches auto snap
    tx = gp.new_transaction()
    next(iter(tx.vertices())).property("age", 1)
    tx.commit()
    gp.tx().rollback()
    got = src.V().out("knows").has("age", P.gt(25)).count().to_list()
    assert got == gp.traversal().V().out("knows") \
        .has("age", P.gt(25)).count().to_list()


def test_unbound_snapshot_refuses_column_build(gp):
    """from_arrays snapshots have no epoch binding to any graph —
    lazily building property columns from the live graph could mix
    datasets undetectably, so the compiled path must refuse."""
    from titan_tpu.olap.tpu import snapshot as snap_mod
    from titan_tpu.query.predicates import P

    full = snap_mod.build(gp)
    unbound = snap_mod.from_arrays(full.n, full.src, full.dst,
                                   full.vertex_ids)
    with pytest.raises(ValueError, match="not bound"):
        (gp.traversal().with_computer("tpu", snapshot=unbound)
         .V().out().has("age", P.gt(25)).count().to_list())
    # explicit attach by the user is the sanctioned path
    unbound.attach_vertex_values(gp, ["age"])
    got = (gp.traversal().with_computer("tpu", snapshot=unbound)
           .V().out().has("age", P.gt(25)).count().to_list())
    assert got == gp.traversal().V().out() \
        .has("age", P.gt(25)).count().to_list()


def test_edge_only_refresh_keeps_property_columns(gp):
    """Edge-only delta merges keep the dense property columns (their
    vertex alignment is unchanged) — no full re-attach per refresh."""
    from titan_tpu.olap.tpu import snapshot as snap_mod
    from titan_tpu.query.predicates import P

    snap = snap_mod.build(gp)
    build = lambda t: t.V().out("knows") \
        .has("age", P.gt(25)).count()                 # noqa: E731
    build(gp.traversal().with_computer("tpu", snapshot=snap)).to_list()
    assert "age" in snap.vertex_values
    tx = gp.new_transaction()
    vs = list(tx.vertices())
    vs[0].add_edge("knows", vs[1])
    tx.commit()
    snap.refresh()
    assert "age" in snap.vertex_values    # survived the edge-only merge
    gp.tx().rollback()
    assert build(gp.traversal().with_computer("tpu", snapshot=snap)) \
        .to_list() == build(gp.traversal()).to_list()
