"""Gremlin→TPU compilation suite: compiled traversals must agree with the
OLTP interpreter (the reference's semantics oracle is TinkerPop's
interpreter; here our own interpreter plays that role).
"""

import random

import pytest

import titan_tpu
from titan_tpu.traversal.olap_compile import try_compile


@pytest.fixture
def g():
    graph = titan_tpu.open("inmemory")
    random.seed(7)
    tx = graph.new_transaction()
    people = [tx.add_vertex("person", name=f"p{i}") for i in range(30)]
    for _ in range(120):
        a, b = random.sample(people, 2)
        tx.add_edge(a, random.choice(["knows", "likes"]), b)
    tx.commit()
    yield graph
    graph.close()


def _both(g, build):
    """Run the same traversal on the interpreter and the TPU computer."""
    oltp = build(g.traversal()).to_list()
    tpu = build(g.traversal().with_computer("tpu")).to_list()
    return oltp, tpu


def test_two_hop_count(g):
    oltp, tpu = _both(g, lambda t: t.V().out().out().count())
    assert oltp == tpu and len(tpu) == 1


def test_labeled_step_count(g):
    oltp, tpu = _both(g, lambda t: t.V().out("knows").count())
    assert oltp == tpu
    oltp, tpu = _both(g, lambda t: t.V().in_("likes").out("knows").count())
    assert oltp == tpu


def test_both_direction(g):
    oltp, tpu = _both(g, lambda t: t.V().both().count())
    assert oltp == tpu


def test_dedup_count(g):
    oltp, tpu = _both(g, lambda t: t.V().out().out().dedup().count())
    assert sorted(oltp) == sorted(tpu)


def test_start_ids_and_id_terminal(g):
    tx = g.new_transaction()
    v0 = next(iter(tx.vertices()))
    tx.commit()
    oltp, tpu = _both(g, lambda t: t.V(v0.id).out().id_())
    assert sorted(oltp) == sorted(tpu)


def test_repeat_times(g):
    from titan_tpu.traversal.dsl import anon
    oltp, tpu = _both(
        g, lambda t: t.V().repeat(anon().out()).times(3).count())
    assert oltp == tpu


def test_has_start_compiles(g):
    oltp, tpu = _both(
        g, lambda t: t.V().has("name", "p0").out().count())
    assert oltp == tpu


def test_vertex_terminal(g):
    oltp, tpu = _both(g, lambda t: t.V().out("knows").dedup())
    assert {v.id for v in oltp} == {v.id for v in tpu}


def test_unsupported_falls_back(g):
    """Steps outside the subset (limit, multi-key values) must still
    answer via the interpreter."""
    tpu = g.traversal().with_computer("tpu").V().has("name", "p3") \
        .values("name").to_list()
    assert tpu == ["p3"]
    # and the matcher itself returns None for unsupported shapes
    src = g.traversal().with_computer("tpu")
    from titan_tpu.traversal.dsl import Traversal
    for t in (src.V().out().limit(3),
              src.V().values("name", "age"),
              src.V().out().order()):
        steps = Traversal._fold_has_into_start(list(t._steps))
        assert try_compile(steps, src) is None


def test_pseudo_key_has_still_works(g):
    """has('label', ...) / has('id', ...) are pseudo-keys answered by the
    streaming filters, not the property-index path."""
    tx = g.new_transaction()
    some = next(iter(tx.vertices()))
    tx.commit()
    assert len(g.traversal().V().has("label", "person").to_list()) == 30
    assert [v.id for v in g.traversal().V().has("id", some.id).to_list()] == \
        [some.id]


def test_multiple_has_id_intersect(g):
    tx = g.new_transaction()
    vs = list(tx.vertices())[:2]
    tx.commit()
    a, b = vs[0].id, vs[1].id
    assert g.traversal().V().has_id(a).has_id(b).to_list() == []
    assert [v.id for v in
            g.traversal().V().has_id(a, b).has_id(a).to_list()] == [a]


def test_anon_direct_execution_raises(g):
    from titan_tpu.traversal.dsl import anon
    with pytest.raises(ValueError):
        anon().out().to_list()


def test_compiled_sees_committed_only(g):
    """The snapshot is a committed-state image; uncommitted writes don't
    appear (documented divergence from the OLTP path)."""
    before = g.traversal().with_computer("tpu").V().out().count().to_list()[0]
    tx = g.new_transaction()
    a = tx.add_vertex("person", name="uncommitted")
    tx.commit()
    # new source → fresh snapshot sees the commit
    after = g.traversal().with_computer("tpu").V().both().count().to_list()[0]
    assert after >= before


def test_start_dedup_collapses_duplicates(g):
    # dedup() before any vertex step must dedup the start multiset
    tx = g.new_transaction()
    vid = next(iter(tx.query().vertices())).id
    tx.rollback()
    oltp, tpu = _both(g, lambda t: t.V(vid, vid).dedup().count())
    assert oltp == tpu == [1]
    oltp, tpu = _both(g, lambda t: t.V(vid, vid).dedup().out().count())
    assert oltp == tpu


def test_label_filter_without_codes_raises(g):
    # an explicitly supplied snapshot IS the dataset: if it lacks label
    # codes, a label-filtered step must raise — silently traversing every
    # edge (or silently answering from the live graph) would be wrong data
    from titan_tpu.olap.tpu import snapshot as snap_mod
    full = snap_mod.build(g)
    stripped = snap_mod.from_arrays(full.n, full.src, full.dst,
                                    full.vertex_ids)
    with pytest.raises(ValueError, match="label"):
        (g.traversal().with_computer("tpu", snapshot=stripped)
         .V().out("knows").count().to_list())
    # unfiltered steps on the same snapshot still run on the device
    got = (g.traversal().with_computer("tpu", snapshot=stripped)
           .V().out().count().to_list())
    assert got == g.traversal().V().out().count().to_list()


@pytest.fixture
def gp():
    """Graph with numeric vertex properties for the widened subset."""
    graph = titan_tpu.open("inmemory")
    random.seed(11)
    tx = graph.new_transaction()
    people = [tx.add_vertex("person", name=f"p{i}", age=20 + (i * 7) % 50)
              for i in range(40)]
    for _ in range(200):
        a, b = random.sample(people, 2)
        tx.add_edge(a, random.choice(["knows", "likes"]), b)
    tx.commit()
    yield graph
    graph.close()


def _assert_both(gp, build):
    oltp = build(gp.traversal()).to_list()
    tpu = build(gp.traversal().with_computer("tpu")).to_list()
    return oltp, tpu


def test_midchain_has_matches_interpreter(gp):
    from titan_tpu.query.predicates import P
    for build in (
        lambda t: t.V().out("knows").has("age", P.gt(40)).count(),
        lambda t: t.V().out().has("age", P.lte(30)).out("likes").count(),
        lambda t: t.V().out().has("age", 27).dedup().count(),
    ):
        oltp, tpu = _assert_both(gp, build)
        assert oltp == tpu, build
    # the matcher actually compiles these (no silent interpreter run)
    src = gp.traversal().with_computer("tpu")
    from titan_tpu.query.predicates import P as P2
    from titan_tpu.traversal.dsl import Traversal
    t = src.V().out("knows").has("age", P2.gt(40)).count()
    steps = Traversal._fold_has_into_start(list(t._steps))
    assert try_compile(steps, src) is not None


def test_values_sum_mean_match_interpreter(gp):
    oltp_s, tpu_s = _assert_both(
        gp, lambda t: t.V().out("knows").values("age").sum_())
    assert oltp_s == pytest.approx(tpu_s)
    oltp_m, tpu_m = _assert_both(
        gp, lambda t: t.V().out().out().values("age").mean())
    assert oltp_m == pytest.approx(tpu_m)
    oltp_v, tpu_v = _assert_both(
        gp, lambda t: t.V().out("likes").values("age"))
    assert sorted(oltp_v) == sorted(tpu_v)


def test_group_count_matches_interpreter(gp):
    oltp, tpu = _assert_both(
        gp, lambda t: t.V().out("knows").group_count("age"))
    assert oltp == tpu
    oltp, tpu = _assert_both(
        gp, lambda t: t.V().out().group_count().by("name"))
    assert oltp == tpu
    # un-keyed: vertices group by element id
    oltp, tpu = _assert_both(gp, lambda t: t.V().out().group_count())
    assert oltp == tpu


def test_ldbc_is3_shape_on_device(gp):
    """The LDBC IS3 4-hop friends shape end-to-end on the device path
    (VERDICT r3 #5 done-criterion)."""
    tx = gp.new_transaction()
    vid = next(iter(tx.vertices())).id
    tx.rollback()
    build = lambda t: t.V(vid).out("knows").out("knows") \
        .out("knows").out("knows").count()            # noqa: E731
    oltp, tpu = _assert_both(gp, build)
    assert oltp == tpu
    src = gp.traversal().with_computer("tpu")
    from titan_tpu.traversal.dsl import Traversal
    t = build(src)
    steps = Traversal._fold_has_into_start(list(t._steps))
    assert try_compile(steps, src) is not None
