"""Index lifecycle + regression coverage for the review findings.

Modeled on the reference's IndexRepairJob/IndexRemoveJob behavior under
SchemaAction (titan-core graphdb/olap/job/, ManagementSystem.updateIndex).
"""

import pytest

import titan_tpu
from titan_tpu.core.defs import SchemaAction, SchemaStatus
from titan_tpu.errors import SchemaViolationError, TitanError
from titan_tpu.query.predicates import P


@pytest.fixture
def g():
    graph = titan_tpu.open({"storage.backend": "inmemory",
                            "index.search.backend": "memindex"})
    yield graph
    graph.close()


def _seed(g, n=4):
    tx = g.new_transaction()
    ids = [tx.add_vertex("person", name=f"p{i}", score=i).id
           for i in range(n)]
    tx.commit()
    return ids


def test_register_reindex_enable(g):
    ids = _seed(g)           # data exists BEFORE the index
    mgmt = g.management()
    idx = mgmt.build_index("lateName", "vertex").add_key("name") \
        .build_composite_index()
    assert idx.status is SchemaStatus.INSTALLED

    mgmt.update_index(idx, SchemaAction.REGISTER_INDEX)
    assert mgmt.get_graph_index("lateName").status is SchemaStatus.REGISTERED
    mgmt.update_index("lateName", SchemaAction.REINDEX)
    assert mgmt.get_graph_index("lateName").status is SchemaStatus.ENABLED
    mgmt.commit()

    tx = g.new_transaction()
    assert [v.id for v in tx.query().has("name", "p2").vertices()] == [ids[2]]
    tx.commit()


def test_reindex_mixed(g):
    _seed(g)
    mgmt = g.management()
    idx = mgmt.build_index("lateSearch", "vertex").add_key("name", "TEXT") \
        .build_mixed_index("search")
    assert idx.status is SchemaStatus.INSTALLED
    mgmt.update_index(idx, SchemaAction.REGISTER_INDEX)
    mgmt.update_index(idx, SchemaAction.REINDEX)
    mgmt.commit()

    tx = g.new_transaction()
    assert len(tx.query().has("name", P.text_contains("p1")).vertices()) == 1
    tx.commit()


def test_disable_and_remove(g):
    mgmt = g.management()
    name = mgmt.make_property_key("name", str)
    idx = mgmt.build_index("n1", "vertex").add_key(name) \
        .build_composite_index()
    mgmt.commit()
    _seed(g)

    mgmt = g.management()
    mgmt.update_index("n1", SchemaAction.DISABLE_INDEX)
    tx = g.new_transaction()
    # disabled index is not queried — full scan still answers
    assert len(tx.query().has("name", "p1").vertices()) == 1
    tx.commit()

    mgmt.update_index("n1", SchemaAction.REMOVE_INDEX)
    # rows are gone from the graphindex store
    from titan_tpu.codec.dataio import DataOutput
    out = DataOutput()
    out.put_uvar(idx.id)
    prefix = out.getvalue()
    store = g.backend.index_store.store
    txh = g.backend.manager.begin_transaction()
    rows = [k for k, es in store.get_keys(
        __import__("titan_tpu.storage.api", fromlist=["SliceQuery"]).SliceQuery(),
        txh) if k.startswith(prefix) and es]
    txh.commit()
    assert rows == []


def test_illegal_transition(g):
    mgmt = g.management()
    name = mgmt.make_property_key("name", str)
    mgmt.build_index("n2", "vertex").add_key(name).build_composite_index()
    mgmt.commit()
    with pytest.raises(TitanError):
        mgmt.update_index("n2", SchemaAction.REGISTER_INDEX)  # already ENABLED
    with pytest.raises(TitanError):
        mgmt.update_index("n2", SchemaAction.REMOVE_INDEX)    # not DISABLED


def test_installed_index_receives_no_writes(g):
    _seed(g, 1)
    mgmt = g.management()
    idx = mgmt.build_index("cold", "vertex").add_key("name") \
        .build_composite_index()
    mgmt.commit()
    tx = g.new_transaction()
    tx.add_vertex(name="newbie")
    tx.commit()
    # INSTALLED: no writes landed in the index store
    from titan_tpu.codec.dataio import DataOutput
    out = DataOutput()
    out.put_uvar(idx.id)
    prefix = out.getvalue()
    from titan_tpu.storage.api import SliceQuery
    txh = g.backend.manager.begin_transaction()
    rows = [k for k, es in g.backend.index_store.store.get_keys(
        SliceQuery(), txh) if k.startswith(prefix) and es]
    txh.commit()
    assert rows == []


# -- review-finding regressions ----------------------------------------------

def test_query_sees_modified_vertex_in_tx(g):
    """Index-backed query must surface a pre-existing vertex whose indexed
    value changed inside the open transaction."""
    mgmt = g.management()
    name = mgmt.make_property_key("name", str)
    mgmt.build_index("n3", "vertex").add_key(name).build_composite_index()
    mgmt.commit()
    ids = _seed(g, 2)

    tx = g.new_transaction()
    tx.vertex(ids[0]).property("name", "renamed")
    hits = {v.id for v in tx.query().has("name", "renamed").vertices()}
    assert hits == {ids[0]}
    assert tx.query().has("name", "p0").vertices() == []
    tx.rollback()


def test_intra_tx_unique_violation(g):
    mgmt = g.management()
    ssn = mgmt.make_property_key("ssn", str)
    mgmt.build_index("u1", "vertex").add_key(ssn).unique() \
        .build_composite_index()
    mgmt.commit()
    tx = g.new_transaction()
    tx.add_vertex(ssn="dup")
    tx.add_vertex(ssn="dup")
    with pytest.raises(SchemaViolationError):
        tx.commit()


def test_unique_value_moves_between_elements(g):
    """Deleting the old holder and adding a new one in ONE tx must pass."""
    mgmt = g.management()
    ssn = mgmt.make_property_key("ssn", str)
    mgmt.build_index("u2", "vertex").add_key(ssn).unique() \
        .build_composite_index()
    mgmt.commit()
    tx = g.new_transaction()
    a = tx.add_vertex(ssn="m1")
    tx.commit()

    tx = g.new_transaction()
    tx.vertex(a.id).remove()
    b = tx.add_vertex(ssn="m1")
    tx.commit()   # must NOT raise

    tx = g.new_transaction()
    assert [v.id for v in tx.query().has("ssn", "m1").vertices()] == [b.id]
    tx.commit()


def test_has_not_on_edges(g):
    tx = g.new_transaction()
    a, b = tx.add_vertex(), tx.add_vertex()
    e1 = tx.add_edge(a, "knows", b, {"w": 1})
    e2 = tx.add_edge(b, "knows", a)
    tx.commit()
    tx = g.new_transaction()
    hits = tx.query().has_not("w").edges()
    assert [h.id for h in hits] == [e2.id]
    # neq must not match edges lacking the key entirely
    hits = tx.query().has("w", P.neq(5)).edges()
    assert [h.id for h in hits] == [e1.id]
    tx.commit()


def test_geo_predicate_on_missing_field(g):
    """Docs without the geo field must not crash the mixed query."""
    from titan_tpu.core.attribute import Geoshape
    mgmt = g.management()
    place = mgmt.make_property_key("place", Geoshape)
    desc = mgmt.make_property_key("desc", str)
    mgmt.build_index("geo2", "vertex").add_key(place).add_key(desc, "TEXT") \
        .build_mixed_index("search")
    mgmt.commit()
    tx = g.new_transaction()
    tx.add_vertex(desc="no location here")
    v = tx.add_vertex(place=Geoshape.point(10.0, 10.0), desc="located")
    tx.commit()
    tx = g.new_transaction()
    hits = tx.query().has(
        "place", P.geo_within(Geoshape.circle(10.0, 10.0, 5))).vertices()
    assert [h.id for h in hits] == [v.id]
    tx.commit()


def test_edge_composite_and_mixed_intersection(g):
    """Composite-edge 4-tuple hits and mixed-edge hits must intersect."""
    mgmt = g.management()
    since = mgmt.make_property_key("since", int)
    weight = mgmt.make_property_key("weight", float)
    mgmt.build_index("eSince", "edge").add_key(since).build_composite_index()
    mgmt.build_index("eWeight", "edge").add_key(weight) \
        .build_mixed_index("search")
    mgmt.commit()

    tx = g.new_transaction()
    a, b = tx.add_vertex(), tx.add_vertex()
    e1 = tx.add_edge(a, "knows", b, {"since": 1999, "weight": 0.9})
    tx.add_edge(b, "knows", a, {"since": 1999, "weight": 0.1})
    tx.commit()

    tx = g.new_transaction()
    hits = tx.query().has("since", 1999).has("weight", P.gt(0.5)).edges()
    assert [h.id for h in hits] == [e1.id]
    tx.commit()


def test_raw_query_on_edge_mixed_index(g):
    mgmt = g.management()
    note = mgmt.make_property_key("note", str)
    mgmt.build_index("eNotes", "edge").add_key(note, "TEXT") \
        .build_mixed_index("search")
    mgmt.commit()
    tx = g.new_transaction()
    a, b = tx.add_vertex(), tx.add_vertex()
    e = tx.add_edge(a, "rel", b, {"note": "important meeting"})
    tx.commit()
    hits = g.index_query("eNotes", "note:important")
    assert [(el.id, s) for el, s in hits] == [(e.id, 1.0)]
    with pytest.raises(TitanError):
        g.index_query("note", "x")   # not an index


def test_memindex_keyinfo_survives_reopen(tmp_path):
    cfg = {"storage.backend": "sqlite",
           "storage.directory": str(tmp_path / "db"),
           "index.search.backend": "memindex",
           "index.search.directory": str(tmp_path / "idx")}
    g = titan_tpu.open(cfg)
    mgmt = g.management()
    code = mgmt.make_property_key("code", str)
    mgmt.build_index("codes", "vertex").add_key(code, "STRING") \
        .build_mixed_index("search")
    mgmt.commit()
    tx = g.new_transaction()
    v = tx.add_vertex(code="alpha beta")
    tx.commit()
    g.close()

    g = titan_tpu.open(cfg)
    tx = g.new_transaction()
    # STRING mapping must persist across reopen: exact-match queries still
    # route through the index, and the provider still knows the mapping
    assert [x.id for x in
            tx.query().has("code", "alpha beta").vertices()] == [v.id]
    provider = g.index_provider("search")
    info = provider._stores["codes"].keyinfo["code"]
    assert "STRING" in info.parameters
    tx.commit()
    g.close()
