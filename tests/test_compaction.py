"""ops/compaction property + contract tests (CPU).

The library's whole value is a CONTRACT: each primitive is bit-equal to
the ``jnp.nonzero(mask, size=cap, fill_value=fill)`` formulation it
replaced in the round loops (ascending survivor order, fill past the
count, overflow truncation), while running at p-scale. These tests pin
that contract against numpy oracles over random masks/bands, check the
cap-overflow and claim-reset behavior the consumers rely on, and hold
the op-scan ban (ISSUE r6) through graftlint rule R1 — auto-discovered
over the whole tree since ISSUE 15, replacing the per-directory
module-count pins that lived here (differential end-to-end coverage of
the refactored BFS/SSSP/WCC consumers lives in test_frontier_models.py
/ test_frontier_bfs.py / test_sharded_bfs.py against independent
oracles)."""

import numpy as np
import pytest

from titan_tpu.ops.compaction import (CLAIM_SENTINEL, banded_frontier,
                                      claim_dedup, claim_reset,
                                      compact_ids, scatter_compact)


def _np_compact(mask, payload, cap, fill):
    """Oracle: the pre-refactor nonzero+gather formulation."""
    idx = np.nonzero(mask)[0][:cap]
    out = np.full((cap,), fill, payload.dtype)
    out[: len(idx)] = payload[idx]
    return out


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("density", [0.0, 0.03, 0.5, 1.0])
def test_scatter_compact_matches_nonzero_oracle(seed, density):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    L = int(rng.integers(1, 3000))
    cap = int(rng.integers(1, 2 * L))
    mask = rng.random(L) < density
    ids = np.arange(L, dtype=np.int32)
    vals = rng.integers(-50, 50, L).astype(np.int32)
    count, (o_ids, o_vals) = scatter_compact(
        jnp.asarray(mask), (jnp.asarray(ids), jnp.asarray(vals)),
        cap, (L, -1))
    assert int(count) == int(mask.sum())       # TOTAL bits, pre-truncation
    assert (np.asarray(o_ids) == _np_compact(mask, ids, cap, L)).all()
    assert (np.asarray(o_vals) == _np_compact(mask, vals, cap, -1)).all()


@pytest.mark.parametrize("seed", range(4))
def test_compact_ids_bit_equal_vs_jnp_nonzero(seed):
    """compact_ids must be indistinguishable from the jnp.nonzero call
    it replaced — same dtype, same order, same fill, same truncation."""
    import jax.numpy as jnp

    rng = np.random.default_rng(100 + seed)
    L = int(rng.integers(1, 2000))
    cap = int(rng.integers(1, L + 10))
    mask = jnp.asarray(rng.random(L) < rng.random())
    ref = jnp.nonzero(mask, size=cap, fill_value=L)[0].astype(jnp.int32)
    count, got = compact_ids(mask, cap, L)
    assert got.dtype == ref.dtype
    assert (np.asarray(got) == np.asarray(ref)).all()
    assert int(count) == int(np.asarray(mask).sum())


def test_scatter_compact_overflow_cap_drops_tail():
    """Survivors past cap are dropped (not wrapped or clamped), and the
    returned count still reports the TRUE total so callers can detect
    the truncation (the _band_plan soundness contract rides on this)."""
    import jax.numpy as jnp

    mask = jnp.ones((10,), bool)
    count, out = compact_ids(mask, 4, 99)
    assert int(count) == 10
    assert np.asarray(out).tolist() == [0, 1, 2, 3]


def test_claim_dedup_single_winner_and_reset_idempotent():
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    n = 64
    lanes = 48
    claim = jnp.full((n + 2,), CLAIM_SENTINEL, jnp.int32)
    # heavy duplication: many lanes race on few keys; pad lanes carry
    # the out-of-band key n+1 (the BFS usage), masked out by validity
    keys_np = rng.integers(0, 8, lanes).astype(np.int32)
    keys_np[rng.random(lanes) < 0.3] = n + 1
    keys = jnp.asarray(keys_np)
    ticket = jnp.arange(lanes, dtype=jnp.int32)
    claim, won = claim_dedup(claim, keys, ticket)
    winner = np.asarray(won) & (keys_np <= n)
    for k in np.unique(keys_np[keys_np <= n]):
        at_k = winner[keys_np == k]
        assert at_k.sum() == 1, f"key {k}: {at_k.sum()} winners"
        # the minimum ticket wins (scatter-min semantics)
        assert at_k[0], f"key {k}: winner is not the min ticket"
    # reset restores the virgin state at every touched position ...
    claim = claim_reset(claim, keys)
    assert (np.asarray(claim) == CLAIM_SENTINEL).all()
    # ... and is idempotent
    claim2 = claim_reset(claim, keys)
    assert (np.asarray(claim2) == np.asarray(claim)).all()
    # a fresh dedup after the reset behaves exactly like the first
    _, won2 = claim_dedup(claim2, keys, ticket)
    assert (np.asarray(won2) == np.asarray(won)).all()


def test_claim_dedup_out_of_range_keys_never_win():
    """An out-of-range key must not report a phantom win via the
    clamped readback gather (the scatter drops it; the winner mask
    must too)."""
    import jax.numpy as jnp

    claim = jnp.full((4,), CLAIM_SENTINEL, jnp.int32)
    #          in-range, OOB high, OOB high matching last slot, negative
    keys = jnp.asarray([3, 100, 4, -7], jnp.int32)
    ticket = jnp.asarray([0, 1, 0, 2], jnp.int32)
    claim, won = claim_dedup(claim, keys, ticket)
    # lane 2 presents ticket 0 == the value lane 0 legitimately wrote
    # to the LAST slot (index 3) — the clamp would read it back equal
    assert np.asarray(won).tolist() == [True, False, False, False]
    assert np.asarray(claim).tolist() == [CLAIM_SENTINEL] * 3 + [0]


@pytest.mark.parametrize("seed", range(3))
def test_banded_frontier_matches_oracle(seed):
    import jax.numpy as jnp

    rng = np.random.default_rng(200 + seed)
    L = int(rng.integers(10, 1500))
    cap = int(rng.integers(4, L + 20))
    k_max = int(rng.integers(1, 12))
    budget = int(rng.integers(1, 300))
    mask = rng.random(L) < rng.random()
    mass = rng.integers(0, 40, L).astype(np.int32)
    nf, m8, overflow, flist, bounds = banded_frontier(
        jnp.asarray(mask), jnp.asarray(mass), cap, k_max, budget, L)
    # oracle: nonzero-compacted list, cumsum + searchsorted bounds
    idx = np.nonzero(mask)[0][:cap]
    ref_list = np.full((cap,), L, np.int32)
    ref_list[: len(idx)] = idx
    ref_mass = np.zeros((cap,), np.int64)
    ref_mass[: len(idx)] = mass[idx]
    cmass = np.cumsum(ref_mass)
    targets = np.arange(1, k_max + 1) * budget
    ref_bounds = np.concatenate(
        [[0], np.minimum(np.searchsorted(cmass, targets, side="right"),
                         cap)])
    assert int(nf) == len(idx)
    assert int(m8) == int(cmass[-1])
    assert int(overflow) == 0
    assert (np.asarray(flist) == ref_list).all()
    assert (np.asarray(bounds) == ref_bounds).all()
    # segment sanity: bounds are monotone list positions
    assert (np.diff(np.asarray(bounds)) >= 0).all()


def test_banded_frontier_flags_int32_mass_overflow():
    """A point-mass band whose listed chunk mass exceeds int32 must be
    DETECTED, not silently wrapped into corrupt segment bounds (ADVICE
    r5 #3). Without x64 the cumsum wraps — the monotonicity break sets
    the overflow flag; the host refuses the round (_frontier_run)."""
    import jax
    import jax.numpy as jnp

    if jax.config.jax_enable_x64:
        pytest.skip("x64 accumulates in int64 — wrap impossible")
    mask = jnp.ones((4,), bool)
    mass = jnp.full((4,), 1 << 30, jnp.int32)    # 2^32 total: wraps
    _, _, overflow, _, _ = banded_frontier(mask, mass, 4, 2, 100, 4)
    assert int(overflow) != 0
    # the sane-mass case on the same shapes stays clean
    _, _, ok_flag, _, _ = banded_frontier(
        mask, jnp.full((4,), 3, jnp.int32), 4, 2, 100, 4)
    assert int(ok_flag) == 0


def test_op_scan_ban_auto_discovers_the_tree():
    """Op-scan regression guard (ISSUE r6, generalized in ISSUE 15):
    n-wide ``jnp.nonzero`` is banned — every compaction goes through
    ops.compaction. The guard used to be a hand-maintained module list
    with per-directory count pins here that every PR had to bump;
    it is now graftlint rule R1 (tools/graftlint, scope ``titan_tpu/``
    + ``bench.py``), which AUTO-DISCOVERS the tree. This test keeps the
    coverage contract explicit: the walk must still reach every
    previously-pinned directory, and the two reference-model
    exemptions (bfs.py, bfs_hybrid_fused.py — not round-loop hot
    paths) must be VISIBLE file-level suppressions, not blind spots."""
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.graftlint.engine import Linter

    result = Linter(root=repo).run(["titan_tpu", "bench.py"])
    assert [f"{f.path}:{f.line}: {f.message}"
            for f in result.unsuppressed
            if f.rule == "opscan"] == []
    # auto-discovery really covered every directory the old pins named
    # (plus anything newer — no count to bump ever again)
    scanned = set(result.files)
    for must in ("titan_tpu/models/frontier.py",
                 "titan_tpu/models/bfs_hybrid.py",
                 "titan_tpu/models/bfs_hybrid_sharded.py",
                 "titan_tpu/ops/epoch_merge.py",
                 "bench.py"):
        assert must in scanned, must
    for pkg in ("titan_tpu/olap/serving/",
                "titan_tpu/olap/serving/interactive/",
                "titan_tpu/olap/recovery/", "titan_tpu/olap/live/",
                "titan_tpu/obs/", "titan_tpu/parallel/",
                # ISSUE 19: the fleet tier joined with zero config
                "titan_tpu/olap/fleet/"):
        assert any(p.startswith(pkg) for p in scanned), pkg
    # the exemptions stay visible: suppressed findings with reasons
    exempt = [f for f in result.findings
              if f.rule == "opscan" and f.suppressed == "file"]
    assert {f.path for f in exempt} == {
        "titan_tpu/models/bfs.py",
        "titan_tpu/models/bfs_hybrid_fused.py"}


def test_op_scan_ban_covers_new_subdirectories_zero_config(tmp_path):
    """The reason the pins died: a brand-new ``titan_tpu/`` subsystem
    directory must be inside the ban the moment it exists, with no
    list to extend and no count to bump."""
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.graftlint.engine import Linter

    pkg = tmp_path / "titan_tpu" / "brand_new_subsystem" / "deeper"
    pkg.mkdir(parents=True)
    (pkg / "kernels.py").write_text(
        "import jax.numpy as jnp\n\n"
        "def scan(mask):\n"
        "    return jnp.nonzero(mask)[0]\n")
    # ISSUE 19 regression: the fleet tier landed as a NEW directory —
    # pin that the walk needs no config change for exactly that shape
    # (a fresh package under an existing olap/ parent)
    fleet = tmp_path / "titan_tpu" / "olap" / "fleet"
    fleet.mkdir(parents=True)
    (fleet / "router.py").write_text(
        "import jax.numpy as jnp\n\n"
        "def pick(mask):\n"
        "    return jnp.nonzero(mask)[0]\n")
    result = Linter(root=str(tmp_path)).run(["titan_tpu"])
    assert len(result.unsuppressed) == 2
    assert {(f.rule, f.path) for f in result.unsuppressed} == {
        ("opscan", "titan_tpu/brand_new_subsystem/deeper/kernels.py"),
        ("opscan", "titan_tpu/olap/fleet/router.py")}


@pytest.mark.parametrize("seed", [3, 11])
def test_sssp_delta_band_plan_differential(seed):
    """The delta-stepping path now runs through the same banded plan as
    quantile/plain (r6 unification) — all three modes must agree with
    each other bit-for-bit on the final distances."""
    from titan_tpu.models.frontier import frontier_sssp
    from titan_tpu.olap.tpu import snapshot as snap_mod

    rng = np.random.default_rng(seed)
    n, m = 180, 700
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    snap = snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    plain, _ = frontier_sssp(snap, source, quantile_mass=0)
    delta, _ = frontier_sssp(snap, source, delta=0.25)
    quant, _ = frontier_sssp(snap, source, quantile_mass=64)
    assert np.asarray(delta) == pytest.approx(np.asarray(plain),
                                              rel=1e-6)
    assert np.asarray(quant) == pytest.approx(np.asarray(plain),
                                              rel=1e-6)
