"""ops/compaction property + contract tests (CPU).

The library's whole value is a CONTRACT: each primitive is bit-equal to
the ``jnp.nonzero(mask, size=cap, fill_value=fill)`` formulation it
replaced in the round loops (ascending survivor order, fill past the
count, overflow truncation), while running at p-scale. These tests pin
that contract against numpy oracles over random masks/bands, check the
cap-overflow and claim-reset behavior the consumers rely on, and scan
the round-loop modules for banned n-wide nonzero calls (the op-scan
regression guard from ISSUE r6 — differential end-to-end coverage of
the refactored BFS/SSSP/WCC consumers lives in test_frontier_models.py
/ test_frontier_bfs.py / test_sharded_bfs.py against independent
oracles)."""

import numpy as np
import pytest

from titan_tpu.ops.compaction import (CLAIM_SENTINEL, banded_frontier,
                                      claim_dedup, claim_reset,
                                      compact_ids, scatter_compact)


def _np_compact(mask, payload, cap, fill):
    """Oracle: the pre-refactor nonzero+gather formulation."""
    idx = np.nonzero(mask)[0][:cap]
    out = np.full((cap,), fill, payload.dtype)
    out[: len(idx)] = payload[idx]
    return out


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("density", [0.0, 0.03, 0.5, 1.0])
def test_scatter_compact_matches_nonzero_oracle(seed, density):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    L = int(rng.integers(1, 3000))
    cap = int(rng.integers(1, 2 * L))
    mask = rng.random(L) < density
    ids = np.arange(L, dtype=np.int32)
    vals = rng.integers(-50, 50, L).astype(np.int32)
    count, (o_ids, o_vals) = scatter_compact(
        jnp.asarray(mask), (jnp.asarray(ids), jnp.asarray(vals)),
        cap, (L, -1))
    assert int(count) == int(mask.sum())       # TOTAL bits, pre-truncation
    assert (np.asarray(o_ids) == _np_compact(mask, ids, cap, L)).all()
    assert (np.asarray(o_vals) == _np_compact(mask, vals, cap, -1)).all()


@pytest.mark.parametrize("seed", range(4))
def test_compact_ids_bit_equal_vs_jnp_nonzero(seed):
    """compact_ids must be indistinguishable from the jnp.nonzero call
    it replaced — same dtype, same order, same fill, same truncation."""
    import jax.numpy as jnp

    rng = np.random.default_rng(100 + seed)
    L = int(rng.integers(1, 2000))
    cap = int(rng.integers(1, L + 10))
    mask = jnp.asarray(rng.random(L) < rng.random())
    ref = jnp.nonzero(mask, size=cap, fill_value=L)[0].astype(jnp.int32)
    count, got = compact_ids(mask, cap, L)
    assert got.dtype == ref.dtype
    assert (np.asarray(got) == np.asarray(ref)).all()
    assert int(count) == int(np.asarray(mask).sum())


def test_scatter_compact_overflow_cap_drops_tail():
    """Survivors past cap are dropped (not wrapped or clamped), and the
    returned count still reports the TRUE total so callers can detect
    the truncation (the _band_plan soundness contract rides on this)."""
    import jax.numpy as jnp

    mask = jnp.ones((10,), bool)
    count, out = compact_ids(mask, 4, 99)
    assert int(count) == 10
    assert np.asarray(out).tolist() == [0, 1, 2, 3]


def test_claim_dedup_single_winner_and_reset_idempotent():
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    n = 64
    lanes = 48
    claim = jnp.full((n + 2,), CLAIM_SENTINEL, jnp.int32)
    # heavy duplication: many lanes race on few keys; pad lanes carry
    # the out-of-band key n+1 (the BFS usage), masked out by validity
    keys_np = rng.integers(0, 8, lanes).astype(np.int32)
    keys_np[rng.random(lanes) < 0.3] = n + 1
    keys = jnp.asarray(keys_np)
    ticket = jnp.arange(lanes, dtype=jnp.int32)
    claim, won = claim_dedup(claim, keys, ticket)
    winner = np.asarray(won) & (keys_np <= n)
    for k in np.unique(keys_np[keys_np <= n]):
        at_k = winner[keys_np == k]
        assert at_k.sum() == 1, f"key {k}: {at_k.sum()} winners"
        # the minimum ticket wins (scatter-min semantics)
        assert at_k[0], f"key {k}: winner is not the min ticket"
    # reset restores the virgin state at every touched position ...
    claim = claim_reset(claim, keys)
    assert (np.asarray(claim) == CLAIM_SENTINEL).all()
    # ... and is idempotent
    claim2 = claim_reset(claim, keys)
    assert (np.asarray(claim2) == np.asarray(claim)).all()
    # a fresh dedup after the reset behaves exactly like the first
    _, won2 = claim_dedup(claim2, keys, ticket)
    assert (np.asarray(won2) == np.asarray(won)).all()


def test_claim_dedup_out_of_range_keys_never_win():
    """An out-of-range key must not report a phantom win via the
    clamped readback gather (the scatter drops it; the winner mask
    must too)."""
    import jax.numpy as jnp

    claim = jnp.full((4,), CLAIM_SENTINEL, jnp.int32)
    #          in-range, OOB high, OOB high matching last slot, negative
    keys = jnp.asarray([3, 100, 4, -7], jnp.int32)
    ticket = jnp.asarray([0, 1, 0, 2], jnp.int32)
    claim, won = claim_dedup(claim, keys, ticket)
    # lane 2 presents ticket 0 == the value lane 0 legitimately wrote
    # to the LAST slot (index 3) — the clamp would read it back equal
    assert np.asarray(won).tolist() == [True, False, False, False]
    assert np.asarray(claim).tolist() == [CLAIM_SENTINEL] * 3 + [0]


@pytest.mark.parametrize("seed", range(3))
def test_banded_frontier_matches_oracle(seed):
    import jax.numpy as jnp

    rng = np.random.default_rng(200 + seed)
    L = int(rng.integers(10, 1500))
    cap = int(rng.integers(4, L + 20))
    k_max = int(rng.integers(1, 12))
    budget = int(rng.integers(1, 300))
    mask = rng.random(L) < rng.random()
    mass = rng.integers(0, 40, L).astype(np.int32)
    nf, m8, overflow, flist, bounds = banded_frontier(
        jnp.asarray(mask), jnp.asarray(mass), cap, k_max, budget, L)
    # oracle: nonzero-compacted list, cumsum + searchsorted bounds
    idx = np.nonzero(mask)[0][:cap]
    ref_list = np.full((cap,), L, np.int32)
    ref_list[: len(idx)] = idx
    ref_mass = np.zeros((cap,), np.int64)
    ref_mass[: len(idx)] = mass[idx]
    cmass = np.cumsum(ref_mass)
    targets = np.arange(1, k_max + 1) * budget
    ref_bounds = np.concatenate(
        [[0], np.minimum(np.searchsorted(cmass, targets, side="right"),
                         cap)])
    assert int(nf) == len(idx)
    assert int(m8) == int(cmass[-1])
    assert int(overflow) == 0
    assert (np.asarray(flist) == ref_list).all()
    assert (np.asarray(bounds) == ref_bounds).all()
    # segment sanity: bounds are monotone list positions
    assert (np.diff(np.asarray(bounds)) >= 0).all()


def test_banded_frontier_flags_int32_mass_overflow():
    """A point-mass band whose listed chunk mass exceeds int32 must be
    DETECTED, not silently wrapped into corrupt segment bounds (ADVICE
    r5 #3). Without x64 the cumsum wraps — the monotonicity break sets
    the overflow flag; the host refuses the round (_frontier_run)."""
    import jax
    import jax.numpy as jnp

    if jax.config.jax_enable_x64:
        pytest.skip("x64 accumulates in int64 — wrap impossible")
    mask = jnp.ones((4,), bool)
    mass = jnp.full((4,), 1 << 30, jnp.int32)    # 2^32 total: wraps
    _, _, overflow, _, _ = banded_frontier(mask, mass, 4, 2, 100, 4)
    assert int(overflow) != 0
    # the sane-mass case on the same shapes stays clean
    _, _, ok_flag, _, _ = banded_frontier(
        mask, jnp.full((4,), 3, jnp.int32), 4, 2, 100, 4)
    assert int(ok_flag) == 0


def test_round_loop_modules_are_nonzero_free():
    """Op-scan regression guard: n-wide ``jnp.nonzero`` is banned inside
    the per-round loops (docs/performance.md) — the round-kernel modules
    must not call it AT ALL; every compaction goes through
    ops.compaction. (bfs.py / bfs_hybrid_fused.py keep theirs: the plain
    reference model and the single-dispatch fused experiment are not
    round-loop hot paths.) The ban extends to the serving layer
    (ISSUE r7): its batched [K, n] round loops — and any future kernel
    code under olap/serving/ — must use the compaction primitives too;
    (ISSUE r8) to olap/recovery/, whose checkpoint callbacks run
    INSIDE the round loops; (ISSUE r9) to olap/live/, whose
    overlay views feed per-round expansion passes; (ISSUE r10) to
    obs/, whose tracing hooks run at every round boundary — since
    ISSUE 10 that includes devprof/flightrec, whose profiler shims and
    ring taps wrap every kernel dispatch; (ISSUE 9) to
    ops/epoch_merge, the device epoch-merge kernel — every survivor
    compaction there must go through ops.compaction; and (ISSUE 11) to
    olap/serving/interactive/, whose hops-mode point queries run the
    same per-level plan/sweep kernels (host-side set extraction uses
    np.flatnonzero, which is not an n-wide device op-scan); and (ISSUE
    13) to titan_tpu/parallel/ — the rebuilt sharding layer's exchange
    primitive and the fused shx_td/shx_bu level kernels compact
    through ops.compaction too, and the rewritten bfs_hybrid_sharded
    stays pinned."""
    import importlib
    import inspect
    import io
    import pkgutil
    import tokenize

    import titan_tpu.obs as obs_pkg
    import titan_tpu.olap.live as live_pkg
    import titan_tpu.olap.recovery as recovery_pkg
    import titan_tpu.olap.serving as serving_pkg
    import titan_tpu.parallel as parallel_pkg
    from titan_tpu.models import bfs_hybrid, bfs_hybrid_sharded, frontier
    from titan_tpu.ops import epoch_merge

    serving_mods = [
        importlib.import_module(f"titan_tpu.olap.serving.{m.name}")
        for m in pkgutil.iter_modules(serving_pkg.__path__)]
    # jobs/pool/hbm/batcher/scheduler + tenants (ISSUE 8) +
    # the interactive subpackage (ISSUE 11) + autotune (ISSUE 14 —
    # the controller's signal reads/knob writes sit beside the round
    # loops, so it rides the same ban)
    assert len(serving_mods) >= 8
    # the interactive lane (ISSUE 11) compiles point queries onto the
    # batched round kernels — its compiler/collector/lane modules are
    # in the ban too
    import titan_tpu.olap.serving.interactive as interactive_pkg
    interactive_mods = [
        importlib.import_module(
            f"titan_tpu.olap.serving.interactive.{m.name}")
        for m in pkgutil.iter_modules(interactive_pkg.__path__)]
    assert len(interactive_mods) >= 3   # compile/collector/scheduler
    recovery_mods = [
        importlib.import_module(f"titan_tpu.olap.recovery.{m.name}")
        for m in pkgutil.iter_modules(recovery_pkg.__path__)]
    assert len(recovery_mods) >= 3  # store/checkpoint/faults
    live_mods = [
        importlib.import_module(f"titan_tpu.olap.live.{m.name}")
        for m in pkgutil.iter_modules(live_pkg.__path__)]
    assert len(live_mods) >= 4      # feed/overlay/compactor/plane
    obs_mods = [
        importlib.import_module(f"titan_tpu.obs.{m.name}")
        for m in pkgutil.iter_modules(obs_pkg.__path__)]
    # tracing/promexport + slo (ISSUE 8) + devprof/flightrec (ISSUE 10)
    assert len(obs_mods) >= 5
    parallel_mods = [
        importlib.import_module(f"titan_tpu.parallel.{m.name}")
        for m in pkgutil.iter_modules(parallel_pkg.__path__)]
    # mesh/partition/multihost (ISSUE 13: the sharding layer)
    assert len(parallel_mods) >= 3

    for mod in (frontier, bfs_hybrid, bfs_hybrid_sharded, epoch_merge,
                *serving_mods, *interactive_mods, *recovery_mods,
                *live_mods, *obs_mods, *parallel_mods):
        src = inspect.getsource(mod)
        calls = [
            (tok.start[0], line)
            for tok, line in (
                (t, t.line) for t in tokenize.generate_tokens(
                    io.StringIO(src).readline)
                if t.type == tokenize.NAME and t.string == "nonzero")
        ]
        assert not calls, (
            f"{mod.__name__} reintroduced a nonzero call "
            f"(banned in round loops — use ops.compaction): {calls}")


@pytest.mark.parametrize("seed", [3, 11])
def test_sssp_delta_band_plan_differential(seed):
    """The delta-stepping path now runs through the same banded plan as
    quantile/plain (r6 unification) — all three modes must agree with
    each other bit-for-bit on the final distances."""
    from titan_tpu.models.frontier import frontier_sssp
    from titan_tpu.olap.tpu import snapshot as snap_mod

    rng = np.random.default_rng(seed)
    n, m = 180, 700
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    snap = snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    plain, _ = frontier_sssp(snap, source, quantile_mass=0)
    delta, _ = frontier_sssp(snap, source, delta=0.25)
    quant, _ = frontier_sssp(snap, source, quantile_mass=64)
    assert np.asarray(delta) == pytest.approx(np.asarray(plain),
                                              rel=1e-6)
    assert np.asarray(quant) == pytest.approx(np.asarray(plain),
                                              rel=1e-6)
