"""SLO engine (obs/slo): declarative objectives → error-budget burn.

Hand-computed fixtures with an injected clock (ISSUE 8 acceptance):
the engine reads the labeled metric children the scheduler writes —
here populated directly — and its multi-window burn rates must equal
the arithmetic done by hand below. No sleeps, no scheduler, no jax.
"""

import pytest

from titan_tpu.obs.promexport import render_prometheus
from titan_tpu.obs.slo import (DEFAULT_WINDOWS, P95_BUDGET, SLO,
                               SLOEngine)
from titan_tpu.utils.metrics import MetricManager


class FakeClock:
    def __init__(self, t0: float = 1000.0):
        self.t = t0

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> float:
        self.t += dt
        return self.t


def test_slo_declaration_validation():
    with pytest.raises(ValueError, match="exactly one"):
        SLO("both", p95_ms=5.0, success_rate=0.99)
    with pytest.raises(ValueError, match="exactly one"):
        SLO("neither")
    with pytest.raises(ValueError, match="success_rate"):
        SLO("bad-rate", success_rate=1.0)
    with pytest.raises(ValueError, match="window"):
        SLO("no-windows", p95_ms=5.0, windows=())
    with pytest.raises(ValueError, match="duplicate"):
        SLOEngine(MetricManager(), [SLO("x", p95_ms=1.0),
                                    SLO("x", success_rate=0.5)])
    s = SLO("sel", tenant="a", algorithm="bfs", p95_ms=5.0)
    assert s.selector == {"tenant": "a", "kind": "bfs"}
    assert s.budget == P95_BUDGET
    assert SLO("r", success_rate=0.999).budget == pytest.approx(0.001)
    assert SLO("d", p95_ms=1.0).windows == DEFAULT_WINDOWS


def test_success_rate_burn_hand_computed_fixture():
    """Two evaluation points 300s apart; tenant 'a' with a 99.9%%
    success objective sees 100 events and 3 failures in the window:

        error_rate = 3/100 = 0.03; budget = 0.001
        burn(300s) = 0.03 / 0.001 = 30.0
    """
    m = MetricManager()
    clk = FakeClock()
    slo = SLO("a-avail", tenant="a", success_rate=0.999,
              windows=(300.0,))
    eng = SLOEngine(m, [slo], clock=clk)

    def done(tenant, n):
        m.counter("serving.jobs.completed",
                  labels={"kind": "bfs", "tenant": tenant}).inc(n)

    def failed(tenant, n):
        m.counter("serving.jobs.failed",
                  labels={"kind": "bfs", "tenant": tenant}).inc(n)

    done("a", 50)                        # pre-window history
    eng.evaluate()                       # baseline point at t=1000
    clk.tick(300.0)
    done("a", 97)
    failed("a", 3)
    failed("b", 40)                      # another tenant: invisible
    rep = eng.evaluate()
    (s,) = rep["slos"]
    assert s["tenant"] == "a"
    w = s["windows"]["300s"]
    assert w["events"] == 100
    assert w["bad"] == pytest.approx(3.0)
    assert w["burn_rate"] == pytest.approx(30.0)
    # cumulative SLI: 147 good / 150 total
    assert s["sli"]["events"] == 150
    assert s["sli"]["success_rate"] == pytest.approx(147 / 150)
    assert s["sli"]["ok"] is False


def test_success_rate_window_past_history_reads_zero_baseline():
    """A window reaching past recorded history treats counts as having
    started at zero — correct for a process younger than the window."""
    m = MetricManager()
    clk = FakeClock()
    eng = SLOEngine(m, [SLO("all", success_rate=0.99,
                            windows=(300.0, 3600.0))], clock=clk)
    m.counter("serving.jobs.completed",
              labels={"kind": "bfs", "tenant": "a"}).inc(9)
    m.counter("serving.jobs.timeout",
              labels={"kind": "bfs", "tenant": "a"}).inc(1)
    rep = eng.evaluate()
    (s,) = rep["slos"]
    # both windows: 10 events, 1 bad, budget 0.01 → burn 10
    for wk in ("300s", "3600s"):
        assert s["windows"][wk]["burn_rate"] == pytest.approx(10.0)
    # an idle objective is never in breach
    idle = SLOEngine(m, [SLO("idle", tenant="nobody",
                             success_rate=0.99)], clock=clk)
    (si,) = idle.evaluate()["slos"]
    assert si["sli"]["ok"] is True
    assert si["sli"]["success_rate"] is None
    assert si["windows"]["300s"]["burn_rate"] == 0.0


def test_p95_latency_burn_hand_computed_fixture():
    """p95 objective at 50ms over 20 samples, 4 over the threshold:

        over-fraction = 4/20 = 0.20; budget = 0.05 (by p95 definition)
        burn = 0.20 / 0.05 = 4.0;  pooled p95 (nearest-rank) = 60.0
    """
    m = MetricManager()
    clk = FakeClock()
    eng = SLOEngine(m, [SLO("lat", tenant="a", p95_ms=50.0,
                            windows=(300.0,))], clock=clk)
    h = m.histogram("serving.job.latency_ms",
                    labels={"kind": "bfs", "tenant": "a"})
    for v in [10.0] * 16 + [60.0] * 4:
        h.update(v)
    m.histogram("serving.job.latency_ms",
                labels={"kind": "bfs", "tenant": "b"}).update(9999.0)
    rep = eng.evaluate()
    (s,) = rep["slos"]
    w = s["windows"]["300s"]
    assert w["events"] == 20
    assert w["bad"] == pytest.approx(4.0)
    assert w["burn_rate"] == pytest.approx(4.0)
    assert s["sli"]["p95_ms"] == pytest.approx(60.0)
    assert s["sli"]["ok"] is False
    # within-objective tenant: zero burn, ok
    ok = SLOEngine(m, [SLO("ok", tenant="a", p95_ms=100.0,
                           windows=(300.0,))], clock=clk)
    (so,) = ok.evaluate()["slos"]
    assert so["windows"]["300s"]["burn_rate"] == 0.0
    assert so["sli"]["ok"] is True


def test_windowed_burn_decays_after_quiet_period():
    """Errors age out: a burst inside one window stops burning once the
    window slides past it (multi-point ring arithmetic)."""
    m = MetricManager()
    clk = FakeClock()
    eng = SLOEngine(m, [SLO("a", tenant="a", success_rate=0.99,
                            windows=(300.0,))], clock=clk)
    c_done = m.counter("serving.jobs.completed",
                       labels={"kind": "bfs", "tenant": "a"})
    c_fail = m.counter("serving.jobs.failed",
                       labels={"kind": "bfs", "tenant": "a"})
    eng.evaluate()                       # t=1000 baseline
    clk.tick(150.0)
    c_done.inc(8)
    c_fail.inc(2)                        # burst
    (s,) = eng.evaluate()["slos"]        # t=1150
    assert s["windows"]["300s"]["burn_rate"] == pytest.approx(20.0)
    clk.tick(150.0)
    c_done.inc(10)                       # quiet recovery
    (s,) = eng.evaluate()["slos"]        # t=1300: burst still in window
    assert s["windows"]["300s"]["burn_rate"] == pytest.approx(10.0)
    clk.tick(200.0)
    c_done.inc(10)
    (s,) = eng.evaluate()["slos"]        # t=1500: window starts at 1200
    assert s["windows"]["300s"]["burn_rate"] == 0.0


def test_register_gauges_exports_labeled_burn_rates():
    m = MetricManager()
    clk = FakeClock()
    eng = SLOEngine(m, [SLO("a-avail", tenant="a", success_rate=0.99,
                            windows=(300.0,))], clock=clk,
                    min_record_s=0.0)
    eng.register_gauges()
    m.counter("serving.jobs.completed",
              labels={"kind": "bfs", "tenant": "a"}).inc(9)
    m.counter("serving.jobs.failed",
              labels={"kind": "bfs", "tenant": "a"}).inc(1)
    # the scrape callback drives evaluation (Prometheus as the sampler)
    assert m.gauge_value("serving.slo.burn_rate",
                         labels={"slo": "a-avail",
                                 "window": "300s"}) == pytest.approx(
        10.0)
    text = render_prometheus(m)
    assert "# TYPE serving_slo_burn_rate gauge" in text
    (line,) = [ln for ln in text.splitlines()
               if ln.startswith('serving_slo_burn_rate{')]
    assert line.startswith('serving_slo_burn_rate{slo="a-avail",'
                           'window="300s"} ')
    assert float(line.rsplit(" ", 1)[1]) == pytest.approx(10.0)


def test_latency_burn_clamped_when_reservoir_estimate_shrinks():
    """The latency SLI's cumulative bad count is a reservoir ESTIMATE
    (count x over-fraction) that can shrink once the reservoir
    overflows — the windowed delta clamps at zero rather than
    exporting a negative burn rate."""
    m = MetricManager()
    clk = FakeClock()
    eng = SLOEngine(m, [SLO("lat", tenant="a", p95_ms=50.0,
                            windows=(300.0,))], clock=clk)
    h = m.histogram("serving.job.latency_ms",
                    labels={"kind": "bfs", "tenant": "a"},
                    )
    # tiny reservoir via direct child access: overflow deterministically
    h.child._max = 4
    for v in (60.0, 60.0, 60.0, 60.0):    # all bad → frac 1.0
        h.update(v)
    eng.evaluate()                         # baseline: bad = 4
    clk.tick(100.0)
    # displace the reservoir with good samples: count grows but the
    # over-fraction (and so the estimated cumulative bad) drops
    for _ in range(64):
        h.update(1.0)
    (s,) = eng.evaluate()["slos"]
    w = s["windows"]["300s"]
    assert w["bad"] >= 0.0, w
    assert w["burn_rate"] >= 0.0, w


def test_window_keys_do_not_collide_on_fractional_windows():
    """Distinct windows differing below one second must keep distinct
    report keys / gauge labels — int-truncation would silently drop
    one of them from GET /slo and overwrite its gauge."""
    m = MetricManager()
    clk = FakeClock()
    eng = SLOEngine(m, [SLO("frac", tenant="a", success_rate=0.9,
                            windows=(60.4, 60.9))], clock=clk)
    (s,) = eng.evaluate()["slos"]
    assert set(s["windows"]) == {"60.4s", "60.9s"}
    eng.register_gauges()
    fams = {tuple(sorted(lbls.items()))
            for lbls, _v in m.gauge_snapshot()
            ["serving.slo.burn_rate"]["children"]}
    assert (("slo", "frac"), ("window", "60.4s")) in fams
    assert (("slo", "frac"), ("window", "60.9s")) in fams
    # integral windows keep their historical short form
    assert set(SLOEngine(
        m, [SLO("int", success_rate=0.9, windows=(300.0,))],
        clock=clk).evaluate()["slos"][0]["windows"]) == {"300s"}


def test_detach_gauges_neutralizes_only_own_callbacks():
    """A closed scheduler's engine must stop evaluating on scrapes:
    detach zeroes ITS burn-rate gauges, while a successor engine that
    re-registered over the same labels keeps its own callbacks."""
    m = MetricManager()
    clk = FakeClock()
    slos = [SLO("a-avail", tenant="a", success_rate=0.9,
                windows=(300.0,))]
    old = SLOEngine(m, slos, clock=clk)
    old.register_gauges()
    m.counter("serving.jobs.failed",
              labels={"tenant": "a", "kind": "bfs"}).inc(5)
    assert m.gauge_value("serving.slo.burn_rate",
                         labels={"slo": "a-avail",
                                 "window": "300s"}) > 0
    old.detach_gauges()
    assert m.gauge_value("serving.slo.burn_rate",
                         labels={"slo": "a-avail",
                                 "window": "300s"}) == 0.0
    # successor takes over the same labels; the old engine's detach
    # (idempotent) must not clobber it
    new = SLOEngine(m, slos, clock=clk)
    new.register_gauges()
    old.detach_gauges()
    assert m.gauge_value("serving.slo.burn_rate",
                         labels={"slo": "a-avail",
                                 "window": "300s"}) > 0
