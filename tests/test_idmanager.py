"""ID bit-packing tests (semantics modeled on the reference's IDManagementTest)."""

import random

import numpy as np
import pytest

from titan_tpu.errors import InvalidIDError
from titan_tpu.ids import IDManager, IDType


@pytest.fixture(params=[0, 1, 5, 10])
def idm(request):
    return IDManager(partition_bits=request.param)


def test_vertex_roundtrip(idm):
    rng = random.Random(1)
    for _ in range(1000):
        count = rng.randint(1, idm.max_count)
        partition = rng.randrange(idm.num_partitions)
        for t in (IDType.NORMAL_VERTEX, IDType.PARTITIONED_VERTEX,
                  IDType.UNMODIFIABLE_VERTEX):
            eid = idm.vertex_id(count, partition, t)
            assert eid > 0
            assert idm.count(eid) == count
            assert idm.partition(eid) == partition
            assert idm.id_type(eid) is t
            assert idm.is_user_vertex_id(eid)
            assert not idm.is_schema_id(eid)


def test_schema_ids(idm):
    for t in (IDType.USER_PROPERTY_KEY, IDType.SYSTEM_PROPERTY_KEY,
              IDType.USER_EDGE_LABEL, IDType.SYSTEM_EDGE_LABEL,
              IDType.VERTEX_LABEL, IDType.GENERIC_SCHEMA):
        eid = idm.schema_id(t, 42)
        assert idm.is_schema_id(eid)
        assert not idm.is_user_vertex_id(eid)
        assert idm.partition(eid) == 0
        assert idm.count(eid) == 42
        assert idm.id_type(eid) is t
    with pytest.raises(InvalidIDError):
        idm.schema_id(IDType.NORMAL_VERTEX, 1)


def test_bounds(idm):
    with pytest.raises(InvalidIDError):
        idm.vertex_id(0, 0)  # count must be positive
    with pytest.raises(InvalidIDError):
        idm.vertex_id(idm.max_count + 1, 0)
    with pytest.raises(InvalidIDError):
        idm.vertex_id(1, idm.num_partitions)
    # relation ids: bare counters
    assert idm.relation_id(1) == 1
    with pytest.raises(InvalidIDError):
        idm.relation_id(0)


def test_key_mapping_roundtrip(idm):
    rng = random.Random(2)
    for _ in range(1000):
        eid = idm.vertex_id(rng.randint(1, idm.max_count),
                            rng.randrange(idm.num_partitions))
        key = idm.key_of(eid)
        assert idm.id_of_key(key) == eid
        assert idm.id_of_key_bytes(idm.key_bytes(eid)) == eid


def test_key_ordering_groups_partitions():
    idm = IDManager(partition_bits=4)
    rng = random.Random(3)
    ids = [idm.vertex_id(rng.randint(1, 1 << 30), rng.randrange(16))
           for _ in range(500)]
    keyed = sorted(ids, key=idm.key_bytes)
    partitions = [idm.partition(e) for e in keyed]
    assert partitions == sorted(partitions)  # contiguous partition runs


def test_partition_key_range():
    idm = IDManager(partition_bits=3)
    for p in range(8):
        lo, hi = idm.partition_key_range(p)
        for _ in range(50):
            eid = idm.vertex_id(random.randint(1, idm.max_count), p)
            assert lo <= idm.key_bytes(eid) < hi


def test_partitioned_vertex_representatives():
    idm = IDManager(partition_bits=3)
    eid = idm.partitioned_vertex_id(77, 2)
    reps = idm.partitioned_vertex_representatives(eid)
    assert len(reps) == 8
    assert len(set(reps)) == 8
    assert all(idm.count(r) == 77 for r in reps)
    assert sorted(idm.partition(r) for r in reps) == list(range(8))
    canon = idm.canonical_vertex_id(eid)
    assert canon in reps
    # canonical is stable across representatives
    assert all(idm.canonical_vertex_id(r) == canon for r in reps)
    # ordinary vertices are their own canonical
    v = idm.vertex_id(5, 3)
    assert idm.canonical_vertex_id(v) == v
    with pytest.raises(InvalidIDError):
        idm.partitioned_vertex_representatives(v)


def test_vectorized_matches_scalar():
    idm = IDManager(partition_bits=6)
    rng = random.Random(5)
    ids = np.array([idm.vertex_id(rng.randint(1, 1 << 40), rng.randrange(64))
                    for _ in range(2000)], dtype=np.int64)
    assert (idm.partitions_np(ids) == [idm.partition(int(e)) for e in ids]).all()
    assert (idm.counts_np(ids) == [idm.count(int(e)) for e in ids]).all()
    assert (idm.types_np(ids) == [int(idm.id_type(int(e))) for e in ids]).all()
    assert (idm.keys_np(ids) == [idm.key_of(int(e)) for e in ids]).all()
