"""Attribute-serializer and edge-codec tests (semantics modeled on the
reference's SerializerTest / EdgeSerializerTest)."""

import datetime as dt
import random
import uuid

import pytest

from titan_tpu.codec.attributes import Serializer
from titan_tpu.codec.dataio import DataOutput, ReadBuffer
from titan_tpu.codec.edges import EdgeCodec
from titan_tpu.codec import relation_ids as rids
from titan_tpu.core.defs import Cardinality, Direction, Multiplicity, RelationCategory
from titan_tpu.ids import IDManager, IDType

S = Serializer()
IDM = IDManager(partition_bits=4)


# ---------------------------------------------------------------------------
# attributes
# ---------------------------------------------------------------------------

import decimal

VALUES = [True, False, 0, 1, -1, 2**40, -(2**40), 3.14159, -2.5e-300, "héllo",
          "", "a\x00b", b"", b"\x00\xff\x00", uuid.uuid4(),
          dt.datetime(2026, 7, 29, tzinfo=dt.timezone.utc),
          [1, "two", 3.0], {"k": [1, 2], 3: None}, None,
          decimal.Decimal("123.456789012345678901234567890"),
          dt.date(1969, 7, 20), dt.time(13, 37, 59, 123456),
          dt.timedelta(days=-3, seconds=7, microseconds=13),
          (1, "two", 3.0), {1, "a"}, frozenset({2.5, "b"})]


def test_self_describing_roundtrip():
    for v in VALUES:
        got = S.value_from_bytes(S.value_bytes(v))
        assert got == v and type(got) is type(v)


def test_new_dtypes_as_graph_properties():
    import titan_tpu
    g = titan_tpu.open("inmemory")
    tx = g.new_transaction()
    v = tx.add_vertex("order",
                      total=decimal.Decimal("19.99"),
                      placed=dt.date(2026, 7, 30),
                      eta=dt.timedelta(days=2),
                      tags=frozenset({"rush", "gift"}))
    vid = v.id
    tx.commit()
    tx2 = g.new_transaction()
    got = tx2.vertex(vid)
    assert got.value("total") == decimal.Decimal("19.99")
    assert got.value("placed") == dt.date(2026, 7, 30)
    assert got.value("eta") == dt.timedelta(days=2)
    assert got.value("tags") == frozenset({"rush", "gift"})
    tx2.rollback()
    g.close()


def test_date_rejects_datetime_and_timedelta_rejects_overflow():
    import pytest
    with pytest.raises(TypeError):
        S.ordered_bytes(dt.datetime(2026, 7, 30, 12, 0), dt.date)
    with pytest.raises(ValueError):
        S.ordered_bytes(dt.timedelta(days=200_000_000), dt.timedelta)
    with pytest.raises(ValueError):
        S.value_bytes(dt.timedelta(days=200_000_000))


def test_ordered_date_and_timedelta():
    dates = [dt.date(1, 1, 1), dt.date(1969, 7, 20), dt.date(2026, 7, 30),
             dt.date(9999, 12, 31)]
    deltas = [dt.timedelta(days=-5), dt.timedelta(0),
              dt.timedelta(microseconds=1), dt.timedelta(days=400)]
    for vals, t in [(dates, dt.date), (deltas, dt.timedelta)]:
        enc = sorted((S.ordered_bytes(v, t), v) for v in vals)
        assert [v for _, v in enc] == sorted(vals)
        for b, v in enc:
            assert S.read_ordered(ReadBuffer(b), t) == v


def test_ordered_roundtrip_and_order():
    rng = random.Random(1)
    ints = [rng.randint(-2**62, 2**62) for _ in range(300)] + [0, 1, -1]
    floats = [rng.uniform(-1e300, 1e300) for _ in range(300)] + [0.0, -0.0, 1.5]
    strs = ["", "a", "ab", "a\x00b", "b", "ba", "ábc"] + \
           ["".join(rng.choices("ab\x00cdé", k=rng.randint(0, 8))) for _ in range(200)]
    for vals, t in [(ints, int), (floats, float), (strs, str)]:
        encoded = [(S.ordered_bytes(v, t), v) for v in vals]
        # roundtrip
        for b, v in encoded:
            got = S.read_ordered(ReadBuffer(b), t)
            assert got == v or (t is float and got == v)  # -0.0 == 0.0 ok
        # byte order == value order
        encoded.sort()
        plain = [v for _, v in encoded]
        assert plain == sorted(plain)


def test_ordered_strings_prefix_free():
    # "a" must not be a byte-prefix of "ab"'s encoding (else slice bounds leak)
    a = S.ordered_bytes("a", str)
    ab = S.ordered_bytes("ab", str)
    assert not ab.startswith(a)


# ---------------------------------------------------------------------------
# fake schema for the edge codec
# ---------------------------------------------------------------------------

class FakeSchema:
    def __init__(self):
        self.keys = {}    # id -> (dtype, cardinality)
        self.labels = {}  # id -> (multiplicity, sort_key tuple)

    def add_key(self, count, dtype, card=Cardinality.SINGLE):
        kid = IDM.schema_id(IDType.USER_PROPERTY_KEY, count)
        self.keys[kid] = (dtype, card)
        return kid

    def add_label(self, count, mult=Multiplicity.MULTI, sort_key=()):
        lid = IDM.schema_id(IDType.USER_EDGE_LABEL, count)
        self.labels[lid] = (mult, tuple(sort_key))
        return lid

    def is_edge_label(self, tid):
        return tid in self.labels

    def data_type(self, kid):
        return self.keys[kid][0]

    def cardinality(self, kid):
        return self.keys[kid][1]

    def multiplicity(self, lid):
        return self.labels[lid][0]

    def sort_key(self, lid):
        return self.labels[lid][1]


@pytest.fixture
def schema():
    return FakeSchema()


@pytest.fixture
def codec():
    return EdgeCodec(S, IDM)


def test_property_roundtrip_all_cardinalities(codec, schema):
    for card in Cardinality:
        kid = schema.add_key({Cardinality.SINGLE: 1, Cardinality.SET: 2,
                              Cardinality.LIST: 3}[card], str, card)
        e = codec.write_property(kid, relation_id=77, value="val", inspector=schema)
        rc = codec.parse(e, schema)
        assert rc.category is RelationCategory.PROPERTY
        assert rc.type_id == kid and rc.relation_id == 77 and rc.value == "val"


def test_single_property_column_collision(codec, schema):
    kid = schema.add_key(1, int, Cardinality.SINGLE)
    e1 = codec.write_property(kid, 1, 10, schema)
    e2 = codec.write_property(kid, 2, 20, schema)
    assert e1.column == e2.column  # SINGLE: same column → overwrite semantics


def test_set_property_distinct_columns_by_value(codec, schema):
    kid = schema.add_key(2, str, Cardinality.SET)
    e1 = codec.write_property(kid, 1, "x", schema)
    e2 = codec.write_property(kid, 2, "y", schema)
    e3 = codec.write_property(kid, 3, "x", schema)
    assert e1.column != e2.column
    assert e1.column == e3.column  # same value → same column → set semantics


def test_list_property_distinct_columns_by_relid(codec, schema):
    kid = schema.add_key(3, str, Cardinality.LIST)
    e1 = codec.write_property(kid, 1, "x", schema)
    e2 = codec.write_property(kid, 2, "x", schema)
    assert e1.column != e2.column  # duplicates allowed


def test_edge_roundtrip_multi_with_props(codec, schema):
    w = schema.add_key(5, float)
    lid = schema.add_label(1, Multiplicity.MULTI)
    for d in (Direction.OUT, Direction.IN):
        e = codec.write_edge(lid, 99, d, other_vertex_id=IDM.vertex_id(7, 3),
                             inspector=schema, properties={w: 0.5})
        rc = codec.parse(e, schema)
        assert rc.is_edge and rc.direction is d
        assert rc.type_id == lid and rc.relation_id == 99
        assert rc.other_vertex_id == IDM.vertex_id(7, 3)
        assert rc.properties == {w: 0.5}


def test_edge_sort_key_ordering(codec, schema):
    t = schema.add_key(6, int)
    lid = schema.add_label(2, Multiplicity.MULTI, sort_key=(t,))
    entries = []
    for i, time in enumerate([50, 10, 30, 20, 40]):
        e = codec.write_edge(lid, 100 + i, Direction.OUT,
                             IDM.vertex_id(1 + i, 0), schema, {t: time})
        entries.append((e, time))
    entries.sort(key=lambda p: p[0].column)
    assert [time for _, time in entries] == [10, 20, 30, 40, 50]
    # parsed sort-key value comes back from the column
    rc = codec.parse(entries[0][0], schema)
    assert rc.properties[t] == 10


def test_edge_unique_direction_column_collision(codec, schema):
    lid = schema.add_label(3, Multiplicity.MANY2ONE)
    e1 = codec.write_edge(lid, 1, Direction.OUT, IDM.vertex_id(5, 0), schema)
    e2 = codec.write_edge(lid, 2, Direction.OUT, IDM.vertex_id(6, 0), schema)
    assert e1.column == e2.column  # one OUT edge per vertex → overwrite/conflict
    e3 = codec.write_edge(lid, 1, Direction.IN, IDM.vertex_id(5, 0), schema)
    e4 = codec.write_edge(lid, 2, Direction.IN, IDM.vertex_id(6, 0), schema)
    assert e3.column != e4.column  # IN side distinguishes by other vertex
    rc = codec.parse(e1, schema)
    assert rc.other_vertex_id == IDM.vertex_id(5, 0) and rc.relation_id == 1


def test_simple_multiplicity_dedups_parallel_edges(codec, schema):
    lid = schema.add_label(4, Multiplicity.SIMPLE)
    a, b = IDM.vertex_id(1, 0), IDM.vertex_id(2, 0)
    e1 = codec.write_edge(lid, 1, Direction.OUT, b, schema)
    e2 = codec.write_edge(lid, 2, Direction.OUT, b, schema)
    assert e1.column == e2.column  # same endpoints → same column
    e3 = codec.write_edge(lid, 3, Direction.OUT, IDM.vertex_id(3, 0), schema)
    assert e3.column != e1.column


def test_type_slice_isolates_one_type(codec, schema):
    lid1 = schema.add_label(10, Multiplicity.MULTI)
    lid2 = schema.add_label(11, Multiplicity.MULTI)
    kid = schema.add_key(12, str)
    entries = []
    for i in range(5):
        entries.append(("l1", codec.write_edge(lid1, i + 1, Direction.OUT,
                                               IDM.vertex_id(i + 1, 0), schema)))
        entries.append(("l2", codec.write_edge(lid2, i + 10, Direction.OUT,
                                               IDM.vertex_id(i + 1, 0), schema)))
        entries.append(("p", codec.write_property(kid, i + 20, f"v{i}", schema)))
    entries.sort(key=lambda p: p[1].column)
    [q] = codec.query_type(lid1, Direction.OUT, schema)
    hit = [tag for tag, e in entries if q.start <= e.column < q.end]
    assert hit == ["l1"] * 5
    # direction BOTH yields two slices; IN slice is empty here
    qs = codec.query_type(lid1, Direction.BOTH, schema)
    assert len(qs) == 2
    hit_in = [tag for tag, e in entries
              if qs[1].start <= e.column < qs[1].end]
    assert hit_in == []


def test_category_slice_groups_properties_vs_edges(codec, schema):
    lid = schema.add_label(10, Multiplicity.MULTI)
    kid = schema.add_key(12, str)
    pe = codec.write_property(kid, 1, "v", schema)
    ee = codec.write_edge(lid, 2, Direction.OUT, IDM.vertex_id(1, 0), schema)
    qp = codec.query_category(RelationCategory.PROPERTY)
    qe = codec.query_category(RelationCategory.EDGE, Direction.OUT,
                              include_system=False)
    assert qp.contains(pe.column) and not qe.contains(pe.column)
    assert qe.contains(ee.column)


def test_sort_key_interval_query(codec, schema):
    t = schema.add_key(6, int)
    lid = schema.add_label(2, Multiplicity.MULTI, sort_key=(t,))
    entries = []
    for i, time in enumerate(range(0, 100, 10)):
        e = codec.write_edge(lid, 100 + i, Direction.OUT,
                             IDM.vertex_id(1 + i, 0), schema, {t: time})
        entries.append((time, e))
    [q] = codec.query_type(lid, Direction.OUT, schema,
                           sort_start=[30], sort_end=[70])
    hits = sorted(time for time, e in entries if q.contains(e.column))
    assert hits == [30, 40, 50, 60]


def test_property_meta_roundtrip_all_cardinalities(codec, schema):
    """Meta-properties ride the value as an optional trailing section for
    every cardinality; rows written without meta keep the legacy layout
    byte-for-byte and both layouts parse."""
    mk = schema.add_key(9, int)
    mk2 = schema.add_key(10, str)
    for card, count in [(Cardinality.SINGLE, 11), (Cardinality.SET, 12),
                        (Cardinality.LIST, 13)]:
        kid = schema.add_key(count, str, card)
        plain = codec.write_property(kid, 77, "val", schema)
        withmeta = codec.write_property(kid, 77, "val", schema,
                                        properties={mk: 42, mk2: "m"})
        # legacy layout untouched when no meta is present
        assert plain == codec.write_property(kid, 77, "val", schema,
                                             properties={})
        for entry, want in [(plain, {}), (withmeta, {mk: 42, mk2: "m"})]:
            rc = codec.parse(entry, schema)
            assert rc.relation_id == 77 and rc.value == "val"
            assert rc.properties == want, card
        # the meta section must precede the backward relation id: a parser
        # that peels the relid first still sees the right id
        assert codec.parse(withmeta, schema).relation_id == 77


def test_ndarray_attribute_roundtrip():
    import numpy as np
    for a in (np.arange(12, dtype=np.float32).reshape(3, 4),
              np.array([1, 2, 3], dtype=np.int64),
              np.zeros((0,), dtype=np.int8),
              np.array([[True, False]], dtype=bool)):
        out = DataOutput()
        S.write_value(out, a)
        back = S.read_value(ReadBuffer(out.getvalue()))
        assert back.dtype == a.dtype and back.shape == a.shape
        assert np.array_equal(back, a)


def test_enum_deserialization_never_imports():
    """Stored bytes must not trigger module imports (module-level code
    execution); only already-imported modules resolve."""
    import enum
    import sys

    from titan_tpu.codec.attributes import Serializer
    s = Serializer()
    from titan_tpu.core.defs import Cardinality
    data = s.value_bytes(Cardinality.SET)
    assert s.value_from_bytes(data) is Cardinality.SET   # first-party: ok
    # forge a member of a never-imported stdlib module (imports on load!)
    victim = "antigravity"
    assert victim not in sys.modules

    class _Fake(enum.Enum):
        X = 1
    _Fake.__module__ = victim
    _Fake.__qualname__ = "X"
    try:
        data2 = s.value_bytes(_Fake.X)
    except TypeError:
        data2 = None                # writer refused: equally safe
    if data2 is not None:
        with pytest.raises(TypeError, match="not.*imported|not importable"):
            s.value_from_bytes(data2)
        assert victim not in sys.modules


def test_bulk_parse_out_matches_python_parser(tmp_path):
    """The native bulk OUT-edge decode in multi_vertex_edges (cold-path
    fast lane) must agree exactly with the per-entry Python parser —
    including falling back for property-bearing edges, sort-key labels,
    and non-MULTI multiplicities."""
    import numpy as np

    import titan_tpu
    from titan_tpu import native
    from titan_tpu.core.defs import Direction

    if not native.available:
        import pytest
        pytest.skip("native codec not built")
    g = titan_tpu.open("inmemory")
    mgmt = g.management()
    since = mgmt.make_property_key("since", int)
    mgmt.make_edge_label("knows")                      # MULTI, no sort key
    mgmt.make_edge_label("follows", sort_key=[since.id])  # sort-key label
    from titan_tpu.core.defs import Multiplicity
    mgmt.make_edge_label("mother", multiplicity=Multiplicity.MANY2ONE)
    mgmt.commit()
    rng = np.random.default_rng(3)
    tx = g.new_transaction()
    vs = [tx.add_vertex("person", name=f"p{i}") for i in range(40)]
    for _ in range(700):                 # >256 so the bulk path engages
        a, b = rng.integers(0, 40, 2)
        if a != b:
            vs[int(a)].add_edge("knows", vs[int(b)])
    for i in range(30):                  # props -> per-entry fallback
        vs[i].add_edge("knows", vs[(i + 1) % 40], since=i)
        vs[i].add_edge("follows", vs[(i + 2) % 40], since=i)
    for i in range(10):
        vs[i].add_edge("mother", vs[39])
    tx.commit()

    tx = g.new_transaction()
    vids = [v.id for v in tx.vertices()]
    got = tx.multi_vertex_edges(vids, Direction.OUT)
    # force the pure-Python path by disabling native
    tx2 = g.new_transaction()
    import titan_tpu.core.tx as tx_mod
    native_avail = native.available
    try:
        native.available = False
        want = tx2.multi_vertex_edges(vids, Direction.OUT)
    finally:
        native.available = native_avail

    def norm(edges):
        return sorted((e.rel.relation_id, e.label(), e.out_vertex().id,
                       e.in_vertex().id, e.value("since")) for e in edges)
    for vid in vids:
        assert norm(got[vid]) == norm(want[vid]), vid
    g.close()
