"""bench.py Report contract: the headline is a one-shot latch.

VERDICT r5 weak #1: BENCH_r05's driver-parsed metric line read
``gods_2hop_p50_ms`` because a later stage's ``rep.headline(...)`` call
overwrote the scale-26 BFS TEPS headline. The latch makes the metric
line OWNED by whichever stage sets it first — the headline BFS stage,
which main() orders first and never budget-skips.
"""

import json

import bench


def test_headline_is_a_one_shot_latch(capsys):
    rep = bench.Report()
    rep.headline("graph500_scale26_bfs_teps", 1.568e8, "TEPS", 0.1568)
    # a later stage trying to claim the line is ignored
    rep.headline("gods_2hop_p50_ms", 0.137, "ms", 0.0)
    rep.emit()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] == "graph500_scale26_bfs_teps"
    assert out["value"] == 1.568e8
    assert out["vs_baseline"] == 0.1568


def test_unlatched_report_is_incomplete():
    rep = bench.Report()
    assert rep.metric == "bench_incomplete"


def test_estimates_reprice_with_measured_tunnel_rate():
    """Stage admission scales upload-heavy estimates by the observed
    H2D rate (VERDICT r5 weak #2: flat fast-day estimates admitted
    bfs_heavy into the external kill)."""
    old = bench._h2d_gbps
    try:
        bench._observe_h2d(9.0, 16.0)          # fast day: ~0.56 GB/s
        fast = bench._est("bfs_heavy")
        bench._observe_h2d(9.0, 480.0)         # slow tunnel day
        slow = bench._est("bfs_heavy")
        assert slow > fast
        # fixed-cost stages are unaffected by tunnel weather
        assert bench._est("ssspwcc") == bench._EST["ssspwcc"][0]
        # tiny/implausible observations are clamped, never zero/inf
        bench._observe_h2d(0.1, 1.0)           # too small to trust
        assert bench._est("bfs_heavy") == slow
    finally:
        bench._h2d_gbps = old
