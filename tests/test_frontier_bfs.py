"""Frontier-sparse BFS (bucketed static shapes) vs reference BFS."""

import numpy as np
import pytest

from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.models.bfs import _next_pow2, frontier_bfs


def np_bfs(n, src, dst, s0):
    from collections import deque
    adj = [[] for _ in range(n)]
    for a, b in zip(src, dst):
        adj[a].append(b)
    d = np.full(n, 1 << 30, np.int64)
    d[s0] = 0
    q = deque([s0])
    while q:
        u = q.popleft()
        for v in adj[u]:
            if d[v] > d[u] + 1:
                d[v] = d[u] + 1
                q.append(v)
    return d


def test_next_pow2():
    assert [_next_pow2(x) for x in (1, 2, 3, 4, 5, 1023, 1024)] == \
        [2, 2, 4, 4, 8, 1024, 1024]


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_frontier_matches_reference(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(30, 600))
    e = int(rng.integers(0, n * 5))
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    s0 = int(rng.integers(0, n))
    dist, levels = frontier_bfs(snap, s0)
    ref = np_bfs(n, src, dst, s0)
    assert np.array_equal(np.where(dist >= (1 << 30), 1 << 30, dist), ref)
    finite = ref[ref < (1 << 30)]
    assert levels >= int(finite.max()) if len(finite) else levels == 0


def test_isolated_source():
    snap = snap_mod.from_arrays(5, np.array([1, 2], np.int32),
                                np.array([2, 3], np.int32))
    dist, levels = frontier_bfs(snap, 0)    # degree-0 source
    assert dist[0] == 0 and (dist[1:] >= (1 << 30)).all()
    assert levels == 0


def test_chain_graph_many_levels():
    n = 300
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    dist, levels = frontier_bfs(snap, 0)
    assert np.array_equal(dist, np.arange(n))
    assert levels == n - 1


@pytest.mark.parametrize("seed", [0, 5])
def test_sharded_matches_single_chip(seed):
    from titan_tpu.models.bfs import frontier_bfs_sharded
    from titan_tpu.parallel.mesh import vertex_mesh
    rng = np.random.default_rng(seed)
    n = int(rng.integers(50, 800))
    e = int(rng.integers(10, n * 6))
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    s0 = int(rng.integers(0, n))
    d_single, _ = frontier_bfs(snap, s0)
    d_sharded, _ = frontier_bfs_sharded(snap, s0, vertex_mesh(8))
    assert np.array_equal(d_single, d_sharded)
    assert np.array_equal(np.where(d_sharded >= (1 << 30), 1 << 30,
                                   d_sharded), np_bfs(n, src, dst, s0))


def test_sharded_chain():
    from titan_tpu.models.bfs import frontier_bfs_sharded
    from titan_tpu.parallel.mesh import vertex_mesh
    n = 100
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    dist, levels = frontier_bfs_sharded(snap, 0, vertex_mesh(8))
    assert np.array_equal(dist, np.arange(n))
    assert levels == n - 1


def test_matches_dense_program():
    from titan_tpu.olap.tpu.engine import TPUGraphComputer
    from titan_tpu.models.bfs import BFS
    rng = np.random.default_rng(9)
    n, e = 256, 1500
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    dist, _ = frontier_bfs(snap, 0)
    comp = TPUGraphComputer(snapshot=snap, num_devices=1)
    res = comp.run(BFS(max_iterations=300), params={"source_dense": 0},
                   snapshot=snap)
    assert np.array_equal(np.asarray(res["dist"]), dist)
