"""Frontier-sparse BFS (bucketed static shapes) vs reference BFS."""

import numpy as np
import pytest

from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.models.bfs import _next_pow2, frontier_bfs


def np_bfs(n, src, dst, s0):
    from collections import deque
    adj = [[] for _ in range(n)]
    for a, b in zip(src, dst):
        adj[a].append(b)
    d = np.full(n, 1 << 30, np.int64)
    d[s0] = 0
    q = deque([s0])
    while q:
        u = q.popleft()
        for v in adj[u]:
            if d[v] > d[u] + 1:
                d[v] = d[u] + 1
                q.append(v)
    return d


def test_next_pow2():
    assert [_next_pow2(x) for x in (1, 2, 3, 4, 5, 1023, 1024)] == \
        [2, 2, 4, 4, 8, 1024, 1024]


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_frontier_matches_reference(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(30, 600))
    e = int(rng.integers(0, n * 5))
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    s0 = int(rng.integers(0, n))
    dist, levels = frontier_bfs(snap, s0)
    ref = np_bfs(n, src, dst, s0)
    assert np.array_equal(np.where(dist >= (1 << 30), 1 << 30, dist), ref)
    finite = ref[ref < (1 << 30)]
    assert levels >= int(finite.max()) if len(finite) else levels == 0


def test_isolated_source():
    snap = snap_mod.from_arrays(5, np.array([1, 2], np.int32),
                                np.array([2, 3], np.int32))
    dist, levels = frontier_bfs(snap, 0)    # degree-0 source
    assert dist[0] == 0 and (dist[1:] >= (1 << 30)).all()
    assert levels == 0


def test_chain_graph_many_levels():
    n = 300
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    dist, levels = frontier_bfs(snap, 0)
    assert np.array_equal(dist, np.arange(n))
    assert levels == n - 1


@pytest.mark.parametrize("seed", [0, 5])
@pytest.mark.slow
def test_sharded_matches_single_chip(seed):
    from titan_tpu.models.bfs import frontier_bfs_sharded
    from titan_tpu.parallel.mesh import vertex_mesh
    rng = np.random.default_rng(seed)
    n = int(rng.integers(50, 800))
    e = int(rng.integers(10, n * 6))
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    s0 = int(rng.integers(0, n))
    d_single, _ = frontier_bfs(snap, s0)
    d_sharded, _ = frontier_bfs_sharded(snap, s0, vertex_mesh(8))
    assert np.array_equal(d_single, d_sharded)
    assert np.array_equal(np.where(d_sharded >= (1 << 30), 1 << 30,
                                   d_sharded), np_bfs(n, src, dst, s0))


def test_sharded_chain():
    from titan_tpu.models.bfs import frontier_bfs_sharded
    from titan_tpu.parallel.mesh import vertex_mesh
    n = 100
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    dist, levels = frontier_bfs_sharded(snap, 0, vertex_mesh(8))
    assert np.array_equal(dist, np.arange(n))
    assert levels == n - 1


def test_matches_dense_program():
    from titan_tpu.olap.tpu.engine import TPUGraphComputer
    from titan_tpu.models.bfs import BFS
    rng = np.random.default_rng(9)
    n, e = 256, 1500
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    dist, _ = frontier_bfs(snap, 0)
    comp = TPUGraphComputer(snapshot=snap, num_devices=1)
    res = comp.run(BFS(max_iterations=300), params={"source_dense": 0},
                   snapshot=snap)
    assert np.array_equal(np.asarray(res["dist"]), dist)


@pytest.mark.parametrize("seed,shards", [(0, 1), (1, 3), (2, 5), (3, 2)])
def test_tiled_matches_reference(seed, shards):
    """Tiled path with tiny tiles/shards so every mechanism fires: multiple
    vertex-range shards, multiple slices per level (edge-budget AND
    frontier-count splits), partial last slices."""
    from titan_tpu.models.bfs import frontier_bfs_tiled
    rng = np.random.default_rng(seed)
    n = int(rng.integers(50, 700))
    e = int(rng.integers(10, n * 6))
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    s0 = int(src[0])
    max_shard_edges = max(1, e // shards)
    dist, levels = frontier_bfs_tiled(
        snap, s0, f_tile=16, m_tile=64, max_shard_edges=max_shard_edges,
        k_max=max(64, 4 * n // 16 + 8))
    ref = np_bfs(n, src, dst, s0)
    assert np.array_equal(np.where(dist >= (1 << 30), 1 << 30, dist), ref)
    finite = ref[ref < (1 << 30)]
    assert levels >= int(finite.max()) if len(finite) else levels == 0


def test_tiled_hub_heavier_than_tile():
    """A hub vertex whose degree exceeds the requested m_tile must not be
    dropped (the tile auto-grows to 2x max degree)."""
    from titan_tpu.models.bfs import frontier_bfs_tiled
    n = 200
    hub_edges = np.arange(1, 150, dtype=np.int32)
    src = np.concatenate([np.zeros(len(hub_edges), np.int32),
                          np.array([150], np.int32)])
    dst = np.concatenate([hub_edges, np.array([151], np.int32)])
    snap = snap_mod.from_arrays(n, src, dst)
    dist, levels = frontier_bfs_tiled(snap, 0, f_tile=8, m_tile=16,
                                      max_shard_edges=64)
    ref = np_bfs(n, src, dst, 0)
    assert np.array_equal(np.where(dist >= (1 << 30), 1 << 30, dist), ref)


def test_tiled_chain_many_levels():
    from titan_tpu.models.bfs import frontier_bfs_tiled
    n = 300
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    snap = snap_mod.from_arrays(n, src, dst)
    dist, levels = frontier_bfs_tiled(snap, 0, f_tile=4, m_tile=8,
                                      max_shard_edges=50)
    assert levels == n - 1 and dist[-1] == n - 1
