"""graftlint engine + rule-catalog tests (ISSUE 15).

Two jobs:

* ENFORCEMENT — the full titan_tpu/ + bench.py tree must lint clean
  (zero unsuppressed findings) inside the 30 s serial-CPU wall budget.
  This is the tier-1 teeth of the op-scan ban and its sibling
  invariants; the per-directory module-count pins it replaced lived in
  test_compaction.py.
* CATALOG — every rule (R1-R5) demonstrably fires on its positive
  fixture and stays quiet on its negative fixture
  (tests/fixtures/graftlint/ mirrors the real scope layout, so the
  SHIPPED config is what's exercised), plus suppression-comment,
  baseline-file, reporter-schema, and CLI semantics.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:          # bare `pytest` from anywhere
    sys.path.insert(0, REPO)

from tools.graftlint.engine import (Baseline, Linter,      # noqa: E402
                                    SUPPRESSED_BASELINE,
                                    SUPPRESSED_FILE, SUPPRESSED_INLINE)
from tools.graftlint.report import render_json             # noqa: E402
from tools.graftlint.rules import default_rules, rule_ids  # noqa: E402

FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")


@pytest.fixture(scope="module")
def fixture_result():
    return Linter(root=FIXTURES).run(["titan_tpu"])


@pytest.fixture(scope="module")
def repo_result():
    return Linter(root=REPO).run(["titan_tpu", "tests", "bench.py"])


def _in(result, rel):
    return [f for f in result.findings if f.path == rel]


def _msgs(findings):
    return " | ".join(f.message for f in findings)


# ---------------------------------------------------------------------------
# the catalog: each rule fires on its positive fixture, not its negative
# ---------------------------------------------------------------------------

def test_r1_opscan_fires_on_every_banned_shape(fixture_result):
    got = _in(fixture_result, "titan_tpu/models/opscan_pos.py")
    assert {f.rule for f in got} == {"opscan"}
    msgs = _msgs(got)
    assert len(got) == 8
    assert "unbounded: data-dependent output shape" in msgs
    assert "bounded, but the op-scan contract lives in ops.compaction" \
        in msgs
    assert "jnp.flatnonzero" in msgs
    assert "jnp.unique" in msgs
    assert "single-argument jnp.where is jnp.nonzero in disguise" \
        in msgs
    assert "bounded by size=" in msgs        # sized 1-arg where: banned
    assert ".nonzero() method call" in msgs  # method spelling: banned
    assert "boolean-mask indexing inside a jitted kernel" in msgs


def test_r1_opscan_negative(fixture_result):
    assert _in(fixture_result, "titan_tpu/models/opscan_ok.py") == []


def test_r2_hostsync_fires_via_both_registration_seams(fixture_result):
    got = _in(fixture_result, "titan_tpu/models/hostsync_pos.py")
    assert {f.rule for f in got} == {"host-sync"}
    msgs = _msgs(got)
    assert len(got) == 7
    # the jit_once kernel: all five host-sync shapes
    assert "Python `if` on a traced value" in msgs
    assert "int() coerces a traced value" in msgs
    assert "np.asarray" in msgs
    assert "jax.device_get" in msgs
    assert ".item()" in msgs
    # the mesh_jit kernel resolves too (call-site following, not names)
    assert "fixture_mesh_sync" in msgs
    assert "Python `while` on a traced value" in msgs


def test_r2_hostsync_negative_statics_and_shape_metadata(fixture_result):
    assert _in(fixture_result, "titan_tpu/models/hostsync_ok.py") == []


def test_r1_r2_see_inside_pallas_kernels(fixture_result):
    """ISSUE 16: ``pl.pallas_call`` is the third registration seam —
    the kernel resolves through both spellings (inline
    ``functools.partial`` and a local ``kern = partial(...)`` name) and
    traced-ref abuse inside the kernel body is flagged, not invisibly
    exempt."""
    got = _in(fixture_result, "titan_tpu/models/pallas_pos.py")
    assert {f.rule for f in got} == {"opscan", "host-sync"}
    assert len(got) == 5
    msgs = _msgs(got)
    assert "Python `if` on a traced value" in msgs
    assert "Python `while` on a traced value" in msgs
    assert "int() coerces a traced value" in msgs
    assert ".item()" in msgs
    assert "boolean-mask indexing inside a jitted kernel" in msgs
    # pallas kernels have no literal key: messages cite the call line
    assert "registered at line" in msgs


def test_pallas_kernel_static_config_params_stay_legal(fixture_result):
    """Keyword-only params bound through ``functools.partial`` are
    compile-time constants: ``while d < block`` ladders and
    ``if masked`` config branches must NOT read as host syncs."""
    assert _in(fixture_result, "titan_tpu/models/pallas_ok.py") == []


def test_r3_lock_discipline_fires(fixture_result):
    got = _in(fixture_result,
              "titan_tpu/olap/serving/lock_pos.py")
    assert {f.rule for f in got} == {"lock-discipline"}
    msgs = _msgs(got)
    assert len(got) == 9
    for needle in ("file I/O (open)", "json.dump", "os.replace",
                   "time.sleep", "urllib.request.urlopen",
                   "subprocess spawn", "device dispatch (jnp.zeros)",
                   "jax.device_put", ".block_until_ready"):
        assert needle in msgs, needle
    # both lock spellings observed
    assert "while holding _cv" in msgs
    assert "while holding _lock" in msgs


def test_r3_lock_discipline_negative(fixture_result):
    assert _in(fixture_result,
               "titan_tpu/olap/serving/lock_ok.py") == []


def test_r4_metric_name_fires(fixture_result):
    got = _in(fixture_result,
              "titan_tpu/olap/serving/metric_pos.py")
    assert {f.rule for f in got} == {"metric-name"}
    msgs = _msgs(got)
    assert len(got) == 3
    assert "'bogus.name' is outside the pinned families" in msgs
    assert "'unpinned.family.name' is outside the pinned" in msgs
    assert "'serving.fixture.undocumented' has no docs/monitoring.md" \
        in msgs


def test_r4_metric_name_negative(fixture_result):
    assert _in(fixture_result,
               "titan_tpu/olap/serving/metric_ok.py") == []


def test_r5_clock_seam_fires(fixture_result):
    got = _in(fixture_result, "titan_tpu/obs/clock_pos.py")
    assert {f.rule for f in got} == {"clock-seam"}
    assert len(got) == 2
    assert "time.time" in got[0].message
    assert "time.monotonic" in got[1].message


def test_r5_clock_seam_negatives(fixture_result):
    assert _in(fixture_result, "titan_tpu/obs/clock_ok.py") == []
    assert _in(fixture_result,
               "titan_tpu/obs/clock_noseam_ok.py") == []


# ---------------------------------------------------------------------------
# suppression semantics
# ---------------------------------------------------------------------------

def test_inline_suppressions_and_bare_allow(fixture_result):
    got = _in(fixture_result, "titan_tpu/suppress_demo.py")
    assert len(got) == 3
    by_line = {f.line: f for f in got}
    trailing = by_line[8]
    assert trailing.suppressed == SUPPRESSED_INLINE
    assert "trailing-line" in trailing.reason
    standalone = by_line[13]       # comment on 12 covers line 13, by alias
    assert standalone.suppressed == SUPPRESSED_INLINE
    assert "next-line" in standalone.reason
    bare = by_line[17]             # allow without reason= stays INERT
    assert bare.suppressed is None
    assert ("titan_tpu/suppress_demo.py", 17) in \
        fixture_result.bare_allows
    # the allow-file directive QUOTED in suppress_demo's string literal
    # is text, not a suppression: had it been honored, every finding in
    # the file (incl. `bare` above) would read suppressed='file'
    assert not any(f.suppressed == SUPPRESSED_FILE for f in got)


def test_allow_file_suppresses_reference_models(repo_result):
    """The two non-round-loop reference models carry file-level
    suppressions for the op-scan ban — the findings still EXIST (the
    exemption is visible, not invisible) but are suppressed with the
    recorded reason."""
    for rel in ("titan_tpu/models/bfs.py",
                "titan_tpu/models/bfs_hybrid_fused.py"):
        got = _in(repo_result, rel)
        assert got, f"expected suppressed opscan findings in {rel}"
        assert all(f.suppressed == SUPPRESSED_FILE for f in got)
        assert all("not a round-loop hot path" in f.reason for f in got)


# ---------------------------------------------------------------------------
# baseline semantics
# ---------------------------------------------------------------------------

_BAD = textwrap.dedent("""\
    import jax.numpy as jnp

    def f(mask):
        return jnp.flatnonzero(mask)
""")


def _mktree(tmp_path, body=_BAD):
    pkg = tmp_path / "titan_tpu" / "newmod"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "gen.py").write_text(body)
    return tmp_path


def test_baseline_grandfathers_then_catches_new(tmp_path):
    root = _mktree(tmp_path)
    first = Linter(root=str(root)).run(["titan_tpu"])
    assert len(first.unsuppressed) == 1
    bl_path = str(tmp_path / "baseline.json")
    Baseline.from_findings(first.findings).write(bl_path)

    # grandfathered: same tree + baseline -> clean
    again = Linter(root=str(root),
                   baseline=Baseline.load(bl_path)).run(["titan_tpu"])
    assert again.unsuppressed == []
    assert [f.suppressed for f in again.findings] == [SUPPRESSED_BASELINE]

    # a NEW finding in the same file is not hidden by the grandfather
    _mktree(tmp_path, _BAD + "\n\ndef g(m):\n    return jnp.unique(m)\n")
    third = Linter(root=str(root),
                   baseline=Baseline.load(bl_path)).run(["titan_tpu"])
    assert len(third.unsuppressed) == 1
    assert "jnp.unique" in third.unsuppressed[0].message


def test_baseline_auto_loaded_by_every_surface(tmp_path):
    """The checked-in baseline must bind EVERY enforcement surface the
    same way: a bare Linter(root=...) auto-loads
    tools/graftlint/baseline.json under its root (the CLI, tier-1
    tests, and bench's lint_clean line can never disagree about the
    same tree). Opt out explicitly with baseline=Baseline()."""
    root = _mktree(tmp_path)
    first = Linter(root=str(root)).run(["titan_tpu"])
    assert len(first.unsuppressed) == 1
    bl_dir = tmp_path / "tools" / "graftlint"
    bl_dir.mkdir(parents=True)
    Baseline.from_findings(first.findings).write(
        str(bl_dir / "baseline.json"))
    # same bare construction now grandfathers via the checked-in file
    auto = Linter(root=str(root)).run(["titan_tpu"])
    assert auto.unsuppressed == []
    assert [f.suppressed for f in auto.findings] == [SUPPRESSED_BASELINE]
    # the explicit opt-out still sees the raw finding
    raw = Linter(root=str(root), baseline=Baseline()).run(["titan_tpu"])
    assert len(raw.unsuppressed) == 1


def test_baseline_counts_duplicate_lines(tmp_path):
    body = _BAD + "\n\ndef g(mask):\n    return jnp.flatnonzero(mask)\n"
    root = _mktree(tmp_path, body)
    first = Linter(root=str(root)).run(["titan_tpu"])
    assert len(first.unsuppressed) == 2
    bl = Baseline.from_findings(first.findings)
    # identical snippets share a key with count 2 — both consumed, a
    # third identical line would NOT be
    assert sum(bl.entries.values()) == 2
    again = Linter(root=str(root), baseline=bl).run(["titan_tpu"])
    assert again.unsuppressed == []


# ---------------------------------------------------------------------------
# reporters + CLI
# ---------------------------------------------------------------------------

def test_json_reporter_schema(fixture_result):
    doc = json.loads(render_json(fixture_result, FIXTURES))
    assert doc["format"] == "graftlint-v1"
    assert set(doc["summary"]) == {"files", "findings", "unsuppressed",
                                   "suppressed", "bare_allows", "wall_s"}
    assert doc["summary"]["files"] == len(fixture_result.files)
    assert doc["summary"]["findings"] == len(fixture_result.findings)
    for f in doc["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message",
                          "snippet", "suppressed", "reason"}
        assert isinstance(f["line"], int) and f["line"] >= 1


def test_cli_exit_codes_and_json():
    env = dict(os.environ, PYTHONPATH=REPO)
    dirty = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--root", FIXTURES,
         "--json", "titan_tpu"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert dirty.returncode == 1
    doc = json.loads(dirty.stdout)
    assert doc["summary"]["unsuppressed"] > 0

    unknown = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--rules", "bogus"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert unknown.returncode == 2

    only_r5 = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--root", FIXTURES,
         "--rules", "R5", "--json", "titan_tpu"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert only_r5.returncode == 1
    doc = json.loads(only_r5.stdout)
    assert {f["rule"] for f in doc["findings"]} == {"clock-seam"}


def test_cli_write_baseline_bootstraps_missing_file(tmp_path):
    """--write-baseline with a target that doesn't exist yet is the
    bootstrap case, not a crash; a missing baseline WITHOUT
    --write-baseline is a clean usage error (exit 2)."""
    pkg = tmp_path / "titan_tpu"
    pkg.mkdir()
    (pkg / "x.py").write_text(
        "import jax.numpy as jnp\n\ndef f(m):\n"
        "    return jnp.flatnonzero(m)\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    bl = str(tmp_path / "bl.json")
    base = [sys.executable, "-m", "tools.graftlint",
            "--root", str(tmp_path), "--baseline", bl]
    boot = subprocess.run([*base, "--write-baseline", "titan_tpu"],
                          cwd=REPO, env=env, capture_output=True,
                          text=True)
    assert boot.returncode == 0, boot.stderr
    assert os.path.exists(bl)
    clean = subprocess.run([*base, "titan_tpu"], cwd=REPO, env=env,
                           capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout
    missing = subprocess.run(
        [*base[:-2], "--baseline", str(tmp_path / "nope.json"),
         "titan_tpu"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert missing.returncode == 2
    assert "baseline file not found" in missing.stderr


def test_rule_catalog_ids_and_aliases():
    ids = rule_ids()
    assert {ids[a] for a in ("R1", "R2", "R3", "R4", "R5")} == \
        {"opscan", "host-sync", "lock-discipline", "metric-name",
         "clock-seam"}
    assert len(default_rules()) == 5


# ---------------------------------------------------------------------------
# enforcement: the real tree, inside the wall budget
# ---------------------------------------------------------------------------

def test_full_tree_zero_unsuppressed_findings(repo_result):
    """THE invariant gate (acceptance: `python -m tools.graftlint
    titan_tpu tests bench.py` exits 0). A finding here means new code
    broke an invariant — fix it or suppress inline WITH a reason."""
    pretty = "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}"
        for f in repo_result.unsuppressed)
    assert repo_result.unsuppressed == [], f"\n{pretty}"
    assert not any(f.rule == "parse-error" for f in repo_result.findings)
    # sanity: the walk really covered the tree
    assert len(repo_result.files) > 150


def test_full_tree_wall_clock_under_30s(repo_result):
    """Lint rides tier-1 (870 s serial-CPU budget) — keep it a rounding
    error."""
    assert repo_result.wall_s < 30.0, repo_result.wall_s


def test_bench_evidence_carries_lint_clean_line():
    """ROADMAP #5 wiring: chip-day bundles record that the invariants
    held — a value (clean flag + counts), never silently absent."""
    import bench

    ev = bench.Evidence.__new__(bench.Evidence)
    ev.rep = bench.Report.__new__(bench.Report)
    ev.rep.detail = {}
    got = ev._lint_clean()
    assert got["present"] is True
    val = got["value"]
    assert val["clean"] is True and val["unsuppressed"] == 0
    assert val["files"] > 100 and val["suppressed"] >= 11
