"""MapReduce stage of the graph computers.

Modeled on the reference's post-BSP MapReduce execution
(FulgoraGraphComputer.java:192-246) with the PageRank/ShortestDistance
MapReduce companions from titan-test as fixtures.
"""

import numpy as np
import pytest

import titan_tpu
from titan_tpu import example
from titan_tpu.olap.api import (DenseMapReduce, MapEmitter, MapReduce,
                                ReduceEmitter, VertexProgram,
                                execute_map_reduce)
from titan_tpu.olap.computer import HostGraphComputer
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.olap.tpu.engine import TPUGraphComputer
from titan_tpu.models import pagerank, sssp
from titan_tpu.models.pagerank import TopRanksMapReduce
from titan_tpu.models.sssp import MaxDistanceMapReduce


# ---------------------------------------------------------------------------
# the contract itself (no computer)
# ---------------------------------------------------------------------------

class _Obj:
    def __init__(self, label, value):
        self._label = label
        self._value = value

    def label(self):
        return self._label

    def get_state(self, key, default=None):
        return self._value


class CountByLabel(MapReduce):
    memory_key = "countByLabel"

    def map(self, vertex, emitter):
        emitter.emit(vertex.label(), 1)

    def combine(self, key, values, emitter):
        emitter.emit(key, sum(values))

    def reduce(self, key, values, emitter):
        emitter.emit(key, sum(values))

    def finalize(self, results):
        return {k: v[0] for k, v in results.items()}


def test_execute_map_reduce_groups_and_combines():
    vertices = [_Obj("a", 0)] * 5 + [_Obj("b", 0)] * 3
    out = execute_map_reduce(CountByLabel(), vertices, chunk=2)
    assert out == {"a": 5, "b": 3}


def test_map_reduce_default_reduce_passthrough():
    class Identity(MapReduce):
        def map(self, vertex, emitter):
            emitter.emit("k", vertex.get_state("x"))

    vertices = [_Obj("a", 0), _Obj("a", 0)]
    out = execute_map_reduce(Identity(), vertices)
    assert out == {"k": [0, 0]}


# ---------------------------------------------------------------------------
# host computer path
# ---------------------------------------------------------------------------

class InDegreeProgram(VertexProgram):
    def execute(self, vertex, messenger, memory):
        if memory.iteration == 0:
            messenger.send(1, [n.id for n in vertex.out()])
        else:
            vertex.set_state("indeg", sum(messenger.receive()))

    def terminate(self, memory):
        return memory.iteration >= 1

    def combiner(self):
        return lambda a, b: a + b


class MaxInDegree(MapReduce):
    memory_key = "maxInDeg"

    def map(self, vertex, emitter):
        emitter.emit("max", vertex.get_state("indeg", 0))

    def reduce(self, key, values, emitter):
        emitter.emit(key, max(values))

    def finalize(self, results):
        return results["max"][0]


def test_host_computer_map_reduce():
    g = titan_tpu.open("inmemory")
    example.load(g)
    comp = HostGraphComputer(g, num_threads=4)
    result = comp.run(InDegreeProgram(), map_reduces=[MaxInDegree()])
    assert result.memory.get("maxInDeg") == 3   # jupiter
    g.close()


# ---------------------------------------------------------------------------
# TPU computer path
# ---------------------------------------------------------------------------

def _random_snap(n=64, e=400, seed=3):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    return snap_mod.from_arrays(n, src, dst)


@pytest.mark.parametrize("ndev", [1, 8])
def test_tpu_dense_map_reduce_top_ranks(ndev):
    snap = _random_snap()
    comp = TPUGraphComputer(snapshot=snap, num_devices=ndev)
    inv = np.where(snap.out_degree > 0,
                   1.0 / np.maximum(snap.out_degree, 1), 0.0).astype(np.float32)
    res = comp.run(pagerank.PageRank(iterations=15),
                   params={"n": snap.n, "inv_outdeg": inv},
                   snapshot=snap, map_reduces=[TopRanksMapReduce(k=5)])
    top = res.memory["pageRank"]
    assert len(top) == 5
    ranks = np.asarray(res["rank"])
    best_dense = int(np.argmax(ranks))
    assert top[0][0] == int(snap.vertex_ids[best_dense])
    assert top[0][1] == pytest.approx(float(ranks.max()), rel=1e-5)
    # descending order
    vals = [r for _, r in top]
    assert vals == sorted(vals, reverse=True)


def test_tpu_classic_map_reduce_over_dense_state():
    snap = _random_snap()
    comp = TPUGraphComputer(snapshot=snap, num_devices=1)
    inv = np.where(snap.out_degree > 0,
                   1.0 / np.maximum(snap.out_degree, 1), 0.0).astype(np.float32)

    class RankSum(MapReduce):
        memory_key = "rankSum"

        def map(self, vertex, emitter):
            emitter.emit("sum", vertex.get_state("rank"))

        def reduce(self, key, values, emitter):
            emitter.emit(key, sum(values))

        def finalize(self, results):
            return results["sum"][0]

    res = comp.run(pagerank.PageRank(iterations=10),
                   params={"n": snap.n, "inv_outdeg": inv},
                   snapshot=snap, map_reduces=[RankSum()])
    assert res.memory["rankSum"] == pytest.approx(float(np.sum(res["rank"])),
                                                  rel=1e-4)


def test_sssp_max_distance_map_reduce():
    snap = _random_snap(n=32, e=200)
    comp = TPUGraphComputer(snapshot=snap, num_devices=1)
    res = comp.run(sssp.SSSP(weight_key="w"),
                   params={"source_dense": 0},
                   snapshot=snap_with_weights(snap),
                   map_reduces=[MaxDistanceMapReduce()])
    m = res.memory["shortestDistance.max"]
    d = np.asarray(res["dist"])
    finite = d < 3.0e38
    assert m == pytest.approx(float(d[finite].max()))


def snap_with_weights(snap, seed=5):
    rng = np.random.default_rng(seed)
    e = len(np.asarray(snap.src))
    w = rng.uniform(0.5, 2.0, e).astype(np.float32)
    return snap_mod.from_arrays(snap.n, np.asarray(snap.src),
                                np.asarray(snap.dst), edge_values={"w": w})
