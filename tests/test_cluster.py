"""Sharded + replicated remote-cluster backend.

(reference role: the Cassandra/HBase cluster under titan-cassandra /
titan-hbase — partitioned + replicated key placement with consistency
levels; exercised here with N in-process KCVSServer nodes.)
"""

import pytest

import titan_tpu
from titan_tpu.errors import TemporaryBackendError
from titan_tpu.storage.api import (Entry, KeyRangeQuery, KeySliceQuery,
                                   SliceQuery)
from titan_tpu.storage.cluster import ClusterStoreManager, HashRing
from titan_tpu.storage.inmemory import InMemoryStoreManager
from titan_tpu.storage.remote import KCVSServer


@pytest.fixture
def nodes():
    servers = [KCVSServer(InMemoryStoreManager()).start() for _ in range(3)]
    yield servers
    for s in servers:
        s.stop()


def hosts_of(servers):
    return [f"127.0.0.1:{s.port}" for s in servers]


def make_mgr(servers, rf=2, wc="all"):
    return ClusterStoreManager(hosts_of(servers), replication=rf,
                               write_consistency=wc, virtual_nodes=16)


def test_ring_distinct_replicas():
    ring = HashRing(5, 3, 32, [f"n{i}" for i in range(5)])
    for k in range(200):
        reps = ring.replicas(b"key%d" % k)
        assert len(reps) == 3 and len(set(reps)) == 3


def test_ring_spread():
    ring = HashRing(4, 1, 64, [f"n{i}" for i in range(4)])
    counts = [0] * 4
    for k in range(2000):
        counts[ring.replicas(b"key%d" % k)[0]] += 1
    assert min(counts) > 200   # no starving node

def test_slice_and_scan_with_replication(nodes):
    mgr = make_mgr(nodes, rf=2)
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    for i in range(60):
        store.mutate(b"k%03d" % i, [Entry(b"c", b"%d" % i)], [], txh)
    # reads
    assert store.get_slice(KeySliceQuery(b"k007", SliceQuery()), txh) == \
        [Entry(b"c", b"7")]
    multi = store.get_slice_multi([b"k003", b"k017", b"k042"],
                                  SliceQuery(), txh)
    assert multi[b"k042"] == [Entry(b"c", b"42")]
    # ordered scan: globally ordered, duplicates collapsed
    rows = list(store.get_keys(KeyRangeQuery(b"k010", b"k030",
                                             SliceQuery()), txh))
    assert [k for k, _ in rows] == [b"k%03d" % i for i in range(10, 30)]
    # unordered scan: every key exactly once
    all_rows = sorted(k for k, _ in store.get_keys(SliceQuery(), txh))
    assert all_rows == [b"k%03d" % i for i in range(60)]


def test_reads_survive_node_failure_with_rf2(nodes):
    mgr = make_mgr(nodes, rf=2)
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    for i in range(40):
        store.mutate(b"k%03d" % i, [Entry(b"c", b"%d" % i)], [], txh)
    nodes[1].stop()
    for i in range(40):   # every key still readable from a live replica
        assert store.get_slice(
            KeySliceQuery(b"k%03d" % i, SliceQuery()), txh) == \
            [Entry(b"c", b"%d" % i)]
    # unordered scan still sees every key exactly once
    all_rows = sorted(k for k, _ in store.get_keys(SliceQuery(), txh))
    assert all_rows == [b"k%03d" % i for i in range(40)]


def test_write_consistency_all_fails_on_dead_node(nodes):
    mgr = make_mgr(nodes, rf=2, wc="all")
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    store.mutate(b"k1", [Entry(b"c", b"1")], [], txh)
    nodes[2].stop()
    with pytest.raises(TemporaryBackendError):
        for i in range(60):   # some key surely replicates to node 2
            store.mutate(b"w%03d" % i, [Entry(b"c", b"x")], [], txh)


def test_write_consistency_one_tolerates_dead_node(nodes):
    mgr = make_mgr(nodes, rf=2, wc="one")
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    nodes[0].stop()
    for i in range(60):
        store.mutate(b"w%03d" % i, [Entry(b"c", b"x")], [], txh)
    for i in range(60):
        assert store.get_slice(
            KeySliceQuery(b"w%03d" % i, SliceQuery()), txh) == \
            [Entry(b"c", b"x")]


def test_graph_over_cluster(nodes):
    g = titan_tpu.open({
        "storage.backend": "remote-cluster",
        "storage.hostname": ",".join(hosts_of(nodes)),
        "storage.cluster.replication-factor": 2,
        "storage.cluster.virtual-nodes": 16,
    })
    try:
        tx = g.new_transaction()
        a = tx.add_vertex("person", name="alice")
        b = tx.add_vertex("person", name="bob")
        tx.add_edge(a, "knows", b)
        tx.commit()
        out = g.traversal().V().has("name", "alice").out("knows") \
            .values("name").to_list()
        assert out == ["bob"]
        # schema listing works over the merged ordered scan
        names = {t.name for t in g.schema.all_types()}
        assert {"person", "name", "knows"} <= names
    finally:
        g.close()


def test_graph_survives_replica_failure(nodes):
    g = titan_tpu.open({
        "storage.backend": "remote-cluster",
        "storage.hostname": ",".join(hosts_of(nodes)),
        "storage.cluster.replication-factor": 3,
        "storage.cluster.write-consistency": "quorum",
        "storage.cluster.virtual-nodes": 16,
    })
    try:
        tx = g.new_transaction()
        a = tx.add_vertex("person", name="alice")
        b = tx.add_vertex("person", name="bob")
        tx.add_edge(a, "knows", b)
        tx.commit()
        nodes[1].stop()
        # reads AND writes keep working at rf=3 / quorum with one node down
        out = g.traversal().V().has("name", "alice").out("knows") \
            .values("name").to_list()
        assert out == ["bob"]
        tx = g.new_transaction()
        c = tx.add_vertex("person", name="carol")
        tx.add_edge(tx.vertex(a.id), "knows", c)
        tx.commit()
        # the first traversal auto-started the THREAD-BOUND tx, whose
        # caches make reads repeatable (reference semantics) — refresh it
        # to observe the commit
        g.tx().rollback()
        assert sorted(g.traversal().V().has("name", "alice").out("knows")
                      .values("name").to_list()) == ["bob", "carol"]
    finally:
        g.close()


def restart(server):
    """Revive a stopped KCVSServer on the same port with its (surviving)
    in-memory store — the 'node comes back' scenario."""
    return KCVSServer(server.manager, port=server.port).start()


def test_hinted_handoff_converges_revived_replica(nodes):
    """VERDICT item 6 / advisor finding: an acknowledged write under
    wc=one with a replica down must reach that replica after it revives
    (hinted handoff), not stay permanently invisible."""
    mgr = make_mgr(nodes, rf=2, wc="one")
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    store.mutate(b"seed", [Entry(b"c", b"0")], [], txh)   # connect peers
    # find a key and kill one of ITS replicas
    key = b"hh-key"
    owners = mgr.ring.replicas(key)
    victim, survivor = owners[0], owners[1]
    nodes[victim].stop()
    store.mutate(key, [Entry(b"c", b"v1")], [], txh)      # acked by survivor
    assert mgr._hints.get(victim), "expected a queued hint"
    nodes[victim] = restart(nodes[victim])
    assert mgr.is_up(victim)                              # replays hints
    assert not mgr._hints.get(victim)
    # prove the revived replica owns the data: kill the OTHER replica
    nodes[survivor].stop()
    got = store.get_slice(KeySliceQuery(key, SliceQuery()), txh)
    assert got == [Entry(b"c", b"v1")]


def test_read_repair_converges_without_hints(nodes):
    """A fresh manager (no hint state — e.g. after a coordinator restart)
    must converge a stale replica through read repair alone."""
    mgr = ClusterStoreManager(hosts_of(nodes), replication=3,
                              write_consistency="quorum", virtual_nodes=16,
                              read_repair=1.0)
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    key = b"rr-key"
    victim = mgr.ring.replicas(key)[0]
    nodes[victim].stop()
    store.mutate(key, [Entry(b"c", b"new")], [], txh)     # quorum 2/3
    mgr.close()
    nodes[victim] = restart(nodes[victim])
    # brand-new coordinator: no hints survive; read triggers the repair
    mgr2 = ClusterStoreManager(hosts_of(nodes), replication=3,
                               write_consistency="quorum", virtual_nodes=16,
                               read_repair=1.0)
    store2 = mgr2.open_database("s")
    got = store2.get_slice(KeySliceQuery(key, SliceQuery()), txh)
    assert got == [Entry(b"c", b"new")]
    # now the revived node must have been repaired: kill the other two
    for p in range(3):
        if p != victim:
            nodes[p].stop()
    mgr3 = ClusterStoreManager([hosts_of(nodes)[victim]], replication=1,
                               virtual_nodes=16)
    got2 = mgr3.open_database("s").get_slice(
        KeySliceQuery(key, SliceQuery()), txh)
    assert got2 == [Entry(b"c", b"new")]


def test_tombstones_prevent_deleted_data_resurrection(nodes):
    """A replica that missed a deletion must not resurrect the cell: the
    tombstone is newer and wins the merge."""
    mgr = ClusterStoreManager(hosts_of(nodes), replication=3,
                              write_consistency="quorum", virtual_nodes=16,
                              read_repair=1.0)
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    key = b"del-key"
    store.mutate(key, [Entry(b"c", b"live")], [], txh)    # all replicas
    victim = mgr.ring.replicas(key)[0]
    nodes[victim].stop()
    store.mutate(key, [], [b"c"], txh)                    # delete w/o victim
    mgr.close()
    nodes[victim] = restart(nodes[victim])                # stale live cell
    mgr2 = ClusterStoreManager(hosts_of(nodes), replication=3,
                               write_consistency="quorum", virtual_nodes=16,
                               read_repair=1.0)
    store2 = mgr2.open_database("s")
    got = store2.get_slice(KeySliceQuery(key, SliceQuery()), txh)
    assert got == []                                      # no resurrection
    rows = dict(store2.get_keys(KeyRangeQuery(key, key + b"\xff",
                                              SliceQuery()), txh))
    assert key not in rows


def test_key_consistent_flag_honesty():
    """Advisor finding: key_consistent must not be advertised when
    wc=one with rf>1 (locks/id-claims would silently lose exclusion)."""
    servers = [KCVSServer(InMemoryStoreManager()).start() for _ in range(2)]
    try:
        weak = ClusterStoreManager(hosts_of(servers), replication=2,
                                   write_consistency="one", virtual_nodes=8)
        assert not weak.features.key_consistent
        strong = ClusterStoreManager(hosts_of(servers), replication=2,
                                     write_consistency="quorum",
                                     virtual_nodes=8)
        assert strong.features.key_consistent
        single = ClusterStoreManager(hosts_of(servers), replication=1,
                                     write_consistency="one",
                                     virtual_nodes=8)
        assert single.features.key_consistent
    finally:
        for s in servers:
            s.stop()


def test_tombstone_compaction(nodes):
    mgr = ClusterStoreManager(hosts_of(nodes), replication=2,
                              virtual_nodes=16)
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    for i in range(10):
        store.mutate(b"k%d" % i, [Entry(b"c", b"v")], [], txh)
    for i in range(10):
        store.mutate(b"k%d" % i, [], [b"c"], txh)       # tombstones
    purged = mgr.compact_tombstones(["s"], grace_seconds=0.0)
    assert purged >= 10                                 # rf=2 -> ~20
    # post-compaction reads are still clean
    for i in range(10):
        assert store.get_slice(KeySliceQuery(b"k%d" % i, SliceQuery()),
                               txh) == []
    # compaction refuses to run with a replica down
    nodes[0].stop()
    with pytest.raises(TemporaryBackendError):
        mgr.compact_tombstones(["s"])


def test_concurrent_writers_converge(nodes):
    """VERDICT weak point 6: concurrent writers through two coordinators;
    LWW cells make the replicas agree on the final value."""
    import threading
    m1 = ClusterStoreManager(hosts_of(nodes), replication=2,
                             virtual_nodes=16, read_repair=1.0,
                             write_consistency="quorum")
    m2 = ClusterStoreManager(hosts_of(nodes), replication=2,
                             virtual_nodes=16, read_repair=1.0,
                             write_consistency="quorum")
    txh = m1.begin_transaction()

    def writer(mgr, who):
        s = mgr.open_database("s")
        for i in range(30):
            s.mutate(b"contended", [Entry(b"c", b"%s-%d" % (who, i))],
                     [], txh)

    t1 = threading.Thread(target=writer, args=(m1, b"a"))
    t2 = threading.Thread(target=writer, args=(m2, b"b"))
    t1.start(); t2.start(); t1.join(); t2.join()
    s1 = m1.open_database("s")
    s2 = m2.open_database("s")
    v1 = s1.get_slice(KeySliceQuery(b"contended", SliceQuery()), txh)
    v2 = s2.get_slice(KeySliceQuery(b"contended", SliceQuery()), txh)
    # both coordinators see the SAME single winning cell
    assert v1 == v2 and len(v1) == 1
    assert v1[0].value.endswith(b"-29")


def test_hint_overflow_forces_merged_reads_until_full_sync(nodes,
                                                          monkeypatch):
    """Spilled hints may include tombstones: merged reads stay forced
    (reconnect alone must not clear the taint) until compact_tombstones
    runs a full anti-entropy pass, which also delivers the missed data."""
    mgr = ClusterStoreManager(hosts_of(nodes), replication=3,
                              write_consistency="quorum", virtual_nodes=16,
                              read_repair=0.0, max_hints_per_peer=1)
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    store.mutate(b"seed", [Entry(b"c", b"0")], [], txh)
    victim = mgr.ring.replicas(b"k0")[0]
    nodes[victim].stop()
    for i in range(4):                      # 1 hint queued, 3 spilled
        store.mutate(b"k%d" % i, [Entry(b"c", b"v%d" % i)], [], txh)
    assert mgr._ever_overflowed == {victim}
    nodes[victim] = restart(nodes[victim])
    assert mgr.is_up(victim)                # replays the 1 queued hint
    # taint survives reconnect; merged reads forced despite read_repair=0
    assert mgr._ever_overflowed == {victim}
    assert mgr.repair_roll() is True
    purged = mgr.compact_tombstones(["s"])  # full sync heals everything
    assert mgr._ever_overflowed == set()
    # prove the victim now holds ALL keys: kill the other replicas
    for p in range(3):
        if p != victim:
            nodes[p].stop()
    solo = ClusterStoreManager([hosts_of(nodes)[victim]], replication=1,
                               virtual_nodes=16)
    s2 = solo.open_database("s")
    for i in range(4):
        assert s2.get_slice(KeySliceQuery(b"k%d" % i, SliceQuery()),
                            txh) == [Entry(b"c", b"v%d" % i)]


def test_same_batch_add_and_delete_add_wins(nodes):
    """KCVMutation.consolidate contract: an addition overrides a deletion
    of the same column within one mutation — including on the DIRECT
    ClusterStore.mutate path where both land with the same cell ts."""
    from titan_tpu.storage.api import StoreTransaction
    mgr = make_mgr(nodes, rf=2, wc="all")
    store = mgr.open_database("e")
    txh = StoreTransaction(None)
    store.mutate(b"k", [Entry(b"c", b"v1")], [b"c"], txh)
    res = store.get_slice(KeySliceQuery(b"k", SliceQuery(b"", b"\xff")), txh)
    assert [(e.column, e.value) for e in res] == [(b"c", b"v1")]
    mgr.close()


def test_hint_replay_does_not_overwrite_newer_direct_write(nodes):
    """Reconnect publishes the peer only after the hint queue drains, so
    a fresh direct write can never be clobbered by an older hinted cell."""
    from titan_tpu.storage.api import StoreTransaction
    mgr = make_mgr(nodes, rf=3, wc="quorum")
    store = mgr.open_database("e")
    txh = StoreTransaction(None)
    victim = 1
    mgr.mark_down(victim)
    nodes[victim].stop()
    store.mutate(b"k", [Entry(b"c", b"old")], [], txh)
    # victim resurrects; its hint queue holds the "old" cell
    revived = KCVSServer(InMemoryStoreManager(),
                         port=nodes[victim].port).start()
    try:
        # reconnect triggers replay-then-publish; afterwards a newer
        # write must win on every replica
        store.mutate(b"k", [Entry(b"c", b"new")], [], txh)
        res = store.get_slice(
            KeySliceQuery(b"k", SliceQuery(b"", b"\xff")), txh)
        assert [(e.column, e.value) for e in res] == [(b"c", b"new")]
    finally:
        revived.stop()
    mgr.close()


def test_auto_compaction_daemon(nodes):
    """STATUS r4 gap: tombstone GC as a background daemon — purges
    aged tombstones on its own schedule, skips cycles (without dying)
    while a replica is down, and stops cleanly on close()."""
    import time as _t

    mgr = ClusterStoreManager(hosts_of(nodes), replication=2,
                              virtual_nodes=16)
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    for i in range(8):
        store.mutate(b"a%d" % i, [Entry(b"c", b"v")], [], txh)
    for i in range(8):
        store.mutate(b"a%d" % i, [], [b"c"], txh)       # tombstones
    mgr.start_auto_compaction(0.2, grace_seconds=0.0)
    deadline = _t.time() + 20
    while _t.time() < deadline and mgr.compaction_stats["purged"] < 8:
        _t.sleep(0.1)
    assert mgr.compaction_stats["purged"] >= 8
    assert mgr.compaction_stats["runs"] >= 1
    for i in range(8):
        assert store.get_slice(KeySliceQuery(b"a%d" % i, SliceQuery()),
                               txh) == []

    # down replica: cycles are skipped, daemon survives
    nodes[0].stop()
    mgr.mark_down(0)
    skipped0 = mgr.compaction_stats["skipped"]
    deadline = _t.time() + 20
    while _t.time() < deadline and \
            mgr.compaction_stats["skipped"] <= skipped0:
        _t.sleep(0.1)
    assert mgr.compaction_stats["skipped"] > skipped0
    assert "replica" in (mgr.compaction_stats["last_error"] or "")
    mgr.close()
    assert mgr._compactor is None


def test_auto_compaction_config_wiring(tmp_path, nodes):
    """storage.cluster.compaction-interval-s starts the daemon through
    the normal open() path."""
    g = titan_tpu.open({
        "storage.backend": "remote-cluster",
        "storage.hostname": hosts_of(nodes),
        "storage.cluster.replication-factor": 2,
        "storage.cluster.compaction-interval-s": 0.5,
        "storage.cluster.gc-grace-seconds": 0.0,
    })
    try:
        raw = g.backend.manager
        while not hasattr(raw, "start_auto_compaction"):
            raw = raw.manager if hasattr(raw, "manager") else raw.inner
        assert raw._compactor is not None
    finally:
        g.close()
