"""Live overlay property tests: base+overlay ≡ freshly rebuilt snapshot.

The acceptance contract of the live plane's device half (ISSUE r9): for
randomized commit streams of edge adds/removals applied to a
DeltaOverlay, BFS / batched multi-source BFS / SSSP / WCC over
(base CSR + overlay view) are BIT-EQUAL to running on a snapshot
rebuilt from the final edge list — while the base chunked-CSR device
arrays stay untouched.

All tests share the n=192 / m=900 / seed-42 graph shape and fixed pow-2
overlay capacities so the jit shape buckets compile once for the whole
module (tier-1 serial CPU budget).

SSSP runs with UNIFORM weights (w_range=0): hashed weights are keyed on
edge SLOT ids, which a rebuild re-assigns — layout-dependent weights
cannot be bit-stable across compaction by construction (docs/live.md).
"""

import numpy as np
import pytest

from titan_tpu.models.bfs_hybrid import (frontier_bfs_batched,
                                         frontier_bfs_hybrid)
from titan_tpu.models.frontier import (frontier_sssp, frontier_wcc,
                                       pagerank_dense)
from titan_tpu.olap.live.overlay import DeltaOverlay
from titan_tpu.olap.tpu import snapshot as snap_mod

N, M, SEED = 192, 900, 42
CAP = 256          # fixed pow-2 overlay capacity bucket


def _base_edges(rng):
    src = rng.integers(0, N, M).astype(np.int32)
    dst = rng.integers(0, N, M).astype(np.int32)
    return src, dst


def _sym_snapshot(src, dst):
    return snap_mod.from_arrays(N, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


def _apply_stream(rng, src, dst, n_add, n_rm):
    """Random delta stream against a fresh base; returns (base snapshot,
    overlay view, rebuilt snapshot over the final edge list)."""
    base = _sym_snapshot(src, dst)
    ov = DeltaOverlay(base, min_cap=CAP)
    a_s = rng.integers(0, N, n_add).astype(np.int32)
    a_d = rng.integers(0, N, n_add).astype(np.int32)
    ov.append_edges(np.concatenate([a_s, a_d]),
                    np.concatenate([a_d, a_s]),
                    np.zeros(2 * n_add, np.int32))
    rm_idx = rng.choice(M, n_rm, replace=False)
    for i in rm_idx:
        assert ov.remove_edge(int(src[i]), int(dst[i]), None)
        assert ov.remove_edge(int(dst[i]), int(src[i]), None)
    keep = np.ones(M, bool)
    keep[rm_idx] = False
    fs = np.concatenate([src[keep], a_s])
    fd = np.concatenate([dst[keep], a_d])
    return base, ov.view(), _sym_snapshot(fs, fd)


@pytest.mark.parametrize(
    "round_", [0,
               pytest.param(1, marks=pytest.mark.slow),
               pytest.param(2, marks=pytest.mark.slow)])
def test_bfs_batched_bit_equal_to_rebuild(round_):
    rng = np.random.default_rng(SEED + round_)
    base, view, rebuilt = _apply_stream(rng, *_base_edges(rng),
                                        n_add=60, n_rm=40)
    sources = [int(x) for x in rng.choice(N, 4, replace=False)]
    d_ov, lv_ov, c_ov = frontier_bfs_batched(base, sources,
                                             overlay=view)
    d_rb, lv_rb, c_rb = frontier_bfs_batched(rebuilt, sources)
    assert (d_ov == d_rb).all()
    assert (lv_ov == lv_rb).all() and (c_ov == c_rb).all()


def test_sssp_uniform_weights_bit_equal_to_rebuild():
    rng = np.random.default_rng(SEED)
    base, view, rebuilt = _apply_stream(rng, *_base_edges(rng),
                                        n_add=60, n_rm=40)
    s = int(np.flatnonzero(rebuilt.out_degree > 0)[0])
    d_ov, _ = frontier_sssp(base, s, min_w=1.0, w_range=0.0,
                            overlay=view)
    d_rb, _ = frontier_sssp(rebuilt, s, min_w=1.0, w_range=0.0)
    assert (np.asarray(d_ov) == np.asarray(d_rb)).all()


def test_wcc_bit_equal_to_rebuild():
    rng = np.random.default_rng(SEED + 7)
    base, view, rebuilt = _apply_stream(rng, *_base_edges(rng),
                                        n_add=60, n_rm=40)
    lab_ov, _ = frontier_wcc(base, overlay=view)
    lab_rb, _ = frontier_wcc(rebuilt)
    assert (np.asarray(lab_ov) == np.asarray(lab_rb)).all()


def test_overlay_only_reachable_vertex():
    """A vertex with NO base edges, connected purely through overlay
    adds, must be found — including through overlay-only CHAINS (the
    empty-plan relax path in _frontier_run)."""
    rng = np.random.default_rng(SEED)
    # base graph leaves vertices N-3..N-1 isolated
    src = rng.integers(0, N - 3, M).astype(np.int32)
    dst = rng.integers(0, N - 3, M).astype(np.int32)
    base = _sym_snapshot(src, dst)
    ov = DeltaOverlay(base, min_cap=CAP)
    # chain: 0 -> N-3 -> N-2 -> N-1 (symmetrized)
    a_s = np.asarray([0, N - 3, N - 2], np.int32)
    a_d = np.asarray([N - 3, N - 2, N - 1], np.int32)
    ov.append_edges(np.concatenate([a_s, a_d]),
                    np.concatenate([a_d, a_s]), np.zeros(6, np.int32))
    view = ov.view()
    rebuilt = _sym_snapshot(np.concatenate([src, a_s]),
                            np.concatenate([dst, a_d]))
    d_ov, _, _ = frontier_bfs_batched(base, [0], overlay=view)
    d_rb, _, _ = frontier_bfs_batched(rebuilt, [0])
    assert (d_ov == d_rb).all()
    assert d_ov[0, N - 1] < (1 << 30)        # reached through the chain
    s_ov, _ = frontier_sssp(base, 0, min_w=1.0, w_range=0.0,
                            overlay=view)
    s_rb, _ = frontier_sssp(rebuilt, 0, min_w=1.0, w_range=0.0)
    assert (np.asarray(s_ov) == np.asarray(s_rb)).all()
    w_ov, _ = frontier_wcc(base, overlay=view)
    w_rb, _ = frontier_wcc(rebuilt)
    assert (np.asarray(w_ov) == np.asarray(w_rb)).all()


def test_tombstones_disconnect_bridge():
    """Removing every bridge row must make the far side unreachable —
    tombstoned slots may not count as parents."""
    # path 0-1-2-3, bridge 1-2
    src = np.asarray([0, 1, 2] + [4] * (M - 3), np.int32)
    dst = np.asarray([1, 2, 3] + [5] * (M - 3), np.int32)
    base = _sym_snapshot(src, dst)
    ov = DeltaOverlay(base, min_cap=CAP)
    assert ov.remove_edge(1, 2, None) and ov.remove_edge(2, 1, None)
    view = ov.view()
    d_ov, _, _ = frontier_bfs_batched(base, [0], overlay=view)
    assert d_ov[0, 1] == 1 and d_ov[0, 2] >= (1 << 30) \
        and d_ov[0, 3] >= (1 << 30)
    lab, _ = frontier_wcc(base, overlay=view)
    lab = np.asarray(lab)
    assert lab[0] == lab[1] and lab[2] == lab[3] and lab[0] != lab[2]


def test_remove_edge_kills_pending_overlay_add():
    rng = np.random.default_rng(SEED)
    src, dst = _base_edges(rng)
    base = _sym_snapshot(src, dst)
    ov = DeltaOverlay(base, min_cap=CAP)
    ov.append_edges(np.asarray([3, 7], np.int32),
                    np.asarray([7, 3], np.int32),
                    np.zeros(2, np.int32))
    assert ov.remove_edge(3, 7, None) and ov.remove_edge(7, 3, None)
    assert ov.dead_adds == 2 and ov.tomb_count == 0
    view = ov.view()
    d_ov, _, _ = frontier_bfs_batched(base, [3], overlay=view)
    d_rb, _, _ = frontier_bfs_batched(base, [3])
    assert (d_ov == d_rb).all()          # net no-op delta


def test_capacity_buckets_are_pow2_and_stable():
    rng = np.random.default_rng(SEED)
    src, dst = _base_edges(rng)
    base = _sym_snapshot(src, dst)
    ov = DeltaOverlay(base, min_cap=CAP)
    caps = set()
    for k in range(5):
        a = rng.integers(0, N, 100).astype(np.int32)
        b = rng.integers(0, N, 100).astype(np.int32)
        ov.append_edges(a, b, np.zeros(100, np.int32))
        caps.add(ov.cap)
        v = ov.view()
        assert v.cap == ov.cap and v.src_dev.shape == (ov.cap,)
    # power-of-two buckets only — appends within a bucket never change
    # the compiled kernel shapes
    assert all(c & (c - 1) == 0 for c in caps)
    assert ov.cap == 512 and ov.count == 500


def test_view_is_immutable_under_later_appends():
    """A leased view must keep serving its epoch while the overlay
    moves on (the consistent-pair lease contract)."""
    rng = np.random.default_rng(SEED)
    src, dst = _base_edges(rng)
    base = _sym_snapshot(src, dst)
    ov = DeltaOverlay(base, min_cap=CAP)
    ov.append_edges(np.asarray([0], np.int32),
                    np.asarray([1], np.int32), np.zeros(1, np.int32))
    v1 = ov.view()
    ov.append_edges(np.asarray([2], np.int32),
                    np.asarray([3], np.int32), np.zeros(1, np.int32))
    ov.remove_edge(int(src[0]), int(dst[0]), None)
    v2 = ov.view()
    assert v1.count == 1 and v2.count == 2
    assert v1.tomb_count == 0 and v2.tomb_count == 1
    assert int(np.asarray(v1.src_dev[1])) == N + 1   # still padded
    assert v1.seq < v2.seq


def test_base_device_csr_untouched_by_overlay():
    """The whole point: applying deltas through the overlay must not
    invalidate the base snapshot's chunked-CSR device cache."""
    rng = np.random.default_rng(SEED)
    src, dst = _base_edges(rng)
    base = _sym_snapshot(src, dst)
    frontier_bfs_batched(base, [0])                  # builds + caches
    cached = base._hybrid_csr
    ov = DeltaOverlay(base, min_cap=CAP)
    ov.append_edges(np.asarray([0, 1], np.int32),
                    np.asarray([1, 0], np.int32), np.zeros(2, np.int32))
    ov.remove_edge(int(src[0]), int(dst[0]), None)
    frontier_bfs_batched(base, [0], overlay=ov.view())
    assert base._hybrid_csr is cached


def test_guards_on_dirty_overlay():
    """Kernels without an overlay seam refuse loudly instead of
    silently answering from the stale base."""
    rng = np.random.default_rng(SEED)
    src, dst = _base_edges(rng)
    base = _sym_snapshot(src, dst)
    ov = DeltaOverlay(base, min_cap=CAP)
    ov.append_edges(np.asarray([0], np.int32),
                    np.asarray([1], np.int32), np.zeros(1, np.int32))
    base._live_overlay = ov.view()
    with pytest.raises(RuntimeError, match="overlay"):
        frontier_bfs_hybrid(base, 0)
    with pytest.raises(RuntimeError, match="compact"):
        pagerank_dense(base, iterations=1)
    # an explicitly-passed EMPTY view (the compacted lease) overrides
    # the snapshot's attached dirty view
    base2 = _sym_snapshot(src, dst)
    empty = DeltaOverlay(base2, min_cap=CAP).view()
    rank, _ = pagerank_dense(base, iterations=1, overlay=empty)
    assert np.isfinite(np.asarray(rank)).all()
