"""Snapshot pool: sharing + epoch freshness under concurrent writes.

Satellite contract (ISSUE r7): concurrent ``refresh()`` vs commit on a
pooled snapshot — the pool must NEVER hand out a stale-epoch snapshot to
a new job. The race-free form of that guarantee: the snapshot returned
by ``acquire()`` has ``epoch >= graph.mutation_epoch`` as sampled BEFORE
the call (olap/tpu/snapshot.py's build()/refresh() epoch-retry paths do
the heavy lifting; the pool adds the lease/replace discipline on top).
"""

import threading

import numpy as np
import pytest

import titan_tpu
from titan_tpu.olap.serving.pool import SnapshotPool
from titan_tpu.olap.tpu import snapshot as snap_mod


@pytest.fixture
def graph():
    g = titan_tpu.open("inmemory")
    tx = g.new_transaction()
    vs = [tx.add_vertex("node", name=f"v{i}") for i in range(8)]
    for a, b in [(0, 1), (1, 2), (2, 3), (3, 4), (4, 5)]:
        vs[a].add_edge("link", vs[b])
    tx.commit()
    yield g
    g.close()


def _add_edge(g):
    tx = g.new_transaction()
    vs = list(tx.vertices())
    rng = np.random.default_rng()
    a, b = rng.choice(len(vs), size=2, replace=False)
    vs[int(a)].add_edge("link", vs[int(b)])
    tx.commit()


def test_pool_shares_one_snapshot_and_refreshes_on_staleness(graph):
    pool = SnapshotPool(graph)
    try:
        with pool.acquire() as s1:
            edges_before = s1.num_edges
            with pool.acquire() as s2:
                assert s2 is s1          # shared, one build
        _add_edge(graph)
        assert s1.stale
        with pool.acquire() as s3:
            # no leases were out: refreshed IN PLACE (same object,
            # delta-applied — no store re-scan). The pool default is
            # directed=False (the BFS kernels need symmetric graphs),
            # so one committed edge lands as two CSR rows.
            assert s3 is s1
            assert not s3.stale
            assert s3.num_edges == edges_before + 2
    finally:
        pool.close()


def test_pool_replaces_leased_snapshot_instead_of_mutating(graph):
    """A stale snapshot with live leases must not be refreshed in place
    (its arrays feed a running device batch) — the pool hands new jobs a
    REPLACEMENT and retires the old object when its lease drops."""
    pool = SnapshotPool(graph)
    try:
        lease = pool.acquire()
        old = lease.snapshot
        edges_before = old.num_edges
        _add_edge(graph)
        with pool.acquire() as fresh:
            assert fresh is not old
            assert not fresh.stale
            # the leased object kept its pre-commit arrays
            assert old.num_edges == edges_before
        assert pool.stats()["retired"] == 1
        lease.release()
        assert pool.stats()["retired"] == 0   # closed on last release
    finally:
        pool.close()


def test_pool_never_hands_out_stale_epoch_under_concurrent_commits(graph):
    """The satellite race: writers commit continuously while readers
    acquire. Every acquired snapshot's epoch must cover every commit
    that was visible before the acquire started — across the refresh
    fast path, the rebuild fallback, and the replace-when-leased path."""
    pool = SnapshotPool(graph)
    stop = threading.Event()
    errors: list = []

    def writer():
        while not stop.is_set():
            try:
                _add_edge(graph)
            except Exception as e:      # pragma: no cover - fail loud
                errors.append(f"writer: {e!r}")
                return

    def reader():
        for _ in range(25):
            e0 = graph.mutation_epoch
            try:
                with pool.acquire() as snap:
                    if snap.epoch < e0:
                        errors.append(
                            f"stale hand-out: epoch {snap.epoch} < {e0}")
                    # CSR invariants hold on whatever was handed out
                    if snap.indptr_in[-1] != snap.num_edges:
                        errors.append("corrupt CSR after refresh")
            except Exception as e:
                errors.append(f"reader: {type(e).__name__}: {e}")

    writers = [threading.Thread(target=writer) for _ in range(2)]
    readers = [threading.Thread(target=reader) for _ in range(3)]
    for t in writers + readers:
        t.start()
    for t in readers:
        t.join(120)
    stop.set()
    for t in writers:
        t.join(30)
    assert not errors, errors[:5]
    pool.close()


def test_pool_degrades_overflow_to_rebuild_and_reanchors(graph):
    """ISSUE r9 satellites: a listener overflow must degrade acquire()
    to a full rebuild — never a job failure — and (with no leases out)
    the rebuild happens IN PLACE, re-anchoring the change queue so
    delta refresh works again afterwards (the overflow flag used to
    stick forever, forcing every future refresh into a rebuild)."""
    pool = SnapshotPool(graph)
    try:
        with pool.acquire() as s1:
            q = s1._listener
        q.overflowed = True                   # simulate >cap backlog
        _add_edge(graph)
        with pool.acquire() as s2:
            # same object, rebuilt in place at the fresh epoch
            assert s2 is s1
            assert not s2.stale
            assert s2.epoch == graph.mutation_epoch
        assert not q.overflowed               # re-anchored
        # next staleness takes the DELTA path again: the queue
        # accumulates and refresh() applies without a rebuild
        edges_before = s1.num_edges
        _add_edge(graph)
        assert len(q) == 1
        with pool.acquire() as s3:
            assert s3 is s1
            assert s3.num_edges == edges_before + 2   # delta-applied
    finally:
        pool.close()


def test_pool_degrades_edge_values_refusal_to_rebuild(graph):
    """refresh()'s extracted-edge_values NotImplementedError must fall
    back to a rebuild inside acquire(), not surface to the job."""
    import numpy as np
    pool = SnapshotPool(graph)
    try:
        with pool.acquire() as s1:
            s1.edge_values = {"w": np.zeros(s1.num_edges)}
        _add_edge(graph)
        with pool.acquire() as s2:
            assert not s2.stale
            assert s2.epoch == graph.mutation_epoch
            assert not s2.edge_values     # rebuilt without edge_keys
    finally:
        pool.close()


def test_pool_overflow_with_live_lease_replaces(graph):
    """Overflow while a lease is out: in-place rebuild would mutate a
    running batch's arrays — the pool must replace instead."""
    pool = SnapshotPool(graph)
    try:
        lease = pool.acquire()
        old = lease.snapshot
        old._listener.overflowed = True
        _add_edge(graph)
        with pool.acquire() as fresh:
            assert fresh is not old
            assert not fresh.stale
        assert pool.stats()["retired"] == 1
        lease.release()
    finally:
        pool.close()


def test_pool_fixed_snapshot_mode():
    n = 6
    src = np.array([0, 1, 2], np.int32)
    dst = np.array([1, 2, 3], np.int32)
    snap = snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    pool = SnapshotPool(snapshot=snap)
    with pool.acquire() as s:
        assert s is snap
    pool.close()
    with pytest.raises(ValueError):
        SnapshotPool()
