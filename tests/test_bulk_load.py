"""Batch-loading ingest (olap/bulk.py): wire-format compatibility with the
edge codec, SPI-visible rows, and snapshot/BFS equivalence with the
generated-graph path (reference: the storage.batch-loading mode,
GraphDatabaseConfiguration.java STORAGE_BATCH + docs/bulkloading.txt)."""

import jax.numpy as jnp
import numpy as np

import titan_tpu
from titan_tpu.storage.api import KeySliceQuery
from titan_tpu.codec.dataio import ReadBuffer
from titan_tpu.core.defs import Direction, RelationCategory
from titan_tpu.olap import bulk
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.utils import varint


def test_encode_uvar_columns_roundtrip():
    rng = np.random.default_rng(3)
    others = rng.integers(1, 1 << 40, size=500, dtype=np.int64)
    relids = rng.integers(1, 1 << 30, size=500, dtype=np.int64)
    prefix = b"\x17\x02"
    buf, offs = bulk.encode_out_edge_columns(prefix, others, relids)
    data = buf.tobytes()
    for i in range(500):
        col = data[offs[i]:offs[i + 1]]
        assert col[:2] == prefix
        v1, pos = varint.read_positive(col, 2)
        v2, pos = varint.read_positive(col, pos)
        assert (v1, v2) == (others[i], relids[i])
        assert pos == len(col)


def test_encode_backward_uvars_roundtrip():
    relids = np.asarray([1, 127, 128, 1 << 20, (1 << 35) + 5], np.int64)
    buf, offs = bulk.encode_backward_uvars(b"\x01", relids)
    data = buf.tobytes()
    for i, want in enumerate(relids):
        chunk = data[offs[i]:offs[i + 1]]
        v, start = varint.read_positive_backward(chunk, len(chunk), 1)
        assert v == want
        assert start == 1


def _ring_edges(n):
    src = np.arange(n, dtype=np.int64)
    dst = (src + 1) % n
    return src, dst


def test_bulk_rows_parse_via_codec():
    g = titan_tpu.open("inmemory")
    try:
        src, dst = _ring_edges(16)
        res = bulk.bulk_load_adjacency(g, src, dst, n=16, label="knows")
        vids = res["vertex_ids"]
        st = g.schema.get_by_name("knows")
        # read one row back through the SPI and the scalar codec
        key = g.idm.key_bytes(int(vids[3]))
        txh = g.backend.manager.begin_transaction()
        entries = g.backend.edge_store.store.get_slice(
            KeySliceQuery(key, g.codec.query_all()), txh)
        txh.commit()
        assert len(entries) == 2          # exists + one out-edge
        parsed = [g.codec.parse(e, g.schema) for e in entries]
        kinds = {p.category for p in parsed}
        assert kinds == {RelationCategory.PROPERTY, RelationCategory.EDGE}
        edge = next(p for p in parsed if p.is_edge)
        assert edge.type_id == st.id
        assert edge.direction is Direction.OUT
        assert edge.other_vertex_id == int(vids[4])
        prop = next(p for p in parsed if not p.is_edge)
        assert prop.value is True
    finally:
        g.close()


def test_bulk_snapshot_matches_direct_arrays():
    g = titan_tpu.open("inmemory")
    try:
        rng = np.random.default_rng(7)
        n, m = 64, 400
        src = rng.integers(0, n, size=m).astype(np.int64)
        dst = rng.integers(0, n, size=m).astype(np.int64)
        bulk.bulk_load_adjacency(g, src, dst, n=n)
        snap = snap_mod.build(g, directed=False)
        assert snap.n == n
        ref = snap_mod.from_arrays(
            n, np.concatenate([src, dst]).astype(np.int32),
            np.concatenate([dst, src]).astype(np.int32))
        assert snap.num_edges == ref.num_edges
        np.testing.assert_array_equal(np.sort(snap.dst), np.sort(ref.dst))
        np.testing.assert_array_equal(snap.out_degree, ref.out_degree)
        # dst-sorted CSR: per-destination source multisets must agree
        for v in range(n):
            a = np.sort(snap.src[snap.indptr_in[v]:snap.indptr_in[v + 1]])
            b = np.sort(ref.src[ref.indptr_in[v]:ref.indptr_in[v + 1]])
            np.testing.assert_array_equal(a, b)
    finally:
        g.close()


def test_ingest_rmat_store_bfs_matches_generated():
    from titan_tpu.models.bfs import INF
    from titan_tpu.models.bfs_hybrid import (build_chunked_csr,
                                             frontier_bfs_hybrid)

    res = bulk.ingest_rmat_store(8, edge_factor=8, seed=2)
    g, snap = res["graph"], res["snapshot"]
    try:
        # build the generated-graph CSR in-process (no disk cache in CI),
        # with the SAME generator ingest_rmat_store used (native and
        # numpy R-MAT produce different edge sets for one seed)
        from titan_tpu import native
        if native.available:
            src, dst = native.rmat_gen((1 << 8) * 8, 8, seed=2)
        else:
            from titan_tpu.olap.tpu.rmat import rmat_edges
            src, dst = rmat_edges(8, 8, seed=2)
        ref = snap_mod.from_arrays(
            1 << 8, np.concatenate([src, dst]).astype(np.int32),
            np.concatenate([dst, src]).astype(np.int32))
        deg = ref.out_degree
        source = int(np.flatnonzero(deg > 0)[0])
        d1, lv1 = frontier_bfs_hybrid(build_chunked_csr(snap), source)
        d2, lv2 = frontier_bfs_hybrid(build_chunked_csr(ref), source)
        # the level counter includes each path's empty probe level, and
        # the two layouts (store keeps self-loops/duplicates the
        # generated CSR drops) can take different mode ladders — distance
        # equality is the correctness check
        assert abs(lv1 - lv2) <= 1
        np.testing.assert_array_equal(np.minimum(d1, INF),
                                      np.minimum(d2, INF))
        assert bulk.dist_match(jnp.asarray(d1), jnp.asarray(d2), int(INF))
    finally:
        g.close()


def test_bulk_packed_rows_slice_correctly():
    """The packed bulk path adopts whole rows — their columns MUST be
    byte-sorted or every later get_slice binary search breaks. Verify a
    type-sliced read and full-row order on bulk-written rows."""
    g = titan_tpu.open("inmemory")
    try:
        rng = np.random.default_rng(17)
        n, m = 40, 400
        src = rng.integers(0, n, size=m).astype(np.int64)
        dst = rng.integers(0, n, size=m).astype(np.int64)
        res = bulk.bulk_load_adjacency(g, src, dst, n=n, label="L")
        vids = res["vertex_ids"]
        st = g.schema.get_by_name("L")
        txh = g.backend.manager.begin_transaction()
        store = g.backend.edge_store.store
        for i in (0, 3, n - 1):
            key = g.idm.key_bytes(int(vids[i]))
            full = store.get_slice(
                KeySliceQuery(key, g.codec.query_all()), txh)
            colbytes = [e.column for e in full]
            assert colbytes == sorted(colbytes)
            # type-sliced edge read must return exactly this row's edges
            [q] = g.codec.query_type(st.id, Direction.OUT, g.schema)
            edges = store.get_slice(KeySliceQuery(key, q), txh)
            want = int((src == i).sum())
            assert len(edges) == want
        txh.commit()
    finally:
        g.close()


def test_bulk_load_fallback_without_packed_ops(tmp_path):
    """Stores without features.packed_ops (sqlite) take the entry-wise
    path and produce the identical snapshot."""
    g = titan_tpu.open({"storage.backend": "sqlite",
                        "storage.directory": str(tmp_path / "s")})
    try:
        assert not g.backend.manager.features.packed_ops
        src, dst = _ring_edges(32)
        bulk.bulk_load_adjacency(g, src, dst, n=32)
        snap = snap_mod.build(g, directed=False)
        assert snap.n == 32 and snap.num_edges == 64
    finally:
        g.close()


def test_packed_path_refuses_shared_category_prefix_byte(monkeypatch):
    """The packed bulk path orders the exists column against the edge
    columns by ONE byte-compare — sound only while category prefixes
    differ in their first byte. A codec drift that shares the byte must
    be refused up front (ADVICE r5 #4), never adopted as unsorted rows."""
    import pytest

    real = bulk.rids.type_prefix

    def shared_first_byte(type_id, idm, category, direction):
        return b"\x7f" + real(type_id, idm, category, direction)[1:]

    monkeypatch.setattr(bulk.rids, "type_prefix", shared_first_byte)
    g = titan_tpu.open("inmemory")
    try:
        assert g.backend.manager.features.packed_ops
        src, dst = _ring_edges(8)
        with pytest.raises(AssertionError, match="share their first byte"):
            bulk.bulk_load_adjacency(g, src, dst, n=8)
    finally:
        g.close()
