"""Flight recorder + postmortem bundles + /healthz (ISSUE 10).

Acceptance coverage: a forced job failure produces a self-contained
postmortem bundle whose span tree MATCHES ``GET /trace?job=<id>`` and
whose device-event section is non-empty; ``GET /jobs/<id>`` references
the bundle; ``POST /debug/dump`` / ``GET /debug/dumps`` work over HTTP
(409 / disabled without a recorder); and ``GET /healthz`` reports
liveness + readiness (ready ⇔ open scheduler with a live worker, pool
can lease, ledger not in host-merge fallback).

Kernel runs reuse the n=192/m=900/seed-42 smoke bucket
(tests/test_serving.py); recorder-only units use no kernels at all.
"""

import json
import os
import shutil
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import titan_tpu
from titan_tpu.obs.flightrec import BUNDLE_FORMAT, FlightRecorder
from titan_tpu.olap.api import JobSpec
from titan_tpu.olap.recovery import FaultPlan
from titan_tpu.olap.serving.scheduler import JobScheduler
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.server import GraphServer
from titan_tpu.utils.metrics import MetricManager

_N = 192


def _sym_snapshot(seed: int = 42, n: int = _N, m: int = 900):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


@pytest.fixture(scope="module")
def snap_main():
    return _sym_snapshot()


# ---------------------------------------------------------------------------
# recorder units (no kernels)
# ---------------------------------------------------------------------------


def test_ring_is_bounded_and_filterable(tmp_path):
    mm = MetricManager()
    rec = FlightRecorder(str(tmp_path), capacity=8, metrics=mm)
    for i in range(20):
        rec.record("tick", i=i)
    evts = rec.events()
    assert len(evts) == 8                      # oldest 12 dropped
    assert [e["i"] for e in evts] == list(range(12, 20))
    rec.record("other")                        # displaces one tick
    assert len(rec.events("tick")) == 7
    assert all(e["kind"] == "tick" for e in rec.events("tick"))
    assert mm.counter_value("flightrec.ring.events") == 21


def test_metric_delta_journals_counter_movement(tmp_path):
    mm = MetricManager()
    rec = FlightRecorder(str(tmp_path), metrics=mm)
    mm.counter("serving.jobs.submitted").inc(3)
    rec.metric_delta()
    mm.counter("serving.jobs.submitted").inc(2)
    rec.metric_delta()
    rec.metric_delta()                         # no movement: no event
    deltas = rec.events("metrics")
    assert len(deltas) == 2
    assert deltas[0]["delta"]["serving.jobs.submitted"] == 3
    assert deltas[1]["delta"]["serving.jobs.submitted"] == 2


def test_dump_bundle_is_parseable_and_atomic(tmp_path):
    mm = MetricManager()
    rec = FlightRecorder(str(tmp_path), metrics=mm, clock=lambda: 123.0)
    rec.record("span", trace="j1", name="round", start=1.0, end=2.0,
               attrs={"frontier": np.int64(7)})   # numpy must not throw
    path = rec.dump(reason="manual", job={"job": "j1"},
                    state={"pool": {"entries": 1}},
                    config={"max_batch": 8})
    bundle = json.load(open(path))
    assert bundle["format"] == BUNDLE_FORMAT
    assert bundle["dumped_at"] == 123.0
    assert bundle["reason"] == "manual"
    assert bundle["rounds"][0]["attrs"]["frontier"] == 7
    assert bundle["state"]["pool"]["entries"] == 1
    assert not [f for f in os.listdir(tmp_path)
                if f.endswith(".tmp")]            # rename committed
    assert mm.counter_value("flightrec.dump.written") == 1
    idx = rec.index()
    assert idx[0]["path"] == path and idx[0]["bytes"] > 0


def test_dump_rounds_are_per_job_and_capped(tmp_path):
    rec = FlightRecorder(str(tmp_path), metrics=MetricManager(),
                         max_rounds_in_dump=4)
    for i in range(10):
        rec.record("span", trace="a", name="round", start=i, end=i)
        rec.record("span", trace="b", name="round", start=i, end=i)
    path = rec.dump(reason="failed", job={"job": "a"})
    bundle = json.load(open(path))
    assert len(bundle["rounds"]) == 4            # last-N only
    assert all(r["trace"] == "a" for r in bundle["rounds"])
    assert bundle["rounds"][-1]["start"] == 9


def test_unwritable_dump_dir_counts_errors(tmp_path):
    mm = MetricManager()
    d = tmp_path / "dumps"
    rec = FlightRecorder(str(d), metrics=mm)
    shutil.rmtree(d)                             # storage vanished
    with pytest.raises(OSError):
        rec.dump(reason="manual")
    assert mm.counter_value("flightrec.dump.errors") == 1
    assert mm.counter_value("flightrec.dump.written") == 0
    assert rec.index() == []                     # index survives


# ---------------------------------------------------------------------------
# scheduler integration: the acceptance path
# ---------------------------------------------------------------------------


def _sched(snap, tmp_path, **kw):
    return JobScheduler(snapshot=snap, metrics=MetricManager(),
                        flight_dir=str(tmp_path), **kw)


def test_forced_failure_writes_matching_bundle(snap_main, tmp_path):
    """ISSUE 10 acceptance: FAILED job → bundle with (a) a span tree
    byte-equal to GET /trace's, (b) a non-empty device-event section,
    (c) >= 1 round record for the job, referenced from the job wire."""
    sched = _sched(snap_main, tmp_path, checkpoint_dir=str(
        tmp_path / "ck"))
    try:
        job = sched.submit(JobSpec(
            kind="bfs",
            params={"source_dense": 0,
                    "faults": FaultPlan(crash_at_round=2)},
            checkpoint_every=1))
        job.wait(60)
        assert job.state.value == "failed"
        deadline = time.time() + 10              # dump lands just after
        while job.dump_path is None and time.time() < deadline:
            time.sleep(0.02)
        assert job.dump_path and os.path.exists(job.dump_path)
        bundle = json.load(open(job.dump_path))
        assert bundle["format"] == BUNDLE_FORMAT
        assert bundle["reason"] == "failed"
        # (a) span tree == the trace endpoint's view, terminal included
        tree = sched.tracer.tree(job.id)
        assert json.loads(json.dumps(tree)) == bundle["span_tree"]
        names = []

        def walk(n):
            names.append(n["name"])
            [walk(c) for c in n["children"]]
        for root in bundle["span_tree"]["spans"]:
            walk(root)
        assert "failed" in names and "round" in names
        # (b) the profiler fed the ring: device events present
        assert bundle["device_events"], "device-event section empty"
        assert bundle["device_totals"]["calls"] > 0
        # (c) per-round records for THIS job
        assert bundle["rounds"]
        assert all(r["trace"] == job.id for r in bundle["rounds"])
        # referenced from the wire envelope
        assert job.to_wire()["postmortem"] == job.dump_path
        # system state rides along
        assert bundle["state"]["scheduler"]["running_batch"] == 0
        assert bundle["config"]["max_batch"] == sched.max_batch
    finally:
        sched.close()


def test_first_retry_dumps_once(snap_main, tmp_path):
    """RETRYING (attempt 2) writes the evidence bundle while it is
    fresh; the successful resume does NOT write another."""
    sched = _sched(snap_main, tmp_path, checkpoint_dir=str(
        tmp_path / "ck"))
    try:
        job = sched.submit(JobSpec(
            kind="bfs",
            params={"source_dense": 0,
                    "faults": FaultPlan(crash_at_round=2)},
            max_retries=1, checkpoint_every=1))
        job.wait(60)
        assert job.state.value == "done"
        dumps = sched.recorder.index()
        assert len(dumps) == 1
        bundle = json.load(open(dumps[0]["path"]))
        assert bundle["reason"] == "retrying"
        assert bundle["job"]["job"] == job.id
    finally:
        sched.close()


def test_dump_debug_on_demand_and_unknown_job(snap_main, tmp_path):
    sched = _sched(snap_main, tmp_path)
    try:
        path = sched.dump_debug()
        assert json.load(open(path))["reason"] == "manual"
        with pytest.raises(ValueError):
            sched.dump_debug("no-such-job")
    finally:
        sched.close()


def test_no_flight_dir_means_no_plane(snap_main):
    sched = JobScheduler(snapshot=snap_main, metrics=MetricManager())
    try:
        assert sched.recorder is None
        assert sched.tracer.tap is None
        with pytest.raises(ValueError):
            sched.dump_debug()
        job = sched.submit(JobSpec(
            kind="bfs", params={"source_dense": 0,
                                "faults": FaultPlan(crash_at_round=2)}))
        job.wait(60)
        assert job.state.value == "failed"
        assert job.dump_path is None             # nothing written
        assert "postmortem" not in job.to_wire()
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# HTTP surface: /healthz, /debug/dump, /debug/dumps
# ---------------------------------------------------------------------------


def _req(srv, path, payload=None, method="GET"):
    req = urllib.request.Request(
        f"http://{srv.host}:{srv.port}{path}",
        data=json.dumps(payload).encode() if payload is not None
        else None,
        headers={"Content-Type": "application/json"}, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def served_flight(snap_main, tmp_path):
    g = titan_tpu.open("inmemory")
    sched = _sched(snap_main, tmp_path)
    srv = GraphServer(g, port=0, scheduler=sched).start()
    yield srv, sched, tmp_path
    srv.stop()
    sched.close()
    g.close()


def test_healthz_ready_and_checks(served_flight):
    srv, sched, _ = served_flight
    code, body = _req(srv, "/healthz")
    assert code == 200
    assert body["live"] is True and body["ready"] is True
    assert body["checks"]["scheduler_open"] is True
    assert "snapshot" in body["checks"]["snapshot_pool"] \
        or "fixed" in body["checks"]["snapshot_pool"]
    assert body["checks"]["ledger_ok"] is True


def test_healthz_not_ready_without_live_worker(snap_main, tmp_path):
    """Readiness is falsifiable: a scheduler whose worker never started
    (autostart=False) answers 503 with the failing check named."""
    g = titan_tpu.open("inmemory")
    sched = JobScheduler(snapshot=snap_main, metrics=MetricManager(),
                         autostart=False)
    srv = GraphServer(g, port=0, scheduler=sched).start()
    try:
        code, body = _req(srv, "/healthz")
        assert code == 503
        assert body["live"] is True and body["ready"] is False
        assert body["checks"]["scheduler_open"] is False
    finally:
        srv.stop()
        sched.close()
        g.close()


def test_debug_dump_and_index_over_http(served_flight):
    srv, sched, tmp = served_flight
    code, body = _req(srv, "/debug/dumps")
    assert code == 200
    assert body["enabled"] is True and body["dumps"] == []
    code, body = _req(srv, "/debug/dump", {}, method="POST")
    assert code == 200
    assert os.path.exists(body["path"])
    code, body = _req(srv, "/debug/dumps")
    assert body["enabled"] is True
    assert len(body["dumps"]) == 1
    assert body["dumps"][0]["file"].startswith("dump-")
    # anchored to an unknown job: a clean 400, no bundle written
    code, body = _req(srv, "/debug/dump", {"job": "nope"},
                      method="POST")
    assert code == 400
    # valid JSON but not an object: still a client-error 400, not 500
    code, body = _req(srv, "/debug/dump", [1], method="POST")
    assert code == 400
    assert len(sched.recorder.index()) == 1


def test_debug_dump_409_without_recorder(snap_main):
    g = titan_tpu.open("inmemory")
    sched = JobScheduler(snapshot=snap_main, metrics=MetricManager())
    srv = GraphServer(g, port=0, scheduler=sched).start()
    try:
        code, body = _req(srv, "/debug/dump", {}, method="POST")
        assert code == 409
        code, body = _req(srv, "/debug/dumps")
        assert code == 200 and body["enabled"] is False
    finally:
        srv.stop()
        sched.close()
        g.close()
