"""Metric-name doc-drift guard (ISSUE r10 satellite).

Every ``serving.*`` / ``serving.live.*`` / ``serving.recovery.*`` —
and, since ISSUE 10, ``device.*`` / ``flightrec.*`` — metric name
created in code must appear in a docs/monitoring.md table, and every
name documented there must exist in code — so the tables stop rotting
as planes grow.

The code scan finds quoted metric-name literals (all real names have
>= 3 dot components, which screens out prefix constants like
``"serving.recovery"``); the two templated families are expanded from
the SAME constants the code iterates (``JobScheduler._STATE_COUNTER``,
``plane._LIVE_COUNTERS``), and recovery/store.py's prefix-built names
are resolved against its default prefix.
"""

import os
import re

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "titan_tpu")
_DOC = os.path.join(_REPO, "docs", "monitoring.md")

# quoted literal with >= 3 dot-components under a guarded family
# prefix; {x} keeps f-string placeholders visible for template
# expansion (device./flightrec. joined serving. in ISSUE 10;
# controller./scan. in ISSUE 14 — the autotune decision plane and the
# distributed-scan instrumentation; obs. in ISSUE 18 — span ingest +
# metrics federation; fleet. in ISSUE 19 — the replica fleet tier,
# whose metric names live under serving.fleet.* but whose family is
# guarded on its own so a future top-level fleet.* name can't dodge
# the doc tables)
_FAMILIES = r"(?:serving|device|flightrec|controller|scan|obs|fleet)"
_LITERAL = re.compile(
    r"""["']f?(""" + _FAMILIES
    + r"""\.[a-z0-9_]+\.[a-z0-9_.{}]+)["']""")
_FSTRING = re.compile(
    r"""f["'](""" + _FAMILIES
    + r"""\.[a-z0-9_]+\.[a-z0-9_.{}]+)["']""")
# names recovery/store.py builds off its configurable prefix (default
# "serving.recovery")
_PREFIXED = re.compile(r"""f["']\{self\._prefix\}\.([a-z0-9_]+)["']""")
# a table row's first column: | `serving.x.y` | ... |
_DOC_ROW = re.compile(
    r"^\|\s*`(" + _FAMILIES + r"\.[a-z0-9_.]+)`\s*\|",
    re.MULTILINE)


def _code_metric_names() -> set:
    from titan_tpu.obs.slo import _BAD_STATES, _GOOD_STATES
    from titan_tpu.olap.live.plane import _LIVE_COUNTERS
    from titan_tpu.olap.serving.scheduler import JobScheduler

    expansions = {
        "serving.jobs.{name}": [
            f"serving.jobs.{v}"
            for v in JobScheduler._STATE_COUNTER.values()],
        "serving.live.{k}": [f"serving.live.{k}"
                             for k in _LIVE_COUNTERS],
        # the SLO engine READS these state counters (obs/slo SLI)
        "serving.jobs.{s}": [f"serving.jobs.{s}"
                             for s in _GOOD_STATES + _BAD_STATES],
    }
    names: set = set()
    for dirpath, dirnames, filenames in os.walk(_PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                src = f.read()
            for m in set(_LITERAL.findall(src)) | set(
                    _FSTRING.findall(src)):
                if "{" in m:
                    got = expansions.get(m)
                    assert got is not None, (
                        f"{fn}: templated metric name {m!r} has no "
                        f"registered expansion — add it to this test "
                        f"(and docs/monitoring.md)")
                    names.update(got)
                else:
                    names.add(m)
            for m in _PREFIXED.findall(src):
                names.add(f"serving.recovery.{m}")
    return names


def _doc_metric_names() -> set:
    with open(_DOC) as f:
        return set(_DOC_ROW.findall(f.read()))


def test_every_code_metric_documented_and_vice_versa():
    code = _code_metric_names()
    docs = _doc_metric_names()
    # sanity: the scan actually found every family (ISSUE 8 extended
    # the guard to the tenant/SLO/gauge names)
    for family in ("serving.jobs.", "serving.live.",
                   "serving.recovery.", "serving.tenant.",
                   "serving.slo.", "serving.hbm.", "serving.pool.",
                   # ISSUE 10: the device-cost + flight-recorder planes
                   "device.compile.", "device.exec.", "device.xfer.",
                   "flightrec.",
                   # ISSUE 11: the interactive point-query lane
                   "serving.interactive.",
                   # ISSUE 14: the autotune decision plane + the
                   # distributed-scan instrumentation
                   "controller.", "scan.remote.",
                   # ISSUE 18: cross-process span ingest + metrics
                   # federation
                   "obs.ingest.", "obs.federate.",
                   # ISSUE 19: the replica fleet routing/failover tier
                   "serving.fleet."):
        assert any(n.startswith(family) for n in code), (family, code)
    # ISSUE 19: the fleet router's admission/failover evidence must
    # stay in the scan (created in olap/fleet/router.py) — including
    # the single-count admission counter the double-count regression
    # test pins
    for name in ("serving.fleet.routed",
                 "serving.fleet.redispatches",
                 "serving.fleet.redispatch_latency_ms",
                 "serving.fleet.replicas_up",
                 "serving.jobs.submitted"):
        assert name in code, name
    # ISSUE 18: the cross-process observability surface must stay in
    # the scan (created in obs/tracing.ingest and obs/federate)
    for name in ("obs.ingest.spans", "obs.ingest.dropped",
                 "obs.ingest.clamped",
                 "obs.federate.scrapes", "obs.federate.errors",
                 "obs.federate.evicted",
                 "obs.federate.series_dropped"):
        assert name in code, name
    # ISSUE 14: the controller's decision-flow surface must stay in
    # the scan (created in olap/serving/autotune.py)
    for name in ("controller.tick.count",
                 "controller.decisions.applied",
                 "controller.decisions.shadowed",
                 "controller.journal.dropped",
                 "controller.knob.value",
                 "scan.remote.splits_dispatched",
                 "scan.remote.splits_redispatched",
                 "scan.remote.worker_failures"):
        assert name in code, name
    # ISSUE 11: the interactive lane's fuse/fallback evidence must stay
    # in the scan (created in olap/serving/interactive/scheduler.py)
    for name in ("serving.interactive.requests",
                 "serving.interactive.fallbacks",
                 "serving.interactive.fuse_k",
                 "serving.interactive.latency_ms"):
        assert name in code, name
    # ISSUE 10: the device-cost observability surface must stay in the
    # scan (created in obs/devprof and obs/flightrec)
    for name in ("device.compile.count", "device.exec.ms",
                 "device.xfer.h2d_bytes", "device.xfer.d2h_bytes",
                 "flightrec.ring.events", "flightrec.dump.written"):
        assert name in code, name
    # ISSUE 9: the epoch-compaction byte/fallback surface must stay in
    # the scan (created in overlay/compactor AND via the _LIVE_COUNTERS
    # template the plane iterates)
    for name in ("serving.live.upload_bytes",
                 "serving.live.download_bytes",
                 "serving.live.device_merge_fallbacks",
                 "serving.live.compact_device_ms"):
        assert name in code, name
    missing_from_docs = code - docs
    assert not missing_from_docs, (
        "metric names created in code but absent from a "
        "docs/monitoring.md table: "
        f"{sorted(missing_from_docs)}")
    stale_in_docs = docs - code
    assert not stale_in_docs, (
        "metric names documented in docs/monitoring.md but no longer "
        f"created anywhere in code: {sorted(stale_in_docs)}")
