"""Native (C++) kernel tests: varint bulk decode, head classification, CSR
build — each cross-checked against the pure-Python/numpy implementations.

(reference analog: titan-test graphdb/serializer/SerializerSpeedTest.java and
VariableLongTest.java cover the same codec surface on the JVM.)"""

import numpy as np
import pytest

import titan_tpu
from titan_tpu import example, native
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.utils import varint

pytestmark = pytest.mark.skipif(not native.available,
                                reason="native library not built")


class TestBulkVarint:
    def test_matches_python_codec(self):
        rng = np.random.default_rng(7)
        values = np.concatenate([
            rng.integers(0, 128, 50),
            rng.integers(0, 1 << 20, 50),
            rng.integers(0, 1 << 62, 50),
            [0, 1, 127, 128, (1 << 63) - 1],
        ]).astype(np.uint64)
        buf = bytearray()
        offsets = []
        for v in values.tolist():
            offsets.append(len(buf))
            varint.write_positive(buf, int(v))
        data = np.frombuffer(bytes(buf), dtype=np.uint8)
        got, ends = native.bulk_read_uvar(data, np.asarray(offsets))
        assert got.astype(np.uint64).tolist() == values.tolist()
        # each end == next start
        assert ends[:-1].tolist() == offsets[1:]
        assert ends[-1] == len(buf)

    def test_matches_numpy_bulk(self):
        buf = bytearray()
        offsets = []
        for v in [3, 1000, 1 << 40, 5]:
            offsets.append(len(buf))
            varint.write_positive(buf, v)
        data = np.frombuffer(bytes(buf), dtype=np.uint8)
        v1, e1 = native.bulk_read_uvar(data, np.asarray(offsets))
        v2, e2 = varint.bulk_read_positive(data, np.asarray(offsets))
        assert v1.tolist() == v2.tolist()
        assert e1.tolist() == e2.tolist()

    def test_corrupt_raises(self):
        data = np.array([0x01, 0x02], dtype=np.uint8)  # no stop bit
        with pytest.raises(ValueError):
            native.bulk_read_uvar(data, np.array([0]))


class TestCSRBuild:
    def test_matches_numpy(self):
        rng = np.random.default_rng(3)
        n, e = 50, 400
        src = rng.integers(0, n, e).astype(np.int32)
        dst = rng.integers(0, n, e).astype(np.int32)
        order, indptr, out_degree = native.csr_build(src, dst, n)
        ref_order = np.argsort(dst, kind="stable")
        assert order.tolist() == ref_order.tolist()
        ref_indptr = np.zeros(n + 1, dtype=np.int64)
        np.add.at(ref_indptr, dst[ref_order] + 1, 1)
        np.cumsum(ref_indptr, out=ref_indptr)
        assert indptr.tolist() == ref_indptr.tolist()
        ref_deg = np.zeros(n, dtype=np.int32)
        np.add.at(ref_deg, src, 1)
        assert out_degree.tolist() == ref_deg.tolist()
        assert native.gather_i32(src, order).tolist() == src[order].tolist()

    def test_empty(self):
        order, indptr, deg = native.csr_build(
            np.empty(0, np.int32), np.empty(0, np.int32), 4)
        assert indptr.tolist() == [0] * 5
        assert deg.tolist() == [0] * 4


class TestNativeScanMatchesPython:
    """The whole-snapshot cross-check: native bulk ingest must produce the
    same graph as the per-entry Python codec path."""

    @pytest.fixture
    def gods(self):
        g = titan_tpu.open("inmemory")
        example.load(g)
        yield g
        g.close()

    def _canon(self, snap):
        edges = sorted(zip(snap.src.tolist(), snap.dst.tolist(),
                           (snap.labels.tolist() if snap.labels is not None
                            else [0] * snap.num_edges)))
        return snap.n, snap.vertex_ids.tolist(), edges

    def test_same_snapshot(self, gods, monkeypatch):
        snap_native = snap_mod.build(gods)
        monkeypatch.setattr(native, "available", False)
        snap_python = snap_mod.build(gods)
        assert self._canon(snap_native) == self._canon(snap_python)

    def test_label_filter_same(self, gods, monkeypatch):
        a = snap_mod.build(gods, labels=["battled", "father"])
        monkeypatch.setattr(native, "available", False)
        b = snap_mod.build(gods, labels=["battled", "father"])
        assert self._canon(a) == self._canon(b)
