"""HTTP job endpoints: the serving layer's wire surface.

POST /jobs, GET /jobs[/<id>], DELETE /jobs/<id> over the gods example
graph, including the in-CI version of scripts/serve_smoke.sh: 8
concurrent BFS jobs submitted through the wire, all fusing into one
batched device run, each completing with its own (distinct) result.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import titan_tpu
from titan_tpu import example
from titan_tpu.olap.serving.scheduler import JobScheduler
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.server import GraphServer
from titan_tpu.utils.metrics import MetricManager


def _req(srv, path, payload=None, method="GET"):
    req = urllib.request.Request(
        f"http://{srv.host}:{srv.port}{path}",
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"Content-Type": "application/json"}, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _poll(srv, job_id, timeout=90.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        code, body = _req(srv, f"/jobs/{job_id}")
        assert code == 200
        if body["status"] not in ("queued", "running"):
            return body
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} did not finish")


@pytest.fixture
def served():
    g = titan_tpu.open("inmemory")
    example.load(g)
    srv = GraphServer(g, port=0).start()
    yield g, srv
    srv.stop()
    g.close()


def test_job_submit_poll_result_and_delete_conflict(served):
    g, srv = served
    code, body = _req(srv, "/traversal",
                      {"gremlin": "g.V().has('name','hercules')"
                                  ".next().id"}, method="POST")
    assert code == 200
    vid = body["result"]
    code, body = _req(srv, "/jobs",
                      {"kind": "bfs", "source": vid, "targets": [vid]},
                      method="POST")
    assert code == 202 and body["status"] == "queued"
    final = _poll(srv, body["job"])
    assert final["status"] == "done", final
    # symmetrized gods graph is one connected component of 12
    assert final["result"]["reached"] == 12
    assert final["result"]["targets"][str(vid)] == 0
    assert final["batch_k"] == 1 and final["exec_ms"] > 0
    # cancel after completion -> 409 Conflict
    code, body = _req(srv, f"/jobs/{final['job']}", method="DELETE")
    assert code == 409
    # unknown id -> 404; listing carries stats
    code, _ = _req(srv, "/jobs/nope")
    assert code == 404
    code, body = _req(srv, "/jobs")
    assert code == 200 and body["stats"]["jobs_total"] >= 1


def test_job_bad_kind_rejected(served):
    _, srv = served
    code, body = _req(srv, "/jobs", {"kind": "explode"}, method="POST")
    assert code == 400 and "unknown job kind" in body["error"]


def test_job_numeric_fields_coerced_at_the_wire(served):
    """A string timeout_s (easy for JSON clients to send) must be
    coerced at submit — an uncoerced one would detonate inside the
    fused batch's level callback and fail every batchmate. Garbage
    values are a 400 for the one caller, not a batch failure."""
    _, srv = served
    code, body = _req(srv, "/jobs",
                      {"kind": "bfs", "source_dense": 0,
                       "timeout_s": "30", "max_levels": "5"},
                      method="POST")
    assert code == 202
    final = _poll(srv, body["job"])
    assert final["status"] == "done", final
    code, body = _req(srv, "/jobs",
                      {"kind": "bfs", "source_dense": 0,
                       "timeout_s": "soon"}, method="POST")
    assert code == 400


def test_delete_cancels_queued_job(served):
    g, srv = served
    # paused scheduler: the job stays QUEUED so DELETE hits the
    # queued-cancellation path deterministically
    metrics = MetricManager()
    srv._scheduler = JobScheduler(graph=g, metrics=metrics,
                                  autostart=False)
    code, body = _req(srv, "/jobs", {"kind": "bfs", "source_dense": 0},
                      method="POST")
    assert code == 202
    code, body = _req(srv, f"/jobs/{body['job']}", method="DELETE")
    assert code == 200 and body["status"] == "cancelled"
    assert metrics.counter_value("serving.jobs.cancelled") == 1


def test_eight_concurrent_jobs_fuse_and_return_distinct_results(served):
    """The smoke contract (scripts/serve_smoke.sh runs the same flow
    out-of-process): 8 BFS jobs POSTed concurrently against a paused
    scheduler fuse into ONE batch and each completes with its own
    per-source result, checked against sequential references."""
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid

    g, srv = served
    metrics = MetricManager()
    srv._scheduler = JobScheduler(graph=g, metrics=metrics,
                                  autostart=False)
    code, body = _req(srv, "/traversal",
                      {"gremlin": "sorted(v.id for v in g.V().to_list())"},
                      method="POST")
    assert code == 200
    vids = body["result"][:8]
    results: dict = {}
    errors: list = []

    def submit(vid):
        try:
            code, body = _req(srv, "/jobs",
                              {"kind": "bfs", "source": vid,
                               "targets": [vids[0]]}, method="POST")
            assert code == 202, body
            results[vid] = body["job"]
        except Exception as e:       # pragma: no cover - fail loud
            errors.append(repr(e))

    threads = [threading.Thread(target=submit, args=(v,)) for v in vids]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors and len(results) == 8, (errors, results)
    srv._scheduler.start()

    # reference: sequential single-source runs on an equivalent
    # symmetrized snapshot
    snap = snap_mod.build(g, directed=False)
    finals = {vid: _poll(srv, jid) for vid, jid in results.items()}
    for vid, final in finals.items():
        assert final["status"] == "done", final
        assert final["batch_k"] == 8     # ONE fused batch
        ref, _ = frontier_bfs_hybrid(snap, snap.dense_of(vid))
        ref = np.asarray(ref)
        assert final["result"]["reached"] == int((ref < (1 << 30)).sum())
        want = int(ref[snap.dense_of(vids[0])])
        got = final["result"]["targets"][str(vids[0])]
        assert got == (want if want < (1 << 30) else None)
    # distinct sources produced distinct jobs (and distinct distances
    # to the probe target for at least two of them)
    assert len({f["job"] for f in finals.values()}) == 8
    target_dists = [f["result"]["targets"][str(vids[0])]
                    for f in finals.values()]
    assert len(set(target_dists)) > 1
    assert metrics.histogram("serving.batch.occupancy").max == 8


def test_tenant_wire_quota_429_and_tenant_slo_endpoints(served):
    """ISSUE 8 wire surface: ``tenant`` rides the POST /jobs body into
    the envelope; a quota-refused submit is 429 + retryable (never a
    400 caller error); GET /tenants returns the attribution rows +
    quotas; GET /slo reports burn rates (and {"enabled": false}
    without objectives)."""
    from titan_tpu.obs.slo import SLO
    from titan_tpu.olap.serving.tenants import TenantQuota

    g, srv = served
    # default scheduler first: /slo and /tenants answer without setup
    code, body = _req(srv, "/slo")
    assert code == 200 and body == {"enabled": False}
    code, body = _req(srv, "/tenants")
    assert code == 200 and body["enforce_quotas"] is False

    sched = JobScheduler(
        graph=g, autostart=False, enforce_quotas=True,
        quotas={"flood": TenantQuota(max_in_flight=1)},
        slos=[SLO("flood-avail", tenant="flood",
                  success_rate=0.999)])
    srv._scheduler = sched
    code, body = _req(srv, "/traversal",
                      {"gremlin": "g.V().has('name','hercules')"
                                  ".next().id"}, method="POST")
    vid = body["result"]
    code, j1 = _req(srv, "/jobs",
                    {"kind": "bfs", "source": vid,
                     "tenant": "flood"}, method="POST")
    assert code == 202 and j1["tenant"] == "flood"
    # paused worker keeps j1 in flight → the second submit violates
    code, err = _req(srv, "/jobs",
                     {"kind": "bfs", "source": vid,
                      "tenant": "flood"}, method="POST")
    assert code == 429, err
    assert err["type"] == "QuotaExceeded" and err["retryable"] is True
    # other tenants unaffected; absent tenant falls back to default
    code, j2 = _req(srv, "/jobs", {"kind": "bfs", "source": vid},
                    method="POST")
    assert code == 202 and j2["tenant"] == "default"
    sched.start()
    assert _poll(srv, j1["job"])["status"] == "done"
    assert _poll(srv, j2["job"])["status"] == "done"
    code, body = _req(srv, "/tenants")
    assert code == 200 and body["enforce_quotas"] is True
    rows = body["tenants"]
    assert rows["flood"]["rejected"] == 1
    assert rows["flood"]["by_state"] == {"completed": 1}
    assert rows["default"]["device_seconds"] > 0
    assert body["quotas"]["flood"]["max_in_flight"] == 1
    code, body = _req(srv, "/slo")
    assert code == 200 and body["enabled"] is True
    (s,) = body["slos"]
    assert s["slo"] == "flood-avail" and s["tenant"] == "flood"
    assert s["sli"]["ok"] is True
    assert s["windows"]["300s"]["burn_rate"] == 0.0
