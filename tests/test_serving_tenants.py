"""Per-tenant SLO plane (ISSUE 8): attribution, quotas, fallbacks.

Covers the tenancy dimension end to end: labeled per-tenant counters
summing EXACTLY to the unlabeled aggregate under concurrent
multi-tenant submits; absent/unknown tenant falling back to
``"default"`` everywhere (wire envelopes, traces, metrics) rather than
a KeyError; quota admission in shadow vs enforce mode (429 over HTTP);
device-seconds / HBM-byte-seconds attribution across a mixed-tenant
fused batch; and the no-tenant regression criterion (metric names,
snapshot schema, exposition parents unchanged).

Host-heavy by design: most paths use ``callable`` jobs (no device
kernels); the fused-batch attribution test reuses the n=192/m=900/
seed-42 shape + K=8 shared with tests/test_serving.py so the XLA
compile buckets stay warm.
"""

import threading
import time

import numpy as np
import pytest

from titan_tpu.olap.api import JobSpec
from titan_tpu.olap.serving.scheduler import JobScheduler
from titan_tpu.olap.serving.tenants import (DEFAULT_TENANT,
                                            QuotaExceeded,
                                            TenantAccounting,
                                            TenantQuota,
                                            effective_tenant)
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.utils.metrics import MetricManager

_N = 192      # ONE shape across serving suites (compile buckets)


def _sym_snapshot(seed: int = 42, n: int = _N, m: int = 900):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


@pytest.fixture(scope="module")
def snap_main():
    return _sym_snapshot()


def _callable_spec(tenant=None, value=1, **kw):
    return JobSpec(kind="callable", params={"fn": lambda: value},
                   tenant=tenant, **kw)


# --------------------------------------------------------------------------
# tenant identity + fallback
# --------------------------------------------------------------------------

def test_effective_tenant_fallback_and_stringification():
    assert effective_tenant(None) == DEFAULT_TENANT == "default"
    assert effective_tenant("") == "default"
    assert effective_tenant("team-a") == "team-a"
    assert effective_tenant(7) == "7"          # wire may send numbers


def test_absent_tenant_is_default_everywhere(snap_main):
    """No ``tenant`` on the spec → "default" in the wire envelope, the
    trace root attrs, the metric children and the accounting rows —
    never a KeyError anywhere."""
    m = MetricManager()
    sched = JobScheduler(snapshot=snap_main, metrics=m)
    try:
        job = sched.submit(_callable_spec())
        assert job.wait(30) and job.state.value == "done"
        assert job.tenant == "default"
        assert job.to_wire()["tenant"] == "default"
        # trace root carries the tenant attr
        tree = sched.tracer.tree(job.id)
        assert tree["spans"][0]["attrs"]["tenant"] == "default"
        # metrics children labeled with the default tenant
        assert m.counter_value("serving.jobs.completed",
                               labels={"tenant": "default"}) == 1
        # accounting row exists under "default"
        rows = sched.tenant_stats()["tenants"]
        assert rows["default"]["submitted"] == 1
        assert rows["default"]["by_state"] == {"completed": 1}
        # an unknown tenant string is just a new row, never an error
        j2 = sched.submit(_callable_spec(tenant="never-seen"))
        assert j2.wait(30)
        assert sched.tenant_stats()["tenants"]["never-seen"][
            "submitted"] == 1
    finally:
        sched.close()


# --------------------------------------------------------------------------
# the roll-up property under concurrency
# --------------------------------------------------------------------------

def test_labeled_counters_sum_to_aggregate_under_concurrent_submits(
        snap_main):
    """ISSUE 8 property: after a concurrent multi-tenant burst, the
    per-tenant children of every job counter sum EXACTLY to the
    unlabeled aggregate, and per-tenant counts match what each thread
    actually submitted."""
    m = MetricManager()
    sched = JobScheduler(snapshot=snap_main, metrics=m)
    tenants = ["alpha", "beta", "gamma", None]
    per_thread = 12
    jobs: list = []
    jobs_lock = threading.Lock()

    def submitter(k):
        mine = []
        for i in range(per_thread):
            mine.append(sched.submit(_callable_spec(
                tenant=tenants[(k + i) % len(tenants)])))
        with jobs_lock:
            jobs.extend(mine)

    try:
        threads = [threading.Thread(target=submitter, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        total = 4 * per_thread
        assert len(jobs) == total
        for j in jobs:
            assert j.wait(60), j
        # worker finalizes counters just after wait() fires — poll
        deadline = time.time() + 10
        while time.time() < deadline and m.counter_value(
                "serving.jobs.completed") < total:
            time.sleep(0.01)
        for name in ("serving.jobs.submitted",
                     "serving.jobs.completed"):
            assert m.counter_value(name) == total
            kids = m.children(name)
            assert sum(c.count for _l, c in kids) == total, name
            # every tenant (incl. the default fallback) present
            seen = {lbl["tenant"] for lbl, _c in kids}
            assert seen == {"alpha", "beta", "gamma", "default"}
        # 4 threads x 12 jobs round-robined over 4 tenants = 12 each
        assert m.counter_value("serving.jobs.completed",
                               labels={"tenant": "alpha"}) == 12
        # latency histogram children roll up exactly too
        lat = m.histogram("serving.job.latency_ms")
        assert lat.count == total
        assert sum(h.count for _l, h in
                   m.children("serving.job.latency_ms")) == total
    finally:
        sched.close()


# --------------------------------------------------------------------------
# quota admission: shadow mode vs enforcement
# --------------------------------------------------------------------------

def test_quota_shadow_mode_admits_but_counts_throttled(snap_main):
    m = MetricManager()
    sched = JobScheduler(
        snapshot=snap_main, metrics=m, autostart=False,
        quotas={"flood": TenantQuota(max_in_flight=1)})
    try:
        j1 = sched.submit(_callable_spec(tenant="flood"))
        j2 = sched.submit(_callable_spec(tenant="flood"))  # violating
        assert j2.state.value == "queued"     # admitted (shadow mode)
        assert m.counter_value("serving.tenant.throttled") == 1
        assert m.counter_value("serving.tenant.throttled",
                               labels={"tenant": "flood"}) == 1
        assert m.counter_value("serving.tenant.rejected") == 0
        assert m.counter_value("serving.jobs.submitted") == 2
        sched.start()
        assert j1.wait(30) and j2.wait(30)
    finally:
        sched.close()


def test_quota_enforcement_rejects_flooder_only(snap_main):
    """With enforcement on, the violating tenant's submit raises
    QuotaExceeded and counts serving.tenant.rejected — while other
    tenants (and the flooder below its limit) stay admitted; rejected
    submits never count as submitted."""
    m = MetricManager()
    sched = JobScheduler(
        snapshot=snap_main, metrics=m, autostart=False,
        enforce_quotas=True,
        quotas={"flood": TenantQuota(max_in_flight=2)})
    try:
        a = sched.submit(_callable_spec(tenant="flood"))
        b = sched.submit(_callable_spec(tenant="flood"))
        with pytest.raises(QuotaExceeded, match="in-flight"):
            sched.submit(_callable_spec(tenant="flood"))
        quiet = sched.submit(_callable_spec(tenant="quiet"))
        assert m.counter_value("serving.tenant.rejected",
                               labels={"tenant": "flood"}) == 1
        assert m.counter_value("serving.tenant.rejected",
                               labels={"tenant": "quiet"}) == 0
        assert m.counter_value("serving.jobs.submitted") == 3
        rows = sched.tenant_stats()
        assert rows["enforce_quotas"] is True
        assert rows["tenants"]["flood"]["rejected"] == 1
        assert rows["quotas"]["flood"]["max_in_flight"] == 2
        sched.start()
        for j in (a, b, quiet):
            assert j.wait(30)
        # in-flight drained: the next flood submit is admitted again
        c = sched.submit(_callable_spec(tenant="flood"))
        assert c.wait(30)
    finally:
        sched.close()


def test_device_seconds_budget_quota():
    """max_device_seconds is a cumulative budget: once the tenant has
    burned it, further submits are refused (enforcement on)."""
    acc = TenantAccounting()
    q = TenantQuota(max_device_seconds=1.0)
    assert acc.violation("t", q) is None
    acc.device_seconds("t", 1.5)
    why = acc.violation("t", q)
    assert why is not None and "device-seconds" in why
    # hbm limit checks bytes held by RUNNING jobs
    q2 = TenantQuota(max_hbm_bytes=100.0)
    acc.hold_hbm("t", 150.0)
    assert "HBM" in acc.violation("t", q2)
    acc.drop_hbm("t", 150.0)
    assert acc.violation("t", q2) is None


# --------------------------------------------------------------------------
# resource attribution across a mixed-tenant fused batch
# --------------------------------------------------------------------------

def test_fused_batch_attribution_splits_across_tenants(snap_main):
    """A K=8 fused BFS batch with 6 alpha + 2 beta jobs: batch wall
    time and the graph image's ledger bytes x wall split EVENLY across
    the K members, so alpha gets exactly 3x beta's device-seconds and
    HBM byte-seconds; per-job and per-tenant views agree."""
    from titan_tpu.olap.serving.hbm import snapshot_csr_bytes

    m = MetricManager()
    sched = JobScheduler(snapshot=snap_main, metrics=m,
                         autostart=False)
    try:
        rng = np.random.default_rng(7)
        nz = np.flatnonzero(np.asarray(snap_main.out_degree) > 0)
        sources = rng.choice(nz, size=8, replace=True)
        jobs = [sched.submit(JobSpec(
            kind="bfs", params={"source_dense": int(s)},
            tenant="alpha" if i < 6 else "beta"))
            for i, s in enumerate(sources)]
        sched.start()
        for j in jobs:
            assert j.wait(120)
        assert all(j.batch_k == 8 for j in jobs), \
            [j.batch_k for j in jobs]
        rows = sched.tenant_stats()["tenants"]
        a, b = rows["alpha"], rows["beta"]
        assert a["device_seconds"] > 0 and b["device_seconds"] > 0
        assert a["device_seconds"] == pytest.approx(
            3 * b["device_seconds"])
        assert a["hbm_byte_seconds"] == pytest.approx(
            3 * b["hbm_byte_seconds"])
        # per-job view consistent with the tenant rollup
        assert sum(j.device_seconds for j in jobs) == pytest.approx(
            a["device_seconds"] + b["device_seconds"])
        # byte-seconds derive from the leased image's ledger bytes
        nbytes = snapshot_csr_bytes(snap_main)
        wall = sum(j.device_seconds for j in jobs)
        assert a["hbm_byte_seconds"] + b["hbm_byte_seconds"] == \
            pytest.approx(nbytes * wall, rel=1e-6)
        # nothing held once the batch finished
        assert a["hbm_running_bytes"] == 0.0
        # wire envelope carries the attribution
        w = jobs[0].to_wire()
        assert w["device_ms"] > 0 and w["hbm_byte_seconds"] > 0
    finally:
        sched.close()


# --------------------------------------------------------------------------
# no-tenant regression: pre-label surfaces unchanged
# --------------------------------------------------------------------------

def test_no_tenant_quotas_off_pre_label_surfaces_unchanged(snap_main):
    """ISSUE 8 acceptance: with no tenant set and quotas off, the
    metric NAMES, the ``snapshot()`` schema, and the Prometheus parent
    lines are exactly the pre-label ones — and no serving.tenant.*
    counter ever moves."""
    from titan_tpu.obs.promexport import render_prometheus

    m = MetricManager()
    sched = JobScheduler(snapshot=snap_main, metrics=m)
    try:
        for _ in range(3):
            assert sched.submit(_callable_spec()).wait(30)
        deadline = time.time() + 10
        while time.time() < deadline and m.counter_value(
                "serving.jobs.completed") < 3:
            time.sleep(0.01)
        snap = m.snapshot()
        assert set(snap) == {"serving.jobs.submitted",
                             "serving.jobs.completed",
                             "serving.queue.depth",
                             "serving.job.latency_ms",
                             "serving.job.queue_ms",
                             "serving.batch.occupancy"}
        # unified pre-label schema: counters {type, count}
        assert snap["serving.jobs.completed"] == {"type": "counter",
                                                  "count": 3}
        assert m.counter_value("serving.tenant.throttled") == 0
        assert m.counter_value("serving.tenant.rejected") == 0
        # parent exposition lines identical to a never-labeled registry
        plain = MetricManager()
        plain.counter("serving.jobs.submitted").inc(3)
        plain.counter("serving.jobs.completed").inc(3)
        want = [ln for ln in render_prometheus(plain).splitlines()
                if ln.startswith("serving_jobs_")]
        got = render_prometheus(m).splitlines()
        for ln in want:
            assert ln in got, ln
    finally:
        sched.close()


# --------------------------------------------------------------------------
# queue depth by priority class (satellite)
# --------------------------------------------------------------------------

def test_queue_depth_labeled_by_priority_class(snap_main):
    m = MetricManager()
    sched = JobScheduler(snapshot=snap_main, autostart=False,
                         metrics=m)
    try:
        for prio in (0, 0, 5):
            sched.submit(_callable_spec(priority=prio))
        assert m.counter_value("serving.queue.depth") == 3
        assert m.counter_value("serving.queue.depth",
                               labels={"priority": "0"}) == 2
        assert m.counter_value("serving.queue.depth",
                               labels={"priority": "5"}) == 1
        # flagged bidirectional → renders as a Prometheus gauge
        from titan_tpu.obs.promexport import render_prometheus
        text = render_prometheus(m)
        assert "# TYPE serving_queue_depth gauge" in text
        assert 'serving_queue_depth{priority="0"} 2' in text
        sched.start()
        for j in sched.jobs():
            assert j.wait(30)
        deadline = time.time() + 10
        while time.time() < deadline and m.counter_value(
                "serving.queue.depth") != 0:
            time.sleep(0.01)
        # drained: children AND parent back to zero (labeled pops)
        assert m.counter_value("serving.queue.depth") == 0
        assert m.counter_value("serving.queue.depth",
                               labels={"priority": "0"}) == 0
        assert m.counter_value("serving.queue.depth",
                               labels={"priority": "5"}) == 0
    finally:
        sched.close()


# --------------------------------------------------------------------------
# HBM / pool gauges (satellite)
# --------------------------------------------------------------------------

def test_hbm_and_pool_gauges_exported(snap_main):
    """HBMLedger residency and snapshot-pool size export as REAL gauges
    (callback views read at scrape time) — resident_bytes was computed
    but never exported before ISSUE 8."""
    from titan_tpu.obs.promexport import render_prometheus
    from titan_tpu.olap.serving.hbm import snapshot_csr_bytes

    m = MetricManager()
    sched = JobScheduler(snapshot=snap_main, metrics=m)
    try:
        j = sched.submit(JobSpec(kind="bfs",
                                 params={"source_dense": 0}))
        assert j.wait(120)
        nbytes = snapshot_csr_bytes(snap_main)
        assert m.gauge_value("serving.hbm.resident_bytes") == nbytes
        # nothing pinned after the batch drains
        assert m.gauge_value("serving.hbm.pinned_bytes") == 0.0
        assert m.gauge_value("serving.pool.snapshots") >= 1.0
        text = render_prometheus(m)
        assert "# TYPE serving_hbm_resident_bytes gauge" in text
        assert "# TYPE serving_hbm_pinned_bytes gauge" in text
        assert "# TYPE serving_pool_snapshots gauge" in text
        assert f"serving_hbm_resident_bytes {nbytes}" in text
    finally:
        sched.close()


def test_quota_check_and_admit_atomic_under_concurrent_submits(
        snap_main):
    """Enforced max_in_flight must hold under CONCURRENT submits (the
    HTTP server runs handlers in parallel): with a limit of 4 and 16
    racing submitters, exactly 4 are admitted — the check and the
    reservation are one critical section, not read-then-write."""
    m = MetricManager()
    sched = JobScheduler(
        snapshot=snap_main, metrics=m, autostart=False,
        enforce_quotas=True,
        quotas={"flood": TenantQuota(max_in_flight=4)})
    admitted: list = []
    refused: list = []
    lock = threading.Lock()

    def submitter():
        try:
            j = sched.submit(_callable_spec(tenant="flood"))
            with lock:
                admitted.append(j)
        except QuotaExceeded as e:
            with lock:
                refused.append(e)

    try:
        threads = [threading.Thread(target=submitter)
                   for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert len(admitted) == 4, (len(admitted), len(refused))
        assert len(refused) == 12
        rows = sched.tenant_stats()["tenants"]["flood"]
        assert rows["in_flight"] == 4
        assert rows["submitted"] == 4
        assert rows["rejected"] == 12
        assert m.counter_value("serving.tenant.rejected",
                               labels={"tenant": "flood"}) == 12
        # rejected submits never counted as submitted
        assert m.counter_value("serving.jobs.submitted") == 4
        sched.start()
        for j in admitted:
            assert j.wait(30)
    finally:
        sched.close()


def test_closed_scheduler_rejection_releases_quota_reservation(
        snap_main):
    """A submit refused because the scheduler closed must back out its
    quota reservation — otherwise rejected submits pin in-flight slots
    forever."""
    sched = JobScheduler(snapshot=snap_main, autostart=False,
                         metrics=MetricManager(),
                         quotas={"t": TenantQuota(max_in_flight=1)},
                         enforce_quotas=True)
    sched.close()
    with pytest.raises(RuntimeError, match="closed"):
        sched.submit(_callable_spec(tenant="t"))
    rows = sched.tenant_stats()["tenants"]["t"]
    assert rows["in_flight"] == 0 and rows["submitted"] == 0


def test_non_executed_jobs_record_no_latency_sample(snap_main):
    """Expired-at-submit and cancelled-while-queued jobs never entered
    execution: they must not drop ~0ms samples into the latency
    histogram, where they would drag the p95 down and dilute the SLO
    engine's latency SLI for their tenant."""
    m = MetricManager()
    sched = JobScheduler(snapshot=snap_main, metrics=m,
                         autostart=False)
    try:
        expired = sched.submit(_callable_spec(
            tenant="a", deadline=time.time() - 1))
        assert expired.state.value == "expired"
        queued = sched.submit(_callable_spec(tenant="a"))
        assert sched.cancel(queued.id)
        assert m.counter_value("serving.jobs.expired") == 1
        assert m.counter_value("serving.jobs.cancelled") == 1
        assert m.histogram("serving.job.latency_ms").count == 0
        # an executed job still samples exactly once
        ran = sched.submit(_callable_spec(tenant="a"))
        sched.start()
        assert ran.wait(30)
        assert m.histogram("serving.job.latency_ms").count == 1
    finally:
        sched.close()


def test_failed_submit_backs_out_quota_reservation(snap_main):
    """A submit that raises AFTER the quota gate (junk deadline type →
    TypeError at the deadline comparison) must release the tenant's
    in-flight reservation — otherwise a few malformed submits lock the
    tenant out of an enforced max_in_flight quota forever."""
    m = MetricManager()
    sched = JobScheduler(
        snapshot=snap_main, metrics=m, autostart=False,
        enforce_quotas=True,
        quotas={"t": TenantQuota(max_in_flight=1)})
    try:
        with pytest.raises(TypeError):
            sched.submit(_callable_spec(tenant="t", deadline="60"))
        rows = sched.tenant_stats()["tenants"]["t"]
        assert rows["in_flight"] == 0 and rows["submitted"] == 0
        # the slot is free: a well-formed submit is admitted
        job = sched.submit(_callable_spec(tenant="t"))
        sched.start()
        assert job.wait(30)
    finally:
        sched.close()


def test_scheduler_close_detaches_slo_burn_gauges(snap_main):
    """close() must neutralize the SLO engine's burn-rate gauge
    callbacks along with the hbm/pool ones — a dead scheduler's engine
    must not keep re-evaluating objectives on every scrape."""
    from titan_tpu.obs.slo import SLO
    m = MetricManager()
    sched = JobScheduler(
        snapshot=snap_main, metrics=m, autostart=False,
        slos=[SLO("t-avail", tenant="t", success_rate=0.9,
                  windows=(300.0,))])
    m.counter("serving.jobs.failed",
              labels={"tenant": "t", "kind": "callable"}).inc(3)
    assert m.gauge_value("serving.slo.burn_rate",
                         labels={"slo": "t-avail",
                                 "window": "300s"}) > 0
    sched.close()
    assert m.gauge_value("serving.slo.burn_rate",
                         labels={"slo": "t-avail",
                                 "window": "300s"}) == 0.0
