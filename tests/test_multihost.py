"""Multi-host (DCN) execution dryrun (SURVEY §2.8: the JAX distributed
runtime across hosts is the rebuild's cross-host data plane, replacing
the reference's Hadoop InputFormat distribution —
titan-hadoop-core/.../scan/HadoopScanMapper.java:33).

Spawns 2 real processes x 4 virtual CPU devices each, joined via
jax.distributed into one 8-device mesh; the sharded hybrid BFS runs with
HOST-SHARDED loading (each process materializes only its own shard
blocks) and must be bit-equal to the single-chip hybrid.
"""

import importlib.util
import os

import pytest


def test_multihost_dryrun():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(here, "__graft_entry__.py"))
    ge = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ge)
    # raises on rc != 0, missing OK line, or bit-inequality
    ge.dryrun_multihost(n_processes=2, per_proc_devices=4, scale=12)
