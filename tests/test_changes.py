"""Trigger logs + LogProcessorFramework change streaming.

Modeled on the reference's TitanBus user-log contract
(docs/TitanBus.md:5-13) and LogProcessorFramework tests: transactions
tagged with a log identifier stream their change set to ulog_<id>; registered
processors receive a ChangeState per committed tx.
"""

import time

import pytest

import titan_tpu
from titan_tpu.core.changes import ChangeState, change_payload


@pytest.fixture
def graph():
    g = titan_tpu.open("inmemory")
    yield g
    g.close()


def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def test_change_payload_contents(graph):
    tx = graph.new_transaction()
    a = tx.add_vertex("person", name="alice")
    b = tx.add_vertex("person", name="bob")
    a.add_edge("knows", b)
    payload = change_payload(graph, tx, 42)
    state = ChangeState(payload)
    assert state.txid == 42
    assert set(state.added_vertices()) == {a.id, b.id}
    knows = state.added_edges("knows")
    assert len(knows) == 1
    assert knows[0]["out"] == a.id and knows[0]["in"] == b.id
    names = {p["value"] for p in state.added_properties("name")}
    assert names == {"alice", "bob"}
    # system relations (vertex-exists, label edges) are filtered out
    all_types = {r["type"] for r in state.added_relations()}
    assert all_types == {"knows", "name"}
    tx.rollback()


def test_processor_receives_committed_changes(graph):
    received = []
    fw = titan_tpu.open_log_processors(graph)
    fw.add_log_processor("stream") \
        .set_start_time(0) \
        .set_read_interval_ms(20) \
        .add_processor(lambda g, txid, state: received.append(state)) \
        .build()

    tx = graph.new_transaction(log_identifier="stream")
    v = tx.add_vertex("person", name="carol")
    vid = v.id
    tx.commit()

    assert _wait_for(lambda: len(received) >= 1)
    state = received[0]
    assert vid in state.added_vertices()
    assert state.added_properties("name")[0]["value"] == "carol"
    assert state.timestamp > 0


def test_untagged_tx_does_not_stream(graph):
    received = []
    fw = titan_tpu.open_log_processors(graph)
    fw.add_log_processor("only-tagged") \
        .set_start_time(0) \
        .set_read_interval_ms(20) \
        .add_processor(lambda g, txid, state: received.append(state)) \
        .build()

    tx = graph.new_transaction()          # no log identifier
    tx.add_vertex("person", name="quiet")
    tx.commit()
    tx2 = graph.new_transaction(log_identifier="only-tagged")
    tx2.add_vertex("person", name="loud")
    tx2.commit()

    assert _wait_for(lambda: len(received) >= 1)
    time.sleep(0.1)
    assert len(received) == 1
    assert received[0].added_properties("name")[0]["value"] == "loud"


def test_removal_changes_stream(graph):
    tx = graph.new_transaction()
    v = tx.add_vertex("person", name="temp")
    vid = v.id
    tx.commit()

    received = []
    fw = titan_tpu.open_log_processors(graph)
    fw.add_log_processor("removals") \
        .set_start_time(0) \
        .set_read_interval_ms(20) \
        .add_processor(lambda g, txid, state: received.append(state)) \
        .build()

    tx2 = graph.new_transaction(log_identifier="removals")
    tx2.vertex(vid).remove()
    tx2.commit()

    assert _wait_for(lambda: len(received) >= 1)
    state = received[0]
    assert vid in state.removed_vertices()
    assert any(r["type"] == "name" for r in state.removed_relations())
