"""Cross-process observability units (ISSUE 18).

The distributed acceptance paths (stitched trace over real worker
processes, bit-equal propagation on/off) live with the cluster fixture
in test_scan_worker.py; this file covers the seams in isolation:
traceparent framing, ``Tracer.ingest`` / ``drain`` (id remap, skew
normalization, bounds, tap pass-through), the ``Federator`` (instance
labeling, HELP-once-per-family, type preservation, escaping, series
cap, stale-peer eviction, fleet roll-up) with an injected fetch + fake
clock, the FlightRecorder's remote-span bundle section, and the
GraphServer's ``/metrics?federate=1`` + ``/fleet`` surface.
"""

import json
import urllib.request

import pytest

from titan_tpu.errors import TemporaryBackendError
from titan_tpu.obs.federate import Federator, _inject_instance
from titan_tpu.obs.promexport import render_prometheus
from titan_tpu.obs.tracing import (INGEST_MAX_SPANS, Tracer,
                                   make_traceparent, parse_traceparent)
from titan_tpu.utils.metrics import MetricManager


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# -- traceparent framing -----------------------------------------------------

def test_traceparent_round_trip():
    assert parse_traceparent(make_traceparent("job-7", 42)) == ("job-7", 42)
    # trace ids are job ids: dashes inside must survive the framing
    assert parse_traceparent(make_traceparent("a-b-c", 1)) == ("a-b-c", 1)


@pytest.mark.parametrize("bad", [
    None, 17, "", "00--1-01", "garbage", "00-t-x-01", "01-t-1-01",
    "00-t-1-00", "t-1",
])
def test_traceparent_malformed_degrades_to_none(bad):
    assert parse_traceparent(bad) is None


# -- Tracer.ingest / drain ---------------------------------------------------

def _wire(span_id, name, start, end, parent=None, **attrs):
    w = {"span": span_id, "name": name, "start": start, "end": end}
    if parent is not None:
        w["parent"] = parent
    if attrs:
        w["attrs"] = attrs
    return w


def test_ingest_remaps_ids_and_attaches_orphans_under_parent():
    clk = FakeClock()
    t = Tracer(clk)
    m = MetricManager()
    root = t.start("j", "split")
    # the remote tracer also counted from 1: ids collide numerically
    batch = [_wire(1, "split", 50.0, 51.0),
             _wire(2, "execute", 50.2, 50.8, parent=1),
             _wire(3, "stray", 50.3, 50.4, parent=777)]
    assert t.ingest("j", batch, parent_id=root.span_id,
                    offset=0.0, instance="w1", metrics=m) == 3
    clk.advance(5)
    t.end(root)
    spans = {s.span_id: s for s in t.spans("j")}
    assert len(spans) == 4 and len({s.span_id for s in spans.values()}) == 4
    by_name = {s.name: s for s in spans.values() if s is not root}
    # in-batch parent link follows the remap; unshipped parents (the
    # remote root AND the ring-orphaned stray) attach under the split
    assert by_name["execute"].parent_id == by_name["split"].span_id
    assert by_name["split"].parent_id == root.span_id
    assert by_name["stray"].parent_id == root.span_id
    for s in by_name.values():
        assert s.attrs["remote"] is True
        assert s.attrs["instance"] == "w1"
    assert m.counter_value("obs.ingest.spans") == 3


def test_ingest_applies_offset_and_clamps_into_window():
    clk = FakeClock(1000.0)
    t = Tracer(clk)
    m = MetricManager()
    root = t.start("j", "split")          # starts at 1000.0
    clk.advance(2.0)                      # response received at 1002.0
    # worker clock runs 900s behind; one span leaks past the window
    batch = [_wire(1, "ok", 100.5, 101.5),
             _wire(2, "leaky", 99.0, 103.5)]
    t.ingest("j", batch, parent_id=root.span_id, offset=900.0,
             window=(1000.0, 1002.0), metrics=m)
    t.end(root)
    by_name = {s.name: s for s in t.spans("j")}
    assert by_name["ok"].t_start == pytest.approx(1000.5)
    assert by_name["ok"].t_end == pytest.approx(1001.5)
    # clamped to the coordinator's send/receive envelope
    assert by_name["leaky"].t_start == 1000.0
    assert by_name["leaky"].t_end == 1002.0
    assert m.counter_value("obs.ingest.clamped") == 1
    assert m.counter_value("obs.ingest.spans") == 2


def test_ingest_bounds_and_malformed_spans_counted_as_dropped():
    t = Tracer(FakeClock())
    m = MetricManager()
    root = t.start("j", "split")
    batch = [_wire(i, f"s{i}", 0.0, 1.0) for i in range(1, 8)]
    batch.append({"span": "not-an-id", "start": "x"})   # malformed
    accepted = t.ingest("j", batch, parent_id=root.span_id,
                        max_spans=5, extra_dropped=2, metrics=m)
    assert accepted == 5
    # 3 past the cap (7 - 5 + the malformed one lands in the tail cut?
    # no: cap slices first, malformed was cut by the cap) + remote's 2
    assert m.counter_value("obs.ingest.dropped") == 2 + 3
    assert m.counter_value("obs.ingest.spans") == 5


def test_ingest_cannot_evict_the_local_root():
    t = Tracer(FakeClock(), max_spans=6)
    m = MetricManager()
    root = t.start("j", "root")
    chatty = [_wire(i, f"s{i}", 0.0, 1.0) for i in range(1, 40)]
    t.ingest("j", chatty, parent_id=root.span_id,
             max_spans=INGEST_MAX_SPANS, metrics=m)
    spans = t.spans("j")
    assert len(spans) == 6
    assert spans[0] is root               # ring kept the root anchor
    assert t.dropped("j") > 0


def test_ingest_disabled_tracer_accepts_nothing():
    t = Tracer(enabled=False)
    m = MetricManager()
    assert t.ingest("j", [_wire(1, "s", 0.0, 1.0)], parent_id=None,
                    metrics=m) == 0
    assert t.spans("j") is None
    assert m.counter_value("obs.ingest.dropped") == 1


def test_ingest_feeds_the_flight_tap():
    t = Tracer(FakeClock())
    seen = []
    t.tap = seen.append
    root = t.start("j", "split")
    t.ingest("j", [_wire(1, "remote-exec", 0.0, 1.0)],
             parent_id=root.span_id, instance="w9")
    assert [s.name for s in seen] == ["remote-exec"]
    assert seen[0].attrs["instance"] == "w9"


def test_drain_pops_completed_spans_once_and_keeps_open_ones():
    clk = FakeClock()
    t = Tracer(clk)
    open_span = t.start("k", "still-open")
    for i in range(4):
        t.event("k", f"done{i}")
    wire, dropped = t.drain("k", max_spans=3)
    assert [w["name"] for w in wire] == ["done0", "done1", "done2"]
    wire2, _ = t.drain("k")
    assert [w["name"] for w in wire2] == ["done3"]
    # the open span survives every drain until it completes
    assert [s.name for s in t.spans("k")] == ["still-open"]
    t.end(open_span)
    assert [w["name"] for w in t.drain("k")[0]] == ["still-open"]
    # fully drained traces are garbage-collected
    assert t.spans("k") is None


# -- Federator ---------------------------------------------------------------

_PEER_A = """\
# HELP scan_remote_splits_served splits executed on this scan-worker node
# TYPE scan_remote_splits_served counter
scan_remote_splits_served 3
# TYPE serving_queue_depth gauge
serving_queue_depth 1
"""

_PEER_B = """\
# HELP scan_remote_splits_served splits executed on this scan-worker node
# TYPE scan_remote_splits_served counter
scan_remote_splits_served 5
scan_remote_splits_served{kind="repair"} 2
# TYPE serving_queue_depth gauge
serving_queue_depth 4
"""


def _fed(fetches, clock=None, **kw):
    """Federator over a scripted fetch: ``fetches[(instance_url, path)]``
    is a text body, a callable, or an exception to raise."""
    def fetch(url, path):
        got = fetches[(url, path)]
        if isinstance(got, BaseException):
            raise got
        return got() if callable(got) else got
    return Federator(metrics=MetricManager(), clock=clock or FakeClock(),
                     fetch=fetch, **kw)


def test_federated_render_instance_labels_help_once_types_kept():
    fetches = {
        ("http://a:1", "/metrics"): _PEER_A,
        ("http://a:1", "/healthz"): '{"live": true}',
        ("http://b:2", "/metrics"): _PEER_B,
        ("http://b:2", "/healthz"): '{"live": true}',
    }
    fed = _fed(fetches)
    fed.add_peer("http://a:1", instance="a")
    fed.add_peer("http://b:2", instance="b")
    assert fed.scrape() == {"a": True, "b": True}
    local = MetricManager()
    local.counter("scan.remote.splits_dispatched").inc(9)
    body = fed.render(render_prometheus(local))
    # HELP/TYPE once per family across all three sources
    assert body.count("# TYPE scan_remote_splits_served counter") == 1
    assert body.count("# HELP scan_remote_splits_served") == 1
    assert body.count("# TYPE serving_queue_depth gauge") == 1
    # local samples unlabeled, peer samples instance-labeled
    assert "scan_remote_splits_dispatched 9" in body
    assert 'scan_remote_splits_served{instance="a"} 3' in body
    assert 'scan_remote_splits_served{instance="b"} 5' in body
    # pre-existing labels keep their pairs, instance lands first
    assert ('scan_remote_splits_served{instance="b",kind="repair"} 2'
            in body)
    # family blocks stay contiguous: every sample of a family sits
    # between its TYPE line and the next comment line
    lines = body.splitlines()
    fam_of = {}
    cur = None
    for ln in lines:
        if ln.startswith("# TYPE "):
            cur = ln.split()[2]
        elif ln and not ln.startswith("#"):
            name = ln.split("{", 1)[0].split(" ", 1)[0]
            fam_of.setdefault(name, set()).add(cur)
    assert all(len(v) == 1 for v in fam_of.values()), fam_of


def test_federated_instance_label_escaping():
    assert _inject_instance("x 1", 'a"b\\c\nd') == \
        'x{instance="a\\"b\\\\c\\nd"} 1'
    assert _inject_instance('x{} 1', "i") == 'x{instance="i"} 1'
    assert _inject_instance('x{l="v"} 1', "i") == \
        'x{instance="i",l="v"} 1'


def test_federator_series_cap_drops_and_counts():
    big = "# TYPE fam counter\n" + "\n".join(
        f'fam{{k="{i}"}} 1' for i in range(50)) + "\n"
    fetches = {("http://a:1", "/metrics"): big,
               ("http://a:1", "/healthz"): "{}"}
    fed = _fed(fetches, max_series_per_peer=10)
    fed.add_peer("http://a:1", instance="a")
    fed.scrape()
    body = fed.render("")
    assert body.count('instance="a"') == 10
    assert fed._metrics.counter_value(
        "obs.federate.series_dropped") == 40


def test_federator_evicts_after_consecutive_failures_and_recovers():
    state = {"dead": False}

    def maybe(url, path):
        if state["dead"]:
            raise TemporaryBackendError("connection refused")
        return _PEER_A if path == "/metrics" else "{}"

    clk = FakeClock()
    fed = Federator(metrics=MetricManager(), clock=clk, fetch=maybe,
                    max_failures=3)
    fed.add_peer("http://a:1", instance="a")
    fed.scrape()
    assert 'instance="a"' in fed.render("")
    state["dead"] = True
    fed.scrape(); fed.scrape()
    # two failures: still cached? no — failures mark but render uses
    # last text until eviction; the third failure evicts
    assert not fed.fleet()["peers"][0]["evicted"]
    fed.scrape()
    peer = fed.fleet()["peers"][0]
    assert peer["evicted"] and not peer["up"]
    assert peer["consecutive_failures"] == 3
    assert "connection refused" in peer["last_error"]
    assert 'instance="a"' not in fed.render("")
    assert fed._metrics.counter_value("obs.federate.evicted") == 1
    assert fed._metrics.counter_value(
        "obs.federate.errors", labels={"instance": "a"}) == 3
    # the worker restarts: one good scrape un-evicts it
    state["dead"] = False
    fed.scrape()
    assert fed.fleet()["peers"][0]["up"]
    assert 'instance="a"' in fed.render("")


def test_fleet_rollup_counts_and_health_passthrough():
    clk = FakeClock(500.0)
    fetches = {
        ("http://a:1", "/metrics"): _PEER_A,
        ("http://a:1", "/healthz"):
            '{"live": true, "ready": true, "splits_served": 11}',
        ("http://b:2", "/metrics"): TemporaryBackendError("down"),
        ("http://b:2", "/healthz"): TemporaryBackendError("down"),
    }
    fed = _fed(fetches, clock=clk)
    fed.add_peer("http://a:1", instance="a")
    fed.add_peer("http://b:2", instance="b")
    fed.scrape()
    clk.advance(7.0)
    fl = fed.fleet()
    assert fl["up"] == 1 and fl["down"] == 1
    rows = {p["instance"]: p for p in fl["peers"]}
    assert rows["a"]["up"] and rows["a"]["last_ok_age_s"] == 7.0
    assert rows["a"]["health"]["splits_served"] == 11
    assert not rows["b"]["up"] and rows["b"]["consecutive_failures"] == 1


# -- FlightRecorder: remote spans in postmortems -----------------------------

def test_postmortem_bundle_carries_ingested_remote_spans(tmp_path):
    from titan_tpu.obs.flightrec import FlightRecorder

    m = MetricManager()
    clk = FakeClock()
    rec = FlightRecorder(str(tmp_path), metrics=m, clock=clk)
    t = Tracer(clk)
    t.tap = rec.span_tap
    root = t.start("job-9", "split")
    t.ingest("job-9",
             [_wire(1, "execute", 999.0, 1000.0)],
             parent_id=root.span_id, instance="http://w:1",
             extra_dropped=4, metrics=m)
    t.end(root)
    # an unrelated local job's remote-free failure must not pick it up
    t.event("job-other", "round")
    path = rec.dump(reason="failed", job={"job": "job-9"},
                    span_tree=t.tree("job-9"))
    with open(path) as f:
        bundle = json.load(f)
    assert [e["name"] for e in bundle["remote_spans"]] == ["execute"]
    assert bundle["remote_spans"][0]["attrs"]["instance"] == "http://w:1"
    assert bundle["ingest_dropped"] == 4
    # a different job's dump excludes this job's remote spans
    path2 = rec.dump(reason="failed", job={"job": "job-other"})
    with open(path2) as f:
        assert json.load(f)["remote_spans"] == []


# -- GraphServer surface -----------------------------------------------------

def _get(srv, path):
    req = urllib.request.Request(
        f"http://{srv.host}:{srv.port}{path}", method="GET")
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


@pytest.fixture
def served():
    import titan_tpu
    from titan_tpu.server import GraphServer
    g = titan_tpu.open({"storage.backend": "inmemory"})
    srv = GraphServer(g, port=0).start()
    yield srv
    srv.stop()
    g.close()


def test_server_fleet_disabled_without_federator(served):
    code, _, body = _get(served, "/fleet")
    assert code == 200
    assert json.loads(body) == {"enabled": False, "peers": []}


def test_server_metrics_federate_param(served):
    fetches = {("http://a:1", "/metrics"): _PEER_A,
               ("http://a:1", "/healthz"): '{"live": true}'}

    def fetch(url, path):
        return fetches[(url, path)]

    served.federator = Federator(metrics=MetricManager(),
                                 clock=FakeClock(), fetch=fetch)
    served.federator.add_peer("http://a:1", instance="a")
    # plain /metrics stays local-only
    code, ctype, body = _get(served, "/metrics")
    assert code == 200 and ctype.startswith("text/plain")
    assert 'instance="a"' not in body
    code, ctype, body = _get(served, "/metrics?federate=1")
    assert code == 200 and ctype.startswith("text/plain")
    assert 'scan_remote_splits_served{instance="a"} 3' in body
    code, _, body = _get(served, "/fleet")
    fl = json.loads(body)
    assert fl["enabled"] and fl["up"] == 1
    assert fl["peers"][0]["health"] == {"live": True}
