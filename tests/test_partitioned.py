"""Partitioned (vertex-cut) vertex labels, end to end.

Modeled on the reference's TitanPartitionGraphTest (titan-test): a
``partition()`` vertex label spreads one vertex's adjacency over all
partitions; OLTP reads fan out over the representative rows, writes colocate
each edge copy with the other endpoint, and OLAP folds representatives into
the canonical vertex.
"""

import numpy as np
import pytest

import titan_tpu
from titan_tpu.core.defs import Direction
from titan_tpu.ids.idmanager import IDType
from titan_tpu.storage.api import KeySliceQuery, SliceQuery


@pytest.fixture
def graph():
    g = titan_tpu.open("inmemory")
    mgmt = g.management()
    mgmt.make_vertex_label("tweet", partitioned=True)
    mgmt.commit()
    yield g
    g.close()


def _make_hub(g, n_neighbors=24):
    tx = g.new_transaction()
    hub = tx.add_vertex("tweet", text="hello world")
    hub_id = hub.id
    user_ids = []
    for i in range(n_neighbors):
        u = tx.add_vertex("person", name=f"u{i}")
        u.add_edge("likes", hub)
        user_ids.append(u.id)
    tx.commit()
    return hub_id, user_ids


def test_partitioned_vertex_id_is_canonical(graph):
    hub_id, _ = _make_hub(graph, 4)
    idm = graph.idm
    assert idm.is_partitioned_vertex(hub_id)
    assert idm.canonical_vertex_id(hub_id) == hub_id


def test_properties_and_label_on_canonical_row(graph):
    hub_id, _ = _make_hub(graph, 4)
    tx = graph.new_transaction()
    v = tx.vertex(hub_id)
    assert v is not None
    assert v.label() == "tweet"
    assert v.value("text") == "hello world"
    tx.rollback()


def test_representative_id_resolves_to_canonical(graph):
    hub_id, _ = _make_hub(graph, 4)
    idm = graph.idm
    reps = idm.partitioned_vertex_representatives(hub_id)
    other = next(r for r in reps if r != hub_id)
    tx = graph.new_transaction()
    v = tx.vertex(other)
    assert v is not None and v.id == hub_id
    tx.rollback()


def test_adjacency_fans_out_over_representatives(graph):
    hub_id, user_ids = _make_hub(graph)
    tx = graph.new_transaction()
    v = tx.vertex(hub_id)
    in_edges = list(v.in_edges("likes"))
    assert len(in_edges) == len(user_ids)
    assert {e.other(v).id for e in in_edges} == set(user_ids)
    # reverse direction intact too
    u = tx.vertex(user_ids[0])
    assert [w.id for w in u.out("likes")] == [hub_id]
    tx.rollback()


def test_edges_physically_spread_across_rows(graph):
    """The vertex cut actually cuts: each edge entry lives on the
    representative row in the OTHER endpoint's partition (deterministic
    check — a 'count distinct rows' assertion is flaky because one tx
    batch places all neighbors in one random partition)."""
    hub_id, user_ids = _make_hub(graph)
    idm = graph.idm
    store = graph.backend.edge_store
    txh = graph.backend.manager.begin_transaction()
    count = idm.count(hub_id)
    for uid in user_ids:
        rep = idm.partitioned_vertex_id(count, idm.partition(uid))
        entries = store.get_slice(
            KeySliceQuery(idm.key_bytes(rep), SliceQuery()), txh)
        assert entries, f"no edge copy colocated with user {uid}"


def test_multi_vertex_query_covers_cut(graph):
    hub_id, user_ids = _make_hub(graph)
    tx = graph.new_transaction()
    out = tx.multi_vertex_edges([hub_id], Direction.IN, ["likes"])
    assert len(out[hub_id]) == len(user_ids)
    tx.rollback()


def test_vertices_scan_yields_hub_once(graph):
    hub_id, user_ids = _make_hub(graph, 8)
    tx = graph.new_transaction()
    ids = [v.id for v in tx.vertices()]
    assert ids.count(hub_id) == 1
    assert len(ids) == 1 + len(user_ids)
    tx.rollback()


def test_edge_removal_on_cut_vertex(graph):
    hub_id, user_ids = _make_hub(graph, 6)
    tx = graph.new_transaction()
    v = tx.vertex(hub_id)
    edges = list(v.in_edges("likes"))
    edges[0].remove()
    tx.commit()
    tx2 = graph.new_transaction()
    assert len(list(tx2.vertex(hub_id).in_edges("likes"))) == 5
    tx2.rollback()


def test_olap_snapshot_folds_representatives(graph):
    hub_id, user_ids = _make_hub(graph)
    from titan_tpu.olap.tpu import snapshot as snap_mod
    snap = snap_mod.build(graph)
    assert hub_id in set(np.asarray(snap.vertex_ids).tolist())
    hub_dense = snap.dense_of(hub_id)
    dst = np.asarray(snap.dst)
    # every 'likes' edge points at the ONE canonical dense row
    assert int((dst == hub_dense).sum()) == len(user_ids)


def test_olap_pagerank_on_cut_graph(graph):
    hub_id, user_ids = _make_hub(graph)
    from titan_tpu.models import pagerank
    comp = graph.compute()
    res = pagerank.run(comp, iterations=15)
    snap = comp.snapshot()
    ranks = np.asarray(res["rank"])
    # the hub absorbs rank from every user: strictly the max
    assert int(np.argmax(ranks)) == snap.dense_of(hub_id)
