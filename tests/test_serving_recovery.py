"""Scheduler retry policy + lifecycle races (no device kernels).

Covers the control half of the recovery plane with 'callable' jobs so
the suite never touches jax (tier-1 is compile-budgeted): RETRYING
transitions, exponential backoff gating, retry exhaustion, cancel /
close interactions, the submitted-vs-rejected metrics fix, and the
close-during-RUNNING race (a job can never go DONE after FAILED and
its terminal metrics fire exactly once).
"""

import threading
import time

import numpy as np
import pytest

from titan_tpu.olap.api import JobSpec
from titan_tpu.olap.serving.jobs import JobState
from titan_tpu.olap.serving.scheduler import JobScheduler
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.utils.metrics import MetricManager


def _tiny_snapshot():
    # callable jobs never lease it; the pool just needs something
    return snap_mod.from_arrays(4, np.array([0, 1], np.int32),
                                np.array([1, 0], np.int32))


@pytest.fixture
def metrics():
    return MetricManager()


@pytest.fixture
def sched(metrics):
    s = JobScheduler(snapshot=_tiny_snapshot(), metrics=metrics)
    yield s
    s.close()


def _flaky(n_failures: int, calls: list):
    """A callable that records call times and fails its first
    ``n_failures`` invocations."""
    def fn():
        calls.append(time.time())
        if len(calls) <= n_failures:
            raise RuntimeError(f"boom #{len(calls)}")
        return 41 + len(calls)
    return fn


def test_retry_then_done(sched, metrics):
    calls = []
    job = sched.submit(JobSpec(kind="callable",
                               params={"fn": _flaky(1, calls)},
                               max_retries=2, retry_backoff_s=0.01))
    assert job.wait(10)
    assert job.state is JobState.DONE
    assert job.attempt == 2 and len(calls) == 2
    assert job.result["value"] == 43
    assert job.not_before is not None        # backoff gate was armed
    assert metrics.counter_value("serving.recovery.retries") == 1
    assert metrics.counter_value("serving.jobs.completed") == 1
    assert metrics.counter_value("serving.jobs.failed") == 0
    assert job.to_wire()["attempt"] == 2


def test_retries_exhausted_goes_failed(sched, metrics):
    calls = []
    job = sched.submit(JobSpec(kind="callable",
                               params={"fn": _flaky(99, calls)},
                               max_retries=2, retry_backoff_s=0.01))
    assert job.wait(10)
    assert job.state is JobState.FAILED
    assert job.attempt == 3 and len(calls) == 3   # initial + 2 retries
    assert "boom #3" in job.error
    assert metrics.counter_value("serving.recovery.retries") == 2
    assert metrics.counter_value(
        "serving.recovery.retries_exhausted") == 1
    assert metrics.counter_value("serving.jobs.failed") == 1


def test_no_retry_without_budget(sched, metrics):
    calls = []
    job = sched.submit(JobSpec(kind="callable",
                               params={"fn": _flaky(99, calls)}))
    assert job.wait(10)
    assert job.state is JobState.FAILED and job.attempt == 1
    assert len(calls) == 1
    assert metrics.counter_value("serving.recovery.retries") == 0


def test_retry_backoff_spacing(sched):
    """The second attempt must not start before the exponential backoff
    elapses (gap can only be LARGER under load, so no flake)."""
    calls = []
    job = sched.submit(JobSpec(kind="callable",
                               params={"fn": _flaky(1, calls)},
                               max_retries=1, retry_backoff_s=0.2))
    assert job.wait(10) and job.state is JobState.DONE
    assert len(calls) == 2
    assert calls[1] - calls[0] >= 0.2 * 0.9   # small clock-skew slack


def test_cancel_while_retrying(sched, metrics):
    calls = []
    job = sched.submit(JobSpec(kind="callable",
                               params={"fn": _flaky(99, calls)},
                               max_retries=3, retry_backoff_s=30.0))
    deadline = time.time() + 10
    while time.time() < deadline and job.state is not JobState.RETRYING:
        time.sleep(0.01)
    assert job.state is JobState.RETRYING
    assert job.to_wire()["retry_at"] > time.time()
    assert sched.cancel(job.id)
    assert job.state is JobState.CANCELLED
    assert len(calls) == 1                    # backoff never elapsed
    assert metrics.counter_value("serving.jobs.cancelled") == 1


def test_close_fails_retrying_job_permanently(metrics):
    sched = JobScheduler(snapshot=_tiny_snapshot(), metrics=metrics)
    calls = []
    job = sched.submit(JobSpec(kind="callable",
                               params={"fn": _flaky(99, calls)},
                               max_retries=3, retry_backoff_s=30.0))
    deadline = time.time() + 10
    while time.time() < deadline and job.state is not JobState.RETRYING:
        time.sleep(0.01)
    assert job.state is JobState.RETRYING
    sched.close()
    # a closing scheduler must not re-enter RETRYING: permanent FAILED
    assert job.state is JobState.FAILED
    assert "scheduler closed" in job.error
    assert len(calls) == 1


def test_exhausted_flag_not_set_by_permanent_failure(metrics):
    """retries_exhausted must mean 'retry budget declined the retry',
    not 'FAILED while attempt happens to exceed max_retries': a
    close()-sweep permanent failure mid-retry does not count."""
    sched = JobScheduler(snapshot=_tiny_snapshot(), metrics=metrics)
    calls = []
    job = sched.submit(JobSpec(kind="callable",
                               params={"fn": _flaky(99, calls)},
                               max_retries=1, retry_backoff_s=30.0))
    deadline = time.time() + 10
    while time.time() < deadline and job.state is not JobState.RETRYING:
        time.sleep(0.01)
    sched.close()                       # permanent fail on attempt 2
    assert job.state is JobState.FAILED
    assert not job.retries_exhausted
    assert metrics.counter_value(
        "serving.recovery.retries_exhausted") == 0


def test_junk_max_levels_fails_permanently(metrics):
    """A bfs job with unparseable max_levels is a param error: it must
    FAIL on attempt 1, never burn its retry budget (the same contract
    as an unresolvable source)."""
    sched = JobScheduler(snapshot=_tiny_snapshot(), metrics=metrics)
    try:
        job = sched.submit(JobSpec(kind="bfs",
                                   params={"source_dense": 0,
                                           "max_levels": "abc"},
                                   max_retries=3, retry_backoff_s=0.01))
        assert job.wait(10)
        assert job.state is JobState.FAILED and job.attempt == 1
        assert metrics.counter_value("serving.recovery.retries") == 0
    finally:
        sched.close()


def test_wire_junk_faults_value_rejected(metrics):
    """An arbitrary params['faults'] value (e.g. from the HTTP body)
    must be rejected at admission — inside the fused batch it would
    fail every batchmate."""
    sched = JobScheduler(snapshot=_tiny_snapshot(), metrics=metrics)
    try:
        with pytest.raises(ValueError):
            sched.submit(JobSpec(kind="bfs",
                                 params={"source_dense": 0,
                                         "faults": {"crash": 2}}))
        assert metrics.counter_value("serving.jobs.rejected") == 1
        assert metrics.counter_value("serving.jobs.submitted") == 0
    finally:
        sched.close()


def test_checkpoint_keys_namespaced_per_scheduler(tmp_path):
    """Two schedulers (processes) sharing one checkpoint_dir must key
    their jobs' checkpoints disjointly — job ids restart per process,
    and resuming another scheduler's checkpoint would serve its state
    as this job's result."""
    s1 = JobScheduler(snapshot=_tiny_snapshot(), autostart=False,
                      checkpoint_dir=str(tmp_path))
    s2 = JobScheduler(snapshot=_tiny_snapshot(), autostart=False,
                      checkpoint_dir=str(tmp_path))
    try:
        j1 = s1.submit(JobSpec(kind="bfs", params={"source_dense": 0},
                               checkpoint_every=1))
        j2 = s2.submit(JobSpec(kind="bfs", params={"source_dense": 0},
                               checkpoint_every=1))
        assert j1.recovery.key.endswith(j1.id)
        assert j1.recovery.key != j1.id          # nonce-prefixed
        ns1 = j1.recovery.key[:-len(j1.id)]
        ns2 = j2.recovery.key[:-len(j2.id)]
        assert ns1 != ns2
    finally:
        s1.close()
        s2.close()


# --------------------------------------------------------------------------
# satellite: submitted-vs-rejected metrics (the submit() counter lie)
# --------------------------------------------------------------------------

def test_rejected_submits_do_not_count_as_submitted(metrics):
    sched = JobScheduler(snapshot=_tiny_snapshot(), metrics=metrics)
    with pytest.raises(ValueError):
        sched.submit(JobSpec(kind="astrology"))
    assert metrics.counter_value("serving.jobs.submitted") == 0
    assert metrics.counter_value("serving.jobs.rejected") == 1
    job = sched.submit(JobSpec(kind="callable",
                               params={"fn": lambda: 1}))
    assert job.wait(10)
    assert metrics.counter_value("serving.jobs.submitted") == 1
    sched.close()
    with pytest.raises(RuntimeError):
        sched.submit(JobSpec(kind="callable",
                             params={"fn": lambda: 1}))
    assert metrics.counter_value("serving.jobs.submitted") == 1
    assert metrics.counter_value("serving.jobs.rejected") == 2


# --------------------------------------------------------------------------
# satellite: close-during-RUNNING — DONE must never follow FAILED
# --------------------------------------------------------------------------

def test_never_done_after_failed_on_close(metrics):
    """close() fails a still-RUNNING job while the worker thread may
    finish afterwards and call complete(): the terminal state must stay
    FAILED and the terminal metrics must fire exactly once."""
    sched = JobScheduler(snapshot=_tiny_snapshot(), metrics=metrics)
    release = threading.Event()
    entered = threading.Event()

    def fn():
        entered.set()
        release.wait(30)
        return "late result"

    job = sched.submit(JobSpec(kind="callable", params={"fn": fn}))
    assert entered.wait(10)
    assert job.state is JobState.RUNNING
    sched.close(timeout=0.2)          # worker still blocked in fn()
    assert job.state is JobState.FAILED
    release.set()                     # the worker now finishes fn()...
    sched._worker.join(10)
    # ...but the completion must lose the race it already lost
    assert job.state is JobState.FAILED
    assert job.result is None
    assert metrics.counter_value("serving.jobs.failed") == 1
    assert metrics.counter_value("serving.jobs.completed") == 0
    # latency histogram sampled exactly once too
    assert metrics.histogram("serving.job.latency_ms").count == 1


def test_done_and_cancel_race_is_single_terminal(sched, metrics):
    """Direct Job-level pin: once terminal, every later transition
    (complete / fail / retrying-fail) is a no-op."""
    from titan_tpu.olap.serving.jobs import Job

    job = Job(JobSpec(kind="callable", max_retries=5))
    assert job.start()                    # QUEUED -> RUNNING
    assert job.fail("dead", permanent=True)
    assert job.state is JobState.FAILED
    assert not job.complete({"v": 1})
    assert job.state is JobState.FAILED and job.result is None
    assert not job.fail("again")
    assert job.metered_once() and not job.metered_once()
