"""Multi-chip hybrid BFS over the virtual 8-device CPU mesh.

(reference role: the distributed OLAP execution tier — HadoopScanMapper /
the v5e-8 BASELINE config; here validated by bit-exact agreement with the
single-chip hybrid on the same graphs, incl. the sparse found-list
exchange and vertex-block edge sharding.)
"""

import numpy as np
import pytest

from titan_tpu.models import bfs_hybrid_sharded as S
from titan_tpu.models.bfs import frontier_bfs
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.olap.tpu.rmat import rmat_edges
from titan_tpu.parallel.mesh import vertex_mesh


def sym_snap_from(src, dst, n):
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


@pytest.mark.parametrize("scale,ef,seed", [(10, 8, 1), (12, 8, 2)])
@pytest.mark.slow
def test_sharded_hybrid_matches_single_chip(scale, ef, seed):
    src, dst = rmat_edges(scale, ef, seed=seed)
    n = 1 << scale
    snap = sym_snap_from(src, dst, n)
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, lv_ref = frontier_bfs(snap, source)
    mesh = vertex_mesh(8)
    d_sh, lv_sh = S.frontier_bfs_hybrid_sharded(snap, source, mesh)
    assert (np.asarray(d_sh) == d_ref).all()
    assert lv_sh == lv_ref


@pytest.mark.slow
def test_sharded_hybrid_random_graphs():
    rng = np.random.default_rng(9)
    mesh = vertex_mesh(8)
    for _ in range(3):
        n = int(rng.integers(64, 500))
        m = int(rng.integers(n, 4 * n))
        src = rng.integers(0, n, m).astype(np.int32)
        dst = rng.integers(0, n, m).astype(np.int32)
        snap = sym_snap_from(src, dst, n)
        source = int(np.flatnonzero(snap.out_degree > 0)[0])
        d_ref, _ = frontier_bfs(snap, source)
        d_sh, _ = S.frontier_bfs_hybrid_sharded(snap, source, mesh)
        assert (np.asarray(d_sh) == d_ref).all()


def test_shard_layout_int32_safety_at_scale26_shape():
    """Shard arithmetic for a scale-26-shaped graph (2^31 symmetrized
    edges, 2^26 vertices, 8 shards): every shard's LOCAL chunk count must
    stay far below 2^31 even though the global slot count exceeds it.
    Pure arithmetic on a synthetic degree profile — no allocation."""
    n = 1 << 26
    rng = np.random.default_rng(0)
    # power-law-ish degrees summing to ~2^31
    deg = rng.zipf(1.7, size=1 << 20).astype(np.int64)
    scale_up = (1 << 31) / deg.sum() / (n / (1 << 20))
    # expand the sample profile across all vertices
    degc = -(-(deg * scale_up).astype(np.int64) // 8)
    colstart_sample = np.concatenate([[0], np.cumsum(degc)])
    total = int(colstart_sample[-1]) * (n // (1 << 20))
    assert total * 8 >= (1 << 30)          # genuinely scale-26-like mass
    per_shard = total // 8
    assert per_shard < (1 << 31)           # local columns are int32-safe
    assert per_shard * 8 * 4 < 5 * (1 << 30)   # < 5GB per chip's slice


@pytest.mark.slow
def test_sharded_hybrid_uses_sparse_exchange_not_full_pmin():
    """The exchange gathers found-id lists sized by the actual per-chip
    discovery maxima (the round-1 design all-reduced all n elements
    every level). On a path graph the frontier is ONE vertex per level,
    so every exchange cap must stay tiny regardless of n."""
    n = 400
    src = np.arange(n - 1, dtype=np.int32)
    dst = src + 1
    snap = sym_snap_from(src, dst, n)
    mesh = vertex_mesh(8)
    d_sh, levels = S.frontier_bfs_hybrid_sharded(snap, 0, mesh)
    d_ref, _ = frontier_bfs(snap, 0)
    assert (np.asarray(d_sh) == d_ref).all()
    assert levels in (n - 1, n)   # final empty round may count
    assert S.LAST_EXCHANGE_CAPS, "exchange instrumentation missing"
    assert max(S.LAST_EXCHANGE_CAPS) <= 8 < n
    # and the per-shard edge arrays are genuinely partitioned
    from titan_tpu.models.bfs_hybrid import build_chunked_csr
    sh = S.shard_chunked_csr(build_chunked_csr(snap), 8)
    assert sh["dstT_sh"].shape[0] == 8
    assert sh["q_max"] <= sh["q_total"]


def test_shard_cut_int32_boundary():
    """VERDICT r2 item 7: the sharded path documents that per-shard LOCAL
    chunk counts must stay int32-safe; this exercises the cut planner at
    the 2^31 boundary with synthetic colstart values (shapes only — no
    giant arrays)."""
    import numpy as np

    from titan_tpu.models.bfs_hybrid_sharded import plan_shard_cuts

    n = 1 << 10
    # global chunk total ~3 * 2^31: far past int32, uniform degree
    per_vertex = (3 * (1 << 31)) // n
    colstart = np.arange(n + 1, dtype=np.int64) * per_vertex

    # 1 shard would need a 3*2^31 local span -> must refuse, not wrap
    with pytest.raises(NotImplementedError, match="int32"):
        plan_shard_cuts(colstart, n, 1)

    # 8 shards: ~3*2^28 per shard, safe; verify exact local indices
    bounds, b_max, q_max = plan_shard_cuts(colstart, n, 8)
    assert q_max < (1 << 31)
    for d in range(len(bounds) - 1):
        lo, hi = int(bounds[d]), int(bounds[d + 1])
        c0 = int(colstart[lo])
        local = (colstart[lo:hi + 1] - c0).astype(np.int32)
        # int32 round trip is exact (no wraparound) for every local start
        assert (local.astype(np.int64) ==
                colstart[lo:hi + 1] - c0).all()
        assert local[-1] < q_max

    # shard spans just UNDER the boundary must pass
    per_vertex = ((1 << 31) - 16) // (n // 4)    # 4 shards ~2^31-eps each
    colstart = np.arange(n + 1, dtype=np.int64) * per_vertex
    bounds, b_max, q_max = plan_shard_cuts(colstart, n, 4)
    assert (1 << 30) < q_max < (1 << 31)
