"""Index subsystem suite: composite + mixed indexes, Geoshape, lifecycle.

Modeled on the reference's TitanIndexTest / IndexSerializer coverage
(titan-test): composite equality retrieval, uniqueness, multi-key indexes,
mixed text/numeric/geo queries, tx-delta visibility, persistence across
reopen.
"""

import pytest

import titan_tpu
from titan_tpu.core.attribute import Geoshape
from titan_tpu.errors import SchemaViolationError
from titan_tpu.query.predicates import P


@pytest.fixture(params=["inmemory", "sqlite", "sqlite+fts"])
def g(request, tmp_path):
    if request.param == "inmemory":
        graph = titan_tpu.open({"storage.backend": "inmemory",
                                "index.search.backend": "memindex"})
    elif request.param == "sqlite":
        graph = titan_tpu.open({"storage.backend": "sqlite",
                                "storage.directory": str(tmp_path / "db"),
                                "index.search.backend": "memindex",
                                "index.search.directory": str(tmp_path / "idx")})
    else:   # the persistent FTS5 provider in the Lucene role
        graph = titan_tpu.open({"storage.backend": "sqlite",
                                "storage.directory": str(tmp_path / "db"),
                                "index.search.backend": "lucene",
                                "index.search.directory": str(tmp_path / "idx")})
    yield graph
    graph.close()


def _mk_people(g, n=5):
    tx = g.new_transaction()
    ids = []
    for i in range(n):
        v = tx.add_vertex("person", name=f"p{i}", age=20 + i)
        ids.append(v.id)
    tx.commit()
    return ids


# -- composite ----------------------------------------------------------------

def test_composite_index_equality(g):
    mgmt = g.management()
    name = mgmt.make_property_key("name", str)
    mgmt.build_index("byName", "vertex").add_key(name).build_composite_index()
    mgmt.commit()
    ids = _mk_people(g)

    tx = g.new_transaction()
    hits = tx.query().has("name", "p3").vertices()
    assert [v.id for v in hits] == [ids[3]]
    assert tx.query().has("name", "nope").vertices() == []
    tx.commit()


def test_composite_index_multi_key(g):
    mgmt = g.management()
    k1 = mgmt.make_property_key("first", str)
    k2 = mgmt.make_property_key("last", str)
    mgmt.build_index("byFullName", "vertex").add_key(k1).add_key(k2) \
        .build_composite_index()
    mgmt.commit()

    tx = g.new_transaction()
    a = tx.add_vertex(first="ada", last="lovelace")
    tx.add_vertex(first="ada", last="wong")
    tx.commit()

    tx = g.new_transaction()
    hits = tx.query().has("first", "ada").has("last", "lovelace").vertices()
    assert [v.id for v in hits] == [a.id]
    # only one key bound -> index doesn't cover, full-scan fallback still works
    assert len(tx.query().has("first", "ada").vertices()) == 2
    tx.commit()


def test_composite_index_updates_on_change(g):
    mgmt = g.management()
    name = mgmt.make_property_key("name", str)
    mgmt.build_index("byName2", "vertex").add_key(name).build_composite_index()
    mgmt.commit()
    [vid] = _mk_people(g, 1)

    tx = g.new_transaction()
    tx.vertex(vid).property("name", "renamed")
    tx.commit()

    tx = g.new_transaction()
    assert tx.query().has("name", "p0").vertices() == []
    assert [v.id for v in tx.query().has("name", "renamed").vertices()] == [vid]
    # removal drops the entry
    tx.vertex(vid).remove()
    tx.commit()
    tx = g.new_transaction()
    assert tx.query().has("name", "renamed").vertices() == []
    tx.commit()


def test_unique_index(g):
    mgmt = g.management()
    ssn = mgmt.make_property_key("ssn", str)
    mgmt.build_index("bySsn", "vertex").add_key(ssn).unique() \
        .build_composite_index()
    mgmt.commit()

    tx = g.new_transaction()
    tx.add_vertex(ssn="123")
    tx.commit()

    tx = g.new_transaction()
    tx.add_vertex(ssn="123")
    with pytest.raises(SchemaViolationError):
        tx.commit()
    # different value is fine
    tx = g.new_transaction()
    tx.add_vertex(ssn="456")
    tx.commit()


def test_index_sees_tx_delta(g):
    mgmt = g.management()
    name = mgmt.make_property_key("name", str)
    mgmt.build_index("byName3", "vertex").add_key(name).build_composite_index()
    mgmt.commit()
    ids = _mk_people(g, 2)

    tx = g.new_transaction()
    v = tx.add_vertex(name="fresh")          # uncommitted
    tx.vertex(ids[0]).remove()               # uncommitted removal
    hits = {u.id for u in tx.query().has("name", "fresh").vertices()}
    assert hits == {v.id}
    assert tx.query().has("name", "p0").vertices() == []
    tx.rollback()


def test_edge_composite_index(g):
    mgmt = g.management()
    since = mgmt.make_property_key("since", int)
    mgmt.build_index("bySince", "edge").add_key(since).build_composite_index()
    mgmt.commit()

    tx = g.new_transaction()
    a = tx.add_vertex(name="a")
    b = tx.add_vertex(name="b")
    e = tx.add_edge(a, "knows", b, {"since": 1999})
    tx.add_edge(b, "knows", a, {"since": 2024})
    tx.commit()

    tx = g.new_transaction()
    hits = tx.query().has("since", 1999).edges()
    assert [h.id for h in hits] == [e.id]
    assert hits[0].label() == "knows"
    tx.commit()


def test_index_survives_reopen(tmp_path):
    cfg = {"storage.backend": "sqlite",
           "storage.directory": str(tmp_path / "db")}
    g = titan_tpu.open(cfg)
    mgmt = g.management()
    name = mgmt.make_property_key("name", str)
    mgmt.build_index("byName", "vertex").add_key(name).build_composite_index()
    mgmt.commit()
    tx = g.new_transaction()
    vid = tx.add_vertex(name="durable").id
    tx.commit()
    g.close()

    g = titan_tpu.open(cfg)
    tx = g.new_transaction()
    assert [v.id for v in tx.query().has("name", "durable").vertices()] == [vid]
    idx = g.management().get_graph_index("byName")
    assert idx is not None and idx.composite
    tx.commit()
    g.close()


def test_index_lifecycle_status(g):
    """An index over a pre-existing key starts INSTALLED and is not used."""
    from titan_tpu.core.defs import SchemaStatus
    _mk_people(g, 1)   # auto-creates "name" before the index exists
    mgmt = g.management()
    idx = mgmt.build_index("late", "vertex").add_key("name") \
        .build_composite_index()
    assert idx.status is SchemaStatus.INSTALLED
    mgmt.commit()

    tx = g.new_transaction()
    # falls back to full scan (INSTALLED index is not queryable) and still
    # finds the pre-existing vertex
    assert len(tx.query().has("name", "p0").vertices()) == 1
    tx.commit()


# -- mixed --------------------------------------------------------------------

def test_mixed_text_and_range(g):
    mgmt = g.management()
    desc = mgmt.make_property_key("desc", str)
    age = mgmt.make_property_key("age2", int)
    mgmt.build_index("search1", "vertex").add_key(desc, "TEXT") \
        .add_key(age).build_mixed_index("search")
    mgmt.commit()

    tx = g.new_transaction()
    v1 = tx.add_vertex(desc="the quick brown fox", age2=10)
    v2 = tx.add_vertex(desc="a lazy dog sleeps", age2=20)
    v3 = tx.add_vertex(desc="quick silver dog", age2=30)
    tx.commit()

    tx = g.new_transaction()
    hits = {v.id for v in tx.query().has("desc", P.text_contains("quick"))
            .vertices()}
    assert hits == {v1.id, v3.id}
    hits = {v.id for v in tx.query().has("desc", P.text_contains("dog"))
            .has("age2", P.gt(25)).vertices()}
    assert hits == {v3.id}
    hits = {v.id for v in tx.query().has("age2", P.between(10, 25)).vertices()}
    assert hits == {v1.id, v2.id}
    tx.commit()


def test_mixed_updates_and_removal(g):
    mgmt = g.management()
    desc = mgmt.make_property_key("bio", str)
    mgmt.build_index("search2", "vertex").add_key(desc, "TEXT") \
        .build_mixed_index("search")
    mgmt.commit()

    tx = g.new_transaction()
    v = tx.add_vertex(bio="loves graphs")
    tx.commit()

    tx = g.new_transaction()
    tx.vertex(v.id).property("bio", "loves tensors")
    tx.commit()

    tx = g.new_transaction()
    assert tx.query().has("bio", P.text_contains("graphs")).vertices() == []
    assert len(tx.query().has("bio", P.text_contains("tensors")).vertices()) == 1
    tx.vertex(v.id).remove()
    tx.commit()

    tx = g.new_transaction()
    assert tx.query().has("bio", P.text_contains("tensors")).vertices() == []
    tx.commit()


def test_mixed_geo(g):
    mgmt = g.management()
    place = mgmt.make_property_key("place", Geoshape)
    mgmt.build_index("geo1", "vertex").add_key(place).build_mixed_index("search")
    mgmt.commit()

    tx = g.new_transaction()
    sf = tx.add_vertex(place=Geoshape.point(37.77, -122.42))
    nyc = tx.add_vertex(place=Geoshape.point(40.71, -74.0))
    tx.commit()

    tx = g.new_transaction()
    bay = Geoshape.circle(37.75, -122.4, 50)
    hits = {v.id for v in tx.query().has("place", P.geo_within(bay)).vertices()}
    assert hits == {sf.id}
    box = Geoshape.box(35.0, -125.0, 45.0, -70.0)
    hits = {v.id for v in tx.query().has("place", P.geo_within(box)).vertices()}
    assert hits == {sf.id, nyc.id}
    tx.commit()


def test_raw_index_query(g):
    mgmt = g.management()
    desc = mgmt.make_property_key("text", str)
    mgmt.build_index("search3", "vertex").add_key(desc, "TEXT") \
        .build_mixed_index("search")
    mgmt.commit()

    tx = g.new_transaction()
    v = tx.add_vertex(text="hello world")
    tx.add_vertex(text="goodbye world")
    tx.commit()

    hits = g.index_query("search3", "text:hello")
    # score scale is provider-specific (memindex: 1.0, FTS: bm25) — assert
    # the hit and that the score is a positive relevance value
    assert [el.id for el, _ in hits] == [v.id]
    assert all(s > 0 for _, s in hits)
    assert len(g.index_query("search3", "world")) == 2


# -- geoshape unit ------------------------------------------------------------

def test_geoshape_geometry():
    p = Geoshape.point(37.77, -122.42)
    c = Geoshape.circle(37.75, -122.4, 50)
    b = Geoshape.box(37.0, -123.0, 38.0, -122.0)
    assert p.within(c) and p.within(b)
    assert not Geoshape.point(40.7, -74.0).within(c)
    assert c.intersect(b)
    assert c.disjoint(Geoshape.circle(40.7, -74.0, 10))
    d = Geoshape.distance_km((37.77, -122.42), (40.71, -74.0))
    assert 4100 < d < 4200   # SF-NYC great-circle ~4130km


def test_geoshape_roundtrip(g):
    tx = g.new_transaction()
    shape = Geoshape.circle(1.5, 2.5, 10.0)
    v = tx.add_vertex(spot=shape)
    tx.commit()
    tx = g.new_transaction()
    assert tx.vertex(v.id).value("spot") == shape
    tx.commit()


def test_cluster_index_names_refuse_memindex_fallback(tmp_path):
    """VERDICT r3 weak #4: backend=elasticsearch/solr must NOT silently
    construct the in-process MemoryIndex (reference maps those names to
    real cluster providers, StandardIndexProvider.java:14-18)."""
    from titan_tpu.errors import ConfigurationError
    for name in ("elasticsearch", "solr"):
        with pytest.raises(ConfigurationError, match="remote-index"):
            titan_tpu.open({"storage.backend": "inmemory",
                            "index.search.backend": name})
    # the explicit in-process spelling still works
    g = titan_tpu.open({"storage.backend": "inmemory",
                        "index.search.backend": "memindex"})
    g.close()
