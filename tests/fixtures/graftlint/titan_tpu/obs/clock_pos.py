"""R5 positive fixture: a declared clock seam with bare wall-clock
reads beside it."""

import time


class Burny:
    def __init__(self, clock=None):
        self.clock = clock or time.time   # the seam default: a REFERENCE

    def record(self):
        now = time.time()                 # bare read despite the seam
        mono = time.monotonic()           # same
        return now, mono
