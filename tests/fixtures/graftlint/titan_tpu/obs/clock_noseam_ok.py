"""R5 negative fixture: no seam declared — the module may read the
wall clock freely (the rule enforces consistency, not seams)."""

import time


class Seamless:
    def stamp(self):
        return time.time()
