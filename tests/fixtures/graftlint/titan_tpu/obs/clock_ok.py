"""R5 negative fixture: a seamed module that routes every read through
the seam."""

import time


class Seamed:
    def __init__(self, clock=None):
        self.clock = clock or time.time

    def stamp(self):
        return self.clock()
