"""Suppression-semantics fixture: trailing allow, standalone allow by
alias, and a reasonless allow that must stay INERT."""

import jax.numpy as jnp


def suppressed_trailing(mask):
    return jnp.nonzero(mask)[0]  # graftlint: allow[opscan] reason=fixture demonstrating trailing-line suppression


def suppressed_standalone(mask):
    # graftlint: allow[R1] reason=fixture demonstrating next-line suppression by alias
    return jnp.flatnonzero(mask)


def bare_allow_is_inert(mask):
    return jnp.unique(mask)  # graftlint: allow[opscan]


# a directive QUOTED in a string is text, not a suppression — if it
# were honored, the allow-file form below would silence this whole
# file (including the deliberately-unsuppressed finding above)
QUOTED = "# graftlint: allow-file[opscan] reason=quoted in a string"
