"""R3 negative fixture: cv waits, clock reads, state mutation under
the lock; blocking work OUTSIDE it (the post-PR-10 `_requeue`)."""

import json
import time


class Disciplined:
    def poll(self, path):
        with self._cv:
            while not self._ready:
                self._cv.wait(0.25)      # waiting is the cv's job
            t0 = time.time()             # clock READ is not blocking
            items, self._queue = self._queue, []
            self._cv.notify_all()
        payload = json.dumps(items)      # serialize outside the lock
        with open(path, "w") as fh:      # I/O outside the lock
            fh.write(payload)
        return t0

    def schedule(self, cb):
        with self._lock:
            def later():                 # nested def doesn't RUN here
                time.sleep(1.0)
                cb()

            self._cb = later
