"""R4 positive fixture: literal metric names outside the pinned
families, and a family-valid name with no monitoring.md row."""


class Metered:
    def __init__(self, metrics):
        metrics.counter("bogus.name").inc()              # 2 components
        metrics.histogram("unpinned.family.name")        # unknown family
        metrics.timer("serving.fixture.undocumented")    # no doc row
