"""R3 positive fixture: the PR-10 `_requeue` stall shape — blocking
work lexically under the scheduler cv / ledger lock. Never imported."""

import json
import os
import subprocess
import time
import urllib.request


class StallProne:
    def _requeue(self, path, payload):
        with self._cv:
            with open(path, "w") as fh:            # file I/O under cv
                json.dump(payload, fh)             # ... twice
            os.replace(path, path + ".done")       # rename under cv
            time.sleep(0.1)                        # sleep under cv
            urllib.request.urlopen("http://x/")    # network under cv
            subprocess.run(["sync"])               # subprocess under cv

    def _dispatch(self, batch):
        import jax
        import jax.numpy as jnp

        with self._lock:
            out = jnp.zeros((8,))                  # device dispatch
            dev = jax.device_put(batch)            # upload under lock
            out.block_until_ready()                # device sync
            return out, dev
