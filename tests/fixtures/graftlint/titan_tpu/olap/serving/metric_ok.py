"""R4 negative fixture: documented literals pass; variables and
templated f-strings are test_docs_metrics's job, not the linter's."""

FAMILY = "serving.fixture.dynamic"


class Ok:
    def __init__(self, metrics, kind):
        metrics.timer("serving.fixture.documented")          # has a row
        metrics.gauge("serving.fixture.documented_gauge",    # has a row
                      lambda: 1.0)
        metrics.counter(FAMILY)                              # variable
        metrics.counter(f"serving.fixture.{kind}")           # templated
