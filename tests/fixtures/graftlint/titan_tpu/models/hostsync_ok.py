"""R2 negative fixture: statics, shape metadata, and host-side code
are all fair game."""

import functools

from titan_tpu.utils.jitcache import jit_once


def good_kernel():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_", "wide"))
        def kern(x, n_: int, wide: bool = False):
            if wide:                     # static param: compile-time branch
                x = x * 2
            rows = int(x.shape[0])       # static metadata off a traced arg
            pad = jnp.asarray(n_)        # jnp coercion stays on device
            return jnp.where(x > 0, x, pad), rows

        return kern

    return jit_once("fixture_host_ok", build)


def host_helper(arr):
    """Not a registered kernel — plain host code may coerce freely."""
    import numpy as np

    return int(arr[0]) + float(np.asarray(arr).sum())


def static_argnums_at_call_site():
    """static_argnums on the registration-site jax.jit CALL (not a
    decorator) must mark the positional param static too."""
    import jax

    def step(x, n):
        if n > 3:                # n is static via static_argnums=(1,)
            return x * n
        return x

    return jit_once("fixture_static_nums",
                    lambda: jax.jit(step, static_argnums=(1,)))
