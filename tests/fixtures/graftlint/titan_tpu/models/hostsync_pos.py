"""R2 positive fixture: every host-sync shape inside a registered
kernel. Never imported."""

import numpy as np

from titan_tpu.utils.jitcache import jit_once


def bad_kernel():
    def build():
        import jax

        @jax.jit
        def kern(x, y):
            if x > 0:                    # Python `if` on a traced value
                y = y + 1
            n = int(x)                   # host coercion of a traced value
            h = np.asarray(y)            # numpy materialization
            g = jax.device_get(y)        # explicit device->host pull
            s = y.sum().item()           # blocking scalar readback
            return n + h + g + s

        return kern

    return jit_once("fixture_host_sync", build)


def bad_mesh_kernel(mesh):
    from titan_tpu.parallel.mesh import mesh_jit

    def build(m):
        def body(x, width):
            while x.any():               # Python `while` on traced
                x = x - 1
            return float(x)              # coercion again

        return body

    return mesh_jit("fixture_mesh_sync", mesh, build, out_specs=None)
