"""R1 negative fixture: the legal neighbors of every banned shape."""

import jax.numpy as jnp
import numpy as np

from titan_tpu.utils.jitcache import jit_once


def fine(mask, x, y, cap):
    sel = jnp.where(mask, x, y)          # 3-arg select
    host = np.nonzero(mask)              # host numpy, function form
    flat = np.flatnonzero(mask)          # ditto
    return sel, host, flat, cap


def masked_scatter():
    def build():
        import jax

        @jax.jit
        def kern(x, m):
            return x.at[m > 0].set(0)    # fixed-shape masked scatter

        return kern

    return jit_once("fixture_masked_scatter", build)
