"""R1/R2 positive fixture: traced-ref abuse inside ``pl.pallas_call``
kernels, through both registration spellings — an inline
``functools.partial`` and a local ``kern = ...`` name. Never
imported."""

import functools

import jax
from jax.experimental import pallas as pl


def _bad_kernel(x_ref, o_ref, *, block):
    if x_ref[0] > 0:                  # Python `if` on a traced ref
        o_ref[0] = 1
    n = int(x_ref[0])                 # host coercion of a traced ref
    s = x_ref[...].sum().item()       # blocking scalar readback
    v = x_ref[...]
    o_ref[...] = v[v > 0]             # bool-mask gather inside a kernel
    del n, s, block


def run_inline(x):
    return pl.pallas_call(
        functools.partial(_bad_kernel, block=128),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def _bad_local(y_ref, o_ref, *, width):
    while y_ref[0] > 0:               # Python `while` on a traced ref
        o_ref[0] = width


def run_local(y):
    kern = functools.partial(_bad_local, width=8)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(y.shape, y.dtype),
    )(y)
