"""R1 positive fixture: every banned op-scan shape. Never imported."""

import jax.numpy as jnp

from titan_tpu.utils.jitcache import jit_once


def hard_banned(mask, n):
    a = jnp.nonzero(mask)[0]                         # unbounded op-scan
    b = jnp.nonzero(mask, size=8, fill_value=n)[0]   # bounded: still banned
    c = jnp.flatnonzero(mask)                        # unbounded
    d = jnp.unique(a)                                # data-dependent shape
    e = jnp.where(mask)[0]                           # nonzero in disguise
    f = jnp.where(mask, size=8)[0]                   # sized disguise: same
    g = mask.nonzero()[0]                            # method spelling: same
    return a, b, c, d, e, f, g


def masked_gather():
    def build():
        import jax

        @jax.jit
        def kern(x, m):
            return x[m > 0]         # bool-mask gather inside a kernel

        return kern

    return jit_once("fixture_masked_gather", build)
