"""R1/R2 negative fixture: a pallas kernel whose Python control flow
runs on keyword-only compile-time constants. ``pallas_call`` passes
only the refs, positionally, so the seam must classify kwonly params
(bound through ``functools.partial``) as static — the ``while d <
block`` ladder idiom of ops/pallas_segment.py. Never imported."""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _ok_kernel(v_ref, o_ref, *, block, masked):
    v = v_ref[...]
    d = 1
    while d < block:                  # static unroll ladder — legal
        v = v + jnp.pad(v[:, :-d], ((0, 0), (d, 0)))
        d <<= 1
    if masked:                        # static config branch — legal
        v = v * 2
    if v_ref.shape[0] > 1:            # static shape metadata — legal
        v = v + 1
    o_ref[...] = v


def run(x):
    kern = functools.partial(_ok_kernel, block=128, masked=False)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
