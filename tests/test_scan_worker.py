"""Multi-host distributed scan: HTTP scan workers against a cluster
backend.

(reference: titan-hadoop-core scan/HadoopScanMapper — ScanJobs executed
in cluster containers against the shared store, with failed-container
re-runs; here 2+ scan-worker nodes speak the worker protocol over HTTP
against remote-cluster storage nodes.)
"""

import pytest

import titan_tpu
from titan_tpu.errors import TemporaryBackendError
from titan_tpu.olap.distributed import ScanJobSpec
from titan_tpu.olap.jobs import VertexCountJob
from titan_tpu.olap.scan_worker import (RemoteScanRunner, ScanWorkerServer,
                                        distributed_reindex_remote)
from titan_tpu.storage.inmemory import InMemoryStoreManager
from titan_tpu.storage.remote import KCVSServer


@pytest.fixture
def cluster():
    storage = [KCVSServer(InMemoryStoreManager()).start() for _ in range(2)]
    cfg = {"storage.backend": "remote-cluster",
           "storage.hostname": [f"127.0.0.1:{s.port}" for s in storage],
           "storage.cluster.replication-factor": 2}
    workers = [ScanWorkerServer().start() for _ in range(2)]
    yield cfg, workers
    for node in workers + storage:
        node.stop()


def _populate(cfg, n_people=30, n_edges=45):
    import numpy as np
    g = titan_tpu.open(cfg)
    tx = g.new_transaction()
    people = [tx.add_vertex("person", name=f"p{i}")
              for i in range(n_people)]
    rng = np.random.default_rng(3)
    for _ in range(n_edges):
        a, b = rng.integers(0, n_people, 2)
        people[int(a)].add_edge("knows", people[int(b)])
    tx.commit()
    g.close()


def test_remote_workers_scan_cluster_backend(cluster):
    cfg, workers = cluster
    _populate(cfg)
    runner = RemoteScanRunner(
        [f"127.0.0.1:{w.port}" for w in workers], cfg)
    spec = ScanJobSpec("titan_tpu.olap.jobs:make_vertex_count_job")
    metrics = runner.run(spec)
    assert metrics.get(VertexCountJob.VERTICES) == 30
    assert metrics.get(VertexCountJob.EDGES) == 45


def test_worker_failover_requeues_splits(cluster):
    cfg, workers = cluster
    _populate(cfg, n_people=20, n_edges=10)
    dead = ScanWorkerServer().start()
    dead_addr = f"127.0.0.1:{dead.port}"
    dead.stop()                     # worker 0 is a corpse
    runner = RemoteScanRunner(
        [dead_addr, f"127.0.0.1:{workers[1].port}"], cfg,
        splits_per_worker=3)
    spec = ScanJobSpec("titan_tpu.olap.jobs:make_vertex_count_job")
    metrics = runner.run(spec)      # survivor picks up the corpse's splits
    assert metrics.get(VertexCountJob.VERTICES) == 20
    assert metrics.get(VertexCountJob.EDGES) == 10


def test_all_workers_dead_raises(cluster):
    cfg, _ = cluster
    _populate(cfg, n_people=2, n_edges=0)
    d1 = ScanWorkerServer().start()
    addr = f"127.0.0.1:{d1.port}"
    d1.stop()
    runner = RemoteScanRunner([addr], cfg)
    with pytest.raises(TemporaryBackendError, match="undispatchable"):
        runner.run(ScanJobSpec(
            "titan_tpu.olap.jobs:make_vertex_count_job"))


def test_distributed_reindex_over_remote_workers(cluster):
    cfg, workers = cluster
    g = titan_tpu.open(cfg)
    tx = g.new_transaction()
    for i in range(15):
        tx.add_vertex("person", name=f"r{i}")
    tx.commit()
    mgmt = g.management()
    key = g.schema.get_by_name("name")
    mgmt.build_index("byNameRemote", "vertex").add_key(key) \
        .build_composite_index()
    mgmt.update_index("byNameRemote", "register")
    g.close()

    metrics = distributed_reindex_remote(
        [f"127.0.0.1:{w.port}" for w in workers], cfg, "byNameRemote")
    assert metrics.get("index-entries-added") == 15

    g2 = titan_tpu.open(cfg)
    g2.management().update_index("byNameRemote", "enable")
    got = g2.traversal().V().has("name", "r7").to_list()
    assert len(got) == 1
    g2.close()


def test_requeued_split_reaches_idle_worker(cluster):
    """A split re-queued by a dying worker must be picked up by a healthy
    worker even if that worker already saw an empty queue (review
    finding: idle drain loops exited too early and orphaned the split)."""
    cfg, workers = cluster
    _populate(cfg, n_people=12, n_edges=6)
    dead = ScanWorkerServer().start()
    dead_addr = f"127.0.0.1:{dead.port}"
    dead.stop()
    # one split per worker: the healthy worker drains its own split and
    # would previously exit before the dead worker's split bounced back
    runner = RemoteScanRunner(
        [f"127.0.0.1:{workers[0].port}", dead_addr], cfg,
        splits_per_worker=1)
    metrics = runner.run(ScanJobSpec(
        "titan_tpu.olap.jobs:make_vertex_count_job"))
    assert metrics.get(VertexCountJob.VERTICES) == 12
    assert metrics.get(VertexCountJob.EDGES) == 6


def test_bad_job_spec_fails_fast_as_permanent(cluster):
    """A permanently-broken job (unresolvable factory) must surface as
    PermanentBackendError immediately, not as a retryable
    'all workers failed' (review finding)."""
    from titan_tpu.errors import PermanentBackendError
    cfg, workers = cluster
    _populate(cfg, n_people=2, n_edges=0)
    runner = RemoteScanRunner(
        [f"127.0.0.1:{w.port}" for w in workers], cfg)
    with pytest.raises(PermanentBackendError):
        runner.run(ScanJobSpec("titan_tpu.no_such_module:nope"))


def test_worker_rejects_unlisted_factory():
    from titan_tpu.errors import PermanentBackendError
    from titan_tpu.utils.httpnode import json_call
    w = ScanWorkerServer().start()
    try:
        with pytest.raises(PermanentBackendError, match="allowlist"):
            json_call(w.url, "/scan", {
                "factory": "os:system", "kwargs": {},
                "graph_config": {}, "key_start": "", "key_end": ""})
    finally:
        w.stop()


def test_worker_bearer_token_gate():
    from titan_tpu.errors import PermanentBackendError
    from titan_tpu.utils.httpnode import json_call
    w = ScanWorkerServer(auth_token="s3cret").start()
    try:
        with pytest.raises(PermanentBackendError, match="bearer"):
            json_call(w.url, "/ping", {})
        assert json_call(w.url, "/ping", {}, token="s3cret") == {"ok": True}
    finally:
        w.stop()


def test_factory_allowlist_is_dot_anchored():
    """ADVICE r3: entry 'myjobs' must not admit sibling 'myjobs_evil'."""
    w = ScanWorkerServer(factory_allow=["myjobs", "titan_tpu."])
    assert w._factory_allowed("myjobs:job")
    assert w._factory_allowed("myjobs.sub:job")
    assert w._factory_allowed("titan_tpu.olap.jobs:GhostVertexRemover")
    assert not w._factory_allowed("myjobs_evil:job")
    assert not w._factory_allowed("titan_tpu_evil.mod:job")
    assert not w._factory_allowed("os:system")


def test_scan_metrics_and_spans_on_failover(cluster):
    """ISSUE 14 satellite: the distributed scan path reports its split
    flow — dispatched / merged / re-dispatched counters, per-{url}
    worker failures, worker-side served counts — and (with a tracer)
    one `split` span per attempt under the reserved "scan" trace id,
    so a dead worker's re-dispatch is visible instead of hiding inside
    a slower wall clock."""
    from titan_tpu.obs.tracing import Tracer
    from titan_tpu.utils.metrics import MetricManager

    cfg, _stock = cluster
    _populate(cfg, n_people=18, n_edges=9)
    m = MetricManager()
    live = ScanWorkerServer(metrics=m).start()
    dead = ScanWorkerServer().start()
    dead_addr = f"127.0.0.1:{dead.port}"
    dead.stop()                     # worker 0 is a corpse
    tracer = Tracer()
    runner = RemoteScanRunner(
        [dead_addr, f"127.0.0.1:{live.port}"], cfg,
        splits_per_worker=2, metrics=m, tracer=tracer)
    try:
        got = runner.run(ScanJobSpec(
            "titan_tpu.olap.jobs:make_vertex_count_job"))
        assert got.get(VertexCountJob.VERTICES) == 18
        # 4 splits total; the corpse's first split re-dispatched to the
        # survivor, which served every split
        assert m.counter_value("scan.remote.splits_merged") == 4
        assert m.counter_value("scan.remote.splits_served") == 4
        assert m.counter_value("scan.remote.splits_redispatched") == 1
        # dispatched counts attempts: 4 merges + the failed one
        assert m.counter_value("scan.remote.splits_dispatched") == 5
        assert m.counter_value(
            "scan.remote.worker_failures",
            labels={"url": f"http://{dead_addr}"}) == 1
        # one completed span per attempt under the reserved "scan" id
        spans = tracer.spans("scan")
        assert spans is not None and len(spans) == 5
        assert all(s.name == "split" and s.t_end is not None
                   for s in spans)
        failed = [s for s in spans if s.attrs.get("redispatched")]
        assert len(failed) == 1
        assert failed[0].attrs["url"] == f"http://{dead_addr}"
        assert "error" in failed[0].attrs
        oks = [s for s in spans if s.attrs.get("ok")]
        assert len(oks) == 4 and all(
            s.attrs["url"] == f"http://127.0.0.1:{live.port}"
            for s in oks)
    finally:
        live.stop()
