"""Multi-host distributed scan: HTTP scan workers against a cluster
backend.

(reference: titan-hadoop-core scan/HadoopScanMapper — ScanJobs executed
in cluster containers against the shared store, with failed-container
re-runs; here 2+ scan-worker nodes speak the worker protocol over HTTP
against remote-cluster storage nodes.)
"""

import pytest

import titan_tpu
from titan_tpu.errors import TemporaryBackendError
from titan_tpu.olap.distributed import ScanJobSpec
from titan_tpu.olap.jobs import VertexCountJob
from titan_tpu.olap.scan_worker import (RemoteScanRunner, ScanWorkerServer,
                                        distributed_reindex_remote)
from titan_tpu.storage.inmemory import InMemoryStoreManager
from titan_tpu.storage.remote import KCVSServer


@pytest.fixture
def cluster():
    storage = [KCVSServer(InMemoryStoreManager()).start() for _ in range(2)]
    cfg = {"storage.backend": "remote-cluster",
           "storage.hostname": [f"127.0.0.1:{s.port}" for s in storage],
           "storage.cluster.replication-factor": 2}
    workers = [ScanWorkerServer().start() for _ in range(2)]
    yield cfg, workers
    for node in workers + storage:
        node.stop()


def _populate(cfg, n_people=30, n_edges=45):
    import numpy as np
    g = titan_tpu.open(cfg)
    tx = g.new_transaction()
    people = [tx.add_vertex("person", name=f"p{i}")
              for i in range(n_people)]
    rng = np.random.default_rng(3)
    for _ in range(n_edges):
        a, b = rng.integers(0, n_people, 2)
        people[int(a)].add_edge("knows", people[int(b)])
    tx.commit()
    g.close()


def test_remote_workers_scan_cluster_backend(cluster):
    cfg, workers = cluster
    _populate(cfg)
    runner = RemoteScanRunner(
        [f"127.0.0.1:{w.port}" for w in workers], cfg)
    spec = ScanJobSpec("titan_tpu.olap.jobs:make_vertex_count_job")
    metrics = runner.run(spec)
    assert metrics.get(VertexCountJob.VERTICES) == 30
    assert metrics.get(VertexCountJob.EDGES) == 45


def test_worker_failover_requeues_splits(cluster):
    cfg, workers = cluster
    _populate(cfg, n_people=20, n_edges=10)
    dead = ScanWorkerServer().start()
    dead_addr = f"127.0.0.1:{dead.port}"
    dead.stop()                     # worker 0 is a corpse
    runner = RemoteScanRunner(
        [dead_addr, f"127.0.0.1:{workers[1].port}"], cfg,
        splits_per_worker=3)
    spec = ScanJobSpec("titan_tpu.olap.jobs:make_vertex_count_job")
    metrics = runner.run(spec)      # survivor picks up the corpse's splits
    assert metrics.get(VertexCountJob.VERTICES) == 20
    assert metrics.get(VertexCountJob.EDGES) == 10


def test_all_workers_dead_raises(cluster):
    cfg, _ = cluster
    _populate(cfg, n_people=2, n_edges=0)
    d1 = ScanWorkerServer().start()
    addr = f"127.0.0.1:{d1.port}"
    d1.stop()
    runner = RemoteScanRunner([addr], cfg)
    with pytest.raises(TemporaryBackendError, match="undispatchable"):
        runner.run(ScanJobSpec(
            "titan_tpu.olap.jobs:make_vertex_count_job"))


def test_distributed_reindex_over_remote_workers(cluster):
    cfg, workers = cluster
    g = titan_tpu.open(cfg)
    tx = g.new_transaction()
    for i in range(15):
        tx.add_vertex("person", name=f"r{i}")
    tx.commit()
    mgmt = g.management()
    key = g.schema.get_by_name("name")
    mgmt.build_index("byNameRemote", "vertex").add_key(key) \
        .build_composite_index()
    mgmt.update_index("byNameRemote", "register")
    g.close()

    metrics = distributed_reindex_remote(
        [f"127.0.0.1:{w.port}" for w in workers], cfg, "byNameRemote")
    assert metrics.get("index-entries-added") == 15

    g2 = titan_tpu.open(cfg)
    g2.management().update_index("byNameRemote", "enable")
    got = g2.traversal().V().has("name", "r7").to_list()
    assert len(got) == 1
    g2.close()


def test_requeued_split_reaches_idle_worker(cluster):
    """A split re-queued by a dying worker must be picked up by a healthy
    worker even if that worker already saw an empty queue (review
    finding: idle drain loops exited too early and orphaned the split)."""
    cfg, workers = cluster
    _populate(cfg, n_people=12, n_edges=6)
    dead = ScanWorkerServer().start()
    dead_addr = f"127.0.0.1:{dead.port}"
    dead.stop()
    # one split per worker: the healthy worker drains its own split and
    # would previously exit before the dead worker's split bounced back
    runner = RemoteScanRunner(
        [f"127.0.0.1:{workers[0].port}", dead_addr], cfg,
        splits_per_worker=1)
    metrics = runner.run(ScanJobSpec(
        "titan_tpu.olap.jobs:make_vertex_count_job"))
    assert metrics.get(VertexCountJob.VERTICES) == 12
    assert metrics.get(VertexCountJob.EDGES) == 6


def test_bad_job_spec_fails_fast_as_permanent(cluster):
    """A permanently-broken job (unresolvable factory) must surface as
    PermanentBackendError immediately, not as a retryable
    'all workers failed' (review finding)."""
    from titan_tpu.errors import PermanentBackendError
    cfg, workers = cluster
    _populate(cfg, n_people=2, n_edges=0)
    runner = RemoteScanRunner(
        [f"127.0.0.1:{w.port}" for w in workers], cfg)
    with pytest.raises(PermanentBackendError):
        runner.run(ScanJobSpec("titan_tpu.no_such_module:nope"))


def test_worker_rejects_unlisted_factory():
    from titan_tpu.errors import PermanentBackendError
    from titan_tpu.utils.httpnode import json_call
    w = ScanWorkerServer().start()
    try:
        with pytest.raises(PermanentBackendError, match="allowlist"):
            json_call(w.url, "/scan", {
                "factory": "os:system", "kwargs": {},
                "graph_config": {}, "key_start": "", "key_end": ""})
    finally:
        w.stop()


def test_worker_bearer_token_gate():
    from titan_tpu.errors import PermanentBackendError
    from titan_tpu.utils.httpnode import json_call
    w = ScanWorkerServer(auth_token="s3cret").start()
    try:
        with pytest.raises(PermanentBackendError, match="bearer"):
            json_call(w.url, "/ping", {})
        assert json_call(w.url, "/ping", {}, token="s3cret") == {"ok": True}
    finally:
        w.stop()


def test_factory_allowlist_is_dot_anchored():
    """ADVICE r3: entry 'myjobs' must not admit sibling 'myjobs_evil'."""
    w = ScanWorkerServer(factory_allow=["myjobs", "titan_tpu."])
    assert w._factory_allowed("myjobs:job")
    assert w._factory_allowed("myjobs.sub:job")
    assert w._factory_allowed("titan_tpu.olap.jobs:GhostVertexRemover")
    assert not w._factory_allowed("myjobs_evil:job")
    assert not w._factory_allowed("titan_tpu_evil.mod:job")
    assert not w._factory_allowed("os:system")


def test_scan_metrics_and_spans_on_failover(cluster):
    """ISSUE 14 satellite: the distributed scan path reports its split
    flow — dispatched / merged / re-dispatched counters, per-{url}
    worker failures, worker-side served counts — and (with a tracer)
    one `split` span per attempt under the reserved "scan" trace id,
    so a dead worker's re-dispatch is visible instead of hiding inside
    a slower wall clock."""
    from titan_tpu.obs.tracing import Tracer
    from titan_tpu.utils.metrics import MetricManager

    cfg, _stock = cluster
    _populate(cfg, n_people=18, n_edges=9)
    m = MetricManager()
    live = ScanWorkerServer(metrics=m).start()
    dead = ScanWorkerServer().start()
    dead_addr = f"127.0.0.1:{dead.port}"
    dead.stop()                     # worker 0 is a corpse
    tracer = Tracer()
    runner = RemoteScanRunner(
        [dead_addr, f"127.0.0.1:{live.port}"], cfg,
        splits_per_worker=2, metrics=m, tracer=tracer)
    try:
        got = runner.run(ScanJobSpec(
            "titan_tpu.olap.jobs:make_vertex_count_job"))
        assert got.get(VertexCountJob.VERTICES) == 18
        # 4 splits total; the corpse's first split re-dispatched to the
        # survivor, which served every split
        assert m.counter_value("scan.remote.splits_merged") == 4
        assert m.counter_value("scan.remote.splits_served") == 4
        assert m.counter_value("scan.remote.splits_redispatched") == 1
        # dispatched counts attempts: 4 merges + the failed one
        assert m.counter_value("scan.remote.splits_dispatched") == 5
        assert m.counter_value(
            "scan.remote.worker_failures",
            labels={"url": f"http://{dead_addr}"}) == 1
        # one completed coordinator span per attempt under the
        # reserved "scan" id (ISSUE 18 also splices the worker's own
        # spans in, marked remote=True — filtered out here)
        all_spans = tracer.spans("scan")
        assert all_spans is not None
        spans = [s for s in all_spans
                 if not (s.attrs or {}).get("remote")]
        assert len(spans) == 5
        assert all(s.name == "split" and s.t_end is not None
                   for s in spans)
        failed = [s for s in spans if s.attrs.get("redispatched")]
        assert len(failed) == 1
        assert failed[0].attrs["url"] == f"http://{dead_addr}"
        assert "error" in failed[0].attrs
        oks = [s for s in spans if s.attrs.get("ok")]
        assert len(oks) == 4 and all(
            s.attrs["url"] == f"http://127.0.0.1:{live.port}"
            for s in oks)
        # ISSUE 18: the dead worker produced no remote spans, but every
        # merged split shipped its worker half back
        remote = [s for s in all_spans
                  if (s.attrs or {}).get("remote")]
        assert remote and all(
            s.attrs["instance"] == f"http://127.0.0.1:{live.port}"
            for s in remote)
    finally:
        live.stop()


def test_distributed_scan_yields_one_stitched_trace(cluster):
    """ISSUE 18 acceptance: a scan fanned out to >= 2 workers yields
    ONE trace tree with worker split/execute/serialize spans parented
    under the coordinator's split spans, timestamps monotonic after
    skew normalization."""
    from titan_tpu.obs.tracing import Tracer
    from titan_tpu.utils.metrics import MetricManager

    cfg, workers = cluster
    _populate(cfg, n_people=24, n_edges=12)
    m = MetricManager()
    tracer = Tracer()
    runner = RemoteScanRunner(
        [f"127.0.0.1:{w.port}" for w in workers], cfg,
        metrics=m, tracer=tracer, trace_id="scan-job-1")
    got = runner.run(ScanJobSpec(
        "titan_tpu.olap.jobs:make_vertex_count_job"))
    assert got.get(VertexCountJob.VERTICES) == 24

    tree = tracer.tree("scan-job-1")
    assert tree is not None and tree["trace"] == "scan-job-1"
    # every root is a coordinator split span; each carries the worker's
    # own split span, which carries execute + serialize
    assert len(tree["spans"]) == 4          # 2 workers x 2 splits
    instances = set()
    for coord in tree["spans"]:
        assert coord["name"] == "split"
        assert "remote" not in (coord.get("attrs") or {})
        kids = coord["children"]
        assert len(kids) == 1 and kids[0]["name"] == "split"
        wroot = kids[0]
        assert wroot["attrs"]["remote"] is True
        instances.add(wroot["attrs"]["instance"])
        names = sorted(c["name"] for c in wroot["children"])
        assert names == ["execute", "serialize"]
        # monotonic after skew normalization: children nest inside
        # their parent's window, parent inside the coordinator span
        def nested(parent, node):
            assert parent["start"] <= node["start"] <= node["end"] \
                <= parent["end"], (parent["name"], node["name"])
            for c in node["children"]:
                nested(node, c)
        for c in kids:
            nested(coord, c)
    # both worker processes contributed spans to the ONE tree
    assert len(instances) == 2
    assert m.counter_value("obs.ingest.spans") == 12  # 3 per split


def test_scan_results_bit_equal_with_propagation_on_and_off(cluster):
    """ISSUE 18 acceptance: trace propagation changes what the trace
    can show, never the scan's results."""
    from titan_tpu.obs.tracing import Tracer
    from titan_tpu.utils.metrics import MetricManager

    cfg, workers = cluster
    _populate(cfg)
    urls = [f"127.0.0.1:{w.port}" for w in workers]
    spec = ScanJobSpec("titan_tpu.olap.jobs:make_vertex_count_job")
    on = RemoteScanRunner(urls, cfg, metrics=MetricManager(),
                          tracer=Tracer(), propagate=True).run(spec)
    off_tracer = Tracer()
    off = RemoteScanRunner(urls, cfg, metrics=MetricManager(),
                           tracer=off_tracer, propagate=False).run(spec)
    bare = RemoteScanRunner(urls, cfg,
                            metrics=MetricManager()).run(spec)
    assert on._counts == off._counts == bare._counts
    # propagate=False means the coordinator's own spans still journal,
    # but nothing remote ever splices in
    assert all(not (s.attrs or {}).get("remote")
               for s in off_tracer.spans("scan"))


def test_worker_failure_label_cardinality_is_bounded():
    """ISSUE 18 satellite: ~300 distinct worker urls must degrade via
    the MAX_CHILDREN path (metrics.labels.dropped counted), not grow
    unbounded per-{url} children."""
    from titan_tpu.utils.metrics import MetricManager

    m = MetricManager()
    n_urls = MetricManager.MAX_CHILDREN + 44       # ~300
    for i in range(n_urls):
        m.counter("scan.remote.worker_failures",
                  labels={"url": f"http://10.0.0.{i}:9{i:03d}"}).inc()
    kids = m.children("scan.remote.worker_failures")
    assert len(kids) == MetricManager.MAX_CHILDREN
    # every increment landed on the parent (degraded ones directly)
    assert m.counter_value("scan.remote.worker_failures") == n_urls
    assert m.counter_value(MetricManager.LABELS_DROPPED) == 44


def test_worker_get_metrics_and_healthz():
    """ISSUE 18: workers expose GET /metrics (Prometheus text) and
    GET /healthz for the federation plane."""
    import json as _json

    from titan_tpu.utils.httpnode import text_get
    from titan_tpu.utils.metrics import MetricManager

    m = MetricManager()
    m.counter("scan.remote.splits_served").inc(7)
    w = ScanWorkerServer(metrics=m).start()
    try:
        body = text_get(w.url, "/metrics")
        assert "scan_remote_splits_served 7" in body
        hz = _json.loads(text_get(w.url, "/healthz"))
        assert hz["live"] and hz["ready"]
        assert hz["role"] == "scan-worker"
        assert hz["splits_served"] == 7
    finally:
        w.stop()


def test_worker_trace_drain_endpoint_is_bounded():
    """Fire-and-forget pickup: spans a worker journaled but never
    shipped drain over POST /trace/drain, at most once, bounded."""
    from titan_tpu.obs.tracing import INGEST_MAX_SPANS
    from titan_tpu.utils.httpnode import json_call

    w = ScanWorkerServer().start()
    try:
        for i in range(5):
            w.tracer.event("bg", f"tick{i}")
        res = json_call(w.url, "/trace/drain",
                        {"trace": "bg", "max_spans": 3})
        assert [s["name"] for s in res["spans"]] == \
            ["tick0", "tick1", "tick2"]
        # a drain pops what it returns; the rest comes next poll
        res2 = json_call(w.url, "/trace/drain",
                         {"trace": "bg", "max_spans": INGEST_MAX_SPANS * 9})
        assert [s["name"] for s in res2["spans"]] == ["tick3", "tick4"]
        assert json_call(w.url, "/trace/drain",
                         {"trace": "bg"})["spans"] == []
    finally:
        w.stop()
