"""Deployment assembly: start/stop/status over pidfiles (titan.sh role)."""

import os
import textwrap
import time

import pytest

from titan_tpu import deploy


@pytest.mark.slow
def test_deploy_lifecycle(tmp_path):
    (tmp_path / "dep.yaml").write_text(textwrap.dedent(f"""\
        services:
          - kind: storage-node
            name: store-a
            data-dir: {tmp_path}/store-a
            port: 18233
          - kind: scan-worker
            name: worker-a
            port: 0
        """))
    path = str(tmp_path / "dep.yaml")
    assert deploy.start(path) == 2
    time.sleep(1.0)
    st = deploy.status(path)
    assert st["store-a"] and st["worker-a"]
    # idempotent start
    assert deploy.start(path) == 0
    assert deploy.stop(path) == 2
    st = deploy.status(path)
    assert st["store-a"] is None and st["worker-a"] is None
    assert os.path.exists(str(tmp_path / ".pids" / "store-a.log"))
