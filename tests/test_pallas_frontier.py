"""Interpreter-mode bit-equality of the Pallas bottom-up frontier
kernel against the XLA chain (ISSUE 16).

``TITAN_TPU_FRONTIER_KERNEL=pallas`` routes the bottom-up candidate
fetch+test+compact through ops/pallas_frontier.frontier_round; off-TPU
the kernel runs in Pallas interpreter mode, so these tests exercise the
EXACT kernel program on CPU and pin bit-equality to the XLA path across
{plain, batched K=8, sharded 8-dev mesh} x {no overlay, tombstone
overlay} x {no masks, level_masks} x seeds. A direct oracle test covers
the kernel contract itself (lane ladder, tombstone slots, stable
survivor compaction, multi-block SMEM cursor carry).
"""

import numpy as np
import pytest

import titan_tpu.models.bfs_hybrid as H
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.ops.pallas_frontier import (frontier_kernel_mode,
                                           frontier_round,
                                           ladder_fetch_counts)

N, M = 192, 900
SEEDS = [0, 1, 2]


def sym_snap(seed, n=N, m=M):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m)
    dst = rng.integers(0, n, m)
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


def force_bu(monkeypatch):
    """Route the plain driver through the bottom-up chain at toy scale
    (the head loop and the endgame would otherwise swallow it — same
    idiom as tests/test_frontier_models.py)."""
    monkeypatch.setattr(H, "SPLIT_LANE_MIN", 2)
    monkeypatch.setattr(H, "END_C_CAP", 0)
    monkeypatch.setattr(H, "END_P_CAP", 0)
    monkeypatch.setattr(H, "HEAD_F_CAP", 1)


def both_modes(monkeypatch, run):
    monkeypatch.setenv("TITAN_TPU_FRONTIER_KERNEL", "xla")
    ref = run()
    monkeypatch.setenv("TITAN_TPU_FRONTIER_KERNEL", "pallas")
    got = run()
    return ref, got


def assert_tuples_equal(ref, got):
    for a, b in zip(ref, got):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_mode_flag_validates(monkeypatch):
    monkeypatch.setenv("TITAN_TPU_FRONTIER_KERNEL", "mosaic")
    with pytest.raises(ValueError, match="TITAN_TPU_FRONTIER_KERNEL"):
        frontier_kernel_mode()
    monkeypatch.delenv("TITAN_TPU_FRONTIER_KERNEL")
    assert frontier_kernel_mode() == "xla"


@pytest.mark.parametrize("seed", SEEDS)
def test_plain_bu_bit_equal(seed, monkeypatch):
    force_bu(monkeypatch)
    snap = sym_snap(seed)
    src = int(np.flatnonzero(snap.out_degree > 0)[0])
    ref, got = both_modes(
        monkeypatch, lambda: H.frontier_bfs_hybrid(snap, src))
    assert np.array_equal(ref[0], got[0])
    assert ref[1] == got[1]


@pytest.mark.parametrize("seed", SEEDS)
def test_batched_bit_equal(seed, monkeypatch):
    snap = sym_snap(seed)
    rng = np.random.default_rng(seed)
    sources = [int(x) for x in rng.choice(N, 8, replace=False)]
    ref, got = both_modes(
        monkeypatch, lambda: H.frontier_bfs_batched(snap, sources))
    assert_tuples_equal(ref, got)


def _overlay_view(snap, seed, src, dst):
    from titan_tpu.olap.live.overlay import DeltaOverlay

    rng = np.random.default_rng(seed + 100)
    ov = DeltaOverlay(snap, min_cap=256)
    a_s = rng.integers(0, N, 60).astype(np.int32)
    a_d = rng.integers(0, N, 60).astype(np.int32)
    ov.append_edges(np.concatenate([a_s, a_d]),
                    np.concatenate([a_d, a_s]),
                    np.zeros(120, np.int32))
    for i in rng.choice(M, 40, replace=False):
        ov.remove_edge(int(src[i]), int(dst[i]), None)
        ov.remove_edge(int(dst[i]), int(src[i]), None)
    return ov.view()


@pytest.mark.parametrize("seed", SEEDS)
def test_batched_tombstone_overlay_bit_equal(seed, monkeypatch):
    """The tombstone bitmap rides the kernel's tbits seam: flag-on must
    match flag-off under a live overlay with adds AND removes."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, N, M).astype(np.int32)
    dst = rng.integers(0, N, M).astype(np.int32)
    snap = snap_mod.from_arrays(N, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    view = _overlay_view(snap, seed, src, dst)
    sources = [int(x) for x in rng.choice(N, 8, replace=False)]
    ref, got = both_modes(
        monkeypatch,
        lambda: H.frontier_bfs_batched(snap, sources, overlay=view))
    assert_tuples_equal(ref, got)


@pytest.mark.parametrize("seed", SEEDS)
def test_batched_level_masks_bit_equal(seed, monkeypatch):
    """Per-level label masks (hops mode) ride the same tbits seam."""
    import jax.numpy as jnp

    snap = sym_snap(seed)
    g = H.build_chunked_csr(snap)
    rng = np.random.default_rng(seed)
    lm_bytes = rng.integers(0, 256, g["q_total"]).astype(np.uint8)
    lm_bytes[-1] = 0                    # the all-pad sink column
    lm = jnp.asarray(lm_bytes)
    sources = [int(x) for x in rng.choice(N, 8, replace=False)]
    ref, got = both_modes(
        monkeypatch,
        lambda: H.frontier_bfs_batched(
            snap, sources, mode="hops", start_level=1, max_levels=4,
            level_masks=[None, lm, lm]))
    assert_tuples_equal(ref, got)


@pytest.mark.parametrize(
    "seed", [SEEDS[0]] + [pytest.param(s, marks=pytest.mark.slow)
                          for s in SEEDS[1:]])
def test_sharded_bit_equal_and_dispatch_budget(seed, monkeypatch):
    """shx_bu_pallas on the 8-device CPU mesh: bit-equal to the plain
    hybrid AND the per-level dispatch budget (<= 2 with the found_cap
    retry) unchanged from the XLA path."""
    import titan_tpu.models.bfs_hybrid_sharded as S
    from titan_tpu.parallel.mesh import vertex_mesh

    snap = sym_snap(seed, n=600, m=3000)
    src = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_plain, lv_plain = H.frontier_bfs_hybrid(snap, src)
    mesh = vertex_mesh(8)

    def run():
        out = S.frontier_bfs_hybrid_sharded(snap, src, mesh)
        return out + ([p["dispatches"] for p in S.LAST_PROFILE],)

    (d0, l0, disp0), (d1, l1, disp1) = both_modes(monkeypatch, run)
    assert np.array_equal(np.asarray(d0), d_plain) and l0 == lv_plain
    assert np.array_equal(np.asarray(d1), d_plain) and l1 == lv_plain
    assert disp0 == disp1 and max(disp1) <= 2


@pytest.mark.parametrize("lanes", [2, 8])
@pytest.mark.parametrize("masked", [False, True])
def test_frontier_round_matches_oracle(lanes, masked):
    """Direct kernel contract vs a numpy oracle: found flags equal the
    flat 8-lane masked bitmap test for every undecided (job, candidate)
    pair; survivors compact in stable candidate order with the
    scatter_compact fills; nsur is exact. block=16 forces the
    multi-block SMEM-cursor path (C=70 -> 5 blocks with a padded
    tail)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    K, C, Q = 3, 70, 51
    q_pad = Q - 1
    n_val = 160                          # parent ids in [0, n_val]
    dstT = rng.integers(0, n_val + 1, (8, Q)).astype(np.int32)
    cols = rng.integers(0, Q, C).astype(np.int32)
    undec = rng.random((K, C)) < 0.7
    has_more = rng.random(C) < 0.6
    pay0 = rng.integers(0, n_val, C).astype(np.int32)
    pay1 = rng.integers(0, 8, C).astype(np.int32)
    fbits = rng.integers(0, 256, (K, (n_val + 9) // 8)).astype(np.uint8)
    tbits = rng.integers(0, 256, Q).astype(np.uint8) if masked else None

    found, p0, p1, nsur = frontier_round(
        jnp.asarray(cols), jnp.asarray(undec), jnp.asarray(has_more),
        jnp.asarray(pay0), jnp.asarray(pay1), jnp.asarray(fbits),
        None if tbits is None else jnp.asarray(tbits),
        jnp.asarray(dstT), lanes=lanes, fill0=-7, fill1=-9, block=16,
        interpret=True)

    par = dstT[:, cols]                              # (8, C)
    hit = (fbits[:, par >> 3] >> (par & 7)[None]) & 1   # (K, 8, C)
    if masked:
        slot = cols[None, :] * 8 + np.arange(8)[:, None]
        hit = hit & ~((tbits[slot >> 3] >> (slot & 7)) & 1)[None]
    hit = hit.any(axis=1)                            # (K, C)
    exp_found = undec & hit
    assert np.array_equal(np.asarray(found), exp_found)

    surv = (undec & ~hit).any(axis=0) & has_more
    idx = np.flatnonzero(surv)
    assert int(nsur) == idx.size
    exp0 = np.full(C, -7, np.int32)
    exp1 = np.full(C, -9, np.int32)
    exp0[:idx.size] = pay0[idx]
    exp1[:idx.size] = pay1[idx]
    assert np.array_equal(np.asarray(p0), exp0)
    assert np.array_equal(np.asarray(p1), exp1)


def test_ladder_never_changes_found_set():
    """The narrow-first ladder (lanes=2) and the flat 8-lane fetch
    (lanes=8) produce identical kernel outputs — the fetched-byte
    saving is free of result risk by construction."""
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    K, C, Q = 2, 40, 33
    dstT = rng.integers(0, 120, (8, Q)).astype(np.int32)
    cols = rng.integers(0, Q, C).astype(np.int32)
    undec = rng.random((K, C)) < 0.8
    has_more = rng.random(C) < 0.5
    pay0 = np.arange(C, dtype=np.int32)
    pay1 = np.arange(C, dtype=np.int32) * 2
    fbits = rng.integers(0, 256, (K, 16)).astype(np.uint8)
    args = (jnp.asarray(cols), jnp.asarray(undec),
            jnp.asarray(has_more), jnp.asarray(pay0),
            jnp.asarray(pay1), jnp.asarray(fbits), None,
            jnp.asarray(dstT))
    outs = [frontier_round(*args, lanes=w, fill0=0, fill1=0, block=16,
                           interpret=True) for w in (2, 8)]
    for a, b in zip(*outs):
        assert np.array_equal(np.asarray(a), np.asarray(b))
