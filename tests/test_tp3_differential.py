"""Differential TP3-semantics harness: random traversals through the
DSL interpreter AND the independent oracle (tests/tp3_oracle.py).

(reference role: the TinkerPop structure/process compliance suites the
reference inherits via titan-test/.../blueprints/
AbstractTitanGraphProvider.java — re-created here as randomized
differential testing against a from-the-spec oracle, since the real TP3
suites are JVM-only.)

Every random spec is built from a grammar that only emits well-formed
pipelines (element steps before property filters, value steps before
numeric folds, order keys that exist on every element, limit only after
order so both sides pick the same prefix). Results compare as multisets
of canonical values — vertices by their unique ``name``, edges by their
unique ``eid`` — except after ``order``, which compares ordered lists.
"""

from __future__ import annotations

import random

import pytest

import titan_tpu
from titan_tpu.query.predicates import P
from titan_tpu.traversal.dsl import anon

import tp3_oracle

V_LABELS = ["person", "place", "thing"]
E_LABELS = ["knows", "likes", "near"]


# --------------------------------------------------------------------------
# paired graph construction (titan inmemory + oracle dicts)
# --------------------------------------------------------------------------

def build_pair(seed: int, n: int = 24, m: int = 60):
    rng = random.Random(seed)
    g = titan_tpu.open("inmemory")
    tx = g.new_transaction()
    og = {"vertices": {}, "edges": {}, "out": {}, "in": {}}
    dsl_v = []
    for i in range(n):
        label = rng.choice(V_LABELS)
        props = {"name": f"n{i}"}
        if rng.random() < 0.8:
            props["age"] = rng.randint(0, 50)
        dsl_v.append(tx.add_vertex(label, **props))
        og["vertices"][i] = {"label": label, "props": dict(props)}
        og["out"][i] = []
        og["in"][i] = []
    for j in range(m):
        a, b = rng.randrange(n), rng.randrange(n)
        if a == b:
            continue                    # self-loop corner covered by hand
        label = rng.choice(E_LABELS)
        props = {"eid": f"e{j}"}
        if rng.random() < 0.7:
            props["weight"] = rng.randint(1, 9)
        dsl_v[a].add_edge(label, dsl_v[b], **props)
        eid = f"e{j}"
        og["edges"][eid] = {"src": a, "dst": b, "label": label,
                            "props": dict(props)}
        og["out"][a].append(eid)
        og["in"][b].append(eid)
    tx.commit()
    # the DSL's groupCount (by=None) keys buckets by ELEMENT ID (a
    # hashable wire-friendly key; TP3 keys by the element object) —
    # the id->name map lets the comparison canonicalize either form
    idmap = {dsl_v[i].id: ("v", f"n{i}") for i in range(n)}
    return g, og, idmap


# --------------------------------------------------------------------------
# random spec grammar
# --------------------------------------------------------------------------

def _labels(rng, pool):
    k = rng.choice([0, 1, 2])
    return tuple(rng.sample(pool, k))


def _has(rng, on_edge=False):
    if on_edge:
        key = "weight"
        v = rng.randint(1, 9)
    elif rng.random() < 0.5:
        key, v = "age", rng.randint(0, 50)
    else:
        key, v = "name", f"n{rng.randrange(24)}"
    if isinstance(v, str):
        pred = rng.choice([("eq", v),
                           ("within", (v, f"n{rng.randrange(24)}"))])
    else:
        pred = rng.choice([("eq", v), ("gt", v), ("lt", v), ("gte", v),
                           ("lte", v), ("neq", v),
                           ("between", max(0, v - 5), v + 5),
                           ("within", (v, v + 1, v + 2))])
    return ("has", key, pred)


def _hop(rng):
    return (rng.choice(["out", "in", "both"]), _labels(rng, E_LABELS))


def _edge_hop(rng):
    e = rng.choice(["outE", "inE", "bothE"])
    if e == "outE":
        back = rng.choice(["inV", "outV"])
    elif e == "inE":
        back = rng.choice(["inV", "outV"])
    else:
        back = "otherV"
    steps = [(e, _labels(rng, E_LABELS))]
    if rng.random() < 0.4:
        steps.append(_has(rng, on_edge=True))
    steps.append((back,))
    return steps


def _sub_pipeline(rng, depth):
    """Sub-traversal for where/not/union/coalesce/repeat: hops and
    filters only (the oracle's traverser-preserving step set)."""
    steps = []
    for _ in range(rng.randint(1, 2)):
        r = rng.random()
        if r < 0.55 or depth > 1:
            steps.append(_hop(rng))
        elif r < 0.75:
            steps.extend(_edge_hop(rng))
        else:
            steps.append(_hop(rng))
            steps.append(_has(rng))
    return steps


def gen_spec(rng):
    """One well-formed random traversal spec + comparison mode."""
    steps = [("V",)]
    as_labels = []
    n_elem = rng.randint(1, 3)
    for depth in range(n_elem):
        r = rng.random()
        if r < 0.30:
            steps.append(_hop(rng))
        elif r < 0.42:
            steps.extend(_edge_hop(rng))
        elif r < 0.52:
            steps.append(_has(rng))
        elif r < 0.58:
            steps.append(("hasLabel", _labels(rng, V_LABELS) or
                          (rng.choice(V_LABELS),)))
        elif r < 0.64:
            steps.append(("dedup",))
        elif r < 0.70:
            steps.append(("where", _sub_pipeline(rng, depth)))
        elif r < 0.74:
            steps.append(("not", _sub_pipeline(rng, depth)))
        elif r < 0.80:
            subs = [_sub_pipeline(rng, depth)
                    for _ in range(rng.randint(2, 3))]
            steps.append((rng.choice(["union", "coalesce"]), subs))
        elif r < 0.88:
            # random `until` on a cyclic graph can be a genuine infinite
            # loop (TP3 would loop too); the do-while form is pinned by
            # the deterministic test below instead
            steps.append(("repeat", [_hop(rng)],
                          ("times", rng.randint(1, 2)),
                          rng.random() < 0.4))
        elif r < 0.94:
            lb = f"s{len(as_labels)}"
            as_labels.append(lb)
            steps.append(("as", lb))
            steps.append(_hop(rng))
        else:
            steps.append(("simplePath",))
    # optional select of accumulated labels
    if as_labels and rng.random() < 0.5:
        take = tuple(rng.sample(as_labels,
                                rng.randint(1, len(as_labels))))
        by = "name" if rng.random() < 0.5 else None
        steps.append(("select", take, by))
        return steps, "multiset"
    # terminal
    r = rng.random()
    if r < 0.25:
        steps.append(("count",))
        return steps, "list"
    if r < 0.40:
        steps.append(("values", ("age",)))
        steps.append((rng.choice(["sum", "min", "max", "mean"]),))
        return steps, "list"
    if r < 0.55:
        by = "name" if rng.random() < 0.5 else None
        steps.append(("groupCount", by))
        return steps, "groupcount"
    if r < 0.70:
        steps.append(("order", "name", rng.random() < 0.5))
        if rng.random() < 0.5:
            steps.append(("limit", rng.randint(1, 5)))
        return steps, "list"
    if r < 0.80:
        steps.append(("path",))
        return steps, "multiset"
    if r < 0.90:
        steps.append(("values", tuple(rng.sample(["name", "age"],
                                                 rng.randint(1, 2)))))
        return steps, "multiset"
    return steps, "multiset"


# --------------------------------------------------------------------------
# spec -> DSL translation
# --------------------------------------------------------------------------

_PREDS = {"eq": P.eq, "neq": P.neq, "gt": P.gt, "gte": P.gte,
          "lt": P.lt, "lte": P.lte}


def _to_pred(p):
    if p[0] == "within":
        return P.within(*p[1])
    if p[0] == "between":
        return P.between(p[1], p[2])
    return _PREDS[p[0]](p[1])


def to_dsl(t, spec):
    """Apply ``spec`` steps to DSL traversal ``t`` (or anon())."""
    for step in spec:
        op = step[0]
        if op == "V":
            t = t.V()
        elif op == "out":
            t = t.out(*step[1])
        elif op == "in":
            t = t.in_(*step[1])
        elif op == "both":
            t = t.both(*step[1])
        elif op == "outE":
            t = t.out_e(*step[1])
        elif op == "inE":
            t = t.in_e(*step[1])
        elif op == "bothE":
            t = t.both_e(*step[1])
        elif op == "outV":
            t = t.out_v()
        elif op == "inV":
            t = t.in_v()
        elif op == "otherV":
            t = t.other_v()
        elif op == "has":
            t = t.has(step[1], _to_pred(step[2]))
        elif op == "hasLabel":
            t = t.has_label(*step[1])
        elif op == "values":
            t = t.values(*step[1])
        elif op == "dedup":
            t = t.dedup()
        elif op == "limit":
            t = t.limit(step[1])
        elif op == "order":
            t = t.order(by=step[1], desc=step[2])
        elif op == "as":
            t = t.as_(step[1])
        elif op == "select":
            t = t.select(*step[1])
            if step[2] is not None:
                t = t.by(step[2])
        elif op == "where":
            t = t.where(to_dsl(anon(), step[1]))
        elif op == "not":
            t = t.not_(to_dsl(anon(), step[1]))
        elif op == "union":
            t = t.union(*[to_dsl(anon(), s) for s in step[1]])
        elif op == "coalesce":
            t = t.coalesce(*[to_dsl(anon(), s) for s in step[1]])
        elif op == "repeat":
            t = t.repeat(to_dsl(anon(), step[1]))
            stop = step[2]
            if stop[0] == "times":
                t = t.times(stop[1])
            else:
                t = t.until(to_dsl(anon(), stop[1]))
            if step[3]:
                t = t.emit()
        elif op == "simplePath":
            t = t.simple_path()
        elif op == "path":
            t = t.path()
        elif op == "count":
            t = t.count()
        elif op == "sum":
            t = t.sum_()
        elif op == "min":
            t = t.min_()
        elif op == "max":
            t = t.max_()
        elif op == "mean":
            t = t.mean()
        elif op == "groupCount":
            t = t.group_count(by=step[1])
        else:
            raise ValueError(f"to_dsl: unknown step {step!r}")
    return t


# --------------------------------------------------------------------------
# canonicalization + comparison
# --------------------------------------------------------------------------

def canon_dsl(x):
    """DSL output -> canonical comparable value (vertices by name,
    edges by eid)."""
    from titan_tpu.core.elements import Edge, Vertex
    if isinstance(x, Vertex):
        return ("v", x.value("name"))
    if isinstance(x, Edge):
        return ("e", x.value("eid"))
    if isinstance(x, dict):
        return tuple(sorted((k if isinstance(k, str) else canon_dsl(k),
                             canon_dsl(v)) for k, v in x.items()))
    if isinstance(x, (list, tuple)):
        return tuple(canon_dsl(i) for i in x)
    if isinstance(x, float):
        return round(x, 9)
    return x


def canon_oracle(og, x):
    if isinstance(x, tuple) and len(x) == 2 and x[0] in ("v", "e") \
            and (x[1] in og["vertices"] if x[0] == "v"
                 else x[1] in og["edges"]):
        if x[0] == "v":
            return ("v", og["vertices"][x[1]]["props"]["name"])
        return ("e", og["edges"][x[1]]["props"]["eid"])
    if isinstance(x, dict):
        return tuple(sorted(
            (k if isinstance(k, str) else canon_oracle(og, k),
             canon_oracle(og, v)) for k, v in x.items()))
    if isinstance(x, tuple):
        return tuple(canon_oracle(og, i) for i in x)
    if isinstance(x, float):
        return round(x, 9)
    return x


def run_both(g, og, spec, mode, idmap=None):
    raw = to_dsl(g.traversal(), spec).to_list()
    if mode == "groupcount" and idmap and raw \
            and isinstance(raw[0], dict):
        raw = [{idmap.get(k, k): v for k, v in raw[0].items()}]
    dsl_out = [canon_dsl(x) for x in raw]
    ora_out = [canon_oracle(og, x) for x in tp3_oracle.evaluate(og, spec)]
    if mode == "list":
        return dsl_out == ora_out, dsl_out, ora_out
    if mode == "groupcount":
        return dsl_out == ora_out or \
            (len(dsl_out) == len(ora_out) == 1
             and sorted(map(repr, dsl_out[0]))
             == sorted(map(repr, ora_out[0]))), dsl_out, ora_out
    return sorted(map(repr, dsl_out)) == sorted(map(repr, ora_out)), \
        dsl_out, ora_out


# --------------------------------------------------------------------------
# tests
# --------------------------------------------------------------------------

GRAPH_SEEDS = [1, 2, 3]
QUERIES_PER_GRAPH = 120


@pytest.mark.parametrize("gseed", GRAPH_SEEDS)
def test_random_traversals_match_oracle(gseed):
    g, og, idmap = build_pair(gseed)
    try:
        rng = random.Random(1000 * gseed)
        failures = []
        for q in range(QUERIES_PER_GRAPH):
            spec, mode = gen_spec(rng)
            ok, d, o = run_both(g, og, spec, mode, idmap)
            if not ok:
                failures.append((q, spec, d[:8], o[:8]))
        assert not failures, (
            f"{len(failures)} mismatching traversals; first: "
            f"{failures[0]}")
    finally:
        g.close()


def test_path_dedup_interplay():
    """dedup keeps the FIRST traverser per object even when later ones
    carry different paths (TP3 dedup is by current object, not path)."""
    g, og, _ = build_pair(7, n=10, m=30)
    try:
        spec = [("V",), ("out", ()), ("out", ()), ("dedup",), ("path",)]
        dsl_paths = [canon_dsl(x) for x in
                     to_dsl(g.traversal(), spec).to_list()]
        # object-level dedup: distinct endpoints == number of paths
        ends = {p[-1] for p in dsl_paths}
        assert len(ends) == len(dsl_paths)
        # endpoints agree with the oracle regardless of which path won
        ora = tp3_oracle.evaluate(og, spec)
        o_ends = {canon_oracle(og, p)[-1] for p in ora}
        assert ends == o_ends
    finally:
        g.close()


def test_until_is_do_while():
    """repeat(out).until(pred): the body runs at least once even when
    the start vertex already satisfies pred (TP3 do-while form)."""
    g = titan_tpu.open("inmemory")
    try:
        tx = g.new_transaction()
        a = tx.add_vertex("person", name="a", age=99)
        b = tx.add_vertex("person", name="b", age=99)
        a.add_edge("knows", b)
        tx.commit()
        out = g.traversal().V().has("name", P.eq("a")) \
            .repeat(anon().out()).until(anon().has("age", P.gt(50))) \
            .values("name").to_list()
        assert out == ["b"]
    finally:
        g.close()


def test_sack_path_sums_survive_bulking():
    """TP3 sack merge rules: each traverser carries its own sack, and
    the bulking barrier must NOT merge traversers whose sacks differ —
    a diamond's two paths produce two distinct weight sums."""
    g = titan_tpu.open("inmemory")
    try:
        tx = g.new_transaction()
        a = tx.add_vertex("v", name="a")
        b = tx.add_vertex("v", name="b")
        c = tx.add_vertex("v", name="c")
        d = tx.add_vertex("v", name="d")
        a.add_edge("e", b, weight=1)
        a.add_edge("e", c, weight=2)
        b.add_edge("e", d, weight=10)
        c.add_edge("e", d, weight=20)
        tx.commit()
        import operator

        from titan_tpu.traversal.dsl import anon
        out = (g.traversal().with_sack(0)
               .V().has("name", P.eq("a"))
               .repeat(anon().out_e("e").sack(operator.add)
                       .by("weight").in_v())
               .times(2).sack().to_list())
        # two paths: 1+10 and 2+20 — distinct sacks, no merge
        assert sorted(out) == [11, 22]
        # equal sacks MAY merge (both paths weight 5): counts preserved
        g2 = titan_tpu.open("inmemory")
        tx = g2.new_transaction()
        a2 = tx.add_vertex("v", name="a")
        b2 = tx.add_vertex("v", name="b")
        c2 = tx.add_vertex("v", name="c")
        d2 = tx.add_vertex("v", name="d")
        a2.add_edge("e", b2, weight=5)
        a2.add_edge("e", c2, weight=5)
        b2.add_edge("e", d2, weight=5)
        c2.add_edge("e", d2, weight=5)
        tx.commit()
        out2 = (g2.traversal().with_sack(0)
                .V().has("name", P.eq("a"))
                .repeat(anon().out_e("e").sack(operator.add)
                        .by("weight").in_v())
                .times(2).sack().to_list())
        assert sorted(out2) == [10, 10]   # one sum PER PATH, bulk or not
        g2.close()
    finally:
        g.close()


def test_sack_initial_value_reads_back():
    g = titan_tpu.open("inmemory")
    try:
        tx = g.new_transaction()
        tx.add_vertex("v", name="x")
        tx.commit()
        out = g.traversal().with_sack(7).V().sack().to_list()
        assert out == [7]
    finally:
        g.close()
