"""The split-lane economics claim, pinned (ISSUE 16 satellite).

experiments/lane_split_probe.py measured the narrow-first ladder's win
(fetch+test 0.427s -> 0.268s per 4.2M candidates at 4 lanes) on live
hardware — a number nobody can re-derive deterministically. This test
promotes the CLAIM into CI: on a hand-built hub graph whose heavy-level
frontier covers the low-id hub (the adjacency lists are id-sorted, so
the hub sits in lane 0 — exactly the scale-26 shape the SPLIT_LANES
comment describes), the narrow-first ladder's fetched bytes
(ops/pallas_frontier.ladder_fetch_counts — the same cost model the
Pallas kernel executes on-chip) must come in strictly below the flat
8-lane baseline, and the ladder's found set must equal the flat test's.
"""

import numpy as np

import titan_tpu.models.bfs_hybrid as H
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.ops.pallas_frontier import (frontier_round,
                                           ladder_fetch_counts)

N = 64
HUB_DEG = 47          # vertices 1..47 hang off hub 0
RING = range(48, 56)  # a hub-free ring: these miss every narrow lane


def _hub_snapshot():
    src = [0] * HUB_DEG + [v for v in RING]
    dst = list(range(1, HUB_DEG + 1)) + [v + 1 if v + 1 in RING
                                         else RING.start for v in RING]
    src, dst = np.asarray(src), np.asarray(dst)
    return snap_mod.from_arrays(N, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


def test_narrow_ladder_fetches_fewer_bytes_than_8_lane_baseline():
    import jax.numpy as jnp

    snap = _hub_snapshot()
    g = H.build_chunked_csr(snap)
    dstT = np.asarray(g["dstT"])
    colstart = np.asarray(g["colstart"])
    degc = np.asarray(g["degc"])

    # frontier = {0}: the hub just turned level — the heavy-level shape
    dist = np.full(N + 1, H.INF, np.int32)
    dist[0] = 0
    fbits = np.asarray(H._pack_bits(jnp.asarray(dist), 0, N))

    # bottom-up candidates: every unvisited vertex with edges
    cand = np.flatnonzero((dist[:N] >= H.INF) & (degc[:N] > 0))
    cols = colstart[cand]

    narrow_b, wide_b, base_b = ladder_fetch_counts(
        cols, fbits, dstT, lanes=2)
    # the 47 hub children decide in lane 0; only the 8 ring vertices
    # pay the wide refetch (the hub itself is visited, not a candidate)
    assert narrow_b + wide_b < base_b, (narrow_b, wide_b, base_b)
    assert wide_b == len(list(RING)) * 4 * 8

    # the ladder's found set is the flat 8-lane test's found set — the
    # kernel executes the same ladder, so cross-check it end to end
    undec = np.ones((1, cand.size), bool)
    found, _, _, _ = frontier_round(
        jnp.asarray(cols.astype(np.int32)), jnp.asarray(undec),
        jnp.asarray(np.zeros(cand.size, bool)),
        jnp.asarray(cand.astype(np.int32)),
        jnp.asarray(np.zeros(cand.size, np.int32)),
        jnp.asarray(fbits)[None, :], None, g["dstT"], lanes=2,
        fill0=N, fill1=0, interpret=True)
    par = dstT[:, cols]
    flat_hit = (((fbits[par >> 3] >> (par & 7)) & 1) > 0).any(axis=0)
    assert np.array_equal(np.asarray(found)[0], flat_hit)
    # and the hub children really are the lane-0 wins the claim rests on
    assert flat_hit[cand < HUB_DEG + 1].all()
    assert not flat_hit[np.isin(cand, list(RING))].any()
