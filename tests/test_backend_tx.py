"""BufferedMutator / backend_op / cache tests (reference semantics:
CacheTransaction buffering, BackendOperation retries, ExpirationKCVSCache)."""

import pytest

from titan_tpu.errors import PermanentBackendError, TemporaryBackendError
from titan_tpu.storage import Entry, KeySliceQuery, SliceQuery
from titan_tpu.storage.cache import ExpirationStoreCache
from titan_tpu.storage.inmemory import InMemoryStoreManager
from titan_tpu.storage.tx import BackendTransaction, BufferedMutator, backend_op


def k(i):
    return i.to_bytes(8, "big")


def c(i):
    return i.to_bytes(4, "big")


def test_backend_op_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TemporaryBackendError("try again")
        return "ok"

    assert backend_op(flaky, attempts=5, wait_ms=1) == "ok"
    assert len(calls) == 3


def test_backend_op_exhausts_attempts():
    def always():
        raise TemporaryBackendError("nope")

    with pytest.raises(TemporaryBackendError):
        backend_op(always, attempts=2, wait_ms=1)


def test_backend_op_permanent_escalates_immediately():
    calls = []

    def perm():
        calls.append(1)
        raise PermanentBackendError("fatal")

    with pytest.raises(PermanentBackendError):
        backend_op(perm, attempts=5, wait_ms=1)
    assert len(calls) == 1


def test_buffered_mutator_flush_threshold():
    m = InMemoryStoreManager()
    t = m.begin_transaction()
    mut = BufferedMutator(m, t, buffer_size=10, wait_ms=1)
    store = m.open_database("edgestore")
    for i in range(9):
        mut.mutate("edgestore", k(i), [Entry(c(0), b"v")])
    # below threshold: nothing flushed yet
    assert store.get_slice(KeySliceQuery(k(0), SliceQuery()), t) == []
    mut.mutate("edgestore", k(9), [Entry(c(0), b"v")])
    # threshold hit: auto-flush
    assert store.get_slice(KeySliceQuery(k(0), SliceQuery()), t) == [Entry(c(0), b"v")]
    assert not mut.has_pending


def test_mutation_consolidation_last_write_wins():
    m = InMemoryStoreManager()
    t = m.begin_transaction()
    mut = BufferedMutator(m, t, buffer_size=100, wait_ms=1)
    mut.mutate("edgestore", k(1), [Entry(c(1), b"old")])
    mut.mutate("edgestore", k(1), [], [c(1)])          # delete...
    mut.mutate("edgestore", k(1), [Entry(c(1), b"new")])  # ...then re-add
    mut.flush()
    store = m.open_database("edgestore")
    assert store.get_slice(KeySliceQuery(k(1), SliceQuery()), t) == \
        [Entry(c(1), b"new")]


def test_backend_transaction_end_to_end():
    m = InMemoryStoreManager()
    edge = ExpirationStoreCache(m.open_database("edgestore"))
    index = ExpirationStoreCache(m.open_database("graphindex"))
    bt = BackendTransaction(m.begin_transaction(), m, edge, index,
                            buffer_size=1000, wait_ms=1)
    bt.mutate_edges(k(1), [Entry(c(1), b"e")])
    bt.mutate_index(k(2), [Entry(c(2), b"i")])
    bt.commit()
    bt2 = BackendTransaction(m.begin_transaction(), m, edge, index, wait_ms=1)
    assert bt2.edge_store_query(KeySliceQuery(k(1), SliceQuery())) == \
        [Entry(c(1), b"e")]
    assert bt2.index_query(KeySliceQuery(k(2), SliceQuery())) == \
        [Entry(c(2), b"i")]
    multi = bt2.edge_store_multi_query([k(1), k(9)], SliceQuery())
    assert multi[k(1)] == [Entry(c(1), b"e")] and multi[k(9)] == []


def test_expiration_cache_hits_and_invalidation():
    m = InMemoryStoreManager()
    raw = m.open_database("edgestore")
    t = m.begin_transaction()
    raw.mutate(k(1), [Entry(c(1), b"v1")], [], t)
    cache = ExpirationStoreCache(raw, expire_ms=60_000, clean_wait_ms=0)
    q = KeySliceQuery(k(1), SliceQuery())
    assert cache.get_slice(q, t) == [Entry(c(1), b"v1")]
    assert cache.get_slice(q, t) == [Entry(c(1), b"v1")]
    assert cache.hits == 1 and cache.misses == 1
    # write around the cache, then invalidate: next read sees new value
    raw.mutate(k(1), [Entry(c(1), b"v2")], [], t)
    cache.invalidate(k(1))
    assert cache.get_slice(q, t) == [Entry(c(1), b"v2")]


def test_cache_invalidation_via_backend_tx_commit():
    m = InMemoryStoreManager()
    edge = ExpirationStoreCache(m.open_database("edgestore"),
                                expire_ms=60_000, clean_wait_ms=0)
    index = ExpirationStoreCache(m.open_database("graphindex"))
    bt = BackendTransaction(m.begin_transaction(), m, edge, index, wait_ms=1)
    q = KeySliceQuery(k(1), SliceQuery())
    assert bt.edge_store_query(q) == []          # caches empty result
    bt.mutate_edges(k(1), [Entry(c(1), b"v")])
    bt.commit()                                   # flush invalidates key
    bt2 = BackendTransaction(m.begin_transaction(), m, edge, index, wait_ms=1)
    assert bt2.edge_store_query(q) == [Entry(c(1), b"v")]
