"""Device-cost profiler (ISSUE 10, titan_tpu/obs/devprof).

Three contracts on the repo-shared n=192/m=900/seed-42 smoke shape:

1. **Compile-bucket regression guard**: after one warm pass, running
   every smoke workload — BFS, batched BFS K in {1, 8}, SSSP, WCC,
   the device epoch merge — under the profiler compiles EXACTLY ZERO
   new XLA shape buckets. A silent recompile regression (per-call
   retrace, weak-type flip-flop, a static argument that stopped
   hashing) fails here in CI instead of burning chip time.
2. **Bit-equality**: kernel results are identical with profiling on
   or off — the profiler never touches the device computation.
3. **Overhead**: smoke-shape BFS with profiling ON completes within
   1.15x of OFF (same guard style as the PR 6 tracing bound; reps are
   summed so the multiplicative bound dominates the noise floor).

ONE vertex count and K set across the file — each distinct (kernel,
static shape) is an XLA compile and CPU compiles dominate tier-1.
"""

import numpy as np
import pytest

from titan_tpu.models.bfs_hybrid import (build_chunked_csr,
                                         frontier_bfs_batched,
                                         frontier_bfs_hybrid)
from titan_tpu.models.frontier import frontier_sssp, frontier_wcc
from titan_tpu.obs import devprof
from titan_tpu.olap.live.compactor import EpochCompactor
from titan_tpu.olap.live.overlay import DeltaOverlay
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.utils import jitcache
from titan_tpu.utils.metrics import MetricManager

#: the repo-shared smoke shape (tests/test_serving.py's bucket)
N, M, SEED = 192, 900, 42


def _sym_snapshot(seed: int = SEED, n: int = N, m: int = M):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


@pytest.fixture(scope="module")
def snap():
    return _sym_snapshot()


@pytest.fixture
def clean_profilers():
    """Truly-OFF baseline: schedulers elsewhere in the suite install
    process-wide profilers and may not have uninstalled; park them for
    the duration so on-vs-off comparisons measure THIS test's
    profiler."""
    saved = list(devprof._PROFILERS)
    devprof._PROFILERS.clear()
    jitcache.set_profile_dispatch(None)
    yield
    devprof._PROFILERS[:] = saved
    if saved:
        jitcache.set_profile_dispatch(devprof._dispatch)


def _overlay(snap):
    """The exact mutation shape test_live_compact_device.py parametrizes
    (adds=120/removes=40/dead-add) so the eager merge ops share its
    compile buckets."""
    rng = np.random.default_rng(SEED)
    src = rng.integers(0, snap.n, 120).astype(np.int32)
    dst = rng.integers(0, snap.n, 120).astype(np.int32)
    labs = rng.integers(0, 3, 120).astype(np.int32)
    ov = DeltaOverlay(snap, min_cap=64)
    ov.append_edges(src, dst, labs)
    ov.remove_edge(int(snap.src[0]), int(snap.dst[0]), None)
    return ov


def _workloads(snap):
    """name -> thunk for every smoke workload the guard pins."""
    rng = np.random.default_rng(7)
    nz = np.flatnonzero(snap.out_degree > 0)
    s8 = [int(s) for s in rng.choice(nz, size=8, replace=True)]

    def merge():
        ov = _overlay(snap)
        build_chunked_csr(snap)
        merged, mode = EpochCompactor().compact(snap, ov)
        assert mode == "device"

    return [
        ("bfs", lambda: frontier_bfs_hybrid(snap, int(nz[0]))),
        ("bfs_batched_k1", lambda: frontier_bfs_batched(
            snap, [int(nz[0])])),
        ("bfs_batched_k8", lambda: frontier_bfs_batched(snap, s8)),
        ("sssp", lambda: frontier_sssp(snap, int(nz[0]))),
        ("wcc", lambda: frontier_wcc(snap)),
        ("epoch_merge", merge),
    ]


def test_zero_recompiles_on_warm_smoke_shapes(snap):
    """THE compile-bucket pin: one warm pass, then every workload under
    the profiler compiles exactly zero new static shape buckets — and
    every dispatch is observed (calls > 0, all cache hits)."""
    for _name, fn in _workloads(snap):
        fn()                                   # warm pass (may compile)
    mm = MetricManager()
    with devprof.DeviceCostProfiler(metrics=mm) as prof:
        for name, fn in _workloads(snap):
            before = prof.compiles()
            fn()
            assert prof.compiles() == before, (
                f"workload {name!r} recompiled on the warm smoke "
                f"shape: {prof.compile_log()[-3:]}")
    stats = prof.stats()
    assert stats["compiles"] == 0
    assert stats["calls"] > 0
    assert stats["cache_hits"] == stats["calls"]
    # per-kernel fingerprints: the interception saw the kernel library,
    # not just one entry point
    kernels = prof.kernel_stats()
    for expected in ("hybrid_head", "batched_plan",
                     "frontier_bandplan_sssp", "frontier_bandplan_wcc",
                     "ops.epoch_merge"):
        assert expected in kernels, (expected, sorted(kernels))
    # ... and landed on the labeled metric families
    assert mm.counter_value("device.exec.calls") == stats["calls"]
    assert mm.counter_value("device.compile.count") == 0
    assert mm.counter_value(
        "device.exec.calls",
        labels={"kernel": "batched_plan"}) > 0


def test_pallas_path_zero_recompiles_and_kernel_labels(snap,
                                                       monkeypatch):
    """TITAN_TPU_FRONTIER_KERNEL=pallas (ISSUE 16): the Pallas bottom-up
    wrappers register through jit_once like every XLA kernel, so they
    carry the same warm-shape contract — one warm pass, then zero new
    compile buckets — and show up under the device.exec.* {kernel}
    labels the decision plane reads."""
    import titan_tpu.models.bfs_hybrid as H

    monkeypatch.setenv("TITAN_TPU_FRONTIER_KERNEL", "pallas")
    # route the plain driver through the bottom-up chain at smoke scale
    # (tests/test_pallas_frontier.py idiom)
    monkeypatch.setattr(H, "SPLIT_LANE_MIN", 2)
    monkeypatch.setattr(H, "END_C_CAP", 0)
    monkeypatch.setattr(H, "END_P_CAP", 0)
    monkeypatch.setattr(H, "HEAD_F_CAP", 1)
    rng = np.random.default_rng(7)
    nz = np.flatnonzero(snap.out_degree > 0)
    s8 = [int(s) for s in rng.choice(nz, size=8, replace=True)]
    workloads = [lambda: frontier_bfs_hybrid(snap, int(nz[0])),
                 lambda: frontier_bfs_batched(snap, s8)]
    for fn in workloads:
        fn()                                   # warm pass (may compile)
    mm = MetricManager()
    with devprof.DeviceCostProfiler(metrics=mm) as prof:
        for fn in workloads:
            fn()
        assert prof.compiles() == 0, (
            f"pallas path recompiled warm: {prof.compile_log()[-3:]}")
    kernels = prof.kernel_stats()
    assert "pallas_bu_start" in kernels, sorted(kernels)
    assert "pallas_batched_bu" in kernels, sorted(kernels)
    for kern in ("pallas_bu_start", "pallas_batched_bu"):
        assert mm.counter_value("device.exec.calls",
                                labels={"kernel": kern}) > 0


def test_compile_miss_counts_once_per_new_bucket(snap):
    """A genuinely new static shape bucket counts exactly one compile,
    and repeating it counts a cache hit — the hit/miss split the guard
    above relies on. K=3 exists nowhere else in the suite, so the
    batched kernels are cold for it (one compile per batched kernel
    dispatched), and a second identical call compiles nothing."""
    nz = np.flatnonzero(snap.out_degree > 0)
    s3 = [int(nz[0])] * 3
    with devprof.DeviceCostProfiler(metrics=MetricManager()) as prof:
        frontier_bfs_batched(snap, s3)
        cold = prof.stats()
        frontier_bfs_batched(snap, s3)
        warm = prof.stats()
    assert cold["compiles"] >= 1
    assert warm["compiles"] == cold["compiles"], "K=3 recompiled warm"
    log = prof.compile_log()
    assert len(log) == cold["compiles"]
    assert all(e["kernel"] for e in log)


def test_results_bit_equal_with_profiling(snap, clean_profilers):
    """Profiling must never perturb the computation: batched BFS and
    SSSP produce bit-identical outputs with the profiler installed."""
    rng = np.random.default_rng(7)
    nz = np.flatnonzero(snap.out_degree > 0)
    s8 = [int(s) for s in rng.choice(nz, size=8, replace=True)]
    d_off, lv_off, c_off = frontier_bfs_batched(snap, s8)
    sp_off, _ = frontier_sssp(snap, int(nz[0]))
    with devprof.DeviceCostProfiler(metrics=MetricManager()):
        d_on, lv_on, c_on = frontier_bfs_batched(snap, s8)
        sp_on, _ = frontier_sssp(snap, int(nz[0]))
    assert (np.asarray(d_on) == np.asarray(d_off)).all()
    assert np.array_equal(np.asarray(lv_on), np.asarray(lv_off))
    assert (c_on == c_off).all()
    assert (np.asarray(sp_on) == np.asarray(sp_off)).all()


def test_profiling_overhead_within_bound(snap, clean_profilers):
    """Acceptance bound (ISSUE 10): smoke-shape BFS with profiling ON
    within 1.15x of OFF. Reps are summed so the multiplicative bound,
    not the timer floor, decides; the additive term absorbs the box's
    scheduling noise (PR 6 guard style)."""
    import time

    rng = np.random.default_rng(7)
    nz = np.flatnonzero(snap.out_degree > 0)
    s8 = [int(s) for s in rng.choice(nz, size=8, replace=True)]
    frontier_bfs_batched(snap, s8)              # warm
    reps = 6
    t0 = time.time()
    for _ in range(reps):
        frontier_bfs_batched(snap, s8)
    off_s = time.time() - t0
    with devprof.DeviceCostProfiler(metrics=MetricManager()):
        t0 = time.time()
        for _ in range(reps):
            frontier_bfs_batched(snap, s8)
        on_s = time.time() - t0
    assert on_s <= off_s * 1.15 + 0.5, (
        f"profiling overhead blew the bound: on={on_s:.3f}s "
        f"off={off_s:.3f}s")


def test_transfer_seams_count_bytes(clean_profilers):
    """H2D/D2H seams land on device.xfer.* with per-site children: a
    fresh snapshot's chunked-CSR upload (same shape — no new compiles)
    and the batched result readback."""
    fresh = _sym_snapshot(SEED)             # device cache empty
    mm = MetricManager()
    with devprof.DeviceCostProfiler(metrics=mm) as prof:
        nz = np.flatnonzero(fresh.out_degree > 0)
        frontier_bfs_batched(fresh, [int(nz[0])])
    stats = prof.stats()
    assert stats["h2d_bytes"] > 0 and stats["d2h_bytes"] > 0
    assert mm.counter_value("device.xfer.h2d_bytes",
                            labels={"site": "bfs.chunked_csr"}) > 0
    assert mm.counter_value("device.xfer.d2h_bytes",
                            labels={"site": "bfs.dist"}) > 0
    assert mm.counter_value("device.xfer.h2d_bytes") \
        == stats["h2d_bytes"]


def test_window_isolates_a_stage(snap, clean_profilers):
    """ProfileWindow deltas: activity before open() is excluded, the
    windowed workload's calls/bytes are included."""
    with devprof.DeviceCostProfiler(metrics=MetricManager()) as prof:
        nz = np.flatnonzero(snap.out_degree > 0)
        frontier_bfs_batched(snap, [int(nz[0])])    # outside
        w = prof.window()
        frontier_bfs_batched(snap, [int(nz[0])])
        delta = w.close()
    assert delta["calls"] > 0
    assert delta["calls"] < prof.stats()["calls"]
    assert delta["wall_s"] >= 0
    assert delta["compiles"] == 0                   # warm shape


def test_uninstall_restores_the_bare_path(snap, clean_profilers):
    """With no profiler installed the shim is one global load + None
    check: dispatch cleared, nothing recorded."""
    prof = devprof.DeviceCostProfiler(metrics=MetricManager())
    prof.install()
    assert prof.installed and jitcache._PROFILE_DISPATCH is not None
    prof.uninstall()
    assert not prof.installed and jitcache._PROFILE_DISPATCH is None
    before = prof.stats()["calls"]
    nz = np.flatnonzero(snap.out_degree > 0)
    frontier_bfs_batched(snap, [int(nz[0])])
    assert prof.stats()["calls"] == before


def test_two_profilers_fan_out(snap, clean_profilers):
    """Measurement happens once and fans out to every installed
    profiler (a bench window beside the scheduler's)."""
    a = devprof.DeviceCostProfiler(metrics=MetricManager()).install()
    b = devprof.DeviceCostProfiler(metrics=MetricManager()).install()
    try:
        nz = np.flatnonzero(snap.out_degree > 0)
        frontier_bfs_batched(snap, [int(nz[0])])
    finally:
        a.uninstall()
        b.uninstall()
    assert a.stats()["calls"] == b.stats()["calls"] > 0
