"""HTTP server surface (Gremlin Server analog).

Modeled on the reference's server deployment contract
(titan-dist gremlin-server.yaml + pkgtest suites that drive the served
graph end to end).
"""

import json
import urllib.request

import pytest

import titan_tpu
from titan_tpu import example
from titan_tpu.server import GraphServer, from_yaml, jsonify


@pytest.fixture
def server():
    g = titan_tpu.open("inmemory")
    example.load(g)
    s = GraphServer(g, port=0).start()
    yield s
    s.stop()
    g.close()


def _get(s, path):
    with urllib.request.urlopen(
            f"http://{s.host}:{s.port}{path}", timeout=10) as r:
        return r.status, json.loads(r.read())


def _post(s, path, payload):
    req = urllib.request.Request(
        f"http://{s.host}:{s.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_status(server):
    code, body = _get(server, "/status")
    assert code == 200
    assert body["backend"] == "inmemory"
    assert body["computer"] in ("tpu", "host")
    assert body["instance"]


def test_schema_listing(server):
    code, body = _get(server, "/schema")
    assert code == 200
    names = {t["name"] for t in body["types"]}
    assert {"name", "age", "father", "battled"} <= names


def test_traversal_count(server):
    code, body = _post(server, "/traversal",
                       {"gremlin": "g.V().count().next()"})
    assert code == 200
    assert body["result"] == 12


def test_traversal_vertices_envelope(server):
    code, body = _post(server, "/traversal", {
        "gremlin": "g.V().has('name','hercules').out('father')"})
    assert code == 200
    [v] = body["result"]
    assert v["@type"] == "vertex" and v["label"] == "god"


def test_traversal_write_and_commit(server):
    code, body = _post(server, "/traversal", {
        "gremlin": "graph.add_vertex('person', name='newbie').id"})
    assert code == 200
    vid = body["result"]
    code, body = _post(server, "/traversal", {
        "gremlin": f"g.V({vid}).values('name')"})
    assert body["result"] == ["newbie"]


def test_bad_requests(server):
    code, body = _post(server, "/traversal", {"nope": 1})
    assert code == 400
    code, body = _post(server, "/traversal", {"gremlin": "g.V().bogus()"})
    assert code == 500 and "error" in body
    code, body = _get(server, "/status")   # server still alive after error
    assert code == 200


def test_jsonify_depth_guard():
    deep = {"a": {"b": {"c": {"d": {"e": {"f": 1}}}}}}
    out = jsonify(deep)
    assert isinstance(out, dict)   # truncates via str() at depth, no crash


def test_from_yaml(tmp_path):
    conf = tmp_path / "server.yaml"
    conf.write_text(
        "host: 127.0.0.1\nport: 0\ngraph:\n  storage.backend: inmemory\n")
    s = from_yaml(str(conf)).start()
    try:
        code, body = _get(s, "/status")
        assert code == 200 and body["backend"] == "inmemory"
    finally:
        s.stop()
        s.graph.close()
