"""HTTP server surface (Gremlin Server analog).

Modeled on the reference's server deployment contract
(titan-dist gremlin-server.yaml + pkgtest suites that drive the served
graph end to end).
"""

import json
import urllib.request

import pytest

import titan_tpu
from titan_tpu import example
from titan_tpu.server import GraphServer, from_yaml, jsonify


@pytest.fixture
def server():
    g = titan_tpu.open("inmemory")
    example.load(g)
    s = GraphServer(g, port=0).start()
    yield s
    s.stop()
    g.close()


def _get(s, path):
    with urllib.request.urlopen(
            f"http://{s.host}:{s.port}{path}", timeout=10) as r:
        return r.status, json.loads(r.read())


def _post(s, path, payload, token=None):
    req = urllib.request.Request(
        f"http://{s.host}:{s.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 **({"Authorization": f"Bearer {token}"} if token
                    else {})},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_status(server):
    code, body = _get(server, "/status")
    assert code == 200
    assert body["backend"] == "inmemory"
    assert body["computer"] in ("tpu", "host")
    assert body["instance"]


def test_schema_listing(server):
    code, body = _get(server, "/schema")
    assert code == 200
    names = {t["name"] for t in body["types"]}
    assert {"name", "age", "father", "battled"} <= names


def test_traversal_count(server):
    code, body = _post(server, "/traversal",
                       {"gremlin": "g.V().count().next()"})
    assert code == 200
    assert body["result"] == 12


def test_traversal_vertices_envelope(server):
    code, body = _post(server, "/traversal", {
        "gremlin": "g.V().has('name','hercules').out('father')"})
    assert code == 200
    [v] = body["result"]
    assert v["@type"] == "vertex" and v["label"] == "god"


def test_traversal_write_and_commit(server):
    code, body = _post(server, "/traversal", {
        "gremlin": "graph.add_vertex('person', name='newbie').id"})
    assert code == 200
    vid = body["result"]
    code, body = _post(server, "/traversal", {
        "gremlin": f"g.V({vid}).values('name')"})
    assert body["result"] == ["newbie"]


def test_bad_requests(server):
    code, body = _post(server, "/traversal", {"nope": 1})
    assert code == 400
    code, body = _post(server, "/traversal", {"gremlin": "g.V().bogus()"})
    # caller-fault taxonomy: unknown step = AttributeError -> 400
    assert code == 400 and "error" in body and body["retryable"] is False
    code, body = _get(server, "/status")   # server still alive after error
    assert code == 200


def test_jsonify_depth_guard():
    deep = {"a": {"b": {"c": {"d": {"e": {"f": 1}}}}}}
    out = jsonify(deep)
    assert isinstance(out, dict)   # truncates via str() at depth, no crash


def test_from_yaml(tmp_path):
    conf = tmp_path / "server.yaml"
    conf.write_text(
        "host: 127.0.0.1\nport: 0\ngraph:\n  storage.backend: inmemory\n")
    s = from_yaml(str(conf)).start()
    try:
        code, body = _get(s, "/status")
        assert code == 200 and body["backend"] == "inmemory"
    finally:
        s.stop()
        s.graph.close()


class _Addr:
    def __init__(self, port):
        self.host, self.port = "127.0.0.1", port


def _post_script(port, script, token=None, path="/traversal"):
    # thin wrapper over the module's _post helper (one wire-contract impl)
    return _post(_Addr(port), path, {"gremlin": script}, token=token)


def test_concurrent_mutating_sessions():
    """VERDICT item 10: N threads mutate through the wire concurrently;
    every write lands exactly once (per-thread bound txs commit per
    request, Gremlin Server semantics)."""
    import threading

    import titan_tpu
    from titan_tpu.server import GraphServer
    g = titan_tpu.open("inmemory")
    srv = GraphServer(g, port=0).start()
    try:
        errors = []

        def writer(i):
            for j in range(5):
                code, body = _post_script(
                    srv.port,
                    f"graph.tx().add_vertex('person', name='w{i}_{j}')")
                if code != 200:
                    errors.append(body)
        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        code, body = _post_script(srv.port, "g.V().has_label('person').count()")
        assert code == 200 and body["result"] == [30]
    finally:
        srv.stop()
        g.close()


def test_wire_error_taxonomy():
    import titan_tpu
    from titan_tpu.server import GraphServer
    g = titan_tpu.open("inmemory")
    srv = GraphServer(g, port=0).start()
    try:
        # caller fault -> 400, retryable False
        code, body = _post_script(srv.port, "this is not ( python")
        assert code == 400 and body["retryable"] is False
        assert body["type"] == "SyntaxError"
        code, body = _post_script(srv.port, "nonexistent_binding.foo()")
        assert code == 400 and body["type"] == "NameError"
        # schema violation over the wire -> 400
        code, body = _post_script(
            srv.port,
            "graph.management().make_property_key('x', object)")
        assert code == 400 and body["retryable"] is False
        # unknown path -> 404 envelope
        code, body = _post_script(srv.port, "1", path="/nope")
        assert code == 404 and body["type"] == "NotFound"
    finally:
        srv.stop()
        g.close()


def test_bearer_token_auth():
    import titan_tpu
    from titan_tpu.server import GraphServer
    g = titan_tpu.open("inmemory")
    srv = GraphServer(g, port=0, auth_token="s3cret").start()
    try:
        code, body = _post_script(srv.port, "g.V().count()")
        assert code == 401 and body["type"] == "Unauthorized"
        code, body = _post_script(srv.port, "g.V().count()", token="wrong")
        assert code == 401
        code, body = _post_script(srv.port, "g.V().count()", token="s3cret")
        assert code == 200 and body["result"] == [0]
    finally:
        srv.stop()
        g.close()


def test_script_endpoint_anonymous_traversals(server):
    """Scripts can use the __ / anon helper for sub-traversal bodies
    (union, repeat, match ...), like the Gremlin console."""
    status, out = _post(server, "/traversal", {
        "gremlin": "g.V().has('name', 'hercules')"
                   ".union(__.out('father'), __.out('mother'))"
                   ".values('name')"})
    assert status == 200
    assert sorted(out["result"]) == ["alcmene", "jupiter"]
