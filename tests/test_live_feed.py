"""ChangeFeed: durable trigger-log tail → columnar deltas (olap/live).

Cross-instance coverage follows tests/test_multi_instance.py: two graph
handles over one shared sqlite directory behave like two cluster nodes —
all coordination flows through the store, so the feed on instance A sees
instance B's tagged commits through the durable ``ulog_*`` log (the
TitanBus contract), resumable via its named read marker.
"""

import time

import numpy as np
import pytest

import titan_tpu
from titan_tpu.core.changes import ChangeState
from titan_tpu.olap.live.feed import ChangeFeed, DeltaBatch
from titan_tpu.olap.tpu import snapshot as snap_mod


@pytest.fixture
def shared_dir(tmp_path):
    return str(tmp_path / "db")


def _open(shared_dir, instance):
    return titan_tpu.open({"storage.backend": "sqlite",
                           "storage.directory": shared_dir,
                           "graph.unique-instance-id": instance})


def _wait_for(pred, timeout=8.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def test_delta_batch_columnar_roundtrip():
    payload = {"txid": 9, "time": 123,
               "added_vertices": [5], "removed_vertices": [6],
               "added": [{"type": "knows", "out": 1, "in": 2},
                         {"type": "name", "out": 1, "value": "x"}],
               "removed": [{"type": "knows", "out": 3, "in": 4}]}
    b = DeltaBatch.from_state(1, ChangeState(payload, sender=b"w1"))
    assert b.seq == 1 and b.txid == 9 and b.sender == b"w1"
    assert b.add_out.tolist() == [1] and b.add_in.tolist() == [2]
    assert b.add_type == ["knows"]
    assert b.del_out.tolist() == [3] and b.del_in.tolist() == [4]
    assert b.vtx_add.tolist() == [5] and b.vtx_del.tolist() == [6]
    assert b.prop_keys == {"name"}
    back = b.to_payload()
    assert back["added"][0] == {"type": "knows", "out": 1, "in": 2}
    assert back["removed"] == [{"type": "knows", "out": 3, "in": 4}]
    assert back["added_vertices"] == [5]
    # property mutations survive as no-"in" relations (the
    # apply_changes column-invalidation shape)
    assert any("in" not in r and r["type"] == "name"
               for r in back["added"])


def test_cross_instance_feed_and_drain_into_snapshot(shared_dir):
    """The unification seam end-to-end: instance B's tagged commits
    reach a snapshot built on instance A through the durable log +
    apply_changes — bit-identical CSR to a full rebuild."""
    g1 = _open(shared_dir, "a")
    g2 = _open(shared_dir, "b")
    try:
        tx = g1.new_transaction()
        vs = [tx.add_vertex("node", name=f"v{i}") for i in range(6)]
        for a, b in [(0, 1), (1, 2), (2, 3)]:
            vs[a].add_edge("link", vs[b])
        tx.commit()
        ids = sorted(v.id for v in g1.new_transaction().vertices())

        snap = snap_mod.build(g1)
        feed = ChangeFeed(g1, "live", read_interval_ms=20)
        # remote writer commits through the SHARED store, tagged
        tx2 = g2.new_transaction(log_identifier="live")
        tx2.vertex(ids[3]).add_edge("link", tx2.vertex(ids[4]))
        tx2.commit()
        tx3 = g2.new_transaction(log_identifier="live")
        e = next(iter(tx3.vertex(ids[0]).out_edges("link")))
        e.remove()
        tx3.commit()

        assert _wait_for(lambda: feed.pending() >= 2), feed.pending()
        stats = feed.drain_into(snap, g1.schema, g1.idm)
        assert stats["batches"] == 2
        assert stats["added_edges"] == 1 and stats["removed_edges"] == 1
        fresh = snap_mod.build(g1)
        assert (snap.vertex_ids == fresh.vertex_ids).all()
        assert (snap.src == fresh.src).all()
        assert (snap.dst == fresh.dst).all()
        assert (snap.indptr_in == fresh.indptr_in).all()
        feed.close()
    finally:
        g1.close()
        g2.close()


def test_feed_skips_own_instance_messages(shared_dir):
    """Local tagged commits arrive through the in-process listener —
    the feed must drop its own rid's log messages or the plane would
    double-apply them."""
    g1 = _open(shared_dir, "a")
    g2 = _open(shared_dir, "b")
    try:
        tx = g1.new_transaction()
        v1 = tx.add_vertex("node", name="x")
        v2 = tx.add_vertex("node", name="y")
        tx.commit()
        feed = ChangeFeed(g1, "own", read_interval_ms=20)
        # g1's OWN tagged commit: logged, but filtered by sender
        tx1 = g1.new_transaction(log_identifier="own")
        tx1.vertex(v1.id).add_edge("link", tx1.vertex(v2.id))
        tx1.commit()
        # g2's commit: kept
        tx2 = g2.new_transaction(log_identifier="own")
        tx2.vertex(v2.id).add_edge("link", tx2.vertex(v1.id))
        tx2.commit()
        assert _wait_for(lambda: feed.pending() >= 1)
        time.sleep(0.2)
        batches = feed.poll()
        assert len(batches) == 1
        assert batches[0].sender == b"b"
        feed.close()
    finally:
        g1.close()
        g2.close()


def test_feed_resumes_from_named_marker(shared_dir):
    """A restarted feed with the same reader_id continues from its
    durable cursor — no replay of already-consumed batches."""
    g1 = _open(shared_dir, "a")
    g2 = _open(shared_dir, "b")
    try:
        tx = g1.new_transaction()
        va = tx.add_vertex("node", name="a")
        vb = tx.add_vertex("node", name="b")
        tx.commit()

        feed1 = ChangeFeed(g1, "mk", reader_id="r1",
                           read_interval_ms=20)
        tx2 = g2.new_transaction(log_identifier="mk")
        tx2.vertex(va.id).add_edge("link", tx2.vertex(vb.id))
        tx2.commit()
        assert _wait_for(lambda: feed1.pending() >= 1)
        got1 = feed1.poll()
        assert len(got1) == 1
        # let the reader thread persist the cursor, then "restart"
        time.sleep(0.3)
        feed1.close()

        feed2 = ChangeFeed(g1, "mk", reader_id="r1",
                           read_interval_ms=20)
        tx3 = g2.new_transaction(log_identifier="mk")
        tx3.vertex(vb.id).add_edge("link", tx3.vertex(va.id))
        tx3.commit()
        assert _wait_for(lambda: feed2.pending() >= 1)
        time.sleep(0.2)
        got2 = feed2.poll()
        # only the NEW commit — the marker (plus the dedup watermark)
        # keeps the consumed one from replaying
        assert len(got2) == 1
        assert got2[0].txid != got1[0].txid \
            or got2[0].timestamp != got1[0].timestamp
        feed2.close()
    finally:
        g1.close()
        g2.close()


def test_feed_backpressure_blocks_ingest(shared_dir):
    """Past the high watermark the log reader blocks (durable cursor
    stops advancing — nothing is lost) and the backpressure counter
    ticks; a poll() drains and resumes ingest."""
    from titan_tpu.utils.metrics import MetricManager

    g1 = _open(shared_dir, "a")
    g2 = _open(shared_dir, "b")
    try:
        tx = g1.new_transaction()
        va = tx.add_vertex("node", name="a")
        vb = tx.add_vertex("node", name="b")
        tx.commit()
        metrics = MetricManager()
        feed = ChangeFeed(g1, "bp", read_interval_ms=20,
                          high_watermark=2, low_watermark=1,
                          metrics=metrics)
        for _ in range(4):
            txw = g2.new_transaction(log_identifier="bp")
            txw.vertex(va.id).add_edge("link", txw.vertex(vb.id))
            txw.commit()
        assert _wait_for(
            lambda: metrics.counter_value(
                "serving.live.backpressure") >= 1)
        assert feed.pending() <= 3     # high + the one that blocked
        # draining releases the reader; everything arrives eventually
        seen = [0]

        def drained():
            seen[0] += len(feed.poll())
            return seen[0] >= 4

        assert _wait_for(drained), seen
        feed.close()
    finally:
        g1.close()
        g2.close()
