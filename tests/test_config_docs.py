"""The generated config reference must match the option tree (docs can't
drift from the single source of truth)."""

import os


def test_config_reference_in_sync():
    from titan_tpu.config.docgen import render
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "config-reference.md")
    with open(path) as f:
        on_disk = f.read()
    assert on_disk == render(), (
        "docs/config-reference.md is stale — regenerate with "
        "python -m titan_tpu.config.docgen > docs/config-reference.md")


def test_reference_covers_all_namespaces():
    from titan_tpu.config.docgen import render
    md = render()
    for ns in ("storage.cluster", "storage.lock", "ids", "graph"):
        assert f"`{ns}`" in md or f"`{ns}." in md
