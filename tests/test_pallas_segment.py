"""Pallas segmented-scan kernel, run in interpreter mode on CPU against the
XLA reference implementation (ops/segment.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from titan_tpu.ops.pallas_segment import (pallas_seg_scan,
                                          pallas_sorted_segment_combine)
from titan_tpu.ops.segment import (seg_scan, segment_metadata,
                                   sorted_segment_combine)


def _random_segments(e=1000, n=37, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    seg = np.sort(rng.integers(0, n, e)).astype(np.int32)
    if np.issubdtype(dtype, np.integer):
        vals = rng.integers(0, 100, e).astype(dtype)
    else:
        vals = rng.uniform(-5, 5, e).astype(dtype)
    indptr = np.zeros(n + 1, np.int64)
    np.add.at(indptr[1:], seg, 1)
    indptr = np.cumsum(indptr)
    return vals, seg, indptr, n


@pytest.mark.parametrize("combine", ["sum", "min", "max"])
@pytest.mark.parametrize("block", [128, 256])
def test_scan_matches_reference(combine, block):
    vals, seg, _, _ = _random_segments(e=700)
    flags = np.concatenate([[True], seg[1:] != seg[:-1]])
    ref = np.asarray(seg_scan(jnp.asarray(vals), jnp.asarray(flags), combine))
    got = np.asarray(pallas_seg_scan(jnp.asarray(vals), jnp.asarray(flags),
                                     combine, block=block, interpret=True))
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_scan_carry_across_many_blocks():
    # one giant segment spanning every block: pure carry chain
    e = 1024
    vals = np.ones(e, np.float32)
    flags = np.zeros(e, bool)
    flags[0] = True
    got = np.asarray(pallas_seg_scan(jnp.asarray(vals), jnp.asarray(flags),
                                     "sum", block=128, interpret=True))
    np.testing.assert_allclose(got, np.arange(1, e + 1, dtype=np.float32))


@pytest.mark.parametrize("combine", ["sum", "min"])
def test_segment_combine_matches_reference(combine):
    vals, seg, indptr, n = _random_segments(e=900, n=53, seed=3)
    last_idx, seg_has = segment_metadata(indptr)
    ref = np.asarray(sorted_segment_combine(
        jnp.asarray(vals), jnp.asarray(seg), jnp.asarray(last_idx),
        jnp.asarray(seg_has), combine))
    got = np.asarray(pallas_sorted_segment_combine(
        jnp.asarray(vals), jnp.asarray(seg), jnp.asarray(last_idx),
        jnp.asarray(seg_has), combine, block=256, interpret=True))
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_int32_min_identity():
    vals = np.array([5, 3, 9, 2], np.int32)
    flags = np.array([True, False, True, False])
    got = np.asarray(pallas_seg_scan(jnp.asarray(vals), jnp.asarray(flags),
                                     "min", block=128, interpret=True))
    np.testing.assert_array_equal(got, [5, 3, 9, 2])
