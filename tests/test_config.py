"""Config-system tests (semantics modeled on the reference's
GraphDatabaseConfigurationTest / ConfigOption behaviors)."""

import pytest

from titan_tpu.config import (Configuration, MapConfiguration, MergedConfiguration,
                              ModifiableConfiguration, Mutability, Restriction)
from titan_tpu.config.options import ConfigNamespace, ConfigOption, positive
from titan_tpu.config import defaults


def make_tree():
    root = ConfigNamespace(None, "root")
    ns = ConfigNamespace(root, "storage")
    opt_str = ConfigOption(ns, "backend", "", str, None, Mutability.LOCAL)
    opt_int = ConfigOption(ns, "buffer-size", "", int, 1024, Mutability.MASKABLE, positive)
    opt_bool = ConfigOption(ns, "read-only", "", bool, False, Mutability.LOCAL)
    fixed = ConfigOption(ns, "cluster-init", "", int, 8, Mutability.FIXED)
    umb = ConfigNamespace(root, "index", umbrella=True)
    umb_opt = ConfigOption(umb, "backend", "", str, "memindex", Mutability.GLOBAL_OFFLINE)
    return root, opt_str, opt_int, opt_bool, fixed, umb, umb_opt


def test_paths_and_umbrella():
    root, opt_str, *_, umb, umb_opt = make_tree()
    assert opt_str.path() == "storage.backend"
    assert umb_opt.path("search") == "index.search.backend"
    with pytest.raises(ValueError):
        umb_opt.path()  # missing umbrella element
    with pytest.raises(ValueError):
        opt_str.path("extra")


def test_typed_get_coercion_and_defaults():
    root, opt_str, opt_int, opt_bool, *_ = make_tree()
    raw = MapConfiguration({"storage.backend": "inmemory",
                            "storage.buffer-size": "2048",
                            "storage.read-only": "true"})
    cfg = Configuration(root, raw)
    assert cfg.get(opt_str) == "inmemory"
    assert cfg.get(opt_int) == 2048  # string coerced
    assert cfg.get(opt_bool) is True
    empty = Configuration(root, MapConfiguration())
    assert empty.get(opt_int) == 1024  # default
    assert empty.get(opt_str) is None


def test_verification():
    root, _, opt_int, *_ = make_tree()
    cfg = Configuration(root, MapConfiguration({"storage.buffer-size": "-1"}))
    with pytest.raises(ValueError):
        cfg.get(opt_int)


def test_mutability_enforcement_on_set():
    root, opt_str, opt_int, opt_bool, fixed, umb, umb_opt = make_tree()
    raw = MapConfiguration()
    mod = ModifiableConfiguration(root, raw, Restriction.GLOBAL)
    with pytest.raises(ValueError):
        mod.set(opt_str, "x")  # LOCAL option not settable in GLOBAL view
    with pytest.raises(ValueError):
        mod.set(fixed, 4)  # FIXED refuses online change
    mod.set(fixed, 4, force=True)  # cluster initialization path
    assert mod.get(fixed) == 4
    with pytest.raises(ValueError):
        mod.set(umb_opt, "es", "search")  # GLOBAL_OFFLINE online
    mod.set(umb_opt, "es", "search", force=True)
    assert mod.get(umb_opt, "search") == "es"


def test_merged_masking_semantics():
    root, opt_str, opt_int, opt_bool, fixed, umb, umb_opt = make_tree()
    local = Configuration(root, MapConfiguration({
        "storage.backend": "inmemory",      # LOCAL: local wins
        "storage.buffer-size": 10,          # MASKABLE: local masks global
        "storage.cluster-init": 99,         # FIXED: global must win
    }))
    glob = Configuration(root, MapConfiguration({
        "storage.buffer-size": 20,
        "storage.cluster-init": 8,
    }))
    merged = MergedConfiguration(local, glob)
    assert merged.get(opt_str) == "inmemory"
    assert merged.get(opt_int) == 10
    assert merged.get(fixed) == 8  # FIXED comes from global store


def test_umbrella_container_discovery():
    root, *_, umb, umb_opt = make_tree()
    cfg = Configuration(root, MapConfiguration({
        "index.search.backend": "memindex",
        "index.geo.backend": "memindex",
    }))
    assert cfg.container_names(umb) == ["geo", "search"]


def test_resolve_option_roundtrip():
    root, opt_str, *_, umb, umb_opt = make_tree()
    cfg = Configuration(root, MapConfiguration())
    opt, fills = cfg.resolve_option("storage.backend")
    assert opt is opt_str and fills == []
    opt, fills = cfg.resolve_option("index.search.backend")
    assert opt is umb_opt and fills == ["search"]
    with pytest.raises(KeyError):
        cfg.resolve_option("storage.nope")
    with pytest.raises(KeyError):
        cfg.resolve_option("storage")


def test_default_tree_is_wellformed():
    # every declared default passes its own verifier; spot-check paths
    assert defaults.STORAGE_BACKEND.path() == "storage.backend"
    assert defaults.INDEX_BACKEND.path("search") == "index.search.backend"
    assert defaults.MAX_PARTITIONS.validate(64) == 64
    with pytest.raises(ValueError):
        defaults.MAX_PARTITIONS.validate(48)  # not a power of two


def test_tuning_options_wire_through():
    """The r4 tuning options actually govern their subsystems (not just
    docgen entries): query.traversal-batch bounds the multiQuery width,
    query.barrier-size bounds the bulking barrier."""
    import titan_tpu
    g = titan_tpu.open({"storage.backend": "inmemory",
                        "query.traversal-batch": 3,
                        "query.barrier-size": 7})
    try:
        tx = g.new_transaction()
        vs = [tx.add_vertex("n") for _ in range(10)]
        for i in range(9):
            vs[i].add_edge("link", vs[i + 1])
        tx.commit()
        calls = []
        tx_cls = type(g.new_transaction())
        real = tx_cls.multi_vertex_edges

        def counting(self, vids, *a, **kw):
            calls.append(len(vids))
            return real(self, vids, *a, **kw)

        tx_cls.multi_vertex_edges = counting
        try:
            n = g.traversal().V().out("link").count().next()
        finally:
            tx_cls.multi_vertex_edges = real
        assert n == 9
        assert calls and max(calls) <= 3      # traversal-batch honored
    finally:
        g.close()


def test_scan_options_wire_through(tmp_path):
    import titan_tpu
    from titan_tpu.storage.scan import StandardScanner
    g = titan_tpu.open({"storage.backend": "inmemory",
                        "storage.scan.threads": 2,
                        "storage.scan.queue-size": 16,
                        "storage.scan.block-size": 5})
    try:
        from titan_tpu.config import defaults as d
        assert g.config.get(d.SCAN_THREADS) == 2
        tx = g.new_transaction()
        for i in range(6):
            tx.add_vertex("n", name=f"x{i}")
        tx.commit()
        # ghost-removal job runs a scan through the configured knobs
        from titan_tpu.olap.jobs import GhostVertexRemover
        metrics = StandardScanner(
            g.backend.edge_store.store, g.backend.manager).execute(
            GhostVertexRemover(g), graph=g)
        assert metrics is not None
    finally:
        g.close()


def test_change_backlog_config_sizes_listener_queue():
    import titan_tpu

    g = titan_tpu.open({"storage.backend": "inmemory",
                        "computer.tpu.change-backlog": 3})
    try:
        token, q = g.subscribe_changes()
        assert q.cap == 3
        for i in range(4):        # cap + 1: the 4th push overflows
            q.push({"epoch": i})
        assert q.overflowed and len(q) == 0
        g.unsubscribe_changes(token)
    finally:
        g.close()


def test_change_backlog_default_single_source():
    """The ConfigOption default and core.changes.CHANGE_QUEUE_CAP must
    not drift (config stays a leaf module, so it cannot import the
    constant directly)."""
    from titan_tpu.config import defaults as d
    from titan_tpu.core.changes import CHANGE_QUEUE_CAP
    assert d.TPU_CHANGE_BACKLOG.default == CHANGE_QUEUE_CAP
