"""Backend-parameterized KCVS contract suite.

Modeled on the reference's shared SPI suites (titan-test
KeyColumnValueStoreTest / MultiWriteKeyColumnValueStoreTest): the same
assertions run against every registered backend, which is how new adapters
prove conformance.
"""

import random

import pytest

from titan_tpu.storage import (Entry, KCVMutation, KeyRangeQuery, KeySliceQuery,
                               SliceQuery)
from titan_tpu.storage.inmemory import InMemoryStoreManager
from titan_tpu.storage.sqlitekv import SqliteStoreManager


@pytest.fixture(params=["inmemory", "sqlite-mem", "sqlite-file", "gdbm"])
def manager(request, tmp_path):
    if request.param == "inmemory":
        m = InMemoryStoreManager()
    elif request.param == "sqlite-mem":
        m = SqliteStoreManager(None)
    elif request.param == "gdbm":
        # third-party engine (GNU dbm): proves the SPI portability claim
        # against a store this project did not write (VERDICT r3 #3)
        pytest.importorskip("dbm.gnu")
        from titan_tpu.storage.gdbmkv import GdbmStoreManager
        m = GdbmStoreManager(str(tmp_path / "gdbm"))
    else:
        m = SqliteStoreManager(str(tmp_path / "db"))
    yield m
    m.close()


def k(i: int) -> bytes:
    return i.to_bytes(8, "big")


def c(i: int) -> bytes:
    return i.to_bytes(4, "big")


def tx(manager):
    return manager.begin_transaction()


def test_roundtrip_and_slice_semantics(manager):
    store = manager.open_database("edgestore")
    t = tx(manager)
    store.mutate(k(1), [Entry(c(j), b"v%d" % j) for j in range(10)], [], t)
    t.commit()
    t = tx(manager)
    # full row
    full = store.get_slice(KeySliceQuery(k(1), SliceQuery()), t)
    assert [e.column for e in full] == [c(j) for j in range(10)]
    # interval [3, 7)
    part = store.get_slice(KeySliceQuery(k(1), SliceQuery(c(3), c(7))), t)
    assert [e.column for e in part] == [c(3), c(4), c(5), c(6)]
    # limit
    lim = store.get_slice(KeySliceQuery(k(1), SliceQuery(c(3), c(7), limit=2)), t)
    assert [e.column for e in lim] == [c(3), c(4)]
    # start inclusive, end exclusive
    edge = store.get_slice(KeySliceQuery(k(1), SliceQuery(c(9), None)), t)
    assert [e.column for e in edge] == [c(9)]
    # missing key
    assert store.get_slice(KeySliceQuery(k(99), SliceQuery()), t) == []
    t.commit()


def test_overwrite_and_delete(manager):
    store = manager.open_database("edgestore")
    t = tx(manager)
    store.mutate(k(5), [Entry(c(1), b"a"), Entry(c(2), b"b")], [], t)
    t.commit()
    t = tx(manager)
    store.mutate(k(5), [Entry(c(1), b"a2")], [c(2)], t)
    t.commit()
    t = tx(manager)
    got = store.get_slice(KeySliceQuery(k(5), SliceQuery()), t)
    assert got == [Entry(c(1), b"a2")]
    t.commit()


def test_multi_key_slice(manager):
    store = manager.open_database("edgestore")
    t = tx(manager)
    for i in range(20):
        store.mutate(k(i), [Entry(c(j), b"x") for j in range(5)], [], t)
    t.commit()
    t = tx(manager)
    keys = [k(i) for i in (3, 7, 11, 99)]
    result = store.get_slice_multi(keys, SliceQuery(c(1), c(4)), t)
    assert set(result.keys()) == set(keys)
    assert [e.column for e in result[k(3)]] == [c(1), c(2), c(3)]
    assert result[k(99)] == []
    t.commit()


def test_ordered_key_scan(manager):
    store = manager.open_database("edgestore")
    t = tx(manager)
    ids = random.Random(1).sample(range(1000), 50)
    for i in ids:
        store.mutate(k(i), [Entry(c(0), b"v")], [], t)
    t.commit()
    t = tx(manager)
    seen = [key for key, _ in store.get_keys(
        KeyRangeQuery(k(0), k(1000), SliceQuery()), t)]
    assert seen == sorted(k(i) for i in ids)
    # sub-range
    lo, hi = k(200), k(700)
    sub = [key for key, _ in store.get_keys(KeyRangeQuery(lo, hi, SliceQuery()), t)]
    assert sub == [key for key in seen if lo <= key < hi]
    t.commit()


def test_unordered_scan_sees_all(manager):
    store = manager.open_database("edgestore")
    t = tx(manager)
    for i in range(30):
        store.mutate(k(i), [Entry(c(i % 3), b"v")], [], t)
    t.commit()
    t = tx(manager)
    rows = dict(store.get_keys(SliceQuery(), t))
    assert len(rows) == 30
    # slice filter applies during scan: only columns in [c(1), c(3))
    rows = dict(store.get_keys(SliceQuery(c(1), c(3)), t))
    assert len(rows) == 20  # keys with i%3 in (1,2)
    t.commit()


def test_mutate_many_batch(manager):
    muts = {
        "edgestore": {k(1): KCVMutation([Entry(c(1), b"a")], []),
                      k(2): KCVMutation([Entry(c(2), b"b")], [])},
        "graphindex": {k(3): KCVMutation([Entry(c(3), b"c")], [])},
    }
    t = tx(manager)
    manager.mutate_many(muts, t)
    t.commit()
    t = tx(manager)
    assert manager.open_database("edgestore").get_slice(
        KeySliceQuery(k(1), SliceQuery()), t) == [Entry(c(1), b"a")]
    assert manager.open_database("graphindex").get_slice(
        KeySliceQuery(k(3), SliceQuery()), t) == [Entry(c(3), b"c")]
    t.commit()


def test_row_deletion_removes_key_from_scan(manager):
    store = manager.open_database("edgestore")
    t = tx(manager)
    store.mutate(k(1), [Entry(c(1), b"a")], [], t)
    store.mutate(k(2), [Entry(c(1), b"a")], [], t)
    t.commit()
    t = tx(manager)
    store.mutate(k(1), [], [c(1)], t)
    t.commit()
    t = tx(manager)
    keys = [key for key, _ in store.get_keys(
        KeyRangeQuery(k(0), k(100), SliceQuery()), t)]
    assert keys == [k(2)]
    t.commit()


def test_clear_storage(manager):
    store = manager.open_database("edgestore")
    t = tx(manager)
    store.mutate(k(1), [Entry(c(1), b"a")], [], t)
    t.commit()
    assert manager.exists()
    manager.clear_storage()
    store = manager.open_database("edgestore")
    t = tx(manager)
    assert store.get_slice(KeySliceQuery(k(1), SliceQuery()), t) == []
    t.commit()


def test_features_declared(manager):
    f = manager.features
    assert f.ordered_scan and f.unordered_scan and f.key_ordered


class TestSqliteTransactionality:
    def test_rollback_discards(self, tmp_path):
        m = SqliteStoreManager(str(tmp_path / "db"))
        store = m.open_database("edgestore")
        t = m.begin_transaction()
        store.mutate(k(1), [Entry(c(1), b"a")], [], t)
        t.rollback()
        t2 = m.begin_transaction()
        assert store.get_slice(KeySliceQuery(k(1), SliceQuery()), t2) == []
        t2.commit()
        m.close()

    def test_persistence_across_reopen(self, tmp_path):
        path = str(tmp_path / "db")
        m = SqliteStoreManager(path)
        store = m.open_database("edgestore")
        t = m.begin_transaction()
        store.mutate(k(1), [Entry(c(1), b"persisted")], [], t)
        t.commit()
        m.close()
        m2 = SqliteStoreManager(path)
        assert m2.exists()
        store2 = m2.open_database("edgestore")
        t = m2.begin_transaction()
        assert store2.get_slice(KeySliceQuery(k(1), SliceQuery()), t) == \
            [Entry(c(1), b"persisted")]
        t.commit()
        m2.close()


class TestGdbmGraphSuite:
    """The full graph stack over the third-party engine: open a graph on
    storage.backend=gdbm, run schema + writes + traversals + reopen."""

    def test_graph_on_gdbm(self, tmp_path):
        import titan_tpu
        d = str(tmp_path / "gd")
        g = titan_tpu.open({"storage.backend": "gdbm",
                            "storage.directory": d})
        tx = g.new_transaction()
        vs = [tx.add_vertex("person", name=f"p{i}") for i in range(20)]
        for i in range(19):
            vs[i].add_edge("knows", vs[i + 1])
        tx.commit()
        assert g.traversal().V().count().next() == 20
        assert g.traversal().V().out("knows").count().next() == 19
        two = g.traversal().V(vs[0].id).out("knows").out("knows") \
            .count().next()
        assert two == 1
        g.close()
        # persistence across reopen through the engine's own files
        g2 = titan_tpu.open({"storage.backend": "gdbm",
                             "storage.directory": d})
        assert g2.traversal().V().count().next() == 20
        names = {v.value("name") for v in g2.traversal().V().to_list()}
        assert names == {f"p{i}" for i in range(20)}
        g2.close()

    def test_olap_snapshot_on_gdbm(self, tmp_path):
        import numpy as np

        import titan_tpu
        from titan_tpu.olap.tpu import snapshot as snap_mod
        g = titan_tpu.open({"storage.backend": "gdbm",
                            "storage.directory": str(tmp_path / "gd2")})
        tx = g.new_transaction()
        vs = [tx.add_vertex("n") for i in range(10)]
        for i in range(9):
            vs[i].add_edge("link", vs[i + 1])
        tx.commit()
        snap = snap_mod.build(g)
        assert snap.n == 10 and snap.num_edges == 9
        g.close()


def test_packed_ops_equivalence(manager):
    """mutate_row_packed / scan_rows_packed must be observably identical
    to the entry-wise SPI (stores without a native packed path inherit
    the base-class adapters; stores declaring features.packed_ops get
    their fast path exercised here)."""
    store = manager.open_database("packedtest")
    txh = tx(manager)
    cols = [c(i) for i in range(6)]
    vals = [b"v%d" % i for i in range(6)]
    store.mutate_row_packed(k(1), cols, vals, txh)
    store.mutate(k(2), [Entry(c(9), b"w")], [], txh)
    txh.commit()
    txh = tx(manager)
    # packed-written row reads back through the entry SPI, sliced
    got = store.get_slice(KeySliceQuery(k(1), SliceQuery(c(1), c(4))), txh)
    assert [(e.column, e.value) for e in got] == \
        [(c(1), b"v1"), (c(2), b"v2"), (c(3), b"v3")]
    # packed upsert into an EXISTING row merges like mutate (commit
    # first: write visibility inside an open store tx is
    # backend-defined, e.g. sqlite buffers until commit)
    store.mutate_row_packed(k(1), [c(2), c(10)], [b"V2", b"x"], txh)
    txh.commit()
    txh = tx(manager)
    got = store.get_slice(KeySliceQuery(k(1), SliceQuery()), txh)
    assert (c(2), b"V2") in [(e.column, e.value) for e in got]
    assert (c(10), b"x") in [(e.column, e.value) for e in got]
    # packed scan sees every row the entry scan sees, same contents
    packed = {key: (list(cs), list(vs))
              for key, cs, vs in store.scan_rows_packed(txh)}
    entry = {key: ([e.column for e in es], [e.value for e in es])
             for key, es in store.get_keys(SliceQuery(), txh)}
    assert packed == entry
    txh.commit()
