"""Snapshot freshness contract (VERDICT r2 item 6 / SURVEY §7 hard part 4).

The reference's OLAP always scans the LIVE store
(StandardScannerExecutor.java:85-188); a build-once device snapshot needs
an explicit epoch + refresh() contract: commit after snapshotting, call
refresh(), and OLAP results include the new data WITHOUT a store re-scan.
"""

import numpy as np
import pytest

import titan_tpu
from titan_tpu.olap.tpu import snapshot as snap_mod


@pytest.fixture
def graph():
    g = titan_tpu.open("inmemory")
    tx = g.new_transaction()
    vs = [tx.add_vertex("node", name=f"v{i}") for i in range(6)]
    for a, b in [(0, 1), (1, 2), (2, 3), (3, 4)]:
        vs[a].add_edge("link", vs[b])
    tx.commit()
    yield g
    g.close()


def _edge_id_pairs(snap):
    return sorted((int(snap.vertex_ids[s]), int(snap.vertex_ids[d]))
                  for s, d in zip(snap.src, snap.dst))


def test_epoch_and_stale_flag(graph):
    snap = snap_mod.build(graph)
    assert not snap.stale
    e0 = snap.epoch
    tx = graph.new_transaction()
    vs = list(tx.vertices())
    vs[0].add_edge("link", vs[4])
    tx.commit()
    assert snap.stale
    assert graph.mutation_epoch > e0
    snap.refresh()
    assert not snap.stale
    assert snap.epoch == graph.mutation_epoch


def test_refresh_appends_new_edges_fast_path(graph):
    snap = snap_mod.build(graph)
    before = snap.num_edges
    tx = graph.new_transaction()
    vs = sorted(tx.vertices(), key=lambda v: v.value("name"))
    v4_id, v5_id = vs[4].id, vs[5].id
    vs[4].add_edge("link", vs[5])
    vs[0].add_edge("link", vs[3])
    tx.commit()
    stats = snap.refresh()
    assert stats["added_edges"] == 2 and stats["added_vertices"] == 0
    assert snap.num_edges == before + 2
    # CSR invariants hold after the in-place merge
    assert (np.diff(snap.dst) >= 0).all()
    assert snap.indptr_in[-1] == snap.num_edges
    assert snap.out_degree.sum() == snap.num_edges
    assert (v4_id, v5_id) in _edge_id_pairs(snap)


def test_refresh_result_matches_full_rebuild_after_mixed_changes(graph):
    snap = snap_mod.build(graph)
    tx = graph.new_transaction()
    vs = sorted(tx.vertices(), key=lambda v: v.value("name"))
    w = tx.add_vertex("node", name="v6")        # new vertex
    vs[2].add_edge("link", w)                   # edge to the new vertex
    e = next(iter(vs[0].out_edges("link")))     # remove an old edge
    e.remove()
    tx.commit()
    snap.refresh()
    fresh = snap_mod.build(graph)
    assert snap.n == fresh.n
    assert (snap.vertex_ids == fresh.vertex_ids).all()
    assert _edge_id_pairs(snap) == _edge_id_pairs(fresh)
    assert (snap.out_degree == fresh.out_degree).all()
    assert (snap.indptr_in == fresh.indptr_in).all()


def test_refresh_feeds_olap_result(graph):
    """The VERDICT's literal done-criterion: commit edges after
    snapshotting, refresh(), OLAP result includes them — no rebuild."""
    from titan_tpu.models.bfs import INF, frontier_bfs

    snap = snap_mod.build(graph, directed=False)
    tx = graph.new_transaction()
    vs = sorted(tx.vertices(), key=lambda v: v.value("name"))
    v0_id, v5_id = vs[0].id, vs[5].id
    dist0, _ = frontier_bfs(snap, snap.dense_of(v0_id))
    # v5 is isolated at build time
    assert dist0[snap.dense_of(v5_id)] >= INF
    vs[4].add_edge("link", vs[5])
    tx.commit()
    snap.refresh()
    dist1, _ = frontier_bfs(snap, snap.dense_of(v0_id))
    assert dist1[snap.dense_of(v5_id)] == 5


def test_refresh_with_vertex_removal(graph):
    snap = snap_mod.build(graph)
    tx = graph.new_transaction()
    vs = sorted(tx.vertices(), key=lambda v: v.value("name"))
    gone = vs[2].id
    vs[2].remove()
    tx.commit()
    snap.refresh()
    fresh = snap_mod.build(graph)
    assert gone not in snap.vertex_ids
    assert _edge_id_pairs(snap) == _edge_id_pairs(fresh)


def test_refresh_with_edge_values_refuses(graph):
    tx = graph.new_transaction()
    mg = graph.management()
    # snapshots with extracted edge properties can't delta-refresh
    snap = snap_mod.build(graph, edge_keys=())
    snap.edge_values = {"w": np.zeros(snap.num_edges)}
    tx.rollback()
    tx = graph.new_transaction()
    vs = list(tx.vertices())
    vs[0].add_edge("link", vs[1])
    tx.commit()
    with pytest.raises(NotImplementedError):
        snap.refresh()


def test_unsubscribed_snapshot_stops_accumulating(graph):
    snap = snap_mod.build(graph)
    snap.close()
    tx = graph.new_transaction()
    vs = list(tx.vertices())
    vs[0].add_edge("link", vs[1])
    tx.commit()
    assert not graph._change_listeners
    with pytest.raises(RuntimeError):
        snap.refresh()


def test_refresh_added_edge_to_vertex_removed_later(graph):
    """Review regression: commit A adds an edge to v; commit B removes v;
    refresh must drop the edge (like a rebuild), not rewire it."""
    snap = snap_mod.build(graph)
    tx = graph.new_transaction()
    vs = sorted(tx.vertices(), key=lambda v: v.value("name"))
    vs[5].add_edge("link", vs[2])
    tx.commit()
    tx = graph.new_transaction()
    vs2 = sorted(tx.vertices(), key=lambda v: v.value("name"))
    vs2[2].remove()
    tx.commit()
    snap.refresh()
    fresh = snap_mod.build(graph)
    assert (snap.vertex_ids == fresh.vertex_ids).all()
    assert _edge_id_pairs(snap) == _edge_id_pairs(fresh)


def test_change_queue_overflow_forces_rebuild(graph):
    from titan_tpu.core import changes as ch
    snap = snap_mod.build(graph)
    snap._listener.overflowed = True      # simulate >10k-commit backlog
    tx = graph.new_transaction()
    vs = list(tx.vertices())
    vs[0].add_edge("link", vs[1])
    tx.commit()
    with pytest.raises(RuntimeError, match="overflow"):
        snap.refresh()


def test_refresh_gap_detection(graph):
    """Payload-epoch continuity: a missing delta (e.g. a commit during
    build()'s scan) must fail loud, not corrupt silently."""
    snap = snap_mod.build(graph)
    tx = graph.new_transaction()
    vs = list(tx.vertices())
    vs[0].add_edge("link", vs[1])
    tx.commit()
    snap._listener.pop(0)                 # simulate a missed commit
    with pytest.raises(RuntimeError, match="gap"):
        snap.refresh()


def test_dropped_snapshot_unregisters_listener(graph):
    import gc
    n0 = len(graph._change_listeners)
    snap = snap_mod.build(graph)
    assert len(graph._change_listeners) == n0 + 1
    del snap
    gc.collect()
    assert len(graph._change_listeners) == n0


def test_refresh_leaves_future_payloads_queued(graph):
    """ADVICE r3: a payload racing past the new_epoch refresh() read must
    stay queued for the NEXT refresh — draining it early and stamping
    self.epoch = new_epoch made the next continuity check see a hole and
    force a spurious rebuild."""
    snap = snap_mod.build(graph)
    tx = graph.new_transaction()
    vs = list(tx.vertices())
    vs[0].add_edge("link", vs[1])
    tx.commit()                                   # epoch 1 payload queued
    future = {"epoch": graph.mutation_epoch + 1, "added": [], "removed": [],
              "added_vertices": [], "removed_vertices": []}
    snap._listener.append(future)                 # racing commit's payload
    snap.refresh()
    assert snap.epoch == graph.mutation_epoch
    assert list(snap._listener) == [future]       # not drained, not applied


def test_refresh_drains_large_backlog(graph):
    """Regression for the O(backlog²) listener drain (ISSUE r8
    satellite): refresh() used ``q.pop(0)`` per payload, quadratic
    against the 10k-commit backlog cap; the drain is now one scan +
    one slice delete. A ~1.2k-commit backlog must apply completely in
    one refresh, leave the queue empty, and keep the racing-payload
    boundary (a future-epoch payload stays queued)."""
    snap = snap_mod.build(graph)
    before = snap.num_edges
    tx = graph.new_transaction()
    ids = [v.id for v in tx.vertices()]
    tx.rollback()
    n_commits = 1200
    for i in range(n_commits):
        tx = graph.new_transaction()
        tx.vertex(ids[i % 6]).add_edge("link",
                                       tx.vertex(ids[(i + 1) % 6]))
        tx.commit()
    q = snap._listener
    assert len(q) == n_commits and not q.overflowed
    future = {"epoch": graph.mutation_epoch + 1, "added": [],
              "removed": [], "added_vertices": [], "removed_vertices": []}
    q.append(future)
    stats = snap.refresh()
    assert stats["added_edges"] == n_commits
    assert snap.num_edges == before + n_commits
    assert snap.epoch == graph.mutation_epoch
    assert list(q) == [future]        # boundary: future payload kept


def test_change_queue_reanchor_resumes_accumulation(graph):
    """ISSUE r9 satellite: once overflowed, push() dropped everything
    forever; reanchor() (called by rebuild_in_place under the commit
    lock) clears the backlog AND the flag so delta refresh resumes."""
    from titan_tpu.core.changes import ChangeQueue
    q = ChangeQueue(cap=2)
    q.push({"epoch": 1})
    q.push({"epoch": 2})
    q.push({"epoch": 3})                  # trips the cap
    assert q.overflowed and len(q) == 0
    q.push({"epoch": 4})                  # dropped while overflowed
    assert len(q) == 0
    q.reanchor()
    assert not q.overflowed
    q.push({"epoch": 5})
    assert list(q) == [{"epoch": 5}]


def test_rebuild_in_place_after_overflow_restores_delta_refresh(graph):
    snap = snap_mod.build(graph)
    q = snap._listener
    q.overflowed = True
    tx = graph.new_transaction()
    vs = list(tx.vertices())
    vs[0].add_edge("link", vs[1])
    tx.commit()
    with pytest.raises(RuntimeError, match="overflow"):
        snap.refresh()
    snap.rebuild_in_place()
    assert snap.epoch == graph.mutation_epoch and not snap.stale
    assert snap._listener is q and not q.overflowed
    fresh = snap_mod.build(graph)
    assert _edge_id_pairs(snap) == _edge_id_pairs(fresh)
    # the SAME queue feeds the next delta refresh
    before = snap.num_edges
    tx = graph.new_transaction()
    vs = list(tx.vertices())
    vs[1].add_edge("link", vs[2])
    tx.commit()
    stats = snap.refresh()
    assert stats["added_edges"] == 1
    assert snap.num_edges == before + 1


def test_undirected_removal_drops_both_rows(graph):
    """Review fix riding ISSUE r9: on symmetrized snapshots a removed
    relation must drop its forward AND reverse row — the old
    reverse-key fallback only caught whichever scanned first, silently
    de-symmetrizing the CSR."""
    snap = snap_mod.build(graph, directed=False)
    tx = graph.new_transaction()
    vs = sorted(tx.vertices(), key=lambda v: v.value("name"))
    e = next(iter(vs[1].out_edges("link")))
    e.remove()
    tx.commit()
    snap.refresh()
    fresh = snap_mod.build(graph, directed=False)
    assert _edge_id_pairs(snap) == _edge_id_pairs(fresh)
    # symmetry invariant: every row has its mirror
    pairs = _edge_id_pairs(snap)
    assert sorted((b, a) for a, b in pairs) == pairs


def test_build_retries_when_commit_races_scan(graph, monkeypatch):
    """build() must detect an epoch bump during its store scan and rescan
    (the racing commit may or may not be in the scanned rows)."""
    real_scan = snap_mod._scan_python
    calls = {"n": 0}

    def racing_scan(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:     # commit lands mid-scan, first attempt only
            tx = graph.new_transaction()
            vs = list(tx.vertices())
            vs[0].add_edge("link", vs[4])
            tx.commit()
        return real_scan(*a, **kw)

    monkeypatch.setattr(snap_mod, "_scan_python", racing_scan)
    monkeypatch.setattr(snap_mod.native, "available", False)
    snap = snap_mod.build(graph)
    assert calls["n"] == 2                         # retried once
    assert snap.epoch == graph.mutation_epoch
    assert not snap.stale
    # the racing edge is in the snapshot exactly once
    assert _edge_id_pairs(snap).count(
        (int(snap.vertex_ids[0]), int(snap.vertex_ids[4]))) == 1
