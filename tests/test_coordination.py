"""Coordination-tier tests: id authority, locking, log bus, WAL recovery,
global config, instance registry, ghost removal.

Modeled on the reference suites: IDAuthorityTest, LockKeyColumnValueStoreTest,
ExpectedValueCheckingTest, KCVSLogTest, TitanEventualGraphTest scenarios."""

import threading
import time

import pytest

import titan_tpu
from titan_tpu.errors import (PermanentLockingError, TemporaryLockingError,
                              TitanError)
from titan_tpu.ids.authority import ConsistentKeyIDAuthority
from titan_tpu.storage.api import Entry, KeySliceQuery, SliceQuery
from titan_tpu.storage.inmemory import InMemoryStoreManager
from titan_tpu.storage.locking import (ConsistentKeyLocker, LocalLockMediator,
                                       LockID, LockState)
from titan_tpu.storage.log import KCVSLog, LogManager, ReadMarker
from titan_tpu.utils.times import MicroProvider, SequenceClock


# ---------------------------------------------------------------------------
# id authority
# ---------------------------------------------------------------------------

class TestIDAuthority:
    def test_blocks_unique_and_contiguous(self):
        m = InMemoryStoreManager()
        store = m.open_database("system_ids")
        auth = ConsistentKeyIDAuthority(store, m, b"u1", MicroProvider(),
                                        wait_ms=1)
        blocks = [auth.get_id_block(b"p0", 100) for _ in range(5)]
        for i, b in enumerate(blocks):
            assert len(b) == 100
            if i:
                assert b.start == blocks[i - 1].end  # contiguous
        # separate namespace starts fresh
        other = auth.get_id_block(b"p1", 50)
        assert other.start == 1

    def test_concurrent_claims_never_overlap(self):
        m = InMemoryStoreManager()
        store = m.open_database("system_ids")
        results = []
        lock = threading.Lock()

        def worker(uid):
            auth = ConsistentKeyIDAuthority(store, m, uid, MicroProvider(),
                                            wait_ms=2)
            got = [auth.get_id_block(b"p0", 20, timeout_s=30) for _ in range(5)]
            with lock:
                results.extend(got)

        threads = [threading.Thread(target=worker, args=(b"u%d" % i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 20
        claimed = set()
        for b in results:
            ids = set(range(b.start, b.end))
            assert not (ids & claimed), "overlapping id blocks!"
            claimed |= ids


# ---------------------------------------------------------------------------
# locking
# ---------------------------------------------------------------------------

def make_locker(m, rid=b"r1", group="g1", **kw):
    store = m.open_database("system_locks")
    return ConsistentKeyLocker(store, m, rid, MicroProvider(), wait_ms=1,
                               mediator=LocalLockMediator.instance(group), **kw)


class TestLocking:
    def test_acquire_check_release(self):
        m = InMemoryStoreManager()
        locker = make_locker(m, group="t1")
        st = LockState()
        lid = LockID("edgestore", b"k", b"c")
        st.expected[lid] = None
        locker.write_lock(lid, st)
        assert st.has_locks
        locker.check_locks(st, lambda l: None)  # value still absent: ok
        locker.release_locks(st)
        assert not st.has_locks

    def test_local_mediation_blocks_second_tx(self):
        m = InMemoryStoreManager()
        locker = make_locker(m, group="t2")
        st1, st2 = LockState(), LockState()
        lid = LockID("edgestore", b"k", b"c")
        locker.write_lock(lid, st1)
        with pytest.raises(TemporaryLockingError):
            locker.write_lock(lid, st2)
        locker.release_locks(st1)
        locker.write_lock(lid, st2)  # now free
        locker.release_locks(st2)

    def test_remote_contention_earliest_wins(self):
        m = InMemoryStoreManager()
        # different mediator groups simulate different processes
        l1 = make_locker(m, rid=b"r1", group="t3a")
        l2 = make_locker(m, rid=b"r2", group="t3b")
        st1, st2 = LockState(), LockState()
        lid = LockID("edgestore", b"k", b"c")
        l1.write_lock(lid, st1)
        with pytest.raises(TemporaryLockingError):
            l2.write_lock(lid, st2)
        l1.release_locks(st1)
        l2.write_lock(lid, st2)
        l2.release_locks(st2)

    def test_expected_value_violation(self):
        m = InMemoryStoreManager()
        locker = make_locker(m, group="t4")
        st = LockState()
        lid = LockID("edgestore", b"k", b"c")
        st.expected[lid] = b"old"
        locker.write_lock(lid, st)
        with pytest.raises(PermanentLockingError):
            locker.check_locks(st, lambda l: b"changed")
        locker.release_locks(st)

    def test_expired_claims_cleaned(self):
        m = InMemoryStoreManager()
        locker = make_locker(m, group="t5", expiry_ms=50)
        st = LockState()
        locker.write_lock(LockID("edgestore", b"k", b"c"), st)
        time.sleep(0.1)  # claim expires but is never released
        assert locker.clean_expired() >= 1


class TestGraphLevelLocking:
    def test_lock_consistency_serializes_single_property(self):
        g = titan_tpu.open("inmemory")
        mgmt = g.management()
        pk = mgmt.make_property_key("bal", int)
        mgmt.set_consistency(pk, "lock")
        tx = g.new_transaction()
        v = tx.add_vertex(bal=10)
        tx.commit()
        # two concurrent txs both overwrite: second must fail on the lock
        tx1 = g.new_transaction()
        tx2 = g.new_transaction()
        tx1.vertex(v.id).property("bal", 20)
        tx2.vertex(v.id).property("bal", 30)
        tx1.commit()
        with pytest.raises((TemporaryLockingError, PermanentLockingError)):
            tx2.commit()
        tx3 = g.new_transaction()
        assert tx3.vertex(v.id).value("bal") == 20
        tx3.rollback()
        g.close()


# ---------------------------------------------------------------------------
# log bus
# ---------------------------------------------------------------------------

class TestLogBus:
    def test_write_read_roundtrip(self):
        m = InMemoryStoreManager()
        lm = LogManager(m, "logstore", b"r1", MicroProvider(),
                        read_interval_ms=20)
        log = lm.open_log("test")
        received = []
        log.register_reader(ReadMarker.from_time(0),
                            lambda msg: received.append(msg.content))
        for i in range(10):
            log.add(b"msg%d" % i)
        deadline = time.time() + 5
        while len(received) < 10 and time.time() < deadline:
            time.sleep(0.02)
        assert sorted(received) == [b"msg%d" % i for i in range(10)]
        lm.close()

    def test_read_marker_resume(self):
        m = InMemoryStoreManager()
        lm = LogManager(m, "logstore", b"r1", MicroProvider(),
                        read_interval_ms=20)
        log = lm.open_log("resume")
        got1 = []
        log.register_reader(ReadMarker.from_identifier("c1", 0),
                            lambda msg: got1.append(msg.content))
        log.add(b"a")
        deadline = time.time() + 5
        while not got1 and time.time() < deadline:
            time.sleep(0.02)
        lm.close()
        # "restart": a new reader with the same identifier resumes PAST a
        log2mgr = LogManager(m, "logstore", b"r1", MicroProvider(),
                             read_interval_ms=20)
        log2 = log2mgr.open_log("resume")
        got2 = []
        log2.register_reader(ReadMarker.from_identifier("c1", 0),
                             lambda msg: got2.append(msg.content))
        log2.add(b"b")
        deadline = time.time() + 5
        while not got2 and time.time() < deadline:
            time.sleep(0.02)
        assert got2 == [b"b"]  # did not re-deliver a
        log2mgr.close()

    def test_multiple_buckets(self):
        m = InMemoryStoreManager()
        lm = LogManager(m, "logstore", b"r1", MicroProvider(),
                        read_interval_ms=20, num_buckets=3)
        log = lm.open_log("buckets")
        received = []
        log.register_reader(ReadMarker.from_time(0),
                            lambda msg: received.append(msg.content))
        for i in range(9):
            log.add(b"m%d" % i)
        deadline = time.time() + 5
        while len(received) < 9 and time.time() < deadline:
            time.sleep(0.02)
        assert len(received) == 9
        lm.close()


# ---------------------------------------------------------------------------
# WAL + recovery
# ---------------------------------------------------------------------------

class TestWAL:
    def test_commit_writes_wal_records(self):
        g = titan_tpu.open({"storage.backend": "inmemory", "tx.log-tx": "true"})
        from titan_tpu.core.wal import (PRECOMMIT, PRIMARY_SUCCESS,
                                        SECONDARY_SUCCESS, TransactionLog)
        tx = g.new_transaction()
        tx.add_vertex(name="walled")
        tx.commit()
        g._wal._log.flush()
        records = []
        wal = g._wal
        log = wal._log
        log.register_reader(ReadMarker.from_time(0),
                            lambda m: records.append(wal.parse(m)))
        deadline = time.time() + 5
        while len(records) < 3 and time.time() < deadline:
            time.sleep(0.02)
        statuses = [s for _, s, _ in records]
        assert statuses == [PRECOMMIT, PRIMARY_SUCCESS, SECONDARY_SUCCESS]
        txids = {t for t, _, _ in records}
        assert len(txids) == 1
        # precommit payload carries the mutations
        payload = records[0][2]
        assert "edgestore" in payload and payload["edgestore"]
        g.close()

    def test_recovery_replays_lost_secondary(self):
        g = titan_tpu.open({"storage.backend": "inmemory", "tx.log-tx": "true"})
        from titan_tpu.core import wal as wal_mod
        wal = g._wal
        txid = wal.next_txid()
        # simulate: primary committed, secondary (graphindex) writes lost
        lost = {"graphindex": {b"idxkey": ([[b"col", b"val"]], [])}}
        wal.log_precommit(txid, lost)
        wal.log_primary_success(txid)
        wal._log.flush()
        recovery = wal_mod.TransactionRecovery(g, wal._log, start_time=0,
                                               persistence_timeout_s=0.05)
        deadline = time.time() + 5
        while recovery.recovered < 1 and time.time() < deadline:
            recovery.force_sweep()
            time.sleep(0.05)
        assert recovery.recovered == 1
        txh = g.backend.manager.begin_transaction()
        got = g.backend.index_store.store.get_slice(
            KeySliceQuery(b"idxkey", SliceQuery()), txh)
        txh.commit()
        assert got == [Entry(b"col", b"val")]
        g.close()


# ---------------------------------------------------------------------------
# global config + instances
# ---------------------------------------------------------------------------

class TestGlobalConfig:
    def test_global_options_persist_and_win(self, tmp_path):
        path = str(tmp_path / "db")
        g = titan_tpu.open({"storage.backend": "sqlite",
                            "storage.directory": path,
                            "cluster.max-partitions": 16})
        assert g.idm.num_partitions == 16
        g.close()
        # reopen with a DIFFERENT local value: the stored global (FIXED) wins
        g2 = titan_tpu.open({"storage.backend": "sqlite",
                             "storage.directory": path,
                             "cluster.max-partitions": 64})
        assert g2.idm.num_partitions == 16
        g2.close()

    def test_duplicate_instance_id_rejected(self):
        from titan_tpu.storage.inmemory import InMemoryStoreManager
        from titan_tpu.storage.backend import Backend
        m = InMemoryStoreManager()
        b = Backend(manager=m, instance_id="i-1")
        b.instance_registry.register("i-1")
        with pytest.raises(TitanError):
            b.instance_registry.register("i-1")
        assert b.instance_registry.instances() == ["i-1"]
        b.instance_registry.force_evict("i-1")
        b.instance_registry.register("i-1")  # after eviction: ok

    def test_management_global_option_roundtrip(self):
        g = titan_tpu.open("inmemory")
        from titan_tpu.config import defaults as d
        mgmt = g.management()
        mgmt.set_global_option(d.LOG_TTL_S, 3600, "mylog")
        assert mgmt.get_global_option(d.LOG_TTL_S, "mylog") == 3600
        g.close()


# ---------------------------------------------------------------------------
# ghost removal
# ---------------------------------------------------------------------------

def test_ghost_vertex_removal():
    from titan_tpu.olap.jobs import remove_ghost_vertices
    g = titan_tpu.open("inmemory")
    tx = g.new_transaction()
    a = tx.add_vertex(name="alive")
    ghost = tx.add_vertex(name="ghost")
    a.add_edge("knows", ghost)
    tx.commit()
    # simulate a half-deleted vertex: existence marker gone, relations remain
    from titan_tpu.core.defs import Direction
    [q] = g.codec.query_type(g.schema.system.vertex_exists, Direction.OUT,
                             g.schema)
    key = g.idm.key_bytes(ghost.id)
    txh = g.backend.manager.begin_transaction()
    entries = g.backend.edge_store.store.get_slice(KeySliceQuery(key, q), txh)
    g.backend.edge_store.store.mutate(key, [], [e.column for e in entries], txh)
    txh.commit()
    g.backend.edge_store.invalidate(key)

    removed = remove_ghost_vertices(g)
    assert removed == 1
    tx = g.new_transaction()
    assert tx.vertex(ghost.id) is None
    # row fully gone
    txh = g.backend.manager.begin_transaction()
    left = g.backend.edge_store.store.get_slice(
        KeySliceQuery(key, SliceQuery()), txh)
    txh.commit()
    assert left == []
    assert tx.vertex(a.id) is not None
    tx.rollback()
    g.close()
