"""Remote index provider: index node over HTTP + client adapter.

Modeled on the reference's external-index coverage (titan-es module
running the shared index suites against a networked Elasticsearch): the
'cluster' here is an in-process IndexServer hosting the FTS5 engine.
"""

import pytest

import titan_tpu
from titan_tpu.indexing.ftsindex import FTSIndex
from titan_tpu.indexing.memindex import MemoryIndex
from titan_tpu.indexing.provider import (And, FieldCondition, IndexQuery,
                                         KeyInformation, RawQuery)
from titan_tpu.indexing.remote import IndexServer, RemoteIndexProvider
from titan_tpu.query.predicates import P


@pytest.fixture
def node():
    server = IndexServer(MemoryIndex("node")).start()
    yield server
    server.stop()


@pytest.fixture
def provider(node):
    return RemoteIndexProvider("t", hostname="127.0.0.1", port=node.port)


def _fill(provider):
    provider.register("s", "title", KeyInformation(str))
    provider.register("s", "price", KeyInformation(float))
    tx = provider.begin_transaction()
    tx.add("s", "d1", "title", "red fish blue fish")
    tx.add("s", "d1", "price", 3.5)
    tx.add("s", "d2", "title", "one fish two fish")
    tx.add("s", "d2", "price", 9.0)
    tx.commit()


def test_text_and_numeric_over_the_wire(provider):
    _fill(provider)
    hits = provider.query("s", IndexQuery(
        FieldCondition("title", P.text_contains("fish"))))
    assert hits == ["d1", "d2"]
    hits = provider.query("s", IndexQuery(
        And((FieldCondition("title", P.text_contains("fish")),
             FieldCondition("price", P.gt(4.0))))))
    assert hits == ["d2"]


def test_raw_query_and_deletion(provider):
    _fill(provider)
    hits = provider.raw_query("s", RawQuery("title:fish"))
    assert {d for d, _ in hits} == {"d1", "d2"}
    tx = provider.begin_transaction()
    tx.delete_document("s", "d1")
    tx.commit()
    assert provider.query("s", IndexQuery(
        FieldCondition("price", P.lt(5.0)))) == []
    provider.drop_store("s")
    assert provider.query("s", IndexQuery(
        FieldCondition("title", P.text_contains("fish")))) == []


def test_multi_value_and_geo_predicates_over_wire(provider):
    from titan_tpu.core.attribute import Geoshape
    _fill(provider)
    provider.register("s", "spot", KeyInformation(Geoshape))
    tx = provider.begin_transaction()
    tx.add("s", "d1", "spot", Geoshape.point(10.0, 10.0))
    tx.commit()
    # between/within ship element lists (tuples aren't serializable)
    assert provider.query("s", IndexQuery(
        FieldCondition("price", P.between(3.0, 5.0)))) == ["d1"]
    assert provider.query("s", IndexQuery(
        FieldCondition("price", P.within(9.0, 11.0)))) == ["d2"]
    hits = provider.query("s", IndexQuery(
        FieldCondition("spot", P.geo_within(
            Geoshape.circle(10.0, 10.0, 50.0)))))
    assert hits == ["d1"]


def test_graph_with_remote_mixed_index(node):
    g = titan_tpu.open({"storage.backend": "inmemory",
                        "index.search.backend": "remote-index",
                        "index.search.hostname": ["127.0.0.1"],
                        "index.search.port": node.port})
    try:
        mgmt = g.management()
        text = mgmt.make_property_key("bio", str)
        mgmt.build_index("bios", "vertex").add_key(text, "TEXT") \
            .build_mixed_index("search")
        mgmt.commit()
        tx = g.new_transaction()
        v = tx.add_vertex("person", bio="graphs on tensor processors")
        tx.add_vertex("person", bio="tables on spinning disks")
        vid = v.id
        tx.commit()
        tx2 = g.new_transaction()
        hits = tx2.query().has("bio", P.text_contains("tensor")).vertices()
        assert [x.id for x in hits] == [vid]
        raw = g.index_query("bios", "bio:graphs")
        assert [el.id for el, _ in raw] == [vid]
        tx2.rollback()
    finally:
        g.close()


def test_fts_backed_node(tmp_path):
    server = IndexServer(FTSIndex("node", str(tmp_path / "idx"))).start()
    try:
        provider = RemoteIndexProvider("t", hostname="127.0.0.1",
                                       port=server.port)
        _fill(provider)
        hits = provider.raw_query("s", RawQuery("fish"))
        assert len(hits) == 2 and all(s > 0 for _, s in hits)
    finally:
        server.stop()
