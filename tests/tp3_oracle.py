"""Independent TinkerPop-3 semantics oracle for differential testing.

VERDICT r3 missing #2: the traversal DSL was only ever tested against
itself (bulked vs unbulked — self-referential). This module is a
deliberately naive, from-the-spec re-implementation of TP3 step
semantics over plain dict graphs: list comprehensions and recursion,
no shared code with ``titan_tpu.traversal.dsl``, no traverser bulking,
no strategies, no storage layer. ``tests/test_tp3_differential.py``
evaluates randomly generated traversals through BOTH interpreters and
compares results, which is the closest available stand-in for the
reference's inherited TinkerPop compliance suites
(titan-test/.../blueprints/AbstractTitanGraphProvider.java) — the real
TP3 suites are JVM-only and the image has no JVM/network.

Semantics implemented from the TinkerPop 3.0 reference documentation
(the version Titan embeds, pom.xml:62):

* map/flatMap steps (V, out/in/both, outE/inE/bothE, inV/outV/otherV,
  values, select) append their output object to the traverser path;
  filter steps (has, hasLabel, where, not, dedup, simplePath, limit,
  order) do not.
* ``repeat(sub).times(n)`` applies sub n times (do-while form);
  ``repeat(sub).until(cond)`` exits a traverser after a pass that
  satisfies cond; ``.emit()`` after repeat emits the traverser after
  every pass (the final pass result is emitted once, not twice).
* ``dedup`` keeps the first traverser per distinct current object.
* ``where(sub)`` / ``not(sub)`` pass iff sub yields any / no result
  starting from the current traverser (path visible to the sub).
* ``select`` of an unlabelled key filters the traverser out; multiple
  labels produce a dict; a ``by(key)`` modulator maps each selected
  element to its property value.
* ``order().by(key)`` requires the key on every element (the grammar
  only orders by always-present keys); plain ``order()`` sorts values.
* barrier terminals: count sums bulks (bulk == 1 here), sum/min/max/
  mean over the incoming values, groupCount builds {object-or-by-key:
  count} — empty incoming stream yields NO result for sum/mean/min/max
  (TP3 emits nothing from an empty reducing barrier), count yields 0.

Graph model: ``{"vertices": {vid: {"label": l, "props": {..}}},
"edges": {eid: {"src": vid, "dst": vid, "label": l, "props": {..}}},
"out": {vid: [eid..]}, "in": {vid: [eid..]}}``. Stream objects are
("v", vid), ("e", eid), or raw values.
"""

from __future__ import annotations


def _pred(p):
    """Compile a predicate spec tuple into a Python callable."""
    op = p[0]
    if op == "eq":
        return lambda x: x == p[1]
    if op == "neq":
        return lambda x: x != p[1]
    if op == "gt":
        return lambda x: x > p[1]
    if op == "gte":
        return lambda x: x >= p[1]
    if op == "lt":
        return lambda x: x < p[1]
    if op == "lte":
        return lambda x: x <= p[1]
    if op == "within":
        return lambda x: x in p[1]
    if op == "between":        # [lo, hi) per TP3 P.between
        return lambda x: p[1] <= x < p[2]
    raise ValueError(f"unknown predicate {p!r}")


class _Trav:
    __slots__ = ("obj", "path", "labels")

    def __init__(self, obj, path, labels):
        self.obj = obj
        self.path = path          # tuple of objects
        self.labels = labels      # dict as-label -> object


def _props(g, obj):
    kind, key = obj
    return (g["vertices"] if kind == "v" else g["edges"])[key]["props"]


def _label(g, obj):
    kind, key = obj
    return (g["vertices"] if kind == "v" else g["edges"])[key]["label"]


def _adj(g, t, direction, labels):
    """Neighbor objects for out/in/both (vertex input only)."""
    kind, vid = t.obj
    assert kind == "v"
    out = []
    if direction in ("out", "both"):
        for eid in g["out"].get(vid, ()):
            e = g["edges"][eid]
            if not labels or e["label"] in labels:
                out.append(("v", e["dst"]))
    if direction in ("in", "both"):
        for eid in g["in"].get(vid, ()):
            e = g["edges"][eid]
            if not labels or e["label"] in labels:
                out.append(("v", e["src"]))
    return out


def _adj_e(g, t, direction, labels):
    kind, vid = t.obj
    assert kind == "v"
    out = []
    if direction in ("out", "both"):
        for eid in g["out"].get(vid, ()):
            if not labels or g["edges"][eid]["label"] in labels:
                out.append(("e", eid))
    if direction in ("in", "both"):
        for eid in g["in"].get(vid, ()):
            if not labels or g["edges"][eid]["label"] in labels:
                out.append(("e", eid))
    return out


def _step_map(t, obj, step=None):
    """Extend a traverser with a new current object (map semantics)."""
    labels = t.labels
    return _Trav(obj, t.path + (obj,), labels)


def evaluate(g, spec, travs=None):
    """Run ``spec`` (list of step tuples) over graph ``g``; returns the
    final stream as a list of python values / object tuples / dicts."""
    if travs is None:
        travs = []
    for step in spec:
        op = step[0]
        if op == "V":
            travs = [_Trav(("v", vid), (("v", vid),), {})
                     for vid in g["vertices"]]
        elif op in ("out", "in", "both"):
            travs = [_step_map(t, o)
                     for t in travs for o in _adj(g, t, op, step[1])]
        elif op in ("outE", "inE", "bothE"):
            travs = [_step_map(t, o)
                     for t in travs
                     for o in _adj_e(g, t, op[:-1], step[1])]
        elif op == "outV":
            travs = [_step_map(t, ("v", g["edges"][t.obj[1]]["src"]))
                     for t in travs]
        elif op == "inV":
            travs = [_step_map(t, ("v", g["edges"][t.obj[1]]["dst"]))
                     for t in travs]
        elif op == "otherV":
            # the endpoint the traverser did NOT come from: the previous
            # vertex in the path is the one it came from
            new = []
            for t in travs:
                e = g["edges"][t.obj[1]]
                prev = next((o for o in reversed(t.path[:-1])
                             if o[0] == "v"), None)
                other = ("v", e["dst"]) if prev == ("v", e["src"]) \
                    else ("v", e["src"])
                new.append(_step_map(t, other))
            travs = new
        elif op == "has":
            key, pred = step[1], _pred(step[2])
            travs = [t for t in travs
                     if key in _props(g, t.obj)
                     and pred(_props(g, t.obj)[key])]
        elif op == "hasLabel":
            travs = [t for t in travs if _label(g, t.obj) in step[1]]
        elif op == "values":
            keys = step[1]
            travs = [_step_map(t, _props(g, t.obj)[k])
                     for t in travs for k in keys
                     if k in _props(g, t.obj)]
        elif op == "id":
            travs = [_step_map(t, t.obj) for t in travs]
        elif op == "label":
            travs = [_step_map(t, _label(g, t.obj)) for t in travs]
        elif op == "dedup":
            seen, out = set(), []
            for t in travs:
                k = t.obj if not isinstance(t.obj, dict) \
                    else tuple(sorted(t.obj.items()))
                if k not in seen:
                    seen.add(k)
                    out.append(t)
            travs = out
        elif op == "limit":
            travs = travs[:step[1]]
        elif op == "order":
            key, desc = step[1], step[2]
            if key is None:
                travs = sorted(travs, key=lambda t: t.obj, reverse=desc)
            else:
                travs = sorted(travs,
                               key=lambda t: _props(g, t.obj)[key],
                               reverse=desc)
        elif op == "as":
            for t in travs:
                t.labels = dict(t.labels)
                t.labels[step[1]] = t.obj
        elif op == "select":
            labels, by = step[1], step[2]
            new = []
            for t in travs:
                if any(lb not in t.labels for lb in labels):
                    continue

                def view(o):
                    return _props(g, o)[by] if by is not None else o

                if len(labels) == 1:
                    new.append(_step_map(t, view(t.labels[labels[0]])))
                else:
                    new.append(_step_map(
                        t, {lb: view(t.labels[lb]) for lb in labels}))
            travs = new
        elif op == "where":
            travs = [t for t in travs
                     if evaluate(g, step[1],
                                 [_Trav(t.obj, t.path, t.labels)])]
        elif op == "not":
            travs = [t for t in travs
                     if not evaluate(g, step[1],
                                     [_Trav(t.obj, t.path, t.labels)])]
        elif op == "union":
            new = []
            for t in travs:
                for sub in step[1]:
                    new.extend(_eval_travs(
                        g, sub, [_Trav(t.obj, t.path, t.labels)]))
            travs = new
        elif op == "coalesce":
            new = []
            for t in travs:
                for sub in step[1]:
                    got = _eval_travs(
                        g, sub, [_Trav(t.obj, t.path, t.labels)])
                    if got:
                        new.extend(got)
                        break
            travs = new
        elif op == "repeat":
            sub, stop, emit = step[1], step[2], step[3]
            out = []
            cur = travs
            if stop[0] == "times":
                for i in range(stop[1]):
                    cur = _eval_travs(g, sub, cur)
                    if emit and i < stop[1] - 1:
                        out.extend(cur)
                out.extend(cur)
            else:                              # ("until", subspec)
                # do-while with a safety bound (grammar graphs are tiny)
                for _ in range(16):
                    if not cur:
                        break
                    cur = _eval_travs(g, sub, cur)
                    done, rest = [], []
                    for t in cur:
                        hit = evaluate(g, stop[1],
                                       [_Trav(t.obj, t.path, t.labels)])
                        (done if hit else rest).append(t)
                    if emit:
                        out.extend(rest)
                    out.extend(done)
                    cur = rest
            travs = out
        elif op == "simplePath":
            travs = [t for t in travs
                     if len(set(map(repr, t.path))) == len(t.path)]
        elif op == "path":
            travs = [_Trav(tuple(t.path), t.path, t.labels)
                     for t in travs]
        elif op == "count":
            return [len(travs)]
        elif op in ("sum", "min", "max", "mean"):
            vals = [t.obj for t in travs]
            if not vals:
                return []
            if op == "sum":
                return [sum(vals)]
            if op == "min":
                return [min(vals)]
            if op == "max":
                return [max(vals)]
            return [sum(vals) / len(vals)]
        elif op == "groupCount":
            by = step[1]
            counts: dict = {}
            for t in travs:
                k = _props(g, t.obj)[by] if by is not None else t.obj
                counts[k] = counts.get(k, 0) + 1
            return [counts]
        else:
            raise ValueError(f"oracle: unknown step {step!r}")
    return [t.obj for t in travs]


def _eval_travs(g, spec, travs):
    """Evaluate a sub-spec returning traversers (not projected objects) —
    used by union/coalesce/repeat so paths keep accumulating. Sub-specs
    are restricted to the traverser-preserving step set the grammar
    emits inside sub-traversals."""
    for step in spec:
        travs = _apply_traverser_step(g, step, travs)
    return travs


def _apply_traverser_step(g, step, travs):
    """Single-step evaluation that RETURNS traversers; mirrors the
    corresponding branch in evaluate() for the sub-spec step set
    (hops, filters, values — the ops the grammar emits inside subs)."""
    op = step[0]
    if op in ("out", "in", "both"):
        return [_step_map(t, o)
                for t in travs for o in _adj(g, t, op, step[1])]
    if op in ("outE", "inE", "bothE"):
        return [_step_map(t, o)
                for t in travs for o in _adj_e(g, t, op[:-1], step[1])]
    if op == "outV":
        return [_step_map(t, ("v", g["edges"][t.obj[1]]["src"]))
                for t in travs]
    if op == "inV":
        return [_step_map(t, ("v", g["edges"][t.obj[1]]["dst"]))
                for t in travs]
    if op == "otherV":
        new = []
        for t in travs:
            e = g["edges"][t.obj[1]]
            prev = next((o for o in reversed(t.path[:-1])
                         if o[0] == "v"), None)
            other = ("v", e["dst"]) if prev == ("v", e["src"]) \
                else ("v", e["src"])
            new.append(_step_map(t, other))
        return new
    if op == "has":
        key, pred = step[1], _pred(step[2])
        return [t for t in travs
                if key in _props(g, t.obj)
                and pred(_props(g, t.obj)[key])]
    if op == "hasLabel":
        return [t for t in travs if _label(g, t.obj) in step[1]]
    if op == "values":
        return [_step_map(t, _props(g, t.obj)[k])
                for t in travs for k in step[1]
                if k in _props(g, t.obj)]
    if op == "dedup":
        seen, out = set(), []
        for t in travs:
            if t.obj not in seen:
                seen.add(t.obj)
                out.append(t)
        return out
    if op == "simplePath":
        return [t for t in travs
                if len(set(map(repr, t.path))) == len(t.path)]
    if op == "where":
        return [t for t in travs
                if evaluate(g, step[1],
                            [_Trav(t.obj, t.path, t.labels)])]
    if op == "not":
        return [t for t in travs
                if not evaluate(g, step[1],
                                [_Trav(t.obj, t.path, t.labels)])]
    raise ValueError(f"oracle sub-spec: unsupported step {step!r}")
