"""Distributed scan runner: key splits, worker processes, metric merge.

Modeled on the reference's Hadoop scan tier (HadoopScanMapper + the
SimpleScanJobRunner duality in titan-test: the SAME job + assertions run
in-process and distributed).
"""

import numpy as np
import pytest

import titan_tpu
from titan_tpu.ids.idmanager import IDManager
from titan_tpu.olap.distributed import (DistributedScanRunner,
                                        InProcessSplitRunner, ScanJobSpec,
                                        distributed_reindex, key_splits)
from titan_tpu.olap.jobs import VertexCountJob


def _populate(g, n_people=40, n_edges=60):
    tx = g.new_transaction()
    people = [tx.add_vertex("person", name=f"p{i}") for i in range(n_people)]
    rng = np.random.default_rng(11)
    for _ in range(n_edges):
        a, b = rng.integers(0, n_people, 2)
        people[int(a)].add_edge("knows", people[int(b)])
    tx.commit()


def test_key_splits_cover_and_are_disjoint():
    idm = IDManager(partition_bits=5)     # 32 partitions
    for n in (1, 3, 4, 32, 64):
        splits = key_splits(idm, n)
        assert len(splits) == min(n, 32)
        # contiguous, disjoint, full coverage
        for (s1, e1), (s2, e2) in zip(splits, splits[1:]):
            assert e1 == s2
        assert splits[0][0] == (0).to_bytes(8, "big")
        assert splits[-1][1] == (32 << (63 - 5)).to_bytes(8, "big")


def test_spec_build_resolves_factory():
    g = titan_tpu.open("inmemory")
    spec = ScanJobSpec("titan_tpu.olap.jobs:make_vertex_count_job")
    job = spec.build(g)
    assert isinstance(job, VertexCountJob)
    with pytest.raises(ValueError):
        ScanJobSpec("no-colon").build(g)
    g.close()


def test_in_process_split_runner_matches_full_scan():
    g = titan_tpu.open("inmemory")
    _populate(g)
    spec = ScanJobSpec("titan_tpu.olap.jobs:make_vertex_count_job")
    metrics = InProcessSplitRunner(g, num_workers=4).run(spec)
    assert metrics.get(VertexCountJob.VERTICES) == 40
    assert metrics.get(VertexCountJob.EDGES) == 60
    g.close()


@pytest.mark.slow
def test_distributed_runner_processes(tmp_path):
    cfg = {"storage.backend": "sqlite",
           "storage.directory": str(tmp_path / "db")}
    g = titan_tpu.open(cfg)
    _populate(g)
    g.close()

    runner = DistributedScanRunner(cfg, num_workers=3)
    spec = ScanJobSpec("titan_tpu.olap.jobs:make_vertex_count_job")
    metrics = runner.run(spec)
    assert metrics.get(VertexCountJob.VERTICES) == 40
    assert metrics.get(VertexCountJob.EDGES) == 60
    # same job, same numbers, in-process — the SimpleScanJobRunner duality
    g2 = titan_tpu.open(cfg)
    m2 = InProcessSplitRunner(g2, num_workers=2).run(spec)
    g2.close()
    assert m2.get(VertexCountJob.VERTICES) == 40
    assert m2.get(VertexCountJob.EDGES) == 60


@pytest.mark.slow
def test_distributed_reindex(tmp_path):
    cfg = {"storage.backend": "sqlite",
           "storage.directory": str(tmp_path / "db")}
    g = titan_tpu.open(cfg)
    _populate(g, n_people=25, n_edges=0)

    # index created AFTER the data: needs REGISTER -> (distributed) REINDEX
    mgmt = g.management()
    key = g.schema.get_by_name("name")
    mgmt.build_index("byNameDist", "vertex").add_key(key) \
        .build_composite_index()
    mgmt.update_index("byNameDist", "register")
    g.close()

    metrics = distributed_reindex(cfg, "byNameDist", num_workers=3)
    assert metrics.get("index-entries-added") == 25

    g2 = titan_tpu.open(cfg)
    mgmt2 = g2.management()
    mgmt2.update_index("byNameDist", "enable")
    tx = g2.new_transaction()
    hits = tx.query().has("name", "p7").vertices()
    assert len(hits) == 1 and hits[0].value("name") == "p7"
    tx.commit()
    g2.close()
