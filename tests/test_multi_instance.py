"""Multi-instance graphs over one shared storage backend.

Modeled on the reference's eventual-consistency and concurrency coverage
(titan-test TitanEventualGraphTest / TitanGraphConcurrentTest and the
instance-registry behaviors in ManagementSystem): Titan instances never
talk to each other directly — all coordination flows through the shared
store — so two graph handles over the same sqlite directory behave like
two cluster nodes.
"""

import threading

import pytest

import titan_tpu
from titan_tpu.errors import TitanError


@pytest.fixture
def shared_dir(tmp_path):
    return str(tmp_path / "db")


def _open(shared_dir, instance=None, **extra):
    cfg = {"storage.backend": "sqlite", "storage.directory": shared_dir}
    if instance:
        cfg["graph.unique-instance-id"] = instance
    cfg.update(extra)
    return titan_tpu.open(cfg)


def test_writes_visible_across_instances(shared_dir):
    g1 = _open(shared_dir, "a")
    g2 = _open(shared_dir, "b")
    try:
        tx = g1.new_transaction()
        v = tx.add_vertex("person", name="alice")
        vid = v.id
        tx.commit()
        tx2 = g2.new_transaction()
        got = tx2.vertex(vid)
        assert got is not None and got.value("name") == "alice"
        tx2.rollback()
    finally:
        g1.close()
        g2.close()


def test_schema_created_by_peer_resolves(shared_dir):
    g1 = _open(shared_dir, "a")
    g2 = _open(shared_dir, "b")
    try:
        mgmt = g1.management()
        mgmt.make_edge_label("follows")
        mgmt.commit()
        # instance b sees the label by name (loaded through the store)
        st = g2.schema.get_by_name("follows")
        assert st is not None and st.is_edge_label
    finally:
        g1.close()
        g2.close()


def test_instance_registry_and_eviction(shared_dir):
    g1 = _open(shared_dir, "node1")
    g2 = _open(shared_dir, "node2")
    try:
        mgmt = g1.management()
        assert set(mgmt.get_open_instances()) == {"node1", "node2"}
        with pytest.raises(TitanError):
            mgmt.force_close_instance("node1")   # not the current one
    finally:
        g2.close()
        g1.close()


def test_dead_instance_blocks_id_then_evicts(shared_dir):
    g1 = _open(shared_dir, "nodeX")
    g1.backend.manager.close()  # simulate a crash: no deregistration
    g1._open = False
    g2 = _open(shared_dir, "alive")
    try:
        # the dead instance's registration is still visible...
        mgmt = g2.management()
        assert "nodeX" in mgmt.get_open_instances()
        # ...a new instance reusing the id is refused...
        with pytest.raises(TitanError):
            _open(shared_dir, "nodeX")
        # ...until force-evicted (reference: forceCloseInstance)
        mgmt.force_close_instance("nodeX")
        g3 = _open(shared_dir, "nodeX")
        g3.close()
    finally:
        g2.close()


def test_id_blocks_disjoint_across_instances(shared_dir):
    g1 = _open(shared_dir, "a")
    g2 = _open(shared_dir, "b")
    try:
        ids1, ids2 = [], []
        tx1, tx2 = g1.new_transaction(), g2.new_transaction()
        for i in range(50):
            ids1.append(tx1.add_vertex("person", name=f"a{i}").id)
            ids2.append(tx2.add_vertex("person", name=f"b{i}").id)
        tx1.commit()
        tx2.commit()
        assert not (set(ids1) & set(ids2))
    finally:
        g1.close()
        g2.close()


def test_concurrent_commits_from_two_instances(shared_dir):
    g1 = _open(shared_dir, "a")
    g2 = _open(shared_dir, "b")
    errors = []

    def writer(g, tag):
        try:
            for i in range(10):
                tx = g.new_transaction()
                tx.add_vertex("person", name=f"{tag}{i}")
                tx.commit()
        except BaseException as e:   # noqa: BLE001
            errors.append(e)

    try:
        t1 = threading.Thread(target=writer, args=(g1, "a"))
        t2 = threading.Thread(target=writer, args=(g2, "b"))
        t1.start(); t2.start(); t1.join(); t2.join()
        assert not errors, errors
        tx = g1.new_transaction()
        assert sum(1 for _ in tx.vertices()) == 20
        tx.rollback()
    finally:
        g1.close()
        g2.close()


def test_ghost_rows_after_concurrent_delete(shared_dir):
    """Eventual-consistency cleanup: instance A deletes a vertex while B
    already wrote an edge to it; the half-alive remnants are swept by the
    ghost remover (reference: GhostVertexRemover semantics)."""
    from titan_tpu.olap.jobs import remove_ghost_vertices
    g1 = _open(shared_dir, "a")
    g2 = _open(shared_dir, "b")
    try:
        tx = g1.new_transaction()
        victim = tx.add_vertex("person", name="victim")
        vid = victim.id
        tx.commit()

        # B observes the victim alive, A deletes it, then B attaches an
        # edge in a FRESH tx without re-checking — the edge lands on a
        # now-dead row (no conflict detected: no locks). (Note: sqlite WAL
        # refuses read→write upgrades across a peer's commit, so B's stale
        # observation and its write are separate transactions — which is
        # also the realistic racing-client shape.)
        tx_look = g2.new_transaction()
        assert tx_look.vertex(vid) is not None
        tx_look.rollback()
        txa = g1.new_transaction()
        txa.vertex(vid).remove()
        txa.commit()
        txb = g2.new_transaction()
        w = txb.add_vertex("person", name="writer")
        txb.add_edge(w, "knows", txb.vertex_handle(vid))
        txb.commit()

        # the victim row now has relation data but no exists marker
        tx3 = g1.new_transaction()
        assert tx3.vertex(vid) is None
        tx3.rollback()
        removed = remove_ghost_vertices(g1)
        assert removed >= 1
        # sweep leaves a clean store: victim row fully gone
        from titan_tpu.storage.api import KeySliceQuery, SliceQuery
        txh = g1.backend.manager.begin_transaction()
        entries = g1.backend.edge_store.store.get_slice(
            KeySliceQuery(g1.idm.key_bytes(vid), SliceQuery()), txh)
        txh.commit()
        assert entries == []
    finally:
        g1.close()
        g2.close()


def test_racing_schema_creation_converges(shared_dir):
    """Lock-backed schema creation (reference: consistent-key locks on the
    system name index): two instances auto-creating the same label at once
    must converge on ONE schema id, and every committed edge must reference
    that id (no rows orphaned under a loser's id)."""
    g1 = _open(shared_dir, "a")
    g2 = _open(shared_dir, "b")
    assert g1.backend.locker is not None   # sqlite has no native locking
    barrier = threading.Barrier(2)
    errors = []

    def writer(g, tag):
        try:
            barrier.wait(timeout=10)
            tx = g.new_transaction()
            u = tx.add_vertex("person", name=f"{tag}-u")
            w = tx.add_vertex("person", name=f"{tag}-w")
            tx.add_edge(u, "collides", w)
            tx.commit()
        except BaseException as e:   # noqa: BLE001
            errors.append(e)

    try:
        t1 = threading.Thread(target=writer, args=(g1, "a"))
        t2 = threading.Thread(target=writer, args=(g2, "b"))
        t1.start(); t2.start(); t1.join(); t2.join()
        assert not errors, errors
        sid1 = g1.schema.get_by_name("collides").id
        g2.schema.expire()
        sid2 = g2.schema.get_by_name("collides").id
        assert sid1 == sid2
        # every edge written by either instance resolves under the winner id
        tx = g1.new_transaction()
        n_edges = sum(1 for v in tx.vertices()
                      for _ in v.out_edges("collides"))
        tx.rollback()
        assert n_edges == 2
    finally:
        g1.close()
        g2.close()
