"""Query profiler + traversal .profile().

Modeled on the reference's QueryProfiler threading
(StandardTitanTx.java:1030,1116,1247) and Gremlin .profile() surfacing
(TP3ProfileWrapper).
"""

import pytest

import titan_tpu
from titan_tpu.query.profile import NO_OP, QueryProfiler


@pytest.fixture
def graph():
    g = titan_tpu.open("inmemory")
    tx = g.new_transaction()
    people = [tx.add_vertex("person", name=f"p{i}", age=20 + i)
              for i in range(6)]
    for i in range(5):
        people[i].add_edge("knows", people[i + 1])
    tx.commit()
    yield g
    g.close()


def test_profiler_tree_and_render():
    p = QueryProfiler()
    with p.group("outer") as outer:
        outer.annotate("k", 1)
        with outer.group("inner"):
            pass
    assert p.children[0].name == "outer"
    assert p.children[0].annotations["k"] == 1
    assert p.children[0].children[0].name == "inner"
    assert p.children[0].time_ns >= p.children[0].children[0].time_ns >= 0
    text = p.render()
    assert "outer" in text and "inner" in text and "k=1" in text
    d = p.to_dict()
    assert d["children"][0]["annotations"] == {"k": 1}


def test_noop_profiler_is_inert():
    before_children = len(NO_OP.children)
    with NO_OP.group("x") as g:
        g.annotate("a", 1)
    assert len(NO_OP.children) == before_children
    assert NO_OP.annotations == {}


def test_graph_query_profiled_full_scan(graph):
    p = QueryProfiler()
    tx = graph.new_transaction()
    from titan_tpu.query.graphquery import GraphQuery
    res = GraphQuery(tx).with_profiler(p).has("age").vertices()
    assert len(res) == 6
    names = [c.name for c in p.children]
    assert "optimization" in names
    # no index on age -> full scan recorded
    assert "full-scan" in names
    scan = p.children[names.index("full-scan")]
    assert scan.annotations["results"] == 6
    tx.commit()


def test_graph_query_profiled_indexed():
    graph = titan_tpu.open("inmemory")
    mgmt = graph.management()
    name_key = mgmt.make_property_key("name", str)
    mgmt.build_index("byName", "vertex").add_key(name_key).build_composite_index()
    mgmt.commit()
    tx0 = graph.new_transaction()
    for i in range(6):
        tx0.add_vertex("person", name=f"p{i}")
    tx0.commit()
    p = QueryProfiler()
    tx = graph.new_transaction()
    from titan_tpu.query.graphquery import GraphQuery
    res = GraphQuery(tx).with_profiler(p).has("name", "p3").vertices()
    assert len(res) == 1
    names = [c.name for c in p.children]
    assert "backend-query" in names
    bq = p.children[names.index("backend-query")]
    assert bq.annotations["results"] == 1
    opt = p.children[names.index("optimization")]
    assert opt.annotations["indexed"] is True
    tx.commit()


def test_traversal_profile_steps(graph):
    m = graph.traversal().V().out("knows").out("knows").count().profile()
    step_names = [s.name for s in m.steps]
    # the final vstep fuses with count into one adjacency-count stage
    assert step_names[-1] == "vstep+count"
    assert step_names.count("vstep") == 1
    # 6 vertices -> 4 two-hop results -> count folds to 1 traverser
    assert m.steps[-1].count == 1
    assert m.total_ns > 0
    # own times sum to <= total
    assert sum(s.own_ns for s in m.steps) <= m.total_ns * 1.5
    text = m.render()
    assert "TOTAL" in text and "count" in text


def test_traversal_profile_compiled(graph):
    src = graph.traversal().with_computer("tpu")
    m = src.V().out("knows").count().profile()
    assert m.compiled
    assert m.steps[0].name == "olap(compiled)"
    assert "compiled OLAP" in m.render()


def test_profile_matches_unprofiled_result(graph):
    plain = graph.traversal().V().out("knows").count().next()
    m = graph.traversal().V().out("knows").count().profile()
    # profiling must not change semantics: the count step saw the same value
    assert plain == 5
    assert m.steps[-1].count == 1
