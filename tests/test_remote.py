"""Remote KCVS adapter: a storage node over HTTP + the client backend.

Modeled on the reference's distributed-adapter coverage (titan-cassandra /
titan-hbase module suites running the shared KCVS + graph suites against a
networked store): here the 'cluster' is an in-process KCVSServer, and the
graph opens it with storage.backend=remote — exercising the full
RPC + client-buffered-mutation + locking-over-eventually-consistent path.
"""

import numpy as np
import pytest

import titan_tpu
from titan_tpu.storage.api import Entry, KeyRangeQuery, KeySliceQuery, \
    SliceQuery, TTLEntry
from titan_tpu.storage.inmemory import InMemoryStoreManager
from titan_tpu.storage.remote import KCVSServer, RemoteStoreManager


@pytest.fixture
def node():
    server = KCVSServer(InMemoryStoreManager()).start()
    yield server
    server.stop()


@pytest.fixture
def mgr(node):
    return RemoteStoreManager("127.0.0.1", node.port)


def test_slice_roundtrip(mgr):
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    store.mutate(b"k", [Entry(b"a", b"1"), Entry(b"b", b"2")], [], txh)
    res = store.get_slice(KeySliceQuery(b"k", SliceQuery()), txh)
    assert res == [Entry(b"a", b"1"), Entry(b"b", b"2")]
    res = store.get_slice(KeySliceQuery(b"k", SliceQuery(b"b")), txh)
    assert res == [Entry(b"b", b"2")]
    store.mutate(b"k", [], [b"a"], txh)
    assert store.get_slice(KeySliceQuery(b"k", SliceQuery()), txh) == \
        [Entry(b"b", b"2")]


def test_multi_and_scan(mgr):
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    for i in range(40):
        store.mutate(b"k%03d" % i, [Entry(b"c", b"%d" % i)], [], txh)
    multi = store.get_slice_multi([b"k003", b"k007"], SliceQuery(), txh)
    assert multi[b"k003"] == [Entry(b"c", b"3")]
    rows = list(store.get_keys(
        KeyRangeQuery(b"k010", b"k020", SliceQuery()), txh))
    assert [k for k, _ in rows] == [b"k%03d" % i for i in range(10, 20)]
    # unordered full scan (paged)
    all_rows = list(store.get_keys(SliceQuery(), txh))
    assert len(all_rows) == 40


def test_ttl_passthrough(mgr):
    import time
    assert mgr.features.cell_ttl
    store = mgr.open_database("s")
    txh = mgr.begin_transaction()
    store.mutate(b"k", [TTLEntry(b"t", b"v", 0.05), Entry(b"p", b"w")], [], txh)
    time.sleep(0.08)
    res = store.get_slice(KeySliceQuery(b"k", SliceQuery()), txh)
    assert res == [Entry(b"p", b"w")]


def test_connection_failure_is_temporary():
    from titan_tpu.errors import TemporaryBackendError
    with pytest.raises(TemporaryBackendError):
        RemoteStoreManager("127.0.0.1", 1)   # nothing listening


def test_graph_over_remote_backend(node):
    g = titan_tpu.open({"storage.backend": "remote",
                        "storage.hostname": "127.0.0.1",
                        "storage.port": node.port})
    try:
        tx = g.new_transaction()
        a = tx.add_vertex("person", name="alice")
        b = tx.add_vertex("person", name="bob")
        a.add_edge("knows", b)
        aid = a.id
        tx.commit()
        assert g.traversal().V(aid).out("knows").count().next() == 1
        # locking + id authority run over the remote store (no native
        # transactions declared) — unique index enforcement proves it
        mgmt = g.management()
        key = mgmt.make_property_key("email", str)
        mgmt.build_index("byEmail", "vertex").add_key(key).unique() \
            .build_composite_index()
        mgmt.commit()
        tx2 = g.new_transaction()
        tx2.vertex(aid).property("email", "a@x")
        tx2.commit()
        from titan_tpu.errors import SchemaViolationError
        tx3 = g.new_transaction()
        tx3.add_vertex("person", name="eve", email="a@x")
        with pytest.raises(SchemaViolationError):
            tx3.commit()
    finally:
        g.close()


def test_olap_snapshot_over_remote(node):
    g = titan_tpu.open({"storage.backend": "remote",
                        "storage.hostname": "127.0.0.1",
                        "storage.port": node.port})
    try:
        from titan_tpu import example
        example.load(g)
        from titan_tpu.models import pagerank
        comp = g.compute()
        res = pagerank.run(comp, iterations=10)
        assert res.n == 12
        snap = comp.snapshot()
        assert snap.num_edges == 17
    finally:
        g.close()


def test_two_graph_instances_share_remote_node(node):
    cfg = {"storage.backend": "remote", "storage.hostname": "127.0.0.1",
           "storage.port": node.port}
    g1 = titan_tpu.open(dict(cfg, **{"graph.unique-instance-id": "r1"}))
    g2 = titan_tpu.open(dict(cfg, **{"graph.unique-instance-id": "r2"}))
    try:
        tx = g1.new_transaction()
        v = tx.add_vertex("person", name="shared")
        vid = v.id
        tx.commit()
        tx2 = g2.new_transaction()
        assert tx2.vertex(vid).value("name") == "shared"
        tx2.rollback()
        assert set(g1.management().get_open_instances()) == {"r1", "r2"}
    finally:
        g2.close()
        g1.close()
