"""LiveGraphPlane + serving integration (olap/live, ISSUE r9).

End-to-end freshness under writes: commits land in the device overlay
(base CSR cache untouched), vertex-set changes compact + republish,
the pool leases (snapshot, overlay-view) pairs at consistent epochs,
jobs report the epoch they ran at, and ``GET /live`` exposes the
``serving.live.*`` surface.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import titan_tpu
from titan_tpu.models.bfs_hybrid import frontier_bfs_batched
from titan_tpu.olap.api import JobSpec
from titan_tpu.olap.live import EpochCompactor, LiveGraphPlane
from titan_tpu.olap.serving.scheduler import JobScheduler
from titan_tpu.olap.tpu import snapshot as snap_mod


@pytest.fixture
def graph():
    g = titan_tpu.open("inmemory")
    tx = g.new_transaction()
    vs = [tx.add_vertex("node", name=f"v{i:02d}") for i in range(10)]
    for a, b in [(0, 1), (1, 2), (2, 3), (3, 4), (4, 5), (5, 6)]:
        vs[a].add_edge("link", vs[b])
    tx.commit()
    yield g
    g.close()


def _ids(g):
    tx = g.new_transaction()
    ids = sorted(v.id for v in tx.vertices())
    tx.rollback()
    return ids


def _vertex(tx, g, i):
    return tx.vertex(_ids(g)[i])


#: lax policy so tiny test graphs don't auto-compact on every delta
LAX = EpochCompactor(max_fill=0.99, max_tomb_fraction=0.99)


def test_edge_deltas_flow_through_overlay_not_rebuild(graph):
    plane = LiveGraphPlane(graph, compactor=LAX)
    try:
        snap0, v0, i0 = plane.lease_state()
        frontier_bfs_batched(snap0, [0], overlay=v0)
        cached = snap0._hybrid_csr
        tx = graph.new_transaction()
        _vertex(tx, graph, 6).add_edge("link", _vertex(tx, graph, 7))
        tx.commit()
        snap1, v1, i1 = plane.lease_state()
        assert snap1 is snap0              # no republish
        assert snap0._hybrid_csr is cached  # no device re-upload
        assert v1.count == 2 and i1["epoch"] == 0
        assert i1["applied_epoch"] == graph.mutation_epoch
        # results see the commit, bit-equal to a rebuild
        d_ov, _, _ = frontier_bfs_batched(snap1, [0], overlay=v1)
        rebuilt = snap_mod.build(graph, directed=False)
        d_rb, _, _ = frontier_bfs_batched(rebuilt, [0])
        assert (d_ov == d_rb).all()
    finally:
        plane.close()


def test_edge_removal_tombstones_then_compaction_folds(graph):
    plane = LiveGraphPlane(graph, compactor=LAX)
    try:
        snap0, _, _ = plane.lease_state()
        tx = graph.new_transaction()
        e = next(iter(_vertex(tx, graph, 0).out_edges("link")))
        e.remove()
        tx.commit()
        snap1, v1, i1 = plane.lease_state()
        assert snap1 is snap0 and v1.tomb_count == 2  # both rows
        d_ov, _, _ = frontier_bfs_batched(snap1, [0], overlay=v1)
        rebuilt = snap_mod.build(graph, directed=False)
        d_rb, _, _ = frontier_bfs_batched(rebuilt, [0])
        assert (d_ov == d_rb).all()
        assert plane.compact_if_dirty()
        snap2, v2, i2 = plane.lease_state()
        assert snap2 is not snap1 and v2.empty
        assert i2["epoch"] == i1["epoch"] + 1
        assert snap2.num_edges == rebuilt.num_edges
        d2, _, _ = frontier_bfs_batched(snap2, [0])
        assert (d2 == d_rb).all()
    finally:
        plane.close()


def test_vertex_change_triggers_compaction_republish(graph):
    plane = LiveGraphPlane(graph, compactor=LAX)
    try:
        snap0, _, i0 = plane.lease_state()
        tx = graph.new_transaction()
        w = tx.add_vertex("node", name="v99")
        _vertex(tx, graph, 2).add_edge("link", w)
        tx.commit()
        snap1, v1, i1 = plane.lease_state()
        assert snap1 is not snap0            # republished
        assert i1["epoch"] == i0["epoch"] + 1 and v1.empty
        fresh = snap_mod.build(graph, directed=False)
        assert snap1.n == fresh.n
        assert (snap1.vertex_ids == fresh.vertex_ids).all()
        assert (snap1.src == fresh.src).all()
        assert (snap1.dst == fresh.dst).all()
    finally:
        plane.close()


def test_pool_retires_leased_base_on_republish(graph):
    from titan_tpu.olap.serving.pool import SnapshotPool

    plane = LiveGraphPlane(graph, compactor=LAX)
    pool = SnapshotPool(live=plane)
    try:
        lease = pool.acquire()
        old = lease.snapshot
        edges_before = old.num_edges
        assert lease.overlay is not None and lease.epoch_info is not None
        # vertex add → compaction → republish while the lease is out
        tx = graph.new_transaction()
        tx.add_vertex("node", name="v98")
        tx.commit()
        with pool.acquire() as snap2:
            assert snap2 is not old
        assert pool.stats()["retired"] == 1
        assert old.num_edges == edges_before   # leased arrays untouched
        lease.release()
        assert pool.stats()["retired"] == 0
    finally:
        pool.close()
        plane.close()


def test_overlay_budget_compaction_and_metrics(graph):
    from titan_tpu.utils.metrics import MetricManager

    metrics = MetricManager()
    plane = LiveGraphPlane(graph, metrics=metrics,
                           compactor=EpochCompactor(
                               max_fill=0.99, max_tomb_fraction=0.1))
    try:
        # removals push the tombstone fraction over 0.2 → auto-compact
        tx = graph.new_transaction()
        for e in list(_vertex(tx, graph, 1).out_edges("link")):
            e.remove()
        tx.commit()
        _, view, info = plane.lease_state()
        assert info["epoch"] >= 1 and view.empty
        st = plane.stats()
        assert st["counters"]["compactions"] >= 1
        assert st["counters"]["edges_tombstoned"] >= 1
        assert st["freshness"]["lag_epochs"] == 0
        assert st["apply_ms"]["count"] >= 1
    finally:
        plane.close()


def test_scheduler_jobs_under_writes_report_epoch(graph):
    plane = LiveGraphPlane(graph, compactor=LAX)
    sched = JobScheduler(live=plane)
    try:
        ids = _ids(graph)
        j1 = sched.submit(JobSpec(kind="bfs",
                                  params={"source": ids[0]}))
        assert j1.wait(60) and j1.result is not None
        r1 = j1.result["reached"]
        tx = graph.new_transaction()
        _vertex(tx, graph, 6).add_edge("link", _vertex(tx, graph, 7))
        tx.commit()
        j2 = sched.submit(JobSpec(kind="bfs",
                                  params={"source": ids[0]}))
        assert j2.wait(60) and j2.result is not None
        assert j2.result["reached"] == r1 + 1       # fresh, no rebuild
        assert j2.ran_epoch["seq"] > j1.ran_epoch["seq"] \
            or j2.ran_epoch["epoch"] > j1.ran_epoch["epoch"]
        assert "epoch" in j2.to_wire()
        # pagerank compacts before running (dense fallback) and still
        # completes under the dirty overlay
        j3 = sched.submit(JobSpec(kind="pagerank",
                                  params={"iterations": 2}))
        assert j3.wait(60), j3.error
        assert j3.state.value == "done", j3.error
        assert j3.ran_epoch["seq"] == 0             # compacted lease
        # wcc over the (possibly clean) overlay
        j4 = sched.submit(JobSpec(kind="wcc"))
        assert j4.wait(60) and j4.state.value == "done", j4.error
    finally:
        sched.close()          # closes the plane too


def test_get_live_endpoint(graph):
    from titan_tpu.server import GraphServer

    plane = LiveGraphPlane(graph, compactor=LAX)
    sched = JobScheduler(live=plane)
    srv = GraphServer(graph, port=0, scheduler=sched).start()
    try:
        def req(path):
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}{path}",
                    timeout=30) as resp:
                return json.loads(resp.read())

        tx = graph.new_transaction()
        _vertex(tx, graph, 3).add_edge("link", _vertex(tx, graph, 8))
        tx.commit()
        plane.pump()
        live = req("/live")
        assert live["enabled"] is True
        assert live["overlay"]["adds"] == 2
        for key in ("freshness", "counters", "apply_ms", "compact_ms"):
            assert key in live
        assert live["freshness"]["lag_epochs"] == 0
    finally:
        srv.stop()


def test_plane_background_pump_and_concurrent_writers(graph):
    """Writers hammer commits while the pump ingests in the background;
    the final lease must converge to the rebuilt truth."""
    plane = LiveGraphPlane(graph, compactor=LAX,
                           poll_interval_s=0.01)
    errors: list = []

    def writer(k):
        try:
            rng = np.random.default_rng(k)
            ids = _ids(graph)
            for _ in range(8):
                tx = graph.new_transaction()
                a, b = rng.choice(len(ids), 2, replace=False)
                tx.vertex(ids[int(a)]).add_edge(
                    "link", tx.vertex(ids[int(b)]))
                tx.commit()
        except Exception as e:     # pragma: no cover - fail loud
            errors.append(repr(e))

    try:
        ts = [threading.Thread(target=writer, args=(k,))
              for k in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errors, errors
        snap, view, info = plane.lease_state()
        assert info["applied_epoch"] == graph.mutation_epoch
        d_ov, _, _ = frontier_bfs_batched(snap, [0], overlay=view)
        rebuilt = snap_mod.build(graph, directed=False)
        d_rb, _, _ = frontier_bfs_batched(rebuilt, [0])
        assert (d_ov == d_rb).all()
    finally:
        plane.close()


def test_resync_on_listener_overflow_reanchors(graph):
    """Listener overflow → full re-scan; the SAME queue resumes
    accumulating afterwards (ChangeQueue.reanchor — the ISSUE r9
    satellite), so the next delta takes the overlay path again."""
    plane = LiveGraphPlane(graph, compactor=LAX)
    try:
        plane._queue.overflowed = True      # simulate >cap backlog
        tx = graph.new_transaction()
        _vertex(tx, graph, 0).add_edge("link", _vertex(tx, graph, 9))
        tx.commit()                          # dropped by the dead queue
        snap1, v1, i1 = plane.lease_state()
        assert i1["epoch"] >= 1 and v1.empty          # resynced
        assert plane.stats()["counters"]["resyncs"] == 1
        assert not plane._queue.overflowed            # re-anchored
        # the next commit flows through the overlay again
        tx = graph.new_transaction()
        _vertex(tx, graph, 1).add_edge("link", _vertex(tx, graph, 8))
        tx.commit()
        snap2, v2, i2 = plane.lease_state()
        assert snap2 is snap1 and v2.count == 2
        assert plane.stats()["counters"]["resyncs"] == 1  # no new scan
    finally:
        plane.close()
