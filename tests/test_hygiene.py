"""Package-tree hygiene (ISSUE 14 satellite).

An aborted build once left ``titan_tpu/olap/serving/fleet/`` behind as
a directory containing nothing but a stale ``__pycache__`` — invisible
to imports, confusing to every reader, and a trap for tooling that
walks the tree. This guard keeps the package tree honest:

* every directory under ``titan_tpu/`` that contains ``.py`` files is a
  real package (has ``__init__.py``) — a module that cannot be imported
  is dead code wearing a live extension;
* no directory under ``titan_tpu/`` is pycache-only (its only contents,
  recursively, are ``__pycache__`` artifacts) — compiled leftovers must
  not outlive the source tree that produced them.
"""

import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "titan_tpu")


def _real_contents(dirpath: str) -> bool:
    """True when the tree under ``dirpath`` holds anything that is not
    a ``__pycache__`` artifact."""
    for root, dirnames, filenames in os.walk(dirpath):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        if filenames:
            return True
    return False


def test_every_py_dir_is_a_package():
    missing = []
    for dirpath, dirnames, filenames in os.walk(_PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        if any(f.endswith(".py") for f in filenames) \
                and "__init__.py" not in filenames:
            missing.append(os.path.relpath(dirpath, _REPO))
    assert not missing, (
        f"directories with .py files but no __init__.py: {missing} — "
        f"either make them packages or remove the orphans")


def test_no_pycache_only_directories():
    ghosts = []
    for dirpath, dirnames, filenames in os.walk(_PKG):
        if "__pycache__" in dirnames and not _real_contents(dirpath):
            ghosts.append(os.path.relpath(dirpath, _REPO))
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
    assert not ghosts, (
        f"pycache-only directories (stale build leftovers): {ghosts} — "
        f"delete them; compiled artifacts must not outlive their "
        f"source")
