"""Test configuration.

Force JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths
(parallel/, olap/tpu/) are exercised without TPU hardware — the same trick
the driver's dryrun uses. NOTE: this environment's sitecustomize registers
an ``axon`` TPU backend and overrides JAX_PLATFORMS, so the env var alone is
not enough — the config update after import is what actually pins CPU.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent XLA compile cache, shared with bench.py (.bench_cache/xla):
# serial-CPU tier-1 is budgeted (870 s) and DOMINATED by XLA compiles,
# not compute — a warm cache cuts the suite by minutes. Threshold 0:
# test-scale kernels compile fast individually but number in the
# hundreds, so even sub-second entries pay for themselves.
from titan_tpu.utils.jitcache import enable_compile_cache  # noqa: E402

enable_compile_cache()
try:
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
except Exception:
    pass

