"""Test configuration.

Force JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths
(parallel/, olap/tpu/) are exercised without TPU hardware — the same trick
the driver's dryrun uses. NOTE: this environment's sitecustomize registers
an ``axon`` TPU backend and overrides JAX_PLATFORMS, so the env var alone is
not enough — the config update after import is what actually pins CPU.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_load_initial_conftests(early_config, parser, args):
    """Default the suite onto 4 xdist workers (687s -> 214s measured)
    WITHOUT hard-requiring the plugin: plain pytest keeps working when
    pytest-xdist is absent, and an explicit -n/--numprocesses wins."""
    if any(a == "-n" or a.startswith("-n") or a.startswith("--numprocesses")
           or a == "no:xdist" for a in args):
        return
    try:
        import xdist  # noqa: F401
    except ImportError:
        return
    args += ["-n", "4"]
