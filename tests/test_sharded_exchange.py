"""Sharded BFS exchange rebuild (ISSUE 13): fused per-level dispatch,
explicit shardings, mesh-aware batch placement.

Property suite for the rebuilt sharded data plane:

* bit-equality of the fused sharded BFS vs the single-chip hybrid on
  the in-process 8-device mesh AND on 1/2-device meshes in subprocesses
  (``XLA_FLAGS=--xla_force_host_platform_device_count={1,2}`` must be
  pinned before jax initializes, so those run out of process — the
  pattern the multihost dryrun uses; the main session keeps its
  conftest-forced 8 devices);
* the per-level dispatch budget: ≤ 2 ``device.exec.calls`` per level
  (1 fused kernel + at most one exchange-cap retry), asserted through
  the DeviceCostProfiler, plus ZERO new compile buckets on the warm
  smoke shape;
* the sparse exchange invariant (caps track the actual per-chip
  discovery maxima — O(frontier) communication);
* mesh-aware batched placement (``parallel/partition.place_batched_csr``
  + ``JobScheduler(mesh=)``): [K, n] cohorts bit-equal over the mesh,
  HBM ledger charged the PER-DEVICE share;
* ``parallel/mesh.global_sum``'s explicit axis-environment check: a
  misspelled axis name raises instead of silently summing per shard.

Shared shape discipline: the module's graphs reuse two fixed shapes
(an rmat scale-9 sym graph and the n=255/m=900/seed-42 serving shape)
so XLA compile buckets are shared across tests (tier-1 is
compile-bound; see tests/conftest.py).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from titan_tpu.models import bfs_hybrid_sharded as S
from titan_tpu.models.bfs import frontier_bfs
from titan_tpu.models.bfs_hybrid import (build_chunked_csr,
                                         frontier_bfs_batched,
                                         frontier_bfs_hybrid)
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.olap.tpu.rmat import rmat_edges
from titan_tpu.parallel.mesh import vertex_mesh


def sym_snap_from(src, dst, n):
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


@pytest.fixture(scope="module")
def rmat9():
    src, dst = rmat_edges(9, 8, seed=3)
    snap = sym_snap_from(src, dst, 1 << 9)
    source = int(np.flatnonzero(snap.out_degree > 0)[0])
    d_ref, lv_ref = frontier_bfs_hybrid(snap, source)
    return snap, source, np.asarray(d_ref), lv_ref


@pytest.fixture(scope="module")
def serving_snap():
    """The n=255/m=900 shape: n+1 = 256 divides over 8 devices, so the
    mesh-placed [K, n+1] state genuinely shards."""
    rng = np.random.default_rng(42)
    n, m = 255, 900
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return sym_snap_from(src, dst, n)


# ------------------------------------------------------- fused sharded BFS

def test_fused_sharded_bit_equal_and_dispatch_budget_8dev(rmat9):
    """Bit-equality on the 8-device mesh, then the ISSUE-13 acceptance
    bound via the device-cost profiler: a WARM sharded run (kernels
    compiled by the first pass) spends ≤2 device dispatches per level
    on the shx_* kernels and mints ZERO new XLA compile buckets."""
    from titan_tpu.obs.devprof import DeviceCostProfiler

    snap, source, d_ref, lv_ref = rmat9
    mesh = vertex_mesh(8)
    d_sh, lv = S.frontier_bfs_hybrid_sharded(snap, source, mesh)
    assert (np.asarray(d_sh) == d_ref).all()
    assert lv == lv_ref
    # every level was ONE fused dispatch (+ rare retry)
    assert S.LAST_PROFILE, "comm-profile instrumentation missing"
    assert all(p["dispatches"] == 1 + p["retries"]
               for p in S.LAST_PROFILE)
    # warm pass under the profiler
    prof = DeviceCostProfiler()
    with prof:
        d_sh, _lv = S.frontier_bfs_hybrid_sharded(snap, source, mesh)
    assert (np.asarray(d_sh) == d_ref).all()
    disp = [p["dispatches"] for p in S.LAST_PROFILE]
    assert max(disp) <= 2, f"per-level dispatch budget blown: {disp}"
    shx = {k: v for k, v in prof.kernel_stats().items()
           if k.startswith("shx_")}
    assert shx, "sharded kernels did not run through the profiler shim"
    assert sum(v["calls"] for v in shx.values()) == sum(disp)
    # warm shape: no new static shape buckets (found_guess seeds from
    # the source degree, so the cap trail is deterministic per graph)
    assert prof.compiles() == 0, prof.compile_log()


def test_exchange_stays_sparse_on_path():
    """O(frontier) invariant: a path graph's frontier is ONE vertex per
    level, so every exchange cap stays tiny regardless of n — and the
    per-shard edge arrays are genuinely partitioned."""
    n = 96
    src = np.arange(n - 1, dtype=np.int32)
    snap = sym_snap_from(src, src + 1, n)
    mesh = vertex_mesh(8)
    d_sh, levels = S.frontier_bfs_hybrid_sharded(snap, 0, mesh)
    d_ref, _ = frontier_bfs(snap, 0)
    assert (np.asarray(d_sh) == d_ref).all()
    assert levels in (n - 1, n)
    assert S.LAST_EXCHANGE_CAPS and max(S.LAST_EXCHANGE_CAPS) <= 8 < n
    sh = S.shard_chunked_csr(build_chunked_csr(snap), 8)
    assert sh["dstT_sh"].shape[0] == 8
    assert sh["q_max"] <= sh["q_total"]
    assert sh["layout"].num_shards == 8
    assert sh["layout"].balance() >= 1.0


_CHILD = r"""
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import sys
ndev = int(sys.argv[1])
assert jax.device_count() == ndev, (jax.device_count(), ndev)
from titan_tpu.utils.jitcache import enable_compile_cache
enable_compile_cache()
try:
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
except Exception:
    pass
from titan_tpu.models import bfs_hybrid_sharded as S
from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.olap.tpu.rmat import rmat_edges
from titan_tpu.parallel.mesh import vertex_mesh
src, dst = rmat_edges(8, 8, seed=5)
snap = snap_mod.from_arrays(1 << 8, np.concatenate([src, dst]),
                            np.concatenate([dst, src]))
source = int(np.flatnonzero(snap.out_degree > 0)[0])
d_ref, lv_ref = frontier_bfs_hybrid(snap, source)
mesh = vertex_mesh(ndev)
d_sh, lv = S.frontier_bfs_hybrid_sharded(snap, source, mesh)
assert (np.asarray(d_sh) == np.asarray(d_ref)).all(), "dist diverged"
assert lv == lv_ref, (lv, lv_ref)
disp = [p["dispatches"] for p in S.LAST_PROFILE]
assert max(disp) <= 2, disp
print(f"SHARDED_CHILD_OK ndev={ndev} levels={lv} max_disp={max(disp)}")
"""


@pytest.mark.parametrize("ndev", [
    pytest.param(1, marks=pytest.mark.slow), 2])
def test_sharded_bit_equal_forced_devices_subprocess(ndev):
    """1- and 2-device meshes need their own processes: the forced
    host device count is an XLA init-time flag, and this session is
    pinned to 8 (conftest). Same pattern as the multihost dryrun.
    Tier-1 budget note: the 1-device case rides the slow tier — the
    1-device mesh path also runs on every CPU bench (`bfs23_sharded`
    stage) and in `experiments/sharded_1dev.py`; tier-1 keeps the
    genuinely-multi-device forced-2 case (8 runs in-process above)."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(
        kept + [f"--xla_force_host_platform_device_count={ndev}"])
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "TPU_", "AXON_")):
            env.pop(k)
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD, str(ndev)], cwd=here, env=env,
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert f"SHARDED_CHILD_OK ndev={ndev}" in proc.stdout, proc.stdout


# ------------------------------------------- mesh-aware batch placement

def test_mesh_placed_batched_cohort_bit_equal(serving_snap):
    """place_batched_csr + the UNCHANGED batched kernels: a [K, n]
    cohort over the 8-device mesh is bit-equal to the single-device
    run, with the dist state genuinely sharded P(None, "v")."""
    from titan_tpu.parallel.partition import place_batched_csr

    snap = serving_snap
    mesh = vertex_mesh(8)
    sources = [0, 5, 9, 11]
    d_ref, lv_ref, comp_ref = frontier_bfs_batched(snap, sources)
    placed = place_batched_csr(snap, mesh)
    assert "_state_sharding" in placed       # 256 % 8 == 0
    assert placed["dstT"].shape[1] % 8 == 0  # column pad to D multiple
    d_m, lv_m, comp_m = frontier_bfs_batched(placed, sources)
    assert (d_m == d_ref).all()
    assert (lv_m == lv_ref).all() and (comp_m == comp_ref).all()
    # placement is cached per mesh on the graph dict
    assert place_batched_csr(snap, mesh) is placed


def test_scheduler_mesh_cohort_and_per_device_ledger(serving_snap):
    """JobScheduler(mesh=): the fused cohort runs placed, results stay
    bit-equal per job, and the HBM ledger charges the PER-DEVICE share
    of the sharded image, not the whole thing."""
    from titan_tpu.olap.api import JobSpec
    from titan_tpu.olap.serving.hbm import (meshed_snapshot_csr_bytes,
                                            snapshot_csr_bytes)
    from titan_tpu.olap.serving.scheduler import JobScheduler

    snap = serving_snap
    mesh = vertex_mesh(8)
    per_dev = meshed_snapshot_csr_bytes(snap, 8)
    assert per_dev < snapshot_csr_bytes(snap)
    sched = JobScheduler(snapshot=snap, mesh=mesh)
    try:
        sources = [0, 5, 9, 11]
        jobs = [sched.submit(JobSpec(kind="bfs",
                                     params={"source_dense": s}))
                for s in sources]
        for j in jobs:
            assert j.wait(180), "mesh cohort did not finish"
        assert all(j.state.value == "done" for j in jobs)
        for j, s in zip(jobs, sources):
            d_ref, _ = frontier_bfs_hybrid(snap, s)
            assert (j.result["dist"] == np.asarray(d_ref)).all()
        assert sched.ledger.resident_bytes() == per_dev
        assert sched._dump_config()["mesh_devices"] == 8
    finally:
        sched.close()


# --------------------------------------------------- global_sum axis check

def test_global_sum_explicit_axis_check():
    """parallel/mesh.global_sum (ISSUE 13 satellite): under the "v"
    mesh it psums the FULL vertex axis; under a mesh whose axis names
    don't include "v" it RAISES (the old NameError swallow silently
    returned a per-shard sum for misspelled axis names); with no axis
    bound it is a plain sum."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from titan_tpu.parallel.mesh import (axis_bound, bound_axes,
                                         global_sum, shard_map_compat)

    x = jnp.arange(16.0)
    # no mesh: plain sum, no axis bound
    assert not axis_bound() and bound_axes() == ()
    assert float(global_sum(x)) == float(x.sum())

    mesh = vertex_mesh(8)
    f = shard_map_compat(lambda s: global_sum(s), mesh=mesh,
                         in_specs=(P("v"),), out_specs=P())
    assert float(jax.jit(f)(x)) == float(x.sum())   # FULL sum, per shard 2 elems

    wrong = Mesh(np.array(jax.devices()[:8]), ("x",))
    g = shard_map_compat(lambda s: global_sum(s), mesh=wrong,
                         in_specs=(P("x"),), out_specs=P())
    with pytest.raises(ValueError, match="bound mapped axes"):
        jax.jit(g)(x)


def test_block_layout_descriptor():
    """parallel/partition.BlockLayout: the one layout definition the
    sharded CSR carries — bounds cover [0, n], caps match the packed
    arrays, describe() is json-able."""
    import json

    from titan_tpu.parallel.partition import BlockLayout, block_layout

    n = 1 << 9
    rng = np.random.default_rng(7)
    degc = rng.integers(0, 5, n).astype(np.int64)
    colstart = np.zeros(n + 1, np.int64)
    np.cumsum(degc, out=colstart[1:])
    lay = block_layout(colstart, degc.astype(np.int32), n, 8)
    assert isinstance(lay, BlockLayout)
    assert lay.bounds[0] == 0 and lay.bounds[-1] == n
    assert len(lay.bounds) == 9
    lo, hi = lay.block_window(0)
    assert 0 == lo < hi <= n
    assert hi - lo <= lay.b_max
    assert max(lay.shard_chunks) < lay.q_max
    json.dumps(lay.describe())
