"""k-core and HITS models vs numpy references, single- and multi-device."""

import numpy as np
import pytest

from titan_tpu.models import hits as hits_mod
from titan_tpu.models import kcore
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.olap.tpu.engine import TPUGraphComputer


def _random_graph(n=120, e=700, seed=4):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    keep = src != dst
    return src[keep], dst[keep], n


def np_kcore(n, src, dst, k):
    """Peeling on the symmetrized multigraph (matches the engine's
    message-count semantics: parallel edges count separately)."""
    alive = np.ones(n, bool)
    while True:
        deg = np.zeros(n, np.int64)
        m = alive[src] & alive[dst]
        np.add.at(deg, dst[m], 1)
        np.add.at(deg, src[m], 1)
        new_alive = alive & (deg >= k)
        if np.array_equal(new_alive, alive):
            return alive
        alive = new_alive


def np_hits(n, src, dst, iterations):
    hub = np.ones(n)
    auth = np.ones(n)
    for _ in range(iterations):
        auth_new = np.zeros(n)
        np.add.at(auth_new, dst, hub[src])
        auth = auth_new / (np.linalg.norm(auth_new) or 1.0)
        hub_new = np.zeros(n)
        np.add.at(hub_new, src, auth[dst])
        hub = hub_new / (np.linalg.norm(hub_new) or 1.0)
    return hub, auth


@pytest.mark.parametrize("ndev", [1, 8])
@pytest.mark.parametrize("k", [2, 4])
def test_kcore_matches_numpy(ndev, k):
    src, dst, n = _random_graph()
    s2, d2 = np.concatenate([src, dst]), np.concatenate([dst, src])
    snap = snap_mod.from_arrays(n, s2, d2)
    comp = TPUGraphComputer(snapshot=snap, num_devices=ndev)
    res = kcore.run(comp, k, snapshot=snap)
    ref = np_kcore(n, src, dst, k)
    assert np.array_equal(np.asarray(res["in_core"]), ref)


@pytest.mark.parametrize("ndev", [1, 8])
def test_hits_matches_numpy(ndev):
    src, dst, n = _random_graph(seed=9)
    snap = hits_mod.bidirectional_snapshot(n, src, dst)
    comp = TPUGraphComputer(snapshot=snap, num_devices=ndev)
    res = comp.run(hits_mod.HITS(iterations=12), params={}, snapshot=snap)
    ref_hub, ref_auth = np_hits(n, src, dst, 12)
    np.testing.assert_allclose(np.asarray(res["hub"]), ref_hub,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(res["auth"]), ref_auth,
                               rtol=1e-4, atol=1e-5)


def test_hits_top_authority_is_popular(seed=3):
    # star graph: everything points at vertex 0
    n = 30
    src = np.arange(1, n, dtype=np.int32)
    dst = np.zeros(n - 1, np.int32)
    snap = hits_mod.bidirectional_snapshot(n, src, dst)
    comp = TPUGraphComputer(snapshot=snap, num_devices=1)
    res = comp.run(hits_mod.HITS(iterations=8), params={}, snapshot=snap)
    assert int(np.argmax(np.asarray(res["auth"]))) == 0
    assert np.asarray(res["hub"])[0] == pytest.approx(0.0, abs=1e-6)


def test_run_helpers_from_graph_computer():
    """The no-snapshot entry points build the right snapshot shapes: k-core
    symmetrizes, HITS synthesizes the bidirectional fwd-flagged layout."""
    import titan_tpu
    from titan_tpu import example
    g = titan_tpu.open("inmemory")
    example.load(g)
    comp = g.compute()
    core = kcore.run(comp, 2)
    snap = comp.snapshot(directed=False)
    in_core = np.asarray(core["in_core"])
    # the jupiter/neptune/pluto brother-triangle survives 2-core peeling
    tx = g.new_transaction()
    names = {snap.dense_of(v.id): v.value("name") for v in tx.vertices()}
    tx.rollback()
    assert {"jupiter", "neptune", "pluto"} <= \
        {names[i] for i in np.flatnonzero(in_core)}
    res = hits_mod.run(comp, iterations=8)
    assert np.asarray(res["auth"]).shape == (snap.n,)
    assert np.asarray(res["auth"]).max() > 0
    g.close()


def test_kcore_chain_has_no_2core():
    n = 10
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    s2, d2 = np.concatenate([src, dst]), np.concatenate([dst, src])
    snap = snap_mod.from_arrays(n, s2, d2)
    comp = TPUGraphComputer(snapshot=snap, num_devices=1)
    res = kcore.run(comp, 2, snapshot=snap)
    assert not np.asarray(res["in_core"]).any()   # chains peel completely
