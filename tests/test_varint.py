"""Varint codec tests (semantics modeled on the reference's VariableLongTest)."""

import random

import numpy as np
import pytest

from titan_tpu.utils import varint


EDGE_VALUES = [0, 1, 2, 63, 64, 127, 128, 129, (1 << 14) - 1, 1 << 14,
               (1 << 21) - 1, 1 << 21, (1 << 42), (1 << 63) - 1]


def test_positive_roundtrip():
    rng = random.Random(7)
    values = EDGE_VALUES + [rng.getrandbits(rng.randint(1, 63)) for _ in range(500)]
    buf = bytearray()
    spans = []
    for v in values:
        start = len(buf)
        varint.write_positive(buf, v)
        spans.append((v, start, len(buf)))
        assert len(buf) - start == varint.positive_length(v)
    for v, start, end in spans:
        got, pos = varint.read_positive(buf, start)
        assert got == v and pos == end


def test_positive_rejects_negative():
    with pytest.raises(ValueError):
        varint.write_positive(bytearray(), -1)


def test_order_preserving_within_length():
    # equal-length encodings must compare byte-wise like their values
    rng = random.Random(3)
    for _ in range(200):
        bits = rng.randint(1, 62)
        a = rng.getrandbits(bits)
        b = rng.getrandbits(bits)
        ba, bb = bytearray(), bytearray()
        varint.write_positive(ba, a)
        varint.write_positive(bb, b)
        if len(ba) == len(bb):
            assert (bytes(ba) < bytes(bb)) == (a < b)


def test_signed_roundtrip():
    rng = random.Random(11)
    values = [0, -1, 1, -(1 << 62), (1 << 62)] + \
             [rng.getrandbits(62) * (1 if rng.random() < .5 else -1) for _ in range(300)]
    for v in values:
        buf = bytearray()
        varint.write_signed(buf, v)
        got, pos = varint.read_signed(buf, 0)
        assert got == v and pos == len(buf)


def test_backward_roundtrip():
    rng = random.Random(13)
    values = EDGE_VALUES + [rng.getrandbits(rng.randint(1, 63)) for _ in range(300)]
    buf = bytearray()
    spans = []
    for v in values:
        start = len(buf)
        varint.write_positive_backward(buf, v)
        spans.append((v, start, len(buf)))
    # read each value backwards from its end offset
    for v, start, end in spans:
        got, s = varint.read_positive_backward(buf, end)
        assert got == v and s == start
    # signed backward
    for v in [-5, 5, 0, -(1 << 40), 1 << 40]:
        b = bytearray()
        varint.write_signed_backward(b, v)
        got, s = varint.read_signed_backward(b, len(b))
        assert got == v and s == 0


def test_prefixed_roundtrip():
    rng = random.Random(17)
    for _ in range(400):
        pbits = rng.randint(1, 6)
        prefix = rng.getrandbits(pbits)
        value = rng.getrandbits(rng.randint(1, 50))
        buf = bytearray()
        varint.write_positive_with_prefix(buf, value, prefix, pbits)
        got_v, got_p, pos = varint.read_positive_with_prefix(buf, 0, pbits)
        assert (got_v, got_p, pos) == (value, prefix, len(buf))


def test_prefixed_order_within_prefix():
    # same prefix, equal length ⇒ byte order == value order
    rng = random.Random(19)
    for _ in range(200):
        bits = rng.randint(1, 40)
        a, b = rng.getrandbits(bits), rng.getrandbits(bits)
        ba, bb = bytearray(), bytearray()
        varint.write_positive_with_prefix(ba, a, 2, 3)
        varint.write_positive_with_prefix(bb, b, 2, 3)
        if len(ba) == len(bb):
            assert (bytes(ba) < bytes(bb)) == (a < b)


def test_bulk_read_matches_scalar():
    rng = random.Random(23)
    values = [rng.getrandbits(rng.randint(1, 62)) for _ in range(2000)]
    buf = bytearray()
    offsets = []
    for v in values:
        offsets.append(len(buf))
        varint.write_positive(buf, v)
    data = np.frombuffer(bytes(buf), dtype=np.uint8)
    got, ends = varint.bulk_read_positive(data, np.array(offsets))
    assert got.tolist() == values
    expected_ends = offsets[1:] + [len(buf)]
    assert ends.tolist() == expected_ends
