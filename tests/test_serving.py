"""OLAP serving layer: batched multi-source execution + scheduler paths.

The acceptance contract (ISSUE r7): >= 8 concurrent same-snapshot BFS
jobs fuse into ONE batched [K, n] device run whose per-job rows are
bit-equal to K sequential single-source runs, with cancellation /
deadline / admission / timeout paths covered and per-job latency +
batch-occupancy metrics exported through utils/metrics.
"""

import time

import numpy as np
import pytest

from titan_tpu.olap.api import JobSpec
from titan_tpu.olap.serving.hbm import HBMLedger, chunked_csr_bytes
from titan_tpu.olap.serving.scheduler import JobScheduler
from titan_tpu.olap.tpu import snapshot as snap_mod
from titan_tpu.utils.metrics import MetricManager


# ONE vertex-count across the file: the batched/hybrid kernels compile
# per power-of-two capacity bucket, and CPU XLA compiles dominate this
# suite's runtime — distinct random n per test would recompile
# everything (tier-1 is serial and budgeted)
_N = 192


def _sym_snapshot(seed: int, n: int = _N, m: int = 900):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))


@pytest.fixture(scope="module")
def snap_main():
    return _sym_snapshot(42)


@pytest.fixture
def metrics():
    return MetricManager()     # isolated registry (not the singleton)


def _await_counter(metrics, name, want, timeout=10.0):
    """Job.wait() fires at the state transition (inside the batch); the
    worker finalizes counters just after — poll briefly before
    asserting."""
    deadline = time.time() + timeout
    while time.time() < deadline and metrics.counter_value(name) < want:
        time.sleep(0.01)
    return metrics.counter_value(name)


# --------------------------------------------------------------------------
# batched kernel: bit-equality property + early-exit masks
# --------------------------------------------------------------------------

@pytest.mark.parametrize(
    "seed", [0, pytest.param(1, marks=pytest.mark.slow)])
def test_batched_bfs_bit_equal_to_sequential(seed):
    """Property: every row of a K-way batched run equals the sequential
    single-source hybrid BFS from that source (duplicate sources
    included — BFS distances are canonical). Random edges per seed; a
    sparse second graph (m < n) exercises multi-component frontiers and
    isolated-ish sources."""
    from titan_tpu.models.bfs_hybrid import (frontier_bfs_batched,
                                             frontier_bfs_hybrid)

    snap = _sym_snapshot(seed, m=900 if seed == 0 else 150)
    rng = np.random.default_rng(100 + seed)
    nz = np.flatnonzero(snap.out_degree > 0)
    # K = 8 everywhere in this suite: each distinct K is a separate
    # XLA compile of the three batched kernels (CPU compiles dominate)
    K = 8
    sources = [int(s) for s in rng.choice(nz, size=K, replace=True)]
    dist, levels, completed = frontier_bfs_batched(snap, sources)
    assert completed.all()
    assert dist.shape == (K, snap.n)
    for k, s in enumerate(sources):
        ref, _ = frontier_bfs_hybrid(snap, s)
        assert (dist[k] == np.asarray(ref)).all(), f"job {k} source {s}"


def test_batched_bfs_on_level_early_exit_mask():
    """A job dropped via the on_level keep mask stops exactly at that
    level (its dist stays partial, completed=False) while the surviving
    jobs finish bit-equal to sequential runs."""
    from titan_tpu.models.bfs import INF
    from titan_tpu.models.bfs_hybrid import (frontier_bfs_batched,
                                             frontier_bfs_hybrid)

    n = 50   # path graph: distances grow one level at a time
    es = np.arange(n - 1, dtype=np.int32)
    ed = es + 1
    snap = snap_mod.from_arrays(n, np.concatenate([es, ed]),
                                np.concatenate([ed, es]))
    seen = []

    def on_level(level, nf):
        seen.append((level, nf.tolist()))
        if level >= 2:
            return np.array([False, True])
        return None

    dist, levels, completed = frontier_bfs_batched(
        snap, [0, n - 1], on_level=on_level)
    assert not completed[0] and completed[1]
    assert levels[0] == 2
    # job 0 explored exactly levels 0 and 1 before the drop
    assert dist[0][0] == 0 and dist[0][2] == 2
    assert (dist[0][3:] >= int(INF)).all()
    ref, _ = frontier_bfs_hybrid(snap, n - 1)
    assert (dist[1] == np.asarray(ref)).all()
    # the callback saw per-job frontier counts every level
    assert seen[0][0] == 0 and seen[0][1] == [1, 1]


def test_batched_bfs_rejects_bad_sources(snap_main):
    from titan_tpu.models.bfs_hybrid import frontier_bfs_batched

    snap = snap_main
    with pytest.raises(IndexError):
        frontier_bfs_batched(snap, [0, snap.n + 5])
    with pytest.raises(ValueError):
        frontier_bfs_batched(snap, [])


# --------------------------------------------------------------------------
# scheduler: fusion, terminal paths, metrics
# --------------------------------------------------------------------------

def test_scheduler_fuses_eight_plus_jobs_and_results_match(metrics, snap_main):
    """>= 8 queued same-snapshot BFS jobs execute as ONE batch (every
    job reports the same batch_k >= 8) and each result is bit-equal to
    its sequential reference; latency/queue/occupancy metrics land in
    the registry."""
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid

    snap = snap_main
    nz = np.flatnonzero(snap.out_degree > 0)
    K = 8
    sched = JobScheduler(snapshot=snap, metrics=metrics, autostart=False)
    try:
        jobs = [sched.submit(JobSpec(kind="bfs",
                                     params={"source_dense": int(s)}))
                for s in nz[:K]]
        assert metrics.counter_value("serving.queue.depth") == K
        sched.start()
        for job in jobs:
            assert job.wait(60), job
            assert job.state.value == "done", (job, job.error)
        assert all(j.batch_k >= 8 for j in jobs), [j.batch_k for j in jobs]
        for job in jobs:
            ref, _ = frontier_bfs_hybrid(
                snap, int(job.spec.params["source_dense"]))
            assert (job.result["dist"] == np.asarray(ref)).all()
            assert job.result["reached"] == int(
                (np.asarray(ref) < (1 << 30)).sum())
        # metrics: occupancy recorded the fused width; per-job latency
        assert _await_counter(metrics, "serving.jobs.completed", K) == K
        occ = metrics.histogram("serving.batch.occupancy")
        assert occ.count >= 1 and occ.max >= 8
        lat = metrics.histogram("serving.job.latency_ms")
        assert lat.count == K and lat.percentile(50) > 0 \
            and lat.percentile(95) >= lat.percentile(50)
        assert metrics.counter_value("serving.queue.depth") == 0
    finally:
        sched.close()


def test_scheduler_cancel_deadline_admission_timeout(metrics, snap_main):
    snap = snap_main
    src = int(np.flatnonzero(snap.out_degree > 0)[0])
    sched = JobScheduler(snapshot=snap, metrics=metrics, autostart=False)
    try:
        # cancellation while queued: immediate terminal state
        c = sched.submit(JobSpec(kind="bfs",
                                 params={"source_dense": src}))
        assert sched.cancel(c.id)
        assert c.state.value == "cancelled"
        # deadline already passed: EXPIRED, never runs
        e = sched.submit(JobSpec(kind="bfs",
                                 params={"source_dense": src},
                                 deadline=time.time() - 1))
        assert e.state.value == "expired"
        # timeout_s=0 trips the level-boundary check on the first level
        t = sched.submit(JobSpec(kind="bfs",
                                 params={"source_dense": src},
                                 timeout_s=0.0))
        sched.start()
        assert t.wait(60) and t.state.value == "timeout", (t.state,
                                                           t.error)
        assert metrics.counter_value("serving.jobs.cancelled") == 1
        assert metrics.counter_value("serving.jobs.expired") == 1
        assert _await_counter(metrics, "serving.jobs.timeout", 1) == 1
    finally:
        sched.close()
    # admission: a budget smaller than the graph image rejects the job
    # with an explanatory error instead of running it
    sched2 = JobScheduler(snapshot=snap, metrics=metrics,
                          hbm_budget_bytes=64)
    try:
        a = sched2.submit(JobSpec(kind="bfs",
                                  params={"source_dense": src}))
        assert a.wait(60) and a.state.value == "failed"
        assert "admission" in a.error
    finally:
        sched2.close()


@pytest.mark.slow
def test_single_execution_kinds_and_round_interrupt(metrics, snap_main):
    """Non-BFS kinds execute through the scheduler; the frontier kinds
    honor cancellation/timeout at ROUND boundaries via
    _frontier_run's on_round veto (the single-execution analog of the
    batched level mask). Slow tier: compiles the sssp/wcc/pagerank
    kernel sets on top of the BFS ones — the tier-1 serial budget is
    knife-edge and the BFS cancellation/timeout/admission acceptance
    paths are covered by the fast tests above."""
    from titan_tpu.models.frontier import (RoundInterrupted,
                                           frontier_sssp)

    snap = snap_main
    src = int(np.flatnonzero(snap.out_degree > 0)[0])
    # direct kernel contract: a vetoing on_round raises with the round
    calls = []

    def veto(rounds):
        calls.append(rounds)
        return rounds < 1
    with pytest.raises(RoundInterrupted) as ei:
        frontier_sssp(snap, src, on_round=veto)
    assert ei.value.rounds == 1 and calls == [0, 1]

    sched = JobScheduler(snapshot=snap, metrics=metrics)
    try:
        s = sched.submit(JobSpec(kind="sssp",
                                 params={"source_dense": src}))
        w = sched.submit(JobSpec(kind="wcc"))
        p = sched.submit(JobSpec(kind="pagerank",
                                 params={"iterations": 3}))
        t = sched.submit(JobSpec(kind="sssp",
                                 params={"source_dense": src},
                                 timeout_s=0.0))
        pt = sched.submit(JobSpec(kind="pagerank", timeout_s=0.0,
                                  params={"iterations": 5}))
        for job in (s, w, p, t, pt):
            assert job.wait(120), job
        assert s.state.value == "done" and s.result["reached"] >= 1
        assert w.state.value == "done" and w.result["components"] >= 1
        assert p.state.value == "done" and p.result["iterations"] == 3
        assert t.state.value == "timeout", (t.state, t.error)
        assert pt.state.value == "timeout", (pt.state, pt.error)
    finally:
        sched.close()


def test_scheduler_unknown_kind_and_unknown_source(metrics, snap_main):
    snap = snap_main
    sched = JobScheduler(snapshot=snap, metrics=metrics)
    try:
        with pytest.raises(ValueError):
            sched.submit(JobSpec(kind="nope"))
        j = sched.submit(JobSpec(kind="bfs", params={}))   # no source
        assert j.wait(60) and j.state.value == "failed"
        assert "source" in j.error
    finally:
        sched.close()


def test_malformed_jobs_never_kill_the_worker(metrics, snap_main):
    """One stuck caller must never wedge the queue: malformed params
    (None source, junk targets, junk max_levels) fail THEIR job — or
    degrade to None target entries — and the worker keeps serving."""
    snap = snap_main
    src = int(np.flatnonzero(snap.out_degree > 0)[0])
    sched = JobScheduler(snapshot=snap, metrics=metrics)
    try:
        bad1 = sched.submit(JobSpec(kind="bfs",
                                    params={"source": None}))
        bad2 = sched.submit(JobSpec(kind="bfs",
                                    params={"source_dense": src,
                                            "max_levels": "soon"}))
        soft = sched.submit(JobSpec(kind="bfs",
                                    params={"source_dense": src,
                                            "targets": ["abc", src]}))
        good = sched.submit(JobSpec(kind="bfs",
                                    params={"source_dense": src}))
        for j in (bad1, bad2, soft, good):
            assert j.wait(60), j
        assert bad1.state.value == "failed" and "source" in bad1.error
        assert bad2.state.value == "failed"
        # junk target degrades to None; the job itself succeeds
        assert soft.state.value == "done"
        assert soft.result["targets"]["abc"] is None
        assert soft.result["targets"][str(src)] == 0
        # the worker survived all of it
        assert good.state.value == "done", (good.state, good.error)
    finally:
        sched.close()


def test_batch_key_separates_incompatible_jobs():
    """Only jobs that can share ONE fused round loop may batch: kind,
    snapshot parameters AND the kind's cohort-wide knobs must agree (a
    tight level cap must not truncate batchmates, nor ride past its
    own). Since ISSUE 19 SSSP and WCC are batchable too — into
    PER-ALGORITHM cohorts whose keys can never collide with another
    kind's (the kind leads every key)."""
    from titan_tpu.olap.serving.batcher import batch_key

    base = batch_key(JobSpec(kind="bfs"))
    assert base is not None
    assert batch_key(JobSpec(kind="bfs")) == base
    assert batch_key(JobSpec(kind="bfs",
                             params={"max_levels": 3})) != base
    assert batch_key(JobSpec(kind="bfs", directed=True)) != base
    assert batch_key(JobSpec(kind="bfs", labels=("knows",))) != base
    # sssp/wcc fuse among themselves, never with bfs or each other
    sssp = batch_key(JobSpec(kind="sssp"))
    wcc = batch_key(JobSpec(kind="wcc"))
    assert sssp is not None and wcc is not None
    assert len({base, sssp, wcc}) == 3
    assert batch_key(JobSpec(kind="sssp")) == sssp
    # SSSP mode knobs are cohort-wide: differing knobs must not fuse
    assert batch_key(JobSpec(kind="sssp",
                             params={"delta": 0.3})) != sssp
    assert batch_key(JobSpec(kind="sssp",
                             params={"quantile_mass": 64})) != sssp
    assert batch_key(JobSpec(kind="sssp",
                             params={"max_rounds": 7})) != sssp
    # junk knob values: run (and fail) alone, never poison a cohort
    assert batch_key(JobSpec(kind="sssp",
                             params={"delta": "wat"})) is None
    # pagerank stays single-execution
    assert batch_key(JobSpec(kind="pagerank")) is None


def test_hbm_ledger_eviction_and_pinning():
    evicted = []
    led = HBMLedger(budget_bytes=1000, on_evict=evicted.append)
    led.reserve("a", 400)
    led.unpin("a")
    led.reserve("b", 500)
    led.unpin("b")
    led.reserve("c", 600)          # must evict the largest idle (b)
    assert evicted == ["b"]
    # a (400) + c (600) fill the budget; c is pinned, a idle
    from titan_tpu.olap.serving.hbm import AdmissionError
    with pytest.raises(AdmissionError):
        led.reserve("d", 700)      # even evicting a leaves c+700 > 1000
    assert chunked_csr_bytes(0, 1) == 8 * 4 + 12


# --------------------------------------------------------------------------
# engine-level batched DenseProgram execution
# --------------------------------------------------------------------------

def test_engine_run_batched_matches_run_single(snap_main):
    """K BFS DensePrograms as one [K, n] vmapped while_loop — per-job
    outputs and iteration counts bit-equal to run_single."""
    from titan_tpu.models.bfs import BFS
    from titan_tpu.olap.tpu.engine import run_single, run_single_batched

    snap = snap_main
    nz = np.flatnonzero(snap.out_degree > 0)
    prog = BFS(max_iterations=100)
    params = [{"source_dense": int(s)} for s in nz[:4]]
    batched = run_single_batched(prog, snap, params)
    for p, res in zip(params, batched):
        ref = run_single(prog, snap, p)
        assert (res["dist"] == ref["dist"]).all()
        assert res.iterations == ref.iterations
    with pytest.raises(TypeError):
        run_single_batched(prog, snap, [{"source_dense": "zero"}])


def test_computer_run_async_delegates_to_scheduler():
    """The host computer's async hook: run_async queues the BSP run
    behind the serving scheduler and returns a waitable handle whose
    result is the usual HostComputerResult."""
    import titan_tpu
    from titan_tpu.core.defs import Direction
    from titan_tpu.olap.api import VertexProgram
    from titan_tpu.olap.computer import HostGraphComputer

    class DegreeProgram(VertexProgram):
        def execute(self, vertex, messenger, memory):
            vertex.set_state("deg", vertex.degree(Direction.OUT))

        def terminate(self, memory):
            return True

    g = titan_tpu.open("inmemory")
    try:
        tx = g.new_transaction()
        vs = [tx.add_vertex("node", name=f"v{i}") for i in range(4)]
        for a, b in [(0, 1), (1, 2), (2, 3)]:
            vs[a].add_edge("link", vs[b])
        vids = [v.id for v in vs]
        tx.commit()
        snap = snap_mod.build(g)
        sched = JobScheduler(snapshot=snap)
        try:
            comp = HostGraphComputer(g, num_threads=2)
            job = comp.run_async(DegreeProgram(), sched)
            assert job.wait(60) and job.state.value == "done", job.error
            res = job.result["value"]
            assert res.state_of(vids[0])["deg"] == 1
            assert res.state_of(vids[3])["deg"] == 0
        finally:
            sched.close()
    finally:
        g.close()


# --------------------------------------------------------------------------
# SSSP/WCC cohorts (ISSUE 19): bit-equality + per-algorithm fusion
# --------------------------------------------------------------------------

@pytest.mark.parametrize("K", [1, 4, 8])
def test_batched_sssp_bit_equal_to_sequential(K, snap_main):
    """Property: every member of a K-way SSSP cohort (shared round
    loop, one stacked plan sync per round) equals the sequential run
    from its source — distances AND round counts, duplicates
    included."""
    from titan_tpu.models.frontier import (frontier_sssp,
                                           frontier_sssp_batched)

    snap = snap_main
    rng = np.random.default_rng(200 + K)
    nz = np.flatnonzero(snap.out_degree > 0)
    sources = [int(s) for s in rng.choice(nz, size=K, replace=True)]
    outs, rounds, stopped = frontier_sssp_batched(snap, sources)
    assert stopped == [None] * K
    for k, s in enumerate(sources):
        ref, ref_rounds = frontier_sssp(snap, s)
        assert rounds[k] == ref_rounds, f"member {k} source {s}"
        assert (np.asarray(outs[k]) == np.asarray(ref)).all(), \
            f"member {k} source {s}"


def test_batched_sssp_delta_mode_bit_equal(snap_main):
    """Cohort-wide mode knobs (delta-stepping here) produce the same
    per-member trajectory the sequential kernel walks under the same
    knobs — the contract behind the batch key pinning them."""
    from titan_tpu.models.frontier import (frontier_sssp,
                                           frontier_sssp_batched)

    snap = snap_main
    nz = np.flatnonzero(snap.out_degree > 0)
    sources = [int(s) for s in nz[:4]]
    outs, rounds, _ = frontier_sssp_batched(snap, sources, delta=0.3)
    for k, s in enumerate(sources):
        ref, ref_rounds = frontier_sssp(snap, s, delta=0.3)
        assert rounds[k] == ref_rounds
        assert (np.asarray(outs[k]) == np.asarray(ref)).all()


@pytest.mark.parametrize("K", [1, 4])
def test_batched_wcc_bit_equal_to_sequential(K, snap_main):
    from titan_tpu.models.frontier import (frontier_wcc,
                                           frontier_wcc_batched)

    snap = snap_main
    ref, ref_rounds = frontier_wcc(snap)
    outs, rounds, stopped = frontier_wcc_batched(snap, K)
    assert stopped == [None] * K
    for k in range(K):
        assert rounds[k] == ref_rounds
        assert (np.asarray(outs[k]) == np.asarray(ref)).all()


def test_batched_sssp_mixed_early_exit(snap_main):
    """A member vetoed mid-cohort (the serving layer's cancel/timeout
    hook) drops at exactly that round — out None, stopped set — while
    every survivor still finishes bit-equal to sequential."""
    from titan_tpu.models.frontier import (frontier_sssp,
                                           frontier_sssp_batched)

    snap = snap_main
    nz = np.flatnonzero(snap.out_degree > 0)
    sources = [int(s) for s in nz[:4]]

    def on_round(k, rounds):
        return not (k == 1 and rounds >= 2)

    outs, rounds, stopped = frontier_sssp_batched(
        snap, sources, on_round=on_round)
    assert outs[1] is None and stopped[1] == 2
    for k in (0, 2, 3):
        assert stopped[k] is None
        ref, ref_rounds = frontier_sssp(snap, sources[k])
        assert rounds[k] == ref_rounds
        assert (np.asarray(outs[k]) == np.asarray(ref)).all()


def test_scheduler_mixed_stream_fuses_per_algorithm(metrics, snap_main):
    """A mixed BFS/SSSP/WCC submit stream fuses into PER-ALGORITHM
    cohorts: each kind's fresh jobs share one batch (batch_k = that
    kind's count), kinds never cross-fuse, and every result is
    bit-equal to its sequential reference."""
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid
    from titan_tpu.models.frontier import frontier_sssp, frontier_wcc

    snap = snap_main
    nz = np.flatnonzero(snap.out_degree > 0)
    sched = JobScheduler(snapshot=snap, metrics=metrics,
                         autostart=False)
    try:
        bfs = [sched.submit(JobSpec(kind="bfs",
                                    params={"source_dense": int(s)}))
               for s in nz[:4]]
        sssp = [sched.submit(JobSpec(kind="sssp",
                                     params={"source_dense": int(s)}))
                for s in nz[:4]]
        wcc = [sched.submit(JobSpec(kind="wcc")) for _ in range(3)]
        sched.start()
        for job in bfs + sssp + wcc:
            assert job.wait(120), job
            assert job.state.value == "done", (job, job.error)
        # per-algorithm fusion, never cross-kind: batch_k equals the
        # kind's own cohort width exactly
        assert [j.batch_k for j in bfs] == [4] * 4
        assert [j.batch_k for j in sssp] == [4] * 4
        assert [j.batch_k for j in wcc] == [3] * 3
        for job in bfs:
            ref, _ = frontier_bfs_hybrid(
                snap, int(job.spec.params["source_dense"]))
            assert (job.result["dist"] == np.asarray(ref)).all()
        for job in sssp:
            ref, ref_rounds = frontier_sssp(
                snap, int(job.spec.params["source_dense"]))
            assert job.result["rounds"] == ref_rounds
            assert (job.result["dist"] == np.asarray(ref)).all()
        wref, wrounds = frontier_wcc(snap)
        for job in wcc:
            assert job.result["rounds"] == wrounds
            assert (job.result["labels"] == np.asarray(wref)).all()
    finally:
        sched.close()
