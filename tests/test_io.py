"""Graph file IO round-trips (GraphSON-style JSON lines + binary snapshot).

(reference: TitanIoTest / GraphSON-Gryo IO via TitanIoRegistry — the suite
asserts a written-then-read graph preserves schema, elements, properties
and special attribute types.)
"""

import datetime
import decimal
from datetime import timezone as _tz
import uuid

import pytest

import titan_tpu
from titan_tpu import io as tio
from titan_tpu.core.attribute import Geoshape
from titan_tpu.core.defs import Cardinality, Multiplicity


@pytest.fixture
def g():
    g = titan_tpu.open("inmemory")
    yield g
    g.close()


@pytest.fixture
def g2():
    g = titan_tpu.open("inmemory")
    yield g
    g.close()


def _build_rich_graph(g):
    mgmt = g.management()
    name = mgmt.make_property_key("name", str)
    nick = mgmt.make_property_key("nick", str, Cardinality.LIST)
    when = mgmt.make_property_key("when", datetime.datetime)
    mgmt.make_property_key("price", decimal.Decimal)
    mgmt.make_edge_label("knows", Multiplicity.MULTI,
                         sort_key=(when.id,))
    mgmt.make_vertex_label("person")
    mgmt.make_vertex_label("hub", partitioned=False, static=False)
    mgmt.build_index("byName", "vertex").add_key(name) \
        .build_composite_index()
    mgmt.commit()

    tx = g.new_transaction()
    a = tx.add_vertex("person", name="alice")
    p = tx.add_property(a, "nick", "ally")
    tx.add_meta_property(p, "since", 2020)
    tx.add_property(a, "nick", "al")
    tx.add_property(a, "when",
                    datetime.datetime(2021, 3, 4, 5, 6, 7, tzinfo=_tz.utc))
    tx.add_property(a, "price", decimal.Decimal("12.50"))
    tx.add_property(a, "uid", uuid.UUID(int=7))
    tx.add_property(a, "blob", b"\x00\x01\xff")
    tx.add_property(a, "spot", Geoshape.point(37.1, -122.3))
    tx.add_property(a, "tags", ("x", "y"))
    b = tx.add_vertex("person", name="bob")
    c = tx.add_vertex(name="carol")   # unlabeled
    tx.add_edge(a, "knows", b,
                {"when": datetime.datetime(2022, 1, 1, tzinfo=_tz.utc),
                 "weight": 0.5})
    tx.add_edge(b, "knows", c, {"when": datetime.datetime(2023, 1, 1, tzinfo=_tz.utc)})
    tx.commit()


def _check_graph(g2):
    # schema survived
    schema = g2.schema
    nick = schema.get_by_name("nick")
    assert nick.cardinality is Cardinality.LIST
    when = schema.get_by_name("when")
    assert when.dtype is datetime.datetime
    knows = schema.get_by_name("knows")
    assert knows.multiplicity is Multiplicity.MULTI
    assert [schema.get_type(k).name for k in knows.sort_key] == ["when"]
    assert schema.get_by_name("person").is_vertex_label
    idx = schema.get_by_name("byName")
    assert idx.composite and \
        [schema.get_type(k).name for k in idx.key_ids] == ["name"]

    tx = g2.new_transaction()
    alice = next(v for v in tx.vertices() if v.value("name") == "alice")
    assert alice.label() == "person"
    assert sorted(alice.values("nick")) == ["al", "ally"]
    assert alice.value("when") == datetime.datetime(2021, 3, 4, 5, 6, 7, tzinfo=_tz.utc)
    assert alice.value("price") == decimal.Decimal("12.50")
    assert alice.value("uid") == uuid.UUID(int=7)
    assert alice.value("blob") == b"\x00\x01\xff"
    assert alice.value("spot") == Geoshape.point(37.1, -122.3)
    assert alice.value("tags") == ("x", "y")
    # meta-property on the "ally" nick
    ally = next(p for p in alice.properties("nick") if p.value == "ally")
    assert ally.meta("since") == 2020
    assert ally.property_map() == {"since": 2020}
    # edges + edge properties
    e = next(iter(alice.out_edges("knows")))
    assert e.in_vertex().value("name") == "bob"
    assert e.value("when") == datetime.datetime(2022, 1, 1, tzinfo=_tz.utc)
    assert e.value("weight") == 0.5
    carol = next(v for v in tx.vertices() if v.value("name") == "carol")
    assert carol.label() == "vertex"   # stayed unlabeled
    # the composite index got populated during import
    got = g2.traversal().V().has("name", "bob").to_list()
    assert len(got) == 1
    tx.rollback()


def test_graphson_roundtrip(g, g2, tmp_path):
    _build_rich_graph(g)
    path = str(tmp_path / "graph.json")
    out = tio.write_graphson(g, path)
    assert out == {"vertices": 3, "edges": 2}
    res = tio.read_graphson(g2, path)
    assert res == {"vertices": 3, "edges": 2}
    _check_graph(g2)


def test_graphbin_roundtrip(g, g2, tmp_path):
    _build_rich_graph(g)
    path = str(tmp_path / "graph.bin")
    out = tio.write_graphbin(g, path)
    assert out == {"vertices": 3, "edges": 2}
    res = tio.read_graphbin(g2, path)
    assert res == {"vertices": 3, "edges": 2}
    _check_graph(g2)


def test_graph_of_the_gods_roundtrip(g, g2, tmp_path):
    from titan_tpu.example import load
    load(g)
    path = str(tmp_path / "gods.json")
    out = tio.write_graphson(g, path)
    res = tio.read_graphson(g2, path)
    assert res == out and out["vertices"] == 12
    # same 2-hop result through the traversal DSL
    a = sorted(g.traversal().V().has("name", "hercules")
               .out("father").out("lives").values("name").to_list())
    b = sorted(g2.traversal().V().has("name", "hercules")
               .out("father").out("lives").values("name").to_list())
    assert a == b and a
    g.close()


def test_graphson_batched_import(g, g2, tmp_path):
    tx = g.new_transaction()
    vs = [tx.add_vertex(n=i) for i in range(50)]
    for i in range(49):
        tx.add_edge(vs[i], "next", vs[i + 1])
    tx.commit()
    path = str(tmp_path / "chain.json")
    tio.write_graphson(g, path)
    res = tio.read_graphson(g2, path, batch_size=7)  # many tx boundaries
    assert res == {"vertices": 50, "edges": 49}
    chain = g2.traversal().V().has("n", 0).out("next").out("next") \
        .values("n").to_list()
    assert chain == [2]


def test_bad_files(g2, tmp_path):
    p = tmp_path / "x.json"
    p.write_text('{"something": 1}\n')
    with pytest.raises(titan_tpu.errors.TitanError):
        tio.read_graphson(g2, str(p))
    pb = tmp_path / "x.bin"
    pb.write_bytes(b"NOTBIN")
    with pytest.raises(titan_tpu.errors.TitanError):
        tio.read_graphbin(g2, str(pb))


def test_ndarray_property_roundtrips_both_formats(g, g2, tmp_path):
    import numpy as np
    tx = g.new_transaction()
    emb = np.arange(8, dtype=np.float32).reshape(2, 4)
    tx.add_vertex("item", name="x", embedding=emb)
    tx.commit()
    # store round-trip
    v = g.traversal().V().has("name", "x").to_list()[0]
    got = g.tx().vertex(v.id).value("embedding")
    assert np.array_equal(got, emb) and got.dtype == np.float32
    # file round-trip (json then binary, chained)
    p1, p2 = str(tmp_path / "a.json"), str(tmp_path / "b.bin")
    tio.write_graphson(g, p1)
    tio.read_graphson(g2, p1)
    v2 = g2.traversal().V().has("name", "x").to_list()[0]
    assert np.array_equal(g2.tx().vertex(v2.id).value("embedding"), emb)
    tio.write_graphbin(g2, p2)
    g3 = titan_tpu.open("inmemory")
    tio.read_graphbin(g3, p2)
    v3 = g3.traversal().V().has("name", "x").to_list()[0]
    assert np.array_equal(g3.tx().vertex(v3.id).value("embedding"), emb)
    g3.close()


def test_truncated_graphbin_raises_titan_error(g, g2, tmp_path):
    """Any truncation point must surface as TitanError, not IndexError
    (advisor finding: read_graphbin assumed a well-formed file)."""
    _build_rich_graph(g)
    p = tmp_path / "full.bin"
    tio.write_graphbin(g, str(p))
    data = p.read_bytes()
    for cut in (len(data) // 3, len(data) // 2, len(data) - 1):
        frag = tmp_path / f"cut{cut}.bin"
        frag.write_bytes(data[:cut])
        gx = titan_tpu.open("inmemory")
        try:
            with pytest.raises(titan_tpu.errors.TitanError):
                tio.read_graphbin(gx, str(frag))
        finally:
            gx.close()


def test_meta_property_on_loaded_property(g):
    """Meta-properties on properties LOADED from storage (not added in the
    same tx) rewrite the owning relation, matching the reference's
    TitanVertexProperty.property() semantics."""
    tx = g.new_transaction()
    v = tx.add_vertex("person", name="ada")
    tx.commit()

    tx = g.new_transaction()
    vv = tx.vertex(v.id)
    [p] = [p for p in tx.vertex_properties(vv.id, ["name"])]
    tx.add_meta_property(p, "since", 1815)
    tx.commit()

    tx = g.new_transaction()
    [p2] = [p for p in tx.vertex_properties(v.id, ["name"])]
    assert p2.value == "ada"
    metas = {tx.schema_name(kid): mv for kid, mv in p2.rel.properties.items()}
    assert metas.get("since") == 1815
    # still exactly one 'name' property (the rewrite replaced, not added)
    assert len(list(tx.vertex_properties(v.id, ["name"]))) == 1
    tx.rollback()


def test_two_meta_properties_on_same_loaded_handle(g):
    tx = g.new_transaction()
    v = tx.add_vertex("person", name="ada")
    tx.commit()
    tx = g.new_transaction()
    [p] = list(tx.vertex_properties(v.id, ["name"]))
    tx.add_meta_property(p, "a", 1)
    tx.add_meta_property(p, "b", 2)
    tx.commit()
    tx = g.new_transaction()
    [p2] = list(tx.vertex_properties(v.id, ["name"]))
    metas = {tx.schema_name(k): mv for k, mv in p2.rel.properties.items()}
    assert metas.get("a") == 1 and metas.get("b") == 2
    tx.rollback()


def test_corrupt_string_and_dangling_edge_raise_titan_error(g, g2, tmp_path):
    _build_rich_graph(g)
    p = tmp_path / "full.bin"
    tio.write_graphbin(g, str(p))
    data = bytearray(p.read_bytes())
    # corrupt a label string: find 'person' bytes and break the utf-8
    i = bytes(data).find(b"person")
    assert i > 0
    data[i] = 0xFF
    bad = tmp_path / "badstr.bin"
    bad.write_bytes(bytes(data))
    gx = titan_tpu.open("inmemory")
    with pytest.raises(titan_tpu.errors.TitanError):
        tio.read_graphbin(gx, str(bad))
    gx.close()
    # dangling edge in GraphSON: reference a vertex id that doesn't exist
    import json as _json
    pj = tmp_path / "g.json"
    tio.write_graphson(g, str(pj))
    lines = pj.read_text().splitlines()
    rec = _json.loads(lines[1])
    rec["outE"] = [["knows", 99999999, {}]]
    pj.write_text("\n".join([lines[0], _json.dumps(rec)]) + "\n")
    gy = titan_tpu.open("inmemory")
    with pytest.raises(titan_tpu.errors.TitanError):
        tio.read_graphson(gy, str(pj))
    gy.close()


# ---------------------------------------------------------------------------
# TinkerPop 3.0.2 adjacency GraphSON (true wire compatibility —
# reference: titan-dist/src/assembly/static/data/*.json format)
# ---------------------------------------------------------------------------

_TP3_FIXTURE = __file__.rsplit("/", 1)[0] + "/data/tp3_adjacency_sample.json"
_REFERENCE_MODERN = ("/root/reference/titan-dist/src/assembly/static/data/"
                     "tinkerpop-modern.json")


def test_tp3_fixture_import(g2):
    res = tio.read_graphson_tp3(g2, _TP3_FIXTURE)
    assert res == {"vertices": 4, "edges": 3}
    tx = g2.new_transaction()
    ada = next(v for v in tx.vertices() if v.value("name") == "ada")
    assert ada.label() == "engineer"
    assert ada.value("level") == 7
    built = [e.in_vertex().value("name") for e in ada.out_edges("builds")]
    assert built == ["compiler"]
    e = next(iter(ada.out_edges("builds")))
    assert e.value("effort") == 0.7
    compiler = next(v for v in tx.vertices()
                    if v.value("name") == "compiler")
    assert compiler.value("active") is True
    assert len(list(compiler.in_edges("builds"))) == 2
    loner = next(v for v in tx.vertices() if v.value("name") == "loner")
    assert loner.label() == "vertex"       # default label round-trips
    tx.rollback()


def test_tp3_export_format_and_roundtrip(g2, tmp_path):
    import json

    tio.read_graphson_tp3(g2, _TP3_FIXTURE)
    out_path = str(tmp_path / "export.json")
    counts = tio.write_graphson_tp3(g2, out_path)
    assert counts == {"vertices": 4, "edges": 3}
    # exact TP3 shape: untyped scalars, outE/inE adjacency, properties
    # as {key: [{id, value}]}; empty sections omitted
    recs = [json.loads(x) for x in open(out_path) if x.strip()]
    assert len(recs) == 4
    by_name = {r["properties"]["name"][0]["value"]: r for r in recs}
    ada = by_name["ada"]
    assert ada["label"] == "engineer"
    assert set(ada["outE"]) == {"builds", "mentors"}
    [b] = ada["outE"]["builds"]
    assert set(b) >= {"id", "inV"} and b["properties"] == {"effort": 0.7}
    assert isinstance(b["inV"], int) and isinstance(b["id"], int)
    assert "inE" not in by_name["loner"] and "outE" not in by_name["loner"]
    [mirror] = by_name["compiler"]["inE"]["builds"][:1]
    assert "outV" in mirror
    # and the file reimports losslessly (vertex ids remapped)
    g3 = titan_tpu.open("inmemory")
    try:
        res = tio.read_graphson_tp3(g3, out_path)
        assert res == {"vertices": 4, "edges": 3}
        tx = g3.new_transaction()
        ada2 = next(v for v in tx.vertices() if v.value("name") == "ada")
        assert [e.in_vertex().value("name")
                for e in ada2.out_edges("builds")] == ["compiler"]
        tx.rollback()
    finally:
        g3.close()


def test_read_graphson_autodetects_tp3(g2):
    # the generic reader must accept reference-format files transparently
    res = tio.read_graphson(g2, _TP3_FIXTURE)
    assert res == {"vertices": 4, "edges": 3}


@pytest.mark.skipif(not __import__("os").path.exists(_REFERENCE_MODERN),
                    reason="reference checkout not present")
def test_reference_shipped_graphson_imports(g2):
    """The actual file the reference distribution ships (tinkerpop-modern:
    6 vertices, 6 edges) must import — interop proof against a foreign
    artifact, not our own export."""
    res = tio.read_graphson_tp3(g2, _REFERENCE_MODERN)
    assert res == {"vertices": 6, "edges": 6}
    marko = next(v for v in g2.new_transaction().vertices()
                 if v.value("name") == "marko")
    assert marko.label() == "person"
    assert marko.value("age") == 29
    knows = sorted(e.in_vertex().value("name")
                   for e in marko.out_edges("knows"))
    assert knows == ["josh", "vadas"]
    created = [e.value("weight") for e in marko.out_edges("created")]
    assert created == [0.4]
