"""Resolve the jitted kernels a module registers — by call site.

R1's boolean-mask check and R2's host-sync checks only make sense
inside code that is actually TRACED. Name heuristics ("looks like a
kernel") rot; the repo has exactly three registration seams every
traced kernel flows through — ``utils/jitcache.jit_once(key,
builder)``, ``parallel/mesh.mesh_jit(name, mesh, builder, ...)`` and
``pl.pallas_call(kernel, ...)`` — so this module follows those call
sites instead:

    registration call -> builder (local def or lambda)
                      -> the callable the builder returns
                      -> through jax.jit / functools.partial(jax.jit)
                         / shard_map wrappers, collecting
                         static_argnames / static_argnums on the way

The resolved function's non-static parameters are the traced values.
Pallas kernels invert the convention: ``pallas_call`` passes only the
refs, positionally, so the kernel's POSITIONAL parameters are the
traced refs while keyword-only parameters (bound through
``functools.partial`` at the call site) are compile-time constants —
Python control flow on them is legal and expected
(ops/pallas_segment.py's ``while d < block`` ladder).
Resolution is best-effort and PURELY lexical: a builder whose return
can't be followed (e.g. mesh.py's own generic ``builder(mesh)``
trampoline) contributes nothing rather than guessing.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

_FN = (ast.FunctionDef, ast.AsyncFunctionDef)
_SCOPES = _FN + (ast.Lambda,)


@dataclasses.dataclass
class JittedFn:
    node: ast.AST            # FunctionDef / Lambda — the traced body
    traced: frozenset        # parameter names traced at call time
    reg_line: int            # the jit_once/mesh_jit call that owns it
    key: Optional[str]       # registration key when it's a literal


def jitted_functions(ms) -> list:
    """All jitted kernels registered by this module (cached on
    ``ms.cache`` so R1 and R2 share one resolution pass)."""
    got = ms.cache.get("jitted")
    if got is None:
        got = _Resolver(ms).resolve()
        ms.cache["jitted"] = got
    return got


def walk_no_nested_fns(body):
    """Yield nodes of ``body`` statements without entering nested
    function/lambda scopes (lexical-only traversals)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, _SCOPES):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _Resolver:
    def __init__(self, ms):
        self.ms = ms
        # id(scope node) -> {name: FunctionDef} for defs bound
        # directly in that scope (module, function, or lambda)
        self.defs: dict = {}
        # id(scope node) -> {name: value expr} for single-target
        # assignments (follows `kern = functools.partial(...)` locals)
        self.assigns: dict = {}
        self.reg_calls: list = []   # (Call, scope chain)
        self._index(ms.tree, (ms.tree,))

    def _index(self, node, chain) -> None:
        scope = chain[-1]
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FN):
                self.defs.setdefault(id(scope), {})[child.name] = child
                self._index(child, chain + (child,))
            elif isinstance(child, ast.Lambda):
                self._index(child, chain + (child,))
            else:
                if isinstance(child, ast.Assign) \
                        and len(child.targets) == 1 \
                        and isinstance(child.targets[0], ast.Name):
                    self.assigns.setdefault(id(scope), {})[
                        child.targets[0].id] = child.value
                if isinstance(child, ast.Call):
                    kind = self._reg_kind(child.func)
                    if kind:
                        self.reg_calls.append((child, chain, kind))
                self._index(child, chain)

    def _reg_kind(self, func) -> Optional[str]:
        d = self.ms.dotted(func)
        if not d:
            return None
        last = d.rsplit(".", 1)[-1]
        if last == "jit_once" or d in self.ms.jitonce_names:
            return "jit_once"
        if last == "mesh_jit" or d in self.ms.meshjit_names:
            return "mesh_jit"
        if last == "pallas_call":
            return "pallas_call"
        return None

    # -- scope-chain name lookup ------------------------------------------

    def _find_def(self, name: str, chain):
        for scope in reversed(chain):
            got = self.defs.get(id(scope), {}).get(name)
            if got is not None:
                return got
        return None

    def _find_assign(self, name: str, chain):
        for scope in reversed(chain):
            got = self.assigns.get(id(scope), {}).get(name)
            if got is not None:
                return got
        return None

    # -- jit-wrapper unwrapping -------------------------------------------

    def _unwrap_call(self, call: ast.Call, chain):
        """(fn node, statics) for jax.jit(f, ...) / partial(jax.jit,
        ...) / shard_map(f, ...) expressions; (None, set()) when the
        wrapper isn't one we know."""
        d = self.ms.canonical(call.func) or ""
        last = d.rsplit(".", 1)[-1]
        statics = _static_names(call)
        target = None
        if last == "jit" and call.args:
            target = call.args[0]
        elif last == "partial" and len(call.args) >= 2:
            inner = self.ms.canonical(call.args[0]) or ""
            if inner.rsplit(".", 1)[-1] == "jit":
                target = call.args[1]
        elif last == "shard_map" and call.args:
            target = call.args[0]
        if target is None:
            return None, statics
        fn = self._as_callable(target, chain)
        if fn is not None:
            # static_argnums on the wrapper CALL resolve to names here,
            # where the function's positional order is known
            statics = statics | _static_nums_to_names(call, fn)
        return fn, statics

    def _as_callable(self, node, chain):
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, ast.Name):
            return self._find_def(node.id, chain)
        return None

    def _returned_callable(self, builder, chain):
        """Follow a builder FunctionDef to the callable it returns."""
        b_chain = chain + (builder,)
        for node in walk_no_nested_fns(builder.body):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            val = node.value
            if isinstance(val, (ast.Name, ast.Lambda)):
                fn = self._as_callable(val, b_chain)
                if fn is not None:
                    return fn, set()
            elif isinstance(val, ast.Call):
                fn, statics = self._unwrap_call(val, b_chain)
                if fn is not None:
                    return fn, statics
        return None, set()

    # -- pallas_call kernels ----------------------------------------------

    def _pallas_kernel(self, call: ast.Call, chain):
        """(kernel fn node, statics) for ``pl.pallas_call(kern, ...)``:
        arg0 as a def/lambda, a ``functools.partial(kernel, **consts)``
        binding compile-time keywords, or a local name assigned one of
        those."""
        target = _arg(call, 0, "kernel")
        statics: set = set()
        for _hop in range(4):
            if not isinstance(target, ast.Name):
                break
            fn = self._find_def(target.id, chain)
            if fn is not None:
                return fn, statics
            target = self._find_assign(target.id, chain)
        if isinstance(target, ast.Lambda):
            return target, statics
        if isinstance(target, ast.Call):
            d = (self.ms.canonical(target.func) or "").rsplit(".", 1)[-1]
            if d == "partial" and target.args:
                statics |= {k.arg for k in target.keywords if k.arg}
                inner = target.args[0]
                if isinstance(inner, ast.Lambda):
                    return inner, statics
                if isinstance(inner, ast.Name):
                    fn = self._find_def(inner.id, chain)
                    if fn is not None:
                        return fn, statics
        return None, statics

    # -- entry -------------------------------------------------------------

    def resolve(self) -> list:
        out: list = []
        seen: set = set()
        for call, chain, kind in self.reg_calls:
            if kind == "pallas_call":
                fn, statics = self._pallas_kernel(call, chain)
                if fn is None or id(fn) in seen:
                    continue
                seen.add(id(fn))
                # only the positional refs are traced: keyword-only
                # params never receive refs through pallas_call
                out.append(JittedFn(
                    node=fn,
                    traced=frozenset(
                        set(_positional_params(fn)) - statics),
                    reg_line=call.lineno,
                    key=None))
                continue
            is_mesh = kind == "mesh_jit"
            builder = _arg(call, 2 if is_mesh else 1, "builder")
            if builder is None:
                continue
            statics = _static_names(call) if is_mesh else set()
            fn = None
            if isinstance(builder, ast.Lambda):
                body = builder.body
                if isinstance(body, ast.Call):
                    fn, s2 = self._unwrap_call(body, chain)
                    statics |= s2
                else:
                    fn = self._as_callable(body, chain)
            elif isinstance(builder, ast.Name):
                b = self._find_def(builder.id, chain)
                if b is not None:
                    fn, s2 = self._returned_callable(b, chain)
                    statics |= s2
            if fn is None or id(fn) in seen:
                continue
            seen.add(id(fn))
            statics |= _decorator_statics(self.ms, fn)
            out.append(JittedFn(
                node=fn,
                traced=frozenset(_param_names(fn) - statics),
                reg_line=call.lineno,
                key=_literal_key(call)))
        return out


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _arg(call: ast.Call, pos: int, kw: str):
    if len(call.args) > pos:
        return call.args[pos]
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    return None


def _literal_key(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _const_str_seq(node) -> set:
    out: set = set()
    if isinstance(node, (ast.Tuple, ast.List)):
        elts = node.elts
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        elts = [node]
    else:
        return out
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.add(e.value)
    return out


def _static_names(call: ast.Call) -> set:
    """static_argnames off a jit/mesh_jit call (static_argnums are
    resolved to names later, at the function, where positions exist)."""
    out: set = set()
    for k in call.keywords:
        if k.arg == "static_argnames":
            out |= _const_str_seq(k.value)
    return out


def _positional_params(fn) -> list:
    if isinstance(fn, ast.Lambda):
        a = fn.args
    else:
        a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


def _param_names(fn) -> set:
    a = fn.args
    return {p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)}


def _static_nums_to_names(call: ast.Call, fn) -> set:
    pos = _positional_params(fn)
    out: set = set()
    for k in call.keywords:
        if k.arg != "static_argnums":
            continue
        nums = []
        if isinstance(k.value, ast.Constant) \
                and isinstance(k.value.value, int):
            nums = [k.value.value]
        elif isinstance(k.value, (ast.Tuple, ast.List)):
            nums = [e.value for e in k.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)]
        for n in nums:
            if 0 <= n < len(pos):
                out.add(pos[n])
    return out


def _decorator_statics(ms, fn) -> set:
    """static_argnames/static_argnums from @jax.jit /
    @functools.partial(jax.jit, ...) decorators."""
    if isinstance(fn, ast.Lambda):
        return set()
    out: set = set()
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        d = (ms.canonical(dec.func) or "").rsplit(".", 1)[-1]
        if d == "jit":
            out |= _static_names(dec) | _static_nums_to_names(dec, fn)
        elif d == "partial" and dec.args:
            inner = (ms.canonical(dec.args[0]) or "").rsplit(".", 1)[-1]
            if inner == "jit":
                out |= _static_names(dec) | _static_nums_to_names(dec, fn)
    return out
