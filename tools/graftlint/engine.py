"""graftlint core: module model, suppressions, baseline, the Linter.

The engine is deliberately boring: parse each file once, hand the
shared :class:`ModuleSource` (AST + alias tables + per-module caches)
to every rule whose scope matches, then fold inline suppressions and
the checked-in baseline over the raw findings. Rules never do I/O and
never import the code under analysis — everything is AST-only, so the
full tree lints in low single-digit seconds on serial CPU (guarded at
30 s by tests/test_lint.py to protect the tier-1 budget).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import time
from typing import Iterable, Iterator, Optional

from tools.graftlint.config import in_scope, merged_config

# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------

#: suppression channels, in the order they are applied
SUPPRESSED_INLINE = "inline"
SUPPRESSED_FILE = "file"
SUPPRESSED_BASELINE = "baseline"


@dataclasses.dataclass
class Finding:
    rule: str
    path: str            # root-relative posix path
    line: int            # 1-based
    col: int             # 0-based
    message: str
    snippet: str = ""    # stripped source line (baseline fingerprint)
    suppressed: Optional[str] = None
    reason: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

# `# graftlint: allow[rule-id] reason=...`        — this line (or, when
#     the comment stands alone, the next line)
# `# graftlint: allow-file[rule-id] reason=...`   — the whole file
# Multiple ids separate with commas; a missing reason makes the
# suppression INERT (reported as a bare-allow note) — every grandfather
# must say why. Scanned over tokenize COMMENT tokens only: the
# directive syntax QUOTED in a docstring or string literal (e.g. docs
# of the convention itself) is text, not a suppression.
_ALLOW_RE = re.compile(
    r"#\s*graftlint:\s*(allow|allow-file)\[([^\]]+)\]"
    r"(?:\s+reason=(\S[^#]*))?")


@dataclasses.dataclass
class _Allow:
    kind: str            # "allow" | "allow-file"
    ids: frozenset
    reason: str
    line: int            # line the comment sits on
    target_line: int     # line it covers (allow only)


def _scan_allows(text: str) -> list:
    import io
    import tokenize

    allows = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except (tokenize.TokenError, IndentationError):
        return allows       # unparsable files surface as parse-error
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _ALLOW_RE.search(tok.string)
        if not m:
            continue
        kind = m.group(1)
        ids = frozenset(s.strip() for s in m.group(2).split(",")
                        if s.strip())
        reason = (m.group(3) or "").strip()
        i = tok.start[0]
        # a comment-only line covers the NEXT line; trailing comments
        # cover their own line
        standalone = tok.line[: tok.start[1]].strip() == ""
        target = i + 1 if (kind == "allow" and standalone) else i
        allows.append(_Allow(kind, ids, reason, i, target))
    return allows


# ---------------------------------------------------------------------------
# module model
# ---------------------------------------------------------------------------

class ModuleSource:
    """One parsed file plus the alias tables every rule needs.

    ``cache`` is a per-module scratch dict rules share expensive
    derived structure through (e.g. the resolved jitted-function set
    used by both R1 and R2)."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.allows = _scan_allows(text)
        self.cache: dict = {}
        # names bound to the modules rules care about
        self.jnp_names: set = set()     # jax.numpy
        self.np_names: set = set()      # numpy
        self.jax_names: set = set()     # jax
        self.time_names: set = set()    # time
        self.sleep_names: set = set()   # from time import sleep
        self.clockfn_names: set = set() # from time import time/monotonic
        self.jitonce_names: set = set()  # from-import bindings of jit_once
        self.meshjit_names: set = set()  # ... and mesh_jit
        self._collect_aliases()

    def _collect_aliases(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    if a.name == "jax.numpy" and a.asname:
                        self.jnp_names.add(a.asname)
                    elif a.name.split(".")[0] == "jax":
                        self.jax_names.add(name)
                    elif a.name == "numpy":
                        self.np_names.add(name)
                    elif a.name == "time":
                        self.time_names.add(name)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    bound = a.asname or a.name
                    if node.module == "jax" and a.name == "numpy":
                        self.jnp_names.add(bound)
                    elif node.module == "time":
                        if a.name == "sleep":
                            self.sleep_names.add(bound)
                        elif a.name in ("time", "monotonic"):
                            self.clockfn_names.add(bound)
                    elif a.name == "jit_once":
                        self.jitonce_names.add(bound)
                    elif a.name == "mesh_jit":
                        self.meshjit_names.add(bound)

    def dotted(self, node) -> Optional[str]:
        """``jnp.nonzero`` for a pure Name/Attribute chain, else None."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return ".".join(reversed(parts))

    def canonical(self, node) -> Optional[str]:
        """Alias-normalized dotted name: whatever the module called
        jax.numpy comes back as ``jnp.<...>``, numpy as ``np.<...>``,
        jax as ``jax.<...>``, time as ``time.<...>``."""
        d = self.dotted(node)
        if d is None:
            return None
        root, _, rest = d.partition(".")
        for names, canon in ((self.jnp_names, "jnp"),
                             (self.np_names, "np"),
                             (self.jax_names, "jax"),
                             (self.time_names, "time")):
            if root in names:
                return f"{canon}.{rest}" if rest else canon
        return d

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


# ---------------------------------------------------------------------------
# rule base
# ---------------------------------------------------------------------------

class Rule:
    """One invariant. ``check`` yields findings with rule/snippet left
    blank — the engine stamps those (and the relpath) so rules stay
    one-screen visitors."""

    id: str = ""
    alias: str = ""          # the catalog number (R1..R5)
    description: str = ""

    def __init__(self, options: dict):
        self.options = options

    def check(self, ms: ModuleSource, ctx: "Linter") -> Iterator[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

class Baseline:
    """Checked-in grandfather list. Keyed on (rule, path, stripped
    source line) — line NUMBERS move too easily to be a fingerprint —
    with a count per key so duplicate lines stay honest. A finding
    consumes one unit of its key's budget; anything past the budget
    reports as new."""

    def __init__(self, entries: Optional[dict] = None):
        self.entries: dict = dict(entries or {})

    @staticmethod
    def key(f: Finding) -> str:
        return f"{f.rule}::{f.path}::{f.snippet}"

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as fh:
            data = json.load(fh)
        if data.get("version") != 1:
            raise ValueError(f"unsupported baseline version in {path}")
        return cls(data.get("entries", {}))

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        entries: dict = {}
        for f in findings:
            if f.suppressed in (SUPPRESSED_INLINE, SUPPRESSED_FILE):
                continue            # inline allows own their findings
            k = cls.key(f)
            entries[k] = entries.get(k, 0) + 1
        return cls(entries)

    def write(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"version": 1,
                       "entries": dict(sorted(self.entries.items()))},
                      fh, indent=1, sort_keys=False)
            fh.write("\n")
        os.replace(tmp, path)

    def apply(self, findings: Iterable[Finding]) -> None:
        budget = dict(self.entries)
        for f in findings:
            if f.suppressed is not None:
                continue
            k = self.key(f)
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                f.suppressed = SUPPRESSED_BASELINE
                f.reason = "baselined"


# ---------------------------------------------------------------------------
# the linter
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Result:
    findings: list               # every finding, suppressed included
    files: list                  # relpaths scanned
    wall_s: float
    bare_allows: list            # (path, line) allows ignored for no reason=

    @property
    def unsuppressed(self) -> list:
        return [f for f in self.findings if f.suppressed is None]

    def by_rule(self, rule_id: str) -> list:
        return [f for f in self.findings if f.rule == rule_id]


_SKIP_DIRS = {"__pycache__", ".git", ".bench_cache", ".pytest_cache",
              "node_modules"}


#: the checked-in grandfather list, auto-loaded (root-relative) by
#: EVERY Linter unless a baseline is passed explicitly — the CLI, the
#: tier-1 tests, and bench.py's lint_clean line must agree about the
#: same tree (pass ``baseline=Baseline()`` to opt out)
DEFAULT_BASELINE_RELPATH = os.path.join("tools", "graftlint",
                                        "baseline.json")


class Linter:
    def __init__(self, root: str, config: Optional[dict] = None,
                 rules: Optional[list] = None,
                 baseline: Optional[Baseline] = None):
        from tools.graftlint.rules import default_rules

        self.root = os.path.abspath(root)
        self.config = merged_config(config)
        rule_classes = rules if rules is not None else default_rules()
        self.rules = [cls(self.config.get(cls.id, {}))
                      for cls in rule_classes]
        if baseline is None:
            default = os.path.join(self.root, DEFAULT_BASELINE_RELPATH)
            baseline = Baseline.load(default) \
                if os.path.exists(default) else Baseline()
        self.baseline = baseline
        self._doc_names: Optional[set] = None
        self._doc_loaded = False

    # -- shared context ----------------------------------------------------

    def doc_metric_names(self, doc_rel: str) -> Optional[set]:
        """Metric names documented as table rows in docs/monitoring.md
        (None when the file doesn't exist under this root — fixture
        trees — in which case the doc-row check is skipped)."""
        if not self._doc_loaded:
            self._doc_loaded = True
            path = os.path.join(self.root, doc_rel)
            if os.path.exists(path):
                with open(path) as fh:
                    text = fh.read()
                row = re.compile(r"^\|\s*`([a-z0-9_.]+)`\s*\|",
                                 re.MULTILINE)
                self._doc_names = set(row.findall(text))
        return self._doc_names

    # -- file discovery ----------------------------------------------------

    def discover(self, paths: Iterable[str]) -> list:
        files: list = []
        seen: set = set()
        for p in paths:
            p = p if os.path.isabs(p) else os.path.join(self.root, p)
            if os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = sorted(d for d in dirnames
                                         if d not in _SKIP_DIRS)
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            full = os.path.join(dirpath, fn)
                            if full not in seen:
                                seen.add(full)
                                files.append(full)
            elif p.endswith(".py") and os.path.exists(p):
                if p not in seen:
                    seen.add(p)
                    files.append(p)
        return files

    # -- run ---------------------------------------------------------------

    def run(self, paths: Iterable[str]) -> Result:
        t0 = time.monotonic()
        findings: list = []
        scanned: list = []
        bare_allows: list = []
        for path in self.discover(paths):
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            scanned.append(rel)
            active = [r for r in self.rules
                      if in_scope(rel, r.options.get("scope", []))]
            if not active:
                continue
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
            try:
                ms = ModuleSource(path, rel, text)
            except SyntaxError as e:
                findings.append(Finding(
                    rule="parse-error", path=rel, line=e.lineno or 0,
                    col=e.offset or 0, message=f"syntax error: {e.msg}",
                    snippet=""))
                continue
            bare_allows.extend(
                (rel, a.line) for a in ms.allows if not a.reason)
            for rule in active:
                for f in rule.check(ms, self):
                    f.rule = rule.id
                    f.path = rel
                    if not f.snippet:
                        f.snippet = ms.snippet(f.line)
                    self._suppress_inline(f, rule, ms)
                    findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        self.baseline.apply(findings)
        return Result(findings=findings, files=scanned,
                      wall_s=time.monotonic() - t0,
                      bare_allows=bare_allows)

    @staticmethod
    def _suppress_inline(f: Finding, rule: Rule, ms: ModuleSource) -> None:
        ids_for = {rule.id, rule.alias, "*"}
        for a in ms.allows:
            if not a.reason or not (a.ids & ids_for):
                continue
            if a.kind == "allow-file":
                f.suppressed = SUPPRESSED_FILE
                f.reason = a.reason
                return
            if a.target_line == f.line:
                f.suppressed = SUPPRESSED_INLINE
                f.reason = a.reason
                return
