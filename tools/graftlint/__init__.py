"""graftlint — the repo's AST-rule static-analysis engine (ISSUE 15).

Three generations of review-hardening caught the same bug classes by
hand: data-dependent op-scans sneaking into kernels, host syncs inside
jitted code, blocking I/O under the scheduler condition variable, and
metric/clock discipline drift. graftlint pins those invariants as
auto-discovering AST rules instead of per-directory module-count pins
someone forgets to bump.

Entry points:

* ``python -m tools.graftlint [paths...]`` — the CLI (``scripts/lint.sh``)
* :class:`tools.graftlint.engine.Linter` — the library API
  (``tests/test_lint.py``, ``bench.py --evidence``'s ``lint_clean`` line)

Rule catalog + suppression/baseline workflow: docs/static-analysis.md.
"""

from tools.graftlint.engine import Baseline, Finding, Linter, Result

__all__ = ["Baseline", "Finding", "Linter", "Result"]
