"""Text and JSON reporters."""

from __future__ import annotations

import json

from tools.graftlint.engine import Result

JSON_FORMAT = "graftlint-v1"


def render_text(result: Result, *, show_suppressed: bool = False) -> str:
    lines = []
    for f in result.findings:
        if f.suppressed is not None and not show_suppressed:
            continue
        tag = f" (suppressed:{f.suppressed} — {f.reason})" \
            if f.suppressed else ""
        lines.append(f"{f.path}:{f.line}:{f.col}: [{f.rule}] "
                     f"{f.message}{tag}")
        if f.snippet:
            lines.append(f"    {f.snippet}")
    for path, line in result.bare_allows:
        lines.append(f"{path}:{line}: [graftlint] allow comment has no "
                     "reason= — it is INERT (every suppression must "
                     "say why)")
    n = len(result.unsuppressed)
    supp = len(result.findings) - n
    lines.append(
        f"graftlint: {len(result.files)} files, {n} finding(s)"
        + (f" ({supp} suppressed)" if supp else "")
        + f", {result.wall_s:.2f}s")
    return "\n".join(lines)


def render_json(result: Result, root: str) -> str:
    return json.dumps({
        "format": JSON_FORMAT,
        "root": root,
        "summary": {
            "files": len(result.files),
            "findings": len(result.findings),
            "unsuppressed": len(result.unsuppressed),
            "suppressed": len(result.findings)
            - len(result.unsuppressed),
            "bare_allows": len(result.bare_allows),
            "wall_s": round(result.wall_s, 4),
        },
        "findings": [f.to_dict() for f in result.findings],
    }, indent=1)
