"""R5 — clock injection discipline.

Historical bug shape: obs/slo and olap/serving/autotune are tested
against fake clocks (burn windows, cooldown hysteresis, decision
journals); one bare ``time.time()`` on a code path those tests cover
reintroduces wall-clock flakiness that only shows up under load. The
convention: a module that DECLARES an injectable clock seam (any
function parameter named ``clock``) must route every read through it.

The seam default itself (``clock or time.time``, ``clock=time.time``)
is a function REFERENCE, not a call, so it never trips the rule.
Modules with no seam (e.g. obs/devprof) are out of scope — the rule
enforces consistency where the seam exists, it doesn't mandate seams.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import Finding, Rule


def _declares_seam(ms) -> bool:
    for node in ast.walk(ms.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = node.args
            for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
                if p.arg == "clock":
                    return True
    return False


class ClockSeamRule(Rule):
    id = "clock-seam"
    alias = "R5"
    description = ("bare time.time()/time.monotonic() in modules that "
                   "declare an injectable clock seam")

    def check(self, ms, ctx) -> Iterator[Finding]:
        if not _declares_seam(ms):
            return
        for node in ast.walk(ms.tree):
            if not isinstance(node, ast.Call):
                continue
            canon = ms.canonical(node.func) or ""
            bare = canon in ("time.time", "time.monotonic") or (
                isinstance(node.func, ast.Name)
                and node.func.id in ms.clockfn_names)
            if bare:
                yield Finding(
                    rule="", path="", line=node.lineno,
                    col=node.col_offset,
                    message=f"bare {canon or node.func.id}() in a "
                            "module that declares an injectable clock "
                            "seam — route it through the seam "
                            "(self.clock()) so fake-clock tests stay "
                            "honest")
