"""R3 — no blocking work under the serving/live locks.

Historical bug (PR-10 review hardening): ``_requeue``'s cancel-race
finalize wrote a flight-recorder postmortem bundle INSIDE ``with
self._cv:`` — a slow dump directory stalled submit/get/cancel for
every caller. The fix moved the write outside the cv; this rule pins
the shape: file I/O, subprocess spawns, HTTP, ``time.sleep`` and
device dispatch are banned lexically inside ``with self._cv:`` /
``with self._lock:`` blocks in the serving and live planes.

``cv.wait`` / ``cv.notify`` are of course fine (they're the point of
holding the cv), as are plain state mutation and clock READS. Nested
function bodies defined under a lock are skipped — they don't run
while the lock is held.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from tools.graftlint.engine import Finding, Rule
from tools.graftlint.jitgraph import walk_no_nested_fns

_LOCK_ATTRS = ("_cv", "_lock")


def _lock_name(expr) -> Optional[str]:
    """`self._cv` / `anything._lock` / `x._foo_lock` -> display name."""
    if isinstance(expr, ast.Attribute) and (
            expr.attr in _LOCK_ATTRS
            or expr.attr.endswith("_lock") or expr.attr.endswith("_cv")):
        return expr.attr
    return None


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    alias = "R3"
    description = ("blocking I/O / sleep / device dispatch inside "
                   "`with self._cv:` / `with self._lock:` blocks")

    def check(self, ms, ctx) -> Iterator[Finding]:
        for node in ast.walk(ms.tree):
            if not isinstance(node, ast.With):
                continue
            lock = next((_lock_name(item.context_expr)
                         for item in node.items
                         if _lock_name(item.context_expr)), None)
            if lock is None:
                continue
            for inner in walk_no_nested_fns(node.body):
                if isinstance(inner, ast.Call):
                    why = self._blocking(ms, inner)
                    if why:
                        yield Finding(
                            rule="", path="", line=inner.lineno,
                            col=inner.col_offset,
                            message=f"{why} while holding {lock} — "
                                    "move it outside the critical "
                                    "section (the PR-10 _requeue "
                                    "stall shape)")

    def _blocking(self, ms, call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name):
            if func.id == "open":
                return "file I/O (open)"
            if func.id in ms.sleep_names:
                return "time.sleep"
            return None
        canon = ms.canonical(func) or ""
        if canon == "time.sleep":
            return "time.sleep"
        if canon.startswith("subprocess."):
            return f"subprocess spawn ({canon})"
        if canon.startswith(("urllib.", "requests.", "http.",
                             "socket.")):
            return f"blocking network call ({canon})"
        if canon in ("os.replace", "os.rename", "os.fsync",
                     "json.dump", "pickle.dump") \
                or canon.startswith("shutil."):
            return f"file I/O ({canon})"
        if canon in ("jax.device_put", "jax.device_get") \
                or canon.startswith(("jnp.", "jax.numpy.")):
            return f"device dispatch ({canon})"
        if isinstance(func, ast.Attribute) \
                and func.attr == "block_until_ready":
            return "device sync (.block_until_ready)"
        return None
