"""R4 — metric-name discipline.

Historical bug: metric names drifted from docs/monitoring.md until
tests/test_docs_metrics.py started pinning the family list by hand.
This rule checks at the CREATION site: every string LITERAL passed to
``metrics.counter/timer/histogram/gauge`` must

* parse as ``<family>.<component>.<leaf...>`` with the family in the
  pinned set (the same families test_docs_metrics._FAMILIES guards —
  keep the two lists in sync), and
* have a ``| `name` | ... |`` row in docs/monitoring.md.

f-strings with placeholders are templated names — those are expanded
and guarded by test_docs_metrics's registered expansions, so they're
skipped here. Names passed through variables/constants are invisible
to a literal scan by design; the doc-drift test still catches them.
When the linted root has no docs/monitoring.md (fixture trees), only
the family check runs.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from tools.graftlint.engine import Finding, Rule

_CREATORS = {"counter", "timer", "histogram", "gauge"}


def _literal_name(arg) -> Optional[str]:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        if all(isinstance(v, ast.Constant) for v in arg.values):
            return "".join(v.value for v in arg.values)
    return None


class MetricNameRule(Rule):
    id = "metric-name"
    alias = "R4"
    description = ("literal metric names must be <family>.<x>.<y> in "
                   "the pinned families with a docs/monitoring.md row")

    def check(self, ms, ctx) -> Iterator[Finding]:
        families = self.options.get("families", [])
        pattern = re.compile(
            r"^(?:" + "|".join(map(re.escape, families))
            + r")\.[a-z0-9_]+\.[a-z0-9_.]+$")
        doc_rel = self.options.get("doc", "docs/monitoring.md")
        doc_names = ctx.doc_metric_names(doc_rel)
        for node in ast.walk(ms.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _CREATORS and node.args):
                continue
            name = _literal_name(node.args[0])
            if name is None:
                continue        # variable or templated — not ours
            if not pattern.match(name):
                yield Finding(
                    rule="", path="", line=node.lineno,
                    col=node.col_offset,
                    message=f"metric name {name!r} is outside the "
                            f"pinned families ({'|'.join(families)}, "
                            ">= 3 dot components) — rename it or "
                            "extend tests/test_docs_metrics._FAMILIES "
                            "and this rule's config together")
            elif doc_names is not None and name not in doc_names:
                yield Finding(
                    rule="", path="", line=node.lineno,
                    col=node.col_offset,
                    message=f"metric name {name!r} has no "
                            f"docs/monitoring.md table row — add one "
                            "(the doc-drift guard will hold it)")
