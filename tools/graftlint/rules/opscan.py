"""R1 — the op-scan ban (docs/performance.md, ISSUE r6).

Historical bug: data-dependent ``jnp.nonzero`` scans sneaking back
into per-round kernels. XLA lowers them through an n-wide sort (or a
host sync for the unbounded form); ops/compaction.py exists precisely
so no kernel pays that. The old guard was a hand-maintained module
list with per-directory count pins in tests/test_compaction.py; this
rule auto-discovers every ``titan_tpu/`` module instead.

Two tiers:

* ``jnp.nonzero`` / ``jnp.flatnonzero`` / ``jnp.argwhere`` are banned
  OUTRIGHT (size= or not) — bounded forms must go through
  ops.compaction so the contract stays in one place. The two
  non-round-loop reference models (models/bfs.py,
  models/bfs_hybrid_fused.py) carry file-level suppressions.
* the METHOD spellings ``x.nonzero()`` / ``x.flatnonzero()`` are the
  same op-scan wearing an attribute — banned too (the tree's host-side
  idiom is the ``np.nonzero(...)`` function form, which stays legal);
* ``jnp.unique`` and single-argument ``jnp.where`` (with or without
  ``size=`` — the sized form is ``jnp.nonzero(size=)`` renamed) are
  banned everywhere.
* boolean-mask indexing (``arr[mask > 0]``) inside a registered jitted
  kernel is a data-dependent gather — banned (``.at[mask]`` scatter
  updates are fixed-shape and stay legal).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import Finding, Rule
from tools.graftlint.jitgraph import jitted_functions

_HARD_BANNED = {"jnp.nonzero", "jnp.flatnonzero", "jnp.argwhere"}


def _canon(ms, func) -> str:
    d = ms.canonical(func) or ""
    # `import jax` modules reach jax.numpy.X without a jnp alias
    if d.startswith("jax.numpy."):
        d = "jnp." + d[len("jax.numpy."):]
    return d


def _is_bool_mask(node) -> bool:
    if isinstance(node, (ast.Compare, ast.BoolOp)):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Invert):
        return _is_bool_mask(node.operand)
    return False


class OpScanRule(Rule):
    id = "opscan"
    alias = "R1"
    description = ("n-wide jnp op-scans (nonzero/flatnonzero/unique/"
                   "1-arg where) and boolean-mask indexing in kernels "
                   "— use ops.compaction")

    def check(self, ms, ctx) -> Iterator[Finding]:
        for node in ast.walk(ms.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _canon(ms, node.func)
            if name in _HARD_BANNED:
                sized = any(k.arg == "size" for k in node.keywords)
                how = ("bounded, but the op-scan contract lives in "
                       "ops.compaction — use compact_ids/scatter_compact"
                       if sized else
                       "unbounded: data-dependent output shape")
                yield Finding(
                    rule="", path="", line=node.lineno,
                    col=node.col_offset,
                    message=f"{name} is banned in titan_tpu/ ({how})")
            elif name == "jnp.unique":
                yield Finding(
                    rule="", path="", line=node.lineno,
                    col=node.col_offset,
                    message="jnp.unique is banned: data-dependent "
                            "output shape (sort + scan per call)")
            elif name == "jnp.where" and len(node.args) == 1:
                sized = any(k.arg == "size" for k in node.keywords)
                yield Finding(
                    rule="", path="", line=node.lineno,
                    col=node.col_offset,
                    message="single-argument jnp.where is jnp.nonzero "
                            "in disguise ("
                            + ("bounded by size=, but the op-scan "
                               "contract lives in ops.compaction"
                               if sized else "unbounded op-scan")
                            + ") — use compact_ids/scatter_compact")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("nonzero", "flatnonzero") \
                    and not node.args and not node.keywords:
                yield Finding(
                    rule="", path="", line=node.lineno,
                    col=node.col_offset,
                    message=f".{node.func.attr}() method call is the "
                            "same op-scan as the banned function form "
                            "— use ops.compaction (host code uses the "
                            "np.nonzero(...) function spelling)")
        # boolean-mask indexing only means a data-dependent gather when
        # the array is traced — check inside registered kernels only
        for jf in jitted_functions(ms):
            for node in ast.walk(jf.node):
                if not isinstance(node, ast.Subscript):
                    continue
                if isinstance(node.value, ast.Attribute) \
                        and node.value.attr == "at":
                    continue    # .at[mask].set() is a fixed-shape scatter
                if _is_bool_mask(node.slice):
                    yield Finding(
                        rule="", path="", line=node.lineno,
                        col=node.col_offset,
                        message="boolean-mask indexing inside a jitted "
                                "kernel is a data-dependent gather — "
                                "compact through ops.compaction (kernel "
                                f"registered at line {jf.reg_line})")
