"""Rule registry. Adding a rule = write the module, list it here, add
its config entry (scope + options) to config.DEFAULT_CONFIG, and give
it a fixture pair under tests/fixtures/graftlint/."""

from tools.graftlint.rules.clockseam import ClockSeamRule
from tools.graftlint.rules.hostsync import HostSyncRule
from tools.graftlint.rules.lockdiscipline import LockDisciplineRule
from tools.graftlint.rules.metricnames import MetricNameRule
from tools.graftlint.rules.opscan import OpScanRule


def default_rules() -> list:
    return [OpScanRule, HostSyncRule, LockDisciplineRule,
            MetricNameRule, ClockSeamRule]


def rule_ids() -> dict:
    """{id-or-alias: id} for CLI --rules / suppression validation."""
    out = {}
    for cls in default_rules():
        out[cls.id] = cls.id
        out[cls.alias] = cls.id
    return out
