"""R2 — host syncs inside registered jitted kernels.

Historical bug: a ``.item()`` / ``int(...)`` coercion or a Python
``if`` on a traced value inside a kernel forces a device->host
round trip per dispatch (~0.1-0.9 s through the axon tunnel, and they
don't pipeline — PERF_NOTES). The kernels are found by following the
``jit_once`` / ``mesh_jit`` registration call sites (tools/graftlint/
jitgraph.py), NOT by name heuristics; parameters listed in
static_argnames/static_argnums are compile-time constants and stay
fair game for Python control flow.

``x.shape`` / ``x.ndim`` / ``x.dtype`` off a traced array are static
metadata — expressions that only touch those are never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import Finding, Rule
from tools.graftlint.jitgraph import jitted_functions

_STATIC_ATTRS = {"shape", "ndim", "dtype"}
_COERCIONS = {"int", "float", "bool"}


def _refs_traced(node, traced) -> bool:
    """Does this expression read a traced parameter (outside static
    .shape/.ndim/.dtype metadata access)?"""
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return False
    if isinstance(node, ast.Name):
        return node.id in traced
    return any(_refs_traced(c, traced)
               for c in ast.iter_child_nodes(node))


class HostSyncRule(Rule):
    id = "host-sync"
    alias = "R2"
    description = (".item()/int()/np.asarray/device_get/Python-if on "
                   "traced values inside jit_once/mesh_jit kernels")

    def check(self, ms, ctx) -> Iterator[Finding]:
        for jf in jitted_functions(ms):
            where = (f"kernel {jf.key!r}" if jf.key
                     else f"kernel registered at line {jf.reg_line}")
            for node in ast.walk(jf.node):
                if isinstance(node, ast.Call):
                    yield from self._check_call(ms, node, jf, where)
                elif isinstance(node, (ast.If, ast.While)):
                    if _refs_traced(node.test, jf.traced):
                        kw = ("if" if isinstance(node, ast.If)
                              else "while")
                        yield Finding(
                            rule="", path="", line=node.lineno,
                            col=node.col_offset,
                            message=f"Python `{kw}` on a traced value "
                                    f"inside {where} forces a host "
                                    "sync per dispatch — use "
                                    "lax.cond/jnp.where/lax.while_loop")

    def _check_call(self, ms, node, jf, where) -> Iterator[Finding]:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not node.args:
            yield Finding(
                rule="", path="", line=node.lineno, col=node.col_offset,
                message=f".item() inside {where} is a blocking "
                        "device->host transfer per dispatch")
            return
        canon = ms.canonical(func) or ""
        if canon == "jax.device_get":
            yield Finding(
                rule="", path="", line=node.lineno, col=node.col_offset,
                message=f"jax.device_get inside {where} is a blocking "
                        "device->host transfer")
        elif canon in ("np.asarray", "np.array"):
            yield Finding(
                rule="", path="", line=node.lineno, col=node.col_offset,
                message=f"{canon} inside {where} materializes a traced "
                        "value on host (use jnp.asarray)")
        elif isinstance(func, ast.Name) and func.id in _COERCIONS \
                and node.args \
                and _refs_traced(node.args[0], jf.traced):
            yield Finding(
                rule="", path="", line=node.lineno, col=node.col_offset,
                message=f"{func.id}() coerces a traced value inside "
                        f"{where} — a host sync per dispatch (keep it "
                        "on device, or make the argument static)")
