"""Per-rule configuration: scopes and options.

A rule runs on a file iff the file's root-relative posix path matches
one of the rule's ``scope`` entries (an entry ending in ``/`` is a
directory prefix, anything else an exact path). Scopes are PREFIXES,
not pins: a brand-new ``titan_tpu/anything/`` subdirectory is covered
the moment it exists — that auto-discovery is the whole point (the
per-directory module-count pins this engine replaced had to be bumped
by hand in every PR; see docs/static-analysis.md).

Tests lint fixture trees by pointing ``Linter(root=...)`` at a
directory whose layout mirrors these prefixes — the shipped scopes
apply unchanged, so a fixture proves the rule as configured, not a
laboratory variant.
"""

from __future__ import annotations

import copy

DEFAULT_CONFIG: dict = {
    # R1 — the op-scan ban (docs/performance.md, ISSUE r6): the whole
    # package plus bench.py's eager device paths. Everything else
    # (tests, experiments) may use op-scans as oracles.
    "opscan": {
        "scope": ["titan_tpu/", "bench.py"],
    },
    # R2 — host syncs inside kernels registered through
    # utils/jitcache.jit_once / parallel/mesh.mesh_jit. The scope is
    # wide; the rule itself only fires inside functions it resolved
    # from a registration call site.
    "host-sync": {
        "scope": ["titan_tpu/", "bench.py"],
    },
    # R3 — blocking work under the serving/live locks (the PR-10
    # `_requeue` postmortem-write stall).
    "lock-discipline": {
        "scope": ["titan_tpu/olap/serving/", "titan_tpu/olap/live/"],
    },
    # R4 — literal metric names must parse into a guarded family and
    # have a docs/monitoring.md row (tests/test_docs_metrics.py pins
    # the same families; keep the two lists in sync).
    "metric-name": {
        "scope": ["titan_tpu/"],
        "families": ["serving", "device", "flightrec", "controller",
                     "scan", "obs", "fleet"],
        "doc": "docs/monitoring.md",
    },
    # R5 — modules that declare an injectable clock seam (a `clock`
    # parameter) must not also read the wall clock directly.
    "clock-seam": {
        "scope": ["titan_tpu/obs/", "titan_tpu/olap/serving/"],
    },
}


def merged_config(overrides: dict | None) -> dict:
    """DEFAULT_CONFIG with per-rule overrides merged in (an override
    replaces keys, not the whole rule entry)."""
    cfg = copy.deepcopy(DEFAULT_CONFIG)
    for rule_id, entry in (overrides or {}).items():
        cfg.setdefault(rule_id, {}).update(entry)
    return cfg


def in_scope(relpath: str, scope: list) -> bool:
    return any(relpath == s or (s.endswith("/") and relpath.startswith(s))
               for s in scope)
