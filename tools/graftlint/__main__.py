"""``python -m tools.graftlint`` — the CLI (scripts/lint.sh wraps it).

Exit codes: 0 clean, 1 unsuppressed findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

from tools.graftlint.engine import (DEFAULT_BASELINE_RELPATH, Baseline,
                                    Linter)
from tools.graftlint.report import render_json, render_text
from tools.graftlint.rules import rule_ids

DEFAULT_PATHS = ["titan_tpu", "tests", "bench.py"]
DEFAULT_BASELINE = DEFAULT_BASELINE_RELPATH


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="AST-rule static analysis for the titan_tpu tree "
                    "(rule catalog: docs/static-analysis.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: "
                         f"{' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--root", default=os.getcwd(),
                    help="repo root scopes/baseline resolve against "
                         "(default: cwd)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids/aliases to run "
                         "(default: all)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} "
                         "under --root when present; 'none' disables)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="re-grandfather: write every current finding "
                         "to the baseline file and exit 0")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include suppressed findings in text output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    ids = rule_ids()
    if args.list_rules:
        from tools.graftlint.rules import default_rules
        for cls in default_rules():
            print(f"{cls.alias:>3} {cls.id:<16} {cls.description}")
        return 0

    root = os.path.abspath(args.root)
    baseline_path = args.baseline
    if baseline_path is None:
        cand = os.path.join(root, DEFAULT_BASELINE)
        baseline_path = cand if os.path.exists(cand) else "none"
    if args.write_baseline or baseline_path == "none":
        # regeneration re-grandfathers from scratch — the target not
        # existing yet is the bootstrap case, not an error
        baseline = Baseline()
    elif not os.path.exists(baseline_path):
        print(f"graftlint: baseline file not found: {baseline_path} "
              "(pass --baseline none to lint without one)",
              file=sys.stderr)
        return 2
    else:
        baseline = Baseline.load(baseline_path)

    rules = None
    if args.rules:
        wanted = set()
        for tok in args.rules.split(","):
            tok = tok.strip()
            if tok not in ids:
                print(f"graftlint: unknown rule {tok!r} "
                      f"(known: {', '.join(sorted(ids))})",
                      file=sys.stderr)
                return 2
            wanted.add(ids[tok])
        from tools.graftlint.rules import default_rules
        rules = [c for c in default_rules() if c.id in wanted]

    paths = args.paths or [p for p in DEFAULT_PATHS
                           if os.path.exists(os.path.join(root, p))]
    if not paths:
        print("graftlint: nothing to lint", file=sys.stderr)
        return 2

    linter = Linter(root=root, rules=rules, baseline=baseline)
    result = linter.run(paths)

    if args.write_baseline:
        target = baseline_path if baseline_path != "none" \
            else os.path.join(root, DEFAULT_BASELINE)
        for f in result.findings:       # re-grandfather everything
            if f.suppressed == "baseline":
                f.suppressed = None
        Baseline.from_findings(result.findings).write(target)
        print(f"graftlint: wrote {target} "
              f"({len(result.unsuppressed)} entr(ies))")
        return 0

    print(render_json(result, root) if args.json
          else render_text(result, show_suppressed=args.show_suppressed))
    return 1 if result.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
