#!/usr/bin/env python
"""Benchmark: Graph500 BFS TEPS on the TPU OLAP engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The headline is Graph500 scale-26 BFS TEPS on the attached accelerator
(BASELINE.md row 1 targets >= 1B TEPS on a v5e-8; a single chip's share is
125M). The graph is host-built (native C++ R-MAT + symmetrize/dedup/chunk
CSR), disk-cached under .bench_cache/, and uploaded once; BFS runs the
direction-optimizing hybrid kernel (models/bfs_hybrid.py) with all state
on device and only scalar readbacks. TEPS follows the official Graph500
definition: input edge tuples (incl. duplicates/self-loops) with both
endpoints in the traversed component, i.e. sum of pre-dedup symmetrized
degrees over reached vertices / 2, divided by BFS wall time.

On CPU (no accelerator) a scale-16 graph keeps CI fast.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def bfs_teps(scale: int, edge_factor: int = 16, seed: int = 2,
             reps: int = 3, sources: int = 1) -> dict:
    import jax

    from titan_tpu.models.bfs import INF
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid
    from titan_tpu.olap.tpu import graph500

    t0 = time.time()
    hg = graph500.load_or_build(scale, edge_factor, seed=seed, verbose=False)
    gen_s = time.time() - t0

    # multi-chip: shard the edge arrays over a vertex mesh (sparse
    # found-list exchange; models/bfs_hybrid_sharded); single chip: the
    # plain hybrid kernel on the uploaded graph
    ndev = jax.device_count()
    t0 = time.time()
    if ndev > 1:
        from titan_tpu.models.bfs_hybrid_sharded import \
            frontier_bfs_hybrid_sharded
        from titan_tpu.parallel.mesh import vertex_mesh
        mesh = vertex_mesh(ndev)

        def run_bfs(source):
            return frontier_bfs_hybrid_sharded(hg, source, mesh,
                                               return_device=True)
        upload_s = 0.0          # sharded path uploads inside the first run
    else:
        g = graph500.to_device(hg)
        jax.block_until_ready(g["dstT"])

        def run_bfs(source):
            return frontier_bfs_hybrid(g, source, return_device=True)
        upload_s = time.time() - t0

    deg = np.asarray(hg["deg"])
    # Graph500 rule: sample DISTINCT sources with degree > 0
    rng = np.random.default_rng(12345)
    nonzero = np.flatnonzero(deg > 0)
    srcs = [int(s) for s in
            rng.choice(nonzero, size=min(sources, len(nonzero)),
                       replace=False)]

    # warm-up / compile
    t0 = time.time()
    dist, levels = run_bfs(srcs[0])
    jax.block_until_ready(dist)
    first_s = time.time() - t0

    deg_dev = graph500.device_degrees(np.asarray(hg["deg_orig"]))
    per_source = []
    for source in srcs:
        times = []
        for _ in range(reps):
            t0 = time.time()
            dist, levels = run_bfs(source)
            jax.block_until_ready(dist)
            times.append(time.time() - t0)
        t_bfs = min(times)
        m2, nreach = graph500.reachable_edge_sum(
            dist, np.asarray(hg["deg_orig"]), int(INF), deg_dev=deg_dev)
        per_source.append({"teps": (m2 // 2) / t_bfs, "t_bfs": t_bfs,
                           "levels": int(levels), "reach": nreach,
                           "m_traversed": m2 // 2, "source": source})
    # Graph500 reports the HARMONIC mean TEPS over the search keys; the
    # detail fields all come from one run (the fastest source) so they
    # stay mutually consistent
    rep = dict(max(per_source, key=lambda r: r["teps"]))
    rep["teps"] = len(per_source) / sum(1.0 / r["teps"]
                                        for r in per_source)
    rep.update({"gen_s": gen_s, "upload_s": upload_s, "first_s": first_s,
                "n": hg["n"], "e_sym_pre_dedup": hg["e_sym"],
                "e_dedup": hg["e_dedup"], "num_sources": len(per_source),
                "n_devices": ndev,
                "per_source_teps": [round(r["teps"], 1)
                                    for r in per_source]})
    return rep


def olap_matrix(scale: int, lj_scale: int = 22) -> dict:
    """BASELINE rows beyond BFS: SSSP + WCC at the bench scale and a
    LiveJournal-class (scale-22 EF16 ~ 67M directed edges, 4.2M vertices)
    PageRank seconds/iteration — the >=50x-vs-MapReduce comparison point
    (reference harness: titan-test TitanGraphIterativeBenchmark; Hadoop
    PageRank on LiveJournal-class graphs runs minutes per iteration)."""
    import jax

    from titan_tpu.models.frontier import (frontier_sssp, frontier_wcc,
                                           pagerank_dense)
    from titan_tpu.olap.tpu import graph500

    out = {}
    hg = graph500.load_or_build(scale, 16, seed=2, verbose=False)
    g = graph500.to_device(hg)
    deg = np.asarray(hg["deg"])
    source = int(np.flatnonzero(deg > 0)[0])

    d, _ = frontier_sssp(g, source, return_device=True)   # warm-up
    jax.block_until_ready(d)
    t0 = time.time()
    d, rounds = frontier_sssp(g, source, return_device=True)
    jax.block_until_ready(d)
    out["sssp_seconds"] = round(time.time() - t0, 3)
    out["sssp_rounds"] = rounds

    lab, _ = frontier_wcc(g, return_device=True)          # warm-up
    jax.block_until_ready(lab)
    t0 = time.time()
    lab, rounds = frontier_wcc(g, return_device=True)
    jax.block_until_ready(lab)
    out["wcc_seconds"] = round(time.time() - t0, 3)
    out["wcc_rounds"] = rounds

    if lj_scale and lj_scale != scale:
        hg2 = graph500.load_or_build(lj_scale, 16, seed=2, verbose=False)
        g2 = graph500.to_device(hg2)
    else:
        hg2, g2 = hg, g
    r, _ = pagerank_dense(g2, iterations=2, return_device=True)  # warm
    jax.block_until_ready(r)
    t0 = time.time()
    iters = 10
    r, _ = pagerank_dense(g2, iterations=iters, return_device=True)
    jax.block_until_ready(r)
    out["pagerank_lj_sec_per_iter"] = round((time.time() - t0) / iters, 3)
    out["pagerank_lj_edges"] = hg2["e_dedup"]
    return out


def ldbc_is3_4hop(tmp_dir: str | None = None,
                  n_persons: int = 10_000, avg_degree: int = 36) -> dict:
    """BASELINE row 4: LDBC-SNB-style interactive short-read latency on
    the embedded persistent store (BerkeleyJE role = sqlite here) — p50
    of a 4-hop friends expansion from sampled persons over an SF1-scale
    synthetic social graph (10k persons, ~180k knows edges), built once
    and cached on disk."""
    import shutil

    import titan_tpu

    base = tmp_dir or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".bench_cache",
        f"ldbc_{n_persons}")
    # a sentinel marks a COMPLETE build: open() itself creates the dir,
    # so dir-existence would treat an interrupted build as a valid cache
    sentinel = os.path.join(base, ".complete")
    fresh = not os.path.exists(sentinel)
    if fresh and os.path.exists(base):
        shutil.rmtree(base, ignore_errors=True)
    g = titan_tpu.open({"storage.backend": "sqlite",
                        "storage.directory": base})
    try:
        if fresh:
            rng = np.random.default_rng(7)
            tx = g.new_transaction()
            people = [tx.add_vertex("person", name=f"p{i}")
                      for i in range(n_persons)]
            m = n_persons * avg_degree // 2
            for a, b in zip(rng.integers(0, n_persons, m),
                            rng.integers(0, n_persons, m)):
                if a != b:
                    people[int(a)].add_edge("knows", people[int(b)])
            tx.commit()
            with open(sentinel, "w") as f:
                f.write("ok")
        rng = np.random.default_rng(99)
        tx = g.new_transaction()
        ids = [v.id for i, v in zip(range(200), tx.vertices())]
        tx.rollback()
        srcs = [ids[int(i)] for i in rng.integers(0, len(ids), 12)]
        lat = []
        counts = []
        for vid in srcs:
            t0 = time.time()
            c = g.traversal().V(vid).out("knows").out("knows") \
                .out("knows").out("knows").count().next()
            lat.append(time.time() - t0)
            counts.append(c)
        lat.sort()
        return {"ldbc_is3_4hop_p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
                "ldbc_is3_4hop_p95_ms": round(lat[-1] * 1e3, 2),
                "ldbc_persons": n_persons,
                "ldbc_4hop_median_reach": int(sorted(counts)[len(counts)//2])}
    finally:
        g.close()
        if tmp_dir is not None:
            shutil.rmtree(base, ignore_errors=True)


def gods_2hop() -> tuple[float, int]:
    """BASELINE config #1: GraphOfTheGods 2-hop Gremlin count on inmemory
    (OLTP traversal latency, p50 of 20 runs)."""
    import titan_tpu
    from titan_tpu import example

    g = titan_tpu.open("inmemory")
    example.load(g)
    two = lambda: g.traversal().V().out().out().count().next()  # noqa: E731
    count = two()
    lat = []
    for _ in range(20):
        t = time.time()
        two()
        lat.append(time.time() - t)
    g.close()
    return sorted(lat)[len(lat) // 2] * 1e3, int(count)


def main() -> None:
    import jax

    try:
        # persist compiled executables across bench processes (first-run
        # compiles go through the axon tunnel at ~10-60s per shape bucket)
        import os
        cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".bench_cache", "xla")
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:
        pass

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else (26 if on_accel
                                                       else 16)

    r = bfs_teps(scale)
    lj_scale = 22 if on_accel else min(scale, 14)
    olap = olap_matrix(scale, lj_scale=lj_scale)
    olap.update(ldbc_is3_4hop() if on_accel
                else ldbc_is3_4hop(n_persons=1000, avg_degree=10))
    twohop_ms, count2 = gods_2hop()

    print(json.dumps({
        "metric": f"graph500_scale{scale}_bfs_teps",
        "value": round(r["teps"], 1),
        "unit": "TEPS",
        "vs_baseline": round(r["teps"] / 1e9, 4),
        "detail": {
            "platform": platform,
            "n_devices": r["n_devices"],
            "num_sources": r["num_sources"],
            "n_vertices": r["n"],
            "m_input_sym_edges": r["e_sym_pre_dedup"],
            "m_dedup_edges": r["e_dedup"],
            "bfs_levels": r["levels"],
            "reachable_vertices": r["reach"],
            "m_traversed": r["m_traversed"],
            "bfs_seconds": round(r["t_bfs"], 4),
            "first_run_seconds": round(r["first_s"], 2),
            "graph_build_seconds": round(r["gen_s"], 2),
            "upload_seconds": round(r["upload_s"], 2),
            "gods_2hop_p50_ms": round(twohop_ms, 3),
            "gods_2hop_count": count2,
            **olap,
        },
    }))


if __name__ == "__main__":
    main()
