#!/usr/bin/env python
"""Benchmark: Graph500 BFS TEPS on the TPU OLAP engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The headline is Graph500 scale-26 BFS TEPS on the attached accelerator
(BASELINE.md row 1 targets >= 1B TEPS on a v5e-8; a single chip's share is
125M). The graph is host-built (native C++ R-MAT + symmetrize/dedup/chunk
CSR), disk-cached under .bench_cache/, and uploaded once; BFS runs the
direction-optimizing hybrid kernel (models/bfs_hybrid.py) with all state
on device and only scalar readbacks. TEPS follows the official Graph500
definition: input edge tuples (incl. duplicates/self-loops) with both
endpoints in the traversed component, i.e. sum of pre-dedup symmetrized
degrees over reached vertices / 2, divided by BFS wall time.

On CPU (no accelerator) a scale-16 graph keeps CI fast.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def bfs_teps(scale: int, edge_factor: int = 16, seed: int = 2,
             reps: int = 3, sources: int = 1) -> dict:
    import jax

    from titan_tpu.models.bfs import INF
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid
    from titan_tpu.olap.tpu import graph500

    t0 = time.time()
    hg = graph500.load_or_build(scale, edge_factor, seed=seed, verbose=False)
    gen_s = time.time() - t0
    t0 = time.time()
    g = graph500.to_device(hg)
    jax.block_until_ready(g["dstT"])
    upload_s = time.time() - t0

    deg = np.asarray(hg["deg"])
    # Graph500 rule: sample sources with degree > 0
    rng = np.random.default_rng(12345)
    nonzero = np.flatnonzero(deg > 0)
    srcs = [int(nonzero[rng.integers(0, len(nonzero))])
            for _ in range(sources)]

    # warm-up / compile
    t0 = time.time()
    dist, levels = frontier_bfs_hybrid(g, srcs[0], return_device=True)
    jax.block_until_ready(dist)
    first_s = time.time() - t0

    deg_dev = graph500.device_degrees(np.asarray(hg["deg_orig"]))
    per_source = []
    for source in srcs:
        times = []
        for _ in range(reps):
            t0 = time.time()
            dist, levels = frontier_bfs_hybrid(g, source, return_device=True)
            jax.block_until_ready(dist)
            times.append(time.time() - t0)
        t_bfs = min(times)
        m2, nreach = graph500.reachable_edge_sum(
            dist, np.asarray(hg["deg_orig"]), int(INF), deg_dev=deg_dev)
        per_source.append({"teps": (m2 // 2) / t_bfs, "t_bfs": t_bfs,
                           "levels": int(levels), "reach": nreach,
                           "m_traversed": m2 // 2, "source": source})
    # Graph500 reports the MEAN TEPS over the sampled search keys
    rep = dict(max(per_source, key=lambda r: r["teps"]))
    rep["teps"] = sum(r["teps"] for r in per_source) / len(per_source)
    rep["t_bfs"] = sum(r["t_bfs"] for r in per_source) / len(per_source)
    rep.update({"gen_s": gen_s, "upload_s": upload_s, "first_s": first_s,
                "n": hg["n"], "e_sym_pre_dedup": hg["e_sym"],
                "e_dedup": hg["e_dedup"], "num_sources": len(per_source)})
    return rep


def gods_2hop() -> tuple[float, int]:
    """BASELINE config #1: GraphOfTheGods 2-hop Gremlin count on inmemory
    (OLTP traversal latency, p50 of 20 runs)."""
    import titan_tpu
    from titan_tpu import example

    g = titan_tpu.open("inmemory")
    example.load(g)
    two = lambda: g.traversal().V().out().out().count().next()  # noqa: E731
    count = two()
    lat = []
    for _ in range(20):
        t = time.time()
        two()
        lat.append(time.time() - t)
    g.close()
    return sorted(lat)[len(lat) // 2] * 1e3, int(count)


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else (26 if on_accel
                                                       else 16)

    r = bfs_teps(scale)
    twohop_ms, count2 = gods_2hop()

    print(json.dumps({
        "metric": f"graph500_scale{scale}_bfs_teps",
        "value": round(r["teps"], 1),
        "unit": "TEPS",
        "vs_baseline": round(r["teps"] / 1e9, 4),
        "detail": {
            "platform": platform,
            "n_vertices": r["n"],
            "m_input_sym_edges": r["e_sym_pre_dedup"],
            "m_dedup_edges": r["e_dedup"],
            "bfs_levels": r["levels"],
            "reachable_vertices": r["reach"],
            "m_traversed": r["m_traversed"],
            "bfs_seconds": round(r["t_bfs"], 4),
            "first_run_seconds": round(r["first_s"], 2),
            "graph_build_seconds": round(r["gen_s"], 2),
            "upload_seconds": round(r["upload_s"], 2),
            "gods_2hop_p50_ms": round(twohop_ms, 3),
            "gods_2hop_count": count2,
        },
    }))


if __name__ == "__main__":
    main()
