#!/usr/bin/env python
"""Benchmark driver: prints ONE cumulative JSON line after EVERY stage.

The harness parses the LAST stdout line, so a timeout costs only the
stages not yet reached — never the ones already measured (round-2
post-mortem: a single final print + a 27-minute compile stall recorded
nothing). A wall-clock budget (``BENCH_BUDGET_S``, default 1100 s = the
driver's OBSERVED external window; r4's internal 2400 s budget was
killed at ~1200 s) skips stages that no longer fit, noting them in
``detail.skipped``.

Stage order (the two BASELINE HARD targets first — the headline
literally first so no slow day can starve it — then measure rows, then
droppable evidence stages):
  1. bfs scale-26    — the headline (BASELINE.md row 1: >=1B on v5e-8,
                       125M/chip share); never budget-skipped
  2. pagerank s22    — LiveJournal-class s/iteration (>=50x-vs-MR row)
  3. gods_2hop       — GraphOfTheGods 2-hop Gremlin count, inmemory OLTP
  4. ldbc_is3_4hop   — LDBC-SNB-style 4-hop friends expansion p50, sqlite
  5. sssp/wcc        — Graph500 scale-26 SSSP + WCC seconds
  6. store_ingest    — bulk-load s22 through the edgestore, scan back to
                       a snapshot, BFS must match the generated graph
  7. bfs_heavy       — Twitter-2010-parity (1.5B-edge) single-chip BFS
  8. bfs23_sharded / bfs23 — warm-scale + sharded-overhead evidence

TEPS follows the official Graph500 definition: input edge tuples (incl.
duplicates/self-loops) with both endpoints in the traversed component /
BFS wall time; harmonic mean over sampled sources.

On CPU (no accelerator) small scales keep CI fast.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# r4 set 2400s and was killed externally at ~1200s (rc=124, losing the
# pagerank evidence stage) — stages must be planned against the real
# limit so the skip logic, not the kill, decides what is dropped
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1100"))
# the stage that owns the report metric line; ordered first and never
# budget-skipped
HEADLINE_STAGE = "bfs26"
_T_START = time.time()

# per-stage wall-clock estimates: a COMPUTE floor plus an UPLOAD
# component (GB through the H2D tunnel), so admission can be re-priced
# against the day's MEASURED tunnel rate instead of a guessed total
# (VERDICT r5 weak #2: bfs_heavy's flat 300s was a fast-day number on a
# tunnel PERF_NOTES documents as a ~30x envelope — it was admitted with
# 402s left and ate the external kill). ``fixed`` covers compiles +
# compute at a slow-day floor; upload cost = gb / measured rate.
_EST = {
    #             fixed_s  upload_gb
    "gods_2hop": (20,      0.0),
    "ldbc":      (90,      0.0),
    "bfs23":     (60,      1.2),
    "bfs23_sharded": (180, 2.4),   # shard replica + plain copy
    "bfs26":     (420,     9.0),
    "ssspwcc":   (300,     0.0),   # shares the resident s26 upload
    "pagerank":  (60,      0.6),
    "store_ingest": (550,  0.6),   # s22 ingest+scan is host-bound;
                                   # scale fallback below re-prices
    "bfs_heavy": (120,     11.6),  # 2 reps ~10s each + compiles
    "live_refresh": (90,   0.3),   # host-array merges + one s20 upload
    "serving":   (90,      0.1),   # small-graph batched BFS + retry
    "tenancy":   (60,      0.1),   # shares serving's kernel shapes
    "interactive": (90,    0.1),   # hops-mode fuse sweep + batched PPR
    "bfs_pallas": (150,    1.2),   # both-mode compiles + warm reps
    "segment_pallas": (60, 0.1),   # synthetic [E] array, two kernels
    "distributed_scan": (30, 0.0),  # host-only: 2 HTTP workers, tiny
                                    # graph, no device work at all
    "fleet": (45, 0.0),             # host-only: router + 2 in-process
                                    # replicas, CPU frontier kernels
}
# nominal fast-day H2D rate (GB/s): bfs26's 9GB uploaded in 16.35s
# (BENCH_r05); the headline stage's measured upload re-prices this
_H2D_NOMINAL_GBPS = 0.55
_h2d_gbps = _H2D_NOMINAL_GBPS
# nothing new starts inside this reserve before the external kill
# (the driver window is observed, not contractual — leave margin for
# the final emits)
_HARD_RESERVE_S = 60.0


def _est(name: str, on_accel: bool = True) -> float:
    fixed, gb = _EST.get(name, (60, 0.0))
    if not on_accel:
        return fixed
    return fixed + gb / max(_h2d_gbps, 1e-3)


def _observe_h2d(gb: float, seconds: float) -> None:
    """Re-price the tunnel from a measured upload (headline stage)."""
    global _h2d_gbps
    if gb > 0.5 and seconds > 0:
        _h2d_gbps = max(min(gb / seconds, 2.0), 0.005)


def _left() -> float:
    return BUDGET_S - (time.time() - _T_START)


class Report:
    """Cumulative result: emit() prints the full JSON line every time.

    ``headline()`` is a ONE-SHOT latch: the first call owns the
    metric/value/vs_baseline line for the rest of the run and every
    later call is ignored (VERDICT r5 weak #1: gods_2hop overwrote the
    scale-26 BFS TEPS headline, so the driver's record reported a 0.137
    ms OLTP latency as the round's metric while the real 156.8M-TEPS
    number sat buried in detail — the headline stage runs first
    precisely so it latches first)."""

    def __init__(self) -> None:
        self.metric = "bench_incomplete"
        self.value = 0.0
        self.unit = ""
        self.vs_baseline = 0.0
        self.detail: dict = {"skipped": [], "budget_s": BUDGET_S}
        self._latched = False

    def headline(self, metric: str, value: float, unit: str,
                 vs_baseline: float) -> None:
        if self._latched:
            return
        self.metric, self.value = metric, value
        self.unit, self.vs_baseline = unit, vs_baseline
        self._latched = True

    def emit(self) -> None:
        self.detail["elapsed_s"] = round(time.time() - _T_START, 1)
        print(json.dumps({
            "metric": self.metric, "value": self.value, "unit": self.unit,
            "vs_baseline": self.vs_baseline, "detail": self.detail,
        }), flush=True)

    def skip(self, stage: str, why: str) -> None:
        self.detail["skipped"].append({"stage": stage, "why": why})
        self.emit()


# device-graph cache shared across stages: the H2D upload of the scale-26
# arrays (9GB) can cost MINUTES through the axon tunnel on a bad day —
# never upload the same graph twice. ALL bench graphs stay resident
# (s22 0.56GB + s23 1.12GB + s26 9.03GB = 10.7GB of 16GB HBM, leaving
# ~3GB for kernel state/temporaries); largest-first eviction only under
# pressure. The budget/eviction logic is the serving layer's HBM library
# (olap/serving/hbm.py) — the same accounting the job scheduler admits
# against, no longer a script-local.
from titan_tpu.olap.serving.hbm import DeviceGraphCache  # noqa: E402

_DEV_GRAPHS = DeviceGraphCache(budget_bytes=12.0e9)


def _load_device_graph(scale: int, edge_factor: int = 16, seed: int = 2):
    import jax

    from titan_tpu.olap.tpu import graph500

    def upload(hg):
        g = graph500.to_device(hg)
        jax.block_until_ready(g["dstT"])
        return g

    hg, g, gen_s, upload_s = _DEV_GRAPHS.get_or_load(
        (scale, edge_factor, seed),
        lambda: graph500.load_or_build(scale, edge_factor, seed=seed,
                                       verbose=False),
        upload)
    if upload_s > 0:
        from titan_tpu.olap.serving.hbm import graph_bytes
        _observe_h2d(graph_bytes(hg) / 1e9, upload_s)
    return hg, g, gen_s, upload_s


def bfs_teps(scale: int, edge_factor: int = 16, seed: int = 2,
             reps: int = 3, sources: int = 1) -> dict:
    import jax

    from titan_tpu.models.bfs import INF
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid
    from titan_tpu.olap.tpu import graph500

    # multi-chip: shard the edge arrays over a vertex mesh (sparse
    # found-list exchange; models/bfs_hybrid_sharded); single chip: the
    # plain hybrid kernel on the uploaded (stage-shared) graph
    ndev = jax.device_count()
    if ndev > 1:
        t0 = time.time()
        hg = graph500.load_or_build(scale, edge_factor, seed=seed,
                                    verbose=False)
        gen_s = time.time() - t0
        from titan_tpu.models.bfs_hybrid_sharded import \
            frontier_bfs_hybrid_sharded
        from titan_tpu.parallel.mesh import vertex_mesh
        mesh = vertex_mesh(ndev)

        def run_bfs(source):
            return frontier_bfs_hybrid_sharded(hg, source, mesh,
                                               return_device=True)
        upload_s = 0.0          # sharded path uploads inside the first run
    else:
        hg, g, gen_s, upload_s = _load_device_graph(scale, edge_factor,
                                                    seed)

        def run_bfs(source):
            return frontier_bfs_hybrid(g, source, return_device=True)

    deg = np.asarray(hg["deg"])
    # Graph500 rule: sample DISTINCT sources with degree > 0
    rng = np.random.default_rng(12345)
    nonzero = np.flatnonzero(deg > 0)
    srcs = [int(s) for s in
            rng.choice(nonzero, size=min(sources, len(nonzero)),
                       replace=False)]

    # warm-up / compile
    t0 = time.time()
    dist, levels = run_bfs(srcs[0])
    jax.block_until_ready(dist)
    first_s = time.time() - t0

    # single-dispatch fused variant (device-side mode/bucket switch —
    # kills the per-level readback floor on slow-tunnel days). "auto":
    # only when a previous successful fused run at THIS scale left a
    # marker (the persistent compile cache is then warm for it) — a
    # cold fused compile costs many minutes through the tunnel, and
    # checking for mere cache entries would be fooled by the plain
    # hybrid's own warmup compiles.
    # default OFF: the persistent XLA cache does NOT survive processes
    # under the axon remote-compile backend (measured: a re-run pays
    # the full compile again), so the fused variant would cost its
    # multi-minute compile EVERY bench run for ~0.4s fast-day gain
    # (its value is slow-tunnel insurance — opt in when that matters)
    fused_mode = os.environ.get("TITAN_TPU_FUSED_BFS", "0")
    marker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".bench_cache", f"fused_warm_s{scale}.flag")
    run_fused = ndev == 1 and (
        fused_mode == "1"
        or (fused_mode == "auto" and os.path.exists(marker)))
    fused_fn = None
    fused_first_s = None
    fused_err = None
    if run_fused:
        from titan_tpu.models.bfs_hybrid_fused import \
            frontier_bfs_hybrid_fused

        def fused_fn(source):
            return frontier_bfs_hybrid_fused(g, source,
                                             return_device=True)
        t0 = time.time()
        try:
            dist_f, _ = fused_fn(srcs[0])
            jax.block_until_ready(dist_f)
            fused_first_s = time.time() - t0
        except Exception as e:       # e.g. OOM at this scale: skip
            fused_fn = None
            fused_err = f"{type(e).__name__}: {e}"
        if fused_fn is not None:
            # marker write OUTSIDE the run try-block (a marker failure
            # must not discard a good run) but fenced on its own: a
            # read-only FS must not abort the whole BFS stage either
            try:
                os.makedirs(os.path.dirname(marker), exist_ok=True)
                with open(marker, "w") as fh:
                    fh.write("ok\n")
            except OSError:
                pass                 # marker is an optimization only

    deg_dev = graph500.device_degrees(np.asarray(hg["deg_orig"]))
    per_source = []
    for source in srcs:
        times = []
        for _ in range(reps):
            t0 = time.time()
            dist, levels = run_bfs(source)
            jax.block_until_ready(dist)
            times.append(time.time() - t0)
        t_bfs = min(times)
        if fused_fn is not None:
            tf = []
            for _ in range(reps):
                t0 = time.time()
                dist_f, levels_f = fused_fn(source)
                jax.block_until_ready(dist_f)
                tf.append(time.time() - t0)
            if min(tf) < t_bfs:     # report the better variant
                t_bfs, dist, levels = min(tf), dist_f, levels_f
        m2, nreach = graph500.reachable_edge_sum(
            dist, np.asarray(hg["deg_orig"]), int(INF), deg_dev=deg_dev)
        per_source.append({"teps": (m2 // 2) / t_bfs, "t_bfs": t_bfs,
                           "levels": int(levels), "reach": nreach,
                           "m_traversed": m2 // 2, "source": source})
    # Graph500 reports the HARMONIC mean TEPS over the search keys; the
    # detail fields all come from one run (the fastest source) so they
    # stay mutually consistent
    rep = dict(max(per_source, key=lambda r: r["teps"]))
    rep["teps"] = len(per_source) / sum(1.0 / r["teps"]
                                        for r in per_source)
    rep.update({"gen_s": gen_s, "upload_s": upload_s, "first_s": first_s,
                "n": hg["n"], "e_sym_pre_dedup": hg["e_sym"],
                "e_dedup": hg["e_dedup"], "num_sources": len(per_source),
                "n_devices": ndev,
                "fused_variant_ran": fused_fn is not None,
                "fused_error": fused_err,
                "fused_first_s": round(fused_first_s, 2)
                if fused_first_s is not None else None,
                "per_source_teps": [round(r["teps"], 1)
                                    for r in per_source]})
    return rep


def _bfs_stage(rep: Report, scale: int, tag: str) -> None:
    # Graph500 proper uses 64 search keys; default 1 keeps the stage
    # inside the budget (each source ~12s at scale 26) — raise via env
    r = bfs_teps(scale,
                 sources=int(os.environ.get("BENCH_BFS_SOURCES", "1")))
    rep.detail[f"bfs_s{scale}"] = {
        "teps": round(r["teps"], 1),
        "n_devices": r["n_devices"],
        "num_sources": r["num_sources"],
        "n_vertices": r["n"],
        "m_input_sym_edges": r["e_sym_pre_dedup"],
        "m_dedup_edges": r["e_dedup"],
        "bfs_levels": r["levels"],
        "reachable_vertices": r["reach"],
        "m_traversed": r["m_traversed"],
        "bfs_seconds": round(r["t_bfs"], 4),
        "first_run_seconds": round(r["first_s"], 2),
        "graph_build_seconds": round(r["gen_s"], 2),
        "upload_seconds": round(r["upload_s"], 2),
    }
    if tag == "headline":
        # only the headline scale owns the report's metric line — the
        # warm-scale stage runs AFTER it and must not overwrite it.
        # vs_baseline stays the RAW ratio against the 1B v5e-8 target;
        # the per-chip share (target/8 — only one chip exists in this
        # environment) is recorded alongside for honest comparison
        if r["n_devices"] == 1:
            rep.detail[f"bfs_s{scale}"]["per_chip_share_of_1e9_target"] = \
                round(r["teps"] / (1e9 / 8), 3)
        rep.headline(f"graph500_scale{scale}_bfs_teps",
                     round(r["teps"], 1), "TEPS",
                     round(r["teps"] / 1e9, 4))
    rep.emit()


def bfs_sharded_overhead(rep: Report, scale: int) -> None:
    """VERDICT r3 #2: the sharded BFS path run on a ONE-device mesh vs
    the plain single-chip hybrid — evidence the sharding machinery
    (shard_map + exchange dispatches) costs little when the mesh is
    trivial, so multi-chip TEPS projections can multiply from the
    single-chip number."""
    import jax

    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid
    from titan_tpu.models.bfs_hybrid_sharded import \
        frontier_bfs_hybrid_sharded
    from titan_tpu.parallel.mesh import vertex_mesh

    hg, g, _, _ = _load_device_graph(scale)
    deg = np.asarray(hg["deg"])
    source = int(np.flatnonzero(deg > 0)[0])
    mesh = vertex_mesh(1)

    def t_of(fn, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = time.time()
            d, _lv = fn()
            _ = int(np.asarray(d[0]))     # force completion (tunnel D2H)
            best = min(best, time.time() - t0)
        return best

    # first sharded call uploads the shard replica + compiles; untimed
    d, _ = frontier_bfs_hybrid_sharded(hg, source, mesh,
                                       return_device=True)
    _ = int(np.asarray(d[0]))
    t_sh = t_of(lambda: frontier_bfs_hybrid_sharded(
        hg, source, mesh, return_device=True), reps=1)
    d, _ = frontier_bfs_hybrid(g, source, return_device=True)
    _ = int(np.asarray(d[0]))
    t_1c = t_of(lambda: frontier_bfs_hybrid(g, source,
                                            return_device=True))
    from titan_tpu.models.bfs_hybrid_sharded import LAST_PROFILE
    disp = [p["dispatches"] for p in LAST_PROFILE]
    rep.detail[f"bfs_s{scale}_sharded_1dev"] = {
        "sharded_seconds": round(t_sh, 3),
        "plain_seconds": round(t_1c, 3),
        "overhead_pct": round(100.0 * (t_sh / t_1c - 1.0), 1),
        # ROADMAP #1 checklist line (ISSUE 13): the 1-device-mesh
        # overhead ratio the 8-chip TEPS projection divides by
        "sharding_overhead_ratio": round(t_sh / t_1c, 3),
        # fused-level dispatch budget (ISSUE 13): 1 dispatch per level
        # + rare exchange-cap retries; ≤2 is the contract
        "dispatches_per_level_max": max(disp) if disp else None,
        "dispatches_per_level_mean": round(sum(disp) / len(disp), 3)
        if disp else None,
        "levels": len(disp),
        "note": (
            "sharded levels are FUSED (ISSUE 13): one shx_td/shx_bu "
            "dispatch per level per cap bucket — opener + chunk "
            "rounds + exhaust + sparse exchange in one kernel (the "
            "r4 host-driven bu0/bu_more/exhaust chain measured 2.0x "
            "here; the r4-morning fused full-width kernel 52x). "
            "Exchange volume is O(frontier) (dryrun COMM_PROFILE).")}
    # free the shard replica before the scale-26 upload
    hg.pop("_shards", None)
    rep.emit()


def sssp_wcc(rep: Report, scale: int) -> None:
    """BASELINE row 6: Graph500 scale-N SSSP + WCC wall seconds."""
    import jax

    from titan_tpu.models.frontier import frontier_sssp, frontier_wcc

    hg, g, _, _ = _load_device_graph(scale)
    deg = np.asarray(hg["deg"])
    source = int(np.flatnonzero(deg > 0)[0])

    # NO warm-up pass: at bench scale one SSSP run costs ~400s (measured
    # 2026-07-30: 25 sliced rounds) — executables come from the
    # persistent XLA cache, so a single timed run is representative
    trace: list = []
    g["_trace_rounds"] = trace       # per-round (band, nf, m8, t, plan_s)
    # isolation drains make plan_s exact at ONE extra host round trip
    # per round — sssp_seconds therefore includes ~rounds x RT of
    # measurement overhead; the count is disclosed below so the <100s
    # comparison can bound it (r5's 121-130s band was untraced)
    g["_trace_plan_drain"] = True
    t0 = time.time()
    d, rounds = frontier_sssp(g, source, return_device=True)
    jax.block_until_ready(d)
    _ = float(np.asarray(d[0]))      # force completion through the tunnel
    rep.detail["sssp_seconds"] = round(time.time() - t0, 3)
    rep.detail["sssp_rounds"] = rounds
    rep.detail["sssp_scale"] = scale
    # per-round PLAN cost (the band extraction + segment-bounds kernel +
    # its one host sync, isolated by a pre-plan drain in _frontier_run):
    # the r5 floor was ~1.1s/round of n-wide nonzero + cap-wide gather;
    # the compaction-library plan must hold this ≥2x lower (ISSUE r6) —
    # recorded here so every bench run keeps the evidence
    plan_costs = [r[4] for r in trace if len(r) > 4]
    if plan_costs:
        rep.detail["sssp_plan_s_per_round_mean"] = round(
            float(np.mean(plan_costs)), 4)
        rep.detail["sssp_plan_s_per_round_p50"] = round(
            float(np.median(plan_costs)), 4)
        rep.detail["sssp_plan_s_per_round_max"] = round(
            float(np.max(plan_costs)), 4)
        rep.detail["sssp_plan_s_total"] = round(
            float(np.sum(plan_costs)), 3)
        rep.detail["sssp_plan_isolation_drains"] = len(plan_costs)
    del g["_trace_rounds"]           # WCC below must not pay the drains
    del g["_trace_plan_drain"]
    rep.emit()

    t0 = time.time()
    lab, rounds = frontier_wcc(g, return_device=True)
    jax.block_until_ready(lab)
    _ = float(np.asarray(lab[0]))
    rep.detail["wcc_seconds"] = round(time.time() - t0, 3)
    rep.detail["wcc_rounds"] = rounds
    rep.emit()


def pagerank_stage(rep: Report, lj_scale: int) -> None:
    """BASELINE row 2: LiveJournal-class PageRank s/iteration — the
    >=50x-vs-MapReduce comparison point (reference harness: titan-test
    TitanGraphIterativeBenchmark; Hadoop PageRank on LiveJournal-class
    graphs runs minutes per iteration through HDFS barriers)."""
    import jax

    from titan_tpu.models.frontier import pagerank_dense

    hg, g, _, _ = _load_device_graph(lj_scale)
    r, _ = pagerank_dense(g, iterations=2, return_device=True)  # warm
    _ = float(np.asarray(r[0]))  # block_until_ready is dispatch-only
    t0 = time.time()             # through the axon tunnel — force D2H
    iters = 10
    r, _ = pagerank_dense(g, iterations=iters, return_device=True)
    _ = float(np.asarray(r[0]))
    sec = (time.time() - t0) / iters
    rep.detail["pagerank_lj_sec_per_iter"] = round(sec, 3)
    rep.detail["pagerank_lj_edges"] = hg["e_dedup"]
    # conservative MR baseline: 180 s/iteration at LiveJournal scale
    rep.detail["pagerank_vs_mapreduce_x"] = round(180.0 / sec, 1)
    rep.detail["pagerank_mr_note"] = (
        "published Hadoop PageRank iterations on LiveJournal-class "
        "graphs run 3-10 MINUTES each on multi-node clusters (every "
        "iteration rewrites the edge list through HDFS map+shuffle+"
        "reduce); 180s is the conservative end. The reference's own "
        "iterative harness (titan-test TitanGraphIterativeBenchmark) "
        "is an OLTP loop over the storage backend — slower still. One "
        "v5e chip replaces a small Hadoop cluster for iterative graph "
        "analytics at >=50x per-iteration wall-clock.")
    rep.emit()


def live_refresh_stage(rep: Report, scale: int) -> None:
    """ISSUE r9 evidence stage (VERDICT r5 missing-evidence complaint):
    the live plane's value claim is that freshness costs a small
    overlay delta-apply instead of a full snapshot rebuild + device
    re-upload. Measure on a synthetic symmetric graph at ``scale``:
    p50/p95 delta-apply latency (append + tombstone + frozen device
    view — the per-commit-batch serving cost), compaction cost (fold
    overlay into a republished CSR), and the full-rebuild baseline the
    overlay avoids. Host+delta-H2D work only, so the numbers are
    CPU-meaningful today; a chip day re-captures them with the real
    tunnel in the loop."""
    import jax

    from titan_tpu.models.bfs_hybrid import frontier_bfs_batched
    from titan_tpu.olap.live.compactor import EpochCompactor
    from titan_tpu.olap.live.overlay import DeltaOverlay
    from titan_tpu.olap.tpu import snapshot as snap_mod

    rng = np.random.default_rng(42)
    n = 1 << scale
    m = n * 8
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)

    def build():
        return snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                    np.concatenate([dst, src]))

    t0 = time.time()
    base = build()
    rebuild_s = time.time() - t0
    # upload baseline: the chunked CSR the rebuild path would re-ship
    t0 = time.time()
    d0, _, _ = frontier_bfs_batched(base, [0], return_device=True)
    jax.block_until_ready(d0)
    upload_and_first_run_s = time.time() - t0

    overlay = DeltaOverlay(base)
    batch_lat: list = []
    batch_edges = 256
    for b in range(32):
        a_s = rng.integers(0, n, batch_edges).astype(np.int32)
        a_d = rng.integers(0, n, batch_edges).astype(np.int32)
        rm = rng.choice(m, 32, replace=False)
        t0 = time.time()
        overlay.append_edges(np.concatenate([a_s, a_d]),
                             np.concatenate([a_d, a_s]),
                             np.zeros(2 * batch_edges, np.int32))
        for i in rm:
            overlay.remove_edge(int(src[i]), int(dst[i]), None)
            overlay.remove_edge(int(dst[i]), int(src[i]), None)
        view = overlay.view()          # includes the delta H2D
        batch_lat.append(time.time() - t0)
    lat = np.asarray(sorted(batch_lat))
    t0 = time.time()
    merged = EpochCompactor().merge(base, overlay)
    compact_s = time.time() - t0

    # ---- ISSUE 9: per-epoch H2D bytes (delta pages vs the full
    # re-upload the host path forces) + device-merge vs host-merge
    # compact cost, as first-class metric lines. One epoch at the
    # DEFAULT policy: feed delta batches until should_compact fires,
    # fold on device, count every byte through an isolated registry.
    from titan_tpu.olap.serving.hbm import snapshot_csr_bytes
    from titan_tpu.utils.metrics import MetricManager

    mm = MetricManager()
    comp = EpochCompactor()
    ov2 = DeltaOverlay(base, metrics=mm)
    epoch_batches = 0
    while not comp.should_compact(ov2):
        a_s = rng.integers(0, n, batch_edges).astype(np.int32)
        a_d = rng.integers(0, n, batch_edges).astype(np.int32)
        ov2.append_edges(np.concatenate([a_s, a_d]),
                         np.concatenate([a_d, a_s]),
                         np.zeros(2 * batch_edges, np.int32))
        for i in rng.choice(m, 8, replace=False):
            ov2.remove_edge(int(src[i]), int(dst[i]), None)
            ov2.remove_edge(int(dst[i]), int(src[i]), None)
        ov2.view()
        epoch_batches += 1
    delta_bytes = mm.counter_value("serving.live.upload_bytes")
    t0 = time.time()
    host_oracle = comp.merge(base, ov2)
    compact_host_s = time.time() - t0
    comp.compact(base, ov2, metrics=mm)   # warm the merge kernels
    t0 = time.time()
    merged_dev, merge_mode = comp.compact(base, ov2, metrics=mm)
    compact_device_s = time.time() - t0
    full_bytes = snapshot_csr_bytes(merged_dev)
    assert merged_dev.num_edges == host_oracle.num_edges

    rep.detail["live_refresh"] = {
        "scale": scale, "edges_sym": 2 * m,
        "delta_batches": len(batch_lat),
        "edges_per_batch": 2 * batch_edges,
        "tombstones_per_batch": 64,
        "apply_p50_ms": round(float(lat[len(lat) // 2]) * 1e3, 3),
        "apply_p95_ms": round(
            float(lat[int(len(lat) * 0.95)]) * 1e3, 3),
        "overlay_capacity": overlay.cap,
        "overlay_device_bytes": view.cap * 8 + overlay.q_total,
        "compact_s": round(compact_s, 3),
        "full_rebuild_s": round(rebuild_s, 3),
        "rebuild_upload_first_run_s": round(upload_and_first_run_s, 3),
        # the headline ratio: per-delta freshness vs the rebuild the
        # overlay avoids (compaction amortizes over every batch since
        # the last epoch)
        "rebuild_over_apply_p50_x": round(
            rebuild_s / max(float(lat[len(lat) // 2]), 1e-9), 1),
        "merged_edges": merged.num_edges,
        # ISSUE 9 epoch-boundary lines: device-resident compaction
        # means the per-epoch H2D cost is the delta pages the overlay
        # shipped incrementally, not the merged CSR image the host
        # path re-uploads — the ratio is the tentpole win, byte-
        # counted so it is CPU-verifiable without a chip
        "merge_mode": merge_mode,
        "epoch_delta_batches": epoch_batches,
        "h2d_delta_bytes_per_epoch": int(delta_bytes),
        "h2d_full_snapshot_bytes": int(full_bytes),
        "h2d_full_over_delta_x": round(
            full_bytes / max(delta_bytes, 1), 1),
        "compact_host_s": round(compact_host_s, 4),
        "compact_device_s": round(compact_device_s, 4),
    }
    rep.emit()


def serving_stage(rep: Report, scale: int) -> None:
    """ISSUE r10 evidence stage (ROADMAP item 5b/5d): the serving and
    recovery planes as FIRST-CLASS metric lines in the driver artifact —
    ``serving.batch.occupancy`` + job latency at K=8 vs K=1, recovery
    replay cost (checkpointed retry: rounds replayed + checkpoint
    commit latency), and the trace digest showing where a fused job's
    time went. Runs the real JobScheduler/Batcher/recovery stack on a
    synthetic graph (CPU-meaningful; a chip day re-captures with the
    tunnel in the loop)."""
    import tempfile

    from titan_tpu.obs.tracing import trace_summary
    from titan_tpu.olap.api import JobSpec
    from titan_tpu.olap.recovery import FaultPlan
    from titan_tpu.olap.serving.scheduler import JobScheduler
    from titan_tpu.olap.tpu import snapshot as snap_mod
    from titan_tpu.utils.metrics import MetricManager

    rng = np.random.default_rng(42)
    n = 1 << scale
    m = n * 8
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    snap = snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    metrics = MetricManager()        # isolated: bench-only lines
    with tempfile.TemporaryDirectory() as ckdir:
        sched = JobScheduler(snapshot=snap, metrics=metrics,
                             autostart=False, checkpoint_dir=ckdir)
        try:
            # K=8 fused batch (paused scheduler pins the composition)
            sources = rng.integers(0, n, 8)
            t0 = time.time()
            batch = [sched.submit(JobSpec(
                kind="bfs", params={"source_dense": int(s)}))
                for s in sources]
            sched.start()
            for j in batch:
                j.wait(120)
            k8_s = time.time() - t0
            # K=1 reference on the warm kernel
            t0 = time.time()
            j1 = sched.submit(JobSpec(kind="bfs",
                                      params={"source_dense": 0}))
            j1.wait(120)
            k1_s = time.time() - t0
            # recovery replay cost: crash at round 2 with per-round
            # checkpoints → the retry resumes instead of restarting
            jr = sched.submit(JobSpec(
                kind="bfs",
                params={"source_dense": int(sources[0]),
                        "faults": FaultPlan(crash_at_round=2)},
                max_retries=1, checkpoint_every=1))
            jr.wait(120)
            occ = metrics.histogram("serving.batch.occupancy").to_dict()
            lat = metrics.histogram("serving.job.latency_ms").to_dict()
            rep.detail["serving"] = {
                "scale": scale, "edges_sym": 2 * m,
                "batch_occupancy": occ,
                "job_latency_ms": lat,
                "queue_ms": metrics.histogram(
                    "serving.job.queue_ms").to_dict(),
                "k8_batch_wall_s": round(k8_s, 3),
                "k1_wall_s": round(k1_s, 3),
                # amortization evidence: wall clock per job in the
                # fused batch vs the single run
                "k8_per_job_over_k1_x": round(
                    (k8_s / 8) / max(k1_s, 1e-9), 3),
                "recovery": {
                    "status": jr.state.value,
                    "attempts": jr.attempt,
                    "rounds_replayed": metrics.counter_value(
                        "serving.recovery.rounds_replayed"),
                    "resumes": metrics.counter_value(
                        "serving.recovery.resumes"),
                    "retries": metrics.counter_value(
                        "serving.recovery.retries"),
                    "checkpoints": metrics.counter_value(
                        "serving.recovery.checkpoints"),
                    "checkpoint_ms": metrics.histogram(
                        "serving.recovery.checkpoint_ms").to_dict(),
                },
                "trace_k8_job": trace_summary(sched.tracer,
                                              batch[0].id),
                "trace_retried_job": trace_summary(sched.tracer, jr.id),
            }
        finally:
            sched.close()
    rep.emit()


def tenancy_stage(rep: Report, scale: int) -> None:
    """ISSUE 8 evidence stage (ROADMAP item 3 observable-first): the
    per-tenant SLO plane as first-class metric lines — two synthetic
    tenants share one scheduler, and the artifact records each
    tenant's p95 latency (from the {tenant}-labeled histogram
    children), its device-seconds / HBM-byte-seconds attribution, and
    the exactness check that labeled children sum to the unlabeled
    aggregate. Feeds the next hardware window: a chip day re-captures
    the same lines with the tunnel in the loop."""
    from titan_tpu.olap.api import JobSpec
    from titan_tpu.olap.serving.scheduler import JobScheduler
    from titan_tpu.olap.tpu import snapshot as snap_mod
    from titan_tpu.utils.metrics import MetricManager, nearest_rank

    rng = np.random.default_rng(42)
    n = 1 << scale
    m = n * 8
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    snap = snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    metrics = MetricManager()        # isolated: bench-only lines
    # autotune defaults to SHADOW — the stage leaves it there and
    # drives one explicit post-load tick so the evidence bundle gets a
    # real journaled decision from real signals (the tick interval is
    # parked high so the worker loop doesn't consume the occupancy
    # delta first)
    sched = JobScheduler(snapshot=snap, metrics=metrics,
                         autostart=False, autotune_tick_s=3600.0)
    try:
        # interleaved submits: alpha floods 12 jobs, beta sends 4 —
        # fused batches mix tenants, which is exactly what the per-K
        # attribution split has to untangle
        sources = rng.integers(0, n, 16)
        jobs = [sched.submit(JobSpec(
            kind="bfs", params={"source_dense": int(s)},
            tenant="alpha" if i % 4 else "beta"))
            for i, s in enumerate(sources)]
        sched.start()
        for j in jobs:
            j.wait(120)
        # wait() fires at the state transition inside the batch; the
        # worker finalizes counters/attribution just after — poll so
        # the roll-up exactness line never reads a mid-finalize state
        deadline = time.time() + 10
        while time.time() < deadline and metrics.counter_value(
                "serving.jobs.completed") < len(jobs):
            time.sleep(0.01)
        rows = sched.tenant_stats()["tenants"]
        per_tenant = {}
        for t in ("alpha", "beta"):
            pooled: list = []
            for _lbls, child in metrics.children(
                    "serving.job.latency_ms", {"tenant": t}):
                pooled.extend(child.values())
            r = rows[t]
            per_tenant[t] = {
                "jobs": r["submitted"],
                "p50_latency_ms": round(
                    nearest_rank(pooled, 0.5), 3) if pooled else None,
                "p95_latency_ms": round(
                    nearest_rank(pooled, 0.95), 3) if pooled else None,
                "queue_ms": round(r["queue_ms"], 3),
                "device_seconds": round(r["device_seconds"], 6),
                "hbm_byte_seconds": round(r["hbm_byte_seconds"], 1),
            }
        labeled_sum = sum(
            c.count for _lbls, c in metrics.children(
                "serving.jobs.completed"))
        # ISSUE 14: one shadow-mode controller tick over the stage's
        # real signals — the decision count + an example journal entry
        # feed the --evidence roadmap5 `controller_decisions` line
        controller = None
        if sched.controller is not None:
            sched.controller.tick(force=True)
            journal = sched.controller.journal()
            controller = {
                "mode": sched.controller.mode,
                "decisions": len(journal),
                "example": journal[-1] if journal else None}
        rep.detail["tenancy"] = {
            "controller": controller,
            "scale": scale, "edges_sym": 2 * m,
            "tenants": per_tenant,
            # roll-up exactness: the labeled children account for every
            # completed job the unlabeled aggregate saw
            "completed_total": metrics.counter_value(
                "serving.jobs.completed"),
            "completed_labeled_sum": labeled_sum,
            "device_seconds_total": round(sum(
                r["device_seconds"] for r in rows.values()), 6),
        }
    finally:
        sched.close()
    rep.emit()


def interactive_stage(rep: Report, scale: int) -> None:
    """ISSUE 11 evidence stage (ROADMAP #3): the interactive lane's
    fuse economics as first-class metric lines — per-query p50/p95 of
    2-hop point queries fused K=16 vs run sequentially (K=1), the fuse
    occupancy histogram, and batched personalized-PageRank throughput
    (one vmapped [S, n] dispatch) vs S sequential personalized runs.
    CPU-meaningful; a chip day re-captures with the tunnel in the
    loop."""
    import threading

    from titan_tpu.models.frontier import pagerank_dense
    from titan_tpu.models.pagerank import pagerank_personalized_batched
    from titan_tpu.olap.serving.interactive import plan_from_wire
    from titan_tpu.olap.serving.scheduler import JobScheduler
    from titan_tpu.olap.tpu import snapshot as snap_mod
    from titan_tpu.utils.metrics import MetricManager, nearest_rank

    rng = np.random.default_rng(42)
    n = 1 << scale
    m = n * 8
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    snap = snap_mod.from_arrays(n, np.concatenate([src, dst]),
                                np.concatenate([dst, src]))
    K = 16

    def q(vid):
        return plan_from_wire({"start": [int(vid)], "dir": "both",
                               "hops": 2, "terminal": "count"})

    sources = rng.integers(0, n, K)
    metrics = MetricManager()            # isolated: bench-only lines
    # fused lane: a window long enough that a thread burst always
    # lands in ONE batch; solo lane: near-zero window, every query its
    # own dispatch (the K=1 reference)
    fused = JobScheduler(snapshot=snap, metrics=metrics,
                         autostart=False, interactive_window_s=0.05,
                         interactive_max_fuse=K)
    solo = JobScheduler(snapshot=snap, metrics=MetricManager(),
                        autostart=False, interactive_window_s=1e-4)
    try:
        lane_f, lane_s = fused.interactive(), solo.interactive()
        # warm both XLA shape buckets (K=16 padded, K=1)
        lane_s.submit(q(sources[0]))
        warm = [threading.Thread(
            target=lambda v=v: lane_f.submit(q(v))) for v in sources]
        for t in warm:
            t.start()
        for t in warm:
            t.join(60)
        fused_ms: list = []
        exec_ms: list = []

        def go(vid):
            t0 = time.time()
            res = lane_f.submit(q(vid))
            fused_ms.append((time.time() - t0) * 1e3)
            exec_ms.append(res["exec_ms"])

        reps = 3
        for _ in range(reps):
            threads = [threading.Thread(target=go, args=(v,))
                       for v in sources]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
        seq_ms: list = []
        for _ in range(reps):
            for vid in sources:
                t0 = time.time()
                lane_s.submit(q(vid))
                seq_ms.append((time.time() - t0) * 1e3)
        occ = metrics.histogram(
            "serving.interactive.fuse_k").to_dict()
        # batched PPR throughput vs sequential personalized oracle
        S, iters = 8, 10
        ppr_src = [int(v) for v in sources[:S]]
        ppr_dense = [snap.dense_of(v) for v in ppr_src]
        pagerank_personalized_batched(snap, ppr_dense,
                                      iterations=iters)  # warm
        t0 = time.time()
        pagerank_personalized_batched(snap, ppr_dense,
                                      iterations=iters)
        batched_s = time.time() - t0
        reset0 = np.zeros(snap.n, np.float32)
        reset0[ppr_dense[0]] = 1.0
        pagerank_dense(snap, iterations=iters, reset=reset0)  # warm
        t0 = time.time()
        for sd in ppr_dense:
            reset = np.zeros(snap.n, np.float32)
            reset[sd] = 1.0
            pagerank_dense(snap, iterations=iters, reset=reset)
        seq_s = time.time() - t0
        rep.detail["interactive"] = {
            "scale": scale, "edges_sym": 2 * m, "k": K,
            "point_query_fused_p50_ms": round(
                nearest_rank(fused_ms, 0.5), 3),
            "point_query_fused_p95_ms": round(
                nearest_rank(fused_ms, 0.95), 3),
            "point_query_seq_p50_ms": round(
                nearest_rank(seq_ms, 0.5), 3),
            "point_query_seq_p95_ms": round(
                nearest_rank(seq_ms, 0.95), 3),
            "fused_exec_ms_per_batch": round(
                nearest_rank(exec_ms, 0.5), 3),
            "fuse_occupancy": occ,
            # device-economics headline: K queries' worth of answers
            # per fused device dispatch vs K separate dispatches
            "fused_device_ms_per_query": round(
                nearest_rank(exec_ms, 0.5) / K, 4),
            "ppr_users": S, "ppr_iterations": iters,
            "ppr_batched_wall_s": round(batched_s, 3),
            "ppr_seq_wall_s": round(seq_s, 3),
            "ppr_batched_users_per_s": round(
                S / max(batched_s, 1e-9), 1),
            "ppr_speedup_x": round(seq_s / max(batched_s, 1e-9), 2),
        }
    finally:
        fused.close()
        solo.close()
    rep.emit()


def bfs_heavy_stage(rep: Report) -> None:
    """BASELINE row 5: Twitter-2010-class (1.5B-edge) single-chip BFS.
    The dataset itself is unreachable in-image (zero egress), so the
    stage substitutes an R-MAT at directed-edge-count parity: scale 25 /
    edge-factor 44 = 1.476B generated edges vs Twitter-2010's 1.468B
    (R-MAT s25 has 33.5M vertices vs Twitter's 41.6M). The one-time
    graph build (~15 min C++) must already be on disk
    (scripts/build_heavy_graph.py); the stage skips rather than blowing
    the budget on it."""
    from titan_tpu.olap.tpu import graph500

    tag = "g500_s25_ef44_seed2"
    if not os.path.exists(os.path.join(graph500.DEFAULT_CACHE,
                                       tag + ".json")):
        rep.skip("bfs_heavy", "graph cache absent (one-time ~15min "
                 "build: python scripts/build_heavy_graph.py)")
        return
    # reps fallback: when the day's tunnel rate prices the full stage
    # out of the remaining budget, one rep still lands a driver-captured
    # number (the upload dominates — a second rep adds ~10s)
    reps = 2
    if _left() < _est("bfs_heavy") + 30:
        reps = 1
        rep.detail["bfs_heavy_reps_fallback"] = {
            "reps": 1, "why": f"{_left():.0f}s left, est "
                              f"{_est('bfs_heavy'):.0f}s at "
                              f"{_h2d_gbps:.3f}GB/s"}
    r = bfs_teps(25, edge_factor=44, reps=reps)
    rep.detail["bfs_heavy_single_chip"] = {
        "substitution": "RMAT s25 ef44 at Twitter-2010 directed-edge "
                        "parity (1.476B vs 1.468B input edges)",
        "teps": round(r["teps"], 1),
        "n_vertices": r["n"],
        "m_input_directed_edges": r["n"] * 44,
        "m_dedup_edges": r["e_dedup"],
        "bfs_levels": r["levels"],
        "reachable_vertices": r["reach"],
        "m_traversed": r["m_traversed"],
        "bfs_seconds": round(r["t_bfs"], 4),
        "first_run_seconds": round(r["first_s"], 2),
        "upload_seconds": round(r["upload_s"], 2),
    }
    rep.emit()


def store_ingest_stage(rep: Report, scale: int,
                       smoke: bool = False) -> None:
    """VERDICT r4 #4 / the north-star contract: OLAP over a CSR snapshot
    OF THE EDGE STORE at benchmark scale. Generates an R-MAT edge list,
    bulk-loads it through the storage plane (KCVS mutations via the
    batch-loading path, reference: GraphDatabaseConfiguration
    STORAGE_BATCH), scans the edgestore back into a snapshot
    (native scan), builds the chunked CSR, and runs the SAME BFS —
    checking the result against the generated-graph BFS.

    SCALE FALLBACK (ISSUE r7): the stage is host-bound and scales
    ~linearly with edges, so when the remaining budget can't cover the
    requested scale it steps down (s22 → s21 → s20) instead of being
    skipped outright — a smaller driver-captured number beats a third
    round of no number at all. The chosen scale is recorded."""
    import jax

    fixed, _gb = _EST["store_ingest"]
    if smoke:                    # CPU/CI scales cost ~1/10th (main())
        fixed = fixed / 10
    full_scale = scale
    candidates = [s for s in range(scale, scale - 3, -1) if s >= 10] \
        or [scale]
    chosen = None
    for s in candidates:
        # est halves per scale step down (edge count halves; the
        # +60s covers the fixed BFS/compile tail that doesn't shrink)
        if _left() > fixed / (2 ** (full_scale - s)) + 60:
            chosen = s
            break
    if chosen is None:
        rep.skip("store_ingest",
                 f"budget: {_left():.0f}s left cannot fit even the "
                 f"s{candidates[-1]} fallback")
        return
    scale = chosen
    if scale != full_scale:
        rep.detail["store_ingest_scale_fallback"] = {
            "requested": full_scale, "ran": scale,
            "why": f"{_left():.0f}s left"}

    from titan_tpu.models.bfs import INF
    from titan_tpu.models.bfs_hybrid import (build_chunked_csr,
                                             frontier_bfs_hybrid)
    from titan_tpu.olap import bulk

    t0 = time.time()
    res = bulk.ingest_rmat_store(scale, edge_factor=16, seed=2)
    g, snap = res["graph"], res["snapshot"]
    try:
        t1 = time.time()
        csr = build_chunked_csr(snap)
        jax.block_until_ready(csr["dstT"])
        csr_s = time.time() - t1

        # BFS on the store-derived snapshot, same source rule as the
        # generated-graph stage. Source picked from the GENERATED graph's
        # degrees (the store path keeps self-loops the generated CSR
        # drops, so its nonzero-degree set can differ — the pick must
        # match the reference stage's exactly); dense index spaces are
        # identical because bulk ids were assigned in dense order.
        hg, gref, _, _ = _load_device_graph(scale)   # shared/resident
        # the dist check only holds if the reference cache and the
        # ingest used the SAME R-MAT generator (native vs numpy edge
        # sets differ for one seed; a native-built cache read on a
        # native-less host would falsely indict the bulk-load path)
        from titan_tpu import native as _native
        gen_here = "native" if _native.available else "numpy"
        gen_ref = hg.get("generator", gen_here)
        deg = np.asarray(hg["deg"])
        rng = np.random.default_rng(12345)
        source = int(rng.choice(np.flatnonzero(deg > 0), size=1,
                                replace=False)[0])
        t2 = time.time()
        dist, levels = frontier_bfs_hybrid(csr, source,
                                           return_device=True)
        jax.block_until_ready(dist)
        bfs_s = time.time() - t2

        # equivalence vs the generated-graph CSR: reachable count and
        # level histogram must match exactly (duplicate edges in the
        # store path don't change BFS distances)
        dist_ref, levels_ref = frontier_bfs_hybrid(gref, source,
                                                   return_device=True)
        match = (bulk.dist_match(dist, dist_ref, int(INF))
                 if gen_ref == gen_here else
                 f"not comparable: reference cache built by "
                 f"{gen_ref} generator, ingest used {gen_here}")
        rep.detail[f"store_ingest_s{scale}"] = {
            "n_vertices": res["n"], "m_edges_ingested": res["m"],
            "ingest_seconds": round(res["ingest_s"], 1),
            "scan_snapshot_seconds": round(res["scan_s"], 1),
            "csr_build_upload_seconds": round(csr_s, 1),
            "bfs_seconds": round(bfs_s, 3),
            "bfs_levels": levels, "bfs_levels_ref": levels_ref,
            "dist_matches_generated": match,
            "total_seconds": round(time.time() - t0, 1),
        }
        rep.emit()
    finally:
        g.close()


def ldbc_is3_4hop(rep: Report, tmp_dir: str | None = None,
                  n_persons: int = 10_000, avg_degree: int = 36) -> None:
    """BASELINE row 4: LDBC-SNB-style interactive short-read latency on
    the embedded persistent store (BerkeleyJE role = sqlite here) — p50
    of a 4-hop friends expansion from sampled persons over an SF1-scale
    synthetic social graph (10k persons, ~180k knows edges), built once
    and cached on disk."""
    import shutil

    import titan_tpu

    base = tmp_dir or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".bench_cache",
        f"ldbc_{n_persons}")
    # a sentinel marks a COMPLETE build: open() itself creates the dir,
    # so dir-existence would treat an interrupted build as a valid cache
    sentinel = os.path.join(base, ".complete")
    fresh = not os.path.exists(sentinel)
    if fresh and os.path.exists(base):
        shutil.rmtree(base, ignore_errors=True)
    g = titan_tpu.open({"storage.backend": "sqlite",
                        "storage.directory": base})
    try:
        t_build0 = time.time()
        if fresh:
            rng = np.random.default_rng(7)
            tx = g.new_transaction()
            people = [tx.add_vertex("person", name=f"p{i}")
                      for i in range(n_persons)]
            m = n_persons * avg_degree // 2
            for a, b in zip(rng.integers(0, n_persons, m),
                            rng.integers(0, n_persons, m)):
                if a != b:
                    people[int(a)].add_edge("knows", people[int(b)])
            tx.commit()
            with open(sentinel, "w") as f:
                f.write("ok")
        build_s = time.time() - t_build0
        rng = np.random.default_rng(99)
        tx = g.new_transaction()
        ids = [v.id for i, v in zip(range(200), tx.vertices())]
        tx.rollback()
        srcs = [ids[int(i)] for i in rng.integers(0, len(ids), 12)]
        # LDBC interactive measures a steady-state window after a
        # warm-up period: run a handful of untimed 4-hop operations
        # from vertices OUTSIDE the timed set (so no timed sample is a
        # hot repeat) to fill the adjacency cache, exactly like the
        # driver's warm-up phase. The cold first-touch latency is
        # reported separately (VERDICT r3 weak #3: the old single
        # warm-up left the first timed queries paying first-touch
        # parse costs — p95 was 8x p50 from cache fill, not from any
        # engine cliff; rep-2 latencies were uniform 31-100ms).
        warm = [i for i in ids if i not in set(srcs)][:8]
        t0 = time.time()
        g.traversal().V(warm[0]).out("knows").out("knows") \
            .out("knows").out("knows").count().next()
        cold_ms = (time.time() - t0) * 1e3
        for w in warm[1:]:
            g.traversal().V(w).out("knows").out("knows") \
                .out("knows").out("knows").count().next()
        lat = []
        counts = []
        for vid in srcs:
            t0 = time.time()
            c = g.traversal().V(vid).out("knows").out("knows") \
                .out("knows").out("knows").count().next()
            lat.append(time.time() - t0)
            counts.append(c)
        lat.sort()
        rep.detail.update({
            "ldbc_is3_4hop_p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
            "ldbc_is3_4hop_p95_ms": round(lat[-1] * 1e3, 2),
            "ldbc_cold_first_ms": round(cold_ms, 2),
            "ldbc_warmup_ops": len(warm),
            "ldbc_persons": n_persons,
            "ldbc_build_s": round(build_s, 1),
            "ldbc_4hop_median_reach": int(sorted(counts)[len(counts)//2])})
        rep.emit()
    finally:
        g.close()
        if tmp_dir is not None:
            shutil.rmtree(base, ignore_errors=True)


def gods_2hop(rep: Report) -> None:
    """BASELINE config #1: GraphOfTheGods 2-hop Gremlin count on inmemory
    (OLTP traversal latency, p50 of 20 runs)."""
    import titan_tpu
    from titan_tpu import example

    g = titan_tpu.open("inmemory")
    example.load(g)
    two = lambda: g.traversal().V().out().out().count().next()  # noqa: E731
    count = two()
    lat = []
    for _ in range(20):
        t = time.time()
        two()
        lat.append(time.time() - t)
    g.close()
    # detail ONLY — the report's metric line belongs to the headline BFS
    # stage (VERDICT r5 weak #1: the old rep.headline call here
    # overwrote the scale-26 TEPS record in the driver artifact)
    rep.detail["gods_2hop_p50_ms"] = round(sorted(lat)[len(lat) // 2] * 1e3,
                                           3)
    rep.detail["gods_2hop_count"] = int(count)
    rep.emit()


def bfs_pallas_stage(rep: Report, scale: int) -> None:
    """ISSUE 16 evidence stage: the fused Pallas bottom-up frontier
    kernel (``TITAN_TPU_FRONTIER_KERNEL=pallas``, ops/pallas_frontier)
    vs the XLA bu chain on the warm-scale graph — warm best-of-3 per
    mode from one source, results asserted bit-equal. Chip-only:
    interpreter mode times an XLA emulation of the kernel, not the
    chip (CPU parity is tier-1's job — tests/test_pallas_frontier.py),
    so on CPU this stage is a recorded skip, never a fake number."""
    from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid

    hg, g, _, _ = _load_device_graph(scale)
    deg = np.asarray(hg["deg"])
    source = int(np.flatnonzero(deg > 0)[0])
    saved = os.environ.get("TITAN_TPU_FRONTIER_KERNEL")

    def timed(mode):
        os.environ["TITAN_TPU_FRONTIER_KERNEL"] = mode
        d, lv = frontier_bfs_hybrid(g, source, return_device=True)
        _ = int(np.asarray(d[0]))     # warm: compiles + first run
        best = float("inf")
        for _ in range(3):
            t0 = time.time()
            d, lv = frontier_bfs_hybrid(g, source, return_device=True)
            _ = int(np.asarray(d[0]))  # force completion (tunnel D2H)
            best = min(best, time.time() - t0)
        return best, np.asarray(d), lv

    try:
        t_x, d_x, lv_x = timed("xla")
        t_p, d_p, lv_p = timed("pallas")
    finally:
        if saved is None:
            os.environ.pop("TITAN_TPU_FRONTIER_KERNEL", None)
        else:
            os.environ["TITAN_TPU_FRONTIER_KERNEL"] = saved
    if lv_x != lv_p or not np.array_equal(d_x, d_p):
        raise AssertionError(
            f"pallas bu result != xla result (levels {lv_p} vs {lv_x})")
    rep.detail["bfs_pallas"] = {
        "scale": scale, "source": source, "levels": lv_p,
        "xla_seconds": round(t_x, 4),
        "pallas_seconds": round(t_p, 4),
        "pallas_bu_speedup_x": round(t_x / max(t_p, 1e-9), 3),
        "results_bit_equal": True,
    }
    rep.emit()


def segment_pallas_stage(rep: Report) -> None:
    """ISSUE 16 satellite: the one-pass Pallas segmented combine
    (``TITAN_TPU_SEGMENT_KERNEL=pallas``, ops/pallas_segment) vs the
    XLA Hillis-Steele scan on a synthetic dst-sorted edge axis — the
    SpMV primitive's kernel verdict as a first-class evidence line.
    Chip-only for the same reason as bfs_pallas (interpreter mode is
    an emulation; CPU parity lives in tests/test_pallas_segment.py)."""
    import jax
    import jax.numpy as jnp

    from titan_tpu.ops.pallas_segment import pallas_sorted_segment_combine
    from titan_tpu.ops.segment import (segment_metadata,
                                       sorted_segment_combine)

    e, n = 1 << 24, 1 << 20
    rng = np.random.default_rng(5)
    seg_ids = np.sort(rng.integers(0, n, e)).astype(np.int32)
    indptr = np.concatenate(
        [[0], np.cumsum(np.bincount(seg_ids, minlength=n))])
    last_idx, seg_has = segment_metadata(indptr)
    vals = jnp.asarray(rng.random(e, dtype=np.float32))
    ids_d = jnp.asarray(seg_ids)
    li, sh = jnp.asarray(last_idx), jnp.asarray(seg_has)
    scan_jit = jax.jit(sorted_segment_combine,
                       static_argnames=("combine",))

    def timed(fn):
        out = fn()
        _ = float(np.asarray(out[0]))     # warm + force D2H
        best = float("inf")
        for _ in range(3):
            t0 = time.time()
            out = fn()
            _ = float(np.asarray(out[0]))
            best = min(best, time.time() - t0)
        return best, out

    t_x, o_x = timed(lambda: scan_jit(vals, ids_d, li, sh, combine="sum"))
    t_p, o_p = timed(lambda: pallas_sorted_segment_combine(
        vals, ids_d, li, sh, "sum"))
    if not np.allclose(np.asarray(o_x), np.asarray(o_p), rtol=1e-5):
        raise AssertionError("pallas segment combine != xla scan")
    rep.detail["segment_pallas"] = {
        "edges": e, "segments": n, "combine": "sum",
        "xla_scan_seconds": round(t_x, 4),
        "pallas_seconds": round(t_p, 4),
        "segment_pallas_speedup_x": round(t_x / max(t_p, 1e-9), 3),
    }
    rep.emit()


def distributed_scan_stage(rep: Report) -> None:
    """ISSUE 18 (ROADMAP #2/#5): cross-process observability evidence.
    A small scan fanned out to two HTTP scan workers over remote-cluster
    storage, with trace propagation ON — records the ONE stitched trace
    (worker split/execute/serialize spans spliced under the
    coordinator's split spans by Tracer.ingest) as span counts + ingest
    drop accounting. Host-only HTTP + dict stores: CPU-runnable."""
    import titan_tpu
    from titan_tpu.obs.tracing import Tracer
    from titan_tpu.olap.distributed import ScanJobSpec
    from titan_tpu.olap.jobs import VertexCountJob
    from titan_tpu.olap.scan_worker import (RemoteScanRunner,
                                            ScanWorkerServer)
    from titan_tpu.storage.inmemory import InMemoryStoreManager
    from titan_tpu.storage.remote import KCVSServer
    from titan_tpu.utils.metrics import MetricManager

    n = 64
    storage = [KCVSServer(InMemoryStoreManager()).start()
               for _ in range(2)]
    workers = [ScanWorkerServer().start() for _ in range(2)]
    try:
        cfg = {"storage.backend": "remote-cluster",
               "storage.hostname":
                   [f"127.0.0.1:{s.port}" for s in storage],
               "storage.cluster.replication-factor": 2}
        g = titan_tpu.open(cfg)
        tx = g.new_transaction()
        for i in range(n):
            tx.add_vertex("person", name=f"b{i}")
        tx.commit()
        g.close()

        m = MetricManager()
        tracer = Tracer()
        t0 = time.time()
        runner = RemoteScanRunner(
            [f"127.0.0.1:{w.port}" for w in workers], cfg,
            metrics=m, tracer=tracer, trace_id="bench-scan")
        got = runner.run(ScanJobSpec(
            "titan_tpu.olap.jobs:make_vertex_count_job"))
        wall = time.time() - t0
        if got.get(VertexCountJob.VERTICES) != n:
            raise AssertionError(
                f"distributed scan counted "
                f"{got.get(VertexCountJob.VERTICES)} != {n}")

        tree = tracer.tree("bench-scan")
        if tree is None:
            raise AssertionError("no stitched trace for bench-scan")
        spans, instances, stack = 0, set(), list(tree["spans"])
        while stack:
            node = stack.pop()
            spans += 1
            attrs = node.get("attrs") or {}
            if attrs.get("remote"):
                instances.add(attrs["instance"])
            stack.extend(node["children"])
        rep.detail["distributed_scan"] = {
            "workers": len(workers),
            "coordinator_splits": len(tree["spans"]),
            "stitched_spans": spans,
            "remote_instances": len(instances),
            "ingest_spans": int(m.counter_value("obs.ingest.spans")),
            "ingest_dropped":
                int(m.counter_value("obs.ingest.dropped")),
            "scan_wall_s": round(wall, 3),
        }
    finally:
        for node in workers + storage:
            node.stop()
    rep.emit()


def fleet_stage(rep: Report) -> None:
    """ISSUE 19 (ROADMAP #2/#5): replica-fleet routing evidence. A
    FleetRouter over two in-process replicas (full GraphServer +
    JobScheduler each) on shared remote-cluster storage, driven by a
    mixed BFS/SSSP/WCC stream — records per-replica occupancy and
    routing-decision counts — then one deterministic failover (a
    never-starting victim scheduler, so the kill always lands mid-
    flight) for the redispatch-latency line. Small CPU frontier
    kernels + host HTTP: runs on CPU and chip days alike."""
    import tempfile

    import titan_tpu
    from titan_tpu.olap.fleet.replica import build
    from titan_tpu.olap.fleet.router import FleetRouter
    from titan_tpu.storage.inmemory import InMemoryStoreManager
    from titan_tpu.storage.remote import KCVSServer
    from titan_tpu.utils.httpnode import json_call, text_get
    from titan_tpu.utils.metrics import MetricManager

    n, m_edges = 192, 900
    storage = KCVSServer(InMemoryStoreManager()).start()
    cfg = {"storage.backend": "remote-cluster",
           "storage.hostname": [f"127.0.0.1:{storage.port}"]}
    g = titan_tpu.open(cfg)
    tx = g.new_transaction()
    vs = [tx.add_vertex("node", name=f"v{i}") for i in range(n)]
    rng = np.random.default_rng(42)
    for _ in range(m_edges):
        a, b = rng.integers(0, n, 2)
        tx.add_edge(vs[int(a)], "link", vs[int(b)])
    tx.commit()
    ids = [v.id for v in vs]
    g.close()
    ck = tempfile.mkdtemp(prefix="bench-fleet-")

    def drive(router, jids, deadline_s=120.0):
        t_end = time.time() + deadline_s
        terminal = ("done", "failed", "timeout", "cancelled",
                    "expired")
        while True:
            router.pump()
            states = [json.loads(text_get(
                router.url, f"/jobs/{j}"))["state"] for j in jids]
            if all(s in terminal for s in states):
                return states
            if time.time() > t_end:
                raise AssertionError(f"fleet stream stalled: {states}")
            time.sleep(0.05)

    # phase 1 — mixed stream routing over two live replicas
    reps = [build({"graph": cfg, "checkpoint_dir": ck})
            for _ in range(2)]
    for _g, _s, srv in reps:
        srv.start()
    mm = MetricManager()
    router = FleetRouter(metrics=mm, autotune="shadow",
                         autopump=False)
    insts = []
    for i, (_g, _s, srv) in enumerate(reps):
        inst = f"replica-{i}"
        router.add_replica(f"http://{srv.host}:{srv.port}",
                           instance=inst)
        insts.append(inst)
    router.start()
    try:
        stream = ([{"kind": "bfs", "source": ids[k]}
                   for k in (0, 3, 7, 11)]
                  + [{"kind": "sssp", "source": ids[k]}
                     for k in (1, 5, 9, 13)]
                  + [{"kind": "wcc"} for _ in range(4)])
        t0 = time.time()
        jids = [json_call(router.url, "/jobs", body)["job"]
                for body in stream]
        states = drive(router, jids)
        stream_wall = time.time() - t0
        if states.count("done") != len(stream):
            raise AssertionError(f"mixed stream not all done: {states}")
        routed = {inst: int(mm.counter_value(
            "serving.fleet.routed", labels={"instance": inst}))
            for inst in insts}
        decisions = int(mm.counter_value("serving.fleet.routed"))
    finally:
        router.stop()
        for _g, _s, srv in reps:
            _s.close()
            srv.stop()
        for _g, _s, _srv in reps:
            _g.close()

    # phase 2 — one deterministic failover for the latency line
    gv, sv, srvv = build({"graph": cfg, "checkpoint_dir": ck,
                          "scheduler": {"autostart": False}})
    gs, ss, srvs = build({"graph": cfg, "checkpoint_dir": ck})
    srvv.start(); srvs.start()
    m2 = MetricManager()
    router = FleetRouter(metrics=m2, autotune="off", autopump=False)
    router.add_replica(f"http://{srvv.host}:{srvv.port}",
                       instance="a-victim")
    router.add_replica(f"http://{srvs.host}:{srvs.port}",
                       instance="b-survivor")
    router.start()
    try:
        jid = json_call(router.url, "/jobs",
                        {"kind": "bfs", "source": ids[0]})["job"]
        router.pump()
        srvv.stop()
        drive(router, [jid])
        w = json.loads(text_get(router.url, f"/jobs/{jid}"))
        if w["state"] != "done" or w["attempts"] != 2:
            raise AssertionError(f"failover did not redispatch: {w}")
        hs = m2.histogram_stats(
            "serving.fleet.redispatch_latency_ms") or {}
    finally:
        router.stop()
        sv.close(); ss.close()
        srvs.stop()
        gv.close(); gs.close()
        storage.stop()

    lo, hi = min(routed.values()), max(routed.values())
    rep.detail["fleet"] = {
        "replicas": 2,
        "stream_jobs": len(stream),
        "stream_mix": {"bfs": 4, "sssp": 4, "wcc": 4},
        "stream_wall_s": round(stream_wall, 3),
        "routing_decisions": decisions,
        "per_replica_routed": routed,
        "occupancy_spread": round((hi - lo) / max(hi, 1), 4),
        "redispatches":
            int(m2.counter_value("serving.fleet.redispatches")),
        "redispatch_latency_ms": round(hs.get("mean", 0.0), 3),
    }
    rep.emit()


class Evidence:
    """``--evidence <path>`` (ISSUE 10, ROADMAP #5): wrap every stage
    in the device-cost profiler and write ONE machine-readable bundle
    beside the stdout report, so a chip day produces a complete
    artifact with zero bespoke scripting.

    The bundle carries the full cumulative detail (skip reasons
    included), a per-stage status + device-cost window (compiles,
    compile/exec wall, H2D/D2H bytes — the numbers that explain a
    slow stage), the process compile log and per-kernel stats, and a
    ``roadmap5`` checklist section where each line ROADMAP #5 demands
    — sharded BFS, batch occupancy + K=8 vs K=1 latency, live_refresh
    delta-vs-rebuild, recovery replay — is either a value or a
    recorded skip reason, never silently absent."""

    FORMAT = "titan-tpu-evidence-v1"

    def __init__(self, path: str, rep: Report):
        from titan_tpu.obs.devprof import DeviceCostProfiler
        from titan_tpu.utils.metrics import MetricManager

        self.path = path
        self.rep = rep
        # isolated registry: the bundle's device.* lines are this
        # run's, not the process history's
        self.metrics = MetricManager()
        self.profiler = DeviceCostProfiler(metrics=self.metrics)
        self.profiler.install()
        self.stages: dict = {}

    def record(self, name: str, status: str, window_delta=None) -> None:
        entry: dict = {"status": status}
        if window_delta is not None:
            entry["device_cost"] = window_delta
        self.stages[name] = entry

    def _lint_clean(self) -> dict:
        """ISSUE 15: chip-day bundles record that the static invariants
        (op-scan ban, host-sync, lock-discipline, metric/clock
        discipline — docs/static-analysis.md) held for the exact tree
        that produced the numbers — a value, or a recorded skip."""
        try:
            repo = os.path.dirname(os.path.abspath(__file__))
            if repo not in sys.path:
                sys.path.insert(0, repo)
            from tools.graftlint.engine import Linter
            res = Linter(root=repo).run(["titan_tpu", "bench.py"])
            return {"present": True, "value": {
                "clean": not res.unsuppressed,
                "unsuppressed": len(res.unsuppressed),
                "suppressed": len(res.findings) - len(res.unsuppressed),
                "files": len(res.files),
                "wall_s": round(res.wall_s, 3)}}
        except Exception as e:          # missing tools/ checkout etc.
            return {"present": False, "stage": "lint",
                    "skip_reason": f"graftlint unavailable: {e!r}"}

    def _checklist(self) -> dict:
        det = self.rep.detail

        def present(value) -> dict:
            return {"present": True, "value": value}

        def absent(stage: str) -> dict:
            why = next((s["why"] for s in det.get("skipped", ())
                        if s["stage"] == stage), "stage did not run")
            return {"present": False, "stage": stage,
                    "skip_reason": why}

        sharded = next((v for k, v in det.items()
                        if k.endswith("_sharded_1dev")), None)
        serving = det.get("serving")
        interactive = det.get("interactive")
        tenancy = det.get("tenancy")
        bfs_pal = det.get("bfs_pallas")
        seg_pal = det.get("segment_pallas")
        return {
            # ISSUE 15: the invariants held for this tree (graftlint)
            "lint_clean": self._lint_clean(),
            # ISSUE 14 (ROADMAP #4): the autotune decision plane — a
            # shadow-mode run of the tenancy stage must produce a
            # journaled, replayable decision; count + one example
            # entry, or the stage's recorded skip reason
            "controller_decisions": (
                present(tenancy["controller"])
                if tenancy is not None
                and tenancy.get("controller") is not None
                else absent("tenancy")),
            "sharded_bfs": (present(sharded) if sharded is not None
                            else absent("bfs23_sharded")),
            # ISSUE 13 (ROADMAP #1): the 1-device sharding-overhead
            # ratio and the fused-level dispatch budget — each a value
            # on any shape the stage ran (CPU proxy included), a
            # recorded skip reason otherwise
            "sharding_overhead_ratio": (
                present(sharded.get("sharding_overhead_ratio"))
                if sharded is not None else absent("bfs23_sharded")),
            "sharded_bfs_dispatches_per_level": (
                present({k: sharded[k] for k in
                         ("dispatches_per_level_max",
                          "dispatches_per_level_mean", "levels")})
                if sharded is not None
                and sharded.get("dispatches_per_level_max") is not None
                else absent("bfs23_sharded")),
            "serving_batch_occupancy_k8_vs_k1": (
                present({k: serving[k] for k in
                         ("batch_occupancy", "job_latency_ms",
                          "k8_batch_wall_s", "k1_wall_s",
                          "k8_per_job_over_k1_x")})
                if serving is not None else absent("serving")),
            "live_refresh_delta_vs_rebuild": (
                present(det["live_refresh"])
                if "live_refresh" in det else absent("live_refresh")),
            "recovery_replay": (present(serving["recovery"])
                                if serving is not None
                                else absent("serving")),
            # ISSUE 11: the interactive lane's fuse economics — point
            # queries K=16 vs sequential + batched-PPR throughput
            "interactive_point_queries": (
                present({k: interactive[k] for k in
                         ("point_query_fused_p50_ms",
                          "point_query_fused_p95_ms",
                          "point_query_seq_p50_ms",
                          "point_query_seq_p95_ms",
                          "fuse_occupancy",
                          "ppr_batched_users_per_s",
                          "ppr_speedup_x")})
                if interactive is not None else absent("interactive")),
            # ISSUE 16: the Pallas kernels' on-chip verdicts — a value
            # on the TPU backend, a recorded skip on CPU (interpreter-
            # mode parity is tier-1's job; wall-clock is the chip's)
            "pallas_bu_speedup": (
                present({k: bfs_pal[k] for k in
                         ("xla_seconds", "pallas_seconds",
                          "pallas_bu_speedup_x", "results_bit_equal")})
                if bfs_pal is not None else absent("bfs_pallas")),
            "segment_kernel_pallas_speedup": (
                present(seg_pal) if seg_pal is not None
                else absent("segment_pallas")),
            # ISSUE 18 (ROADMAP #2): the cross-process trace — stitched
            # span count across 2 worker processes + ingest drop
            # accounting, or the stage's recorded skip reason
            "distributed_scan_trace": (
                present(det["distributed_scan"])
                if det.get("distributed_scan") is not None
                else absent("distributed_scan")),
            # ISSUE 19 (ROADMAP #2): the replica fleet's routing plane —
            # per-replica occupancy + decision counts under a mixed
            # stream and the failover redispatch latency, or the
            # stage's recorded skip reason
            "fleet_routing": (
                present(det["fleet"])
                if det.get("fleet") is not None
                else absent("fleet")),
        }

    def write(self) -> None:
        self.profiler.uninstall()
        rep = self.rep
        bundle = {
            "format": self.FORMAT,
            "generated_at": time.time(),
            "headline": {"metric": rep.metric, "value": rep.value,
                         "unit": rep.unit,
                         "vs_baseline": rep.vs_baseline},
            "roadmap5": self._checklist(),
            "stages": self.stages,
            "compile_log": self.profiler.compile_log(),
            "device_totals": self.profiler.stats(),
            "kernels": self.profiler.kernel_stats(),
            "detail": rep.detail,
        }
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        os.replace(tmp, self.path)   # a torn write never becomes an
        #                              artifact (cf. obs/flightrec)


def _parse_args(argv: list) -> tuple:
    """(evidence_path, positional) — bench predates argparse and the
    driver invokes it positionally; keep that contract."""
    evidence = None
    rest = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--evidence":
            if i + 1 >= len(argv):
                sys.exit("bench.py: --evidence requires a path")
            evidence = argv[i + 1]
            i += 2
        elif a.startswith("--evidence="):
            evidence = a.split("=", 1)[1]
            i += 1
        else:
            rest.append(a)
            i += 1
    return evidence, rest


def main() -> None:
    import jax

    # persist compiled executables across bench processes (first-run
    # compiles go through the axon tunnel at ~10-60s per shape bucket);
    # single source of truth for the cache path/config
    from titan_tpu.utils.jitcache import enable_compile_cache
    enable_compile_cache()

    evidence_path, argv = _parse_args(sys.argv[1:])
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    headline_scale = (int(argv[0]) if argv
                      else (26 if on_accel else 16))
    warm_scale = min(23, headline_scale)
    lj_scale = 22 if on_accel else min(headline_scale, 14)

    rep = Report()
    rep.detail["platform"] = platform
    rep.detail["n_devices"] = jax.device_count()
    ev = Evidence(evidence_path, rep) if evidence_path else None

    # stage order = the two BASELINE HARD targets FIRST and in full
    # possession of the budget (the headline BFS literally first — on a
    # slow-tunnel day nothing may run before it; r4 lost its pagerank
    # number to the driver kill by running it last), then the cheap
    # OLTP measures, then the "measure" rows (sssp/wcc share the
    # resident scale-26 upload; store-ingest + heavy are r5 evidence
    # stages), then the warm-scale/sharded evidence stages that are
    # first to drop under pressure. The s22 pagerank graph (0.56GB)
    # fits HBM alongside the s26 graph, so pagerank never evicts.
    stages = [
        (HEADLINE_STAGE, lambda: _bfs_stage(rep, headline_scale,
                                            "headline")),
        ("pagerank", lambda: pagerank_stage(rep, lj_scale)),
        ("gods_2hop", lambda: gods_2hop(rep)),
        ("ldbc", (lambda: ldbc_is3_4hop(rep)) if on_accel else
         (lambda: ldbc_is3_4hop(rep, n_persons=1000, avg_degree=10))),
        # store_ingest AHEAD of ssspwcc (VERDICT r5 #2: it is the
        # north-star store->CSR contract and has gone uncaptured for two
        # rounds; SSSP/WCC are "measure" rows and share the resident
        # s26 upload either way)
        ("store_ingest", lambda: store_ingest_stage(
            rep, 22 if on_accel else min(headline_scale, 14),
            smoke=not on_accel)),
        ("ssspwcc", lambda: sssp_wcc(rep, headline_scale)),
        ("bfs_heavy", lambda: bfs_heavy_stage(rep)),
        # live-plane freshness evidence (ISSUE r9): delta-apply p50/p95
        # vs full rebuild; droppable under budget pressure like the
        # other evidence stages
        ("live_refresh", lambda: live_refresh_stage(
            rep, 20 if on_accel else min(headline_scale, 14))),
        # serving/recovery evidence (ISSUE r10): batch occupancy +
        # latency K=8 vs K=1, recovery replay cost, trace digest —
        # first-class metric lines next to live_refresh's
        ("serving", lambda: serving_stage(
            rep, 16 if on_accel else min(headline_scale, 12))),
        # per-tenant SLO plane evidence (ISSUE 8): per-tenant p95 +
        # device-seconds / HBM-byte-seconds attribution, labeled-sum
        # exactness — same scale as serving so the kernels stay warm
        ("tenancy", lambda: tenancy_stage(
            rep, 16 if on_accel else min(headline_scale, 12))),
        # interactive lane evidence (ISSUE 11): 2-hop point queries
        # fused K=16 vs sequential + batched-PPR throughput — the
        # fuse-economics lines ROADMAP #3 asked for
        ("interactive", lambda: interactive_stage(
            rep, 14 if on_accel else min(headline_scale, 12))),
        # cross-process observability evidence (ISSUE 18): stitched
        # distributed-scan trace + ingest accounting — host-only HTTP
        # against dict stores, so it runs on CPU and chip days alike
        ("distributed_scan", lambda: distributed_scan_stage(rep)),
        # replica-fleet routing evidence (ISSUE 19): per-replica
        # occupancy + routing decisions under a mixed BFS/SSSP/WCC
        # stream, and the failover redispatch-latency line — host HTTP
        # + small CPU kernels, runs on CPU and chip days alike
        ("fleet", lambda: fleet_stage(rep)),
        # Pallas kernel verdicts (ISSUE 16): the fused bottom-up
        # frontier kernel and the one-pass segment scan vs their XLA
        # paths — chip-only (interpreter mode times an XLA emulation)
        ("bfs_pallas", lambda: bfs_pallas_stage(rep, warm_scale)),
        ("segment_pallas", lambda: segment_pallas_stage(rep)),
        # the sharded-overhead stage also times the plain hybrid at the
        # warm scale, so it outranks the standalone warm stage when the
        # budget is tight
        ("bfs23_sharded", lambda: bfs_sharded_overhead(rep, warm_scale)),
        ("bfs23", lambda: _bfs_stage(rep, warm_scale, "warm")),
    ]
    # environment-filtered stages get RECORDED skip reasons, not
    # silent removal — the evidence checklist (ROADMAP #5) must show a
    # value or a reason for every line
    if not on_accel:
        cpu_skips = {
            "bfs_heavy":
                "no accelerator: Twitter-parity graph needs a chip",
            "bfs_pallas":
                "no accelerator: interpreter mode times an XLA "
                "emulation of the kernel, not the chip; interpreter-"
                "mode bit-equality is pinned in tier-1 "
                "(tests/test_pallas_frontier.py)",
            "segment_pallas":
                "no accelerator: the pallas segment combine engages "
                "only on the TPU backend; interpreter-mode parity is "
                "pinned in tier-1 (tests/test_pallas_segment.py)",
        }
        stages = [s for s in stages if s[0] not in cpu_skips]
        for st, why in cpu_skips.items():
            rep.detail["skipped"].append({"stage": st, "why": why})
    if warm_scale == headline_scale:      # CPU/CI path: one BFS scale
        # the plain warm BFS duplicates the headline at this scale and
        # drops; the SHARDED overhead stage stays — it reuses the
        # resident headline graph, and its sharding_overhead_ratio /
        # dispatches-per-level lines are ROADMAP-#1 checklist values
        # the evidence bundle must carry ON CPU too (ISSUE 13: skip
        # reasons are allowed only for chip-scale shapes)
        stages = [s for s in stages if s[0] != "bfs23"]
        rep.detail["skipped"].append(
            {"stage": "bfs23",
             "why": f"warm scale == headline scale "
                    f"(s{headline_scale}): single-BFS-scale run"})

    for name, fn in stages:
        # estimates re-price against the MEASURED tunnel rate (the
        # headline stage's own upload observes it — VERDICT r5 weak #2:
        # flat fast-day numbers admitted bfs_heavy into the driver kill)
        est = _est(name, on_accel)
        # stages with IN-STAGE fallbacks are admitted at their cheapest
        # fallback cost — pricing them at full cost here would make the
        # fallback paths unreachable (the stage itself then right-sizes
        # scale/reps against _left())
        if name == "store_ingest":
            est = est / 4 + 60      # two scale steps down (~halves/step)
        elif name == "bfs_heavy":
            est = max(est - 60, est / 2)   # reps 2 -> 1
        if not on_accel and headline_scale < 20:
            # CI/smoke scales: the table's estimates assume bench-scale
            # graphs; a small-scale CPU run costs ~1/10th. On an
            # accelerator the guard must NOT shrink — several stages pin
            # their own scale regardless of the headline (store_ingest
            # s22, pagerank s22, bfs_heavy s25) and admitting them on a
            # tenth of their true cost would blow the driver clock
            est = max(est // 10, 20)
        # the HEADLINE stage is never budget-skipped: a report without
        # the headline metric is worthless however honest the skip note
        # (it runs first, so this only matters for sub-estimate smoke
        # budgets). Everything else also respects a hard reserve before
        # the observed external window — nothing new starts that could
        # ride into the driver kill (rc=124 three rounds running).
        if name != HEADLINE_STAGE and _left() < est + _HARD_RESERVE_S:
            rep.skip(name, f"budget: {_left():.0f}s left < est "
                           f"{est:.0f}s + {_HARD_RESERVE_S:.0f}s reserve "
                           f"(h2d {_h2d_gbps:.3f}GB/s)")
            if ev is not None:
                ev.record(name, "skipped")
            continue
        # each stage runs inside its own profiler window so the bundle
        # attributes compiles / device wall / transfer bytes per stage
        w = ev.profiler.window() if ev is not None else None
        try:
            fn()
            if ev is not None:
                ev.record(name, "ok", w.close())
        except Exception as e:            # a broken stage must not eat
            rep.skip(name, f"error: {type(e).__name__}: {e}")
            if ev is not None:
                ev.record(name, f"error: {type(e).__name__}", w.close())

    rep.emit()
    if ev is not None:
        ev.write()
        rep.detail["evidence"] = ev.path
        rep.emit()


if __name__ == "__main__":
    main()
