#!/usr/bin/env python
"""Benchmark: Graph500-style BFS TEPS on the TPU OLAP engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is measured TEPS / 1e9 (the BASELINE.md target: >= 1B TEPS on
Graph500 scale-26 BFS on a v5e-8; this runs single-chip at a scale sized to
the device, so vs_baseline is the fraction of the full multi-chip target
achieved on one chip).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else (23 if on_accel else 16)
    edge_factor = 16

    from titan_tpu.models.bfs import INF, frontier_bfs
    from titan_tpu.olap.tpu.rmat import rmat_edges
    from titan_tpu.olap.tpu import snapshot as snap_mod

    t0 = time.time()
    src, dst = rmat_edges(scale, edge_factor, seed=2)
    n = 1 << scale
    # Graph500 BFS runs on the symmetrized graph
    s2 = np.concatenate([src, dst])
    d2 = np.concatenate([dst, src])
    snap = snap_mod.from_arrays(n, s2, d2)
    gen_s = time.time() - t0

    # pick a source with out-degree > 0 (Graph500 rule)
    deg = snap.out_degree
    source = int(np.flatnonzero(deg > 0)[0])

    # frontier-sparse BFS (O(E) total work; see PERF_NOTES.md); sharded
    # over all chips when more than one is attached; tiled (vertex-range
    # CSR shards, int32-safe) when the edge count overflows int32 indices
    ndev = jax.device_count()
    if snap.num_edges >= (1 << 31):
        # >= 2^31 directed edges: only the tiled path is int32-safe (the
        # mesh-sharded path still indexes the whole edge array per chip)
        from titan_tpu.models.bfs import frontier_bfs_tiled
        run_bfs = lambda: frontier_bfs_tiled(snap, source)  # noqa: E731
    elif ndev > 1:
        from titan_tpu.models.bfs import frontier_bfs_sharded
        from titan_tpu.parallel.mesh import vertex_mesh
        mesh = vertex_mesh(ndev)
        run_bfs = lambda: frontier_bfs_sharded(snap, source, mesh)  # noqa: E731
    else:
        run_bfs = lambda: frontier_bfs(snap, source)  # noqa: E731

    # warm-up / compile + converged run
    t1 = time.time()
    dist, iters = run_bfs()
    first_s = time.time() - t1

    # timed runs (compile cached)
    times = []
    for _ in range(3):
        t2 = time.time()
        dist, iters = run_bfs()
        times.append(time.time() - t2)
    t_bfs = min(times)

    reachable = dist < int(INF)
    # Graph500 TEPS: input (undirected) edges with both endpoints reachable
    m_traversed = int(np.count_nonzero(reachable[s2]) // 2)
    teps = m_traversed / t_bfs

    # BASELINE config #1: GraphOfTheGods 2-hop Gremlin on inmemory (OLTP
    # traversal latency; p50 of repeated runs)
    import titan_tpu
    from titan_tpu import example
    g = titan_tpu.open("inmemory")
    example.load(g)
    twohop = lambda: g.traversal().V().out().out().count().next()  # noqa: E731
    count2 = twohop()
    lat = []
    for _ in range(20):
        t = time.time()
        twohop()
        lat.append(time.time() - t)
    twohop_ms = sorted(lat)[len(lat) // 2] * 1e3
    g.close()

    print(json.dumps({
        "metric": f"graph500_scale{scale}_bfs_teps",
        "value": round(teps, 1),
        "unit": "TEPS",
        "vs_baseline": round(teps / 1e9, 4),
        "detail": {
            "platform": platform,
            "n_vertices": n,
            "n_directed_edges": int(len(s2)),
            "bfs_supersteps": int(iters),
            "reachable_vertices": int(np.count_nonzero(reachable)),
            "bfs_seconds": round(t_bfs, 4),
            "first_run_seconds": round(first_s, 2),
            "graphgen_seconds": round(gen_s, 2),
            "gods_2hop_p50_ms": round(twohop_ms, 3),
            "gods_2hop_count": int(count2),
        },
    }))


if __name__ == "__main__":
    main()
